"""Benchmark entry point — prints ONE JSON line for the driver.

Flagship config (BASELINE.json config 2/4): ResNet-50 ImageNet-shape
training throughput, static-graph Executor, bf16 AMP, SGD+momentum, one
chip.  The step loop runs ON DEVICE via Executor.run_steps (lax.scan over
K steps per executable call) so there are zero per-step host syncs —
fetches are jax async arrays and the single sync happens after timing.

Baseline: A100 ResNet-50 training ~2900 images/sec (NGC/MLPerf AMP
figures); the BASELINE.json bar is 0.9x that.
"""
import json
import time

import numpy as np

BATCH = 128
STEPS_PER_CALL = 60
TIMED_CALLS = 2
A100_IMG_PER_SEC = 2900.0


def main():
    import paddle_tpu as pt
    from paddle_tpu.amp.static_amp import decorate
    from paddle_tpu.framework.place import _default_place
    from paddle_tpu.framework.program import program_guard
    from paddle_tpu.vision.static_models import resnet50_train_program

    main_p, startup, (img, label), loss, opt = resnet50_train_program(
        lr=0.1, momentum=0.9)
    main_p.random_seed = 1
    with program_guard(main_p, startup):
        decorate(opt, use_bf16=True).minimize(loss)

    place = _default_place()
    exe = pt.Executor(place)
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)

    import jax

    rng = np.random.RandomState(0)
    # device_put once: timed calls reuse the on-device batch, so the loop
    # measures pure step throughput (no per-call host->device copies)
    feed = {
        "image": jax.device_put(rng.randn(BATCH, 3, 224, 224).astype("float32")),
        "label": jax.device_put(
            rng.randint(0, 1000, (BATCH, 1)).astype("int32")),
    }

    # warmup: compiles the K-step executable and transfers the batch once
    out = exe.run_steps(main_p, feed=feed, fetch_list=[loss], scope=scope,
                        steps=STEPS_PER_CALL)
    np.asarray(out[0])  # block until warmup completes

    t0 = time.perf_counter()
    for _ in range(TIMED_CALLS):
        out = exe.run_steps(main_p, feed=feed, fetch_list=[loss], scope=scope,
                            steps=STEPS_PER_CALL)
    final = np.asarray(out[0])  # single sync for the whole run
    dt = time.perf_counter() - t0
    assert np.isfinite(final).all(), final

    ips = BATCH * STEPS_PER_CALL * TIMED_CALLS / dt
    print(
        json.dumps(
            {
                "metric": "resnet50_bf16_images_per_sec",
                "value": round(ips, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(ips / (0.9 * A100_IMG_PER_SEC), 3),
            }
        )
    )


if __name__ == "__main__":
    main()
