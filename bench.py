"""Benchmark entry point — prints ONE JSON line for the driver.

Two flagship configs (BASELINE.json):
- config 2/4: ResNet-50 ImageNet-shape training, static-graph Executor,
  bf16 AMP, SGD+momentum, one chip -> images/sec/chip.
- config 3: BERT-base pretraining (MLM+NSP, masked-position head, fused
  attention), AdamW, bf16 AMP -> tokens/sec/chip.

Step loops run ON DEVICE via Executor.run_steps (lax.scan over K steps
per executable call): zero per-step host syncs; fetches are async jax
arrays and the single sync happens after timing.

Baselines (A100 SXM4, AMP):
- ResNet-50: ~2900 img/s (NGC/MLPerf convnet figures).
- BERT-base phase-1 (seq 128): ~160k tokens/s, derived from NVIDIA
  DeepLearningExamples BERT-LARGE A100 throughput (~410-440 seq/s/GPU at
  seq 128) scaled by the ~3.07x param/FLOP ratio large->base
  (340M->110M params), i.e. ~1250 seq/s * 128 tok.
The BASELINE.json bar is 0.9x A100 for both; vs_baseline in the output
is measured/(0.9*A100).  The primary metric line reports ResNet-50 and
carries the BERT numbers as extra keys; vs_baseline is the MIN of the
two ratios so the driver's single number only passes when both do.
"""
import json
import math
import time

import numpy as np

RESNET_BATCH = 128
RESNET_STEPS = 150  # more on-device steps per call: amortizes tunnel
RESNET_CALLS = 2    # dispatch/fetch latency into the measurement
A100_IMG_PER_SEC = 2900.0

BERT_BATCH = 256
BERT_SEQ = 128
BERT_PREDS = 20
BERT_STEPS = 20
BERT_CALLS = 2
A100_BERT_TOKENS_PER_SEC = 160_000.0


def bench_resnet(pt, jax):
    from paddle_tpu.amp.static_amp import decorate
    from paddle_tpu.framework.place import _default_place
    from paddle_tpu.framework.program import program_guard
    from paddle_tpu.vision.static_models import resnet50_train_program

    main_p, startup, _, loss, opt = resnet50_train_program(
        lr=0.1, momentum=0.9)
    main_p.random_seed = 1
    with program_guard(main_p, startup):
        decorate(opt, use_bf16=True).minimize(loss)

    exe = pt.Executor(_default_place())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    # device_put once: timed calls reuse the on-device batch, so the loop
    # measures pure step throughput (no per-call host->device copies)
    feed = {
        "image": jax.device_put(
            rng.randn(RESNET_BATCH, 3, 224, 224).astype("float32")),
        "label": jax.device_put(
            rng.randint(0, 1000, (RESNET_BATCH, 1)).astype("int32")),
    }
    out = exe.run_steps(main_p, feed=feed, fetch_list=[loss], scope=scope,
                        steps=RESNET_STEPS)
    np.asarray(out[0])  # block until warmup (compile) completes

    t0 = time.perf_counter()
    for _ in range(RESNET_CALLS):
        out = exe.run_steps(main_p, feed=feed, fetch_list=[loss],
                            scope=scope, steps=RESNET_STEPS)
    final = np.asarray(out[0])  # single sync for the whole run
    dt = time.perf_counter() - t0
    assert np.isfinite(final).all(), final
    return RESNET_BATCH * RESNET_STEPS * RESNET_CALLS / dt


def bench_bert(pt, jax):
    from paddle_tpu.amp.static_amp import decorate
    from paddle_tpu.framework.place import _default_place
    from paddle_tpu.framework.program import program_guard
    from paddle_tpu.text import bert_base_pretrain_program

    B, S, P = BERT_BATCH, BERT_SEQ, BERT_PREDS
    main_p, startup, _, loss, opt = bert_base_pretrain_program(
        batch_size=B, seq_len=S, max_preds_per_seq=P)
    main_p.random_seed = 1
    with program_guard(main_p, startup):
        decorate(opt, use_bf16=True).minimize(loss)

    exe = pt.Executor(_default_place())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 30522, (B, S)).astype("int64")
    flat_pos = np.concatenate(
        [b * S + rng.choice(S, P, replace=False) for b in range(B)]
    ).astype("int64")
    labels = ids.reshape(-1)[flat_pos].reshape(-1, 1).astype("int64")
    feed = {k: jax.device_put(v) for k, v in {
        "input_ids": ids,
        "token_type_ids": np.zeros((B, S), "int64"),
        "pos_ids": np.tile(np.arange(S, dtype="int64"), (B, 1)),
        "input_mask": np.zeros((B, 1, 1, S), "float32"),
        "masked_flat_pos": flat_pos,
        "masked_labels": labels,
        "masked_weights": np.ones((B * P, 1), "float32"),
        "nsp_labels": rng.randint(0, 2, (B, 1)).astype("int64"),
    }.items()}
    out = exe.run_steps(main_p, feed=feed, fetch_list=[loss], scope=scope,
                        steps=BERT_STEPS)
    np.asarray(out[0])

    t0 = time.perf_counter()
    for _ in range(BERT_CALLS):
        out = exe.run_steps(main_p, feed=feed, fetch_list=[loss],
                            scope=scope, steps=BERT_STEPS)
    final = np.asarray(out[0])
    dt = time.perf_counter() - t0
    assert np.isfinite(final).all(), final
    return B * S * BERT_STEPS * BERT_CALLS / dt


PIPE_BATCH = 128
PIPE_CHUNK = 5       # steps per run_steps call (stacked feed dim)
PIPE_CALLS = 4
PIPE_WORKERS = 2
PIPE_STEPS = 20      # per-step Executor.run calls in the pipelined bench


def _pipeline_collate(batch):
    """Module-level (spawned workers pickle by reference): stack + cast
    labels to the int32 the train program feeds."""
    import numpy as _np

    from paddle_tpu.io import default_collate_fn

    im, lb = default_collate_fn(batch)
    return _np.asarray(im), _np.asarray(lb).astype("int32")


class _SyntheticImageNet:
    """Decode-like synthetic dataset: per-sample uint8 image generated
    + randomly cropped/flipped in the worker (the CPU work a JPEG
    pipeline does), labels derived from the index."""

    def __init__(self, n=100_000, src=256, crop=224):
        self.n, self.src, self.crop = n, src, crop

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rs = np.random.RandomState(i % 7919)
        img = rs.randint(0, 256, (3, self.src, self.src), np.uint8)
        y0, x0 = rs.randint(0, self.src - self.crop, 2)
        img = img[:, y0:y0 + self.crop, x0:x0 + self.crop]
        if rs.rand() > 0.5:
            img = img[:, :, ::-1]
        return np.ascontiguousarray(img), np.array([i % 1000], np.int64)


def bench_resnet_pipeline(pt, jax):
    """Input-pipeline-INCLUSIVE throughput: multiprocess DataLoader
    (decode-like per-sample transform in worker processes) -> uint8
    host->device transfer (4x less bandwidth; normalize runs on device)
    -> on-device chunks of PIPE_CHUNK steps, double-buffered so the host
    assembles chunk N+1 while the chip runs chunk N.

    Returns ``(images_per_sec, extras)``: extras carries the PR 5
    pipelined per-step dispatch telemetry
    (``resnet50_pipelined_step_time_ms_p50`` from drain-timed
    Executor.run handles, ``input_wait_ms_p50`` /
    ``fetch_sync_ms_p50`` from the loader device-prefetch stage and the
    window drains) — the sync-mode ``resnet50_step_time_ms_*`` keys from
    bench_resnet stay alongside for comparison."""
    from paddle_tpu.amp.static_amp import decorate
    from paddle_tpu.framework.place import _default_place
    from paddle_tpu.framework.program import program_guard
    from paddle_tpu.io import DataLoader
    from paddle_tpu.vision.static_models import resnet50_train_program

    main_p, startup, _, loss, opt = resnet50_train_program(
        lr=0.1, momentum=0.9, uint8_input=True)
    main_p.random_seed = 1
    with program_guard(main_p, startup):
        decorate(opt, use_bf16=True).minimize(loss)

    exe = pt.Executor(_default_place())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)

    loader = DataLoader(_SyntheticImageNet(), batch_size=PIPE_BATCH,
                        num_workers=PIPE_WORKERS, shuffle=False)
    it = iter(loader)

    def next_chunk():
        imgs, lbls = [], []
        for _ in range(PIPE_CHUNK):
            im, lb = next(it)
            imgs.append(np.asarray(im))
            lbls.append(np.asarray(lb).astype("int32"))
        return {"image": jax.device_put(np.stack(imgs)),
                "label": jax.device_put(np.stack(lbls))}

    feed = next_chunk()
    out = exe.run_steps(main_p, feed=feed, fetch_list=[loss], scope=scope)
    np.asarray(out[0])  # compile + warm

    t0 = time.perf_counter()
    nxt = next_chunk()
    for _ in range(PIPE_CALLS):
        out = exe.run_steps(main_p, feed=nxt, fetch_list=[loss],
                            scope=scope)  # async dispatch
        nxt = next_chunk()  # host pipeline overlaps the device chunk
    final = np.asarray(out[0])
    dt = time.perf_counter() - t0
    assert np.isfinite(final).all(), final
    ips = PIPE_BATCH * PIPE_CHUNK * PIPE_CALLS / dt

    # ---- pipelined per-step dispatch (PR 5): Executor.run handles +
    # bounded in-flight window + DataLoader device-side prefetch.
    # FLAGS_benchmark must be OFF here: it forces a per-call drain, and
    # this bench measures the windowed overlap the training loop sees.
    from paddle_tpu import observe

    extras = {}
    prev_benchmark = pt.get_flags("FLAGS_benchmark")["FLAGS_benchmark"]
    pt.set_flags({"FLAGS_benchmark": False})
    try:
        dl = DataLoader(_SyntheticImageNet(), batch_size=PIPE_BATCH,
                        num_workers=PIPE_WORKERS, shuffle=False,
                        collate_fn=_pipeline_collate, device_prefetch=True)
        dit = iter(dl)

        def next_feed():
            im, lb = next(dit)
            return {"image": im, "label": lb}

        last = exe.run(main_p, feed=next_feed(), fetch_list=[loss],
                       scope=scope)
        last.numpy()  # compile + warm
        # reset AFTER the warm step so its compile-bound drain and the
        # worker spin-up wait don't contaminate the reported quantiles
        observe.reset_step_stats()
        observe.histogram("input_wait_seconds").reset()
        observe.histogram("fetch_sync_seconds").reset()
        for _ in range(PIPE_STEPS):
            last = exe.run(main_p, feed=next_feed(), fetch_list=[loss],
                           scope=scope)
        assert np.isfinite(last.numpy()[0]).all()
        exe.drain()
        step_hist = observe.step_timer().summary().get("step_time_s", {})
        if step_hist.get("count"):
            extras["resnet50_pipelined_step_time_ms_p50"] = round(
                step_hist["p50"] * 1e3, 3)
        for key, hist_name in (("input_wait_ms_p50", "input_wait_seconds"),
                               ("fetch_sync_ms_p50", "fetch_sync_seconds")):
            h = observe.histogram(hist_name).summary()
            if h.get("count"):
                extras[key] = round(h["p50"] * 1e3, 3)
    finally:
        pt.set_flags({"FLAGS_benchmark": prev_benchmark})
    return ips, extras


# small BERT-style config shared by the tensor-parallel flagship and the
# reduced-scale preflight fallback (compiles in ~20s on a CPU host —
# resnet50's 224px conv stack does not)
TP_BATCH = 16
TP_SEQ = 32
TP_VOCAB = 512
TP_HIDDEN = 64
TP_LAYERS = 2
TP_HEADS = 4
TP_FFN = 128
TP_PREDS = 4
TP_STEPS = 10


def _small_bert(pt, batch=TP_BATCH, seq=TP_SEQ, use_fleet_tp=False):
    """(main, startup, loss, feed) for a small BERT-style pretraining
    step; with ``use_fleet_tp`` the program is built through
    fleet.distributed_optimizer with strategy.tensor_parallel (default
    Megatron rules match the enc_*_{q,k,v,out}/ffn1/ffn2 +
    word_embedding naming)."""
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.program import program_guard
    from paddle_tpu.text import bert_base_pretrain_program

    B, S, P = batch, seq, TP_PREDS
    with unique_name.guard():  # repeat builds keep .w_0 param names
        main_p, startup, _, loss, opt = bert_base_pretrain_program(
            batch_size=B, seq_len=S, vocab_size=TP_VOCAB, hidden=TP_HIDDEN,
            n_layers=TP_LAYERS, n_heads=TP_HEADS, ffn_size=TP_FFN,
            max_preds_per_seq=P)
    main_p.random_seed = 1
    with unique_name.guard(), program_guard(main_p, startup):
        if use_fleet_tp:
            from paddle_tpu.distributed import fleet

            strat = fleet.DistributedStrategy()
            strat.tensor_parallel = True
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(opt)
            fleet.minimize(loss)
        else:
            opt.minimize(loss)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, TP_VOCAB, (B, S)).astype("int64")
    flat_pos = np.concatenate(
        [b * S + rng.choice(S, P, replace=False) for b in range(B)]
    ).astype("int64")
    labels = ids.reshape(-1)[flat_pos].reshape(-1, 1).astype("int64")
    feed = {
        "input_ids": ids,
        "token_type_ids": np.zeros((B, S), "int64"),
        "pos_ids": np.tile(np.arange(S, dtype="int64"), (B, 1)),
        "input_mask": np.zeros((B, 1, 1, S), "float32"),
        "masked_flat_pos": flat_pos,
        "masked_labels": labels,
        "masked_weights": np.ones((B * P, 1), "float32"),
        "nsp_labels": rng.randint(0, 2, (B, 1)).astype("int64"),
    }
    return main_p, startup, loss, feed


def bench_bert_tp(pt, jax):
    """Tensor-parallel BERT-style step time over a dp×mp mesh built
    from every visible device (ROADMAP item 1 acceptance: the
    MULTICHIP dryrun's tp leg runs this on the 8-virtual-device CPU
    mesh; a multi-chip TPU round runs it on real chips).  Returns
    {"bert_tp_step_time_ms_p50", "tp_degree", ...} keys."""
    from paddle_tpu import observe
    from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh
    from paddle_tpu.framework.place import _default_place

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        raise RuntimeError(f"bench_bert_tp needs >= 2 devices, have {n}")
    mp = 4 if n % 4 == 0 else 2
    dp = max(n // mp, 1)
    # odd device counts (e.g. 3, 7): use the largest dp*mp <= n chips
    mesh = jax.sharding.Mesh(
        np.array(devs[:dp * mp]).reshape(dp, mp), ("dp", "mp"))
    reset_mesh()
    set_mesh(mesh)
    try:
        main_p, startup, loss, feed = _small_bert(pt, use_fleet_tp=True)
        exe = pt.Executor(_default_place(), mesh=mesh)
        scope = pt.framework.Scope()
        exe.run(startup, scope=scope)
        last = exe.run(main_p, feed=feed, fetch_list=[loss], scope=scope)
        final = np.asarray(last[0])  # compile + warm
        assert np.isfinite(final).all(), final
        observe.reset_step_stats()
        for _ in range(TP_STEPS):
            last = exe.run(main_p, feed=feed, fetch_list=[loss],
                           scope=scope)
        assert np.isfinite(np.asarray(last[0])).all()
        exe.drain()
        # the acceptance oracle rides along: a QKV weight must be
        # PHYSICALLY sharded over mp (1/mp of the bytes per chip)
        w = scope.get_var("enc_0_attn_q.w_0")
        shard_elems = int(np.prod(w.addressable_shards[0].data.shape))
        assert shard_elems * mp == int(np.prod(w.shape)), (
            f"enc_0_attn_q.w_0 not mp-sharded: shard {shard_elems} elems of "
            f"{int(np.prod(w.shape))} over mp={mp}")
        out = {"tp_degree": mp, "tp_mesh": [dp, mp]}
        hist = observe.step_timer().summary().get("step_time_s", {})
        if hist.get("count"):
            out["bert_tp_step_time_ms_p50"] = round(hist["p50"] * 1e3, 3)
            out["bert_tp_tokens_per_sec"] = round(
                TP_BATCH * TP_SEQ / hist["p50"], 1)
        return out
    finally:
        reset_mesh()


DLRM_BATCH = 256
DLRM_VOCAB = 65_536
DLRM_EMB_DIM = 32
DLRM_FIELDS = 26   # Criteo categorical layout
DLRM_DENSE = 13    # Criteo dense layout
DLRM_STEPS = 10


def bench_dlrm(pt, jax):
    """Recommender flagship (ISSUE 16): wide&deep over a vocabulary
    whose embedding tables live ROW-SHARDED over the mesh's 'mp' axis
    (paddle_tpu.distributed.embedding) — the TPU-native stand-in for
    the reference's parameter-server sparse training.  Returns
    {"dlrm_examples_per_sec", "dlrm_table_bytes_per_chip",
    "dlrm_lookup_alltoall_bytes", ...}."""
    from paddle_tpu import observe
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.embedding import (alltoall_bytes_per_lookup,
                                                  shard_info)
    from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.place import _default_place
    from paddle_tpu.framework.program import program_guard
    from paddle_tpu.monitor import stat_get
    from paddle_tpu.rec import wide_deep_program

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        raise RuntimeError(f"bench_dlrm needs >= 2 devices, have {n}")
    mp = 4 if n % 4 == 0 else 2
    dp = max(n // mp, 1)
    mesh = jax.sharding.Mesh(
        np.array(devs[:dp * mp]).reshape(dp, mp), ("dp", "mp"))
    reset_mesh()
    set_mesh(mesh)
    try:
        with unique_name.guard():
            main_p, startup, feeds, loss, opt = wide_deep_program(
                batch_size=DLRM_BATCH, vocab_size=DLRM_VOCAB,
                emb_dim=DLRM_EMB_DIM, n_fields=DLRM_FIELDS,
                n_dense=DLRM_DENSE, hidden=(128, 64), padding_idx=0,
                sparse=True, lr=1e-2)
            with program_guard(main_p, startup):
                strat = fleet.DistributedStrategy()
                strat.tensor_parallel = True
                fleet.init(is_collective=True, strategy=strat)
                fleet.distributed_optimizer(opt)
                fleet.minimize(loss)
        rng = np.random.RandomState(0)
        feed = {
            "sparse_ids": rng.randint(
                0, DLRM_VOCAB,
                (DLRM_BATCH, DLRM_FIELDS)).astype("int64"),
            "dense_x": rng.randn(DLRM_BATCH,
                                 DLRM_DENSE).astype("float32"),
            "labels": rng.randint(0, 2, (DLRM_BATCH, 1)).astype("int64"),
        }
        exe = pt.Executor(_default_place(), mesh=mesh)
        scope = pt.framework.Scope()
        exe.run(startup, scope=scope)
        last = exe.run(main_p, feed=feed, fetch_list=[loss], scope=scope)
        assert np.isfinite(np.asarray(last[0])).all()  # compile + warm
        observe.reset_step_stats()
        for _ in range(DLRM_STEPS):
            last = exe.run(main_p, feed=feed, fetch_list=[loss],
                           scope=scope)
        assert np.isfinite(np.asarray(last[0])).all()
        exe.drain()
        # acceptance oracle: the deep table is PHYSICALLY row-sharded
        # (vocab/mp rows per chip), so the model's table footprint
        # never replicates
        tbl = scope.get_var("wd_table")
        shard_rows = int(tbl.addressable_shards[0].data.shape[0])
        assert shard_rows * mp == DLRM_VOCAB, (
            f"wd_table not row-sharded: {shard_rows} rows/chip of "
            f"{DLRM_VOCAB} over mp={mp}")
        from paddle_tpu.framework import passes as passes_mod

        planned = passes_mod.apply_passes(
            main_p, fetch_names=(loss.name,),
            feed_names=("sparse_ids", "dense_x", "labels"), mesh=mesh)
        info = shard_info(planned, "wd_table", mesh=mesh)
        out = {
            "dlrm_tp_degree": mp,
            "dlrm_table_bytes_per_chip": info["bytes_per_chip"],
            "dlrm_table_rows_per_chip": shard_rows,
            # per-step collective payload of the two lookups (deep +
            # wide), from the engine's static accounting
            "dlrm_lookup_alltoall_bytes": (
                alltoall_bytes_per_lookup(
                    DLRM_BATCH * DLRM_FIELDS, mp, DLRM_EMB_DIM)
                + alltoall_bytes_per_lookup(
                    DLRM_BATCH * DLRM_FIELDS, mp, 1)),
            "dlrm_emb_alltoall_bytes_traced": stat_get(
                "emb_alltoall_bytes"),
        }
        hist = observe.step_timer().summary().get("step_time_s", {})
        if hist.get("count"):
            out["dlrm_step_time_ms_p50"] = round(hist["p50"] * 1e3, 3)
            out["dlrm_examples_per_sec"] = round(
                DLRM_BATCH / hist["p50"], 1)
        return out
    finally:
        reset_mesh()


def _fallback_reduced_run(result):
    """Device preflight failed: fall back to a reduced-scale CPU run so
    the round still reports perf data — ``status: "partial"`` with the
    structured failure record kept — instead of a failure with no
    numbers (ROADMAP item 4 slice; BENCH_r04/r05 zeroed every metric).

    The fallback model is the small BERT config (resnet50's conv stack
    takes many minutes to compile on a CPU host); ``vs_baseline`` stays
    0.0 — a host-CPU number is not comparable to the accelerator
    baseline and must not masquerade as one."""
    import os

    t0 = time.perf_counter()
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        # the container may have imported jax (TPU plugin registered)
        # before this runs; the live-config update still wins as long as
        # no backend was initialized — and the dead device is never
        # touched because only the cpu backend is ever instantiated
        jax.config.update("jax_platforms", "cpu")
        if jax.devices()[0].platform != "cpu":
            raise RuntimeError("cpu backend unavailable for fallback")

        import paddle_tpu as pt

        main_p, startup, loss, feed = _small_bert(pt)
        from paddle_tpu.framework.place import _default_place

        exe = pt.Executor(_default_place())
        scope = pt.framework.Scope()
        exe.run(startup, scope=scope)
        out = exe.run_steps(main_p, feed=feed, fetch_list=[loss],
                            scope=scope, steps=TP_STEPS)
        np.asarray(out[0])  # compile + warm
        t1 = time.perf_counter()
        out = exe.run_steps(main_p, feed=feed, fetch_list=[loss],
                            scope=scope, steps=TP_STEPS)
        final = np.asarray(out[0])
        dt = time.perf_counter() - t1
        assert np.isfinite(final).all(), final
        tps = TP_BATCH * TP_SEQ * TP_STEPS / dt
        result.update(
            status="partial",
            fallback={
                "platform": "cpu",
                "model": "bert_small",
                "batch": TP_BATCH, "seq_len": TP_SEQ,
                "steps": TP_STEPS,
                "bert_small_tokens_per_sec": round(tps, 1),
                "wall_seconds": round(time.perf_counter() - t0, 1),
                "note": "reduced-scale CPU run after device preflight "
                        "failure; vs_baseline stays 0.0 (not comparable "
                        "to the accelerator baseline)",
            })
    except Exception as e:  # noqa: BLE001 — the record must still print
        result["fallback_error"] = f"{type(e).__name__}: {e}"[:500]
        return result
    try:
        # the decode engine runs its step loop on whatever backend is
        # live, so the generative-serving keys (and the continuous-vs-
        # one-shot A/B, which is a RATIO — host-comparable) still land
        # on a chip-less round
        import jax

        import paddle_tpu as pt

        result.update(bench_decode(pt, jax))
    except Exception as e:  # noqa: BLE001
        result["fallback_decode_error"] = f"{type(e).__name__}: {e}"[:500]
    return result


# mixture-of-experts flagship (ISSUE 20): sized so the [E, capacity, D]
# dispatch buffer's capacity (ceil(B*K*cf/E) = 40) divides the chunk
# count — the overlap A/B must ENGAGE chunking, not fall back
MOE_BATCH = 64
MOE_DM = 32
MOE_FFN_DIM = 64
MOE_EXPERTS = 4
MOE_TOPK = 2
MOE_CF = 1.25
MOE_STEPS = 6
MOE_CHUNKS = 4


def bench_moe(pt, jax):
    """Mixture-of-experts flagship over a dp×ep mesh (ISSUE 20).

    Four measurements: (1) loss parity of the expert-parallel run vs
    the replicated single-device oracle (the dense execution of the
    same routed FFN — matched activated FLOPs by construction);
    (2) throughput vs a dense-equivalent MLP whose hidden width is
    top_k * ffn_dim (what the same activated FLOPs buy without
    routing), data-parallel over the same chips; (3) the overlap A/B:
    FLAGS_moe_alltoall_chunks off vs on must keep losses BITWISE equal
    (capacity-axis chunking + one final combine) while the PR 18
    ledger shows >= 1 hidden all-to-all and a strictly lower exposed
    share; (4) the quantized-expert serving leg's quality tax through
    quant_quality_delta.  Emits moe_tokens_per_sec,
    moe_expert_balance_ppm, moe_dropped_fraction_ppm,
    moe_overlap_step_time_ratio and friends."""
    import time as _time

    from paddle_tpu import layers
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh
    from paddle_tpu.framework import passes as passes_mod
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.observe.phases import collective_inventory
    from paddle_tpu.ops.moe_ops import moe_balance_gauges
    from paddle_tpu.optimizer import MomentumOptimizer

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        raise RuntimeError(f"bench_moe needs >= 2 devices, have {n}")
    ep = 4 if n % 4 == 0 else 2
    dp = max(n // ep, 1)
    ep_mesh = jax.sharding.Mesh(
        np.array(devs[:dp * ep]).reshape(dp, ep), ("dp", "ep"))
    dp_mesh = jax.sharding.Mesh(np.array(devs[:dp * ep]), ("dp",))

    def build(kind):
        main_p, startup = Program(), Program()
        main_p.random_seed = 1
        with unique_name.guard(), program_guard(main_p, startup):
            x = layers.data("x", [MOE_DM])
            y = layers.data("y", [1])
            load = None
            if kind == "dense":
                # dense-equivalent at matched ACTIVATED FLOPs: every
                # token runs top_k experts of width ffn_dim, so the
                # dense twin gets one MLP of width top_k * ffn_dim
                h = layers.fc(x, MOE_TOPK * MOE_FFN_DIM, act="gelu",
                              name="dense_up")
                h = layers.fc(h, MOE_DM, name="dense_down")
                pred = layers.fc(h, 1, name="head")
                loss = layers.mean(layers.square_error_cost(pred, y))
            else:
                h, aux, load = layers.moe_ffn(
                    x, num_experts=MOE_EXPERTS, ffn_dim=MOE_FFN_DIM,
                    top_k=MOE_TOPK, capacity_factor=MOE_CF, name="moe0")
                pred = layers.fc(h, 1, name="head")
                loss0 = layers.mean(layers.square_error_cost(pred, y))
                loss = layers.elementwise_add(
                    loss0, layers.scale(aux, 0.01))
            opt = MomentumOptimizer(0.05, 0.9)
            if kind == "moe_ep":
                strat = fleet.DistributedStrategy()
                strat.expert_parallel = True
                fleet.init(is_collective=True, strategy=strat)
                fleet.distributed_optimizer(opt)
                fleet.minimize(loss)
            elif kind == "dense":
                fleet.init(is_collective=True)
                fleet.distributed_optimizer(opt)
                fleet.minimize(loss)
            else:  # replicated oracle
                opt.minimize(loss)
        return main_p, startup, loss, load

    rs = np.random.RandomState(0)
    X = rs.randn(MOE_BATCH, MOE_DM).astype(np.float32)
    Y = (X.sum(axis=1, keepdims=True) * 0.3).astype(np.float32)

    def train(kind, mesh, steps=MOE_STEPS):
        main_p, startup, loss, load = build(kind)
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
        exe.run(startup, scope=scope)
        fetches = [loss] + ([load] if load is not None else [])
        out = exe.run(main_p, feed={"x": X, "y": Y}, fetch_list=fetches,
                      scope=scope)  # compile + warm
        assert np.isfinite(np.asarray(out[0])).all()
        t0 = _time.perf_counter()
        losses, last_load = [], None
        for _ in range(steps):
            out = exe.run(main_p, feed={"x": X, "y": Y},
                          fetch_list=fetches, scope=scope)
            losses.append(float(np.asarray(out[0]).ravel()[0]))
            if load is not None:
                last_load = np.asarray(out[1])
        exe.drain()
        wall = _time.perf_counter() - t0
        return losses, last_load, wall, main_p

    # replicated oracle (dense execution of the same routed FFN)
    reset_mesh()
    base, _, _, _ = train("moe_local", None)

    pt.set_flags({"FLAGS_moe_alltoall_chunks": 0})
    set_mesh(ep_mesh)
    try:
        seq_losses, load, seq_wall, prog = train("moe_ep", ep_mesh)
        rel = max(abs(a - b) / max(abs(a), 1e-8)
                  for a, b in zip(base, seq_losses))
        assert rel <= 1e-4, (
            f"ep loss parity {rel} vs replicated oracle", base, seq_losses)
        gauges = moe_balance_gauges(load, MOE_BATCH, MOE_TOPK)

        # overlap A/B: same program, chunked all-to-all schedule
        pt.set_flags({"FLAGS_moe_alltoall_chunks": MOE_CHUNKS})
        chunk_losses, _, chunk_wall, _ = train("moe_ep", ep_mesh)
        assert chunk_losses == seq_losses, (
            "chunked schedule is not bitwise-equal to sequential",
            seq_losses, chunk_losses)

        # ledger: chunking must hide >= 1 all-to-all and strictly
        # lower the exposed share of the a2a bytes
        plan_prog = passes_mod.apply_passes(
            prog, fetch_names=(), feed_names=("x", "y"), mesh=ep_mesh)
        blk = plan_prog.global_block

        def a2a_exposed_share(chunks):
            inv = [e for e in collective_inventory(
                blk, list(blk.ops), mesh=ep_mesh,
                tp_plan=plan_prog._tp_plan, moe_chunks=chunks)
                if e["op"] == "ep_alltoall"]
            total = sum(e["bytes"] for e in inv)
            exposed = sum(e["bytes"] for e in inv if not e["overlap"])
            hidden_n = sum(1 for e in inv if e["overlap"])
            return exposed / max(total, 1), hidden_n

        share_seq, hidden_seq = a2a_exposed_share(0)
        share_chunk, hidden_chunk = a2a_exposed_share(MOE_CHUNKS)
        assert hidden_chunk >= 1, "chunked schedule hid no all-to-all"
        assert share_chunk < share_seq, (share_chunk, share_seq)
    finally:
        pt.set_flags({"FLAGS_moe_alltoall_chunks": 0})
        reset_mesh()

    # dense-equivalent throughput over the same chips (dp only)
    set_mesh(dp_mesh)
    try:
        _, _, dense_wall, _ = train("dense", dp_mesh)
    finally:
        reset_mesh()

    toks = MOE_BATCH * MOE_STEPS
    out = {
        "ep_degree": ep,
        "moe_mesh": [dp, ep],
        "moe_tokens_per_sec": round(toks / seq_wall, 1),
        "moe_dense_equiv_tokens_per_sec": round(toks / dense_wall, 1),
        "moe_loss_parity_vs_oracle": rel,
        "moe_expert_balance_ppm": gauges["moe_expert_balance_ppm"],
        "moe_dropped_fraction_ppm": gauges["moe_dropped_fraction_ppm"],
        # sequential/chunked step time: > 1.0 means the overlapped
        # schedule is faster (higher-is-better, bench_diff "ratio$")
        "moe_overlap_step_time_ratio": round(seq_wall / chunk_wall, 3),
        "moe_alltoall_hidden": hidden_chunk,
        "moe_alltoall_exposed_share_seq": round(share_seq, 3),
        "moe_alltoall_exposed_share_chunked": round(share_chunk, 3),
    }
    out.update(_bench_moe_serving_quant(pt, jax))
    return out


def _bench_moe_serving_quant(pt, jax):
    """Quantized-expert serving leg: int8 stacked expert carriers vs
    the full-precision oracle on the SAME decode engine surface, the
    quality tax reported through quant_quality_delta (satellite of
    ISSUE 20 riding the bench_quant convention)."""
    from paddle_tpu.ops.quant_ops import quant_quality_delta
    from paddle_tpu.serving.decode import (DecodeConfig, DecodeEngine,
                                           TransformerLM,
                                           quantize_moe_weights)

    model = TransformerLM(vocab_size=64, d_model=32, num_layers=2,
                          num_heads=2, moe_experts=MOE_EXPERTS,
                          moe_top_k=MOE_TOPK)
    weights = model.init_weights(jax.random.PRNGKey(0))
    prompts = [[1, 2, 3], [7, 5, 11, 2]]

    # quantized run first; the full-precision oracle is TEACHER-FORCED
    # on the quantized run's own tokens (bench_quant's kv-leg
    # convention) so logits stay position-comparable after the
    # trajectories would otherwise diverge
    eq = DecodeEngine(model, quantize_moe_weights(weights, "int8"),
                      DecodeConfig(slots=2, max_seq_len=64,
                                   page_size=8)).start()
    try:
        reqs = [eq.submit(p, max_new_tokens=8, record_logits=True)
                for p in prompts]
        outs = [r.result(timeout=300) for r in reqs]
        quant = np.concatenate(
            [np.stack([np.asarray(x) for x in r.logits_trace])
             for r in reqs])
    finally:
        eq.stop()
    ef = DecodeEngine(model, weights, DecodeConfig(
        slots=2, max_seq_len=64, page_size=8)).start()
    try:
        ref = np.concatenate(
            [np.stack([ef.recompute_logits(list(p) + o[:t])
                       for t in range(len(o))])
             for p, o in zip(prompts, outs)])
    finally:
        ef.stop()
    delta = quant_quality_delta(quant, ref)
    return {"moe_quant_quality_delta": {
        "max_abs_logit_delta": round(delta["max_abs_logit_delta"], 6),
        "top1_agreement": round(delta["top1_agreement"], 4),
    }}


# transformer-depth flagship (scan-over-layers acceptance): dims are
# deliberately tiny — the quantity under test is trace+compile scaling
# with DEPTH, not step throughput, and the deep unrolled compile is the
# expensive half of the A-B
DEPTH_SHALLOW = 8
DEPTH_DEEP = 48
DEPTH_BATCH = 4
DEPTH_SEQ = 16
DEPTH_VOCAB = 128
DEPTH_HIDDEN = 32
DEPTH_HEADS = 2
DEPTH_FFN = 64
DEPTH_PREDS = 2


def bench_transformer_depth(pt, jax):
    """Scan-over-layers acceptance flagship (ROADMAP item 5): compile
    an 8- and a 48-layer transformer with FLAGS_layer_scan off and on
    (A-B in one round) and report what XLA actually built — compile
    wall seconds (the compile_seconds histogram the Executor feeds),
    executable size, and optimized-HLO op count.
    ``compile_speedup_vs_unrolled`` (48-layer unrolled/scan) is THE
    acceptance number (>=5x); ``transformer48_executable_hlo_ops``
    staying ~equal to the 8-layer count is the superlinear-shrink
    evidence.  Loss parity between the four runs is reported, never
    assumed."""
    from paddle_tpu import observe
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.place import _default_place
    from paddle_tpu.framework.program import program_guard
    from paddle_tpu.monitor import stat_get, stat_set
    from paddle_tpu.text import bert_base_pretrain_program

    B, S, V, P = DEPTH_BATCH, DEPTH_SEQ, DEPTH_VOCAB, DEPTH_PREDS

    def build(n_layers):
        with unique_name.guard():
            main_p, startup, _, loss, opt = bert_base_pretrain_program(
                batch_size=B, seq_len=S, vocab_size=V,
                hidden=DEPTH_HIDDEN, n_layers=n_layers,
                n_heads=DEPTH_HEADS, ffn_size=DEPTH_FFN,
                max_preds_per_seq=P)
            main_p.random_seed = 1
            with program_guard(main_p, startup):
                opt.minimize(loss)
        return main_p, startup, loss

    rng = np.random.RandomState(0)
    ids = rng.randint(0, V, (B, S)).astype("int64")
    flat_pos = np.concatenate(
        [b * S + rng.choice(S, P, replace=False) for b in range(B)]
    ).astype("int64")
    labels = ids.reshape(-1)[flat_pos].reshape(-1, 1).astype("int64")
    feed = {
        "input_ids": ids,
        "token_type_ids": np.zeros((B, S), "int64"),
        "pos_ids": np.tile(np.arange(S, dtype="int64"), (B, 1)),
        "input_mask": np.zeros((B, 1, 1, S), "float32"),
        "masked_flat_pos": flat_pos,
        "masked_labels": labels,
        "masked_weights": np.ones((B * P, 1), "float32"),
        "nsp_labels": rng.randint(0, 2, (B, 1)).astype("int64"),
    }

    def compile_once(n_layers, scan):
        pt.set_flags({"FLAGS_layer_scan": scan})
        main_p, startup, loss = build(n_layers)
        exe = pt.Executor(_default_place())
        scope = pt.framework.Scope()
        exe.run(startup, scope=scope)
        # reset AFTER startup so the histogram holds only the train
        # step's trace+compile
        observe.histogram("compile_seconds").reset()
        stat_set("executable_size_bytes", 0)
        stat_set("executable_hlo_ops", 0)
        stat_set("pass_layer_scan_segments", 0)
        out = exe.run(main_p, feed=feed, fetch_list=[loss], scope=scope)
        loss_v = float(np.asarray(out[0]).item())
        ch = observe.histogram("compile_seconds").summary()
        rec = {
            "compile_seconds": round(float(ch.get("sum") or 0.0), 3),
            "executable_size_bytes": int(
                stat_get("executable_size_bytes") or 0),
            "executable_hlo_ops": int(stat_get("executable_hlo_ops") or 0),
            "segments": int(stat_get("pass_layer_scan_segments") or 0),
            "loss": loss_v,
        }
        exe.close()
        return rec

    try:
        res = {(d, sc): compile_once(d, sc)
               for d in (DEPTH_SHALLOW, DEPTH_DEEP)
               for sc in (False, True)}
    finally:
        pt.set_flags({"FLAGS_layer_scan": False})

    deep_off = res[(DEPTH_DEEP, False)]
    deep_on = res[(DEPTH_DEEP, True)]
    shallow_on = res[(DEPTH_SHALLOW, True)]
    out = {
        "transformer8_compile_seconds": shallow_on["compile_seconds"],
        "transformer48_compile_seconds": deep_on["compile_seconds"],
        "transformer48_compile_seconds_unrolled":
            deep_off["compile_seconds"],
        "transformer48_executable_size_bytes":
            deep_on["executable_size_bytes"],
        "transformer48_executable_hlo_ops": deep_on["executable_hlo_ops"],
        "transformer48_executable_hlo_ops_unrolled":
            deep_off["executable_hlo_ops"],
        "transformer48_layer_scan_segments": deep_on["segments"],
        "transformer_depth_loss_parity": bool(
            deep_on["loss"] == deep_off["loss"]
            and shallow_on["loss"] == res[(DEPTH_SHALLOW, False)]["loss"]),
    }
    if deep_on["compile_seconds"] > 0:
        out["compile_speedup_vs_unrolled"] = round(
            deep_off["compile_seconds"] / deep_on["compile_seconds"], 2)
    return out


# 3D-parallelism / overlap flagship (ISSUE 15): dims tiny — the
# quantities under test are schedule ratios and placement, not raw
# throughput
P3D_HIDDEN = 32
P3D_BATCH = 16
P3D_MICRO = 4
P3D_STEPS = 8


def _megatron_pp_program(pt, use_tp, n_micro=P3D_MICRO, hidden=P3D_HIDDEN):
    """2-stage GPipe program of Megatron ffn pairs (names match
    DEFAULT_MEGATRON_RULES: ffn1 column-parallel, ffn2 row-parallel),
    built through the REAL production path when ``use_tp``
    (strategy.tensor_parallel + strategy.pipeline -> the dp×mp×pp
    composition in distributed/pipeline.py)."""
    from paddle_tpu import layers
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.program import (Program, device_guard,
                                              program_guard)
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.optimizer import MomentumOptimizer, PipelineOptimizer
    from paddle_tpu.param_attr import ParamAttr

    def attr(v):
        return ParamAttr(initializer=ConstantInitializer(v))

    H = hidden
    main, startup = Program(), Program()
    main.random_seed = 1
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [H])
        y = layers.data("y", [1])
        h = x
        for s in range(2):
            with device_guard(f"stage:{s}"):
                h = layers.fc(h, 4 * H, act="relu", name=f"b{s}_ffn1",
                              param_attr=attr(0.02), bias_attr=attr(0.0))
                h = layers.fc(h, H, name=f"b{s}_ffn2",
                              param_attr=attr(0.02), bias_attr=attr(0.0))
        with device_guard("stage:1"):
            pred = layers.fc(h, 1, name="head", param_attr=attr(0.05),
                             bias_attr=False)
            loss = layers.mean(layers.square_error_cost(pred, y))
        opt = MomentumOptimizer(0.02, 0.9)
        if use_tp:
            from paddle_tpu.distributed import fleet

            strat = fleet.DistributedStrategy()
            strat.tensor_parallel = True
            strat.pipeline = True
            strat.pipeline_configs = {"micro_batch": n_micro}
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(opt)
            fleet.minimize(loss)
        else:
            PipelineOptimizer(opt, num_microbatches=n_micro).minimize(loss)
    rng = np.random.RandomState(0)
    X = rng.randn(P3D_BATCH, H).astype("f4")
    Y = (X.sum(1, keepdims=True) * 0.1).astype("f4")
    return main, startup, loss, {"x": X, "y": Y}


def bench_overlap_3d(pt, jax):
    """ISSUE 15 acceptance legs.

    (A) **overlap A/B** on the transformer flagship: the depth-8
    layer-scanned BERT-style step under the fleet dp transpile, run at
    identical config with FLAGS_overlap_grad_allreduce off (sequential
    schedule: one greedy bucket drags the stacked grad carrier's
    allreduce to the end of the unrolled backward tail) vs on
    (stretched buckets: the carrier dispatches at the scan boundary,
    under the remaining backward compute).  Emits
    ``overlap_step_time_ratio`` (on/off p50) and
    ``overlap_hidden_comm_seconds`` (per-step comm wall hidden =
    max(0, seq_p50 - ovl_p50); ~0 on a CPU host whose per-device
    streams are synchronous — the placement is asserted structurally
    and the wire-time win realizes on hardware with async collectives).
    Loss equality between the two schedules is ASSERTED (the rewrite
    is placement-only).

    (B) **pp×tp leg**: the 2-stage Megatron-ffn GPipe program on a
    ('mp','pp') — or ('dp','mp','pp') with 8+ devices — mesh through
    strategy.tensor_parallel × strategy.pipeline, loss parity ≤1e-4
    ASSERTED vs the SAME schedule with mp replicated, emitting
    ``bert_3d_tokens_per_sec`` (rows/sec through the stacked ffn
    blocks), ``pp_bubble_fraction`` (the GPipe (S-1)/(K+S-1) schedule
    cost, also a _ppm gauge), and the MFU estimate when a peak is
    configured."""
    from paddle_tpu import observe
    from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh
    from paddle_tpu.framework.place import _default_place
    from paddle_tpu.monitor import stat_get, stat_reset, stat_set

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        raise RuntimeError(f"bench_overlap_3d needs >= 2 devices, have {n}")
    out = {}

    # ---- (A) overlap A/B on the scanned transformer ----------------------
    dp = min(n, 8)
    mesh_dp = jax.sharding.Mesh(np.array(devs[:dp]), ("dp",))

    def run_overlap(overlap):
        from paddle_tpu import layers
        from paddle_tpu.distributed import fleet
        from paddle_tpu.framework import unique_name
        from paddle_tpu.framework.program import Program, program_guard
        from paddle_tpu.initializer import ConstantInitializer
        from paddle_tpu.optimizer import MomentumOptimizer
        from paddle_tpu.param_attr import ParamAttr

        pt.set_flags({"FLAGS_overlap_grad_allreduce": overlap,
                      "FLAGS_layer_scan": True})
        reset_mesh()
        set_mesh(mesh_dp)
        try:
            # the transformer flagship's SCANNED region: a depth-8
            # isomorphic ffn stack (the shard_map dp path needs
            # per-shard-shapeable programs, which rules out the BERT
            # builder's static global-batch reshapes), plus unrolled
            # head/loss edges whose grads form the post-scan tail
            H, depth = DEPTH_FFN, DEPTH_SHALLOW
            main_p, startup = Program(), Program()
            main_p.random_seed = 1
            with unique_name.guard(), program_guard(main_p, startup):
                x = layers.data("x", [H])
                y = layers.data("y", [1])
                h = x
                for i in range(depth):
                    h = layers.fc(h, H, act="relu", name=f"ffn_{i}",
                                  param_attr=ParamAttr(
                                      initializer=ConstantInitializer(
                                          0.02)),
                                  bias_attr=False)
                pred = layers.fc(h, 1, name="head",
                                 param_attr=ParamAttr(
                                     initializer=ConstantInitializer(
                                         0.05)),
                                 bias_attr=False)
                loss = layers.mean(layers.square_error_cost(pred, y))
                fleet.init(is_collective=True)
                fleet.distributed_optimizer(MomentumOptimizer(0.02, 0.9))
                fleet.minimize(loss)
            rng = np.random.RandomState(0)
            X = rng.randn(P3D_BATCH * dp, H).astype("f4")
            feed = {"x": X,
                    "y": (X.sum(1, keepdims=True) * 0.05).astype("f4")}
            exe = pt.Executor(_default_place(), mesh=mesh_dp)
            try:
                scope = pt.framework.Scope()
                exe.run(startup, scope=scope)
                stat_reset("pass_overlap_stretched_buckets")
                warm = np.asarray(exe.run(main_p, feed=feed,
                                          fetch_list=[loss],
                                          scope=scope)[0]).item()
                exe.drain()
                stretched = int(
                    stat_get("pass_overlap_stretched_buckets"))
                return exe, scope, main_p, loss, feed, warm, stretched
            except BaseException:
                try:
                    exe.close()
                finally:
                    raise
        finally:
            pt.set_flags({"FLAGS_overlap_grad_allreduce": True,
                          "FLAGS_layer_scan": False})
            reset_mesh()

    # interleaved A/B (the request-trace bench pattern): one timed step
    # per schedule per round so host drift cancels; median per-step
    # wall time is the schedule's number.  The leg's OWN flags are
    # re-set before each timed step — both are affects_lowering, so a
    # step run under the other leg's flag state would re-key the pass/
    # compile caches and silently recompile BOTH legs onto one schedule
    # (the warm-up compiled each leg under its own state; matching it
    # here makes every timed call a cache hit)
    legs = {}
    times = {False: [], True: []}
    try:
        legs[False] = run_overlap(False)
        legs[True] = run_overlap(True)
        losses = {False: [legs[False][5]], True: [legs[True][5]]}
        compiles_before = stat_get("executor_compile")
        for _ in range(2 * P3D_STEPS):
            for ov in (False, True):
                exe, scope, main_p, loss, feed, _, _ = legs[ov]
                pt.set_flags({"FLAGS_overlap_grad_allreduce": ov,
                              "FLAGS_layer_scan": True})
                t0 = time.perf_counter()
                v = exe.run(main_p, feed=feed, fetch_list=[loss],
                            scope=scope)[0]
                losses[ov].append(np.asarray(v).item())
                times[ov].append(time.perf_counter() - t0)
        if stat_get("executor_compile") != compiles_before:
            raise RuntimeError(
                "overlap A/B timed steps recompiled — a leg ran under "
                "the other leg's flag state; the ratio would compare "
                "one schedule against itself")
    finally:
        pt.set_flags({"FLAGS_overlap_grad_allreduce": True,
                      "FLAGS_layer_scan": False})
        for leg in legs.values():
            # close even on the error paths: a leaked Executor keeps
            # its compiled fns + buffers alive for the rest of the
            # bench process
            try:
                leg[0].close()
            except Exception:  # noqa: BLE001 — closing is best-effort
                pass
    if losses[False] != losses[True]:
        raise RuntimeError(
            f"overlap A/B losses diverged — the bucket stretch must be "
            f"placement-only: {losses[False][:3]} vs {losses[True][:3]}")
    stretched = legs[True][6]
    if stretched < 1:
        raise RuntimeError(
            "overlapped schedule did not stretch any bucket at the "
            "scan boundary (pass_overlap_stretched_buckets == 0)")
    seq_p50 = float(np.median(times[False]))
    ovl_p50 = float(np.median(times[True]))
    hidden = max(seq_p50 - ovl_p50, 0.0)
    out["overlap_step_time_ms_p50"] = round(ovl_p50 * 1e3, 3)
    out["overlap_sequential_step_time_ms_p50"] = round(seq_p50 * 1e3, 3)
    if seq_p50 > 0:
        out["overlap_step_time_ratio"] = round(ovl_p50 / seq_p50, 4)
    out["overlap_hidden_comm_seconds"] = round(hidden, 6)
    out["overlap_stretched_buckets"] = stretched
    stat_set("overlap_hidden_comm_seconds_micro", int(hidden * 1e6))

    # ---- (B) pp×tp leg ---------------------------------------------------
    if n >= 4:
        if n >= 8:
            mesh_3d = jax.sharding.Mesh(
                np.array(devs[:8]).reshape(2, 2, 2), ("dp", "mp", "pp"))
            mesh_oracle = jax.sharding.Mesh(
                np.array(devs[:4]).reshape(2, 2), ("dp", "pp"))
        else:
            mesh_3d = jax.sharding.Mesh(
                np.array(devs[:4]).reshape(2, 2), ("mp", "pp"))
            mesh_oracle = jax.sharding.Mesh(np.array(devs[:2]), ("pp",))

        def run_3d(mesh, use_tp, timed=False):
            reset_mesh()
            if use_tp:
                set_mesh(mesh)
            try:
                main_p, startup, loss, feed = _megatron_pp_program(
                    pt, use_tp=use_tp)
                exe = pt.Executor(_default_place(), mesh=mesh)
                scope = pt.framework.Scope()
                exe.run(startup, scope=scope)
                losses = [np.asarray(exe.run(
                    main_p, feed=feed, fetch_list=[loss],
                    scope=scope)[0]).item()]
                if timed:
                    observe.reset_step_stats()
                t0 = time.perf_counter()
                for _ in range(P3D_STEPS):
                    losses.append(np.asarray(exe.run(
                        main_p, feed=feed, fetch_list=[loss],
                        scope=scope)[0]).item())
                exe.drain()
                dt = time.perf_counter() - t0
                mfu = observe.step_timer().summary().get("mfu") \
                    if timed else None
                exe.close()
                return losses, dt, mfu
            finally:
                reset_mesh()

        oracle, _, _ = run_3d(mesh_oracle, use_tp=False)
        got, dt, mfu = run_3d(mesh_3d, use_tp=True, timed=True)
        np.testing.assert_allclose(got, oracle, rtol=1e-4, atol=1e-6)
        out["bert_3d_tokens_per_sec"] = round(
            P3D_BATCH * P3D_STEPS / dt, 1)
        out["bert_3d_mesh"] = list(mesh_3d.devices.shape)
        out["bert_3d_loss_parity"] = True
        out["pp_bubble_fraction"] = round(
            stat_get("pp_bubble_fraction_ppm") / 1e6, 4)
        if mfu is not None:
            out["bert_3d_mfu_estimate"] = mfu
    return out


SERVE_CLIENTS = 32
SERVE_REQS = 256
SERVE_FEAT = 64
SERVE_SEQ_BUCKETS = (8, 16, 32, 64)
SERVE_BATCH_BUCKETS = (1, 2, 4, 8, 16)


def bench_serving(pt, jax):
    """Serving-layer throughput: rows(images)/sec for SERVE_REQS
    variable-length requests pushed by SERVE_CLIENTS concurrent clients
    through serving.Server's dynamic micro-batcher, vs the same request
    stream run one-at-a-time through the bare Predictor.  Both paths are
    measured steady-state (every shape warmed first), so the ratio is
    the pure batching win, not compile-storm avoidance (the tests pin
    that separately)."""
    import shutil
    import tempfile
    import threading

    from paddle_tpu import layers, serving
    from paddle_tpu.fluid import io as fluid_io
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.place import _default_place
    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.framework.scope import _switch_scope
    from paddle_tpu.inference import Config, create_predictor

    d = tempfile.mkdtemp(prefix="serving_bench_")
    try:
        main, startup = Program(), Program()
        main.random_seed = 11
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [-1, SERVE_FEAT])  # [-1, -1, feat]
            h = layers.fc(x, 256, num_flatten_dims=2, act="relu",
                          bias_attr=False)
            out = layers.reduce_sum(h, dim=1)
        sc = pt.framework.Scope()
        exe = pt.Executor(_default_place())
        exe.run(startup, scope=sc)
        old = _switch_scope(sc)
        try:
            fluid_io.save_inference_model(d, ["x"], [out], exe, main)
        finally:
            _switch_scope(old)

        rs = np.random.RandomState(0)
        # lengths drawn from the bucket grid keep the sequential path's
        # warmup to a handful of executables (this bench times steady
        # state, not compilation)
        reqs = [rs.randn(1 + rs.randint(4),
                         int(rs.choice(SERVE_SEQ_BUCKETS)),
                         SERVE_FEAT).astype("f4")
                for _ in range(SERVE_REQS)]
        rows = sum(r.shape[0] for r in reqs)

        pred = create_predictor(Config(d))
        for r in reqs:
            pred.run({"x": r})  # warm every raw shape
        t0 = time.perf_counter()
        for r in reqs:
            np.asarray(pred.run({"x": r})[0])
        seq_rps = rows / (time.perf_counter() - t0)

        srv = serving.Server(d, serving.ServingConfig(
            batch_sizes=SERVE_BATCH_BUCKETS, seq_lens=SERVE_SEQ_BUCKETS,
            batch_window_ms=2.0, max_queue=SERVE_REQS + SERVE_CLIENTS))
        srv.start()  # AOT-warms every bucket

        def client(chunk):
            for r in chunk:
                np.asarray(srv.infer({"x": r})[0])

        threads = [threading.Thread(target=client,
                                    args=(reqs[i::SERVE_CLIENTS],))
                   for i in range(SERVE_CLIENTS)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        srv_rps = rows / (time.perf_counter() - t0)
        srv.stop(drain=True)
        return srv_rps, seq_rps
    finally:
        shutil.rmtree(d, ignore_errors=True)


DECODE_SLOTS = 8
DECODE_REQS = 32
DECODE_VOCAB = 128
DECODE_MAX_SEQ = 64
DECODE_PAGE = 8
DECODE_MEAN_GAP_S = 0.001  # Poisson open-loop mean inter-arrival


def bench_decode(pt, jax):
    """Generative serving (paddle_tpu.serving.decode): one Poisson
    open-loop request stream run A-B through the SAME decode engine in
    continuous-batching mode vs one-shot group mode (the static
    bucket-batcher baseline: a new group only starts when every slot is
    free).  Emits decode_tokens_per_sec / ttft_ms_p99 / tpot_ms_p50 for
    the continuous engine, the one-shot counterparts, and the speedups
    — continuous batching must win BOTH throughput and tail TTFT.

    Also measures per-token throughput at 16 vs 128 generated tokens
    (8x) on an idle engine and ASSERTS the long run stays within 2x of
    the short one: a prefix-recompute engine would be ~8x slower per
    token at the long length, so this refutes recompute while leaving
    room for host timing noise (the in-test oracle pins bitwise cache
    correctness separately)."""
    from paddle_tpu.observe.histogram import histogram
    from paddle_tpu.serving.decode import (DecodeConfig, DecodeEngine,
                                           TransformerLM)

    model = TransformerLM(vocab_size=DECODE_VOCAB, d_model=64,
                          num_layers=2, num_heads=2, max_seq_len=256)
    weights = model.init_weights(jax.random.PRNGKey(0))
    cfg = DecodeConfig(slots=DECODE_SLOTS, max_seq_len=DECODE_MAX_SEQ,
                       page_size=DECODE_PAGE, max_queue=DECODE_REQS + 8)

    # one arrival schedule shared verbatim by both modes: (prompt,
    # new-token budget, inter-arrival gap) per request
    rs = np.random.RandomState(17)
    # high-variance generation budgets (8..48) are what one-shot group
    # admission pads away: the group runs to its LONGEST member while
    # finished slots sit idle
    schedule = [
        (list(rs.randint(1, DECODE_VOCAB, rs.randint(1, 13))),
         int(rs.randint(8, 49)),
         float(rs.exponential(DECODE_MEAN_GAP_S)))
        for _ in range(DECODE_REQS)
    ]

    def run_phase(continuous):
        eng = DecodeEngine(model, weights, cfg,
                           continuous=continuous).start()
        try:
            for plen in (4, 12):  # warm both prefill buckets + the step
                eng.generate(list(range(1, plen + 1)), max_new_tokens=2)
            histogram("tpot_seconds").reset()
            reqs = []
            t0 = time.perf_counter()
            for i, (prompt, n_new, gap) in enumerate(schedule):
                time.sleep(gap)  # open loop: arrivals don't wait
                reqs.append(eng.submit(prompt, max_new_tokens=n_new,
                                       seed=i))
            outs = [r.result(timeout=600) for r in reqs]
            wall = time.perf_counter() - t0
            toks = sum(len(o) for o in outs)
            ttfts = sorted(r.t_first_token - r.t_enqueue for r in reqs)
            tpot = histogram("tpot_seconds").summary()
        finally:
            eng.stop()
        return {
            "tokens_per_sec": toks / wall,
            "ttft_ms_p99": 1e3 * ttfts[
                min(len(ttfts) - 1, int(math.ceil(0.99 * len(ttfts))))],
            "tpot_ms_p50": 1e3 * tpot.get("p50", 0.0),
        }

    cont = run_phase(continuous=True)
    oneshot = run_phase(continuous=False)

    # cache-vs-recompute: per-token cost at 16 vs 128 (8x) generated
    # tokens on an idle single-slot engine.  Runs FIRST among the
    # single-engine phases (and after a gc of the A/B engines): dead
    # engines' device pools awaiting collection measurably inflate
    # per-dispatch cost, and this phase is the one with a hard bound.
    import gc

    gc.collect()
    eng = DecodeEngine(model, weights,
                       DecodeConfig(slots=1, max_seq_len=256,
                                    page_size=DECODE_PAGE)).start()
    try:
        eng.generate([1, 2], max_new_tokens=130)  # warm the long path
        # under prefix caching the repeats below are cache HITS — warm
        # that path too (prefill-skip + the one-time CoW executable)
        eng.generate([1, 2], max_new_tokens=2)
        t0 = time.perf_counter()
        for _ in range(4):
            eng.generate([1, 2], max_new_tokens=16)
        short_tps = 64 / (time.perf_counter() - t0)
        t0 = time.perf_counter()
        eng.generate([1, 2], max_new_tokens=128)
        long_tps = 128 / (time.perf_counter() - t0)
    finally:
        eng.stop()
    ratio = long_tps / short_tps
    if ratio < 0.5:
        raise RuntimeError(
            f"decode throughput fell {1 / ratio:.2f}x when the "
            f"generated length grew 8x ({short_tps:.0f} -> "
            f"{long_tps:.0f} tok/s) — the KV cache is not being "
            f"reused (prefix recompute)")
    gc.collect()

    # -- shared-prefix Poisson workload (prefix-cache tentpole) ----------
    # every prompt opens with the same 24-token system/template prefix
    # (3 full pages); the first completion registers it and every later
    # admission shares those pages and skips their prefill compute.
    # The same phase exercises the SLO/goodput plane (observe/slo.py):
    # a generous ttft p99 objective + the default error-rate objective,
    # so decode_goodput_rps / decode_slo_violations come from a real
    # open-loop run rather than a synthetic feed.
    from paddle_tpu.monitor import stat_get
    from paddle_tpu.observe import slo as slo_mod

    slo_mod.configure([
        # generous ttft target: mid-phase bucket compiles on a cold
        # CPU backend can cost seconds and are not the signal here
        slo_mod.Objective("ttft_p99", "ttft", 10.0, 0.01),
        slo_mod.Objective("error_rate", "error", None, 0.01),
    ])
    violations_before = stat_get("decode_slo_violations")
    shared_prefix = list(range(1, 25))
    eng = DecodeEngine(model, weights, cfg).start()
    try:
        eng.generate(shared_prefix + [99], max_new_tokens=4)  # register
        reqs = []
        for i in range(DECODE_REQS):
            time.sleep(float(rs.exponential(DECODE_MEAN_GAP_S)))
            tail = list(rs.randint(1, DECODE_VOCAB, rs.randint(1, 6)))
            reqs.append(eng.submit(shared_prefix + tail,
                                   max_new_tokens=int(rs.randint(4, 17)),
                                   seed=1000 + i))
        for r in reqs:
            r.result(timeout=600)
        st = eng.stats()
        cache_hit_rate = st["cache_hit_rate"]
        cow_copies = st["cow_copies"]
        # snapshot() forces a fresh window evaluation — the raw gauge
        # is refresh-throttled and may predate the last completions
        goodput_rps = slo_mod.snapshot()["goodput_rps"]
        slo_violations = stat_get("decode_slo_violations") \
            - violations_before
    finally:
        eng.stop()
    gc.collect()

    # -- request-trace overhead A/B --------------------------------------
    # closed-loop token burst (no open-loop sleeps to wash the signal
    # out) with head-sampling fully ON vs fully OFF; tracing records
    # either way (tail retention needs the timeline), sampling decides
    # retention — the ratio proves the recording path is ~free
    from paddle_tpu.framework import flags as flags_mod

    e = DecodeEngine(model, weights, DecodeConfig(
        slots=1, max_seq_len=64, page_size=DECODE_PAGE,
        prefix_cache=False)).start()
    try:
        e.generate([1, 2], max_new_tokens=50)  # warm the whole path

        def trace_run(sample):
            flags_mod.set_flags({"request_trace_sample": sample})
            t0 = time.perf_counter()
            toks = len(e.generate([1, 2, 3], max_new_tokens=48))
            return toks / (time.perf_counter() - t0)

        # interleaved best-of-6 per mode: alternating runs on ONE warm
        # engine cancel host thermal/GC drift between the phases
        traced_tps = untraced_tps = 0.0
        for _ in range(6):
            traced_tps = max(traced_tps, trace_run(1.0))
            untraced_tps = max(untraced_tps, trace_run(0.0))
    finally:
        e.stop()
        flags_mod.set_flags({"request_trace_sample": 1.0})
        slo_mod.configure(None)
    trace_overhead_ratio = untraced_tps / max(traced_tps, 1e-9)
    gc.collect()

    # -- admission capacity at a FIXED pool: shared vs unshared ----------
    # each request needs 3 pages unshared; the 7-page pool then holds 2
    # concurrently.  With the 2-page prefix shared, every extra request
    # allocates only 1 fresh page.
    cap_prefix = list(range(1, 17))

    def peak_concurrency(prefix_cache):
        e = DecodeEngine(model, weights, DecodeConfig(
            slots=6, max_seq_len=64, page_size=8, num_pages=8,
            max_queue=16, prefix_cache=prefix_cache)).start()
        try:
            if prefix_cache:
                e.generate(cap_prefix + [50], max_new_tokens=5)
            rr = [e.submit(cap_prefix + [51 + i], max_new_tokens=6,
                           on_token=lambda t: time.sleep(0.05))
                  for i in range(6)]
            peak = 0
            t_end = time.perf_counter() + 20
            while time.perf_counter() < t_end \
                    and not all(r.done() for r in rr):
                peak = max(peak, e.live_slots)
                time.sleep(0.005)
            for r in rr:
                r.result(timeout=120)
        finally:
            e.stop()
        return peak

    cap_unshared = peak_concurrency(False)
    cap_shared = peak_concurrency(True)

    # -- speculative decoding A/B ----------------------------------------
    # accurate-draft regime (the trained-draft production case): the
    # draft is the target's first layer + shared embeddings/head, and
    # the target's SECOND layer writes a small residual, so proposals
    # usually match.  Acceptance is measured, never assumed — and the
    # output tokens must be bitwise-identical either way.
    spec_target = TransformerLM(vocab_size=DECODE_VOCAB, d_model=64,
                                num_layers=2, num_heads=2,
                                max_seq_len=256)
    tw = spec_target.init_weights(jax.random.PRNGKey(3))
    tw["layers"][1]["wo"] = tw["layers"][1]["wo"] * 0.05
    tw["layers"][1]["w2"] = tw["layers"][1]["w2"] * 0.05
    spec_draft = TransformerLM(vocab_size=DECODE_VOCAB, d_model=64,
                               num_layers=1, num_heads=2,
                               max_seq_len=256)
    dw = {k: tw[k] for k in ("tok_emb", "pos_emb", "lm_head", "lnf_g",
                             "lnf_b")}
    dw["layers"] = [tw["layers"][0]]
    spec_prompts = [[int(t) for t in rs.randint(1, DECODE_VOCAB, 6)]
                    for _ in range(4)]

    def spec_phase(spec_k, draft):
        e = DecodeEngine(spec_target, tw, DecodeConfig(
            slots=4, max_seq_len=128, page_size=8, spec_k=spec_k,
            prefix_cache=False),
            draft_model=draft[0] if draft else None,
            draft_weights=draft[1] if draft else None).start()
        try:
            e.generate([1, 2], max_new_tokens=4)  # pay the compiles
            t0 = time.perf_counter()
            outs = [e.generate(p, max_new_tokens=64)
                    for p in spec_prompts]
            wall = time.perf_counter() - t0
            st = e.stats()
        finally:
            e.stop()
        toks = sum(len(o) for o in outs)
        return outs, toks / wall, st

    gc.collect()  # spec A/B on a clean heap, same as the other phases
    base_outs, base_tps, _ = spec_phase(0, None)
    spec_outs, spec_tps, spec_st = spec_phase(4, (spec_draft, dw))
    if spec_outs != base_outs:
        raise RuntimeError(
            "speculative greedy output diverged from non-speculative "
            "decode — the lossless-acceptance contract is broken")
    spec_speedup = spec_tps / base_tps

    # -- quantized KV cache A/B at a FIXED pool byte budget --------------
    # the pool is sized in BYTES (what the chip actually has), so int8
    # pages + their scale planes fit ~2x the page count of bf16 pages —
    # which is ~2x the concurrent slots the admission reservation covers
    from paddle_tpu.monitor import stat_set
    from paddle_tpu.serving.kv_cache import CacheConfig

    def _kv_cfg(quantized, num_pages):
        return CacheConfig(model.num_layers, model.num_heads,
                           model.head_dim, num_slots=12, max_seq_len=64,
                           page_size=8, num_pages=num_pages,
                           dtype="bfloat16", quantized=quantized)

    kv_budget = _kv_cfg(False, 13).cache_bytes()  # bf16 pool: 13 pages
    q_pages = kv_budget // _kv_cfg(True, 2).per_page_pool_bytes()

    def kv_capacity(kv_quant, num_pages):
        # each request reserves exactly 2 pages (10 prompt + 6 new at
        # page 8); slots (12) exceed what either pool can admit, so the
        # measured peak is page-bound — the quantity under test
        e = DecodeEngine(model, weights, DecodeConfig(
            slots=12, max_seq_len=64, page_size=8,
            num_pages=int(num_pages), max_queue=16, prefix_cache=False,
            kv_quant=kv_quant, cache_dtype="bfloat16")).start()
        try:
            rr = [e.submit(list(rs.randint(1, DECODE_VOCAB, 10)),
                           max_new_tokens=6,
                           on_token=lambda t: time.sleep(0.05))
                  for i in range(12)]
            peak = 0
            t_end = time.perf_counter() + 30
            while time.perf_counter() < t_end \
                    and not all(r.done() for r in rr):
                peak = max(peak, e.live_slots)
                time.sleep(0.005)
            for r in rr:
                r.result(timeout=120)
        finally:
            e.stop()
        return peak

    kv_cap_base = kv_capacity(False, 13)
    kv_cap_quant = kv_capacity(True, q_pages)
    gc.collect()

    # quantized throughput + the quality tax, measured never assumed:
    # teacher-forced greedy top-1 agreement and max-abs-logit delta of
    # the quantized run against the full-precision recompute oracle
    from paddle_tpu.ops.quant_ops import quant_quality_delta

    def kv_phase(kv_quant):
        e = DecodeEngine(model, weights, DecodeConfig(
            slots=4, max_seq_len=128, page_size=DECODE_PAGE,
            prefix_cache=False, kv_quant=kv_quant)).start()
        try:
            e.generate([1, 2], max_new_tokens=4)  # pay the compiles
            t0 = time.perf_counter()
            reqs = [e.submit(p, max_new_tokens=32, record_logits=True)
                    for p in spec_prompts]
            outs = [r.result(timeout=600) for r in reqs]
            wall = time.perf_counter() - t0
            oracle = None
            if kv_quant:
                # teacher-forced: the oracle replays the QUANTIZED
                # run's own tokens so logits stay position-comparable
                oracle = [
                    np.stack([e.recompute_logits(list(p) + o[:t])
                              for t in range(len(o))])
                    for p, o in zip(spec_prompts, outs)]
                quant_logits = [np.stack(r.logits_trace)
                                for r in reqs]
        finally:
            e.stop()
        toks = sum(len(o) for o in outs)
        if not kv_quant:
            return toks / wall, None
        delta = quant_quality_delta(np.concatenate(quant_logits),
                                    np.concatenate(oracle))
        return toks / wall, delta

    kv_base_tps, _ = kv_phase(False)
    kv_quant_tps, kv_delta = kv_phase(True)
    stat_set("decode_kv_quant_top1_agreement_ppm",
             int(kv_delta["top1_agreement"] * 1e6))
    gc.collect()

    # -- ragged prefill packing A/B (flash-attention PR serving leg) ------
    # the SAME Poisson arrival schedule run with chunked prefill, padded
    # per-slot dispatches vs ragged lane packing (several prompts' tails
    # in one multi-row dispatch): outputs must be identical and the
    # measured prefill_pad_waste (padded fraction of dispatched prefill
    # rows, from serving/buckets.record_pad_waste) must DROP.
    from paddle_tpu.monitor import stat_reset

    def ragged_phase(lanes):
        for name in ("prefill_pad_waste", "prefill_padded_tokens_total",
                     "prefill_live_tokens_total"):
            stat_reset(name)
        e = DecodeEngine(model, weights, DecodeConfig(
            slots=DECODE_SLOTS, max_seq_len=DECODE_MAX_SEQ,
            page_size=DECODE_PAGE, max_queue=DECODE_REQS + 8,
            prefill_chunk_pages=1, prefix_cache=False,
            ragged_prefill_rows=lanes)).start()
        try:
            rr = []
            for i, (prompt, n_new, gap) in enumerate(schedule):
                time.sleep(gap)
                rr.append(e.submit(prompt, max_new_tokens=n_new, seed=i))
            outs = [r.result(timeout=600) for r in rr]
        finally:
            e.stop()
        return outs, stat_get("prefill_pad_waste") / 1e6

    padded_outs, padded_waste = ragged_phase(0)
    ragged_outs, ragged_waste = ragged_phase(16)
    if ragged_outs != padded_outs:
        raise RuntimeError(
            "ragged prefill packing changed decoded tokens — the "
            "per-lane chunk-equivalence contract is broken")
    if padded_waste > 0 and ragged_waste >= padded_waste:
        raise RuntimeError(
            f"ragged packing did not reduce prefill pad waste "
            f"({padded_waste:.4f} -> {ragged_waste:.4f})")
    gc.collect()

    return {
        "prefill_pad_waste_padded": round(padded_waste, 4),
        "prefill_pad_waste_ragged": round(ragged_waste, 4),
        "prefill_pad_waste_reduction": round(
            padded_waste / max(ragged_waste, 1e-9), 3),
        "decode_kv_quant_capacity": kv_cap_quant,
        "decode_kv_unquant_capacity": kv_cap_base,
        "decode_kv_quant_capacity_ratio": round(
            kv_cap_quant / max(kv_cap_base, 1), 3),
        "decode_kv_quant_pool_pages": int(q_pages),
        "decode_kv_unquant_pool_pages": 13,
        "decode_kv_quant_tokens_per_sec": round(kv_quant_tps, 1),
        "decode_kv_unquant_tokens_per_sec": round(kv_base_tps, 1),
        "decode_kv_quant_speedup": round(
            kv_quant_tps / max(kv_base_tps, 1e-9), 3),
        "decode_kv_quant_top1_agreement": round(
            kv_delta["top1_agreement"], 4),
        "decode_kv_quant_max_abs_logit_delta": round(
            kv_delta["max_abs_logit_delta"], 6),
        "decode_tokens_per_sec": round(cont["tokens_per_sec"], 1),
        "ttft_ms_p99": round(cont["ttft_ms_p99"], 3),
        "tpot_ms_p50": round(cont["tpot_ms_p50"], 3),
        "decode_oneshot_tokens_per_sec": round(
            oneshot["tokens_per_sec"], 1),
        "decode_oneshot_ttft_ms_p99": round(oneshot["ttft_ms_p99"], 3),
        "decode_continuous_speedup": round(
            cont["tokens_per_sec"] / oneshot["tokens_per_sec"], 3),
        "decode_ttft_p99_improvement": round(
            oneshot["ttft_ms_p99"] / cont["ttft_ms_p99"], 3),
        "decode_seqlen8x_throughput_ratio": round(ratio, 3),
        "decode_cache_hit_rate": round(cache_hit_rate, 4),
        "decode_cow_copies": cow_copies,
        "decode_goodput_rps": round(goodput_rps, 3),
        "decode_slo_violations": int(slo_violations),
        "request_trace_overhead_ratio": round(trace_overhead_ratio, 4),
        "decode_shared_admission_capacity": cap_shared,
        "decode_unshared_admission_capacity": cap_unshared,
        "decode_shared_admission_capacity_ratio": round(
            cap_shared / max(cap_unshared, 1), 3),
        "decode_spec_tokens_per_sec": round(spec_tps, 1),
        "decode_baseline_tokens_per_sec": round(base_tps, 1),
        "decode_spec_speedup": round(spec_speedup, 3),
        "decode_spec_accept_rate": round(spec_st["spec_accept_rate"], 4),
    }


DISAGG_REQS = 24


def bench_disagg(pt, jax):
    """Disaggregated prefill/decode serving (serving/disagg.py), four
    legs, each asserted in-bench:

    1. **Migration oracle**: the same seeded request served
       disaggregated (prefill replica -> KV-page migration -> decode
       replica) must produce BITWISE the tokens of a local
       prefill+decode — plain and kv_quant pools both.
    2. **Goodput A/B at a FIXED fleet of 2**: a mixed
       long-prompt-adversary / short-chat Poisson stream through a
       1 prefill + 1 decode DisaggServer vs a 2-replica unified
       DecodeServer running chunked prefill (the best co-located
       mitigation).  Goodput counts requests whose per-request TPOT
       stays within 2x the idle-engine decode floor — the quantity a
       co-located long prefill steals and disaggregation protects.
       Disagg must win goodput, and its ttft p99 must HOLD (<= 1.5x
       unified) — the decode-side win cannot come from starving
       prefill.
    3. **Chaos**: a prefill replica hard-killed mid-stream
       (``kill_prefill_replica``) must drop ZERO requests — the router
       re-dispatches the orphaned legs to the survivor.
    4. **Autoscaler**: real induced ttft burn (an impossible SLO
       objective over real traffic) must re-role a decode replica to
       the prefill set via the REAL burn signal, and the cooldown must
       suppress the immediate retrigger (no flapping).
    """
    import gc

    from paddle_tpu.distributed.fleet.elastic import chaos
    from paddle_tpu.monitor import stat_get
    from paddle_tpu.observe import slo as slo_mod
    from paddle_tpu.serving.decode import (DecodeConfig, DecodeEngine,
                                           TransformerLM)
    from paddle_tpu.serving.disagg import (Autoscaler, DisaggConfig,
                                           DisaggServer)
    from paddle_tpu.serving.server import DecodeServer

    model = TransformerLM(vocab_size=DECODE_VOCAB, d_model=64,
                          num_layers=2, num_heads=2, max_seq_len=256)
    weights = model.init_weights(jax.random.PRNGKey(1))

    # -- leg 1: migrated-vs-local bitwise oracle -------------------------
    def bitwise_leg(kv_quant):
        cfg = DecodeConfig(slots=2, max_seq_len=32, page_size=8,
                           prefix_cache=False, kv_quant=kv_quant)
        prompts = [[5, 4, 3, 2, 1, 6, 7, 8], list(range(1, 14))]
        srv = DisaggServer(model, weights, config=cfg,
                           disagg=DisaggConfig(prefill_replicas=1,
                                               decode_replicas=1))
        with srv:
            rr = [srv.submit(p, max_new_tokens=4, temperature=1.0,
                             seed=40 + i)
                  for i, p in enumerate(prompts)]
            douts = [r.result(timeout=300) for r in rr]
        eng = DecodeEngine(model, weights, cfg).start()
        try:
            louts = [eng.submit(p, max_new_tokens=4, temperature=1.0,
                                seed=40 + i).result(timeout=300)
                     for i, p in enumerate(prompts)]
        finally:
            eng.stop()
        if douts != louts:
            raise RuntimeError(
                f"migrated decode diverged from local prefill "
                f"(kv_quant={kv_quant}): {douts} vs {louts}")

    bitwise_leg(False)
    bitwise_leg(True)
    gc.collect()

    # -- leg 2: goodput A/B at a fixed fleet of 2 ------------------------
    rs = np.random.RandomState(23)
    # every other request is a 48-token adversary (6 pages of prefill);
    # the rest are short chats whose decode stream is what the
    # co-located prefills interrupt
    schedule = []
    for i in range(DISAGG_REQS):
        if i % 2 == 0:
            prompt, n_new = list(rs.randint(1, DECODE_VOCAB, 48)), 4
        else:
            prompt = list(rs.randint(1, DECODE_VOCAB,
                                     rs.randint(2, 7)))
            n_new = 16
        schedule.append((prompt, n_new, float(rs.exponential(0.002))))

    def _cfg(chunked):
        # unified replicas chunk their prefills (protecting co-located
        # decoders is the point of chunking); the dedicated prefill
        # replica has no decoders to protect and runs whole-prompt
        # prefill — each system gets its best configuration
        return DecodeConfig(slots=8, max_seq_len=64, page_size=8,
                            max_queue=DISAGG_REQS + 8,
                            prefix_cache=False,
                            prefill_chunk_pages=1 if chunked else 0)

    # the goodput budget: 2x the pure-decode TPOT floor of an idle warm
    # engine — requests a co-located prefill pushed past that lost the
    # latency the disaggregation is buying
    eng = DecodeEngine(model, weights, _cfg(False)).start()
    try:
        eng.generate([1, 2], max_new_tokens=33)  # pay the compiles
        r = eng.submit([1, 2], max_new_tokens=33)
        r.result(timeout=300)
        t_base = (r.t_last_token - r.t_first_token) / 32
    finally:
        eng.stop()
    tpot_budget = 2.0 * t_base

    def phase_metrics(reqs, wall):
        ttfts = sorted(r.t_first_token - r.t_enqueue for r in reqs)
        p99 = ttfts[min(len(ttfts) - 1,
                        int(math.ceil(0.99 * len(ttfts))))]
        good = 0
        for r in reqs:
            dr = getattr(r, "decode_request", r)
            n = len(dr.generated)
            if n >= 2 and dr.t_last_token is not None \
                    and dr.t_first_token is not None:
                tpot = (dr.t_last_token - dr.t_first_token) / (n - 1)
            else:
                tpot = 0.0
            good += tpot <= tpot_budget
        return {"goodput_rps": good / wall, "ttft_ms_p99": 1e3 * p99}

    def run_stream(submit):
        reqs = []
        t0 = time.perf_counter()
        for i, (prompt, n_new, gap) in enumerate(schedule):
            time.sleep(gap)  # open loop: arrivals don't wait
            reqs.append(submit(prompt, max_new_tokens=n_new, seed=i))
        for r in reqs:
            r.result(timeout=600)
        return reqs, time.perf_counter() - t0

    usrv = DecodeServer(model, weights, _cfg(True), replicas=2)
    usrv.start()
    try:
        for e in usrv._engines:  # warm both replicas' executables
            e.generate(schedule[0][0], max_new_tokens=2)
            e.generate([1, 2, 3], max_new_tokens=2)
        ureqs, uwall = run_stream(usrv.submit)
    finally:
        usrv.stop()
    uni = phase_metrics(ureqs, uwall)
    gc.collect()

    dsrv = DisaggServer(model, weights, config=_cfg(False),
                        disagg=DisaggConfig(prefill_replicas=1,
                                            decode_replicas=1))
    with dsrv:
        dsrv.generate(schedule[0][0], max_new_tokens=2)  # warm both
        dsrv.generate([1, 2, 3], max_new_tokens=2)       # roles' paths
        dreqs, dwall = run_stream(dsrv.submit)
        dstats = dsrv.stats()
    dis = phase_metrics(dreqs, dwall)
    gc.collect()

    if dis["goodput_rps"] <= uni["goodput_rps"]:
        raise RuntimeError(
            f"disaggregation did not beat the unified fleet on decode "
            f"goodput at a fixed replica count "
            f"({dis['goodput_rps']:.3f} <= {uni['goodput_rps']:.3f} "
            f"rps, tpot budget {tpot_budget * 1e3:.2f}ms)")
    if dis["ttft_ms_p99"] > 1.5 * uni["ttft_ms_p99"]:
        raise RuntimeError(
            f"disagg ttft p99 did not hold under the long-prompt "
            f"adversary ({dis['ttft_ms_p99']:.1f}ms vs unified "
            f"{uni['ttft_ms_p99']:.1f}ms with chunked prefill alone)")

    # -- leg 3: chaos — prefill replica death, zero drops ----------------
    deaths0 = stat_get("disagg_replica_deaths")
    redisp0 = stat_get("disagg_redispatches_total")
    chaos.clear()
    chaos.inject("kill_prefill_replica", count=1, replica=0)
    try:
        csrv = DisaggServer(model, weights, config=_cfg(False),
                            disagg=DisaggConfig(prefill_replicas=2,
                                                decode_replicas=1))
        with csrv:
            rr = [csrv.submit([3 + i, 5, 7, 9, 2], max_new_tokens=4,
                              seed=50 + i) for i in range(6)]
            outs = [r.result(timeout=600) for r in rr]
    finally:
        chaos.clear()
    chaos_dropped = sum(1 for o in outs if len(o) != 4)
    if chaos_dropped:
        raise RuntimeError(
            f"prefill replica death dropped {chaos_dropped}/6 requests "
            f"— the re-dispatch path is broken")
    chaos_deaths = stat_get("disagg_replica_deaths") - deaths0
    chaos_redispatches = stat_get("disagg_redispatches_total") - redisp0
    gc.collect()

    # -- leg 4: autoscaler re-role under REAL induced burn ---------------
    # an impossible ttft objective makes every completed request a
    # violation, so the DEFAULT burn signal (observe/slo.py snapshot)
    # fires — nothing about the trigger is simulated except the SLO bar
    slo_mod.configure([
        slo_mod.Objective("ttft_p99", "ttft", 1e-6, 0.01)])
    try:
        asrv = DisaggServer(
            model, weights,
            config=DecodeConfig(slots=2, max_seq_len=32, page_size=8,
                                prefix_cache=False),
            disagg=DisaggConfig(prefill_replicas=1, decode_replicas=3,
                                autoscale_cooldown_s=3600.0))
        with asrv:
            rr = [asrv.submit([9, 8, 7], max_new_tokens=4, seed=70 + i)
                  for i in range(4)]
            for r in rr:
                r.result(timeout=600)
            auto = Autoscaler(asrv, queue_fn=lambda: 0.0,
                              preflight=lambda: True)
            reroles0 = stat_get("autoscale_reroles_total")
            skips0 = stat_get("autoscale_cooldown_skips_total")
            first = auto.tick()
            second = auto.tick()
    finally:
        slo_mod.configure(None)
    if first != "decode->prefill":
        raise RuntimeError(
            f"induced ttft burn did not re-role a decode replica "
            f"(tick -> {first!r})")
    if second is not None \
            or stat_get("autoscale_cooldown_skips_total") != skips0 + 1:
        raise RuntimeError(
            "the cooldown did not suppress the immediate re-trigger — "
            "the autoscaler flapped")
    autoscale_reroles = stat_get("autoscale_reroles_total") - reroles0
    gc.collect()

    return {
        "disagg_migrated_bitwise_ok": 1,
        "disagg_goodput_rps": round(dis["goodput_rps"], 3),
        "unified_goodput_rps": round(uni["goodput_rps"], 3),
        "disagg_goodput_improvement": round(
            dis["goodput_rps"] / max(uni["goodput_rps"], 0.001), 3),
        "disagg_ttft_ms_p99": round(dis["ttft_ms_p99"], 3),
        "unified_ttft_ms_p99": round(uni["ttft_ms_p99"], 3),
        "disagg_ttft_p99_improvement": round(
            uni["ttft_ms_p99"] / max(dis["ttft_ms_p99"], 1e-9), 3),
        "disagg_tpot_budget_ms": round(tpot_budget * 1e3, 3),
        "disagg_handoffs": int(dstats["handoffs_total"]),
        "disagg_migrate_pages": int(dstats["migrate_pages_total"]),
        "disagg_migrate_bytes": int(dstats["migrate_bytes_total"]),
        "disagg_chaos_dropped": int(chaos_dropped),
        "disagg_chaos_replica_deaths": int(chaos_deaths),
        "disagg_chaos_redispatches": int(chaos_redispatches),
        "autoscale_reroles": int(autoscale_reroles),
        "autoscale_cooldown_skips": int(
            stat_get("autoscale_cooldown_skips_total") - skips0),
    }


def bench_quant(pt, jax):
    """Weight-only quantized inference (slim PostTrainingWeightQuantPass
    + ops/quant_ops.dequant_matmul): a matmul-heavy inference program
    run bf16-precision vs FLAGS_weight_quant=int8, emitting (1) the PR 8
    ``hbm_required_bytes`` ratio — the executable no longer takes the
    f32 weights as arguments, only the int8 carriers + scales, so the
    predicted per-chip footprint should drop well below the 0.55x
    acceptance bar — and (2) the ``quant_quality_delta`` report
    (max-abs-logit delta + greedy top-1 agreement over a fixed eval
    batch, mirrored onto /metrics as gauges)."""
    import numpy as np

    from paddle_tpu import layers
    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.monitor import stat_get
    from paddle_tpu.ops.quant_ops import quant_quality_delta

    # equal-width stack: XLA reuses ONE dequant temp buffer across the
    # layers, so the carrier savings dominate the footprint even on the
    # CPU reference path (the TPU Pallas path never materializes the
    # dequantized weight at all)
    depth, width, classes, batch = 6, 1024, 16, 64
    main_p, startup = Program(), Program()
    main_p.random_seed = 11
    with program_guard(main_p, startup):
        x = layers.data("x", [width])
        h = x
        for _ in range(depth):
            h = layers.fc(h, width, act="relu")
        logits = layers.fc(h, classes, bias_attr=False)
    exe = pt.Executor()
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    feed = {"x": np.random.RandomState(5).randn(batch, width)
            .astype("f4")}

    def phase(quant):
        pt.set_flags({"FLAGS_weight_quant": "int8" if quant else ""})
        try:
            out = np.asarray(exe.run(main_p, feed=feed,
                                     fetch_list=[logits],
                                     scope=scope)[0])
            t0 = time.perf_counter()
            for _ in range(8):
                out = np.asarray(exe.run(main_p, feed=feed,
                                         fetch_list=[logits],
                                         scope=scope)[0])
            wall = time.perf_counter() - t0
        finally:
            pt.set_flags({"FLAGS_weight_quant": ""})
        return out, stat_get("hbm_required_bytes"), wall / 8

    ref, hbm_ref, t_ref = phase(False)
    q, hbm_q, t_q = phase(True)
    delta = quant_quality_delta(q, ref)
    out = {
        "quant_quality_delta": {
            "max_abs_logit_delta": round(
                delta["max_abs_logit_delta"], 6),
            "top1_agreement": round(delta["top1_agreement"], 4),
        },
        "quant_quality_top1_agreement": round(
            delta["top1_agreement"], 4),
        "weight_quant_step_time_ratio": round(
            t_q / max(t_ref, 1e-9), 3),
    }
    if hbm_ref and hbm_q:
        # PR 8 accounting: predicted per-chip executable footprint;
        # absent (no memory_analysis on this jax) the ratio is omitted
        # rather than guessed
        out["weight_quant_hbm_bytes"] = int(hbm_q)
        out["weight_quant_baseline_hbm_bytes"] = int(hbm_ref)
        out["weight_quant_hbm_ratio"] = round(hbm_q / hbm_ref, 3)
    return out


FLASH_SEQS = (512, 1024, 2048, 4096)  # hbm sweep (ISSUE 17: 512 -> 4k)
FLASH_GATE_SEQ = 2048                 # acceptance: ratio < 0.6 here
FLASH_PARITY_SEQ = 512                # loss-parity + step-time leg
FLASH_PARITY_STEPS = 5


def bench_flash_attention(pt, jax):
    """Flash-attention training A/B (ISSUE 17): a 1-layer unfused-chain
    BERT at growing seq lens, FLAGS_flash_attention never (the
    matmul/softmax oracle) vs always (FlashAttentionPass rewrite; the
    Pallas kernels engage in interpret mode off-TPU via the
    ``fused._FORCE_INTERPRET`` hook so the tiled O(N) memory shape is
    what XLA actually compiles).  Emits the ``flash_attn_hbm_ratio``
    sweep (fused vs unfused ``hbm_required_bytes``), the
    MFU-at-identical-config pair (program IR FLOPs are identical by
    construction — hapi/model_stat prices the fused op as the two
    contractions it replaced), and runs the PR 8 budget-gate assert:
    with the capacity pinned to 0.6x the unfused footprint, the
    unfused compile must be REFUSED (MemoryBudgetError before
    dispatch) while the fused one passes — the acceptance bar as an
    executable check."""
    import numpy as np

    from paddle_tpu.framework import flags as _fl
    from paddle_tpu.framework.program import program_guard
    from paddle_tpu.hapi.model_stat import program_flops
    from paddle_tpu.monitor import stat_get
    from paddle_tpu.observe import mfu_estimate
    from paddle_tpu.observe.xla_stats import MemoryBudgetError
    from paddle_tpu.ops import fused as _fused
    from paddle_tpu.text import bert_base_pretrain_program

    B, HID, HEADS, VOCAB, PREDS = 1, 128, 2, 512, 4

    def build(seq):
        main, startup, _, loss, opt = bert_base_pretrain_program(
            batch_size=B, seq_len=seq, vocab_size=VOCAB, hidden=HID,
            n_layers=1, n_heads=HEADS, ffn_size=2 * HID,
            dropout_prob=0.0, max_preds_per_seq=PREDS,
            use_fused_attention=False)
        main.random_seed = startup.random_seed = 7
        with program_guard(main, startup):
            opt.minimize(loss)
        return main, startup, loss

    def feed(seq):
        rng = np.random.RandomState(3)
        ids = rng.randint(0, VOCAB, (B, seq)).astype("int64")
        flat_pos = np.concatenate(
            [b * seq + rng.choice(seq, PREDS, replace=False)
             for b in range(B)]).astype("int64")
        return {
            "input_ids": ids,
            "token_type_ids": np.zeros((B, seq), "int64"),
            # max_pos embedding is 512-wide; wrap longer sweeps (the
            # bench measures memory shape, not modelling quality)
            "pos_ids": np.tile(np.arange(seq, dtype="int64") % 512,
                               (B, 1)),
            "input_mask": np.zeros((B, 1, 1, seq), "float32"),
            "masked_flat_pos": flat_pos,
            "masked_labels": ids.reshape(-1)[flat_pos]
            .reshape(-1, 1).astype("int64"),
            "masked_weights": np.ones((B * PREDS, 1), "float32"),
            "nsp_labels": rng.randint(0, 2, (B, 1)).astype("int64"),
        }

    def phase(seq, mode, steps=1, capacity=0):
        """One fresh program+Executor under FLAGS_flash_attention=mode
        (the pass rewrites the program IN PLACE, so phases never share
        a Program).  Returns (losses, hbm_required_bytes,
        sec_per_step, program_flops_after_lowering)."""
        old_mode = _fl.flag("flash_attention")
        old_int = _fused._FORCE_INTERPRET
        try:
            pt.set_flags({
                "FLAGS_flash_attention": mode,
                "FLAGS_hbm_bytes_per_device": int(capacity),
                "FLAGS_hbm_budget_fraction": 1.0 if capacity else 0.0,
            })
            _fused._FORCE_INTERPRET = (mode == "always")
            main, startup, loss = build(seq)
            exe = pt.Executor()
            scope = pt.framework.Scope()
            exe.run(startup, scope=scope)
            fd = feed(seq)
            losses, t0 = [], None
            for i in range(steps):
                out = exe.run(main, feed=fd, fetch_list=[loss],
                              scope=scope)
                losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
                if i == 0:
                    t0 = time.perf_counter()
            sec = ((time.perf_counter() - t0) / (steps - 1)
                   if steps > 1 else 0.0)
            return losses, stat_get("hbm_required_bytes"), sec, \
                program_flops(main)
        finally:
            _fused._FORCE_INTERPRET = old_int
            pt.set_flags({"FLAGS_flash_attention": old_mode,
                          "FLAGS_hbm_bytes_per_device": 0,
                          "FLAGS_hbm_budget_fraction": 0.0})

    out = {"flash_attn_hbm_sweep": {}}

    # --- parity + step-time leg (identical config, both modes) ---------
    ref_losses, hbm_ref, t_ref, fl_ref = phase(
        FLASH_PARITY_SEQ, "never", steps=FLASH_PARITY_STEPS)
    fused_losses, hbm_fused, t_fused, fl_fused = phase(
        FLASH_PARITY_SEQ, "always", steps=FLASH_PARITY_STEPS)
    drift = max(abs(a - b) for a, b in zip(ref_losses, fused_losses))
    if not (np.isfinite(drift) and drift <= 1e-4):
        raise RuntimeError(
            f"flash-attention loss parity broke: max |fused - unfused| "
            f"over {FLASH_PARITY_STEPS} steps = {drift} (> 1e-4) at "
            f"seq {FLASH_PARITY_SEQ}")
    out["flash_attn_loss_drift"] = float(f"{drift:.3g}")
    if fl_ref != fl_fused:
        raise RuntimeError(
            f"program FLOPs moved under the rewrite ({fl_ref} -> "
            f"{fl_fused}): MFU is no longer comparable at identical "
            f"config (hapi/model_stat pricing bug)")
    # identical-config MFU pair: same numerator by construction, so on
    # TPU this moves iff the step time moves; peak pinned to 1 TFLOP/s
    # so the pair is comparable even where FLAGS_device_peak_tflops is
    # unset for the host
    if t_ref > 0:
        out["flash_attn_bert_mfu_unfused"] = float(
            f"{mfu_estimate(fl_ref, t_ref, 1.0):.4g}")
    if t_fused > 0:
        out["flash_attn_bert_mfu_fused"] = float(
            f"{mfu_estimate(fl_fused, t_fused, 1.0):.4g}")
    out["flash_attn_hbm_sweep"][FLASH_PARITY_SEQ] = {
        "unfused_bytes": int(hbm_ref), "fused_bytes": int(hbm_fused)}

    # --- hbm sweep 512 -> 4k -------------------------------------------
    for seq in FLASH_SEQS:
        if seq == FLASH_PARITY_SEQ:
            continue
        _, h_ref, _, _ = phase(seq, "never", steps=1)
        _, h_fused, _, _ = phase(seq, "always", steps=1)
        out["flash_attn_hbm_sweep"][seq] = {
            "unfused_bytes": int(h_ref), "fused_bytes": int(h_fused)}
    for seq, row in out["flash_attn_hbm_sweep"].items():
        if row["unfused_bytes"] and row["fused_bytes"]:
            row["ratio"] = round(
                row["fused_bytes"] / row["unfused_bytes"], 4)

    gate_row = out["flash_attn_hbm_sweep"].get(FLASH_GATE_SEQ, {})
    if not (gate_row.get("unfused_bytes") and gate_row.get("fused_bytes")):
        # no memory_analysis on this jax: the accounting keys are
        # omitted rather than guessed (bench_quant convention) and the
        # budget-gate assert cannot run
        out["flash_attn_budget_gate"] = "skipped (no memory_analysis)"
        return out
    out["flash_attn_hbm_ratio"] = gate_row["ratio"]

    # --- budget-gate assert: capacity = 0.6x the unfused footprint -----
    capacity = int(0.6 * gate_row["unfused_bytes"])
    try:
        phase(FLASH_GATE_SEQ, "never", steps=1, capacity=capacity)
        raise RuntimeError(
            f"hbm budget gate did NOT refuse the unfused chain at seq "
            f"{FLASH_GATE_SEQ} with capacity {capacity} (unfused "
            f"footprint {gate_row['unfused_bytes']})")
    except MemoryBudgetError:
        pass
    phase(FLASH_GATE_SEQ, "always", steps=1, capacity=capacity)  # passes
    out["flash_attn_budget_gate"] = {
        "capacity_bytes": capacity,
        "unfused_rejected": True,
        "fused_passed": True,
    }
    return out


CKPT_ARRAYS = 16
CKPT_ARRAY_ELEMS = 1 << 20  # 16 x 4MB fp32 = 64MB per checkpoint
CKPT_SAVES = 5


def bench_checkpoint(pt):
    """Blocking-time-per-save of the async checkpoint manager
    (paddle_tpu.ckpt) on a 64MB synthetic state: save() should block
    only for the host snapshot hand-off while the writer thread does
    serialization + fsync + manifest commit off the step loop.  Returns
    (mean blocking ms, p50 full-write ms from the ckpt_write_seconds
    histogram, MB per save)."""
    import shutil
    import tempfile

    import numpy as np

    from paddle_tpu import observe
    from paddle_tpu.ckpt import CheckpointManager

    rs = np.random.RandomState(0)
    state = {f"w{i}": rs.standard_normal(CKPT_ARRAY_ELEMS).astype("f4")
             for i in range(CKPT_ARRAYS)}
    mb = sum(a.nbytes for a in state.values()) / 2 ** 20
    d = tempfile.mkdtemp(prefix="ckpt_bench_")
    try:
        observe.histogram("ckpt_write_seconds").reset()
        m = CheckpointManager(d, keep_n=2, async_save=True)
        m.save(0, state=state, wait=True)  # warm the writer thread
        blocking = []
        for s in range(1, CKPT_SAVES + 1):
            t0 = time.perf_counter()
            m.save(s, state=state)
            blocking.append(time.perf_counter() - t0)
            m.wait()  # measure every save (no coalescing in the bench)
        m.close()
        hist = observe.histogram("ckpt_write_seconds").summary()
        return (1e3 * sum(blocking) / len(blocking),
                1e3 * hist.get("p50", 0.0), mb)
    finally:
        shutil.rmtree(d, ignore_errors=True)


FUSION_NRANKS = 4


def bench_allreduce_fusion(pt):
    """Comm-op count pre/post the fused-allreduce graph pass
    (framework/passes.py) on the ResNet-50 train program transpiled for
    FUSION_NRANKS-way data parallelism.  Host-side graph work only — no
    device time — so the bench trajectory records the collective count
    the pass achieves, not just throughput."""
    from paddle_tpu.framework import passes as passes_mod
    from paddle_tpu.framework.program import program_guard
    from paddle_tpu.distributed.fleet.collective_transpiler import (
        GradAllReduce)
    from paddle_tpu.vision.static_models import resnet50_train_program

    main_p, startup, _, loss, opt = resnet50_train_program(
        lr=0.1, momentum=0.9)
    with program_guard(main_p, startup):
        opt.minimize(loss)
    GradAllReduce(FUSION_NRANKS, fuse_all_reduce=True).transpile(
        main_p, loss_grad_name=loss.name + "@GRAD")

    def n_allreduce(p):
        return sum(1 for op in p.global_block.ops
                   if op.type == "c_allreduce_sum")

    pre = n_allreduce(main_p)
    fused = passes_mod.FuseAllReducePass()
    work = main_p.clone()
    fused.apply(work, passes_mod.PassContext())
    return pre, n_allreduce(work)


PHASE_STEPS = 40
# big enough that a step is a few ms on CPU — the attribution drain work
# is a fixed tens-of-microseconds cost, and the 1.05x overhead budget is
# about real training steps, not a sub-millisecond microbenchmark
PHASE_H = 128
PHASE_BATCH = 512


def bench_phases(pt, jax):
    """ISSUE 18 acceptance legs (observe/phases + profiler_capture).

    (A) **pure-observer A/B**: the same seeded MLP stepped with
    FLAGS_phase_attribution on vs off, interleaved one step per side
    per round so host drift cancels.  ASSERTS bitwise loss equality
    (the plane never touches lowering — the flag is read only at
    drain) and overhead p50(on)/p50(off) <= 1.05; both are emitted.

    (B) **overlap ledger A/B** (>=2 devices): the scanned dp program
    under FLAGS_overlap_grad_allreduce off vs on; ASSERTS the ledger's
    exposed-comm share strictly drops when stretching engages — the
    per-bucket *explanation* behind overlap_step_time_ratio.  Both
    sides are the deterministic cost model, so this holds on CPU.

    (C) **anomaly capture**: an induced inter-drain stall on a live
    training loop; ASSERTS exactly one bounded capture fires
    (latch + FLAGS_prof_cooldown_s), its bundle contains phases.json,
    and ``tools.postmortem`` renders the phase table from it."""
    import os
    import shutil
    import tempfile

    from paddle_tpu import layers, observe
    from paddle_tpu.framework import flags as _fl
    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.framework import unique_name
    from paddle_tpu.observe import phases as _phases
    from paddle_tpu.observe import profiler_capture as _prof
    from paddle_tpu.optimizer import MomentumOptimizer

    out = {}

    def mlp(fleet_dp=False, depth=2, seed=1):
        from paddle_tpu.distributed import fleet

        main_p, startup = Program(), Program()
        main_p.random_seed = seed
        with unique_name.guard(), program_guard(main_p, startup):
            x = layers.data("x", [PHASE_H])
            y = layers.data("y", [1])
            h = x
            for i in range(depth):
                h = layers.fc(h, PHASE_H, act="relu", name=f"ph_{i}")
            pred = layers.fc(h, 1, name="ph_head")
            loss = layers.mean(layers.square_error_cost(pred, y))
            opt = MomentumOptimizer(0.02, 0.9)
            if fleet_dp:
                fleet.init(is_collective=True)
                fleet.distributed_optimizer(opt)
                fleet.minimize(loss)
            else:
                opt.minimize(loss)
        return main_p, startup, loss

    rng = np.random.RandomState(0)
    X = rng.randn(PHASE_BATCH, PHASE_H).astype("f4")
    feed = {"x": X, "y": (X.sum(1, keepdims=True) * 0.05).astype("f4")}

    # ---- (A) bitwise parity + overhead, interleaved ----------------------
    _phases.reset_phases()
    main_p, startup, loss = mlp()
    exe = pt.Executor(pt.CPUPlace())
    scopes, losses, times = {}, {}, {True: [], False: []}
    try:
        for on in (True, False):
            scopes[on] = pt.framework.Scope()
            losses[on] = []
            pt.set_flags({"FLAGS_phase_attribution": on})
            exe.run(startup, scope=scopes[on])
            exe.run(main_p, feed=feed, fetch_list=[loss],
                    scope=scopes[on])  # warm (compile drains here)
        for _ in range(PHASE_STEPS):
            for on in (True, False):
                pt.set_flags({"FLAGS_phase_attribution": on})
                t0 = time.perf_counter()
                v = exe.run(main_p, feed=feed, fetch_list=[loss],
                            scope=scopes[on])[0]
                # FLAGS_benchmark: the call synced, so its drain (and
                # the attribution work being measured) is inside t1-t0
                times[on].append(time.perf_counter() - t0)
                losses[on].append(np.asarray(v).copy())
    finally:
        exe.close()
        pt.set_flags({"FLAGS_phase_attribution": True})
    if not all(np.array_equal(a, b) for a, b in
               zip(losses[True], losses[False])):
        raise RuntimeError(
            "phase attribution changed numerics — the observer must be "
            "bitwise-neutral")
    on_p50 = float(np.median(times[True]))
    off_p50 = float(np.median(times[False]))
    ratio = on_p50 / off_p50 if off_p50 > 0 else 1.0
    out["phase_parity_bitwise"] = True
    out["phase_overhead_ratio"] = round(ratio, 4)
    if ratio > 1.05:
        raise RuntimeError(
            f"phase attribution overhead {ratio:.3f}x exceeds the 1.05 "
            f"budget (on {on_p50 * 1e3:.3f}ms vs off "
            f"{off_p50 * 1e3:.3f}ms p50)")
    rep = _phases.phases_report()
    if rep["steps"] < PHASE_STEPS:
        raise RuntimeError(
            f"attribution engine saw {rep['steps']} steps, expected >= "
            f"{PHASE_STEPS}")
    for b, f in rep["measured_fractions"].items():
        out[f"phase_{b}_fraction"] = round(f, 4)

    # ---- (B) overlap ledger A/B ------------------------------------------
    if len(jax.devices()) >= 2:
        shares = {}
        try:
            for overlap in (False, True):
                _phases.reset_phases()
                pt.set_flags({
                    "FLAGS_overlap_grad_allreduce": overlap,
                    "FLAGS_layer_scan": True,
                    # huge modeled compute budget + slow modeled fabric:
                    # the stretched carrier hides fully, and the tiny
                    # test grads price above rounding (prediction-only
                    # flags — measured numerics never read them)
                    "FLAGS_device_peak_tflops": 1e-6,
                    "FLAGS_phase_interconnect_gbps": 1e-3})
                main_p, startup, loss = mlp(fleet_dp=True, depth=6)
                exe = pt.Executor(pt.CPUPlace())
                try:
                    sc = pt.framework.Scope()
                    exe.run(startup, scope=sc)
                    for _ in range(3):
                        exe.run(main_p, feed=feed, fetch_list=[loss],
                                scope=sc)
                finally:
                    exe.close()
                r = _phases.phases_report()
                if r["comm_exposed_s"] + r["comm_hidden_s"] <= 0:
                    raise RuntimeError(
                        "overlap A/B priced no collectives")
                shares[overlap] = r["comm_exposed_share"]
        finally:
            pt.set_flags({"FLAGS_overlap_grad_allreduce": True,
                          "FLAGS_layer_scan": False,
                          "FLAGS_device_peak_tflops": 275.0,
                          "FLAGS_phase_interconnect_gbps": 100.0})
            from paddle_tpu.distributed.parallel_env import reset_mesh

            reset_mesh()
        if not shares[True] < shares[False]:
            raise RuntimeError(
                f"stretching did not drop the exposed-comm share: "
                f"on={shares[True]} vs off={shares[False]}")
        out["phase_comm_exposed_share_overlap_off"] = round(
            shares[False], 4)
        out["phase_comm_exposed_share_overlap_on"] = round(
            shares[True], 4)

    # ---- (C) induced spike -> exactly one rendered bundle ----------------
    import io as _io

    pm_dir = tempfile.mkdtemp(prefix="bench_phases_pm_")
    old_pm = _fl.flag("postmortem_dir")
    _prof.reset_capture()
    _phases.reset_phases()
    try:
        pt.set_flags({"FLAGS_prof_trigger_ratio": 4.0,
                      "FLAGS_prof_capture_s": 0.1,
                      "FLAGS_postmortem_dir": pm_dir})
        main_p, startup, loss = mlp(seed=2)
        exe = pt.Executor(pt.CPUPlace())
        try:
            sc = pt.framework.Scope()
            exe.run(startup, scope=sc)

            def step():
                exe.run(main_p, feed=feed, fetch_list=[loss], scope=sc)

            for _ in range(12):
                step()
            time.sleep(0.3)  # the anomaly: one slow inter-drain gap
            step()
            for _ in range(3):
                step()
        finally:
            exe.close()
        eng = _prof.capture_engine()
        if not eng.wait(60):
            raise RuntimeError("profiler capture did not finish")
        if eng.captures != 1 or len(eng.bundles) != 1:
            raise RuntimeError(
                f"induced spike produced {eng.captures} captures / "
                f"{len(eng.bundles)} bundles, expected exactly 1")
        bundle = eng.bundles[0]
        if not os.path.isfile(os.path.join(bundle, "phases.json")):
            raise RuntimeError("capture bundle is missing phases.json")
        from tools import postmortem as _pm

        buf = _io.StringIO()
        if _pm.render(bundle, out=buf) != 0 \
                or "phase attribution" not in buf.getvalue():
            raise RuntimeError(
                "tools.postmortem did not render the phase section")
        out["prof_capture_bundles"] = 1
        out["prof_capture_render_ok"] = True
        out["prof_capture_trigger"] = json.load(
            open(os.path.join(bundle, "meta.json")))["extra"][
            "trigger"][:120]
    finally:
        pt.set_flags({"FLAGS_prof_trigger_ratio": 0.0,
                      "FLAGS_prof_capture_s": 2.0,
                      "FLAGS_postmortem_dir": old_pm})
        _prof.reset_capture()
        shutil.rmtree(pm_dir, ignore_errors=True)
    return out


def preflight_device(attempts=None, timeout=None):
    """Bounded-time device-init probe in a SUBPROCESS, with retries.

    Round-4 postmortem: the first in-process jax.devices() call died
    ("Unable to initialize backend") and zeroed every metric.  The
    probe now lives in ``fleet.elastic.preflight`` (subprocess-isolated
    tiny jit dispatch, structured ok/init_timeout/compile_error
    verdict, exponential backoff per FLAGS_elastic_backoff_s) — this
    wrapper keeps the historical (platform, diag, attempts) contract
    and additionally returns the verdict object for the result record.
    """
    from paddle_tpu.distributed.fleet.elastic import preflight as epf

    if attempts is None:
        # the historical 2-attempt budget, NOT the restart budget: a
        # genuinely dead device must reach the reduced-scale fallback
        # in ~2 deadlines, not 4 (the flagships' supervised() retries
        # are where the full FLAGS_elastic_max_restarts budget lives)
        attempts = 2
    v = epf.preflight_device(attempts=attempts, timeout_s=timeout)
    if v.ok:
        return v.platform, None, v
    return None, v.diag, v


def bench_elastic(pt):
    """Chaos leg (ISSUE 14 acceptance): an injected preflight
    init-timeout AND a rank kill mid-step, driven through
    ``fleet.elastic.ElasticSupervisor`` — the round must emit REAL
    throughput numbers after recovery (``elastic_restarts >= 1``,
    ``elastic_status != "failed"``) instead of the 0.0 that killed
    rounds r04/r05.  Runs a small fc-regression flagship (CPU-cheap)
    with per-step async checkpoints; the kill forces a re-shard
    (world 2 -> 1) + elastic restore, the preflight fault forces one
    preflight retry."""
    import shutil
    import tempfile

    from paddle_tpu import layers
    from paddle_tpu.ckpt import CheckpointManager
    from paddle_tpu.distributed.fleet import elastic
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.framework.scope import Scope

    rs = np.random.RandomState(7)
    batches = [(rs.randn(16, 8).astype("f4"),
                rs.randn(16, 1).astype("f4")) for _ in range(4)]

    def train_fn(topo):
        main, startup = Program(), Program()
        main.random_seed = 5
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [8])
            y = layers.data("y", [1])
            h = layers.fc(x, 32, act="relu")
            pred = layers.fc(h, 1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            from paddle_tpu.optimizer import MomentumOptimizer

            MomentumOptimizer(0.01, 0.9).minimize(loss)
        sc = Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=sc)

        class Prog:
            scope = sc

            def step(self, batch):
                bx, by = batch
                out = exe.run(main, feed={"x": bx, "y": by},
                              fetch_list=[loss], scope=sc)
                return float(np.asarray(out[0]).ravel()[0])

            def close(self):
                exe.close()

        return Prog()

    ckpt_dir = tempfile.mkdtemp(prefix="bench_elastic_ckpt_")
    elastic.chaos.clear()
    try:
        elastic.chaos.inject("preflight_init_timeout", count=1)
        elastic.chaos.inject("kill_rank_mid_step", rank=1, at_step=4)
        mgr = CheckpointManager(ckpt_dir, keep_n=0, async_save=True)
        sup = elastic.ElasticSupervisor(
            world_size=2, preflight=True, preflight_attempts=2,
            preflight_timeout_s=60.0, backoff_s=0.2)
        r = sup.run(train_fn, manager=mgr, loader=batches,
                    total_steps=10)
        mgr.close()
        if not r.losses or not np.isfinite(r.losses).all():
            raise RuntimeError(
                f"elastic chaos leg recovered but emitted no real "
                f"numbers: losses={r.losses!r}")
        return {
            "elastic_restarts": r.restarts + r.preflight_retries,
            "elastic_reshards": r.reshards,
            "elastic_status": r.status,
            "elastic_final_world_size": r.final_world_size,
            "elastic_recovered_steps_per_sec": round(r.steps_per_sec, 2),
        }
    finally:
        elastic.chaos.clear()
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def _device_failure_record(result, stage, diag, attempts):
    """Structured failure record for a preflight/device failure: the
    driver (and the future elastic supervisor, ROADMAP item 4) gets
    machine-readable ``status``/``failure_stage``/``diag`` keys plus a
    postmortem bundle path — not a bare 0.0 with a one-line string.
    The bundle is dumped host-side (stacks, metrics, flight tail,
    flags): importing paddle_tpu does NOT touch the dead device."""
    result.update(status="device_failure", failure_stage=stage,
                  diag=diag, preflight_attempts=attempts,
                  error=f"device {stage} failed: {diag}")
    try:
        from paddle_tpu.observe import flight, health

        flight.record("bench/device_failure", stage=stage,
                      diag=diag[:500], attempts=attempts)
        result["postmortem"] = health.dump_postmortem(
            f"device_{stage}", extra={"diag": diag,
                                      "attempts": attempts})
    except Exception as e:  # noqa: BLE001 - the record must still print
        result["postmortem_error"] = f"{type(e).__name__}: {e}"[:300]
    return result


def main():
    result = {
        "metric": "resnet50_bf16_images_per_sec",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
    }
    errors = {}

    platform, diag, verdict = preflight_device()
    result["preflight_verdict"] = verdict.verdict
    result["preflight_attempts"] = verdict.attempts
    # restarts this round survived (preflight retries now; flagship
    # retries and the chaos leg's add below): the driver's signal that
    # a flaky device was RECOVERED rather than fatal
    elastic_restarts = max(verdict.attempts - 1, 0)
    if platform is None:
        _device_failure_record(result, "preflight", diag,
                               verdict.attempts)
        # reduced-scale CPU fallback: a round with SOME perf data and
        # status "partial" beats a structured failure with none
        _fallback_reduced_run(result)
        print(json.dumps(result))
        return

    import jax

    import paddle_tpu as pt

    from paddle_tpu import observe
    from paddle_tpu.observe import flight, health

    # a bench process that dies mid-flagship must leave the same bundle
    # a stall would: crash hook + fatal-signal stacks, and a flight
    # event marking the round's start (run metadata follows at the
    # first Executor construction)
    health.install_crash_handler()
    flight.record("bench/start", platform=platform,
                  preflight_attempts=verdict.attempts)

    def supervised(name, fn):
        """Flagship-level elastic retry: a failure that LOOKS like the
        device (init/backend/RESOURCE_EXHAUSTED/stall markers — see
        fleet.elastic.is_device_failure) retries with exponential
        backoff under the FLAGS_elastic_max_restarts budget instead of
        zeroing the round; a program bug still fails immediately."""
        nonlocal elastic_restarts
        from paddle_tpu.distributed.fleet import elastic as _elastic
        from paddle_tpu.framework import flags as _fl

        budget = int(_fl.flag("elastic_max_restarts"))
        backoff = float(_fl.flag("elastic_backoff_s"))
        for attempt in range(budget + 1):
            try:
                return fn()
            except Exception as e:
                if attempt >= budget or not _elastic.is_device_failure(e):
                    raise
                elastic_restarts += 1
                flight.record("bench/elastic_retry", flagship=name,
                              attempt=attempt + 1,
                              error=f"{type(e).__name__}: {e}"[:300])
                time.sleep(min(backoff * (2 ** attempt), 60.0))

    # FLAGS_benchmark: the Executor syncs each call before stopping its
    # step clock, so the StepTimer histogram holds real per-step wall
    # times (jax arrays are async; without the sync a run_steps call
    # records dispatch latency).  The flagship throughput numbers are
    # still measured by this harness's own outer timers.
    pt.set_flags({"FLAGS_benchmark": True})

    from paddle_tpu.monitor import stat_get, stat_set

    def reset_flagship_telemetry():
        """Per-flagship baseline: step stats, the XLA compile-time
        histogram, and the newest-executable-size gauge all reset so
        the emitted keys attribute to THIS flagship's compiles."""
        observe.reset_step_stats()
        observe.histogram("compile_seconds").reset()
        stat_set("executable_size_bytes", 0)

    def step_telemetry(prefix):
        """BENCH_* keys from the StepTimer the Executor fed during the
        flagship's timed calls: per-step p50/p95 (ms) + MFU estimate
        (observe/step_stats.py; FLOPs from the program IR), plus the
        XLA introspection keys (observe/xla_stats.py) — total AOT
        trace+compile wall time and executable size, the ROADMAP item 5
        acceptance baseline the scan-over-layers PR must beat."""
        s = observe.step_timer().summary()
        hist = s.get("step_time_s", {})
        out = {}
        if hist.get("count"):
            out[f"{prefix}_step_time_ms_p50"] = round(
                hist["p50"] * 1e3, 3)
            out[f"{prefix}_step_time_ms_p95"] = round(
                hist["p95"] * 1e3, 3)
        # mfu is None when FLAGS_device_peak_tflops is unset/zero (no
        # denominator): omit the key rather than publish a null/0 MFU
        if s.get("mfu") is not None:
            out[f"{prefix}_mfu_estimate"] = s["mfu"]
        if "allreduce_bytes_per_step" in s:
            out[f"{prefix}_allreduce_bytes_per_step"] = \
                s["allreduce_bytes_per_step"]
        ch = observe.histogram("compile_seconds").summary()
        if ch.get("count"):
            out[f"{prefix}_compile_seconds"] = round(ch["sum"], 3)
            out[f"{prefix}_compiles"] = int(ch["count"])
        size = stat_get("executable_size_bytes")
        if size:
            out[f"{prefix}_executable_size_bytes"] = int(size)
        return out

    # Each flagship is isolated: one failure records its diagnostic and
    # the rest still report (partial results beat a zeroed round).
    ips = tps = pipe_ips = serve = None
    try:
        pre, post = bench_allreduce_fusion(pt)
        result["allreduce_ops_per_step"] = {"pre_fusion": pre,
                                            "post_fusion": post}
    except Exception as e:
        errors["allreduce_fusion"] = f"{type(e).__name__}: {e}"[:500]
    try:
        blk_ms, write_ms, ckpt_mb = bench_checkpoint(pt)
        result["ckpt_save_blocking_ms"] = round(blk_ms, 3)
        result["ckpt_write_ms_p50"] = round(write_ms, 3)
        result["ckpt_mb_per_save"] = round(ckpt_mb, 1)
    except Exception as e:
        errors["checkpoint"] = f"{type(e).__name__}: {e}"[:500]
    try:
        def _run_resnet():
            reset_flagship_telemetry()
            return bench_resnet(pt, jax)

        ips = supervised("resnet50", _run_resnet)
        result.update(step_telemetry("resnet50"))
    except Exception as e:
        errors["resnet50"] = f"{type(e).__name__}: {e}"[:500]
    try:
        def _run_bert():
            reset_flagship_telemetry()
            return bench_bert(pt, jax)

        tps = supervised("bert", _run_bert)
        result.update(step_telemetry("bert"))
    except Exception as e:
        errors["bert"] = f"{type(e).__name__}: {e}"[:500]
    try:
        pipe_ips, pipe_extras = bench_resnet_pipeline(pt, jax)
        result.update(pipe_extras)
    except Exception as e:
        errors["resnet50_pipeline"] = f"{type(e).__name__}: {e}"[:500]
    try:
        # scan-over-layers A-B (compile-time flagship; ROADMAP item 5
        # acceptance: compile_speedup_vs_unrolled >= 5 at depth 48)
        reset_flagship_telemetry()
        result.update(bench_transformer_depth(pt, jax))
    except Exception as e:
        errors["transformer_depth"] = f"{type(e).__name__}: {e}"[:500]
    try:
        serve = bench_serving(pt, jax)
    except Exception as e:
        errors["serving"] = f"{type(e).__name__}: {e}"[:500]
    try:
        # generative serving: Poisson open-loop A-B (continuous vs
        # one-shot group batching) + the cache-not-recompute ratio
        result.update(bench_decode(pt, jax))
    except Exception as e:
        errors["decode"] = f"{type(e).__name__}: {e}"[:500]
    try:
        # disaggregated serving (ISSUE 19): migrated-page bitwise
        # oracle, fixed-fleet goodput/ttft A/B vs unified chunked
        # prefill, chaos zero-drop leg, autoscaler burn re-role
        result.update(bench_disagg(pt, jax))
    except Exception as e:
        errors["disagg"] = f"{type(e).__name__}: {e}"[:500]
    try:
        # weight-only quantized inference: hbm_required_bytes ratio +
        # the measured quality tax (quant_quality_delta)
        result.update(bench_quant(pt, jax))
    except Exception as e:
        errors["quant"] = f"{type(e).__name__}: {e}"[:500]
    try:
        # flash-attention A/B (ISSUE 17): hbm_required_bytes sweep +
        # loss parity + the 0.6x budget-gate refusal assert
        result.update(bench_flash_attention(pt, jax))
    except Exception as e:
        errors["flash_attention"] = f"{type(e).__name__}: {e}"[:500]
    try:
        # elastic chaos leg: injected preflight init-timeout + rank
        # kill, recovered through the supervisor — must emit real
        # numbers with elastic_restarts >= 1 (ISSUE 14 acceptance)
        result.update(bench_elastic(pt))
    except Exception as e:
        errors["elastic"] = f"{type(e).__name__}: {e}"[:500]
    try:
        # step-phase attribution (ISSUE 18): bitwise parity + <=1.05
        # overhead A/B, overlap-ledger exposed-share drop, and the
        # induced-spike -> exactly-one-rendered-bundle capture leg
        result.update(bench_phases(pt, jax))
    except Exception as e:
        errors["phases"] = f"{type(e).__name__}: {e}"[:500]
    # tensor-parallel flagship (dp×mp mesh) — only where a mesh exists;
    # single-chip rounds skip it silently (the MULTICHIP dryrun's tp
    # leg covers the 8-virtual-device case every round)
    if len(jax.devices()) >= 2:
        try:
            result.update(bench_bert_tp(pt, jax))
        except Exception as e:
            errors["bert_tp"] = f"{type(e).__name__}: {e}"[:500]
        try:
            # 3D parallelism + overlap A/B (ISSUE 15): stretched-bucket
            # schedule ratio on the scanned transformer and the pp×tp
            # composition leg with loss parity vs the mp-replicated
            # oracle
            result.update(bench_overlap_3d(pt, jax))
        except Exception as e:
            errors["overlap_3d"] = f"{type(e).__name__}: {e}"[:500]
        try:
            # recommender flagship (ISSUE 16): sharded-embedding
            # wide&deep — dlrm_examples_per_sec + table-bytes-per-chip
            # + lookup all-to-all payload
            result.update(bench_dlrm(pt, jax))
        except Exception as e:
            errors["dlrm"] = f"{type(e).__name__}: {e}"[:500]
        try:
            # mixture-of-experts flagship (ISSUE 20): dp×ep loss
            # parity vs the replicated oracle, dense-equivalent
            # activated-FLOPs throughput twin, bitwise overlap A/B
            # with the ledger's hidden all-to-alls, and the
            # quantized-expert serving quality tax
            result.update(bench_moe(pt, jax))
        except Exception as e:
            errors["moe"] = f"{type(e).__name__}: {e}"[:500]

    ratios = []
    if ips is not None:
        r = ips / (0.9 * A100_IMG_PER_SEC)
        ratios.append(r)
        result.update(value=round(ips, 1),
                      resnet50_images_per_sec=round(ips, 1),
                      resnet50_vs_baseline=round(r, 3))
    if tps is not None:
        r = tps / (0.9 * A100_BERT_TOKENS_PER_SEC)
        ratios.append(r)
        result.update(bert_base_tokens_per_sec=round(tps, 1),
                      bert_vs_baseline=round(r, 3))
    if pipe_ips is not None:
        result["resnet50_pipeline_images_per_sec"] = round(pipe_ips, 1)
        if ips:
            result["resnet50_pipeline_fraction_of_synthetic"] = round(
                pipe_ips / ips, 3)
    if serve is not None:
        srv_rps, seq_rps = serve
        result["serving_batched_images_per_sec"] = round(srv_rps, 1)
        result["serving_sequential_images_per_sec"] = round(seq_rps, 1)
        result["serving_batching_speedup"] = round(srv_rps / seq_rps, 3)
    # the single driver number is the MIN of the two FLAGSHIP ratios
    # (docstring contract); it zeroes only when a flagship itself
    # failed — a failure in the auxiliary pipeline bench is reported in
    # "error" but does not void the round
    flagship_ok = ips is not None and tps is not None
    result["vs_baseline"] = round(min(ratios), 3) if flagship_ok else 0.0
    # total restarts survived this round: preflight + flagship retries
    # (accumulated above) + the chaos leg's own (already in result)
    result["elastic_restarts"] = \
        int(result.get("elastic_restarts", 0)) + elastic_restarts
    result["status"] = "ok" if not errors else (
        "partial" if flagship_ok or ips is not None or tps is not None
        else "failed")
    if result["status"] == "ok" and elastic_restarts > 0:
        # every number is real AND the round survived device trouble:
        # the driver must see "recovered", not silently "ok"
        result["status"] = "recovered"
    if errors:
        result["error"] = "; ".join(f"{k}: {v}" for k, v in errors.items())
        if not flagship_ok:
            # flagships died AFTER a passing preflight: in-run device
            # loss — leave the same structured record + bundle the
            # preflight path does (partial aux results stay in place)
            result["failure_stage"] = "flagship"
            try:
                result["postmortem"] = health.dump_postmortem(
                    "flagship_failure", extra={"errors": errors})
            except Exception as e:  # noqa: BLE001
                result["postmortem_error"] = \
                    f"{type(e).__name__}: {e}"[:300]
    print(json.dumps(result))


if __name__ == "__main__":
    main()
