"""Benchmark entry point — prints ONE JSON line for the driver.

Flagship config (BASELINE.json config 1 for now; upgraded to BERT-base as
the op/model inventory widens): LeNet-class CNN training throughput,
static-graph fluid-style Executor on one chip.
"""
import json
import time

import numpy as np


def main():
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.framework.place import _default_place
    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.optimizer import MomentumOptimizer

    batch = 256
    main_p, startup = Program(), Program()
    main_p.random_seed = 1
    with program_guard(main_p, startup):
        img = layers.data("img", [1, 28, 28])
        label = layers.data("label", [1], dtype="int64")
        c1 = layers.conv2d(img, 32, 5, padding=2, act="relu")
        p1 = layers.pool2d(c1, 2, "max", 2)
        c2 = layers.conv2d(p1, 64, 5, padding=2, act="relu")
        p2 = layers.pool2d(c2, 2, "max", 2)
        f1 = layers.fc(p2, 512, act="relu")
        logits = layers.fc(f1, 10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        MomentumOptimizer(0.01, 0.9).minimize(loss)

    place = _default_place()
    exe = pt.Executor(place)
    exe.run(startup)

    rng = np.random.RandomState(0)
    imgs = rng.randn(batch, 1, 28, 28).astype("float32")
    labels = rng.randint(0, 10, (batch, 1)).astype("int64")
    feed = {"img": imgs, "label": labels}

    # warmup (compile)
    for _ in range(3):
        exe.run(main_p, feed=feed, fetch_list=[loss])

    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        out = exe.run(main_p, feed=feed, fetch_list=[loss])
    _ = float(np.asarray(out[0])[0])  # force sync
    dt = time.perf_counter() - t0

    ips = batch * iters / dt
    # A100 reference for this config (small CNN, fp32): ~60k img/s; target
    # is >=0.9x per BASELINE.json.
    baseline = 60000.0
    print(
        json.dumps(
            {
                "metric": "lenet_mnist_images_per_sec",
                "value": round(ips, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(ips / (0.9 * baseline), 3),
            }
        )
    )


if __name__ == "__main__":
    main()
