"""Per-op numpy parity + gradient checks via the OpTest harness.

Mirrors reference unittests/test_*_op.py structure (SURVEY.md §4).
"""
import numpy as np
import pytest

from op_test import OpTest


class TestElementwiseAdd(OpTest):
    op_type = "elementwise_add"

    def setup(self):
        x = np.random.rand(3, 4).astype("float32")
        y = np.random.rand(3, 4).astype("float32")
        self.inputs = {"X": [("x", x)], "Y": [("y", y)]}
        self.outputs = {"Out": [("out", x + y)]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x", "y"], "Out")


class TestElementwiseAddBroadcast(OpTest):
    op_type = "elementwise_add"

    def setup(self):
        x = np.random.rand(2, 3, 4).astype("float32")
        y = np.random.rand(3,).astype("float32")
        self.inputs = {"X": [("x", x)], "Y": [("y", y)]}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": [("out", x + y[None, :, None])]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x", "y"], "Out")


class TestMatmul(OpTest):
    op_type = "matmul"

    def setup(self):
        x = np.random.rand(4, 5).astype("float32")
        y = np.random.rand(5, 3).astype("float32")
        self.inputs = {"X": [("x", x)], "Y": [("y", y)]}
        self.outputs = {"Out": [("out", x @ y)]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x", "y"], "Out", max_relative_error=0.01)


class TestMatmulTranspose(OpTest):
    op_type = "matmul"

    def setup(self):
        x = np.random.rand(5, 4).astype("float32")
        y = np.random.rand(3, 5).astype("float32")
        self.inputs = {"X": [("x", x)], "Y": [("y", y)]}
        self.attrs = {"transpose_X": True, "transpose_Y": True}
        self.outputs = {"Out": [("out", x.T @ y.T)]}

    def test_output(self):
        self.check_output()


class TestMul(OpTest):
    op_type = "mul"

    def setup(self):
        x = np.random.rand(2, 3, 4).astype("float32")
        y = np.random.rand(12, 5).astype("float32")
        self.inputs = {"X": [("x", x)], "Y": [("y", y)]}
        self.attrs = {"x_num_col_dims": 1, "y_num_col_dims": 1}
        self.outputs = {"Out": [("out", x.reshape(2, 12) @ y)]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x", "y"], "Out", max_relative_error=0.01)


class TestSoftmax(OpTest):
    op_type = "softmax"

    def setup(self):
        x = np.random.rand(3, 7).astype("float32")
        e = np.exp(x - x.max(-1, keepdims=True))
        self.inputs = {"X": [("x", x)]}
        self.outputs = {"Out": [("out", e / e.sum(-1, keepdims=True))]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"], "Out")


class TestSoftmaxWithCrossEntropy(OpTest):
    op_type = "softmax_with_cross_entropy"

    def setup(self):
        logits = np.random.rand(5, 10).astype("float32")
        labels = np.random.randint(0, 10, (5, 1)).astype("int64")
        e = np.exp(logits - logits.max(-1, keepdims=True))
        softmax = e / e.sum(-1, keepdims=True)
        loss = -np.log(softmax[np.arange(5), labels.ravel()])[:, None]
        self.inputs = {"Logits": [("logits", logits)], "Label": [("label", labels)]}
        self.outputs = {
            "Softmax": [("softmax", softmax)],
            "Loss": [("loss", loss)],
        }

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["logits"], "Loss")


class TestReduceSum(OpTest):
    op_type = "reduce_sum"

    def setup(self):
        x = np.random.rand(3, 4, 5).astype("float32")
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"dim": [1]}
        self.outputs = {"Out": [("out", x.sum(1))]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"], "Out")


class TestReduceMeanKeepdim(OpTest):
    op_type = "reduce_mean"

    def setup(self):
        x = np.random.rand(3, 4).astype("float32")
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"dim": [0], "keep_dim": True}
        self.outputs = {"Out": [("out", x.mean(0, keepdims=True))]}

    def test_output(self):
        self.check_output()


class TestConv2d(OpTest):
    op_type = "conv2d"

    def setup(self):
        x = np.random.rand(2, 3, 8, 8).astype("float32")
        w = np.random.rand(6, 3, 3, 3).astype("float32")
        self.inputs = {"Input": [("x", x)], "Filter": [("w", w)]}
        self.attrs = {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1], "groups": 1}
        # scipy-free reference conv
        out = self._conv_ref(x, w, 1, 1)
        self.outputs = {"Output": [("out", out)]}

    @staticmethod
    def _conv_ref(x, w, stride, pad):
        n, c, h, ww = x.shape
        o, _, kh, kw = w.shape
        xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        oh = (h + 2 * pad - kh) // stride + 1
        ow = (ww + 2 * pad - kw) // stride + 1
        out = np.zeros((n, o, oh, ow), dtype=x.dtype)
        for i in range(oh):
            for j in range(ow):
                patch = xp[:, :, i * stride : i * stride + kh, j * stride : j * stride + kw]
                out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
        return out

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)

    def test_grad(self):
        self.check_grad(["x", "w"], "Output", max_relative_error=0.02, numeric_delta=1e-2)


class TestPool2dMax(OpTest):
    op_type = "pool2d"

    def setup(self):
        # well-separated values so numeric diff never flips the argmax
        x = (np.random.permutation(2 * 3 * 4 * 4).astype("float32") / 10.0).reshape(2, 3, 4, 4)
        out = x.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5))
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"pooling_type": "max", "ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]}
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"], "Out", max_relative_error=0.02, numeric_delta=1e-3)


class TestPool2dAvg(OpTest):
    op_type = "pool2d"

    def setup(self):
        x = np.random.rand(2, 3, 4, 4).astype("float32")
        out = x.reshape(2, 3, 2, 2, 2, 2).mean(axis=(3, 5))
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"pooling_type": "avg", "ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]}
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output()


class TestLayerNorm(OpTest):
    op_type = "layer_norm"

    def setup(self):
        x = np.random.rand(4, 6).astype("float32")
        scale = np.random.rand(6).astype("float32")
        bias = np.random.rand(6).astype("float32")
        m = x.mean(-1, keepdims=True)
        v = x.var(-1, keepdims=True)
        y = (x - m) / np.sqrt(v + 1e-5) * scale + bias
        self.inputs = {"X": [("x", x)], "Scale": [("scale", scale)], "Bias": [("bias", bias)]}
        self.attrs = {"epsilon": 1e-5, "begin_norm_axis": 1}
        self.outputs = {
            "Y": [("y", y)],
            "Mean": [("m", m.ravel())],
            "Variance": [("v", v.ravel())],
        }

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(["x", "scale", "bias"], "Y", max_relative_error=0.02, numeric_delta=1e-2)


class TestBatchNormInference(OpTest):
    op_type = "batch_norm"

    def setup(self):
        x = np.random.rand(2, 3, 4, 4).astype("float32")
        scale = np.random.rand(3).astype("float32")
        bias = np.random.rand(3).astype("float32")
        mean = np.random.rand(3).astype("float32")
        var = np.random.rand(3).astype("float32") + 0.5
        y = (x - mean[None, :, None, None]) / np.sqrt(var[None, :, None, None] + 1e-5)
        y = y * scale[None, :, None, None] + bias[None, :, None, None]
        self.inputs = {
            "X": [("x", x)],
            "Scale": [("scale", scale)],
            "Bias": [("bias", bias)],
            "Mean": [("mean", mean)],
            "Variance": [("var", var)],
        }
        self.attrs = {"is_test": True, "epsilon": 1e-5}
        self.outputs = {"Y": [("y", y)]}

    def test_output(self):
        self.check_output(atol=1e-4)


class TestLookupTableV2(OpTest):
    op_type = "lookup_table_v2"

    def setup(self):
        w = np.random.rand(10, 4).astype("float32")
        ids = np.random.randint(0, 10, (3, 5)).astype("int64")
        self.inputs = {"W": [("w", w)], "Ids": [("ids", ids)]}
        self.outputs = {"Out": [("out", w[ids])]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["w"], "Out")


class TestDropoutTestMode(OpTest):
    op_type = "dropout"

    def setup(self):
        x = np.random.rand(4, 4).astype("float32")
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"dropout_prob": 0.35, "is_test": True}
        self.outputs = {
            "Out": [("out", x * 0.65)],
            "Mask": [("mask", np.ones_like(x, dtype=np.uint8))],
        }

    def test_output(self):
        self.check_output(no_check_set=["Mask"])


class TestTranspose(OpTest):
    op_type = "transpose2"

    def setup(self):
        x = np.random.rand(2, 3, 4).astype("float32")
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"axis": [1, 0, 2]}
        self.outputs = {
            "Out": [("out", x.transpose(1, 0, 2))],
            "XShape": [("xshape", np.zeros((0, 2, 3, 4), "float32"))],
        }

    def test_output(self):
        self.check_output(no_check_set=["XShape"])

    def test_grad(self):
        self.check_grad(["x"], "Out")


class TestReshape(OpTest):
    op_type = "reshape2"

    def setup(self):
        x = np.random.rand(2, 6).astype("float32")
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"shape": [3, -1]}
        self.outputs = {
            "Out": [("out", x.reshape(3, 4))],
            "XShape": [("xshape", np.zeros((0, 2, 6), "float32"))],
        }

    def test_output(self):
        self.check_output(no_check_set=["XShape"])

    def test_grad(self):
        self.check_grad(["x"], "Out")


class TestConcat(OpTest):
    op_type = "concat"

    def setup(self):
        a = np.random.rand(2, 3).astype("float32")
        b = np.random.rand(2, 5).astype("float32")
        self.inputs = {"X": [("a", a), ("b", b)]}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": [("out", np.concatenate([a, b], 1))]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["a", "b"], "Out")


class TestSliceOp(OpTest):
    op_type = "slice"

    def setup(self):
        x = np.random.rand(5, 6).astype("float32")
        self.inputs = {"Input": [("x", x)]}
        self.attrs = {"axes": [0, 1], "starts": [1, 2], "ends": [4, 6]}
        self.outputs = {"Out": [("out", x[1:4, 2:6])]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"], "Out")


class TestGather(OpTest):
    op_type = "gather"

    def setup(self):
        x = np.random.rand(8, 3).astype("float32")
        idx = np.array([1, 3, 5], dtype="int64")
        self.inputs = {"X": [("x", x)], "Index": [("idx", idx)]}
        self.outputs = {"Out": [("out", x[idx])]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"], "Out")


class TestTopK(OpTest):
    op_type = "top_k"

    def setup(self):
        x = np.random.rand(3, 6).astype("float32")
        k = 2
        idx = np.argsort(-x, axis=1)[:, :k]
        vals = np.take_along_axis(x, idx, 1)
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"k": k}
        self.outputs = {"Out": [("out", vals)], "Indices": [("indices", idx.astype("int64"))]}

    def test_output(self):
        self.check_output()


class TestCast(OpTest):
    op_type = "cast"

    def setup(self):
        from paddle_tpu.framework import dtypes

        x = np.random.rand(3, 4).astype("float32")
        self.inputs = {"X": [("x", x)]}
        self.attrs = {
            "in_dtype": dtypes.to_enum("float32"),
            "out_dtype": dtypes.to_enum("int32"),
        }
        self.outputs = {"Out": [("out", x.astype("int32"))]}

    def test_output(self):
        self.check_output()


class TestScale(OpTest):
    op_type = "scale"

    def setup(self):
        x = np.random.rand(3, 4).astype("float32")
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"scale": 2.5, "bias": 0.7}
        self.outputs = {"Out": [("out", x * 2.5 + 0.7)]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"], "Out")


class TestSigmoidCrossEntropyWithLogits(OpTest):
    op_type = "sigmoid_cross_entropy_with_logits"

    def setup(self):
        x = np.random.randn(4, 5).astype("float32")
        label = np.random.randint(0, 2, (4, 5)).astype("float32")
        loss = np.maximum(x, 0) - x * label + np.log1p(np.exp(-np.abs(x)))
        self.inputs = {"X": [("x", x)], "Label": [("label", label)]}
        self.outputs = {"Out": [("out", loss)]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"], "Out")


class TestActivations(OpTest):
    """Several activations batch-checked against numpy references."""

    op_type = "activations"

    def setUp(self):
        pass

    def test_many(self):
        acts = {
            "relu": lambda x: np.maximum(x, 0),
            "sigmoid": lambda x: 1 / (1 + np.exp(-x)),
            "tanh": np.tanh,
            "leaky_relu": lambda x: np.where(x > 0, x, 0.02 * x),
            "softplus": lambda x: np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0),
            "silu": lambda x: x / (1 + np.exp(-x)),
            "square": np.square,
            "sqrt_abs": None,
        }
        for name, ref in acts.items():
            if ref is None:
                continue

            class T(OpTest):
                op_type = name

            t = T(methodName="run")
            # seeded, and kept away from 0: relu-family kinks inside the
            # finite-difference delta make the numeric grad flaky
            x = np.random.RandomState(7).randn(3, 4).astype("float32")
            x = np.where(np.abs(x) < 5e-3, 5e-3, x)
            t.inputs = {"X": [("x", x)]}
            t.attrs = {}
            t.outputs = {"Out": [("out", ref(x).astype("float32"))]}
            t.check_output(atol=1e-5)
            t.check_grad(["x"], "Out", max_relative_error=0.02, numeric_delta=1e-3)
