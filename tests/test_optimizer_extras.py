"""Optimizer-inventory tail (reference fluid/optimizer.py rows the
round-4 inventory missed): ExponentialMovingAverage (:3443),
ModelAverage (:3134), LookaheadOptimizer (:4853), Dpsgd
(operators/optimizers/dpsgd_op.cc)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.optimizer import (
    DpsgdOptimizer,
    ExponentialMovingAverage,
    LookaheadOptimizer,
    ModelAverage,
    SGDOptimizer,
)


def _net(seed=1):
    from paddle_tpu.framework import unique_name
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = Program(), Program()
    main.random_seed = seed
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        pred = layers.fc(x, 1, param_attr=ParamAttr(
            initializer=ConstantInitializer(0.1)), bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
    return main, startup, loss


def _data(rng, n=16):
    X = rng.randn(n, 4).astype("f4")
    Y = (X.sum(axis=1, keepdims=True) * 0.3).astype("f4")
    return X, Y


def test_ema_tracks_bias_corrected_shadow():
    from paddle_tpu.framework.scope import global_scope

    rng = np.random.RandomState(0)
    X, Y = _data(rng)
    main, startup, loss = _net()
    with program_guard(main, startup):
        SGDOptimizer(0.1).minimize(loss)
        ema = ExponentialMovingAverage(0.5)
        ema.update()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    p = "fc_0.w_0"
    shadow_oracle, w_hist = 0.0, []
    for _ in range(4):
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        w = np.asarray(global_scope().get_var(p)).copy()
        w_hist.append(w)
        shadow_oracle = 0.5 * shadow_oracle + 0.5 * w
    corrected = shadow_oracle / (1.0 - 0.5 ** 4)
    with ema.apply():
        np.testing.assert_allclose(
            np.asarray(global_scope().get_var(p)), corrected,
            rtol=1e-5, atol=1e-6)
    # restored after the guard
    np.testing.assert_allclose(np.asarray(global_scope().get_var(p)),
                               w_hist[-1], rtol=1e-6)


def test_model_average_applies_running_mean():
    from paddle_tpu.framework.scope import global_scope

    rng = np.random.RandomState(1)
    X, Y = _data(rng)
    main, startup, loss = _net()
    with program_guard(main, startup):
        SGDOptimizer(0.1).minimize(loss)
        avg = ModelAverage(0.15)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    p = "fc_0.w_0"
    ws = []
    for _ in range(5):
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        ws.append(np.asarray(global_scope().get_var(p)).copy())
    with avg.apply():
        np.testing.assert_allclose(
            np.asarray(global_scope().get_var(p)),
            np.mean(ws, axis=0), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(global_scope().get_var(p)),
                               ws[-1], rtol=1e-6)


def test_lookahead_syncs_every_k_steps():
    from paddle_tpu.framework.scope import global_scope

    rng = np.random.RandomState(2)
    X, Y = _data(rng)

    # oracle: replicate fast/slow recurrence with plain SGD steps
    main0, startup0, loss0 = _net()
    with program_guard(main0, startup0):
        SGDOptimizer(0.1).minimize(loss0)
    sc0 = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup0, scope=sc0)
    p = "fc_0.w_0"
    slow = np.asarray(sc0.get_var(p)).copy()
    fast_hist = []
    for step in range(1, 5):
        exe.run(main0, feed={"x": X, "y": Y}, fetch_list=[loss0],
                scope=sc0)
        fast = np.asarray(sc0.get_var(p)).copy()
        if step % 2 == 0:  # k=2 sync
            slow = slow + 0.5 * (fast - slow)
            fast = slow
            sc0.set_var(p, fast)
        fast_hist.append(fast.copy())

    main, startup, loss = _net()
    with program_guard(main, startup):
        LookaheadOptimizer(SGDOptimizer(0.1), alpha=0.5, k=2).minimize(loss)
    exe.run(startup)
    for step in range(4):
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
    np.testing.assert_allclose(np.asarray(global_scope().get_var(p)),
                               fast_hist[-1], rtol=1e-5, atol=1e-6)


def test_dpsgd_noise_free_is_clipped_sgd():
    rng = np.random.RandomState(3)
    X, Y = _data(rng)
    main, startup, loss = _net()
    with program_guard(main, startup):
        DpsgdOptimizer(learning_rate=0.1, clip=1e-4,
                       sigma=0.0).minimize(loss)
    sc = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=sc)
    p = "fc_0.w_0"
    w0 = np.asarray(sc.get_var(p)).copy()
    exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss], scope=sc)
    w1 = np.asarray(sc.get_var(p))
    step_norm = np.linalg.norm(w1 - w0)
    # clipped to ||g||<=1e-4, lr=0.1 -> step norm <= 1e-5 (+eps)
    assert 0 < step_norm <= 1.1e-5, step_norm


def test_ema_need_restore_false_then_restore():
    """apply(need_restore=False) + later restore() is the reference
    pattern; backups must live on the instance, not the guard."""
    from paddle_tpu.framework.scope import global_scope

    rng = np.random.RandomState(4)
    X, Y = _data(rng)
    main, startup, loss = _net()
    with program_guard(main, startup):
        SGDOptimizer(0.1).minimize(loss)
        ema = ExponentialMovingAverage(0.5)
        ema.update()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    p = "fc_0.w_0"
    for _ in range(3):
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
    trained = np.asarray(global_scope().get_var(p)).copy()
    with ema.apply(need_restore=False):
        pass
    swapped = np.asarray(global_scope().get_var(p)).copy()
    assert not np.allclose(swapped, trained)
    ema.restore()
    np.testing.assert_allclose(np.asarray(global_scope().get_var(p)),
                               trained, rtol=1e-6)


def test_ema_thres_steps_ramps_decay():
    """With thres_steps the per-step decay is min(decay, (1+t)/(10+t))
    (evaluated on the pre-increment... the op sees t AFTER increment,
    so step 1 uses 2/11 etc.)."""
    from paddle_tpu.framework.scope import global_scope

    rng = np.random.RandomState(5)
    X, Y = _data(rng)
    main, startup, loss = _net()
    with program_guard(main, startup):
        SGDOptimizer(0.1).minimize(loss)
        ema = ExponentialMovingAverage(0.999, thres_steps=True)
        ema.update()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    p = "fc_0.w_0"
    shadow, prod = 0.0, 1.0
    for t in range(1, 4):
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        w = np.asarray(global_scope().get_var(p)).copy()
        d = min(0.999, (1 + t) / (10 + t))
        shadow = d * shadow + (1 - d) * w
        prod *= d
    with ema.apply():
        np.testing.assert_allclose(
            np.asarray(global_scope().get_var(p)),
            shadow / (1 - prod), rtol=1e-4, atol=1e-6)


def test_model_average_window_rotation_bounds_history():
    """With max_average_window=2, weights older than 2 windows must drop
    out of the average (the two-buffer rotation)."""
    from paddle_tpu.framework.scope import global_scope

    rng = np.random.RandomState(6)
    X, Y = _data(rng)
    main, startup, loss = _net()
    with program_guard(main, startup):
        SGDOptimizer(0.1).minimize(loss)
        avg = ModelAverage(max_average_window=2)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    p = "fc_0.w_0"
    ws = []
    for _ in range(6):
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        ws.append(np.asarray(global_scope().get_var(p)).copy())
    # after 6 steps with window 2: cur holds {w5,w6}? rotation at each
    # multiple of 2 rolls cur->old; average = (old+cur)/counts covers
    # at most the last 4 step weights
    with avg.apply():
        got = np.asarray(global_scope().get_var(p)).copy()
    full_mean = np.mean(ws, axis=0)
    last4_mean = np.mean(ws[2:], axis=0)
    assert np.allclose(got, last4_mean, rtol=1e-5, atol=1e-6) or \
        not np.allclose(got, full_mean, rtol=1e-5, atol=1e-6), \
        "rotation had no effect: average still covers all history"
