"""3D parallelism (dp×mp×pp) with collective–compute overlap.

Composition matrix for the composed mesh (distributed/pipeline.py v4):
tensor parallelism INSIDE pipeline stages (manual Megatron f/g at the
ShardingPropagationPass anchors), scan-over-layers INSIDE each stage
(bitwise vs the unrolled trace), stretched allreduce buckets at the
scan boundary (FuseAllReducePass + FLAGS_overlap_grad_allreduce), the
latency-hiding chunked collective matmul, and elastic checkpoint
save/restore across a pp-degree change.

Oracle discipline: the mp composition is compared against the SAME
GPipe schedule with mp replicated (a pp-only / dp×pp mesh) so micro-
batching and the per-(stage, microbatch) dropout keys are identical —
the only difference left is the mp matmul split, bounded by 1e-4
(float reassociation of the row-parallel psum).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import passes as passes_mod
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import (Program, device_guard,
                                          program_guard)
from paddle_tpu.initializer import ConstantInitializer
from paddle_tpu.monitor import stat_get, stat_reset
from paddle_tpu.optimizer import MomentumOptimizer, PipelineOptimizer
from paddle_tpu.param_attr import ParamAttr

H = 16


def _data(n=8, h=H, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, h).astype("f4")
    Y = (X.sum(1, keepdims=True) * 0.2).astype("f4")
    return X, Y


def _attr(v):
    return ParamAttr(initializer=ConstantInitializer(v))


def _build_megatron_pp(use_tp, n_micro=2, dropout=False, n_stages=2):
    """Two Megatron ffn pairs split over ``n_stages`` pipeline stages;
    param names match DEFAULT_MEGATRON_RULES (ffn1 column-parallel,
    ffn2 row-parallel).  Dropout (optional) sits AFTER the row-parallel
    reduce — the replicated point, per the Megatron block shape."""
    from paddle_tpu.distributed import fleet

    main, startup = Program(), Program()
    main.random_seed = 3
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [H])
        y = layers.data("y", [1])
        with device_guard("stage:0"):
            h = layers.fc(x, 2 * H, act="relu", name="s0_ffn1",
                          param_attr=_attr(0.05), bias_attr=_attr(0.01))
            h = layers.fc(h, H, name="s0_ffn2", param_attr=_attr(0.04),
                          bias_attr=_attr(0.0))
            if dropout:
                h = layers.dropout(h, 0.25)
        with device_guard(f"stage:{n_stages - 1}"):
            h2 = layers.fc(h, 2 * H, act="relu", name="s1_ffn1",
                           param_attr=_attr(0.03), bias_attr=_attr(0.0))
            h2 = layers.fc(h2, H, name="s1_ffn2", param_attr=_attr(0.05),
                           bias_attr=False)
            pred = layers.fc(h2, 1, name="head", param_attr=_attr(0.1),
                             bias_attr=False)
            loss = layers.mean(layers.square_error_cost(pred, y))
        opt = MomentumOptimizer(0.05, 0.9)
        if use_tp:
            strat = fleet.DistributedStrategy()
            strat.tensor_parallel = True
            strat.pipeline = True
            strat.pipeline_configs = {"micro_batch": n_micro}
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(opt)
            fleet.minimize(loss)
        else:
            PipelineOptimizer(opt, num_microbatches=n_micro).minimize(loss)
    return main, startup, loss


def _train(main, startup, loss, X, Y, mesh, steps=4, scope=None):
    sc = scope if scope is not None else pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup, scope=sc)
    out = [float(np.asarray(exe.run(main, feed={"x": X, "y": Y},
                                    fetch_list=[loss], scope=sc)[0]).item())
           for _ in range(steps)]
    exe.drain()
    return out, sc, exe


@pytest.fixture
def mesh_pp2():
    import jax

    return jax.sharding.Mesh(np.array(jax.devices()[:2]), ("pp",))


@pytest.fixture
def _set_mesh():
    from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh

    try:
        yield set_mesh
    finally:
        reset_mesh()


# ---------------------------------------------------------------------------
# tier-1-lean units (no jit compile)
# ---------------------------------------------------------------------------


class TestAnchorsAndBuckets:
    def test_anchor_partial_flag_roundtrip(self):
        enc = "out\tNone,mp"
        assert passes_mod.decode_anchor(enc) == ("out", (None, "mp"),
                                                 False)
        assert passes_mod.decode_anchor("out\tdp,None\tP") == (
            "out", ("dp", None), True)
        assert passes_mod.decode_anchor("out\t") == ("out", (), False)

    def _allreduce_program(self, stacked_first=2, tail=2):
        """``stacked_first`` adjacent stacked-carrier allreduces (the
        pulled-out post-scan collectives), then ``tail`` unstacked
        allreduces each behind a compute op (the unrolled edge-layer
        backward)."""
        from paddle_tpu.framework.passes import (FUSED_ALLREDUCE_ATTR,
                                                 LAYER_STACK_ATTR)

        main = Program()
        block = main.global_block
        names = []

        def grad(name, stack):
            block.create_var(name=name, shape=[64, 64], dtype="float32")
            block.append_op("fill_constant", {}, {"Out": [name]},
                            {"shape": [64, 64], "dtype": "float32",
                             "value": 1.0})
            attrs = {"ring_id": 0, FUSED_ALLREDUCE_ATTR: True}
            if stack:
                attrs[LAYER_STACK_ATTR] = stack
            return name, attrs

        # backward scan -> adjacent stacked carriers
        pending = []
        for i in range(stacked_first):
            n, attrs = grad(f"stk{i}", 8)
            pending.append((n, attrs))
        for n, attrs in pending:
            block.append_op("c_allreduce_sum", {"X": [n]}, {"Out": [n]},
                            attrs)
            names.append(n)
        # unrolled tail: compute between each grad's allreduce
        for i in range(tail):
            n, attrs = grad(f"tail{i}", 0)
            block.append_op("c_allreduce_sum", {"X": [n]}, {"Out": [n]},
                            attrs)
            names.append(n)
        return main, names

    def test_stretched_bucket_closes_at_scan_boundary(self):
        """Overlap ON: the stacked carriers' bucket refuses the
        unstacked tail grads separated by backward compute — the bulk
        allreduce keeps its post-scan anchor (dispatches under the
        remaining backward) instead of being dragged to the tail."""
        from paddle_tpu.framework.passes import (FuseAllReducePass,
                                                 PassContext)

        pt.set_flags({"FLAGS_overlap_grad_allreduce": True})
        stat_reset("pass_overlap_stretched_buckets")
        main, _ = self._allreduce_program()
        FuseAllReducePass().apply(main, PassContext())
        ops = main.global_block.ops
        groups = [op.inputs["Input"] for op in ops
                  if op.type == "coalesce_tensor"]
        assert ["stk0", "stk1"] in groups, groups
        assert all("stk0" not in g or "tail0" not in g for g in groups)
        assert stat_get("pass_overlap_stretched_buckets") >= 1
        # the carrier bucket's fused collective sits BEFORE the tail
        # grads' producing compute ops
        idx_of = {op.type + str(i): i for i, op in enumerate(ops)}
        carrier_ar = next(i for i, op in enumerate(ops)
                          if op.type == "c_allreduce_sum"
                          and "FUSED" in op.inputs["X"][0])
        first_tail_fill = next(
            i for i, op in enumerate(ops)
            if op.type == "fill_constant"
            and op.outputs["Out"][0].startswith("tail"))
        assert carrier_ar < first_tail_fill, (carrier_ar, first_tail_fill)

    def test_sequential_schedule_with_flag_off(self):
        """Overlap OFF (the bench A/B baseline): one greedy bucket
        drags the carriers to the tail — the pre-overlap schedule."""
        from paddle_tpu.framework.passes import (FuseAllReducePass,
                                                 PassContext)

        pt.set_flags({"FLAGS_overlap_grad_allreduce": False})
        try:
            main, _ = self._allreduce_program()
            FuseAllReducePass().apply(main, PassContext())
            groups = [op.inputs["Input"] for op in main.global_block.ops
                      if op.type == "coalesce_tensor"]
            assert any("stk0" in g and "tail1" in g for g in groups), groups
        finally:
            pt.set_flags({"FLAGS_overlap_grad_allreduce": True})

    def test_packed_param_ref_mp_views(self):
        """PackedParamRef over an mp-packed (S, MP, W) buffer
        materializes the TRUE global value: sharded vars reassemble
        along their sharded dim, replicated vars read one rank's row."""
        from paddle_tpu.framework.scope import PackedParamRef, Scope

        sc = Scope()
        w = np.arange(24, dtype=np.float32).reshape(4, 6)
        b = np.arange(4, dtype=np.float32)
        buf = np.zeros((1, 2, 20), np.float32)
        for r in range(2):
            buf[0, r, :12] = w[:, 3 * r:3 * (r + 1)].ravel()
            buf[0, r, 12:16] = b
        sc.set_var("@PK@", buf)
        ref_w = PackedParamRef(sc, "@PK@", 0, 0, (4, 6), np.float32,
                               mp_degree=2, mp_dim=1)
        ref_b = PackedParamRef(sc, "@PK@", 0, 12, (4,), np.float32,
                               mp_degree=2, mp_dim=None)
        np.testing.assert_array_equal(np.asarray(ref_w), w)
        np.testing.assert_array_equal(np.asarray(ref_b), b)
        assert ref_w.local_shape == (4, 3)

    def test_pp_degree_flag_shapes_default_mesh(self):
        from paddle_tpu.distributed.parallel_env import (init_parallel_env,
                                                         reset_mesh)

        pt.set_flags({"FLAGS_pp_degree": 2})
        try:
            mesh = init_parallel_env()
            assert tuple(mesh.axis_names) == ("dp", "pp")
            assert int(mesh.shape["pp"]) == 2
            # an EXPLICIT axis_names wins over the flag
            mesh = init_parallel_env(axis_names=("batch",))
            assert tuple(mesh.axis_names) == ("batch",)
            pt.set_flags({"FLAGS_pp_degree": 3})  # 8 % 3 != 0
            with pytest.raises(ValueError, match="pp_degree"):
                init_parallel_env()
        finally:
            pt.set_flags({"FLAGS_pp_degree": 0})
            reset_mesh()

    def test_mp_flow_validation_rejects_sharded_softmax(self, _set_mesh):
        """An op outside the understood family consuming an mp-sharded
        activation is refused at plan time, naming the op."""
        import jax

        from paddle_tpu.distributed import fleet

        devs = np.array(jax.devices())
        mesh = jax.sharding.Mesh(devs[:4].reshape(2, 2), ("mp", "pp"))
        _set_mesh(mesh)
        main, startup = Program(), Program()
        main.random_seed = 1
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [H])
            y = layers.data("y", [1])
            with device_guard("stage:0"):
                h = layers.fc(x, 2 * H, name="s0_ffn1",
                              param_attr=_attr(0.05), bias_attr=False)
                # softmax over the COLUMN-PARALLEL (mp-sharded) output:
                # a local softmax would normalize over the shard only
                h = layers.softmax(h)
                h = layers.fc(h, H, name="s0_ffn2",
                              param_attr=_attr(0.04), bias_attr=False)
            with device_guard("stage:1"):
                pred = layers.fc(h, 1, name="head", param_attr=_attr(0.1),
                                 bias_attr=False)
                loss = layers.mean(layers.square_error_cost(pred, y))
            strat = fleet.DistributedStrategy()
            strat.tensor_parallel = True
            strat.pipeline = True
            strat.pipeline_configs = {"micro_batch": 2}
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
            fleet.minimize(loss)
        X, Y = _data()
        sc = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
        exe.run(startup, scope=sc)
        with pytest.raises(NotImplementedError, match="softmax"):
            exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                    scope=sc)


# ---------------------------------------------------------------------------
# composition matrix (compile-heavy: slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestComposedMesh:
    def test_mp_pp_parity_vs_replicated_oracle(self, mesh_pp2, _set_mesh):
        """mp×pp loss parity ≤1e-4 vs the same GPipe schedule with mp
        replicated, plus the memory point: the packed buffer grows an
        mp dim and each (pp, mp) rank holds shard-sized rows, while
        the scope views still materialize full values."""
        import jax

        from paddle_tpu.distributed.pipeline import PACKED_STATE_VAR

        X, Y = _data()
        base, _, _ = _train(*_build_megatron_pp(False), X, Y, mesh_pp2)

        devs = np.array(jax.devices())
        mesh = jax.sharding.Mesh(devs[:4].reshape(2, 2), ("mp", "pp"))
        _set_mesh(mesh)
        stat_reset("pp_bubble_fraction_ppm")
        got, sc, _ = _train(*_build_megatron_pp(True), X, Y, mesh)
        np.testing.assert_allclose(got, base, rtol=1e-4, atol=1e-6)
        # GPipe schedule-cost gauge: S=2, K=2 -> (S-1)/(K+S-1) = 1/3
        assert stat_get("pp_bubble_fraction_ppm") == pytest.approx(
            333333, abs=2)
        assert stat_get("pp_stages") == 2

        buf = sc.get_var(PACKED_STATE_VAR)
        assert buf.shape[0] == 2 and buf.shape[1] == 2  # (S, MP, W)
        # a column-parallel weight's view reassembles the global shape
        w = np.asarray(sc.get_var("s0_ffn1.w_0"))
        assert w.shape == (H, 2 * H)
        # per-(pp, mp) rank: one (1, 1, W) row of the packed buffer
        per_dev = {sh.device: sh.data.shape
                   for sh in buf.addressable_shards}
        assert len(per_dev) == 4
        assert all(s == (1, 1, buf.shape[-1]) for s in per_dev.values())

    def test_dp_mp_pp_parity_with_dropout(self, _set_mesh):
        """Full 3-axis composition (2,2,2) vs the dp×pp oracle WITH
        dropout: identical micro-batching, identical per-(stage,
        microbatch, dp-shard) dropout keys (partitionable threefry),
        so the mp split is the only delta — ≤1e-4."""
        import jax

        X, Y = _data()
        devs = np.array(jax.devices())
        mesh_dpp = jax.sharding.Mesh(devs[:4].reshape(2, 2),
                                     ("dp", "pp"))
        base, _, _ = _train(*_build_megatron_pp(False, dropout=True),
                            X, Y, mesh_dpp)
        mesh_3d = jax.sharding.Mesh(devs[:8].reshape(2, 2, 2),
                                    ("dp", "mp", "pp"))
        _set_mesh(mesh_3d)
        got, _, _ = _train(*_build_megatron_pp(True, dropout=True),
                           X, Y, mesh_3d)
        np.testing.assert_allclose(got, base, rtol=1e-4, atol=1e-6)

    def test_chunked_collective_matmul_pipeline(self, _set_mesh):
        """FLAGS_collective_matmul_chunks on the manual pipeline×mp
        path: per-chunk g-psum, numerics equal to the unchunked run."""
        import jax

        X, Y = _data()
        devs = np.array(jax.devices())
        mesh = jax.sharding.Mesh(devs[:4].reshape(2, 2), ("mp", "pp"))
        _set_mesh(mesh)
        a, _, _ = _train(*_build_megatron_pp(True), X, Y, mesh)
        stat_reset("collective_matmul_chunked")
        pt.set_flags({"FLAGS_collective_matmul_chunks": 2})
        try:
            b, _, _ = _train(*_build_megatron_pp(True), X, Y, mesh)
        finally:
            pt.set_flags({"FLAGS_collective_matmul_chunks": 0})
        assert stat_get("collective_matmul_chunked") > 0
        np.testing.assert_allclose(b, a, rtol=1e-5, atol=1e-7)

    def test_chunked_collective_matmul_gspmd_mp_only(self, _set_mesh):
        """GSPMD path: chunking engages on an mp-only mesh (exact vs
        unchunked); a mesh with a live dp axis falls back LOUDLY — the
        partitioner mis-partitions that pattern (probed), so the dp
        compositions route through the pipeline's manual path."""
        import jax

        from paddle_tpu.distributed import fleet

        def build():
            main, startup = Program(), Program()
            main.random_seed = 3
            with unique_name.guard(), program_guard(main, startup):
                x = layers.data("x", [H])
                y = layers.data("y", [1])
                h = layers.fc(x, 2 * H, act="relu", name="blk_ffn1",
                              param_attr=_attr(0.05), bias_attr=False)
                h = layers.fc(h, H, name="blk_ffn2",
                              param_attr=_attr(0.04), bias_attr=False)
                pred = layers.fc(h, 1, name="head", param_attr=_attr(0.1),
                                 bias_attr=False)
                loss = layers.mean(layers.square_error_cost(pred, y))
                strat = fleet.DistributedStrategy()
                strat.tensor_parallel = True
                fleet.init(is_collective=True, strategy=strat)
                fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
                fleet.minimize(loss)
            return main, startup, loss

        X, Y = _data()
        devs = np.array(jax.devices())
        mesh = jax.sharding.Mesh(devs[:4], ("mp",))
        _set_mesh(mesh)
        a, _, _ = _train(*build(), X, Y, mesh, steps=3)
        stat_reset("collective_matmul_chunked")
        stat_reset("collective_matmul_fallback")
        pt.set_flags({"FLAGS_collective_matmul_chunks": 2})
        try:
            b, _, _ = _train(*build(), X, Y, mesh, steps=3)
            assert stat_get("collective_matmul_chunked") > 0
            np.testing.assert_allclose(b, a, rtol=1e-5, atol=1e-7)

            # dp×mp: loud fallback, numerics unchanged
            mesh2 = jax.sharding.Mesh(devs[:8].reshape(2, 4),
                                      ("dp", "mp"))
            _set_mesh(mesh2)
            stat_reset("collective_matmul_chunked")
            c, _, _ = _train(*build(), X, Y, mesh2, steps=3)
            assert stat_get("collective_matmul_chunked") == 0
            assert stat_get("collective_matmul_fallback") > 0
            np.testing.assert_allclose(c, a, rtol=1e-4, atol=1e-6)
        finally:
            pt.set_flags({"FLAGS_collective_matmul_chunks": 0})


@pytest.mark.slow
class TestScanInsideStage:
    def _build_deep(self, n_layers=4, dropout=True, head_stage=2):
        """Two stages of ``n_layers`` isomorphic fc(+dropout) layers;
        the head/loss live in ``head_stage``.  With head_stage=2 every
        scanned stage contains ONLY its layer run — the shape the
        bitwise pin uses: an unscanned op trailing a scan in the SAME
        stage sits at a different XLA fusion boundary and can move by
        one ulp (probed; the 2-stage variant is pinned to 1e-6)."""
        main, startup = Program(), Program()
        main.random_seed = 5
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [H])
            y = layers.data("y", [1])
            h = x
            for s in range(2):
                with device_guard(f"stage:{s}"):
                    for i in range(n_layers):
                        h = layers.fc(h, H, act="relu",
                                      name=f"st{s}_l{i}",
                                      param_attr=_attr(0.05 + 0.01 * i),
                                      bias_attr=False)
                        if dropout:
                            h = layers.dropout(h, 0.1)
            with device_guard(f"stage:{head_stage}"):
                pred = layers.fc(h, 1, name="head", param_attr=_attr(0.1),
                                 bias_attr=False)
                loss = layers.mean(layers.square_error_cost(pred, y))
            PipelineOptimizer(MomentumOptimizer(0.05, 0.9),
                              num_microbatches=2).minimize(loss)
        return main, startup, loss

    def _run(self, scan, X, Y, mesh, head_stage):
        pt.set_flags({"FLAGS_layer_scan": scan,
                      "FLAGS_layer_scan_min_layers": 4})
        try:
            losses, sc, _ = _train(
                *self._build_deep(head_stage=head_stage), X, Y, mesh)
        finally:
            pt.set_flags({"FLAGS_layer_scan": False})
        state = {n: np.asarray(sc.get_var(n))
                 for n in sorted(sc.local_var_names())
                 if n.startswith("st") and ".w_" in n}
        return losses, state

    def test_scan_inside_stage_bitwise(self):
        """FLAGS_layer_scan on a staged program: isomorphic per-layer
        runs inside each stage trace as ONE lax.scan — losses AND final
        trained state bitwise vs the unscanned pipeline (dropout RNG
        chain threaded through the scan carry)."""
        import jax

        X, Y = _data()
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:3]), ("pp",))
        base, st_base = self._run(False, X, Y, mesh, head_stage=2)
        stat_reset("pipeline_scan_segments")
        got, st_got = self._run(True, X, Y, mesh, head_stage=2)
        assert stat_get("pipeline_scan_segments") >= 2  # fwd + opt runs
        assert got == base, (got, base)
        for n in st_base:
            np.testing.assert_array_equal(st_base[n], st_got[n])

    def test_scan_with_trailing_stage_ops_close(self, mesh_pp2):
        """Head sharing the last scanned stage: the trailing op sits at
        a different fusion boundary, so the pin is 1e-6, not bitwise."""
        X, Y = _data()
        base, _ = self._run(False, X, Y, mesh_pp2, head_stage=1)
        got, _ = self._run(True, X, Y, mesh_pp2, head_stage=1)
        np.testing.assert_allclose(got, base, rtol=1e-6, atol=1e-7)


@pytest.mark.slow
class TestElasticCkptAcrossPPDegree:
    def test_save_restore_across_pp_degree_change(self, tmp_path,
                                                  mesh_pp2):
        """Train 2 steps at pp=2, checkpoint through the manager (the
        PackedParamRef views materialize true per-var values), restore
        into a 4-stage retagging of the same layers on a pp=4 mesh,
        and continue — the restored continuation matches the
        single-device continuation from the same checkpoint ≤1e-4
        (params AND momentum slots round-trip exactly; only schedule
        reassociation differs)."""
        import jax

        from paddle_tpu.ckpt import CheckpointManager

        def build(n_stages):
            main, startup = Program(), Program()
            main.random_seed = 1
            with unique_name.guard(), program_guard(main, startup):
                x = layers.data("x", [H])
                y = layers.data("y", [1])
                h = x
                for i in range(4):
                    stage = i if n_stages == 4 else i // 2
                    with device_guard(f"stage:{stage}"):
                        h = layers.fc(h, H, act="relu", name=f"l{i}",
                                      param_attr=_attr(0.05 + 0.01 * i),
                                      bias_attr=False)
                with device_guard(f"stage:{n_stages - 1}"):
                    pred = layers.fc(h, 1, name="head",
                                     param_attr=_attr(0.1),
                                     bias_attr=False)
                    loss = layers.mean(layers.square_error_cost(pred, y))
                PipelineOptimizer(MomentumOptimizer(0.05, 0.9),
                                  num_microbatches=2).minimize(loss)
            return main, startup, loss

        X, Y = _data()
        # phase 1: pp=2
        main2, startup2, loss2 = build(2)
        _, sc, exe = _train(main2, startup2, loss2, X, Y, mesh_pp2,
                            steps=2)
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        state_names = [n for n in sorted(sc.local_var_names())
                       if (".w_" in n or "velocity" in n.lower()
                           or "_moment" in n)]
        mgr.save(2, scope=sc, var_names=state_names)

        def continue_from(main, startup, loss, mesh, steps=2):
            sc2 = pt.framework.Scope()
            exe2 = pt.Executor(pt.CPUPlace(), mesh=mesh)
            exe2.run(startup, scope=sc2)
            res = mgr.restore(scope=sc2, var_names=state_names)
            assert res and res["step"] == 2
            out = [float(np.asarray(
                exe2.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                         scope=sc2)[0]).item()) for _ in range(steps)]
            exe2.drain()
            return out

        # restored continuation on the NEW topology (pp=4)
        devs = np.array(jax.devices())
        mesh4 = jax.sharding.Mesh(devs[:4], ("pp",))
        main4, startup4, loss4 = build(4)
        got = continue_from(main4, startup4, loss4, mesh4)
        # oracle: single-device continuation from the same checkpoint
        main1, startup1, loss1 = build(2)
        base = continue_from(main1, startup1, loss1, None)
        np.testing.assert_allclose(got, base, rtol=1e-4, atol=1e-6)


@pytest.mark.slow
class TestStretchedBucketE2E:
    def test_stretched_bucket_numerics_bitwise_vs_unfused(self):
        """A layer-scanned dp program whose stacked grad carriers AND
        unrolled head grads ride FuseAllReducePass: stretched buckets
        (overlap ON) keep losses bitwise-equal to the unfused run
        (FLAGS_fuse_passes off — layer scan still applies via its own
        gate), and the carrier bucket's collective sits before the
        unrolled tail in the post-pass stream."""
        import jax

        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.parallel_env import (reset_mesh,
                                                         set_mesh)

        def build():
            main, startup = Program(), Program()
            main.random_seed = 2
            with unique_name.guard(), program_guard(main, startup):
                x = layers.data("x", [H])
                y = layers.data("y", [1])
                h = x
                for i in range(4):
                    h = layers.fc(h, H, act="relu", name=f"l{i}",
                                  param_attr=_attr(0.05), bias_attr=False)
                pred = layers.fc(h, 1, name="head", param_attr=_attr(0.1),
                                 bias_attr=False)
                loss = layers.mean(layers.square_error_cost(pred, y))
                fleet.init(is_collective=True)
                fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
                fleet.minimize(loss)
            return main, startup, loss

        X, Y = _data()
        devs = np.array(jax.devices())
        mesh = jax.sharding.Mesh(devs[:2], ("dp",))
        set_mesh(mesh)
        pt.set_flags({"FLAGS_layer_scan": True,
                      "FLAGS_layer_scan_min_layers": 3})
        try:
            fused, _, _ = _train(*build(), X, Y, mesh, steps=4)
            pt.set_flags({"FLAGS_fuse_passes": False})
            try:
                unfused, _, _ = _train(*build(), X, Y, mesh, steps=4)
            finally:
                pt.set_flags({"FLAGS_fuse_passes": True})
            assert fused == unfused, (fused, unfused)
        finally:
            pt.set_flags({"FLAGS_layer_scan": False})
            reset_mesh()
