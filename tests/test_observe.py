"""Observability layer (paddle_tpu.observe): span tracer + Chrome-trace
export, log-bucketed latency histograms, Prometheus /metrics exposition,
and the Executor-fed step telemetry (StepTimer/MFU).

Reference parity: DeviceTracer -> profiler.proto -> tools/timeline.py
(SURVEY L11) and StatRegistry runtime counters, rebuilt TPU-native as an
in-process ring buffer + text exposition (no CUPTI, no proto hop).
"""
import json
import math
import threading
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, observe
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.monitor import export_stats, stat_add, stat_reset
from paddle_tpu.observe.histogram import BUCKET_BOUNDS, Histogram


@pytest.fixture
def tracer_on():
    observe.clear()
    observe.enable()
    yield
    observe.disable()
    observe.clear()


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------


class TestHistogram:
    def test_count_sum_max_exact(self):
        h = Histogram("t")
        for v in (0.001, 0.002, 0.004, 0.1):
            h.observe(v)
        assert h.count == 4
        assert h.sum == pytest.approx(0.107)
        assert h.max == 0.1

    def test_quantiles_within_bucket_resolution(self):
        h = Histogram("t")
        vals = [0.001] * 50 + [0.010] * 45 + [0.500] * 5
        for v in vals:
            h.observe(v)
        # log2 buckets: the estimate must land within one bucket (2x)
        # of the true quantile, and never above the exact max
        assert h.percentile(50) <= 0.002048  # bucket containing 1ms
        assert 0.008 <= h.percentile(95) <= 0.02
        assert h.percentile(99) <= h.max == 0.5

    def test_negative_and_nan_dropped(self):
        h = Histogram("t")
        h.observe(-1.0)
        h.observe(float("nan"))
        assert h.count == 0

    def test_out_of_range_goes_to_inf_bucket(self):
        h = Histogram("t")
        h.observe(1e9)  # way past the last finite bound
        rows = h.cumulative_buckets()
        assert rows[-1] == (math.inf, 1)
        assert rows[-2][1] == 0  # not in any finite bucket

    def test_bucket_bounds_are_log2_from_1us(self):
        assert BUCKET_BOUNDS[0] == 1e-6
        for lo, hi in zip(BUCKET_BOUNDS, BUCKET_BOUNDS[1:]):
            assert hi == pytest.approx(2 * lo)

    def test_stat_time_rides_export_stats(self):
        observe.histogram("obs_test_seconds").reset()
        from paddle_tpu.monitor import stat_time

        stat_time("obs_test_seconds", 0.25)
        stat_time("obs_test_seconds", 0.25)
        snap = dict(export_stats())
        assert snap["obs_test_seconds_count"] == 2
        assert snap["obs_test_seconds_max"] == pytest.approx(0.25)
        names = [n for n, _ in export_stats()]
        assert names == sorted(names)  # still one sorted snapshot


class TestPrometheus:
    def _parse(self, text):
        """Minimal exposition-format parser: name{labels} value."""
        metrics = {}
        for line in text.strip().splitlines():
            if line.startswith("#"):
                continue
            name_part, value = line.rsplit(" ", 1)
            metrics[name_part] = float(value)
        return metrics

    def test_counters_and_histogram_render(self):
        stat_reset()
        observe.histogram("step_time_seconds").reset()
        stat_add("executor_run", 7)
        observe.stat_time("step_time_seconds", 0.004)
        observe.stat_time("step_time_seconds", 0.016)
        text = observe.prometheus_text()
        m = self._parse(text)
        assert m["paddle_tpu_executor_run"] == 7
        assert m["paddle_tpu_step_time_seconds_count"] == 2
        assert m["paddle_tpu_step_time_seconds_sum"] == pytest.approx(0.02)
        # cumulative buckets: monotone, +Inf == count
        buckets = [(k, v) for k, v in m.items()
                   if k.startswith("paddle_tpu_step_time_seconds_bucket")]
        assert buckets, text
        counts = [v for _, v in buckets]
        assert counts == sorted(counts)
        assert m['paddle_tpu_step_time_seconds_bucket{le="+Inf"}'] == 2
        assert "# TYPE paddle_tpu_step_time_seconds histogram" in text

    def test_name_sanitization(self):
        observe.stat_time("weird name-with.chars_seconds", 0.001)
        text = observe.prometheus_text()
        assert "paddle_tpu_weird_name_with_chars_seconds_count" in text

    def test_metrics_route_over_real_http(self):
        from paddle_tpu.distributed.fleet.utils.http_server import KVServer

        observe.stat_time("step_time_seconds", 0.008)
        kv = KVServer(0)
        kv.start()
        try:
            url = f"http://127.0.0.1:{kv.port}/metrics"
            with urllib.request.urlopen(url, timeout=10) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/plain")
                body = r.read().decode()
        finally:
            kv.stop()
        assert "paddle_tpu_step_time_seconds_bucket{" in body
        self._parse(body)  # parses clean


# ---------------------------------------------------------------------------
# tracer + timeline
# ---------------------------------------------------------------------------


class TestTracer:
    def test_disabled_span_is_noop(self):
        observe.disable()
        observe.clear()
        with observe.span("should_not_record"):
            pass
        assert observe.snapshot() == []

    def test_disabled_overhead_near_zero(self):
        """ISSUE acceptance: tracer off => near-zero per-span cost.  10k
        disabled spans must stay far under a millisecond each (generous
        CI bound; typical is <1us)."""
        import time

        observe.disable()
        n = 10_000
        t0 = time.perf_counter()
        for _ in range(n):
            with observe.span("off"):
                pass
        per_span = (time.perf_counter() - t0) / n
        assert per_span < 50e-6, f"disabled span cost {per_span * 1e6:.1f}us"

    def test_nesting_and_args(self, tracer_on):
        with observe.span("outer", phase="x"):
            with observe.span("inner", bytes=128):
                pass
        recs = {r.name: r for r in observe.snapshot()}
        assert recs["inner"].depth == 1
        assert recs["inner"].parent == "outer"
        assert recs["inner"].args == {"bytes": 128}
        assert recs["outer"].depth == 0 and recs["outer"].parent is None
        assert recs["outer"].t_begin <= recs["inner"].t_begin
        assert recs["inner"].t_end <= recs["outer"].t_end

    def test_concurrent_threads_nest_independently(self, tracer_on):
        """Each thread gets its own parent stack: sibling threads never
        corrupt each other's nesting."""
        barrier = threading.Barrier(2)

        def work(tag):
            barrier.wait()
            for _ in range(20):
                with observe.span(f"{tag}/outer"):
                    with observe.span(f"{tag}/inner"):
                        pass

        ts = [threading.Thread(target=work, args=(f"t{i}",))
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        recs = observe.snapshot()
        assert len(recs) == 80
        for r in recs:
            tag = r.name.split("/", 1)[0]
            if r.name.endswith("/inner"):
                assert r.depth == 1 and r.parent == f"{tag}/outer"
            else:
                assert r.depth == 0 and r.parent is None
        # spans of different tags come from different threads
        tids = {r.name.split("/", 1)[0]: r.tid for r in recs}
        assert tids["t0"] != tids["t1"]

    def test_explicit_begin_end_respects_flag_and_stays_balanced(self):
        """Module-level begin()/end() are gated like span(); a begin
        made while disabled leaves only a discard sentinel, so nesting
        stays correct even when the flag flips mid-pair."""
        observe.clear()
        observe.disable()
        observe.begin("off")
        observe.end()
        assert observe.snapshot() == []
        observe.begin("off2")  # disabled: sentinel only
        observe.enable()
        try:
            with observe.span("live"):  # nested "under" the sentinel
                pass
        finally:
            observe.end()  # pops the sentinel, records nothing
            observe.disable()
        recs = observe.snapshot()
        assert [r.name for r in recs] == ["live"]
        assert recs[0].depth == 0 and recs[0].parent is None
        observe.clear()

    def test_ring_buffer_bounds_memory(self):
        t = observe.Tracer(capacity=8)
        for i in range(20):
            t.begin(f"s{i}")
            t.end()
        assert len(t.snapshot()) == 8
        assert t.dropped == 12
        assert t.snapshot()[-1].name == "s19"

    def test_chrome_trace_schema(self, tracer_on, tmp_path):
        with observe.span("a", k=1):
            with observe.span("b"):
                pass
        path = str(tmp_path / "trace.json")
        observe.export_chrome_trace(path)
        with open(path) as f:
            doc = json.load(f)  # schema-valid JSON
        evs = doc["traceEvents"]
        assert isinstance(evs, list)
        xs = [e for e in evs if e.get("ph") == "X"]
        assert {e["name"] for e in xs} == {"a", "b"}
        for e in xs:
            for field in ("name", "ph", "ts", "dur", "pid", "tid"):
                assert field in e, e
            assert e["dur"] >= 0
        # thread metadata present so Perfetto labels the lane
        assert any(e.get("ph") == "M" and e.get("name") == "thread_name"
                   for e in evs)
        # nesting is containment on the shared lane
        a = next(e for e in xs if e["name"] == "a")
        b = next(e for e in xs if e["name"] == "b")
        assert a["tid"] == b["tid"]
        assert a["ts"] <= b["ts"]
        assert b["ts"] + b["dur"] <= a["ts"] + a["dur"] + 1e-3


# ---------------------------------------------------------------------------
# executor integration (8-device mesh, acceptance scenario)
# ---------------------------------------------------------------------------


def _fleet_mlp():
    """2-layer MLP transpiled for 8-way data parallelism: its backward
    carries transpiler-marked c_allreduce_sum ops the fuse pass buckets."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.optimizer import MomentumOptimizer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = Program(), Program()
    main.random_seed = 1
    with program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1])
        h = layers.fc(x, 16, act="relu", param_attr=ParamAttr(
            initializer=ConstantInitializer(0.1)), bias_attr=False)
        pred = layers.fc(h, 1, param_attr=ParamAttr(
            initializer=ConstantInitializer(0.2)), bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = MomentumOptimizer(0.05, 0.9)
        fleet.init(is_collective=True)
        fleet.distributed_optimizer(opt)
        fleet.minimize(loss)
    return main, startup, loss


class TestExecutorTelemetry:
    def test_mesh_run_produces_phase_and_collective_spans(self, tracer_on,
                                                          tmp_path):
        """ISSUE acceptance: Executor.run on the 8-device mesh with the
        tracer enabled -> Chrome trace with nested pass-pipeline /
        lowering / compile / execute spans AND per-collective spans
        carrying byte counts."""
        from paddle_tpu.distributed.parallel_env import (init_parallel_env,
                                                         reset_mesh)

        mesh = init_parallel_env()
        try:
            main, startup, loss = _fleet_mlp()
            scope = pt.framework.Scope()
            exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
            exe.run(startup, scope=scope)
            X = np.random.RandomState(0).randn(16, 8).astype("f4")
            Y = np.ones((16, 1), "f4")
            feed = {"x": X, "y": Y}
            exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
            out = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
            # pipelined dispatch: the executor/fetch span fires when the
            # fetches are actually READ (StepHandle materialization)
            np.asarray(out[0])
        finally:
            reset_mesh()

        recs = observe.snapshot()
        names = {r.name for r in recs}
        for phase in ("executor/run", "executor/pass_pipeline",
                      "executor/analysis", "executor/compile",
                      "executor/lowering", "executor/execute",
                      "executor/fetch"):
            assert phase in names, sorted(names)
        # per-pass span under the pipeline (fuse pass bucketed 2 grads)
        assert "pass/fuse_allreduce" in names
        # collective spans carry bytes + dtype
        colls = [r for r in recs if r.name.startswith("collective/")]
        assert any(r.name == "collective/c_allreduce_sum" for r in colls)
        for r in colls:
            if r.name == "collective/c_allreduce_sum":
                assert r.args and r.args["bytes"] > 0
                assert "float32" in r.args["dtype"]
        # nesting: lowering under compile, compile under run
        by_name = {r.name: r for r in recs}
        assert by_name["executor/lowering"].depth \
            > by_name["executor/compile"].depth
        assert by_name["executor/compile"].parent == "executor/run"
        # second run is a cache hit: an execute span at depth 1
        execs = [r for r in recs if r.name == "executor/execute"]
        assert any(r.parent == "executor/run" for r in execs)

        # the whole thing exports as schema-valid Chrome trace JSON
        path = str(tmp_path / "mesh_trace.json")
        observe.export_chrome_trace(path)
        with open(path) as f:
            doc = json.load(f)
        assert any(e.get("name") == "collective/c_allreduce_sum"
                   and e.get("args", {}).get("bytes", 0) > 0
                   for e in doc["traceEvents"])

    def test_step_timer_feeds_histogram_and_mfu_accounting(self):
        observe.reset_step_stats()
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", [4])
            y = layers.fc(x, 2, bias_attr=False)
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.framework.Scope()
        exe.run(startup, scope=scope)
        feed = {"x": np.ones((3, 4), "f4")}
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[y], scope=scope)
        s = observe.step_timer().summary()
        assert s["compiles"] >= 1
        assert s["steps"] >= 2  # non-compile runs
        assert s["step_time_s"]["count"] == s["steps"]
        assert s["step_time_s"]["p50"] > 0
        assert s["examples_per_sec"] > 0
        # fc(3x4 -> 2): matmul flops counted per step, batch-scaled
        assert s["flops_per_step"] >= 2 * 3 * 2 * 4
        assert "paddle_tpu_step_time_seconds_bucket{" \
            in observe.prometheus_text()

    def test_step_timer_counts_allreduce_bytes(self):
        from paddle_tpu.distributed.parallel_env import (init_parallel_env,
                                                         reset_mesh)

        observe.reset_step_stats()
        mesh = init_parallel_env()
        try:
            main, startup, loss = _fleet_mlp()
            scope = pt.framework.Scope()
            exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
            exe.run(startup, scope=scope)
            feed = {"x": np.zeros((16, 8), "f4"),
                    "y": np.zeros((16, 1), "f4")}
            exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
            exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        finally:
            reset_mesh()
        s = observe.step_timer().summary()
        # grads: 8x16 + 16x1 floats = 144 * 4 bytes reduced per step
        assert s["allreduce_bytes_per_step"] == 144 * 4

    def test_mfu_estimate_math(self):
        # 1 TFLOP in 0.1s = 10 TFLOP/s; at a 100-TFLOP/s peak -> 0.1
        assert observe.mfu_estimate(1e12, 0.1, peak_tflops=100.0) \
            == pytest.approx(0.1)
        assert observe.mfu_estimate(0.0, 0.1, peak_tflops=100.0) == 0.0
        assert observe.mfu_estimate(1e12, 0.0, peak_tflops=100.0) == 0.0


# ---------------------------------------------------------------------------
# serving lifecycle + hapi callback
# ---------------------------------------------------------------------------


class TestServingTelemetry:
    def test_batch_lifecycle_spans_and_latency_histogram(self, tracer_on,
                                                         tmp_path):
        import shutil
        import tempfile

        from paddle_tpu import serving
        from paddle_tpu.fluid import io as fluid_io
        from paddle_tpu.framework import unique_name
        from paddle_tpu.framework.place import _default_place
        from paddle_tpu.framework.scope import _switch_scope

        observe.histogram("serving_latency_seconds").reset()
        d = tempfile.mkdtemp(prefix="observe_serving_")
        try:
            main, startup = Program(), Program()
            with unique_name.guard(), program_guard(main, startup):
                x = layers.data("x", [4])
                out = layers.fc(x, 2, bias_attr=False)
            sc = pt.framework.Scope()
            exe = pt.Executor(_default_place())
            exe.run(startup, scope=sc)
            old = _switch_scope(sc)
            try:
                fluid_io.save_inference_model(d, ["x"], [out], exe, main)
            finally:
                _switch_scope(old)

            srv = serving.Server(d, serving.ServingConfig(
                batch_sizes=(1, 2, 4), batch_window_ms=1.0))
            srv.start()
            try:
                srv.infer({"x": np.ones((1, 4), "f4")})
                srv.infer({"x": np.ones((2, 4), "f4")})
            finally:
                srv.stop(drain=True)
        finally:
            shutil.rmtree(d, ignore_errors=True)

        names = {r.name for r in observe.snapshot()}
        for phase in ("serving/enqueue", "serving/coalesce", "serving/pad",
                      "serving/execute", "serving/reply"):
            assert phase in names, sorted(names)
        h = observe.histogram("serving_latency_seconds").summary()
        assert h["count"] == 2
        assert h["p50"] > 0
        # latency quantiles reach the /stats payload
        snap = dict(export_stats())
        assert snap["serving_latency_seconds_count"] == 2


class TestBenchmarkCallback:
    def test_fit_records_step_histogram_and_summary(self, capsys):
        import paddle_tpu.optimizer as optim
        from paddle_tpu import nn
        from paddle_tpu.hapi import BenchmarkCallback
        from paddle_tpu.hapi.model import InputSpec

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 1)

            def forward(self, x):
                return self.fc(x)

        model = pt.Model(Net(), inputs=[InputSpec([None, 4], "float32", "x")],
                         labels=[InputSpec([None, 1], "float32", "y")])
        model.prepare(optim.Adam(0.01, parameters=model.parameters()),
                      nn.MSELoss())
        X = np.random.RandomState(0).randn(16, 4).astype("f4")
        Y = np.ones((16, 1), "f4")
        cb = BenchmarkCallback(batch_size=8)
        model.fit(list(zip(X, Y)), batch_size=8, epochs=2, verbose=0,
                  callbacks=[cb])
        s = cb.last_summary
        assert s is not None
        assert s["steps"] > 0
        assert s["step_time_s"]["count"] == s["steps"]
        assert s["steps_per_sec"] > 0
        assert s["examples_per_sec"] > 0
        assert "[bench]" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# timeline CLI (satellite: dump a trace from any run, no code changes)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_timeline_cli_traces_a_script(tmp_path):
    import os
    import subprocess
    import sys

    script = tmp_path / "tiny.py"
    script.write_text(
        "import numpy as np\n"
        "import paddle_tpu as pt\n"
        "from paddle_tpu import layers\n"
        "from paddle_tpu.framework.program import Program, program_guard\n"
        "main, startup = Program(), Program()\n"
        "with program_guard(main, startup):\n"
        "    x = layers.data('x', [4])\n"
        "    y = layers.fc(x, 2)\n"
        "exe = pt.Executor(pt.CPUPlace())\n"
        "scope = pt.framework.Scope()\n"
        "exe.run(startup, scope=scope)\n"
        "exe.run(main, feed={'x': np.ones((3, 4), 'f4')},\n"
        "        fetch_list=[y], scope=scope)\n")
    out = tmp_path / "trace.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.observe.timeline",
         str(out), str(script)],
        capture_output=True, text=True, timeout=300, env=env, cwd=root)
    assert r.returncode == 0, r.stderr[-2000:]
    with open(out) as f:
        doc = json.load(f)
    names = {e.get("name") for e in doc["traceEvents"]}
    assert "executor/run" in names
    assert "executor/lowering" in names
