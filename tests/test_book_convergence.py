"""Book-style end-to-end convergence tests.

Role parity: reference python/paddle/fluid/tests/book/ (test_fit_a_line.py,
test_recognize_digits.py) — build a model with layers, train with an
optimizer through the Executor, assert the loss falls below a threshold.
Data is synthetic (no-egress environment): class-prototype images with
noise, which LeNet must fit nearly perfectly.
"""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.optimizer import AdamOptimizer, MomentumOptimizer, SGDOptimizer


def _proto_sampler(rng, num_classes=10, hw=28):
    protos = rng.randn(num_classes, 1, hw, hw).astype("float32")

    def sample(n):
        labels = rng.randint(0, num_classes, n).astype("int64")
        imgs = protos[labels] + 0.15 * rng.randn(n, 1, hw, hw).astype("float32")
        return imgs, labels[:, None]

    return sample


def test_fit_a_line():
    """Linear regression converges (reference book/test_fit_a_line.py)."""
    rng = np.random.RandomState(0)
    true_w = rng.randn(13, 1).astype("float32")
    main, startup = Program(), Program()
    main.random_seed = 7
    with program_guard(main, startup):
        x = layers.data("x", [13])
        y = layers.data("y", [1])
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        SGDOptimizer(learning_rate=0.01).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    last = None
    for i in range(200):
        xv = rng.randn(32, 13).astype("float32")
        yv = xv @ true_w
        (last,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
    assert last[0] < 0.1, f"fit_a_line did not converge: {last}"


def _lenet(img, label):
    c1 = layers.conv2d(img, 6, 5, padding=2, act="relu")
    p1 = layers.pool2d(c1, 2, "max", 2)
    c2 = layers.conv2d(p1, 16, 5, act="relu")
    p2 = layers.pool2d(c2, 2, "max", 2)
    f1 = layers.fc(p2, 120, act="relu")
    f2 = layers.fc(f1, 84, act="relu")
    logits = layers.fc(f2, 10)
    loss = layers.mean(
        layers.softmax_with_cross_entropy(logits, label)
    )
    acc = layers.accuracy(layers.softmax(logits), label)
    return loss, acc


def test_recognize_digits_lenet():
    """LeNet on synthetic digits (reference book/test_recognize_digits.py)."""
    rng = np.random.RandomState(42)
    main, startup = Program(), Program()
    main.random_seed = 42
    with program_guard(main, startup):
        img = layers.data("img", [1, 28, 28])
        label = layers.data("label", [1], dtype="int64")
        loss, acc = _lenet(img, label)
        AdamOptimizer(learning_rate=1e-3).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    sample = _proto_sampler(rng)
    losses = []
    for i in range(60):
        imgs, labels = sample(32)
        lv, av = exe.run(main, feed={"img": imgs, "label": labels}, fetch_list=[loss, acc])
        losses.append(float(lv[0]))
    assert losses[-1] < 0.5, f"LeNet did not converge: {losses[-5:]}"
    assert losses[-1] < losses[0] * 0.5


def test_mlp_adam_accuracy():
    rng = np.random.RandomState(3)
    main, startup = Program(), Program()
    main.random_seed = 3
    with program_guard(main, startup):
        x = layers.data("x", [20])
        label = layers.data("label", [1], dtype="int64")
        h = layers.fc(x, 64, act="relu")
        logits = layers.fc(h, 4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        AdamOptimizer(learning_rate=1e-3).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    protos = rng.randn(4, 20).astype("float32")
    accs = []
    for i in range(300):
        lbl = rng.randint(0, 4, 64).astype("int64")
        xv = protos[lbl] + 0.3 * rng.randn(64, 20).astype("float32")
        lv, av = exe.run(
            main, feed={"x": xv, "label": lbl[:, None]}, fetch_list=[loss, acc]
        )
        accs.append(float(av[0]))
    assert np.mean(accs[-20:]) > 0.95, f"accuracy too low: {np.mean(accs[-20:])}"


def test_word2vec_embedding_trains():
    """Embedding + fc language-model-ish task (reference book/test_word2vec.py)."""
    rng = np.random.RandomState(5)
    vocab, dim = 50, 16
    main, startup = Program(), Program()
    main.random_seed = 5
    with program_guard(main, startup):
        w = layers.data("w", [3], dtype="int64", append_batch_size=True)
        emb = layers.embedding(w, (vocab, dim))
        flat = layers.reshape(emb, [-1, 3 * dim])
        h = layers.fc(flat, 64, act="relu")
        logits = layers.fc(h, vocab)
        label = layers.data("label", [1], dtype="int64")
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        AdamOptimizer(learning_rate=5e-3).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    # synthetic rule: next word = (sum of context) % vocab
    losses = []
    for i in range(200):
        ctx = rng.randint(0, vocab, (64, 3)).astype("int64")
        nxt = (ctx.sum(1) % vocab)[:, None].astype("int64")
        (lv,) = exe.run(main, feed={"w": ctx, "label": nxt}, fetch_list=[loss])
        losses.append(float(lv[0]))
    assert losses[-1] < losses[0], f"word2vec loss not decreasing: {losses[::50]}"
