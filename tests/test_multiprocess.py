"""Multi-process distributed oracle: REAL subprocesses + loss parity.

Reference parity: unittests/test_dist_base.py `check_with_place` (:1007)
— spawn local trainer processes on 127.0.0.1, run N steps, assert the
distributed per-step losses match the single-process run.  This is the
only test that actually executes distributed/launch.py,
jax.distributed.initialize, and cross-process XLA collectives (gloo CPU
backend standing in for ICI/DCN).
"""
import json
import os
import socket
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.distributed.launch import (
    start_local_trainers,
    terminate_local_procs,
    watch_local_trainers,
)
from paddle_tpu.framework.program import Program, program_guard

TRAINER = os.path.join(os.path.dirname(__file__), "dist_trainer.py")

# capability probe (tests/conftest.py jax_capability, backed by
# framework/jax_compat.py): jax versions without the
# jax_cpu_collectives_implementation config have NO cross-process CPU
# collectives — the XLA CPU client rejects multiprocess computations
# outright ("Multiprocess computations aren't implemented on the CPU
# backend"), so the localhost federation these tests ride cannot exist.
# Before the guarded accessor this surfaced as an AttributeError inside
# init_parallel_env; now it is an explicit environment skip.
from conftest import jax_capability  # noqa: E402

if not jax_capability("cpu_collectives"):
    pytest.skip(
        "installed jax has no CPU cross-process collectives backend "
        "(jax_cpu_collectives_implementation config absent)",
        allow_module_level=True)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env():
    """Trainer env: CPU backend, gloo cross-process collectives, and NO
    xla_force_host_platform_device_count (it breaks CPU federation —
    each process must contribute exactly its real local devices)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = "gloo"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(flags)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(pt.__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _run_cluster(tmp_path, nproc, steps=5, extra_env=None):
    port = _free_port()
    outs = [str(tmp_path / f"out-{r}.json") for r in range(nproc)]
    env = _child_env()
    env.update(extra_env or {})
    procs = []
    old = os.environ.copy()
    os.environ.clear()
    os.environ.update(env)
    try:
        for r in range(nproc):
            procs += start_local_trainers(
                1, f"127.0.0.1:{port}", TRAINER, [outs[r], str(steps)],
                log_dir=str(tmp_path / "logs"), base_rank=r, total=nproc)
        rc = watch_local_trainers(procs)
    finally:
        terminate_local_procs(procs)
        os.environ.clear()
        os.environ.update(old)
    if rc != 0:
        logs = ""
        logdir = tmp_path / "logs"
        for f in sorted(logdir.glob("workerlog.*")):
            logs += f"\n----- {f.name} -----\n" + f.read_text()[-3000:]
        raise AssertionError(f"cluster exited rc={rc}{logs}")
    return [json.load(open(p)) for p in outs]


def _single_process_losses(steps=5):
    # the SAME model/batch the ranks run (shared builder in dist_trainer)
    from tests.dist_trainer import build_model, make_batch

    main, startup, loss = build_model(use_fleet=False)
    X, Y = make_batch()
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    return [float(np.asarray(
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                scope=scope)[0]).ravel()[0]) for _ in range(steps)]



def test_two_process_loss_parity(tmp_path):
    """The reference oracle: 2-process distributed losses == local run."""
    results = _run_cluster(tmp_path, nproc=2, steps=5)
    base = _single_process_losses(steps=5)
    for res in results:
        np.testing.assert_allclose(res["losses"], base, rtol=1e-4,
                                   atol=1e-6)
    # both ranks must see the SAME (full-batch) loss sequence
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)



def test_two_process_dygraph_data_parallel_parity(tmp_path):
    """Dygraph DataParallel over 2 REAL processes (reference
    TestParallelDyGraphRunnerBase oracle): scale_loss +
    apply_collective_grads must reproduce the single-process full-batch
    trajectory."""
    import jax.numpy as jnp

    results = _run_cluster(tmp_path, nproc=2, steps=5,
                           extra_env={"PADDLE_TPU_TEST_DYGRAPH": "1"})
    # single-process oracle: same model, manual SGD on the full batch
    from tests.dist_trainer import make_batch

    X, Y = make_batch()
    w = np.full((8, 1), 0.1, "f4")
    base = []
    for _ in range(5):
        pred = X @ w
        diff = pred - Y
        base.append(float(np.mean(diff * diff)))
        grad = 2.0 * X.T @ diff / len(X)
        w = w - 0.05 * grad
    for res in results:
        np.testing.assert_allclose(res["losses"], base, rtol=1e-4,
                                   atol=1e-6)


def test_two_process_zero_sharding_parity(tmp_path):
    """ZeRO-1 over 2 REAL processes: reduce-scattered grads + dp-sharded
    optimizer state must still reproduce the single-process trajectory
    (each process feeds jax only its dp block of the replicated-startup
    state)."""
    results = _run_cluster(tmp_path, nproc=2, steps=5,
                           extra_env={"PADDLE_TPU_TEST_SHARDING": "1"})
    base = _single_process_losses(steps=5)
    for res in results:
        np.testing.assert_allclose(res["losses"], base, rtol=1e-4,
                                   atol=1e-6)


def test_two_process_localsgd_runs_and_converges(tmp_path):
    """LocalSGD's first end-to-end execution: k_steps=2 param averaging
    across 2 real processes; losses must be finite and decreasing (exact
    parity does not hold by construction — params sync every k steps)."""
    results = _run_cluster(tmp_path, nproc=2, steps=6,
                           extra_env={"PADDLE_TPU_TEST_LOCALSGD": "1"})
    for res in results:
        ls = res["losses"]
        assert np.isfinite(ls).all(), ls
        assert ls[-1] < ls[0], ls
