"""Multiprocess DataLoader workers (reference
fluid/dataloader/dataloader_iter.py:467 _DataLoaderIterMultiProcess):
real processes, ordered reassembly, error propagation, worker_info.
"""
import os

import numpy as np
import pytest

from paddle_tpu.io import DataLoader, Dataset, get_worker_info


class _Square(Dataset):
    def __len__(self):
        return 20

    def __getitem__(self, i):
        return np.array([i * i], "f4"), np.array([i], "i4")


class _PidDataset(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        return np.array([os.getpid()], "i8"), np.array([i], "i4")


class _WorkerInfoDataset(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        info = get_worker_info()
        wid = -1 if info is None else info.id
        return np.array([wid], "i4"), np.array([i], "i4")


class _Boom(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("synthetic decode failure at 5")
        return np.array([i], "f4"), np.array([i], "i4")


def test_mp_matches_serial_in_order():
    serial = [b for b in DataLoader(_Square(), batch_size=4, num_workers=0,
                                    use_buffer_reader=False)]
    mp = [b for b in DataLoader(_Square(), batch_size=4, num_workers=2,
                                use_buffer_reader=False)]
    assert len(serial) == len(mp) == 5
    for (sx, sy), (mx, my) in zip(serial, mp):
        np.testing.assert_array_equal(np.asarray(sx), np.asarray(mx))
        np.testing.assert_array_equal(np.asarray(sy), np.asarray(my))


def test_workers_are_real_processes():
    batches = list(DataLoader(_PidDataset(), batch_size=2, num_workers=2,
                              use_buffer_reader=False))
    pids = {int(p) for b in batches for p in np.asarray(b[0]).ravel()}
    assert os.getpid() not in pids, "samples were loaded in-process"
    assert len(pids) >= 1


def test_worker_info_visible_in_worker():
    batches = list(DataLoader(_WorkerInfoDataset(), batch_size=2,
                              num_workers=2, use_buffer_reader=False))
    wids = {int(w) for b in batches for w in np.asarray(b[0]).ravel()}
    assert wids <= {0, 1} and wids, wids
    assert get_worker_info() is None  # parent process


def test_worker_error_propagates():
    with pytest.raises(RuntimeError, match="synthetic decode failure"):
        list(DataLoader(_Boom(), batch_size=2, num_workers=2,
                        use_buffer_reader=False))


def test_abandoned_iterator_reaps_workers():
    """Breaking out of an epoch must shut the forked workers down, not
    leak one set per abandoned epoch."""
    import gc
    import time

    import multiprocessing as mp

    before = len(mp.active_children())
    loader = DataLoader(_Square(), batch_size=2, num_workers=2)
    it = iter(loader)
    next(it)
    del it, loader
    gc.collect()
    deadline = time.time() + 10
    while time.time() < deadline:
        if len(mp.active_children()) <= before:
            break
        time.sleep(0.2)
    assert len(mp.active_children()) <= before, \
        f"{len(mp.active_children())} workers still alive"


def test_shuffle_epoch_coverage():
    loader = DataLoader(_Square(), batch_size=5, shuffle=True,
                        num_workers=2, use_buffer_reader=False)
    seen = sorted(int(i) for b in loader
                  for i in np.asarray(b[1]).ravel())
    assert seen == list(range(20))


class _TinyN(Dataset):
    """An epoch with fewer batches than the prefetch queue capacity —
    the round-4 regression: the producer finished while the bounded
    queue was full, dropped the _END sentinel, and __next__ blocked
    forever."""

    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.array([i], "f4")


@pytest.mark.parametrize("n_batches", [1, 2, 3])
def test_short_epoch_terminates(n_batches):
    loader = DataLoader(_TinyN(4 * n_batches), batch_size=4)  # buffered
    for _ in range(3):  # several epochs: sentinel must arrive every time
        assert len(list(loader)) == n_batches


@pytest.mark.parametrize("n_batches", [1, 2])
def test_short_epoch_terminates_with_workers(n_batches):
    loader = DataLoader(_TinyN(4 * n_batches), batch_size=4, num_workers=2)
    assert len(list(loader)) == n_batches


_FORK_MARKER = [0]  # mutated in the parent; survives only into FORKED children


class _StartMethodProbe(Dataset):
    """Forked children inherit the parent's mutated module state (and
    with it the parent's live JAX/TPU client); spawned children
    re-import this module fresh, so the marker reads 0."""

    def __len__(self):
        return 4

    def __getitem__(self, i):
        return np.array([_FORK_MARKER[0]], "i4")


def test_unpicklable_dataset_falls_back_to_threads():
    """A dataset that spawn can't pickle (local class) must degrade to
    the thread pool, not error the epoch — and must not leave the
    parent's JAX_PLATFORMS pin behind."""
    class _Local(Dataset):  # local => unpicklable by spawn
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return np.array([i], "f4")

    before = os.environ.get("JAX_PLATFORMS")
    batches = list(DataLoader(_Local(), batch_size=2, num_workers=2,
                              use_buffer_reader=False))
    assert len(batches) == 4
    assert os.environ.get("JAX_PLATFORMS") == before


def test_set_get_device_roundtrip():
    import paddle_tpu as pt
    from paddle_tpu.framework import place as place_mod

    saved = place_mod._pinned_place
    try:
        p = pt.set_device("cpu")
        assert type(p).__name__ == "CPUPlace"
        assert pt.get_device() == "cpu"
        p = pt.set_device("gpu:1")  # compat alias; index must stick
        assert p.device_id == 1
        assert pt.get_device() == "tpu:1"
    finally:
        place_mod._pinned_place = saved
        import jax

        jax.config.update("jax_platforms", "cpu")  # test env contract
        place_mod.accelerator_devices.cache_clear()


def test_workers_are_spawned_not_forked():
    """Workers must start interpreter-fresh (spawn): forking a
    jax-initialized multithreaded parent risks deadlock, and a forked
    orphan inheriting TPU client state can wedge the chip for every
    later process (reference workers are CPU-only by contract,
    dataloader_iter.py:467)."""
    _FORK_MARKER[0] = os.getpid()
    try:
        batches = list(DataLoader(_StartMethodProbe(), batch_size=2,
                                  num_workers=2, use_buffer_reader=False))
    finally:
        _FORK_MARKER[0] = 0
    seen = {int(v) for b in batches for v in np.asarray(b).ravel()}
    assert seen == {0}, f"workers saw parent memory (forked): {seen}"


def test_loader_module_is_importable_as_main(tmp_path):
    """A script iterating a num_workers>0 loader at top level WITHOUT an
    `if __name__ == "__main__"` guard must complete (fork tolerated
    this; spawn children fall back to threads while importing __main__
    instead of crashing the bootstrap)."""
    import subprocess
    import sys
    import textwrap

    repo_root = str(__import__("pathlib").Path(__file__).resolve().parents[1])
    ds_mod = tmp_path / "ds_mod.py"
    ds_mod.write_text(textwrap.dedent("""
        import numpy as np
        from paddle_tpu.io import Dataset

        class Sq(Dataset):
            def __len__(self): return 12
            def __getitem__(self, i): return np.array([i], "f4")
    """))
    script = tmp_path / "unguarded.py"
    script.write_text(textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {repo_root!r})
        sys.path.insert(0, {str(tmp_path)!r})
        import jax; jax.config.update('jax_platforms', 'cpu')
        from paddle_tpu.io import DataLoader
        from ds_mod import Sq
        n = sum(1 for _ in DataLoader(Sq(), batch_size=4, num_workers=2,
                                      use_buffer_reader=False))
        assert n == 3, n
        print("OK", n)
    """))
    env = dict(__import__("os").environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, str(script)], env=env,
                       capture_output=True, text=True, timeout=240,
                       cwd=repo_root)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "OK 3" in r.stdout, r.stdout
