"""paddle.autograd.PyLayer (reference python/paddle/autograd/
py_layer.py): custom forward/backward, ctx state, composition with the
tape, hooks, and paddle.grad."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import dygraph
from paddle_tpu.autograd import PyLayer


class Exp(PyLayer):
    @staticmethod
    def forward(ctx, x):
        from paddle_tpu import tensor as T

        y = T.exp(x)
        ctx.save_for_backward(y)
        return y

    @staticmethod
    def backward(ctx, dy):
        (y,) = ctx.saved_tensor()
        return dy * y


class ScaleByAttr(PyLayer):
    @staticmethod
    def forward(ctx, x, k):  # k is a plain python float
        ctx.k = k
        return x * k

    @staticmethod
    def backward(ctx, dy):
        return dy * ctx.k


class TwoInTwoOut(PyLayer):
    @staticmethod
    def forward(ctx, a, b):
        return a + b, a * b

    @staticmethod
    def backward(ctx, da, db):
        # d(a+b)/da=1, d(ab)/da=b — but backward sees only cotangents;
        # use a deliberately custom rule to prove IT is what runs
        return da * 2.0, db * 3.0


def test_exp_forward_backward_matches_analytic():
    with dygraph.guard():
        x = dygraph.to_variable(np.array([0.0, 1.0], "f4"))
        x.stop_gradient = False
        y = Exp.apply(x)
        np.testing.assert_allclose(np.asarray(y._value),
                                   np.exp([0.0, 1.0]), rtol=1e-6)
        y.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad._value),
                                   np.exp([0.0, 1.0]), rtol=1e-6)


def test_nontensor_arg_and_ctx_attr():
    with dygraph.guard():
        x = dygraph.to_variable(np.array([2.0], "f4"))
        x.stop_gradient = False
        y = ScaleByAttr.apply(x, 5.0)
        np.testing.assert_allclose(np.asarray(y._value), [10.0])
        y.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad._value), [5.0])


def test_custom_backward_rule_is_used():
    with dygraph.guard():
        a = dygraph.to_variable(np.array([1.0], "f4"))
        b = dygraph.to_variable(np.array([4.0], "f4"))
        a.stop_gradient = False
        b.stop_gradient = False
        s, p = TwoInTwoOut.apply(a, b)
        (s * 1.0 + p * 1.0).sum().backward()
        # custom rule: da = cot_s*2 = 2, db = cot_p*3 = 3
        np.testing.assert_allclose(np.asarray(a.grad._value), [2.0])
        np.testing.assert_allclose(np.asarray(b.grad._value), [3.0])


def test_composes_with_surrounding_tape_and_grad_api():
    with dygraph.guard():
        x = dygraph.to_variable(np.array([1.0], "f4"))
        x.stop_gradient = False
        h = x * 3.0
        y = Exp.apply(h) * 2.0
        (gx,) = dygraph.grad([y.sum()], [x])
        np.testing.assert_allclose(np.asarray(gx._value),
                                   [2.0 * 3.0 * np.exp(3.0)], rtol=1e-5)


def test_autograd_backward_with_explicit_cotangent():
    from paddle_tpu.autograd import backward

    with dygraph.guard():
        x = dygraph.to_variable(np.array([1.0, 2.0], "f4"))
        x.stop_gradient = False
        y = x * x
        backward([y], grad_tensors=[dygraph.to_variable(
            np.array([1.0, 10.0], "f4"))])
        np.testing.assert_allclose(np.asarray(x.grad._value),
                                   [2.0, 40.0])


class GatherRows(PyLayer):
    """Integer index input: its cotangent slot must be float0, and the
    user backward returns None for it."""

    @staticmethod
    def forward(ctx, x, idx):
        ctx.save_for_backward(idx)
        from paddle_tpu import tensor as T

        return T.gather(x, idx)

    @staticmethod
    def backward(ctx, dy):
        (idx,) = ctx.saved_tensor()
        from paddle_tpu import tensor as T
        import paddle_tpu as pt

        z = pt.to_tensor(np.zeros((4, 2), "f4"))
        return T.scatter(z, idx, dy), None


def test_integer_tensor_input():
    with dygraph.guard():
        x = dygraph.to_variable(np.arange(8, dtype="f4").reshape(4, 2))
        x.stop_gradient = False
        idx = dygraph.to_variable(np.array([2, 0], "i4"))
        y = GatherRows.apply(x, idx)
        np.testing.assert_allclose(np.asarray(y._value),
                                   [[4.0, 5.0], [0.0, 1.0]])
        y.sum().backward()
        expect = np.zeros((4, 2), "f4")
        expect[2] = 1.0
        expect[0] = 1.0
        np.testing.assert_allclose(np.asarray(x.grad._value), expect)
