"""Fused flash-attention training kernels (ISSUE 17).

Three layers under test, oracle-first:

- ops/flash_attention.py — the Pallas online-softmax forward + tiled
  recompute backward behind ONE ``jax.custom_vjp``.  Oracle is the
  pure-jnp masked softmax (``flash_attention_ref``), which stays the
  CPU/tier-1 default; the kernels are pinned to it in interpret mode
  (fwd <= 1e-6, grads ~1e-5 f32).
- framework/passes.py FlashAttentionPass — the graph rewrite of the
  unfused matmul -> [mask add] -> softmax -> matmul chain (plus its
  generic grad chain) into flash_attention/flash_attention_grad.
  Oracle is the unfused program itself: with FLAGS_flash_attention
  'never' (or 'auto' on CPU) nothing moves; under 'always' the
  rewritten program's losses match the unfused run bitwise on the CPU
  reference lowering.
- composition — the rewrite rides tensor parallelism (heads-dim mp
  specs flow through the fused op; losses match the single-chip
  oracle) and LayerScanPass (slow matrix).
"""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import flags as flags_mod
from paddle_tpu.framework import passes as passes_mod
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.initializer import NormalInitializer
from paddle_tpu.monitor import stat_get, stat_reset
from paddle_tpu.optimizer import MomentumOptimizer
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.ops import flash_attention as fa

from conftest import jax_capability

needs_pallas = pytest.mark.skipif(
    not jax_capability("pallas_interpret"),
    reason="no usable Pallas interpret mode on this jax")


@pytest.fixture(autouse=True)
def _flag_reset():
    yield
    pt.set_flags({"FLAGS_flash_attention": "auto",
                  "FLAGS_layer_scan": False})


def _qkv(rs, B=1, H=2, S=256, D=64):
    return (jnp.asarray(rs.randn(B, H, S, D).astype("f4")),
            jnp.asarray(rs.randn(B, H, S, D).astype("f4")),
            jnp.asarray(rs.randn(B, H, S, D).astype("f4")))


def _mask(rs, kind, B=1, H=2, S=256):
    if kind == "none":
        return None
    if kind == "key":
        keep = rs.rand(B, 1, 1, S) > 0.2
        return jnp.asarray(np.where(keep, 0.0, -1e9).astype("f4"))
    return jnp.asarray(rs.randn(B, H, S, S).astype("f4"))


# -- kernel vs jnp reference (interpret mode) -----------------------------


@needs_pallas
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("mask_kind", ["none", "key", "full"])
def test_forward_parity_vs_ref(causal, mask_kind):
    rs = np.random.RandomState(0)
    q, k, v = _qkv(rs)
    mask = _mask(rs, mask_kind)
    ref = fa.flash_attention_ref(q, k, v, mask, sm_scale=0.125,
                                 causal=causal)
    got = fa.flash_attention(q, k, v, mask, sm_scale=0.125,
                             causal=causal, use_pallas=True,
                             interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-6, rtol=1e-5)


@needs_pallas
@pytest.mark.parametrize("causal", [False, True])
def test_grad_parity_vs_ref(causal):
    """q/k/v cotangents through the tiled recompute backward match
    jax.vjp over the jnp reference (the custom_vjp's whole contract)."""
    rs = np.random.RandomState(1)
    q, k, v = _qkv(rs)
    mask = _mask(rs, "key")
    ct = jnp.asarray(rs.randn(*q.shape).astype("f4"))

    _, vjp_ref = jax.vjp(
        lambda q, k, v: fa.flash_attention_ref(
            q, k, v, mask, sm_scale=0.125, causal=causal), q, k, v)
    _, vjp_got = jax.vjp(
        lambda q, k, v: fa.flash_attention(
            q, k, v, mask, sm_scale=0.125, causal=causal,
            use_pallas=True, interpret=True), q, k, v)
    for name, r, g in zip("qkv", vjp_ref(ct), vjp_got(ct)):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), atol=1e-5, rtol=1e-3,
            err_msg=f"d{name} diverged from the reference vjp")


@needs_pallas
def test_mask_is_a_constant():
    """The fused op treats the additive mask as a constant: its
    cotangent is exactly zero (the pass refuses learnable masks for
    the same reason)."""
    rs = np.random.RandomState(2)
    q, k, v = _qkv(rs)
    mask = _mask(rs, "key")
    _, vjp = jax.vjp(
        lambda m: fa.flash_attention(q, k, v, m, sm_scale=0.125,
                                     use_pallas=True, interpret=True),
        mask)
    (dm,) = vjp(jnp.ones_like(q))
    assert float(jnp.abs(dm).max()) == 0.0


def test_unaligned_shapes_are_loud():
    rs = np.random.RandomState(3)
    q, k, v = _qkv(rs, S=96)  # not a multiple of the 128 block
    with pytest.raises(ValueError, match="multiples"):
        fa.flash_attention(q, k, v, use_pallas=True)
    with pytest.raises(ValueError, match="rank"):
        fa.flash_attention(q[0], k[0], v[0])


def test_cpu_default_is_the_reference():
    """use_pallas=None off-TPU must resolve to the jnp reference —
    tier-1 numerics never move when the kernels land."""
    rs = np.random.RandomState(4)
    q, k, v = _qkv(rs, S=128)
    mask = _mask(rs, "key", S=128)
    got = fa.flash_attention(q, k, v, mask, sm_scale=0.125)
    ref = fa.flash_attention_ref(q, k, v, mask, sm_scale=0.125)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


# -- the FlashAttentionPass graph rewrite ---------------------------------

S, HEADS, D = 16, 2, 8
HID = HEADS * D


def _attn_train_program(with_mask=True, dropout=0.0, learnable_mask=False,
                        seed=11):
    """A train program around the exact unfused chain static_models
    emits: qkv projections -> matmul(alpha) -> [mask add] -> softmax ->
    matmul -> out projection -> mse, SGD-with-momentum backward."""
    main, startup = Program(), Program()
    main.random_seed = seed
    with program_guard(main, startup):
        x = layers.data("x", [S, HID])
        y = layers.data("y", [S, HID])

        def proj(name, src=None):
            t = layers.fc(src if src is not None else x, HID,
                          num_flatten_dims=2, name=name,
                          param_attr=ParamAttr(
                              initializer=NormalInitializer(0.0, 0.05)))
            t = layers.reshape(t, [0, S, HEADS, D])
            return layers.transpose(t, [0, 2, 1, 3])

        q, k, v = proj("attn_q"), proj("attn_k"), proj("attn_v")
        scores = layers.matmul(q, k, transpose_y=True,
                               alpha=1.0 / math.sqrt(D))
        mask = None
        if learnable_mask:
            m = layers.fc(x, S, num_flatten_dims=2, name="attn_mask")
            mask = layers.reshape(m, [0, 1, S, S])
        elif with_mask:
            mask = layers.data("mask", [1, 1, S])
        if mask is not None:
            scores = layers.elementwise_add(scores, mask)
        probs = layers.softmax(scores)
        if dropout:
            probs = layers.dropout(probs, dropout)
        ctxv = layers.matmul(probs, v)
        ctxv = layers.transpose(ctxv, [0, 2, 1, 3])
        ctxv = layers.reshape(ctxv, [0, S, HID])
        out = layers.fc(ctxv, HID, num_flatten_dims=2, name="attn_out",
                        param_attr=ParamAttr(
                            initializer=NormalInitializer(0.0, 0.05)))
        loss = layers.mean(layers.square_error_cost(out, y))
        MomentumOptimizer(0.05, 0.9).minimize(loss)
    return main, startup, loss, probs.name


def _feed(with_mask=True, n=4):
    rs = np.random.RandomState(0)
    fd = {"x": rs.randn(n, S, HID).astype("f4"),
          "y": rs.randn(n, S, HID).astype("f4")}
    if with_mask:
        fd["mask"] = np.where(rs.rand(n, 1, 1, S) > 0.2,
                              0.0, -1e9).astype("f4")
    return fd


def _train(main, startup, loss, fd, steps=3, mesh=None):
    scope = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup, scope=scope)
    return [float(np.asarray(exe.run(main, feed=fd, fetch_list=[loss],
                                     scope=scope)[0]).item())
            for _ in range(steps)]


def _op_types(program):
    return [op.type for op in program.global_block.ops]


def _reset_pass_stats():
    stat_reset("pass_flash_attention_fused")
    stat_reset("pass_flash_attention_grad_fused")


@pytest.mark.parametrize("with_mask", [True, False])
def test_pass_rewrites_chain_and_grads(with_mask):
    main, _, loss, _ = _attn_train_program(with_mask=with_mask)
    pt.set_flags({"FLAGS_flash_attention": "always"})
    _reset_pass_stats()
    p = passes_mod.FlashAttentionPass()
    ctx = passes_mod.PassContext(fetch_names=(loss.name,))
    assert p.should_apply(main, ctx)
    assert p.apply(main, ctx)
    types = _op_types(main)
    assert types.count("flash_attention") == 1
    assert types.count("flash_attention_grad") == 1
    for gone in ("softmax", "softmax_grad", "matmul_grad"):
        assert gone not in types, f"{gone} survived the rewrite"
    # the qkv/out projection matmuls (via fc -> mul) must survive
    fop = next(op for op in main.global_block.ops
               if op.type == "flash_attention")
    assert ("Mask" in fop.inputs) == with_mask
    assert abs(float(fop.attr("scale")) - 1.0 / math.sqrt(D)) < 1e-12
    assert stat_get("pass_flash_attention_fused") == 1
    assert stat_get("pass_flash_attention_grad_fused") == 1


def test_flag_gating_and_lowering_rekey():
    """'never' and CPU-'auto' never rewrite (tier-1 numerics are
    untouched by default); the flag is affects_lowering so every flip
    re-keys the executor's pass + compile caches."""
    main, _, loss, _ = _attn_train_program()
    p = passes_mod.FlashAttentionPass()
    ctx = passes_mod.PassContext(fetch_names=(loss.name,))
    pt.set_flags({"FLAGS_flash_attention": "never"})
    key_never = flags_mod.lowering_key()
    assert not p.should_apply(main, ctx)
    pt.set_flags({"FLAGS_flash_attention": "auto"})
    assert jax.default_backend() != "tpu" and not p.should_apply(main, ctx)
    pt.set_flags({"FLAGS_flash_attention": "always"})
    assert p.should_apply(main, ctx)
    assert flags_mod.lowering_key() != key_never


def test_executor_always_matches_never_bitwise():
    """End-to-end oracle: the same attention net trained 4 steps under
    'never' (unfused chain) and 'always' (rewritten to the fused op,
    reference lowering on CPU) produces bitwise-identical losses —
    the rewrite changes memory shape, not math."""
    fd = _feed()
    pt.set_flags({"FLAGS_flash_attention": "never"})
    with unique_name.guard():
        ref = _train(*_attn_train_program()[:3], fd, steps=4)
    _reset_pass_stats()
    pt.set_flags({"FLAGS_flash_attention": "always"})
    with unique_name.guard():
        got = _train(*_attn_train_program()[:3], fd, steps=4)
    assert stat_get("pass_flash_attention_fused") >= 1
    assert stat_get("pass_flash_attention_grad_fused") >= 1
    np.testing.assert_array_equal(ref, got)


def test_pass_refuses_dropout_on_probs():
    """Dropout on the attention probs consumes the softmax output, so
    the chain must be left alone (the flash trade-off is no probs
    dropout — silently dropping it would change the model)."""
    main, _, loss, _ = _attn_train_program(dropout=0.3)
    pt.set_flags({"FLAGS_flash_attention": "always"})
    assert not passes_mod.FlashAttentionPass().apply(
        main, passes_mod.PassContext(fetch_names=(loss.name,)))
    assert "softmax" in _op_types(main)


def test_pass_refuses_fetched_intermediate():
    main, _, loss, probs_name = _attn_train_program()
    pt.set_flags({"FLAGS_flash_attention": "always"})
    assert not passes_mod.FlashAttentionPass().apply(
        main, passes_mod.PassContext(
            fetch_names=(loss.name, probs_name)))
    assert "softmax" in _op_types(main)


def test_pass_refuses_learnable_mask():
    """A mask that wants gradients can't ride the fused op (it treats
    the mask as a constant): the grad chain's Y@GRAD on the add is the
    refusal signal."""
    main, _, loss, _ = _attn_train_program(learnable_mask=True)
    pt.set_flags({"FLAGS_flash_attention": "always"})
    assert not passes_mod.FlashAttentionPass().apply(
        main, passes_mod.PassContext(fetch_names=(loss.name,)))
    assert "softmax" in _op_types(main)


# -- composition: tensor parallelism & layer scan (slow matrix) -----------

TP_RULES = [(r"attn_[qkv]\.w_\d+$", "None,mp"),
            (r"attn_[qkv]\.b_\d+$", "mp"),
            (r"attn_out\.w_\d+$", "mp,None")]


def _tp_program(seed=5):
    from paddle_tpu.distributed import fleet

    main, startup = Program(), Program()
    main.random_seed = seed
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [S, HID])
        y = layers.data("y", [S, HID])
        mask = layers.data("mask", [1, 1, S])

        def proj(name):
            t = layers.fc(x, HID, num_flatten_dims=2, name=name,
                          param_attr=ParamAttr(
                              initializer=NormalInitializer(0.0, 0.05)))
            t = layers.reshape(t, [0, S, HEADS, D])
            return layers.transpose(t, [0, 2, 1, 3])

        q, k, v = proj("attn_q"), proj("attn_k"), proj("attn_v")
        scores = layers.matmul(q, k, transpose_y=True,
                               alpha=1.0 / math.sqrt(D))
        scores = layers.elementwise_add(scores, mask)
        probs = layers.softmax(scores)
        ctxv = layers.matmul(probs, v)
        ctxv = layers.transpose(ctxv, [0, 2, 1, 3])
        ctxv = layers.reshape(ctxv, [0, S, HID])
        out = layers.fc(ctxv, HID, num_flatten_dims=2, name="attn_out",
                        param_attr=ParamAttr(
                            initializer=NormalInitializer(0.0, 0.05)))
        loss = layers.mean(layers.square_error_cost(out, y))
        opt = MomentumOptimizer(0.05, 0.9)
        st = fleet.DistributedStrategy()
        st.tensor_parallel = True
        st.tensor_parallel_configs = {"partition_rules": TP_RULES}
        fleet.init(is_collective=True, strategy=st)
        fleet.distributed_optimizer(opt)
        fleet.minimize(loss)
    return main, startup, loss


@pytest.mark.slow
def test_tp_composition_heads_sharded(mesh_dp_mp):
    """Megatron column-parallel qkv shards the fused op's heads dim:
    under the 2x4 dp×mp mesh with FLAGS_flash_attention=always the
    rewrite fires, the mp-flow walk accepts the fused op, and losses
    match the tp run of the UNFUSED chain bitwise (same mesh, same
    math) — which itself sits on the single-chip oracle."""
    fd = _feed()
    pt.set_flags({"FLAGS_flash_attention": "never"})
    plain = _train(*_tp_program(), fd, steps=4, mesh=mesh_dp_mp)
    _reset_pass_stats()
    pt.set_flags({"FLAGS_flash_attention": "always"})
    fused = _train(*_tp_program(), fd, steps=4, mesh=mesh_dp_mp)
    assert stat_get("pass_flash_attention_fused") >= 1
    np.testing.assert_array_equal(plain, fused)


@pytest.mark.slow
def test_layer_scan_composition():
    """FlashAttentionPass runs before LayerScanPass, so the scanned
    layer body already holds the fused op: a 3-deep attention stack
    scanned+fused must match the unscanned unfused oracle bitwise."""
    depth = 3

    def build():
        main, startup = Program(), Program()
        main.random_seed = 13
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [S, HID])
            y = layers.data("y", [S, HID])
            mask = layers.data("mask", [1, 1, S])
            h = x
            for i in range(depth):
                def proj(name, src):
                    t = layers.fc(src, HID, num_flatten_dims=2,
                                  name=name, param_attr=ParamAttr(
                                      initializer=NormalInitializer(
                                          0.0, 0.05)))
                    t = layers.reshape(t, [0, S, HEADS, D])
                    return layers.transpose(t, [0, 2, 1, 3])

                q = proj(f"blk{i}_q", h)
                k = proj(f"blk{i}_k", h)
                v = proj(f"blk{i}_v", h)
                scores = layers.matmul(q, k, transpose_y=True,
                                       alpha=1.0 / math.sqrt(D))
                scores = layers.elementwise_add(scores, mask)
                probs = layers.softmax(scores)
                ctxv = layers.matmul(probs, v)
                ctxv = layers.transpose(ctxv, [0, 2, 1, 3])
                ctxv = layers.reshape(ctxv, [0, S, HID])
                h = layers.fc(ctxv, HID, num_flatten_dims=2,
                              name=f"blk{i}_out", param_attr=ParamAttr(
                                  initializer=NormalInitializer(
                                      0.0, 0.05)))
            loss = layers.mean(layers.square_error_cost(h, y))
            MomentumOptimizer(0.05, 0.9).minimize(loss)
        return main, startup, loss

    fd = _feed()
    pt.set_flags({"FLAGS_flash_attention": "never",
                  "FLAGS_layer_scan": False})
    ref = _train(*build(), fd, steps=4)

    _reset_pass_stats()
    stat_reset("pass_layer_scan_segments")
    pt.set_flags({"FLAGS_flash_attention": "always",
                  "FLAGS_layer_scan": True,
                  "FLAGS_layer_scan_min_layers": 2})
    try:
        got = _train(*build(), fd, steps=4)
    finally:
        pt.set_flags({"FLAGS_layer_scan": False,
                      "FLAGS_layer_scan_min_layers": 4})
    assert stat_get("pass_flash_attention_fused") >= depth
    np.testing.assert_array_equal(ref, got)
