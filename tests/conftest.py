"""Test configuration: CPU simulation with 8 virtual devices.

Mirrors the reference's localhost-cluster test pattern (SURVEY.md §4): all
tests run on the jax CPU backend with 8 virtual devices so multi-chip
sharding is exercised without TPU hardware.  Must run before jax imports.
"""
import os
import sys

# make the repo importable regardless of pytest's invocation cwd
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# the container's sitecustomize imports jax (registering the axon TPU
# backend) before this file runs, so env vars alone are too late — force
# the platform through the live config as well.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", jax.devices()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _test_watchdog():
    """Per-test hang watchdog: a blocked queue/lock must surface as a
    test FAILURE, not an unbounded suite stall (round-4 postmortem —
    the suite deadlocked at test 50/337 and the snapshot shipped
    unverified).  SIGALRM interrupts lock waits on the main thread, so
    even a bare queue.get() is caught."""
    import signal

    if not hasattr(signal, "SIGALRM"):
        yield
        return

    def _on_alarm(signum, frame):
        raise TimeoutError(
            "test exceeded the 300s hang watchdog (tests/conftest.py)")

    old = signal.signal(signal.SIGALRM, _on_alarm)
    signal.alarm(300)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Each test gets fresh default programs + scope (reference tests use
    new Programs per test via program_guard)."""
    import paddle_tpu as pt
    from paddle_tpu.framework import program as prog_mod
    from paddle_tpu.framework import scope as scope_mod
    from paddle_tpu.framework import unique_name

    old_main = prog_mod._main_program
    old_startup = prog_mod._startup_program
    old_scope = scope_mod._global_scope
    prog_mod._main_program = prog_mod.Program()
    prog_mod._startup_program = prog_mod.Program()
    scope_mod._global_scope = scope_mod.Scope()
    with unique_name.guard():
        yield
    prog_mod._main_program = old_main
    prog_mod._startup_program = old_startup
    scope_mod._global_scope = old_scope
    # fleet.init installs a global mesh; leaking it into the next test
    # makes plain Executors run SPMD on non-transpiled programs
    from paddle_tpu.distributed.parallel_env import reset_mesh

    reset_mesh()


@pytest.fixture
def rng():
    return np.random.RandomState(1234)


# ---------------------------------------------------------------------------
# Shared jax capability probes (dedupe of the per-file version guards:
# config entries, AOT stages, and compiled-executable introspection all
# come and go across jax versions).  Module-level skips import this
# directly — `from conftest import jax_capability` — which works under
# pytest's default rootdir import mode (same mechanism as op_test).
# ---------------------------------------------------------------------------

_CAPABILITY_CACHE = {}


def _probe_compiled():
    """One tiny AOT lower+compile, cached: the probe object every
    compiled-introspection capability reads."""
    if "_compiled" not in _CAPABILITY_CACHE:
        try:
            compiled = jax.jit(lambda x: x + 1).lower(
                np.ones((2,), "float32")).compile()
        except Exception:  # noqa: BLE001 - no AOT stages on this jax
            compiled = None
        _CAPABILITY_CACHE["_compiled"] = compiled
    return _CAPABILITY_CACHE["_compiled"]


def jax_capability(name: str) -> bool:
    """Does the installed jax support <name>?  Probes:

    - ``cpu_collectives``: cross-process CPU collectives config
      (``jax_cpu_collectives_implementation``) — the localhost fleet
      federation tests need it.
    - ``aot_stages``: ``jit(f).lower(...).compile()`` works.
    - ``memory_analysis`` / ``cost_analysis``: AOT-compiled executables
      expose per-module memory/cost introspection
      (observe/xla_stats.py capability-skips without them).
    - ``pallas_interpret``: ``pl.pallas_call(..., interpret=True)`` runs
      on the CPU backend (the Pallas kernel equivalence tests need it).
    """
    if name not in _CAPABILITY_CACHE:
        from paddle_tpu.framework import jax_compat

        if name == "cpu_collectives":
            ok = jax_compat.has_config("jax_cpu_collectives_implementation")
        elif name == "aot_stages":
            ok = _probe_compiled() is not None
        elif name == "memory_analysis":
            c = _probe_compiled()
            ok = c is not None and \
                jax_compat.compiled_memory_stats(c) is not None
        elif name == "cost_analysis":
            c = _probe_compiled()
            ok = c is not None and \
                jax_compat.compiled_cost_analysis(c) is not None
        elif name == "pallas_interpret":
            try:
                import jax.experimental.pallas as pl

                out = pl.pallas_call(
                    lambda x_ref, o_ref: o_ref.__setitem__(
                        ..., x_ref[...] + 1.0),
                    out_shape=jax.ShapeDtypeStruct((8, 128), np.float32),
                    interpret=True,
                )(np.zeros((8, 128), np.float32))
                ok = float(np.asarray(out)[0, 0]) == 1.0
            except Exception:  # noqa: BLE001 - no usable Pallas here
                ok = False
        else:
            raise KeyError(f"unknown jax capability probe {name!r}")
        _CAPABILITY_CACHE[name] = ok
    return _CAPABILITY_CACHE[name]


@pytest.fixture
def require_memory_analysis():
    """Skip (don't fail) on jax builds whose AOT compiled objects lack
    ``memory_analysis()`` — the HBM-accounting capability."""
    if not jax_capability("memory_analysis"):
        pytest.skip("installed jax exposes no compiled.memory_analysis()")


# ---------------------------------------------------------------------------
# Shared mesh fixtures (the XLA_FLAGS 8-virtual-device setup above is THE
# one copy; test files must not re-set it, and mesh construction for tp/dp
# tests lives here instead of per-file duplicates).
# ---------------------------------------------------------------------------


@pytest.fixture
def mesh8():
    """8-device 1D data-parallel mesh installed as the global parallel
    env (what fleet.init would build); torn down after the test."""
    from paddle_tpu.distributed.parallel_env import (init_parallel_env,
                                                     reset_mesh)

    reset_mesh()
    mesh = init_parallel_env()
    yield mesh
    reset_mesh()


@pytest.fixture
def mesh_dp_mp():
    """2×4 ('dp','mp') mesh for tensor-parallel tests, installed as the
    global parallel env; torn down after the test."""
    from paddle_tpu.distributed.parallel_env import (init_parallel_env,
                                                     reset_mesh)

    reset_mesh()
    mesh = init_parallel_env(mesh_shape=[2, 4], axis_names=("dp", "mp"))
    yield mesh
    reset_mesh()


@pytest.fixture
def mesh_mp_only():
    """1×8 ('dp','mp') mesh — pure tensor parallelism (dp degree 1)."""
    from paddle_tpu.distributed.parallel_env import (init_parallel_env,
                                                     reset_mesh)

    reset_mesh()
    mesh = init_parallel_env(mesh_shape=[1, 8], axis_names=("dp", "mp"))
    yield mesh
    reset_mesh()
