"""Flight recorder, stall watchdog, postmortem bundles, cluster health.

The device-failure diagnosability plane (paddle_tpu/observe/flight.py +
health.py): bounded structured event ring with run metadata and
lifecycle events, a watchdog that converts a hung device call into a
readable postmortem bundle, per-rank heartbeats over the real fleet KV
HTTP server with rank-0 aggregation (straggler skew, liveness), and the
``python -m tools.postmortem`` bundle reader.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, observe
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.monitor import stat_get
from paddle_tpu.observe import flight, health

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_plane():
    """Each test starts with an empty flight ring, no watchdog, no
    crash hook, and the default flags."""
    flight.clear_events()
    yield
    health.stop_watchdog()
    health.uninstall_crash_handler()
    pt.set_flags({"FLAGS_flight_recorder": True,
                  "FLAGS_flight_recorder_file": "",
                  "FLAGS_flight_recorder_max_mb": 0.0,
                  "FLAGS_stall_timeout_s": 0.0,
                  "FLAGS_device_peak_tflops": 275.0})
    flight.clear_events()


def _tiny_step(exe=None, scope=None):
    """One fc program + a ready (exe, scope, run) triple."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.fc(x, 2, bias_attr=False)
    exe = exe or pt.Executor(pt.CPUPlace())
    scope = scope or pt.framework.Scope()
    exe.run(startup, scope=scope)

    def run():
        return exe.run(main, feed={"x": np.ones((3, 4), "f4")},
                       fetch_list=[y], scope=scope)

    return exe, scope, run


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_record_order_seq_and_fields(self):
        flight.record("test/a", k=1)
        flight.record("test/b", s="x", arr=(1, 2))
        evs = flight.snapshot_events()
        assert [e["event"] for e in evs] == ["test/a", "test/b"]
        assert evs[0]["k"] == 1 and evs[1]["arr"] == [1, 2]
        assert evs[1]["seq"] == evs[0]["seq"] + 1
        assert evs[0]["ts"] <= evs[1]["ts"]

    def test_flag_gates_recording(self):
        pt.set_flags({"FLAGS_flight_recorder": False})
        assert flight.record("test/off") is None
        assert flight.snapshot_events() == []
        pt.set_flags({"FLAGS_flight_recorder": True})
        assert flight.record("test/on") is not None

    def test_ring_is_bounded(self):
        r = flight.FlightRecorder(capacity=8)
        for i in range(20):
            r.record("e", i=i)
        evs = r.snapshot()
        assert len(evs) == 8
        assert [e["i"] for e in evs] == list(range(12, 20))
        assert r.dropped == 12

    def test_unserializable_field_degrades_to_repr(self):
        flight.record("test/obj", obj=object())
        ev = flight.snapshot_events()[-1]
        assert "object object at" in ev["obj"]
        json.dumps(ev)  # the ring only ever holds JSON-able events

    def test_file_sink_appends_flushed_jsonl(self, tmp_path):
        p = str(tmp_path / "fr" / "events.jsonl")
        pt.set_flags({"FLAGS_flight_recorder_file": p})
        flight.record("test/sink", n=1)
        flight.record("test/sink", n=2)
        # flushed per event: readable NOW, without any shutdown hook
        lines = [json.loads(l) for l in open(p).read().splitlines()]
        assert [e["n"] for e in lines] == [1, 2]
        pt.set_flags({"FLAGS_flight_recorder_file": ""})
        flight.record("test/sink", n=3)
        assert len(open(p).read().splitlines()) == 2  # sink detached

    def test_file_sink_rotates_at_size_cap_and_tail_survives(
            self, tmp_path):
        """FLAGS_flight_recorder_max_mb: the active segment rotates to
        <path>.1 at the cap and a reader concatenating .1 + active —
        the post-SIGKILL recovery path, no shutdown hook involved —
        sees an unbroken, parseable event history spanning the
        rotation."""
        p = str(tmp_path / "fr" / "events.jsonl")
        before = stat_get("flight_sink_rotations")
        pt.set_flags({"FLAGS_flight_recorder_file": p,
                      "FLAGS_flight_recorder_max_mb": 0.002})  # ~2 KB
        pad = "x" * 64
        for i in range(200):  # ~130 bytes/line >> 2 KB: many rotations
            flight.record("test/rot", i=i, pad=pad)
        # no close/flush call: every line was already flushed at write
        assert os.path.isfile(p) and os.path.isfile(p + ".1")
        assert os.path.getsize(p + ".1") >= 2 * 1024
        assert stat_get("flight_sink_rotations") > before
        events = []
        for seg in (p + ".1", p):  # rotated first, then active
            for line in open(seg).read().splitlines():
                events.append(json.loads(line))  # every line parses
        idx = [e["i"] for e in events if e["event"] == "test/rot"]
        # contiguous tail ending at the last event: rotation dropped
        # only history OLDER than the kept two segments
        assert idx == list(range(idx[0], 200))
        assert len(idx) >= 20  # spans at least one rotation boundary

    def test_run_metadata_once_and_content(self):
        ev = flight.record_run_metadata()
        assert ev is not None
        assert ev["event"] == "run/metadata"
        assert ev["jax_version"]
        assert ev["pid"] == os.getpid()
        assert "flags" in ev and "max_inflight_steps" in ev["flags"]
        assert flight.record_run_metadata() is None  # once per process
        assert flight.record_run_metadata(force=True) is not None

    def test_executor_feeds_lifecycle_events(self):
        _, _, run = _tiny_step()
        run().numpy()
        run().numpy()
        names = [e["event"] for e in flight.snapshot_events()]
        assert "run/metadata" in names
        assert "executor/created" in names
        assert "run/devices" in names
        assert "executor/compile" in names
        assert names.count("executor/dispatch") >= 3  # startup + 2 steps
        dev = next(e for e in flight.snapshot_events()
                   if e["event"] == "run/devices")
        assert dev["platform"] == "cpu" and dev["device_count"] == 8

    def test_record_overhead_is_microseconds(self):
        n = 2000
        t0 = time.perf_counter()
        for i in range(n):
            flight.record("test/overhead", i=i)
        per = (time.perf_counter() - t0) / n
        # acceptance: < 2% of a multi-ms step; one event is ~µs, bound
        # generously for loaded CI
        assert per < 100e-6, f"{per * 1e6:.1f}µs per event"

    def test_dump_writes_jsonl(self, tmp_path):
        flight.record("test/d", x=1)
        p = flight.dump(str(tmp_path / "tail.jsonl"))
        rows = [json.loads(l) for l in open(p).read().splitlines()]
        assert rows[-1]["event"] == "test/d"


# ---------------------------------------------------------------------------
# ckpt lifecycle events
# ---------------------------------------------------------------------------


class TestCkptFlightEvents:
    def test_save_commit_restore_events(self, tmp_path):
        from paddle_tpu.ckpt import CheckpointManager

        m = CheckpointManager(str(tmp_path), async_save=False)
        m.save(3, state={"w": np.ones((4,), "f4")})
        m.restore()
        m.close()
        names = [e["event"] for e in flight.snapshot_events()]
        assert "ckpt/save" in names
        assert "ckpt/commit" in names
        assert "ckpt/restore" in names
        commit = next(e for e in flight.snapshot_events()
                      if e["event"] == "ckpt/commit")
        assert commit["step"] == 3 and commit["bytes"] == 16


# ---------------------------------------------------------------------------
# postmortem bundles
# ---------------------------------------------------------------------------

BUNDLE_FILES = ("meta.json", "stacks.txt", "trace.json", "metrics.prom",
                "flight.jsonl", "flags.json", "requests.json")


class TestPostmortem:
    def test_bundle_is_complete(self, tmp_path):
        observe.enable()
        try:
            with observe.span("test/pm"):
                pass
        finally:
            observe.disable()
        flight.record("test/before_dump", k=1)
        b = health.dump_postmortem("unit", directory=str(tmp_path),
                                   extra={"why": "test"})
        for f in BUNDLE_FILES:
            assert os.path.isfile(os.path.join(b, f)), f
        meta = json.load(open(os.path.join(b, "meta.json")))
        assert meta["reason"] == "unit"
        assert meta["pid"] == os.getpid()
        assert meta["extra"] == {"why": "test"}
        assert "dispatched" in meta["progress"]
        assert meta["section_errors"] == {}
        stacks = open(os.path.join(b, "stacks.txt")).read()
        assert "MainThread" in stacks and "test_bundle_is_complete" in stacks
        trace = json.load(open(os.path.join(b, "trace.json")))
        assert any(e.get("name") == "test/pm"
                   for e in trace["traceEvents"])
        prom = open(os.path.join(b, "metrics.prom")).read()
        assert "paddle_tpu_" in prom
        fl = [json.loads(l) for l in
              open(os.path.join(b, "flight.jsonl")).read().splitlines()]
        assert any(e["event"] == "test/before_dump" for e in fl)
        flags = json.load(open(os.path.join(b, "flags.json")))
        assert "stall_timeout_s" in flags
        # the dump itself is a flight event + a counter
        assert any(e["event"] == "postmortem/dump"
                   for e in flight.snapshot_events())

    def test_two_dumps_same_second_get_distinct_dirs(self, tmp_path):
        b1 = health.dump_postmortem("dup", directory=str(tmp_path))
        b2 = health.dump_postmortem("dup", directory=str(tmp_path))
        assert b1 != b2 and os.path.isdir(b1) and os.path.isdir(b2)

    def test_crash_handler_dumps_and_chains(self, tmp_path):
        seen = []
        prev = sys.excepthook
        sys.excepthook = lambda *a: seen.append(a)
        try:
            health.install_crash_handler(directory=str(tmp_path))
            try:
                raise ValueError("boom-for-bundle")
            except ValueError:
                sys.excepthook(*sys.exc_info())
        finally:
            health.uninstall_crash_handler()
            sys.excepthook = prev
        assert len(seen) == 1  # chained to the previous hook
        bundles = [d for d in os.listdir(tmp_path)
                   if d.startswith("bundle_")]
        assert len(bundles) == 1
        meta = json.load(open(tmp_path / bundles[0] / "meta.json"))
        assert meta["reason"] == "crash"
        assert meta["exception"]["type"] == "ValueError"
        assert "boom-for-bundle" in meta["exception"]["value"]
        # faulthandler armed for fatal signals in the same dir
        assert any(d.startswith("fatal_") for d in os.listdir(tmp_path))


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------


class _HungDeviceCall:
    """A mocked never-completing device call: jax.block_until_ready
    duck-calls .block_until_ready(), which parks on an Event."""

    def __init__(self, release: threading.Event):
        self._release = release

    def block_until_ready(self):
        self._release.wait(timeout=60)
        return self


class TestStallWatchdog:
    def test_hung_step_trips_within_timeout_and_bundle_is_complete(
            self, tmp_path):
        """Chaos test: a deliberately hung step (mocked never-completing
        device call) must trip the watchdog within the stall timeout and
        leave a complete postmortem bundle."""
        from paddle_tpu.framework.executor import _InflightStep

        exe, _, run = _tiny_step()
        run().numpy()  # healthy baseline step
        exe.drain()
        base_drained = stat_get("executor_steps_drained")

        release = threading.Event()
        entry = _InflightStep(
            sync_refs=(_HungDeviceCall(release),), nan_flags=None,
            nan_ops=(), t_dispatch=time.perf_counter(), steps=1,
            examples=0, compiled=False, flops_per_step=0.0,
            allreduce_bytes=0)
        exe._window.push(entry)
        drainer = threading.Thread(target=exe.drain,
                                   name="hung-train-loop", daemon=True)
        drainer.start()

        timeout = 0.6
        wd = health.StallWatchdog(timeout_s=timeout, poll_s=0.1,
                                  directory=str(tmp_path))
        t0 = time.perf_counter()
        wd.start()
        try:
            deadline = time.time() + 15
            while not wd.bundles and time.time() < deadline:
                time.sleep(0.05)
            tripped_after = time.perf_counter() - t0
            assert wd.bundles, "watchdog never tripped on the hung step"
            # fires once the no-progress window exceeds the timeout —
            # within timeout + a few polls of slack, not minutes later
            assert tripped_after < timeout + 2.0
            b = wd.bundles[0]
            for f in BUNDLE_FILES:
                assert os.path.isfile(os.path.join(b, f)), f
            meta = json.load(open(os.path.join(b, "meta.json")))
            assert meta["reason"] == "stall"
            assert meta["progress"]["inflight"] >= 1
            assert meta["progress"]["drained"] == base_drained
            # the hung thread is IN the stack dump, named, inside the
            # mocked device call
            stacks = open(os.path.join(b, "stacks.txt")).read()
            assert "hung-train-loop" in stacks
            assert "block_until_ready" in stacks
            # latched: a continuing stall produces no second bundle
            time.sleep(3 * wd.poll_s + timeout)
            assert len(wd.bundles) == 1
            assert stat_get("watchdog_stalls") >= 1
            assert any(e["event"] == "health/stall"
                       for e in flight.snapshot_events())
        finally:
            release.set()
            drainer.join(timeout=10)
            wd.stop()
        assert not drainer.is_alive()
        assert stat_get("executor_steps_drained") == base_drained + 1

    def test_no_trip_while_progressing_or_idle(self, tmp_path):
        state = {"drained": 0}

        def progress():
            state["drained"] += 1  # every poll sees fresh progress
            return {"dispatched": state["drained"] + 1,
                    "drained": state["drained"], "inflight": 1,
                    "oldest_inflight_age_s": 0.01}

        wd = health.StallWatchdog(timeout_s=0.2, poll_s=0.05,
                                  directory=str(tmp_path),
                                  progress_fn=progress)
        wd.start()
        time.sleep(0.6)
        wd.stop()
        assert wd.bundles == []
        # idle (nothing pending) never trips either
        wd2 = health.StallWatchdog(
            timeout_s=0.2, poll_s=0.05, directory=str(tmp_path),
            progress_fn=lambda: {"dispatched": 5, "drained": 5,
                                 "inflight": 0,
                                 "oldest_inflight_age_s": None})
        wd2.start()
        time.sleep(0.6)
        wd2.stop()
        assert wd2.bundles == []

    def test_rearms_after_progress_resumes(self, tmp_path):
        state = {"drained": 0, "stuck": True}

        def progress():
            if not state["stuck"]:
                state["drained"] += 1
            return {"dispatched": state["drained"] + 1,
                    "drained": state["drained"], "inflight": 1,
                    "oldest_inflight_age_s": None}

        wd = health.StallWatchdog(timeout_s=0.2, poll_s=0.05,
                                  directory=str(tmp_path),
                                  progress_fn=progress)
        wd.start()
        try:
            deadline = time.time() + 10
            while len(wd.bundles) < 1 and time.time() < deadline:
                time.sleep(0.05)
            assert len(wd.bundles) == 1
            state["stuck"] = False  # progress resumes -> re-arm
            time.sleep(0.3)
            state["stuck"] = True   # second stall
            deadline = time.time() + 10
            while len(wd.bundles) < 2 and time.time() < deadline:
                time.sleep(0.05)
            assert len(wd.bundles) == 2
        finally:
            wd.stop()

    def test_ready_but_unread_entry_is_idle_not_a_stall(self, tmp_path):
        """A dispatched step whose fetch buffers are device-complete
        but unread (interactive pause, slow consumer) must read as an
        idle host, not a hung device."""
        _, _, run = _tiny_step()
        run().numpy()
        h = run()  # dispatched, never read: entry stays in the window
        deadline = time.time() + 10
        while (health.executor_progress()["oldest_ready"] is not True
               and time.time() < deadline):
            time.sleep(0.02)
        p = health.executor_progress()
        assert p["inflight"] >= 1 and p["oldest_ready"] is True
        wd = health.StallWatchdog(timeout_s=0.2, poll_s=0.05,
                                  directory=str(tmp_path))
        wd.start()
        time.sleep(0.7)
        wd.stop()
        assert wd.bundles == []
        h.numpy()  # now read it; the window drains

    def test_compile_grace_scales_the_timeout(self, tmp_path):
        """Pending work + frozen counters during an in-flight compile
        only trips once compile_grace * timeout is exceeded — a long
        XLA compile is not a stall, a compile hung far past it is."""

        def progress():
            return {"dispatched": 1, "drained": 0, "inflight": 1,
                    "oldest_inflight_age_s": 99.0, "oldest_ready": None,
                    "compiling": True, "compile_age_s": 99.0}

        wd = health.StallWatchdog(timeout_s=0.2, poll_s=0.05,
                                  compile_grace=1000.0,
                                  directory=str(tmp_path),
                                  progress_fn=progress)
        wd.start()
        time.sleep(0.7)  # far past timeout_s, far under the grace
        wd.stop()
        assert wd.bundles == []
        wd2 = health.StallWatchdog(timeout_s=0.2, poll_s=0.05,
                                   compile_grace=2.0,
                                   directory=str(tmp_path),
                                   progress_fn=progress)
        wd2.start()
        deadline = time.time() + 10
        while not wd2.bundles and time.time() < deadline:
            time.sleep(0.05)
        wd2.stop()
        assert len(wd2.bundles) == 1  # hung compile IS the failure

    def test_executor_marks_active_compile(self):
        from paddle_tpu.framework.executor import _ACTIVE_COMPILES

        seen = {}
        orig = health.executor_progress

        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", [6])
            y = layers.fc(x, 3, bias_attr=False)
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.framework.Scope()
        exe.run(startup, scope=scope)
        # sample the marker from a sibling thread while the first call
        # (trace+compile) runs on the main thread
        stop = threading.Event()

        def sampler():
            while not stop.is_set():
                if _ACTIVE_COMPILES:
                    seen["during"] = orig()
                time.sleep(0.001)

        t = threading.Thread(target=sampler, daemon=True)
        t.start()
        exe.run(main, feed={"x": np.ones((2, 6), "f4")},
                fetch_list=[y], scope=scope).numpy()
        stop.set()
        t.join()
        exe.drain()
        assert seen, "sampler never saw the active-compile marker"
        assert seen["during"]["compiling"] is True
        assert seen["during"]["compile_age_s"] >= 0.0
        assert health.executor_progress()["compiling"] is False

    def test_idle_executor_cannot_mask_another_executors_hang(self):
        """oldest_ready is judged PER WINDOW: a second executor with a
        device-complete-but-unread entry must not hide a hung entry in
        the first one."""
        from paddle_tpu.framework.executor import _InflightStep

        _, _, run_a = _tiny_step()
        run_a().numpy()
        h = run_a()  # executor A: ready-but-unread entry in the window
        deadline = time.time() + 10
        while (health.executor_progress()["oldest_ready"] is not True
               and time.time() < deadline):
            time.sleep(0.02)
        assert health.executor_progress()["oldest_ready"] is True

        exe_b = pt.Executor(pt.CPUPlace())  # executor B: hung entry
        release = threading.Event()
        exe_b._window.push(_InflightStep(
            (_HungDeviceCall(release),), None, (), time.perf_counter(),
            1, 0, False, 0.0, 0))
        try:
            p = health.executor_progress()
            assert p["inflight"] >= 2
            assert p["oldest_ready"] is False  # B's hang wins
        finally:
            release.set()
            exe_b._window._entries.clear()
            h.numpy()

    def test_flag_gates_auto_start(self):
        assert health.maybe_start_watchdog() is None  # 0.0 = disabled
        pt.set_flags({"FLAGS_stall_timeout_s": 30.0})
        try:
            wd = health.maybe_start_watchdog()
            assert wd is not None and wd.running
            assert wd.timeout_s == 30.0
            # Executor construction is the auto-start hook
            assert health.get_watchdog() is wd
            assert health.start_watchdog() is wd  # singleton
        finally:
            health.stop_watchdog()
            pt.set_flags({"FLAGS_stall_timeout_s": 0.0})

    def test_requires_positive_timeout(self):
        with pytest.raises(ValueError):
            health.StallWatchdog(timeout_s=0.0)


# ---------------------------------------------------------------------------
# cluster health over the real fleet KV HTTP server
# ---------------------------------------------------------------------------


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read().decode())


class TestClusterHealth:
    def test_two_rank_heartbeats_and_straggler_skew_over_real_http(self):
        """Acceptance: a 2-rank run over the real KV HTTP server shows
        per-rank heartbeats and a nonzero straggler-skew gauge on
        /metrics/cluster when one rank is artificially slowed."""
        from paddle_tpu.distributed.fleet.utils.http_server import KVServer

        srv = KVServer(0)
        srv.start()
        try:
            health.serve_cluster_health(srv, world_size=2)
            ep = f"127.0.0.1:{srv.port}"
            # rank 1 is artificially 3x slower than rank 0
            r0 = health.HealthReporter(
                ep, rank=0, world_size=2, interval_s=5.0,
                stats_fn=lambda: {"step_time_p50_s": 0.1,
                                  "drained": 10, "dispatched": 10})
            r1 = health.HealthReporter(
                ep, rank=1, world_size=2, interval_s=5.0,
                stats_fn=lambda: {"step_time_p50_s": 0.3,
                                  "drained": 7, "dispatched": 8})
            assert r0.publish_once() and r1.publish_once()

            doc = _get_json(f"http://{ep}/metrics/cluster")
            assert doc["world_size"] == 2
            assert doc["alive_ranks"] == 2 and doc["dead_ranks"] == []
            assert set(doc["ranks"]) == {"0", "1"}
            for r in ("0", "1"):
                assert doc["ranks"][r]["last_heartbeat_age_s"] < 5.0
                assert doc["ranks"][r]["alive"] is True
            assert doc["ranks"]["1"]["step_time_p50_s"] == 0.3
            # straggler gauge: (0.3 - 0.1) / 0.1 = 2.0
            assert doc["step_time_skew"] == pytest.approx(2.0)
            assert doc["straggler_rank"] == 1

            # the liveness/skew gauges are mirrored onto plain /metrics
            with urllib.request.urlopen(
                    f"http://{ep}/metrics", timeout=10) as resp:
                prom = resp.read().decode()
            assert "paddle_tpu_cluster_ranks_alive 2" in prom
            assert "paddle_tpu_cluster_step_time_skew_ppm 2000000" in prom
        finally:
            srv.stop()

    def test_dead_rank_detection(self):
        now = time.time()
        kv = {
            "health/rank/0": json.dumps(
                {"rank": 0, "ts": now, "interval_s": 1.0}).encode(),
            "health/rank/1": json.dumps(
                {"rank": 1, "ts": now - 100.0,
                 "interval_s": 1.0}).encode(),
            "unrelated/key": b"junk",
            "health/rank/bogus": b"not json",
        }
        doc = health.cluster_health(kv, world_size=3, now=now)
        assert doc["alive_ranks"] == 1
        assert doc["dead_ranks"] == [1, 2]  # stale beat + never beat
        assert doc["ranks"]["1"]["alive"] is False
        assert doc["ranks"]["1"]["last_heartbeat_age_s"] == \
            pytest.approx(100.0, abs=1.0)
        assert doc["step_time_skew"] == 0.0  # <2 timed ranks: no skew

    def test_reporter_thread_beats_periodically_with_default_stats(self):
        from paddle_tpu.distributed.fleet.utils.http_server import KVServer

        srv = KVServer(0)
        srv.start()
        try:
            r = health.HealthReporter(f"127.0.0.1:{srv.port}", rank=0,
                                      interval_s=0.1)
            r.start()
            time.sleep(0.45)
            r.stop()
            assert r.beats >= 2  # immediate first beat + periodic
            snap = srv.kv_snapshot(health.HEALTH_KEY_PREFIX)
            payload = json.loads(snap["health/rank/0"].decode())
            assert payload["pid"] == os.getpid()
            # default stats: executor progress counters ride along
            assert "dispatched" in payload and "drained" in payload
        finally:
            srv.stop()

    def test_reporter_survives_unreachable_server(self):
        r = health.HealthReporter("127.0.0.1:9", rank=0, interval_s=5.0,
                                  timeout_s=0.5)
        assert r.publish_once() is False
        assert r.failures == 1
        assert stat_get("health_heartbeat_failures") >= 1

    def test_restarted_rank_rejoins_alive_with_bumped_epoch(self):
        """ISSUE 14 satellite: a dead-listed rank that RESUMES
        heartbeating re-enters alive_ranks and clears from dead_ranks,
        and its restart (new pid, dispatched counter reset) bumps a
        MONOTONIC rank-epoch — so the supervisor can tell a restarted
        rank from a straggler whose counters 'went backwards'."""
        book = {}
        now = time.time()

        def hb(rank, ts, pid, disp, p50):
            return json.dumps(
                {"rank": rank, "ts": ts, "interval_s": 1.0, "pid": pid,
                 "dispatched": disp, "drained": disp,
                 "step_time_p50_s": p50}).encode()

        # scrape 1: rank 1's beat is stale -> dead-listed
        kv = {"health/rank/0": hb(0, now, 100, 50, 0.1),
              "health/rank/1": hb(1, now - 100.0, 200, 40, 0.1)}
        d1 = health.cluster_health(kv, world_size=2, now=now, book=book)
        assert d1["dead_ranks"] == [1]
        assert d1["rank_epochs"] == {"0": 0, "1": 0}

        # scrape 2: rank 1 restarted — fresh pid, counters reset, live
        # beat.  It must REJOIN alive, leave dead_ranks, and bump its
        # epoch; its reset step-time must NOT enter the skew gauge.
        kv = {"health/rank/0": hb(0, now + 1, 100, 60, 0.1),
              "health/rank/1": hb(1, now + 1, 201, 2, 9.9)}
        d2 = health.cluster_health(kv, world_size=2, now=now + 1,
                                   book=book)
        assert d2["dead_ranks"] == [] and d2["alive_ranks"] == 2
        assert d2["ranks"]["1"]["epoch"] == 1
        assert d2["ranks"]["1"]["restarted"] is True
        assert d2["rank_epochs"]["1"] == 1
        assert d2["step_time_skew"] == 0.0  # restarted rank excluded
        assert stat_get("cluster_rank_restarts") >= 1

        # scrape 2b: the restarted rank has NOT dispatched a step yet
        # (counters unchanged) — the exclusion must be STICKY, not a
        # single-scrape flag, or the cold p50 pollutes the skew gauge
        # one scrape after detection
        kv = {"health/rank/0": hb(0, now + 1.5, 100, 65, 0.1),
              "health/rank/1": hb(1, now + 1.5, 201, 2, 9.9)}
        d2b = health.cluster_health(kv, world_size=2, now=now + 1.5,
                                    book=book)
        assert d2b["ranks"]["1"]["epoch"] == 1  # no double bump
        assert d2b["ranks"]["1"]["restarted"] is True
        assert d2b["step_time_skew"] == 0.0

        # scrape 3: the restarted rank's counters move FORWARD again —
        # no further bump, and it re-enters the skew computation
        kv = {"health/rank/0": hb(0, now + 2, 100, 70, 0.1),
              "health/rank/1": hb(1, now + 2, 201, 12, 0.3)}
        d3 = health.cluster_health(kv, world_size=2, now=now + 2,
                                   book=book)
        assert d3["ranks"]["1"]["epoch"] == 1
        assert "restarted" not in d3["ranks"]["1"]
        assert d3["step_time_skew"] == pytest.approx(2.0)

    def test_counter_regression_alone_bumps_epoch(self):
        """A rank whose cumulative dispatched counter went backwards
        restarted even if its pid looks unchanged (pid reuse / missing
        pid field): the epoch must still bump exactly once."""
        book = {}
        now = time.time()

        def hb(disp):
            return json.dumps({"rank": 0, "ts": now, "interval_s": 1.0,
                               "dispatched": disp}).encode()

        for disp, want_epoch in ((30, 0), (31, 0), (4, 1), (5, 1)):
            doc = health.cluster_health(
                {"health/rank/0": hb(disp)}, world_size=1, now=now,
                book=book)
            assert doc["rank_epochs"]["0"] == want_epoch, disp

    def test_heartbeat_blackhole_chaos_dead_lists_then_recovers(self):
        """fleet.elastic.chaos 'heartbeat_blackhole' drops a live
        rank's beats (the injected dead-rank path); clearing the fault
        lets the next beat through."""
        from paddle_tpu.distributed.fleet.elastic import chaos

        from paddle_tpu.distributed.fleet.utils.http_server import \
            KVServer

        srv = KVServer(0)
        srv.start()
        try:
            r = health.HealthReporter(f"127.0.0.1:{srv.port}", rank=0,
                                      interval_s=5.0)
            chaos.inject("heartbeat_blackhole", rank=0, count=-1)
            try:
                assert r.publish_once() is False
                assert r.publish_once() is False
                assert srv.kv_snapshot(health.HEALTH_KEY_PREFIX) == {}
                assert stat_get("health_heartbeat_blackholed") >= 2
            finally:
                chaos.clear()
            assert r.publish_once() is True
            assert "health/rank/0" in srv.kv_snapshot(
                health.HEALTH_KEY_PREFIX)
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# /metrics scrape thread-safety under live recording (satellite)
# ---------------------------------------------------------------------------


class TestConcurrentScrape:
    def test_scrape_while_training_thread_records(self):
        """Concurrent /metrics scrapes over real HTTP while StepTimer +
        histograms + counters are being fed from a 'training' thread:
        every scrape must return 200 with well-formed exposition."""
        from paddle_tpu.distributed.fleet.utils.http_server import KVServer
        from paddle_tpu.monitor import stat_add, stat_time

        srv = KVServer(0)
        srv.start()
        stop = threading.Event()
        errors = []

        def trainer():
            timer = observe.StepTimer("concurrent_scrape_seconds")
            i = 0
            while not stop.is_set():
                i += 1
                stat_time("concurrent_scrape_seconds", 1e-4 * (i % 7 + 1))
                timer.record_run(1e-3, steps=1, examples=4,
                                 compiled=(i == 1))
                stat_add("concurrent_scrape_ops")
                flight.record("test/scrape_step", i=i)

        def scraper():
            url = f"http://127.0.0.1:{srv.port}/metrics"
            for _ in range(25):
                try:
                    with urllib.request.urlopen(url, timeout=10) as r:
                        assert r.status == 200
                        body = r.read().decode()
                    # well-formed: every sample line is "name value"
                    for ln in body.splitlines():
                        if ln and not ln.startswith("#"):
                            float(ln.rsplit(" ", 1)[1])
                    assert "concurrent_scrape_seconds_bucket" in body
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        tr = threading.Thread(target=trainer, daemon=True)
        scrapers = [threading.Thread(target=scraper) for _ in range(4)]
        tr.start()
        for s in scrapers:
            s.start()
        for s in scrapers:
            s.join()
        stop.set()
        tr.join(timeout=10)
        srv.stop()
        assert errors == []


# ---------------------------------------------------------------------------
# StepTimer MFU guard (satellite)
# ---------------------------------------------------------------------------


class TestMFUGuard:
    def test_mfu_is_null_when_peak_unset(self):
        t = observe.StepTimer("mfu_guard_seconds")
        t.record_run(0.01, steps=1, examples=1, compiled=True)
        t.record_run(0.01, steps=1, examples=1, flops_per_step=1e9)
        pt.set_flags({"FLAGS_device_peak_tflops": 0.0})
        s = t.summary()
        assert "mfu" in s and s["mfu"] is None
        assert s["flops_per_step"] > 0  # the numerator still reports
        json.dumps(s)  # null, not NaN/inf: stays JSON-clean
        # explicit peak overrides the dead flag
        assert t.summary(peak_tflops=100.0)["mfu"] > 0
        pt.set_flags({"FLAGS_device_peak_tflops": 275.0})
        assert t.summary()["mfu"] > 0

    def test_benchmark_callback_survives_null_mfu(self, capsys):
        """on_train_end formats the MFU — a null one (peak unset) must
        print 'no MFU' gracefully, not TypeError on the format spec."""
        from paddle_tpu.hapi.callbacks import BenchmarkCallback

        cb = BenchmarkCallback(batch_size=4, flops_per_step=1e9,
                               log_freq=0)
        cb.on_train_begin()
        for i in range(3):
            cb.on_train_batch_begin(i)
            time.sleep(0.001)
            cb.on_train_batch_end(i)
        pt.set_flags({"FLAGS_device_peak_tflops": 0.0})
        cb.on_train_end()  # crashed with TypeError before the guard
        assert cb.last_summary["mfu"] is None
        out = capsys.readouterr().out
        assert "[bench]" in out and "MFU" not in out
        pt.set_flags({"FLAGS_device_peak_tflops": 275.0})
        cb.on_train_end()
        assert "MFU" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# tools/postmortem.py CLI (satellite)
# ---------------------------------------------------------------------------


class TestPostmortemCLI:
    def _bundle(self, tmp_path):
        flight.record("test/cli", marker="xyz")
        # a retained violator so the bundle's requests.json section is
        # populated (observe/request_trace.py)
        from paddle_tpu.observe import request_trace as rt

        store = rt.get_trace_store()
        tr = store.start("decode", replica="replica-cli")
        tr.event("admit", slot=0)
        store.finish(tr, outcome="deadline", reason="cli smoke",
                     violations=["ttft_p99"], latency_s=0.5)
        return health.dump_postmortem("cli_smoke",
                                      directory=str(tmp_path))

    def test_in_process_render_and_latest_selection(self, tmp_path,
                                                    capsys):
        from tools import postmortem as pm

        b = self._bundle(tmp_path)
        assert pm.main([str(b)]) == 0
        out = capsys.readouterr().out
        assert "cli_smoke" in out and "flight recorder" in out
        # a parent dir resolves to its newest bundle
        assert pm.resolve_bundle(str(tmp_path)) == b
        assert pm.main([str(tmp_path), "--stacks"]) == 0
        assert "MainThread" in capsys.readouterr().out
        assert pm.main([str(tmp_path / "nope")]) == 2

    def test_python_dash_m_smoke(self, tmp_path):
        b = self._bundle(tmp_path)
        r = subprocess.run(
            [sys.executable, "-m", "tools.postmortem", b],
            capture_output=True, text=True, cwd=ROOT, timeout=60)
        assert r.returncode == 0, r.stderr
        assert "postmortem bundle" in r.stdout
        assert "cli_smoke" in r.stdout
        # the requests.json section renders: violator row + its SLO
        # violation, plus the reqtrace pointer
        assert "violators" in r.stdout
        assert "ttft_p99" in r.stdout
        assert "tools.reqtrace" in r.stdout
