"""Data pipeline + high-level API + vision model tests.

Parity model: reference unittests test_dataloader_*.py, test_metrics.py,
test_model.py, test_vision_models.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.io import (
    BatchSampler, DataLoader, Dataset, DistributedBatchSampler,
    IterableDataset, RandomSampler, TensorDataset, random_split,
)
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall
from paddle_tpu.vision.datasets import FakeData


class TestDataLoader:
    def test_tensor_dataset_batching(self):
        X = np.arange(40, dtype="f4").reshape(10, 4)
        Y = np.arange(10, dtype="int64")
        ds = TensorDataset([X, Y])
        loader = DataLoader(ds, batch_size=4, drop_last=True)
        batches = list(loader)
        assert len(batches) == 2
        xb, yb = batches[0]
        assert xb.shape == (4, 4) and yb.shape == (4,)
        np.testing.assert_allclose(xb, X[:4])

    def test_shuffle_covers_all(self):
        ds = TensorDataset([np.arange(16, dtype="f4")])
        loader = DataLoader(ds, batch_size=4, shuffle=True)
        seen = np.concatenate([b[0] for b in loader])
        assert sorted(seen.tolist()) == list(range(16))

    def test_iterable_dataset(self):
        class Stream(IterableDataset):
            def __iter__(self):
                for i in range(10):
                    yield np.asarray([i], dtype="f4")

        loader = DataLoader(Stream(), batch_size=3, drop_last=False)
        batches = list(loader)
        assert [len(b) for b in batches] == [3, 3, 3, 1]

    def test_batch_sampler_and_random_split(self):
        ds = TensorDataset([np.arange(10, dtype="f4")])
        bs = BatchSampler(ds, batch_size=3)
        assert len(bs) == 4
        a, b = random_split(ds, [7, 3], generator=0)
        assert len(a) == 7 and len(b) == 3

    def test_distributed_batch_sampler_shards(self):
        ds = TensorDataset([np.arange(16, dtype="f4")])
        shards = []
        for rank in range(4):
            s = DistributedBatchSampler(ds, batch_size=2, num_replicas=4,
                                        rank=rank)
            shards.append([i for batch in s for i in batch])
        flat = sorted(i for s in shards for i in s)
        assert flat == list(range(16))

    def test_prefetch_propagates_errors(self):
        class Bad(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            list(DataLoader(Bad(), batch_size=2))

    def test_collate_dict(self):
        class D(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                return {"x": np.ones(2, dtype="f4") * i, "y": i}

        batch = next(iter(DataLoader(D(), batch_size=4)))
        assert batch["x"].shape == (4, 2) and batch["y"].shape == (4,)


class TestMetrics:
    def test_accuracy(self):
        m = Accuracy()
        pred = np.asarray([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]], dtype="f4")
        label = np.asarray([[0], [1], [1]], dtype="int64")
        m.update(m.compute(paddle.to_tensor(pred), paddle.to_tensor(label)))
        assert abs(m.accumulate() - 2 / 3) < 1e-6

    def test_accuracy_topk(self):
        m = Accuracy(topk=(1, 2))
        pred = np.asarray([[0.5, 0.3, 0.2], [0.1, 0.2, 0.7]], dtype="f4")
        label = np.asarray([[1], [1]], dtype="int64")
        m.update(m.compute(paddle.to_tensor(pred), paddle.to_tensor(label)))
        top1, top2 = m.accumulate()
        assert abs(top1 - 0.0) < 1e-6 and abs(top2 - 1.0) < 1e-6

    def test_precision_recall(self):
        p, r = Precision(), Recall()
        preds = np.asarray([0.9, 0.8, 0.2, 0.7], dtype="f4")
        labels = np.asarray([1, 0, 1, 1], dtype="int64")
        p.update(preds, labels)
        r.update(preds, labels)
        assert abs(p.accumulate() - 2 / 3) < 1e-6  # tp=2 fp=1
        assert abs(r.accumulate() - 2 / 3) < 1e-6  # tp=2 fn=1

    def test_auc_perfect_separation(self):
        auc = Auc()
        preds = np.asarray([0.1, 0.2, 0.8, 0.9])
        labels = np.asarray([0, 0, 1, 1])
        auc.update(preds, labels)
        assert abs(auc.accumulate() - 1.0) < 1e-3


class MLPNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.act = nn.ReLU()
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        from paddle_tpu.tensor.manipulation import flatten

        return self.fc2(self.act(self.fc1(flatten(x, 1))))


class TestHapiModel:
    def _fake(self, n=64):
        return FakeData(num_samples=n, image_shape=(1, 4, 4), num_classes=4)

    def test_fit_reduces_loss(self):
        model = paddle.Model(MLPNet())
        model.prepare(paddle.optimizer.Adam(0.01, parameters=model.parameters()),
                      nn.CrossEntropyLoss(),
                      Accuracy())
        hist = model.fit(self._fake(), epochs=3, batch_size=16, verbose=0,
                         shuffle=False)
        assert hist["loss"][-1] < hist["loss"][0] / 2

    def test_evaluate_and_predict(self):
        model = paddle.Model(MLPNet())
        model.prepare(paddle.optimizer.Adam(0.01, parameters=model.parameters()),
                      nn.CrossEntropyLoss(), Accuracy())
        model.fit(self._fake(), epochs=2, batch_size=16, verbose=0)
        logs = model.evaluate(self._fake(32), batch_size=16, verbose=0)
        assert logs["acc"] > 0.5
        preds = model.predict(self._fake(32), batch_size=16, stack_outputs=True)
        assert preds[0].shape == (32, 4)

    def test_save_load_roundtrip(self, tmp_path):
        model = paddle.Model(MLPNet())
        model.prepare(paddle.optimizer.Adam(0.01, parameters=model.parameters()),
                      nn.CrossEntropyLoss())
        model.fit(self._fake(32), epochs=1, batch_size=16, verbose=0)
        path = str(tmp_path / "ckpt")
        model.save(path)

        model2 = paddle.Model(MLPNet())
        model2.prepare(paddle.optimizer.Adam(0.01, parameters=model2.parameters()),
                       nn.CrossEntropyLoss())
        model2.load(path)
        x = np.random.RandomState(0).randn(4, 1, 4, 4).astype("f4")
        np.testing.assert_allclose(model.predict_batch([x])[0],
                                   model2.predict_batch([x])[0], rtol=1e-5)

    def test_early_stopping(self):
        from paddle_tpu.hapi.callbacks import EarlyStopping

        model = paddle.Model(MLPNet())
        model.prepare(paddle.optimizer.Adam(0.0, parameters=model.parameters()),
                      nn.CrossEntropyLoss())
        es = EarlyStopping(monitor="loss", patience=1, mode="min")
        hist = model.fit(self._fake(32), eval_data=self._fake(16), epochs=10,
                         batch_size=16, verbose=0, callbacks=[es])
        assert len(hist["loss"]) < 10  # stopped early (lr=0 -> no improvement)


class TestVisionModels:
    def test_lenet_forward_backward(self):
        net = paddle.vision.LeNet()
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 1, 28, 28).astype("f4"))
        out = net(x)
        assert out.shape == [2, 10]
        paddle.mean(paddle.square(out)).backward()
        assert all(p.grad is not None for p in net.parameters())

    def test_resnet18_shapes(self):
        net = paddle.vision.resnet18(num_classes=7)
        net.eval()
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, 64, 64).astype("f4"))
        assert net(x).shape == [2, 7]

    def test_resnet50_bottleneck(self):
        net = paddle.vision.resnet50(num_classes=5)
        net.eval()
        x = paddle.to_tensor(np.random.RandomState(0).randn(1, 3, 64, 64).astype("f4"))
        assert net(x).shape == [1, 5]

    def test_mobilenet_v2(self):
        net = paddle.vision.mobilenet_v2(num_classes=6)
        net.eval()
        x = paddle.to_tensor(np.random.RandomState(0).randn(1, 3, 64, 64).astype("f4"))
        assert net(x).shape == [1, 6]

    def test_vgg11(self):
        net = paddle.vision.vgg11(num_classes=3)
        net.eval()
        x = paddle.to_tensor(np.random.RandomState(0).randn(1, 3, 224, 224).astype("f4"))
        assert net(x).shape == [1, 3]

    def test_transforms(self):
        from paddle_tpu.vision.transforms import (
            Compose, Normalize, Resize, ToTensor,
        )

        img = (np.random.RandomState(0).rand(28, 28, 3) * 255).astype("uint8")
        t = Compose([ToTensor(), Normalize([0.5] * 3, [0.5] * 3)])
        out = t(img)
        assert out.shape == (3, 28, 28)
        assert out.min() >= -1.001 and out.max() <= 1.001
        r = Resize((14, 14))(out)
        assert r.shape == (3, 14, 14)


class TestStaticModel:
    """Static-graph Model mode (reference hapi/model.py _AdapterStatic):
    prepare() builds train/eval/predict programs once; fit/evaluate/
    predict drive the Executor with one XLA compile per program."""

    def _make(self):
        from paddle_tpu.hapi.model import InputSpec

        paddle.enable_static()
        model = paddle.Model(
            MLPNet(),
            inputs=[InputSpec([None, 1, 4, 4], "float32", "img")],
            labels=[InputSpec([None, 1], "int64", "lbl")])
        model.prepare(
            paddle.optimizer.SGD(0.1, parameters=model.parameters()),
            nn.CrossEntropyLoss(), Accuracy())
        return model

    def test_static_fit_evaluate_predict(self):
        try:
            model = self._make()
            assert model._static_mode and model._st is not None
            hist = model.fit(self._fake(), epochs=3, batch_size=16,
                             verbose=0, shuffle=False)
            assert hist["loss"][-1] < hist["loss"][0] / 2, hist["loss"]
            logs = model.evaluate(self._fake(32), batch_size=16, verbose=0)
            assert logs["acc"] > 0.5
            preds = model.predict(self._fake(32), batch_size=16,
                                  stack_outputs=True)
            assert preds[0].shape == (32, 4)
        finally:
            paddle.disable_static()

    def test_static_save_syncs_trained_params(self, tmp_path):
        try:
            model = self._make()
            before = np.asarray(model.parameters()[0].numpy()).copy()
            model.fit(self._fake(32), epochs=2, batch_size=16, verbose=0)
            model.save(str(tmp_path / "m"))
            after = np.asarray(model.parameters()[0].numpy())
            assert not np.allclose(before, after), \
                "trained scope values must sync back into parameters"
        finally:
            paddle.disable_static()

    def _fake(self, n=64):
        return FakeData(num_samples=n, image_shape=(1, 4, 4), num_classes=4)


class TestModelStat:
    """paddle.flops / paddle.summary / memory_usage (reference hapi +
    fluid/contrib/model_stat.py, memory_usage_calc.py)."""

    def test_summary_and_flops(self):
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                            nn.Linear(32, 4))
        stats = paddle.summary(net, input_size=(2, 16))
        assert stats["total_params"] == 16 * 32 + 32 + 32 * 4 + 4
        # exact: 2 matmuls (2*MACs) + bias adds + relu, batch 2
        expect = 2*2*16*32 + 2*2*32*4 + 2*32 + 2*4 + 2*32
        assert stats["flops"] == expect, stats["flops"]

    def test_program_memory_usage(self):
        from paddle_tpu.framework.program import Program, program_guard
        from paddle_tpu.hapi.model_stat import memory_usage

        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = paddle.fluid.layers.data("img", [16])
            h = nn.functional.relu(x)
        m = memory_usage(main, batch_size=64)
        assert m["total_mb"] > 0
        assert m["activation_mb"] >= 64 * 16 * 4 / 2**20
