"""Per-request tracing + SLO burn-rate/goodput plane (observe/
request_trace.py, observe/slo.py, and their threading through the
serving stack).

The load-bearing properties:

- recording is always on, retention is head-sampled, and an SLO
  violator / abnormal ending is retained even at
  ``FLAGS_request_trace_sample=0`` (tail retention) with its FULL
  timeline — admission wait, prefill chunks, spec rounds, outcome;
- tracing must be a pure observer: decode outputs are bitwise-equal
  with sampling on vs off at the spec x prefix x chunked composition,
  and the recording path costs <= 5% tokens/sec;
- the debug plane (``/debug/requests``, ``/debug/request/<id>``)
  stays well-formed under concurrent scrape while the engine
  admits/reaps (the test_xla_stats 4-scraper x 25-GET pattern);
- every terminal outcome lands in the flat per-outcome counters so
  error-rate SLOs have a denominator.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from paddle_tpu.framework import flags as flags_mod
from paddle_tpu.monitor import stat_get
from paddle_tpu.observe import request_trace as rt
from paddle_tpu.observe import slo as slo_mod
from paddle_tpu.serving.batcher import InferenceRequest
from paddle_tpu.serving.buckets import (DeadlineExceededError,
                                        QueueFullError,
                                        RequestTooLargeError)
from paddle_tpu.serving.decode import (DecodeConfig, DecodeEngine,
                                       TransformerLM)
from paddle_tpu.serving.server import DecodeServer

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VOCAB = 37


@pytest.fixture(scope="module")
def model_and_weights():
    import jax

    model = TransformerLM(vocab_size=VOCAB, d_model=32, num_layers=2,
                          num_heads=2, max_seq_len=256)
    return model, model.init_weights(jax.random.PRNGKey(5))


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test starts from an empty trace store, default sampling,
    and flag-default SLO objectives."""
    rt.get_trace_store().clear()
    flags_mod.set_flags({"request_trace_sample": 1.0})
    slo_mod.configure(None)
    yield
    rt.get_trace_store().clear()
    flags_mod.set_flags({"request_trace_sample": 1.0})
    slo_mod.configure(None)


def make_engine(model_and_weights, **cfg_kw):
    model, weights = model_and_weights
    kw = dict(slots=2, max_seq_len=64, page_size=8, max_new_tokens=8)
    kw.update(cfg_kw)
    return DecodeEngine(model, weights, DecodeConfig(**kw))


# ---------------------------------------------------------------------------
# store + SLO engine units
# ---------------------------------------------------------------------------


def test_head_sampling_is_deterministic_exact_rate():
    store = rt.TraceStore(capacity=64)
    flags_mod.set_flags({"request_trace_sample": 0.25})
    kept = 0
    for _ in range(32):
        tr = store.start("decode", replica="r0")
        store.finish(tr, outcome="completed")
        kept += tr.sampled
    assert kept == 8  # exactly 25%, not a coin flip
    assert len(store.retained()) == 8


def test_tail_retention_keeps_violators_and_abnormal_at_sample_zero():
    store = rt.TraceStore(capacity=64)
    flags_mod.set_flags({"request_trace_sample": 0.0})
    ok = store.start("decode")
    store.finish(ok, outcome="completed")
    bad = store.start("decode")
    store.finish(bad, outcome="deadline", reason="mid-decode")
    viol = store.start("decode")
    store.finish(viol, outcome="completed", violations=["ttft_p99"])
    ids = [t.trace_id for t in store.retained()]
    assert bad.trace_id in ids and viol.trace_id in ids
    assert ok.trace_id not in ids
    assert [t.trace_id for t in store.violators()] == ids
    # lookup works for retained and is None for the sampled-out one
    assert store.get(bad.trace_id) is bad
    assert store.get(ok.trace_id) is None


def test_trace_event_cap_counts_drops():
    store = rt.TraceStore(capacity=4)
    tr = store.start("decode")
    for i in range(rt.MAX_EVENTS_PER_TRACE + 7):
        tr.event("token", n=i)
    assert len(tr.events) == rt.MAX_EVENTS_PER_TRACE
    assert tr.dropped_events == 7
    store.finish(tr, outcome="error", reason="overflow test")
    d = tr.to_dict()
    assert d["dropped_events"] == 7
    # finish appended its terminal event inside the cap'd list? finish
    # always lands (appended after the flag flip)
    assert tr.events[-1][1] == "finish"


def test_slo_engine_burn_rates_and_goodput():
    eng = slo_mod.SLOEngine(
        objectives=[slo_mod.Objective("ttft_p99", "ttft", 0.010, 0.01),
                    slo_mod.Objective("error_rate", "error", None, 0.5)],
        windows=(60.0, 300.0))
    # 3 good, 1 slow-ttft, 1 error
    for _ in range(3):
        assert eng.observe({"outcome": "completed", "ttft_s": 0.001}) == []
    assert eng.observe({"outcome": "completed", "ttft_s": 0.5}) \
        == ["ttft_p99"]
    assert eng.observe({"outcome": "deadline", "ttft_s": None}) \
        == ["ttft_p99", "error_rate"]
    snap = eng.snapshot()
    # ttft: 2 bad of 5 -> frac 0.4 over budget 0.01 -> burn 40x
    assert snap["burn_rates"]["ttft_p99"]["60s"] == pytest.approx(40.0)
    # error: 1 bad of 5 -> 0.2 / 0.5 -> 0.4x, budget remaining 60%
    assert snap["burn_rates"]["error_rate"]["60s"] == pytest.approx(0.4)
    assert snap["budget_remaining"]["error_rate"] == pytest.approx(0.6)
    assert snap["budget_remaining"]["ttft_p99"] == 0.0  # exhausted
    assert snap["goodput_rps"] > 0.0  # 3 good completions just landed
    assert snap["violations_total"] == 3


def test_slo_latency_objective_counts_missing_signal_as_violated():
    o = slo_mod.Objective("ttft_p99", "ttft", 0.5, 0.01)
    assert o.is_violated({"outcome": "deadline", "ttft_s": None})
    assert not o.is_violated({"outcome": "completed", "ttft_s": 0.1})


# ---------------------------------------------------------------------------
# the acceptance scenario: induced violation, retained at sample=0
# ---------------------------------------------------------------------------


def test_induced_violation_end_to_end(model_and_weights, tmp_path,
                                      capsys):
    """The acceptance scenario in one run: long-prompt adversary + an
    unmeetable ttft objective, head sampling fully OFF — the violator
    must still come back with its whole timeline, burn gauges must be
    nonzero, and the trace must render on every surface (chrome
    export, /metrics gauges, postmortem requests.json, tools/reqtrace,
    tools/postmortem, python -m reqtrace)."""
    flags_mod.set_flags({"request_trace_sample": 0.0})
    slo_mod.configure([
        slo_mod.Objective("ttft_p99", "ttft", 1e-4, 0.01),
        slo_mod.Objective("error_rate", "error", None, 0.01)])
    eng = make_engine(model_and_weights, slots=2,
                      prefill_chunk_pages=1)
    with eng:
        # adversary: a 5-page prompt prefilled one page per step
        # boundary; the victim rides behind it
        adv = eng.submit(list(range(1, 41)), max_new_tokens=4)
        vic = eng.submit([1, 2, 3], max_new_tokens=4)
        adv.result(timeout=120)
        vic.result(timeout=120)

    store = rt.get_trace_store()
    tid = adv.trace.trace_id
    tr = store.get(tid)
    assert tr is not None, "violator dropped despite sample=0"
    assert "ttft_p99" in tr.violations
    names = [e[1] for e in tr.events]
    assert "enqueue" in names and "admit" in names
    assert names.count("prefill_chunk") >= 5  # 5 pages, 1 per chunk
    assert "token" in names and "finish" in names
    assert tr.outcome == "completed" and tr.reason == "budget"
    assert tr.summary["ttft_s"] > 1e-4
    # the victim (also a violator under the 0.1ms objective) shows
    # the admission wait behind the adversary
    tv = store.get(vic.trace.trace_id)
    assert tv is not None and "ttft_p99" in tv.violations
    # burn-rate + goodput gauges are live on the registry
    assert stat_get("slo_burn_rate_ttft_p99_ppm") > 0
    assert stat_get("slo_budget_remaining_ttft_p99_ppm") == 0
    assert stat_get("decode_goodput_rps_ppm") == 0  # nobody met SLO
    assert stat_get("decode_slo_violations") > 0

    # chrome export through observe/timeline.py
    doc = rt.chrome_trace(tid)
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert any(e["name"] == "request/admit" for e in spans)
    assert doc["otherData"]["trace_id"] == tid

    # postmortem bundle requests.json
    from paddle_tpu.observe import health

    b = health.dump_postmortem("slo_violation", directory=str(tmp_path))
    rq = json.load(open(os.path.join(b, "requests.json")))
    assert any(t["trace_id"] == tid for t in rq["violators"])
    assert rq["slo"]["burn_rates"]["ttft_p99"]["60s"] > 0

    # tools/reqtrace renders the section and the single timeline
    from tools import reqtrace

    assert reqtrace.main([os.path.join(b, "requests.json")]) == 0
    out = capsys.readouterr().out
    assert "SLO verdict" in out and tid in out
    assert reqtrace.main([os.path.join(b, "requests.json"),
                          "--id", tid]) == 0
    out = capsys.readouterr().out
    assert "prefill_chunk" in out and "outcome:  completed" in out

    # tools/postmortem renders the violator table + SLO verdict
    from tools import postmortem as pm

    assert pm.main([b]) == 0
    out = capsys.readouterr().out
    assert "violators" in out and tid in out and "ttft_p99" in out

    # the pure-stdlib CLI works from a clean interpreter
    r = subprocess.run(
        [sys.executable, "-m", "tools.reqtrace",
         os.path.join(b, "requests.json"), "--id", tid],
        capture_output=True, text=True, cwd=ROOT, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "timeline" in r.stdout and "admit" in r.stdout


# ---------------------------------------------------------------------------
# pure-observer contract: bitwise parity + bounded overhead
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def self_draft(model_and_weights):
    # self-draft (full acceptance) keeps the spec path deterministic
    # and fast; the low-acceptance path is pinned elsewhere
    return model_and_weights


def test_trace_on_off_bitwise_parity_spec_prefix_chunked(
        model_and_weights, self_draft):
    """spec x prefix x chunked composition decoded twice — sampling
    fully on vs fully off — must produce bitwise-identical tokens AND
    logits (tracing is a pure observer)."""
    model, weights = model_and_weights
    dm, dw = self_draft
    prompts = [list(range(1, 20)), list(range(1, 23)),
               list(range(1, 20)), [5, 6, 7]]

    def run(sample):
        flags_mod.set_flags({"request_trace_sample": sample})
        eng = DecodeEngine(
            model, weights,
            DecodeConfig(slots=2, max_seq_len=64, page_size=8,
                         prefix_cache=True, prefill_chunk_pages=1,
                         spec_k=2),
            draft_model=dm, draft_weights=dw)
        outs, logits = [], []
        with eng:
            for i, p in enumerate(prompts):
                r = eng.submit(p, max_new_tokens=6, seed=i,
                               record_logits=True)
                outs.append(r.result(timeout=120))
                logits.append([a.copy() for a in r.logits_trace])
            st = eng.stats()
        return outs, logits, st

    on_outs, on_logits, on_stats = run(1.0)
    off_outs, off_logits, _ = run(0.0)
    assert on_outs == off_outs
    for a_seq, b_seq in zip(on_logits, off_logits):
        assert len(a_seq) == len(b_seq)
        for a, b in zip(a_seq, b_seq):
            assert np.array_equal(a, b)
    # the composition actually engaged every path while traced
    store = rt.get_trace_store()
    all_events = [e[1] for t in store.retained() for e in t.events]
    assert "prefill_chunk" in all_events
    assert "spec_round" in all_events
    assert "cache/register" in all_events
    # the run exercised prefix sharing + full-acceptance speculation
    # (per-ENGINE exact rates; the registry gauges below are global
    # cumulative and other tests in the process feed them too)
    assert on_stats["cache_hit_rate"] > 0
    assert on_stats["spec_accept_rate"] == 1.0  # self-draft
    # float-precision _ppm companions of the (deprecated) integer
    # percent gauges are live and mutually consistent
    hit_pct = stat_get("decode_cache_hit_rate")
    hit_ppm = stat_get("decode_cache_hit_rate_ppm")
    assert hit_ppm > 0
    assert abs(hit_ppm / 1e4 - hit_pct) < 1.0  # same quantity, finer
    acc_pct = stat_get("spec_accept_rate")
    acc_ppm = stat_get("spec_accept_rate_ppm")
    assert acc_ppm > 0
    assert abs(acc_ppm / 1e4 - acc_pct) < 1.0
    # all 8 requests completed within the default (error-rate-only)
    # objectives -> goodput is live and nonzero on the registry
    assert stat_get("decode_goodput_rps_ppm") > 0


def test_request_trace_overhead_ratio_below_5pct(model_and_weights):
    """Closed-loop tokens/sec with sampling on vs off, INTERLEAVED
    best-of-4 per mode (alternating runs cancel host drift): recording
    must cost <= 5%.  GC is quiesced during measurement — mid-suite,
    collection pauses over earlier tests' dead device pools dwarf the
    ~µs/event recording cost being measured (the same effect bench.py
    guards its seqlen8x ratio against) — and a failing attempt is
    re-measured up to twice before it counts."""
    import gc

    eng = make_engine(model_and_weights, slots=1, max_seq_len=128,
                      prefix_cache=False)

    def one_run(sample):
        flags_mod.set_flags({"request_trace_sample": sample})
        t0 = time.perf_counter()
        out = eng.generate([1, 2, 3], max_new_tokens=48)
        return len(out) / (time.perf_counter() - t0)

    with eng:
        eng.generate([1, 2, 3], max_new_tokens=50)  # warm every path
        ratio = None
        for _attempt in range(3):
            gc.collect()
            gc.disable()
            try:
                traced, untraced = 0.0, 0.0
                for _ in range(4):
                    traced = max(traced, one_run(1.0))
                    untraced = max(untraced, one_run(0.0))
            finally:
                gc.enable()
            ratio = untraced / traced
            if ratio <= 1.05:
                break
    assert ratio <= 1.05, (
        f"request tracing costs {100 * (ratio - 1):.1f}% tokens/sec "
        f"(traced {traced:.0f} vs untraced {untraced:.0f}) across 3 "
        f"attempts")


# ---------------------------------------------------------------------------
# outcome counters (error-rate SLO denominator)
# ---------------------------------------------------------------------------


class TestOutcomeCounters:
    def test_deadline_and_reject_counters(self, model_and_weights):
        eng = make_engine(model_and_weights, slots=1, max_queue=1)
        base_dl = stat_get("decode_requests_total_deadline")
        base_rej = stat_get("decode_requests_total_rejected")
        lat_count = stat_get("decode_request_latency_seconds_count") or 0
        with eng:
            with pytest.raises(RequestTooLargeError):
                eng.submit(list(range(200)), max_new_tokens=200)
            r = eng.submit([1, 2], max_new_tokens=4, deadline_ms=0.0001)
            with pytest.raises(DeadlineExceededError):
                r.result(timeout=60)
        assert stat_get("decode_requests_total_rejected") == base_rej + 1
        assert stat_get("decode_requests_total_deadline") == base_dl + 1
        from paddle_tpu.observe.histogram import histogram

        assert histogram("decode_request_latency_seconds").count \
            > lat_count
        # both abnormal endings are tail-retained with outcomes
        outs = {t.outcome for t in rt.get_trace_store().retained()}
        assert {"rejected", "deadline"} <= outs

    def test_abandon_outcome(self, model_and_weights):
        base = stat_get("decode_requests_total_abandoned")
        eng = make_engine(model_and_weights, slots=1, max_seq_len=128)
        with eng:
            r = eng.submit([1, 2], max_new_tokens=64,
                           on_token=lambda t: time.sleep(0.01))
            for _ in range(200):
                if r.generated:
                    break
                time.sleep(0.005)
            assert r.abandon("test gives up")
            # the engine must free the slot at a step boundary and
            # accept new work
            out = eng.generate([3, 4], max_new_tokens=2)
            assert len(out) == 2
        assert stat_get("decode_requests_total_abandoned") == base + 1
        tr = rt.get_trace_store().get(r.trace.trace_id)
        assert tr is not None and tr.outcome == "abandoned"

    def test_batcher_deadline_records_latency_and_counter(self):
        from paddle_tpu.observe.histogram import histogram

        base = stat_get("serving_requests_total_deadline")
        count = histogram("serving_latency_seconds").count
        req = InferenceRequest([], 1, (1,),
                               deadline=time.monotonic() - 0.01)
        with pytest.raises(DeadlineExceededError):
            req.result()
        assert stat_get("serving_requests_total_deadline") == base + 1
        assert histogram("serving_latency_seconds").count == count + 1

    def test_queue_full_rejection_counter(self, model_and_weights):
        base = stat_get("decode_requests_total_rejected")
        eng = make_engine(model_and_weights, slots=1, max_queue=1)
        # engine NOT started: the queue fills and stays full
        eng.submit([1], max_new_tokens=2)
        with pytest.raises(QueueFullError):
            eng.submit([2], max_new_tokens=2)
        eng.stop(drain=False)
        assert stat_get("decode_requests_total_rejected") == base + 1


# ---------------------------------------------------------------------------
# /debug plane under concurrent scrape (test_xla_stats pattern)
# ---------------------------------------------------------------------------


class TestConcurrentDebugScrape:
    def test_debug_requests_while_engine_admits_and_reaps(
            self, model_and_weights):
        """4 scrapers x 25 GETs over real HTTP against /debug/requests,
        /debug/request/<id>, /debug/slo, and /metrics while the server
        admits, decodes, deadline-reaps, and finishes a request stream:
        every response must stay well-formed JSON (or a well-formed
        exposition) and never 500."""
        model, weights = model_and_weights
        slo_mod.configure([
            slo_mod.Objective("ttft_p99", "ttft", 1e-4, 0.01)])
        srv = DecodeServer(
            model, weights,
            DecodeConfig(slots=2, max_seq_len=64, page_size=8,
                         max_queue=64),
            replicas=2, http_port=0)
        errors = []
        reqs = []
        stop = threading.Event()

        def feeder():
            i = 0
            while not stop.is_set() and i < 40:
                i += 1
                try:
                    reqs.append(srv.submit(
                        [1 + i % 7, 2, 3], max_new_tokens=3 + i % 5,
                        deadline_ms=0.05 if i % 9 == 0 else None,
                        seed=i))
                except QueueFullError:
                    pass
                time.sleep(0.002)

        def scraper():
            port = srv.http_port
            tid = None
            for _ in range(25):
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/debug/requests",
                            timeout=10) as r:
                        assert r.status == 200
                        doc = json.loads(r.read().decode())
                    assert "requests" in doc and isinstance(
                        doc["requests"], list)
                    for row in doc["requests"]:
                        assert "phase" in row and "replica" in row
                        tid = row.get("trace_id") or tid
                    if tid is not None:
                        with urllib.request.urlopen(
                                f"http://127.0.0.1:{port}"
                                f"/debug/request/{tid}",
                                timeout=10) as r:
                            assert r.status == 200
                            json.loads(r.read().decode())
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/debug/slo",
                            timeout=10) as r:
                        assert r.status == 200
                        assert "burn_rates" in json.loads(
                            r.read().decode())
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/metrics",
                            timeout=10) as r:
                        body = r.read().decode()
                    for ln in body.splitlines():
                        if ln and not ln.startswith("#"):
                            float(ln.rsplit(" ", 1)[1])
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        with srv:
            srv.generate([1, 2], max_new_tokens=2)  # warm compiles
            ft = threading.Thread(target=feeder, daemon=True)
            scrapers = [threading.Thread(target=scraper)
                        for _ in range(4)]
            ft.start()
            for s in scrapers:
                s.start()
            for s in scrapers:
                s.join()
            stop.set()
            ft.join(timeout=30)
            for r in reqs:
                try:
                    r.result(timeout=120)
                except DeadlineExceededError:
                    pass
            st = srv.stats()
        assert not errors, errors[:3]
        # the metrics surface carried the SLO plane
        assert stat_get("slo_burn_rate_ttft_p99_ppm") >= 0
        assert stat_get("decode_requests_total_completed") > 0
        # DecodeServer aggregation carries the goodput/violation plane
        assert "goodput_rps" in st and "slo_violations" in st
        # replica-tagged traces from the engines land in ONE store
        replicas = {t.replica for t in rt.get_trace_store().retained()
                    if t.kind == "decode"}
        assert replicas and all(r.startswith("replica-")
                                for r in replicas)

    def test_debug_request_unknown_id_is_a_json_answer(
            self, model_and_weights):
        model, weights = model_and_weights
        srv = DecodeServer(model, weights,
                           DecodeConfig(slots=1, max_seq_len=32,
                                        page_size=8),
                           replicas=1, http_port=0)
        with srv:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.http_port}"
                    f"/debug/request/nope-000001", timeout=10) as r:
                doc = json.loads(r.read().decode())
        assert "error" in doc and "nope-000001" in doc["error"]


