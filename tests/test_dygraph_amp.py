"""Dygraph AMP: auto_cast actually casts; grads reach fp32 masters.

Reference parity: imperative/amp_auto_cast.cc (NeedCast:51) +
python/paddle/amp/auto_cast.py amp_guard.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import amp, nn
from paddle_tpu.dygraph.tensor import Tensor


def test_auto_cast_runs_white_ops_low_precision():
    lin = nn.Linear(8, 4)
    x = Tensor(np.random.RandomState(0).randn(2, 8).astype("f4"),
               stop_gradient=False)
    with amp.auto_cast(dtype="bfloat16"):
        y = lin(x)
    assert str(y.dtype) == "bfloat16", y.dtype
    # outside the guard: fp32 again
    y2 = lin(x)
    assert str(y2.dtype) == "float32"


def test_auto_cast_grads_are_fp32_and_close_to_fp32_run():
    rs = np.random.RandomState(1)
    lin = nn.Linear(8, 1)
    x = Tensor(rs.randn(16, 8).astype("f4"))

    with amp.auto_cast(dtype="bfloat16"):
        loss = pt.tensor.math.sum(lin(x))
    loss.backward()
    g_amp = np.asarray(lin.weight.grad.numpy())
    assert g_amp.dtype == np.float32  # master param grad dtype

    lin.clear_gradients()
    loss2 = pt.tensor.math.sum(lin(x))
    loss2.backward()
    g_fp32 = np.asarray(lin.weight.grad.numpy())
    np.testing.assert_allclose(g_amp, g_fp32, rtol=2e-2, atol=1e-2)


def test_backward_after_scope_exit_uses_recorded_dtype():
    """The standard pattern: forward under auto_cast(float16), backward
    OUTSIDE the scope — the replay must cast exactly as the forward did
    (policy captured at record time, not read live)."""
    rs = np.random.RandomState(3)
    lin = nn.Linear(8, 4)
    x = Tensor(rs.randn(2, 8).astype("f4"))
    with amp.auto_cast(dtype="float16"):
        loss = pt.tensor.math.sum(lin(x))
    loss.backward()  # scope exited; default dtype differs
    g = np.asarray(lin.weight.grad.numpy())
    assert g.dtype == np.float32 and np.isfinite(g).all()


def test_grad_scaler_scales_unscales_and_skips_inf_steps():
    """fp16-style dynamic loss scaling: scaled backward, unscale to the
    true grads, inf grads skip the update and shrink the scale
    (reference amp/grad_scaler.py state machine)."""
    import jax.numpy as jnp

    from paddle_tpu.optimizer import SGD

    rs = np.random.RandomState(4)
    lin = nn.Linear(4, 1, bias_attr=False)
    opt = SGD(learning_rate=0.1, parameters=lin.parameters())
    scaler = amp.GradScaler(init_loss_scaling=2.0 ** 8,
                            decr_every_n_nan_or_inf=1)
    x = Tensor(rs.randn(3, 4).astype("f4"))

    # normal step: unscaled grad equals the plain-backward grad
    loss = pt.tensor.math.sum(lin(x))
    scaled = scaler.scale(loss)
    assert float(np.asarray(scaled.numpy()).ravel()[0]) == pytest.approx(
        256.0 * float(np.asarray(loss.numpy()).ravel()[0]), rel=1e-6)
    w_before = np.asarray(lin.weight.numpy()).copy()
    scaled.backward()
    scaler.step(opt)
    opt.clear_grad()
    want_grad = np.asarray(x.numpy()).sum(0, keepdims=True).T
    got_w = np.asarray(lin.weight.numpy())
    np.testing.assert_allclose(got_w, w_before - 0.1 * want_grad,
                               rtol=1e-5, atol=1e-6)

    # poisoned step: inf grad -> update skipped, scale halved
    w_before = got_w.copy()
    scale_before = scaler.get_loss_scaling()
    bad = Tensor(np.array([[np.inf, 0, 0, 0]], "f4"))
    loss2 = pt.tensor.math.sum(lin(bad))
    scaler.scale(loss2).backward()
    scaler.step(opt)
    opt.clear_grad()
    np.testing.assert_array_equal(np.asarray(lin.weight.numpy()), w_before)
    assert scaler.get_loss_scaling() < scale_before


def test_black_list_op_stays_fp32():
    x = Tensor(np.random.RandomState(2).rand(4, 4).astype("f4") + 0.5)
    with amp.auto_cast(dtype="bfloat16"):
        # softmax_with_cross_entropy is black -> runs fp32 even under amp
        out = pt.log(x)
    assert str(out.dtype) == "float32"
