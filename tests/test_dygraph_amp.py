"""Dygraph AMP: auto_cast actually casts; grads reach fp32 masters.

Reference parity: imperative/amp_auto_cast.cc (NeedCast:51) +
python/paddle/amp/auto_cast.py amp_guard.
"""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import amp, nn
from paddle_tpu.dygraph.tensor import Tensor


def test_auto_cast_runs_white_ops_low_precision():
    lin = nn.Linear(8, 4)
    x = Tensor(np.random.RandomState(0).randn(2, 8).astype("f4"),
               stop_gradient=False)
    with amp.auto_cast(dtype="bfloat16"):
        y = lin(x)
    assert str(y.dtype) == "bfloat16", y.dtype
    # outside the guard: fp32 again
    y2 = lin(x)
    assert str(y2.dtype) == "float32"


def test_auto_cast_grads_are_fp32_and_close_to_fp32_run():
    rs = np.random.RandomState(1)
    lin = nn.Linear(8, 1)
    x = Tensor(rs.randn(16, 8).astype("f4"))

    with amp.auto_cast(dtype="bfloat16"):
        loss = pt.tensor.math.sum(lin(x))
    loss.backward()
    g_amp = np.asarray(lin.weight.grad.numpy())
    assert g_amp.dtype == np.float32  # master param grad dtype

    lin.clear_gradients()
    loss2 = pt.tensor.math.sum(lin(x))
    loss2.backward()
    g_fp32 = np.asarray(lin.weight.grad.numpy())
    np.testing.assert_allclose(g_amp, g_fp32, rtol=2e-2, atol=1e-2)


def test_backward_after_scope_exit_uses_recorded_dtype():
    """The standard pattern: forward under auto_cast(float16), backward
    OUTSIDE the scope — the replay must cast exactly as the forward did
    (policy captured at record time, not read live)."""
    rs = np.random.RandomState(3)
    lin = nn.Linear(8, 4)
    x = Tensor(rs.randn(2, 8).astype("f4"))
    with amp.auto_cast(dtype="float16"):
        loss = pt.tensor.math.sum(lin(x))
    loss.backward()  # scope exited; default dtype differs
    g = np.asarray(lin.weight.grad.numpy())
    assert g.dtype == np.float32 and np.isfinite(g).all()


def test_black_list_op_stays_fp32():
    x = Tensor(np.random.RandomState(2).rand(4, 4).astype("f4") + 0.5)
    with amp.auto_cast(dtype="bfloat16"):
        # softmax_with_cross_entropy is black -> runs fp32 even under amp
        out = pt.log(x)
    assert str(out.dtype) == "float32"
