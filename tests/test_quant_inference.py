"""Quantized inference (PR 13): int8/fp8 weight-only serving
(slim.PostTrainingWeightQuantPass + ops/quant_ops.dequant_matmul) and
the quantized paged KV cache (serving/kv_cache.py int8 pages +
per-page scale planes).

The load-bearing invariants:

- WEIGHT quant is a graph pass: flag-gated, cache-re-keyed, carriers +
  per-channel scales in scope, the f32 weight dropped from the
  executable's arguments; composes with LayerScanPass (stacked int8
  carriers), the AMP cast path, and the TP sharding plan.
- KV quant stores WRITE-ONCE bytes (per-position per-head scales), so
  every composition path — prefix hit, CoW, chunked prefill,
  speculative decode — is BITWISE-identical to the plain quantized
  run, and the quality tax vs the full-precision oracle is bounded and
  measured (quant_quality_delta), never assumed.
- Scales are clamped PER SLICE: an all-zero channel/head dequantizes
  to exact zeros instead of dividing by ~0 (the _abs_max bugfix).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.monitor import stat_get
from paddle_tpu.serving.decode import DecodeConfig, DecodeEngine, \
    TransformerLM

VOCAB = 61


@pytest.fixture(scope="module")
def model_and_weights():
    import jax

    model = TransformerLM(vocab_size=VOCAB, d_model=32, num_layers=2,
                          num_heads=2, max_seq_len=256)
    weights = model.init_weights(jax.random.PRNGKey(7))
    return model, weights


def make_engine(model_and_weights, draft=None, **cfg_kw):
    model, weights = model_and_weights
    kw = dict(slots=2, max_seq_len=64, page_size=8, max_new_tokens=8,
              kv_quant=True)
    kw.update(cfg_kw)
    dm, dw = draft if draft is not None else (None, None)
    return DecodeEngine(model, weights, DecodeConfig(**kw),
                        draft_model=dm, draft_weights=dw)


# -- scale clamping: the per-slice bugfix ---------------------------------


def test_scale_clamp_is_per_slice_not_global():
    """An all-zero output channel (weight) or head (KV) must get a
    CLAMPED scale of its own — dequantizing to exact zeros — while its
    non-zero neighbors keep real scales.  A global-max clamp would
    leave the zero slice's scale at ~0 and the new per-page path would
    divide by it."""
    import jax.numpy as jnp

    from paddle_tpu.ops.quant_ops import (SCALE_EPS, dequantize_weight,
                                          quantize_weight)
    from paddle_tpu.serving.kv_cache import dequantize_kv, quantize_kv

    rs = np.random.RandomState(0)
    w = rs.randn(16, 8).astype("f4")
    w[:, 3] = 0.0
    q, s = quantize_weight(w, 1, "int8")
    assert np.isfinite(np.asarray(s)).all()
    assert np.asarray(s)[3] == np.float32(SCALE_EPS)
    assert np.asarray(s)[2] > 1e-4  # neighbor keeps its real scale
    wd = np.asarray(dequantize_weight(q, s, 1))
    assert np.all(wd[:, 3] == 0.0) and np.isfinite(wd).all()

    kv = rs.randn(4, 2, 8).astype("f4")
    kv[1, 0] = 0.0  # one all-zero (position, head) slice
    qk, sk = quantize_kv(jnp.asarray(kv))
    sk = np.asarray(sk)
    assert np.isfinite(sk).all() and (sk > 0).all()
    assert sk[1, 0] == np.float32(SCALE_EPS)
    back = np.asarray(dequantize_kv(qk, jnp.asarray(sk), jnp.float32))
    assert np.all(back[1, 0] == 0.0) and np.isfinite(back).all()


# -- dequant_matmul op ----------------------------------------------------


def test_dequant_matmul_reference_accuracy_and_pallas_interpret():
    import jax.numpy as jnp

    from paddle_tpu.ops.quant_ops import dequant_matmul, quantize_weight

    rs = np.random.RandomState(1)
    x = rs.randn(16, 64).astype("f4")
    w = rs.randn(64, 32).astype("f4")
    q, s = quantize_weight(w, 1, "int8")
    ref = x @ w
    out = np.asarray(dequant_matmul(jnp.asarray(x), q, s,
                                    use_pallas="never"))
    assert np.abs(out - ref).max() / np.abs(ref).max() < 0.02
    pal = np.asarray(dequant_matmul(jnp.asarray(x), q, s,
                                    use_pallas="always", interpret=True))
    np.testing.assert_allclose(pal, out, rtol=1e-5, atol=1e-5)


def test_fp8_mode_quantizes_or_degrades_loudly():
    from paddle_tpu.framework import jax_compat
    from paddle_tpu.ops.quant_ops import (dequantize_weight,
                                          quantize_weight,
                                          resolve_quant_mode)

    rs = np.random.RandomState(2)
    w = rs.randn(32, 16).astype("f4")
    mode = resolve_quant_mode("fp8_e4m3")
    q, s = quantize_weight(w, 1, "fp8_e4m3")
    if jax_compat.float8_e4m3_dtype() is not None:
        assert mode == "fp8_e4m3"
        assert "float8" in str(q.dtype)
    else:
        assert mode == "int8" and q.dtype == np.int8
    err = np.abs(np.asarray(dequantize_weight(q, s, 1)) - w).max()
    assert err < 0.2  # fp8 e4m3: ~2 mantissa bits
    with pytest.raises(ValueError, match="unknown weight-quant mode"):
        resolve_quant_mode("int4")


# -- PostTrainingWeightQuantPass ------------------------------------------


def _fc_program(depth=2, width=16, seed=3):
    from paddle_tpu import layers
    from paddle_tpu.framework.program import Program, program_guard

    main, startup = Program(), Program()
    main.random_seed = seed
    with program_guard(main, startup):
        x = layers.data("x", [width])
        h = x
        for _ in range(depth):
            h = layers.fc(h, width, act="relu")
    return main, startup, h


def test_weight_quant_pass_flag_gated_end_to_end():
    """FLAGS_weight_quant rewrites matmul-family ops to dequant_matmul
    with int8 carriers + per-channel scales in scope; output stays
    close; flipping the flag back re-keys the cache and reproduces the
    float path BITWISE."""
    main, startup, h = _fc_program()
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    feed = {"x": np.random.RandomState(0).randn(4, 16).astype("f4")}
    base = np.asarray(exe.run(main, feed=feed, fetch_list=[h],
                              scope=scope)[0])
    n0 = stat_get("pass_weight_quant_ops")
    pt.set_flags({"FLAGS_weight_quant": "int8"})
    try:
        q = np.asarray(exe.run(main, feed=feed, fetch_list=[h],
                               scope=scope)[0])
    finally:
        pt.set_flags({"FLAGS_weight_quant": ""})
    assert stat_get("pass_weight_quant_ops") - n0 == 2
    assert scope.has_var("fc_0.w_0@WQ")
    assert scope.has_var("fc_0.w_0@WQ_SCALE")
    assert np.asarray(scope.get_var("fc_0.w_0@WQ")).dtype == np.int8
    assert np.abs(q - base).max() < 0.05 * max(np.abs(base).max(), 1.0)
    back = np.asarray(exe.run(main, feed=feed, fetch_list=[h],
                              scope=scope)[0])
    assert np.array_equal(back, base)


def test_weight_quant_mark_per_program_without_flag():
    from paddle_tpu.slim import mark_weight_quant

    main, startup, h = _fc_program(depth=1, seed=4)
    mark_weight_quant(main, "int8")
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    feed = {"x": np.ones((2, 16), "f4")}
    out = np.asarray(exe.run(main, feed=feed, fetch_list=[h],
                             scope=scope)[0])
    assert scope.has_var("fc_0.w_0@WQ")
    assert np.isfinite(out).all()
    with pytest.raises(ValueError, match="unknown weight-quant mode"):
        mark_weight_quant(main, "int3")


def test_weight_quant_resolves_through_amp_cast():
    """A weight consumed through an AMP-style cast is quantized at the
    source: the dequant lands at X's dtype and the orphaned cast is
    removed by DCE — the executable takes neither the f32 weight nor
    the cast output."""
    from paddle_tpu.framework import dtypes
    from paddle_tpu.framework.program import (Operator, Program,
                                              program_guard)
    from paddle_tpu import layers

    main, startup = Program(), Program()
    main.random_seed = 5
    with program_guard(main, startup):
        x = layers.data("x", [8])
        h = layers.fc(x, 8, bias_attr=False)
    block = main.global_block
    (op,) = [o for o in block.ops if o.type == "mul"]
    wname = op.input("Y")[0]
    cast_out = block.create_var(name=wname + ".cast", dtype="float32",
                                stop_gradient=False)
    block.ops.insert(
        block.ops.index(op),
        Operator(block, "cast", {"X": [wname]},
                 {"Out": [cast_out.name]},
                 {"out_dtype": dtypes.to_enum("float32")}))
    op._rename_input(wname, cast_out.name)
    main._bump()
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    feed = {"x": np.random.RandomState(1).randn(4, 8).astype("f4")}
    base = np.asarray(exe.run(main, feed=feed, fetch_list=[h],
                              scope=scope)[0])
    pt.set_flags({"FLAGS_weight_quant": "int8"})
    try:
        q = np.asarray(exe.run(main, feed=feed, fetch_list=[h],
                               scope=scope)[0])
    finally:
        pt.set_flags({"FLAGS_weight_quant": ""})
    assert scope.has_var(wname + "@WQ")
    assert np.abs(q - base).max() < 0.05 * max(np.abs(base).max(), 1.0)


def test_weight_quant_composes_with_layer_scan():
    """Isomorphic quantized layers still scan: the int8 carriers and
    their scales ride ONE stacked array each, and the scanned program
    is bitwise-equal to the unscanned quantized run."""
    main, startup, h = _fc_program(depth=6, width=32, seed=6)
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    feed = {"x": np.random.RandomState(2).randn(4, 32).astype("f4")}
    pt.set_flags({"FLAGS_weight_quant": "int8"})
    try:
        q_only = np.asarray(exe.run(main, feed=feed, fetch_list=[h],
                                    scope=scope)[0])
        pt.set_flags({"FLAGS_layer_scan": 1,
                      "FLAGS_layer_scan_min_layers": 4})
        try:
            q_scan = np.asarray(exe.run(main, feed=feed,
                                        fetch_list=[h], scope=scope)[0])
        finally:
            pt.set_flags({"FLAGS_layer_scan": 0})
    finally:
        pt.set_flags({"FLAGS_weight_quant": ""})
    assert stat_get("pass_layer_scan_segments") >= 1
    carrier = scope.get_var("@LAYER_STACK@fc_0.w_0@WQ")
    assert np.asarray(carrier).dtype == np.int8
    assert np.asarray(carrier).shape[0] == 6
    scale = scope.get_var("@LAYER_STACK@fc_0.w_0@WQ_SCALE")
    assert np.asarray(scale).shape == (6, 32)
    assert np.array_equal(q_scan, q_only)


def test_weight_quant_scale_inherits_tp_spec():
    """With a TPShardingPlan on the program, the carrier inherits the
    weight's spec and the scale inherits the sharded axis' entry."""
    from paddle_tpu.framework.passes import PassContext, TPShardingPlan
    from paddle_tpu.slim import PostTrainingWeightQuantPass

    main, startup, h = _fc_program(depth=1, seed=7)
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    main._tp_plan = TPShardingPlan(
        {"fc_0.w_0": (None, "mp")}, mp_degree=2)
    changed = PostTrainingWeightQuantPass(mode="int8").apply(
        main, PassContext(scope=scope))
    assert changed
    assert main._tp_plan.specs["fc_0.w_0@WQ"] == (None, "mp")
    assert main._tp_plan.specs["fc_0.w_0@WQ_SCALE"] == ("mp",)


# -- quantized KV cache ---------------------------------------------------


def test_kv_quant_cache_bytes_and_capacity_at_fixed_budget():
    """int8 pages + scale planes cost ~half the bf16 bytes, so a fixed
    pool byte budget holds ~2x the pages — and the page-count admission
    reservation turns that directly into slot capacity."""
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.serving.kv_cache import CacheConfig, PagedKVCache

    kw = dict(num_layers=2, num_heads=2, head_dim=32, num_slots=16,
              max_seq_len=64, page_size=8)
    bf16 = CacheConfig(num_pages=13, dtype="bfloat16", **kw)
    qcfg_probe = CacheConfig(num_pages=2, quantized=True, **kw)
    ratio = bf16.page_bytes() / qcfg_probe.page_bytes()
    assert 1.7 <= ratio <= 2.0  # head_dim 32: (2*32)/(32+4) = 1.78
    budget = bf16.cache_bytes()
    q_pages = budget // qcfg_probe.per_page_pool_bytes()
    qcfg = CacheConfig(num_pages=int(q_pages), quantized=True, **kw)
    assert qcfg.cache_bytes() <= budget

    def capacity(cfg):
        cache = PagedKVCache(cfg, Scope(), prefix_cache=False)
        n = 0
        while cache.claim(n, 16) is not None:  # 2 pages per claim
            n += 1
            if n >= cfg.num_slots:
                break
        return n

    cap_bf16 = capacity(bf16)
    cap_q = capacity(qcfg)
    assert cap_q >= 1.7 * cap_bf16, (cap_q, cap_bf16)


def test_kv_quant_decode_bitwise_vs_quantized_self_oracle(
        model_and_weights):
    """Decode-with-quantized-cache logits equal the quantized full
    recompute BITWISE at every step (the PR 10 oracle contract carried
    into the quantized representation), while the delta vs the FULL-
    PRECISION oracle stays small and measured."""
    from paddle_tpu.ops.quant_ops import quant_quality_delta

    eng = make_engine(model_and_weights).start()
    prompt = [1, 2, 3, 4, 5]
    try:
        r = eng.submit(prompt, max_new_tokens=6, record_logits=True)
        out = r.result(timeout=120)
        full, quant = [], []
        for t in range(len(out)):
            seq = prompt + out[:t]
            qo = eng.recompute_logits(seq, quantized=True)
            assert np.array_equal(qo, r.logits_trace[t]), (
                f"quantized cache diverged from its own quantized "
                f"recompute at step {t}")
            full.append(eng.recompute_logits(seq))
            quant.append(r.logits_trace[t])
    finally:
        eng.stop()
    eng._cache.debug_check()
    delta = quant_quality_delta(np.stack(quant), np.stack(full))
    assert delta["max_abs_logit_delta"] < 0.1
    assert delta["top1_agreement"] >= 0.8  # tiny random model; the
    # flagship-scale bound (>= 0.99) is enforced by bench_quant
    assert stat_get("quant_quality_top1_agreement_ppm") >= 800000


@pytest.mark.parametrize("path", [
    "prefix_hit", "chunked",
    # the spec leg is the compile-heaviest (two drafted engines); the
    # tier-1 chaos test already cycles spec rounds with kv_quant on,
    # so the bitwise pin rides the slow tier
    pytest.param("spec", marks=pytest.mark.slow)])
def test_kv_quant_composition_matrix_bitwise(model_and_weights, path):
    """The composition matrix: prefix-hit (+CoW), chunked prefill, and
    speculative decode each produce BITWISE the plain quantized run's
    tokens — per-position write-once scales make stored bytes
    order-independent, so no path can drift."""
    model, weights = model_and_weights
    prompt = [3, 1, 4, 1, 5]
    if path == "prefix_hit":
        eng = make_engine(model_and_weights).start()
        try:
            cow0 = stat_get("decode_cow_copies")
            out1 = eng.generate(prompt, max_new_tokens=6)
            out2 = eng.generate(prompt, max_new_tokens=6)
            st = eng.stats()
            assert out2 == out1
            assert stat_get("decode_prefill_skipped") > 0
            assert stat_get("decode_cow_copies") > cow0
        finally:
            eng.stop()
        eng._cache.debug_check()
        # stats + /metrics surface (piggybacked on this engine rather
        # than compiling another)
        assert st["kv_quant"] is True
        assert st["page_bytes"] == eng._cache.config.page_bytes()
        from paddle_tpu.observe.histogram import prometheus_text

        text = prometheus_text()
        for series in ("decode_kv_quant_enabled",
                       "decode_kv_page_bytes"):
            assert series in text, series
        return
    if path == "chunked":
        long_prompt = list(range(1, 28))

        def run(chunk):
            eng = make_engine(model_and_weights, prefix_cache=False,
                              prefill_chunk_pages=chunk).start()
            try:
                return eng.generate(long_prompt, max_new_tokens=5)
            finally:
                eng.stop()

        assert run(1) == run(0)
        return
    import jax

    draft = TransformerLM(vocab_size=VOCAB, d_model=16, num_layers=1,
                          num_heads=2, max_seq_len=256)
    dw = draft.init_weights(jax.random.PRNGKey(99))

    def run(spec_k):
        eng = make_engine(model_and_weights, prefix_cache=False,
                          spec_k=spec_k, draft=(draft, dw)).start()
        try:
            return eng.generate(prompt, max_new_tokens=10)
        finally:
            eng.stop()

    assert run(4) == run(0)


def test_kv_quant_debug_check_audits_scale_pools():
    """The extended audit, at cache level (no engine/compiles): writes
    stamp live scales, release resets freed planes; a non-finite scale
    or a freed page whose plane kept live values is a loud
    AssertionError."""
    import jax.numpy as jnp

    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.serving.kv_cache import (CacheConfig, K_PAGES_VAR,
                                             K_SCALES_VAR, PagedKVCache,
                                             write_token_layer)

    scope = Scope()
    cache = PagedKVCache(
        CacheConfig(1, 2, 8, num_slots=2, max_seq_len=16, page_size=4,
                    num_pages=6, quantized=True),
        scope, prefix_cache=False)
    assert cache.claim(0, 8) is not None
    # write one position the way a step would (quantize + scale stamp)
    pid, off = cache.write_coords(0)
    val = jnp.ones((1, 2, 8), jnp.float32)
    pages, scales = write_token_layer(
        scope.get_var(K_PAGES_VAR), scope.get_var(K_SCALES_VAR), 0,
        val, jnp.asarray([pid]), jnp.asarray([off]))
    scope.set_var(K_PAGES_VAR, pages)
    scope.set_var(K_SCALES_VAR, scales)
    cache.lengths[0] = 1
    cache.debug_check()  # live page with a live scale: balanced
    cache.release(0)     # frees the page -> its plane resets
    cache.debug_check()
    arr = scope.get_var(K_SCALES_VAR)
    # corrupt a FREE page's scale plane with a live-looking value
    free_pid = cache.allocator._free[0]
    scope.set_var(K_SCALES_VAR, arr.at[0, free_pid, 0, 0].set(0.5))
    with pytest.raises(AssertionError, match="skipped the reset"):
        cache.debug_check()
    scope.set_var(K_SCALES_VAR,
                  arr.at[0, free_pid, 0, 0].set(jnp.nan))
    with pytest.raises(AssertionError, match="non-finite"):
        cache.debug_check()


def test_kv_quant_pallas_interpret_matches_reference():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas_decode_attention import (
        paged_chunk_attention, paged_decode_attention)
    from paddle_tpu.serving.kv_cache import quantize_kv

    rs = np.random.RandomState(0)
    s, h, d, pool, page, pps = 3, 2, 16, 9, 8, 4
    kq, ks = quantize_kv(jnp.asarray(rs.randn(pool, page, h, d)
                                     .astype("f4")))
    vq, vs = quantize_kv(jnp.asarray(rs.randn(pool, page, h, d)
                                     .astype("f4")))
    table = jnp.asarray(rs.randint(1, pool, (s, pps)).astype("i4"))
    q = jnp.asarray(rs.randn(s, h, d).astype("f4"))
    lengths = jnp.asarray(np.array([5, 17, 32], "i4"))
    ref = paged_decode_attention(q, kq, vq, table, lengths,
                                 k_scales=ks, v_scales=vs,
                                 use_pallas="never")
    pal = paged_decode_attention(q, kq, vq, table, lengths,
                                 k_scales=ks, v_scales=vs,
                                 use_pallas="always", interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(pal),
                               rtol=1e-5, atol=1e-5)
    qr = jnp.asarray(rs.randn(s, 5, h, d).astype("f4"))
    rl = jnp.asarray(np.array([7, 0, 27], "i4")[:, None]
                     + np.arange(1, 6, dtype="i4")[None, :])
    ref = paged_chunk_attention(qr, kq, vq, table, rl, k_scales=ks,
                                v_scales=vs, use_pallas="never")
    pal = paged_chunk_attention(qr, kq, vq, table, rl, k_scales=ks,
                                v_scales=vs, use_pallas="always",
                                interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(pal),
                               rtol=1e-5, atol=1e-5)


