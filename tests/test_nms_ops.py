"""NMS / proposal / matching op parity vs numpy oracles.

Parity model: reference detection/multiclass_nms_op.cc (NMSFast +
MultiClassNMS), matrix_nms_op.cc, bipartite_match_op.cc,
generate_proposals_op.cc — the oracles below re-implement the
reference algorithms with plain loops; the lowerings must agree on the
VALID rows (padding tails are checked for the -1/zero convention).
"""
import numpy as np

from op_test import OpTest


def _iou(a, b, off):
    ax1, ay1, ax2, ay2 = a
    bx1, by1, bx2, by2 = b
    area_a = max(ax2 - ax1 + off, 0) * max(ay2 - ay1 + off, 0)
    area_b = max(bx2 - bx1 + off, 0) * max(by2 - by1 + off, 0)
    iw = max(min(ax2, bx2) - max(ax1, bx1) + off, 0)
    ih = max(min(ay2, by2) - max(ay1, by1) + off, 0)
    inter = iw * ih
    union = area_a + area_b - inter
    return inter / union if union > 0 else 0.0


def _nms_fast(boxes, scores, score_thr, nms_top_k, iou_thr, eta, off):
    """Reference NMSFast: returns kept original indices in score order."""
    idx = [i for i in np.argsort(-scores, kind="stable")
           if scores[i] > score_thr]
    if nms_top_k > 0:
        idx = idx[:nms_top_k]
    kept = []
    thr = iou_thr
    for i in idx:
        ok = all(_iou(boxes[i], boxes[j], off) <= thr for j in kept)
        if ok:
            kept.append(i)
            if eta < 1.0 and thr > 0.5:
                thr *= eta
    return kept


def _multiclass_nms_oracle(boxes, scores, background, score_thr,
                           nms_top_k, iou_thr, eta, keep_top_k,
                           normalized):
    off = 0.0 if normalized else 1.0
    dets = []
    for c in range(scores.shape[0]):
        if c == background:
            continue
        for i in _nms_fast(boxes, scores[c], score_thr, nms_top_k,
                           iou_thr, eta, off):
            dets.append((scores[c, i], c, i))
    dets.sort(key=lambda t: -t[0])
    if keep_top_k > 0:
        dets = dets[:keep_top_k]
    return dets  # (score, class, box index), sorted desc


class TestMulticlassNms(OpTest):
    op_type = "multiclass_nms2"

    def setup(self):
        rs = np.random.RandomState(3)
        M, C, KEEP = 12, 4, 6
        centers = rs.uniform(2, 18, (M, 2))
        wh = rs.uniform(1.5, 5, (M, 2))
        boxes = np.concatenate([centers - wh / 2, centers + wh / 2],
                               axis=1).astype("f4")
        scores = rs.uniform(0, 1, (C, M)).astype("f4")
        attrs = dict(background_label=0, score_threshold=0.25,
                     nms_top_k=10, nms_threshold=0.4, nms_eta=1.0,
                     keep_top_k=KEEP, normalized=True)
        dets = _multiclass_nms_oracle(boxes, scores, 0, 0.25, 10, 0.4,
                                      1.0, KEEP, True)
        out = np.zeros((1, KEEP, 6), "f4")
        index = np.full((1, KEEP), -1, np.int32)
        for k, (s, c, i) in enumerate(dets):
            out[0, k] = [c, s, *boxes[i]]
            index[0, k] = i
        out[0, len(dets):, 0] = -1
        self.inputs = {"BBoxes": [("b", boxes[None])],
                       "Scores": [("s", scores[None])]}
        self.attrs = attrs
        self.outputs = {"Out": [("out", out)],
                        "Index": [("idx", index)],
                        "NmsRoisNum": [("n", np.array([len(dets)],
                                                      np.int32))]}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestMulticlassNmsEta(TestMulticlassNms):
    """Adaptive eta < 1 decays the threshold after each kept box."""

    def setup(self):
        super().setup()
        rs = np.random.RandomState(5)
        boxes = np.asarray(self.inputs["BBoxes"][0][1][0])
        scores = np.asarray(self.inputs["Scores"][0][1][0])
        KEEP = 6
        attrs = dict(self.attrs, nms_eta=0.9, nms_threshold=0.7)
        dets = _multiclass_nms_oracle(boxes, scores, 0, 0.25, 10, 0.7,
                                      0.9, KEEP, True)
        out = np.zeros((1, KEEP, 6), "f4")
        index = np.full((1, KEEP), -1, np.int32)
        for k, (s, c, i) in enumerate(dets):
            out[0, k] = [c, s, *boxes[i]]
            index[0, k] = i
        out[0, len(dets):, 0] = -1
        self.attrs = attrs
        self.outputs = {"Out": [("out", out)],
                        "Index": [("idx", index)],
                        "NmsRoisNum": [("n", np.array([len(dets)],
                                                      np.int32))]}


class TestMulticlassNmsEtaAdversarial(OpTest):
    """Deterministic candidate-time-threshold case: IoU 0.66 boxes with
    thr 0.7 decayed to 0.63 by eta=0.9 after the first keep — the second
    box MUST be suppressed (keeper-time evaluation would keep it)."""
    op_type = "multiclass_nms2"

    def setup(self):
        boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 6.6]], "f4")
        scores = np.array([[0.0, 0.0], [0.9, 0.8]], "f4")  # class 0 = bg
        KEEP = 2
        attrs = dict(background_label=0, score_threshold=0.1,
                     nms_top_k=2, nms_threshold=0.7, nms_eta=0.9,
                     keep_top_k=KEEP, normalized=True)
        dets = _multiclass_nms_oracle(boxes, scores, 0, 0.1, 2, 0.7,
                                      0.9, KEEP, True)
        assert len(dets) == 1, dets  # oracle itself keeps only box 0
        out = np.zeros((1, KEEP, 6), "f4")
        index = np.full((1, KEEP), -1, np.int32)
        for k, (s, c, i) in enumerate(dets):
            out[0, k] = [c, s, *boxes[i]]
            index[0, k] = i
        out[0, len(dets):, 0] = -1
        self.inputs = {"BBoxes": [("b", boxes[None])],
                       "Scores": [("s", scores[None])]}
        self.attrs = attrs
        self.outputs = {"Out": [("out", out)],
                        "Index": [("idx", index)],
                        "NmsRoisNum": [("n", np.array([1], np.int32))]}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestMatrixNms(OpTest):
    op_type = "matrix_nms"

    def setup(self):
        rs = np.random.RandomState(7)
        M, C, KEEP = 10, 3, 8
        centers = rs.uniform(2, 18, (M, 2))
        wh = rs.uniform(2, 6, (M, 2))
        boxes = np.concatenate([centers - wh / 2, centers + wh / 2],
                               axis=1).astype("f4")
        scores = rs.uniform(0, 1, (C, M)).astype("f4")
        sthr, pthr, topk = 0.2, 0.3, 8

        dets = []
        for c in range(C):
            if c == 0:  # background
                continue
            idx = [i for i in np.argsort(-scores[c], kind="stable")
                   if scores[c, i] > sthr][:topk]
            srt = [scores[c, i] for i in idx]
            n = len(idx)
            ious = np.zeros((n, n))
            for a in range(n):
                for b in range(a):
                    ious[a, b] = _iou(boxes[idx[a]], boxes[idx[b]], 0.0)
            comp = np.array([ious[i, :i].max() if i else 0.0
                             for i in range(n)])
            for j in range(n):
                decay = 1.0
                for i in range(j):
                    decay = min(decay,
                                (1 - ious[j, i]) / (1 - comp[i]))
                ds = srt[j] * decay
                if ds > pthr:
                    dets.append((ds, c, idx[j]))
        dets.sort(key=lambda t: -t[0])
        dets = dets[:KEEP]
        out = np.zeros((1, KEEP, 6), "f4")
        index = np.full((1, KEEP), -1, np.int32)
        for k, (s, c, i) in enumerate(dets):
            out[0, k] = [c, s, *boxes[i]]
            index[0, k] = i
        out[0, len(dets):, 0] = -1
        self.inputs = {"BBoxes": [("b", boxes[None])],
                       "Scores": [("s", scores[None])]}
        self.attrs = dict(background_label=0, score_threshold=sthr,
                          post_threshold=pthr, nms_top_k=topk,
                          keep_top_k=KEEP, use_gaussian=False,
                          gaussian_sigma=2.0, normalized=True)
        self.outputs = {"Out": [("out", out)],
                        "Index": [("idx", index)],
                        "RoisNum": [("n", np.array([len(dets)],
                                                   np.int32))]}

    def test_output(self):
        self.check_output(atol=1e-4)


class TestBipartiteMatch(OpTest):
    op_type = "bipartite_match"

    def setup(self):
        dist = np.array([[[0.5, 0.9, 0.3],
                          [0.7, 0.2, 0.8]]], "f4")  # [1, 2 rows, 3 cols]
        # greedy: max 0.9 -> col1=row0; mask row0/col1; max 0.8 ->
        # col2=row1; no rows left -> col0 unmatched
        idx = np.array([[-1, 0, 1]], np.int32)
        val = np.array([[0.0, 0.9, 0.8]], "f4")
        self.inputs = {"DistMat": [("d", dist)]}
        self.attrs = {"match_type": "bipartite"}
        self.outputs = {"ColToRowMatchIndices": [("i", idx)],
                        "ColToRowMatchDist": [("v", val)]}

    def test_output(self):
        self.check_output()


class TestBipartiteMatchPerPrediction(OpTest):
    op_type = "bipartite_match"

    def setup(self):
        dist = np.array([[[0.5, 0.9, 0.3],
                          [0.7, 0.2, 0.8]]], "f4")
        # bipartite pass as above; per_prediction fills col0 with its
        # argmax row 1 (0.7 >= 0.6)
        idx = np.array([[1, 0, 1]], np.int32)
        val = np.array([[0.7, 0.9, 0.8]], "f4")
        self.inputs = {"DistMat": [("d", dist)]}
        self.attrs = {"match_type": "per_prediction",
                      "dist_threshold": 0.6}
        self.outputs = {"ColToRowMatchIndices": [("i", idx)],
                        "ColToRowMatchDist": [("v", val)]}

    def test_output(self):
        self.check_output()


class TestGenerateProposals(OpTest):
    op_type = "generate_proposals"

    def setup(self):
        rs = np.random.RandomState(11)
        A, H, W = 3, 4, 4
        N = A * H * W
        POST = 8
        scores = rs.uniform(0, 1, (1, A, H, W)).astype("f4")
        deltas = (rs.randn(1, 4 * A, H, W) * 0.2).astype("f4")
        im_info = np.array([[40.0, 40.0, 1.0]], "f4")
        # anchors laid out [H, W, A, 4]
        anchors = np.zeros((H, W, A, 4), "f4")
        for y in range(H):
            for x in range(W):
                for a in range(A):
                    size = 6 + 4 * a
                    cx, cy = x * 10 + 5, y * 10 + 5
                    anchors[y, x, a] = [cx - size / 2, cy - size / 2,
                                        cx + size / 2, cy + size / 2]
        variances = np.full((H, W, A, 4), 0.5, "f4")

        # oracle
        sc = scores[0].transpose(1, 2, 0).reshape(N)
        dl = deltas[0].reshape(A, 4, H, W).transpose(2, 3, 0, 1) \
            .reshape(N, 4)
        anc = anchors.reshape(N, 4)
        var = variances.reshape(N, 4)
        order = np.argsort(-sc, kind="stable")
        props, vals = [], []
        for i in order:
            aw = anc[i, 2] - anc[i, 0] + 1
            ah = anc[i, 3] - anc[i, 1] + 1
            acx, acy = anc[i, 0] + aw / 2, anc[i, 1] + ah / 2
            clipv = np.log(1000.0 / 16.0)
            cx = var[i, 0] * dl[i, 0] * aw + acx
            cy = var[i, 1] * dl[i, 1] * ah + acy
            w = np.exp(min(var[i, 2] * dl[i, 2], clipv)) * aw
            h = np.exp(min(var[i, 3] * dl[i, 3], clipv)) * ah
            box = [cx - w / 2, cy - h / 2, cx + w / 2 - 1, cy + h / 2 - 1]
            box = [np.clip(box[0], 0, 39), np.clip(box[1], 0, 39),
                   np.clip(box[2], 0, 39), np.clip(box[3], 0, 39)]
            bw, bh = box[2] - box[0] + 1, box[3] - box[1] + 1
            if bw >= 3.0 and bh >= 3.0:
                props.append(box)
                vals.append(sc[i])
        kept = _nms_fast(np.array(props), np.array(vals), -1e9, -1, 0.6,
                         1.0, 1.0)[:POST]
        rois = np.zeros((1, POST, 4), "f4")
        probs = np.zeros((1, POST, 1), "f4")
        for k, i in enumerate(kept):
            rois[0, k] = props[i]
            probs[0, k, 0] = vals[i]
        self.inputs = {"Scores": [("s", scores)],
                       "BboxDeltas": [("d", deltas)],
                       "ImInfo": [("ii", im_info)],
                       "Anchors": [("a", anchors)],
                       "Variances": [("v", variances)]}
        self.attrs = {"pre_nms_topN": N, "post_nms_topN": POST,
                      "nms_thresh": 0.6, "min_size": 3.0, "eta": 1.0}
        self.outputs = {"RpnRois": [("r", rois)],
                        "RpnRoiProbs": [("p", probs)],
                        "RpnRoisNum": [("n", np.array([len(kept)],
                                                      np.int32))]}

    def test_output(self):
        self.check_output(atol=1e-4)


def test_ssd_head_end_to_end():
    """Detector head through the public API: prior_box -> box_coder ->
    multiclass_nms over a conv feature, on the Executor."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.layer_helper import LayerHelper
    from paddle_tpu.framework.program import Program, program_guard

    rs = np.random.RandomState(0)
    main, startup = Program(), Program()
    with program_guard(main, startup):
        feat = layers.data("feat", [8, 4, 4])     # [B, C, H, W]
        img = layers.data("img", [3, 32, 32])
        loc = layers.data("loc", [48, 4])         # predicted offsets
        conf = layers.data("conf", [3, 48])       # class scores
        h = LayerHelper("ssd")
        pb = h.create_variable_for_type_inference()
        pbv = h.create_variable_for_type_inference()
        h.append_op("prior_box", {"Input": [feat.name], "Image": [img.name]},
                    {"Boxes": [pb.name], "Variances": [pbv.name]},
                    {"min_sizes": [4.0], "aspect_ratios": [1.0, 2.0],
                     "variances": [0.1, 0.1, 0.2, 0.2], "flip": True,
                     "clip": True})
        # prior_box gives [H, W, n_prior, 4] = [4, 4, 3, 4] -> 48 boxes
        pb2 = layers.reshape(pb, [-1, 4])
        pbv2 = layers.reshape(pbv, [-1, 4])
        dec = h.create_variable_for_type_inference()
        h.append_op("box_coder",
                    {"PriorBox": [pb2.name], "PriorBoxVar": [pbv2.name],
                     "TargetBox": [loc.name]},
                    {"OutputBox": [dec.name]},
                    {"code_type": "decode_center_size", "axis": 0,
                     "box_normalized": True})
        out = h.create_variable_for_type_inference()
        idx = h.create_variable_for_type_inference()
        cnt = h.create_variable_for_type_inference()
        h.append_op("multiclass_nms2",
                    {"BBoxes": [dec.name], "Scores": [conf.name]},
                    {"Out": [out.name], "Index": [idx.name],
                     "NmsRoisNum": [cnt.name]},
                    {"background_label": 0, "score_threshold": 0.3,
                     "nms_top_k": 16, "nms_threshold": 0.45,
                     "keep_top_k": 10, "normalized": True})
    exe = pt.Executor(pt.CPUPlace())
    res = exe.run(main, feed={
        "feat": rs.randn(1, 8, 4, 4).astype("f4"),
        "img": rs.randn(1, 3, 32, 32).astype("f4"),
        "loc": (rs.randn(48, 4) * 0.1).astype("f4"),
        "conf": rs.uniform(0, 1, (3, 48)).astype("f4"),
    }, fetch_list=[out, idx, cnt])
    o, ix, n = (np.asarray(v) for v in res)
    n = int(n.reshape(-1)[0])
    assert o.shape == (10, 6) or o.shape == (1, 10, 6)
    o = o.reshape(-1, 6)
    ix = ix.reshape(-1)
    assert 0 < n <= 10
    # valid rows first: class >= 1, scores above threshold and sorted
    assert (o[:n, 0] >= 1).all()
    assert (o[:n, 1] > 0.3).all()
    assert (np.diff(o[:n, 1]) <= 1e-6).all()
    assert (ix[:n] >= 0).all()
    # padding rows carry the -1 class marker
    assert (o[n:, 0] == -1).all()
