"""fused_multihead_attention: numpy-oracle parity + gradient flow.

Reference parity: operators/fused/multihead_matmul_op.cu (the fused
transformer attention path).
"""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.backward import append_backward
from paddle_tpu.framework.program import Program, program_guard

B, S, H, NH = 2, 8, 16, 4


def _oracle(q, k, v, bias, n_heads):
    b, s, hidden = q.shape
    d = hidden // n_heads

    def heads(x):
        return x.reshape(b, s, n_heads, d).transpose(0, 2, 1, 3)

    qh, kh, vh = heads(q), heads(k), heads(v)
    scores = np.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(d)
    if bias is not None:
        scores = scores + bias
    scores = scores - scores.max(-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(-1, keepdims=True)
    out = np.einsum("bhqk,bhkd->bhqd", p, vh)
    return out.transpose(0, 2, 1, 3).reshape(b, s, hidden)


def test_fused_attention_matches_numpy_and_grads_flow():
    main, startup = Program(), Program()
    with program_guard(main, startup):
        q = layers.data("q", [B, S, H], append_batch_size=False)
        k = layers.data("k", [B, S, H], append_batch_size=False)
        v = layers.data("v", [B, S, H], append_batch_size=False)
        mask = layers.data("mask", [B, 1, 1, S], append_batch_size=False)
        for t in (q, k, v):
            t.stop_gradient = False
        out = layers.fused_multihead_attention(q, k, v, num_heads=NH,
                                               bias_qk=mask)
        loss = layers.mean(out)
        append_backward(loss)
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    qv = rng.randn(B, S, H).astype("float32")
    kv = rng.randn(B, S, H).astype("float32")
    vv = rng.randn(B, S, H).astype("float32")
    bias = np.zeros((B, 1, 1, S), "float32")
    bias[0, 0, 0, -2:] = -1e4  # mask the last two keys of batch 0
    got, dq = exe.run(
        main, feed={"q": qv, "k": kv, "v": vv, "mask": bias},
        fetch_list=[out, "q@GRAD"], scope=scope)
    want = _oracle(qv, kv, vv, bias, NH)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-5)
    dq = np.asarray(dq)
    assert dq.shape == (B, S, H) and np.any(dq != 0.0)


def test_bert_builder_fused_matches_unfused():
    """Same weights (shared startup seeds won't match across builds), so
    compare structurally: the fused program must produce a finite loss
    and strictly fewer ops than the unfused chain."""
    from paddle_tpu.text import bert_base_pretrain_program

    m1, *_ = bert_base_pretrain_program(
        batch_size=2, seq_len=8, vocab_size=32, hidden=16, n_layers=1,
        n_heads=4, ffn_size=32, dropout_prob=0.0, max_preds_per_seq=2,
        use_fused_attention=True)
    m2, *_ = bert_base_pretrain_program(
        batch_size=2, seq_len=8, vocab_size=32, hidden=16, n_layers=1,
        n_heads=4, ffn_size=32, dropout_prob=0.0, max_preds_per_seq=2,
        use_fused_attention=False)
    n1 = len(m1.global_block.ops)
    n2 = len(m2.global_block.ops)
    assert n1 < n2
    assert any(op.type == "fused_multihead_attention"
               for op in m1.global_block.ops)
