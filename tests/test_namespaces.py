"""2.0 namespace surface: paddle.static / paddle.jit / paddle.text /
paddle.distribution mirror the reference layout.

Reference parity: python/paddle/static/__init__.py __all__,
python/paddle/distribution.py, python/paddle/text/.
"""
import numpy as np
import pytest

import paddle_tpu as pt


def test_static_namespace_surface():
    import paddle_tpu.static as static

    for name in ["Executor", "Program", "program_guard", "data", "InputSpec",
                 "save_inference_model", "load_inference_model",
                 "append_backward", "gradients", "BuildStrategy",
                 "CompiledProgram", "ExecutionStrategy", "scope_guard",
                 "global_scope", "default_main_program",
                 "default_startup_program", "cpu_places", "name_scope",
                 "py_func", "nn"]:
        assert hasattr(static, name), name


def test_static_trains_through_namespace():
    import paddle_tpu.static as static

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4])
        y = static.nn.fc(x, 2)
        loss = static.nn.mean(y)
        static.append_backward(loss)
    exe = static.Executor(pt.CPUPlace())
    scope = static.Scope()
    exe.run(startup, scope=scope)
    out = exe.run(main, feed={"x": np.ones((2, 4), "f4")},
                  fetch_list=[loss], scope=scope)
    assert np.isfinite(out[0]).all()


def test_compiled_program_duck_types():
    import paddle_tpu.static as static

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4])
        y = static.nn.fc(x, 2)
    cp = static.CompiledProgram(main).with_data_parallel(loss_name=None)
    exe = static.Executor(pt.CPUPlace())
    scope = static.Scope()
    exe.run(startup, scope=scope)
    out = exe.run(cp._program, feed={"x": np.ones((2, 4), "f4")},
                  fetch_list=[y], scope=scope)
    assert out[0].shape == (2, 2)


def test_distribution_normal_uniform_categorical():
    from paddle_tpu.distribution import Categorical, Normal, Uniform

    n = Normal(0.0, 1.0)
    lp = np.asarray(n.log_prob(0.0).numpy())
    np.testing.assert_allclose(lp, -0.5 * np.log(2 * np.pi), rtol=1e-5)
    ent = np.asarray(n.entropy().numpy())
    np.testing.assert_allclose(ent, 0.5 + 0.5 * np.log(2 * np.pi), rtol=1e-5)
    n2 = Normal(1.0, 2.0)
    kl = np.asarray(n.kl_divergence(n2).numpy())
    want = 0.5 * (0.25 + 0.25 - 1 - np.log(0.25))
    np.testing.assert_allclose(kl, want, rtol=1e-5)

    u = Uniform(0.0, 2.0)
    np.testing.assert_allclose(np.asarray(u.log_prob(1.0).numpy()),
                               -np.log(2.0), rtol=1e-6)
    s = u.sample([100], seed=7)
    sv = np.asarray(s.numpy())
    assert (sv >= 0).all() and (sv < 2).all()

    c = Categorical(np.log(np.array([0.2, 0.3, 0.5], "f4")))
    np.testing.assert_allclose(np.asarray(c.log_prob(np.array([2])).numpy()),
                               [np.log(0.5)], rtol=1e-5)
    ent = np.asarray(c.entropy().numpy())
    want = -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5))
    np.testing.assert_allclose(ent, want, rtol=1e-4)


def test_text_datasets_offline_contract(tmp_path):
    from paddle_tpu.text.datasets import Imdb, UCIHousing

    with pytest.raises(RuntimeError, match="egress"):
        UCIHousing(data_file=None)
    with pytest.raises(FileNotFoundError):
        Imdb(data_file=str(tmp_path / "nope.tgz"))
    # real parse path on a synthetic housing file (reference format:
    # whitespace-separated rows of 14 floats)
    rows = np.random.RandomState(0).rand(50, 14).astype("f4")
    f = tmp_path / "housing.data"
    np.savetxt(f, rows)
    ds = UCIHousing(data_file=str(f), mode="train")
    assert len(ds) == 40
    feat, lbl = ds[0]
    assert feat.shape == (13,) and lbl.shape == (1,)
    ds_test = UCIHousing(data_file=str(f), mode="test")
    assert len(ds_test) == 10


def test_py_func_static():
    import paddle_tpu.static as static

    def double_it(x):
        return (np.asarray(x) * 2.0).astype("f4")

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [3])
        out = main.global_block.create_var(name="pf_out", shape=[-1, 3],
                                           dtype="float32")
        static.py_func(double_it, x, out)
    exe = static.Executor(pt.CPUPlace())
    scope = static.Scope()
    exe.run(startup, scope=scope)
    res = exe.run(main, feed={"x": np.ones((2, 3), "f4")},
                  fetch_list=[out], scope=scope)
    np.testing.assert_allclose(np.asarray(res[0]), 2.0 * np.ones((2, 3)))
