"""Native MultiSlot data feed: C++ parser vs python fallback parity.

Reference parity: framework/data_feed.cc MultiSlotDataFeed — count-
prefixed float/uint64 slots per line, LoD level-0 offsets.
"""
import numpy as np

from paddle_tpu import native
from paddle_tpu.io.data_feed import MultiSlotDataFeed

DATA = (
    b"2 11 12 1 0.5 3 1.0 2.0 3.0\n"
    b"1 99 1 -0.25 2 4.0 5.0\n"
    b"\n"
    b"3 7 8 9 1 2.5 1 6.0\n"
)
TYPES = "uff"


def test_extension_builds_and_loads():
    assert native.has_native(), "C++ extension failed to build/load"


def test_parse_matches_python_fallback():
    n_c, out_c = native.parse_multislot(DATA, TYPES)
    n_p, out_p = native._parse_multislot_py(DATA, TYPES)
    assert n_c == n_p == 3
    for (vc, lc), (vp, lp) in zip(out_c, out_p):
        np.testing.assert_array_equal(vc, vp)
        np.testing.assert_array_equal(lc, lp)
        assert vc.dtype == vp.dtype


def test_parse_values_and_lod():
    n, out = native.parse_multislot(DATA, TYPES)
    ids, ids_lod = out[0]
    np.testing.assert_array_equal(ids, np.array([11, 12, 99, 7, 8, 9],
                                                np.uint64))
    np.testing.assert_array_equal(ids_lod, [0, 2, 3, 6])
    f1, f1_lod = out[1]
    np.testing.assert_allclose(f1, [0.5, -0.25, 2.5])
    np.testing.assert_array_equal(f1_lod, [0, 1, 2, 3])
    f2, f2_lod = out[2]
    np.testing.assert_allclose(f2, [1.0, 2.0, 3.0, 4.0, 5.0, 6.0])
    np.testing.assert_array_equal(f2_lod, [0, 3, 5, 6])


def test_malformed_input_raises():
    import pytest

    with pytest.raises(ValueError, match="line"):
        native.parse_multislot(b"2 1\n", "u")  # count says 2, one value
    with pytest.raises(ValueError, match="trailing"):
        native.parse_multislot(b"1 5 9\n", "u")  # extra token
    # a short line must NOT steal tokens from the next line
    with pytest.raises(ValueError):
        native.parse_multislot(b"1 5\n1 6 1 7\n", "uu")
    # partial-token consumption: "3.5" must not parse as count 3
    with pytest.raises(ValueError):
        native.parse_multislot(b"3.5 1 2 3\n", "u")
    with pytest.raises(ValueError):
        native.parse_multislot(b"1 2.5\n", "u")  # float token in id slot
    # hex floats, uint64 overflow: rejected by BOTH paths (strtof/strtoull
    # would accept/saturate where python errors — parity means both error)
    for fn in (native.parse_multislot, native._parse_multislot_py):
        with pytest.raises(ValueError):
            fn(b"1 0x10\n", "f")
        with pytest.raises(ValueError):
            fn(b"1 18446744073709551616\n", "u")
    # negative ids wrap into uint64 identically in both paths
    for fn in (native.parse_multislot, native._parse_multislot_py):
        _, out = fn(b"1 -5\n", "u")
        assert int(out[0][0][0]) == 2 ** 64 - 5
    # python fallback raises identically
    with pytest.raises(ValueError, match="line"):
        native._parse_multislot_py(b"2 1\n", "u")
    with pytest.raises(ValueError):
        native._parse_multislot_py(b"3.5 1 2 3\n", "u")
    with pytest.raises(ValueError):
        native._parse_multislot_py(b"1 2.5\n", "u")
    with pytest.raises(ValueError, match="trailing"):
        native._parse_multislot_py(b"1 5 9\n", "u")
    with pytest.raises(ValueError):
        native._parse_multislot_py(b"1 5\n1 6 1 7\n", "uu")


def test_buffer_slice_is_bounded():
    """A memoryview slice must not be read past its logical end."""
    n, out = native.parse_multislot(memoryview(b"1 2 extra")[:4], "u")
    assert n == 1
    np.testing.assert_array_equal(out[0][0], np.array([2], np.uint64))


def test_data_feed_batches(tmp_path):
    # 5 instances, 2 slots: ragged ids + declared-dense float (dim 2);
    # batch_size 2 -> two full batches plus the partial tail batch
    lines = []
    for i in range(5):
        ids = " ".join(str(10 * i + j) for j in range(i + 1))
        lines.append(f"{i + 1} {ids} 2 {i}.0 {i}.5")
    p = tmp_path / "part-0"
    p.write_text("\n".join(lines) + "\n")

    feed = MultiSlotDataFeed([("ids", "u"), ("dense", "f", 2)],
                             batch_size=2)
    batches = list(feed.read_file(str(p)))
    assert len(batches) == 3  # tail batch kept (no silent drop)
    v, lod = batches[0]["dense"]
    assert v.shape == (2, 2)  # declared dim -> deterministic shape
    np.testing.assert_allclose(v, [[0.0, 0.5], [1.0, 1.5]])
    ids_v, ids_lod = batches[1]["ids"]
    np.testing.assert_array_equal(ids_lod, [0, 3, 7])
    np.testing.assert_array_equal(
        ids_v, np.array([20, 21, 22, 30, 31, 32, 33], np.uint64))
    # ragged slot stays flat + lod even when a batch is uniform
    b0_ids, b0_lod = batches[0]["ids"]
    assert b0_ids.ndim == 1
    tail_v, _ = batches[2]["dense"]
    assert tail_v.shape == (1, 2)


def test_native_speedup_smoke():
    """Not a perf assertion — just exercise a larger buffer through the
    native path end-to-end."""
    rs = np.random.RandomState(0)
    lines = []
    for _ in range(2000):
        n = rs.randint(1, 20)
        ids = " ".join(str(x) for x in rs.randint(0, 1 << 40, n))
        lines.append(f"{n} {ids} 1 {rs.rand():.6f}")
    data = ("\n".join(lines) + "\n").encode()
    n, out = native.parse_multislot(data, "uf")
    assert n == 2000
    assert out[1][0].shape == (2000,)
