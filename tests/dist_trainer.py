"""Multi-process trainer workload for the subprocess loss-parity oracle.

Role parity: reference dist_mnist.py-style workloads driven by
test_dist_base.py — a deterministic small model whose per-step losses the
parent compares against a single-process run.  Each rank feeds ITS shard
of the deterministic global batch (trainer-local data, reference
semantics); the loss fetch is the cross-replica mean, so ranks print
identical full-batch losses.

Invoked by paddle_tpu.distributed.launch with the fleet env contract set;
writes one JSON line {"rank": r, "losses": [...]} to --out-<rank>.json.
"""
import json
import os
import sys


def build_model(use_fleet, strategy=None):
    """Shared between ranks and the parent's single-process oracle — the
    parity assertion is only meaningful if both run THIS model."""
    from paddle_tpu import layers
    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.optimizer import MomentumOptimizer
    from paddle_tpu.param_attr import ParamAttr

    main_p, startup = Program(), Program()
    main_p.random_seed = 1
    with program_guard(main_p, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1])
        h = layers.fc(x, 16, act="relu", param_attr=ParamAttr(
            initializer=ConstantInitializer(0.1)), bias_attr=False)
        pred = layers.fc(h, 1, param_attr=ParamAttr(
            initializer=ConstantInitializer(0.2)), bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = MomentumOptimizer(0.05, 0.9)
        if use_fleet:
            from paddle_tpu.distributed import fleet

            fleet.init(is_collective=True, strategy=strategy)
            fleet.distributed_optimizer(opt)
            fleet.minimize(loss)
        else:
            opt.minimize(loss)
    return main_p, startup, loss


def make_batch():
    import numpy as np

    rs = np.random.RandomState(0)
    return rs.randn(32, 8).astype("f4"), rs.randn(32, 1).astype("f4")


def run_dygraph(out_path, steps):
    """Dygraph DataParallel over real processes (reference
    TestParallelDyGraphRunnerBase oracle, test_dist_base.py:379):
    scale_loss + apply_collective_grads across ranks."""
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.distributed.parallel import DataParallel
    from paddle_tpu.distributed.parallel_env import init_parallel_env
    from paddle_tpu.dygraph.tensor import Tensor
    from paddle_tpu import nn

    init_parallel_env()
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))

    net = nn.Linear(8, 1, bias_attr=False)
    # deterministic init shared by every rank and the oracle
    net.weight._set_raw(jnp.asarray(np.full((8, 1), 0.1, "f4")))
    model = DataParallel(net)

    X, Y = make_batch()
    per = len(X) // nranks
    Xl = X[rank * per:(rank + 1) * per]
    Yl = Y[rank * per:(rank + 1) * per]

    losses = []
    lr = 0.05
    for _ in range(steps):
        pred = model(Tensor(Xl))
        diff = pred - Tensor(Yl)
        loss = pt.tensor.math.mean(diff * diff)
        scaled = model.scale_loss(loss)
        scaled.backward()
        model.apply_collective_grads()
        # manual SGD (keeps the oracle trivial)
        w = net.weight
        w._set_raw(w._value - lr * w.grad._value)
        w.grad = None
        # every rank reports the FULL-batch loss: mean of local losses
        from jax.experimental import multihost_utils

        all_losses = multihost_utils.process_allgather(
            np.asarray(loss._value))
        losses.append(float(np.mean(all_losses)))

    with open(out_path, "w") as f:
        json.dump({"rank": rank, "losses": losses}, f)


def main():
    # CPU backend must be forced through live config: the container's
    # sitecustomize imports jax (axon TPU plugin) before this runs
    import jax

    if os.environ.get("PADDLE_TPU_TEST_CPU", "1") == "1":
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.parallel_env import init_parallel_env

    out_path = sys.argv[1]
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    if os.environ.get("PADDLE_TPU_TEST_DYGRAPH") == "1":
        run_dygraph(out_path, steps)
        return
    localsgd = os.environ.get("PADDLE_TPU_TEST_LOCALSGD") == "1"

    mesh = init_parallel_env()
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))

    strategy = DistributedStrategy()
    if localsgd:
        strategy.localsgd = True
        strategy.localsgd_configs = {"k_steps": 2}
    if os.environ.get("PADDLE_TPU_TEST_SHARDING") == "1":
        strategy.sharding = True
    main_p, startup, loss = build_model(use_fleet=True, strategy=strategy)

    # deterministic global batch, shard by rank (trainer-local data)
    X, Y = make_batch()
    per = len(X) // nranks
    Xl, Yl = X[rank * per:(rank + 1) * per], Y[rank * per:(rank + 1) * per]

    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    losses = []
    for _ in range(steps):
        out = exe.run(main_p, feed={"x": Xl, "y": Yl}, fetch_list=[loss],
                      scope=scope)
        losses.append(float(np.asarray(out[0]).ravel()[0]))

    with open(out_path, "w") as f:
        json.dump({"rank": rank, "losses": losses}, f)


if __name__ == "__main__":
    main()
