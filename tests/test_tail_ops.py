"""Op-tail parity vs numpy oracles: CRF, spectral_norm, pool3d-with-
index, psroi/prroi pooling, padded select family, sequence_scatter.

Parity model: reference linear_chain_crf_op.h ForwardOneSequence,
crf_decoding_op.h Decode, spectral_norm_op.h, pool_with_index_op.cc,
psroi_pool_op.h, index_sample_op.cc, masked_select_op.cc,
where_index_op.cc, sequence_scatter_op.cc.
"""
import itertools

import numpy as np

from op_test import OpTest


class TestIndexSample(OpTest):
    op_type = "index_sample"

    def setup(self):
        rs = np.random.RandomState(0)
        x = rs.randn(3, 8).astype("f4")
        idx = rs.randint(0, 8, (3, 4)).astype("i4")
        out = np.take_along_axis(x, idx, axis=1)
        self.inputs = {"X": [("x", x)], "Index": [("i", idx)]}
        self.outputs = {"Out": [("o", out)]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"], "Out", max_relative_error=0.01)


class TestMaskedSelect(OpTest):
    op_type = "masked_select"

    def setup(self):
        rs = np.random.RandomState(1)
        x = rs.randn(3, 4).astype("f4")
        mask = rs.rand(3, 4) > 0.5
        flat = x.ravel()
        sel = flat[mask.ravel()]
        y = np.zeros(12, "f4")
        y[:sel.size] = sel
        self.inputs = {"X": [("x", x)], "Mask": [("m", mask)]}
        self.outputs = {"Y": [("y", y)],
                        "Count": [("c", np.int32(sel.size))]}

    def test_output(self):
        self.check_output()


class TestWhereIndex(OpTest):
    op_type = "where_index"

    def setup(self):
        cond = np.array([[True, False, True], [False, True, False]])
        out = np.full((6, 2), -1, np.int32)
        coords = np.argwhere(cond)
        out[:coords.shape[0]] = coords
        self.inputs = {"Condition": [("c", cond)]}
        self.outputs = {"Out": [("o", out)],
                        "Count": [("n", np.int32(coords.shape[0]))]}

    def test_output(self):
        self.check_output()


class TestSequenceScatter(OpTest):
    op_type = "sequence_scatter"

    def setup(self):
        x = np.zeros(6, "f4")
        ids = np.array([1, 3, 3, 5], np.int32)
        upd = np.array([1.0, 2.0, 4.0, 8.0], "f4")
        out = x.copy()
        np.add.at(out, ids, upd)
        self.inputs = {"X": [("x", x)], "Ids": [("i", ids)],
                       "Updates": [("u", upd)]}
        self.outputs = {"Out": [("o", out)]}

    def test_output(self):
        self.check_output()


class TestSpectralNorm(OpTest):
    op_type = "spectral_norm"

    def setup(self):
        rs = np.random.RandomState(3)
        w = rs.randn(4, 6).astype("f4")
        u = rs.randn(4).astype("f4")
        v = rs.randn(6).astype("f4")
        iters, eps = 3, 1e-12
        uu, vv = u.copy(), v.copy()
        for _ in range(iters):
            vv = w.T @ uu
            vv /= np.linalg.norm(vv) + eps
            uu = w @ vv
            uu /= np.linalg.norm(uu) + eps
        sigma = uu @ w @ vv
        self.inputs = {"Weight": [("w", w)], "U": [("u", u)],
                       "V": [("v", v)]}
        self.attrs = {"dim": 0, "power_iters": iters, "eps": eps}
        self.outputs = {"Out": [("o", w / sigma)]}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestMaxPool3dWithIndex(OpTest):
    op_type = "max_pool3d_with_index"

    def setup(self):
        rs = np.random.RandomState(4)
        x = rs.randn(1, 2, 4, 4, 4).astype("f4")
        k, s = 2, 2
        D = H = W = 4
        oD = oH = oW = 2
        out = np.zeros((1, 2, oD, oH, oW), "f4")
        mask = np.zeros((1, 2, oD, oH, oW), np.int32)
        for c in range(2):
            for d, h, w in itertools.product(range(oD), range(oH),
                                             range(oW)):
                blk = x[0, c, d*s:d*s+k, h*s:h*s+k, w*s:w*s+k]
                out[0, c, d, h, w] = blk.max()
                off = np.unravel_index(blk.argmax(), blk.shape)
                mask[0, c, d, h, w] = ((d*s+off[0]) * H + h*s+off[1]) * W \
                    + w*s + off[2]
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"ksize": [k]*3, "strides": [s]*3,
                      "paddings": [0]*3}
        self.outputs = {"Out": [("o", out)], "Mask": [("m", mask)]}

    def test_output(self):
        self.check_output()


class TestPsroiPool(OpTest):
    op_type = "psroi_pool"

    def setup(self):
        rs = np.random.RandomState(5)
        OC, PH, PW = 2, 2, 2
        C = OC * PH * PW
        x = rs.randn(1, C, 8, 8).astype("f4")
        rois = np.array([[0.0, 0.0, 5.0, 5.0]], "f4")
        out = np.zeros((1, OC, PH, PW), "f4")
        # oracle mirrors psroi_pool_op.h with spatial_scale=1
        x1, y1 = round(0.0) * 1.0, round(0.0) * 1.0
        x2, y2 = round(5.0 + 1) * 1.0, round(5.0 + 1) * 1.0
        bw = max(x2 - x1, 0.1) / PW
        bh = max(y2 - y1, 0.1) / PH
        for c in range(OC):
            for ph in range(PH):
                for pw in range(PW):
                    hs = int(np.floor(y1 + ph * bh))
                    he = int(np.ceil(y1 + (ph + 1) * bh))
                    ws = int(np.floor(x1 + pw * bw))
                    we = int(np.ceil(x1 + (pw + 1) * bw))
                    hs, he = max(hs, 0), min(he, 8)
                    ws, we = max(ws, 0), min(we, 8)
                    ch = c * PH * PW + ph * PW + pw
                    blk = x[0, ch, hs:he, ws:we]
                    out[0, c, ph, pw] = blk.mean() if blk.size else 0.0
        self.inputs = {"X": [("x", x)], "ROIs": [("r", rois)]}
        self.attrs = {"output_channels": OC, "pooled_height": PH,
                      "pooled_width": PW, "spatial_scale": 1.0}
        self.outputs = {"Out": [("o", out)]}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestPrroiPoolRunsAndBounds(OpTest):
    """prroi_pool is documented as a dense-sample approximation of the
    bilinear integral; parity check = within-range + constant-field
    exactness (integral of a constant is the constant)."""
    op_type = "prroi_pool"

    def setup(self):
        x = np.full((1, 3, 8, 8), 2.5, "f4")
        rois = np.array([[1.0, 1.0, 6.0, 6.0]], "f4")
        out = np.full((1, 3, 2, 2), 2.5, "f4")
        self.inputs = {"X": [("x", x)], "ROIs": [("r", rois)]}
        self.attrs = {"pooled_height": 2, "pooled_width": 2,
                      "spatial_scale": 1.0}
        self.outputs = {"Out": [("o", out)]}

    def test_output(self):
        self.check_output(atol=1e-4)


def _np_crf_ll(emission, label, trans_full, length):
    """logZ - path_score, start/stop in rows 0/1 (linear_chain_crf_op.h)."""
    start, stop, trans = trans_full[0], trans_full[1], trans_full[2:]
    n = length
    alpha = start + emission[0]
    for t in range(1, n):
        alpha = np.array([
            np.logaddexp.reduce(alpha + trans[:, j]) + emission[t, j]
            for j in range(trans.shape[1])])
    logz = np.logaddexp.reduce(alpha + stop)
    path = start[label[0]] + emission[np.arange(n), label[:n]].sum() \
        + trans[label[:n - 1], label[1:n]].sum() + stop[label[n - 1]]
    return logz - path


class TestLinearChainCrf(OpTest):
    op_type = "linear_chain_crf"

    def setup(self):
        rs = np.random.RandomState(7)
        B, T, D = 2, 5, 3
        em = rs.randn(B, T, D).astype("f4")
        trans = (rs.randn(D + 2, D) * 0.5).astype("f4")
        label = rs.randint(0, D, (B, T)).astype("i4")
        lens = np.array([5, 3], np.int32)
        ll = np.array([[_np_crf_ll(em[b], label[b], trans, lens[b])]
                       for b in range(B)], "f4")
        self.inputs = {"Emission": [("e", em)],
                       "Transition": [("t", trans)],
                       "Label": [("l", label)],
                       "Length": [("n", lens)]}
        self.outputs = {"LogLikelihood": [("ll", ll)]}

    def test_output(self):
        self.check_output(no_check_set=["Alpha", "EmissionExps",
                                        "TransitionExps"], atol=1e-4)


class TestCrfDecoding(OpTest):
    op_type = "crf_decoding"

    def setup(self):
        rs = np.random.RandomState(8)
        B, T, D = 2, 5, 3
        em = rs.randn(B, T, D).astype("f4")
        trans = (rs.randn(D + 2, D) * 0.5).astype("f4")
        lens = np.array([5, 3], np.int32)
        start, stop, tr = trans[0], trans[1], trans[2:]

        paths = np.zeros((B, T), np.int32)
        for b in range(B):
            n = lens[b]
            score = start + em[b, 0]
            back = np.zeros((n, D), np.int32)
            for t in range(1, n):
                cand = score[:, None] + tr
                back[t] = cand.argmax(0)
                score = cand.max(0) + em[b, t]
            cur = int((score + stop).argmax())
            for t in range(n - 1, -1, -1):
                paths[b, t] = cur
                if t > 0:
                    cur = int(back[t][cur])
        self.inputs = {"Emission": [("e", em)],
                       "Transition": [("t", trans)],
                       "Length": [("n", lens)]}
        self.outputs = {"ViterbiPath": [("p", paths)]}

    def test_output(self):
        self.check_output()


def test_crf_trains_end_to_end():
    """CRF loss decreases when the transition/emission params train
    (the generic-vjp gradient path through logsumexp scans)."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.layer_helper import LayerHelper
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.optimizer import MomentumOptimizer

    B, T, D = 4, 6, 3
    rs = np.random.RandomState(0)
    main, startup = Program(), Program()
    with unique_name.guard(), program_guard(main, startup):
        em_in = layers.data("em", [T, D])
        lbl = layers.data("lbl", [T], dtype="int32")
        ln = layers.data("ln", [], dtype="int32")
        h = LayerHelper("crf")
        trans = h.create_parameter(attr=None, shape=[D + 2, D],
                                   dtype="float32")
        ll = h.create_variable_for_type_inference()
        h.append_op("linear_chain_crf",
                    {"Emission": [em_in.name], "Transition": [trans.name],
                     "Label": [lbl.name], "Length": [ln.name]},
                    {"LogLikelihood": [ll.name]}, {})
        loss = layers.mean(ll)
        MomentumOptimizer(0.1, 0.9).minimize(loss)

    exe = pt.Executor(pt.CPUPlace())
    sc = pt.framework.Scope()
    exe.run(startup, scope=sc)
    em = rs.randn(B, T, D).astype("f4")
    lb = rs.randint(0, D, (B, T)).astype("i4")
    lens = np.full((B,), T, np.int32)
    losses = [float(np.asarray(
        exe.run(main, feed={"em": em, "lbl": lb, "ln": lens},
                fetch_list=[loss], scope=sc)[0]).ravel()[0])
        for _ in range(25)]
    # only the transition matrix trains (emissions are feeds), so the
    # attainable drop against random labels plateaus at ~0.776x the
    # initial loss (measured: steps 25/40/60 all sit at 0.776-0.784 —
    # the entropy floor of random labels under fixed emissions); the
    # old 0.75 margin was below the floor and failed every run
    assert losses[-1] < losses[0] * 0.80, losses
