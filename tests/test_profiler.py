"""Profiler subsystem: trace capture + RecordEvent annotations.

Reference parity: python/paddle/fluid/profiler.py:131/:198/:255 and the
RecordEvent scoped annotations (platform/profiler.cc:53).
"""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, profiler
from paddle_tpu.framework.program import Program, program_guard


def _tiny_run(tmp_scope):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.fc(x, size=2)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=tmp_scope)
    return exe.run(main, feed={"x": np.ones((3, 4), "float32")},
                   fetch_list=[y], scope=tmp_scope)


def test_profiler_context_manager_captures_trace(tmp_path):
    out = str(tmp_path / "trace")
    scope = pt.framework.Scope()
    with profiler.profiler(profile_path=out):
        with profiler.RecordEvent("tiny_step"):
            _tiny_run(scope)
    # jax dumps plugins/profile/<date>/*.xplane.pb under the trace dir
    found = []
    for root, _dirs, files in os.walk(out):
        found.extend(f for f in files if f.endswith((".xplane.pb", ".json.gz",
                                                     ".trace.json.gz")))
    assert found, f"no trace artifacts written under {out}"


def test_start_stop_and_double_start_rejected(tmp_path):
    out = str(tmp_path / "trace2")
    profiler.start_profiler(profile_path=out)
    with pytest.raises(RuntimeError):
        profiler.start_profiler(profile_path=out)
    assert profiler.stop_profiler() == out
    with pytest.raises(RuntimeError):
        profiler.stop_profiler()


def test_record_event_without_capture_is_noop():
    with profiler.RecordEvent("outside_capture"):
        pass


def test_stop_profiler_resets_dir_and_t0(tmp_path):
    out = str(tmp_path / "trace3")
    profiler.start_profiler(profile_path=out)
    assert profiler._state["dir"] == out
    assert profiler._state["t0"] is not None
    assert profiler.stop_profiler() == out
    # full state reset: a later capture must never see this one's
    # dir/t0 (previously they leaked until process exit)
    assert profiler._state == {"running": False, "dir": None, "t0": None}


def test_failed_start_does_not_wedge_running_check(tmp_path, monkeypatch):
    """A start_trace failure must roll the state back so the process
    can still profile later (previously the pre-set 'running' flag — or
    a partially-updated dir — wedged every subsequent start)."""
    import jax

    def boom(*a, **k):
        raise RuntimeError("synthetic capture failure")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    with pytest.raises(RuntimeError, match="synthetic"):
        profiler.start_profiler(profile_path=str(tmp_path / "bad"))
    assert profiler._state == {"running": False, "dir": None, "t0": None}
    monkeypatch.undo()
    # the profiler still works after the failure
    out = str(tmp_path / "good")
    profiler.start_profiler(profile_path=out)
    assert profiler.stop_profiler() == out


def test_record_event_dual_feeds_observe_tracer():
    """RecordEvent spans land in the observe ring buffer when
    FLAGS_enable_tracer is set — no XLA capture needed."""
    from paddle_tpu import observe

    observe.clear()
    observe.enable()
    try:
        with profiler.RecordEvent("outer_evt"):
            with profiler.RecordEvent("inner_evt"):
                pass
    finally:
        observe.disable()
    recs = {r.name: r for r in observe.snapshot()}
    assert recs["inner_evt"].parent == "outer_evt"
    assert recs["inner_evt"].depth == 1
    observe.clear()


def test_record_event_spans_nest_under_concurrent_threads():
    import threading

    from paddle_tpu import observe

    observe.clear()
    observe.enable()
    try:
        barrier = threading.Barrier(2)

        def work(tag):
            barrier.wait()
            with profiler.RecordEvent(f"{tag}_outer"):
                with profiler.RecordEvent(f"{tag}_inner"):
                    pass

        ts = [threading.Thread(target=work, args=(f"w{i}",))
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        observe.disable()
    recs = {r.name: r for r in observe.snapshot()}
    for tag in ("w0", "w1"):
        assert recs[f"{tag}_inner"].parent == f"{tag}_outer"
    assert recs["w0_outer"].tid != recs["w1_outer"].tid
    observe.clear()


def test_shared_record_event_is_reentrant_and_thread_safe():
    """ONE RecordEvent instance used via the explicit begin()/end() API
    reentrantly and from multiple threads: every pair must record its
    own span with correct nesting (per-call state, not per-instance)."""
    import threading

    from paddle_tpu import observe

    observe.clear()
    observe.enable()
    ev = profiler.RecordEvent("shared")
    try:
        ev.begin()
        ev.begin()  # reentrant on one thread
        ev.end()
        ev.end()
        barrier = threading.Barrier(2)

        def work():
            barrier.wait()
            for _ in range(10):
                ev.begin()
                ev.end()

        ts = [threading.Thread(target=work) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        observe.disable()
    recs = [r for r in observe.snapshot() if r.name == "shared"]
    assert len(recs) == 22  # 2 reentrant + 20 threaded, none lost
    inner = [r for r in recs if r.depth == 1]
    assert len(inner) == 1 and inner[0].parent == "shared"
    observe.clear()


def test_exported_timeline_is_schema_valid_chrome_trace(tmp_path):
    """Tracer-driven Executor run -> export -> valid Chrome trace JSON
    (the tools/timeline.py parity path, no CUPTI/XLA capture)."""
    import json

    from paddle_tpu import observe

    observe.clear()
    observe.enable()
    try:
        scope = pt.framework.Scope()
        _tiny_run(scope)
    finally:
        observe.disable()
    path = str(tmp_path / "host_trace.json")
    observe.export_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    xs = [e for e in evs if e.get("ph") == "X"]
    assert {"executor/run", "executor/lowering"} <= {e["name"] for e in xs}
    for e in xs:
        assert e["dur"] >= 0 and e["ts"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    observe.clear()


def test_tracer_disabled_run_overhead_is_negligible():
    """ISSUE acceptance: tracer off => the instrumented Executor.run
    path costs ~nothing extra.  Microbench the actual disabled span
    call (the only added per-run work) rather than racing two full
    runs against CI noise."""
    import time

    from paddle_tpu import observe

    observe.disable()
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        with observe.span("executor/run"):
            pass
    per_call = (time.perf_counter() - t0) / n
    # ~7 disabled spans per Executor.run; even a 100us run budget keeps
    # this under 1% — assert an order of magnitude of headroom
    assert per_call < 20e-6, f"{per_call * 1e6:.2f}us per disabled span"
