"""Profiler subsystem: trace capture + RecordEvent annotations.

Reference parity: python/paddle/fluid/profiler.py:131/:198/:255 and the
RecordEvent scoped annotations (platform/profiler.cc:53).
"""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, profiler
from paddle_tpu.framework.program import Program, program_guard


def _tiny_run(tmp_scope):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.fc(x, size=2)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=tmp_scope)
    return exe.run(main, feed={"x": np.ones((3, 4), "float32")},
                   fetch_list=[y], scope=tmp_scope)


def test_profiler_context_manager_captures_trace(tmp_path):
    out = str(tmp_path / "trace")
    scope = pt.framework.Scope()
    with profiler.profiler(profile_path=out):
        with profiler.RecordEvent("tiny_step"):
            _tiny_run(scope)
    # jax dumps plugins/profile/<date>/*.xplane.pb under the trace dir
    found = []
    for root, _dirs, files in os.walk(out):
        found.extend(f for f in files if f.endswith((".xplane.pb", ".json.gz",
                                                     ".trace.json.gz")))
    assert found, f"no trace artifacts written under {out}"


def test_start_stop_and_double_start_rejected(tmp_path):
    out = str(tmp_path / "trace2")
    profiler.start_profiler(profile_path=out)
    with pytest.raises(RuntimeError):
        profiler.start_profiler(profile_path=out)
    assert profiler.stop_profiler() == out
    with pytest.raises(RuntimeError):
        profiler.stop_profiler()


def test_record_event_without_capture_is_noop():
    with profiler.RecordEvent("outside_capture"):
        pass
