"""Ring attention (sequence parallelism) vs full-attention oracle.

Beyond-reference component (the reference has no long-context story,
SURVEY §5); parity oracle is plain softmax attention on the gathered
sequence, forward AND backward.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distributed.ring_attention import (
    ring_attention_sharded,
)

B, H, S, D = 2, 3, 32, 8
SP = 4


@pytest.fixture
def mesh():
    return jax.sharding.Mesh(np.array(jax.devices()[:SP]), ("sp",))


def _full_attention(q, k, v, causal=False):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _qkv(seed=0):
    rs = np.random.RandomState(seed)
    return (jnp.asarray(rs.randn(B, H, S, D), jnp.float32),
            jnp.asarray(rs.randn(B, H, S, D), jnp.float32),
            jnp.asarray(rs.randn(B, H, S, D), jnp.float32))


def test_forward_matches_full_attention(mesh):
    q, k, v = _qkv()
    got = ring_attention_sharded(q, k, v, mesh)
    want = _full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_causal_matches_full_attention(mesh):
    q, k, v = _qkv(1)
    got = ring_attention_sharded(q, k, v, mesh, causal=True)
    want = _full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_backward_matches_full_attention(mesh):
    """jax.vjp through the ring (ppermute transposes to a reverse ring)
    must equal the dense-attention gradient."""
    from paddle_tpu.framework.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.ring_attention import ring_attention

    q, k, v = _qkv(2)
    spec = P(None, None, "sp", None)

    def ring_loss(q, k, v):
        def f(q, k, v):
            return ring_attention(q, k, v, axis_name="sp")

        out = shard_map(f, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec, check_vma=False)(q, k, v)
        return jnp.sum(out * out)

    def full_loss(q, k, v):
        out = _full_attention(q, k, v)
        return jnp.sum(out * out)

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_full = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"grad {name}")


def test_fused_op_uses_ring_under_sp(mesh):
    """The fused_multihead_attention lowering routes to the ring when the
    executor runs inside an 'sp' shard_map."""
    from paddle_tpu.framework.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.framework.lowering import LOWERINGS, LoweringContext

    hidden = H * D
    q2 = np.random.RandomState(3).randn(B, S, hidden).astype("f4")

    class FakeOp:
        type = "fused_multihead_attention"
        inputs = {"Q": ["q"], "K": ["k"], "V": ["v"]}
        outputs = {"Out": ["o"]}

        def attr(self, name, default=None):
            return {"head_number": H, "alpha": 0.0,
                    "sequence_parallel": True}.get(name, default)

        def output_arg_names(self):
            return ["o"]

    def f(qkv):
        env = {"q": qkv, "k": qkv, "v": qkv}

        class B_:
            program = None

            def _find_var_recursive(self, n):
                return None

        ctx = LoweringContext(B_(), env, axis_env=("sp",))
        LOWERINGS["fused_multihead_attention"](ctx, FakeOp())
        return env["o"]

    spec = P(None, "sp", None)
    got = jax.jit(shard_map(f, mesh=mesh, in_specs=(spec,),
                            out_specs=spec, check_vma=False))(
        jnp.asarray(q2))
    # oracle: dense self-attention with q=k=v
    qh = jnp.transpose(jnp.asarray(q2).reshape(B, S, H, D), (0, 2, 1, 3))
    want = jnp.transpose(_full_attention(qh, qh, qh), (0, 2, 1, 3)).reshape(
        B, S, hidden)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_key_mask_bias_matches_full_attention(mesh):
    """A padding key-mask rotates around the ring with its k/v shard
    (round-5: the SP path previously rejected any bias)."""
    q, k, v = _qkv(5)
    rs = np.random.RandomState(6)
    bias = jnp.asarray(
        np.where(rs.rand(B, 1, 1, S) > 0.25, 0.0, -1e9), jnp.float32)
    got = ring_attention_sharded(q, k, v, mesh, bias=bias)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D) + bias
    want = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_key_mask_bias_backward(mesh):
    q, k, v = _qkv(7)
    rs = np.random.RandomState(8)
    bias = jnp.asarray(
        np.where(rs.rand(B, 1, 1, S) > 0.25, 0.0, -1e9), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh,
                                              bias=bias) ** 2)

    def loss_full(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D) + bias
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        return jnp.sum(o ** 2)

    gr = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gg, "qkv"):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"grad {name}")
