"""2.0 API tests: paddle.tensor / paddle.nn / paddle.optimizer.

Parity model: reference unittests for the 2.0 namespaces; numpy is the
oracle, plus eager-vs-static cross-checks (the same op must produce the
same numbers through both execution modes).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


def T(x):
    return paddle.to_tensor(np.asarray(x, dtype="float32"))


class TestTensorAPI:
    def test_creation(self):
        np.testing.assert_allclose(paddle.zeros([2, 3]).numpy(), np.zeros((2, 3)))
        np.testing.assert_allclose(paddle.full([2], 7.0).numpy(), [7.0, 7.0])
        np.testing.assert_allclose(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(paddle.eye(3).numpy(), np.eye(3))
        np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(),
                                   np.linspace(0, 1, 5), rtol=1e-6)
        np.testing.assert_allclose(paddle.tril(T(np.ones((3, 3)))).numpy(),
                                   np.tril(np.ones((3, 3))))

    def test_math(self, rng):
        a, b = rng.randn(3, 4).astype("f4"), rng.randn(3, 4).astype("f4")
        x, y = T(a), T(b)
        np.testing.assert_allclose(paddle.add(x, y).numpy(), a + b, rtol=1e-6)
        np.testing.assert_allclose(paddle.multiply(x, y).numpy(), a * b, rtol=1e-6)
        np.testing.assert_allclose(paddle.exp(x).numpy(), np.exp(a), rtol=1e-5)
        np.testing.assert_allclose(paddle.maximum(x, y).numpy(), np.maximum(a, b))
        np.testing.assert_allclose(paddle.clip(x, -0.5, 0.5).numpy(),
                                   np.clip(a, -0.5, 0.5))
        np.testing.assert_allclose(paddle.sum(x, axis=1).numpy(), a.sum(1), rtol=1e-5)
        np.testing.assert_allclose(paddle.cumsum(x, axis=1).numpy(),
                                   np.cumsum(a, 1), rtol=1e-5)
        np.testing.assert_allclose(paddle.std(x).numpy(), a.std(ddof=1), rtol=1e-4)

    def test_manipulation(self, rng):
        a = rng.randn(2, 3, 4).astype("f4")
        x = T(a)
        assert paddle.reshape(x, [6, 4]).shape == [6, 4]
        assert paddle.transpose(x, [2, 0, 1]).shape == [4, 2, 3]
        assert paddle.flatten(x, 1).shape == [2, 12]
        assert paddle.unsqueeze(x, 0).shape == [1, 2, 3, 4]
        assert paddle.concat([x, x], axis=1).shape == [2, 6, 4]
        parts = paddle.split(x, [1, 3], axis=2)
        assert [p.shape for p in parts] == [[2, 3, 1], [2, 3, 3]]
        assert paddle.tile(x, [1, 2, 1]).shape == [2, 6, 4]
        assert paddle.stack([x, x]).shape == [2, 2, 3, 4]
        np.testing.assert_allclose(paddle.flip(x, 0).numpy(), a[::-1], rtol=1e-6)

    def test_linalg_and_search(self, rng):
        a = rng.randn(5, 6).astype("f4")
        x = T(a)
        np.testing.assert_allclose(paddle.matmul(x, x, transpose_y=True).numpy(),
                                   a @ a.T, rtol=1e-4)
        np.testing.assert_allclose(paddle.norm(x).numpy(),
                                   np.linalg.norm(a), rtol=1e-5)
        np.testing.assert_allclose(paddle.argmax(x, -1).numpy(), a.argmax(-1))
        vals, idx = paddle.topk(x, 2, axis=1)
        np.testing.assert_allclose(vals.numpy(),
                                   np.sort(a, 1)[:, ::-1][:, :2], rtol=1e-6)
        np.testing.assert_allclose(paddle.sort(x, 1).numpy(), np.sort(a, 1), rtol=1e-6)

    def test_tensor_methods_patched(self, rng):
        a = rng.randn(3, 3).astype("f4")
        x = T(a)
        np.testing.assert_allclose(x.matmul(x).numpy(), a @ a, rtol=1e-4)
        np.testing.assert_allclose(x.flatten().numpy(), a.ravel(), rtol=1e-6)
        np.testing.assert_allclose(x.exp().numpy(), np.exp(a), rtol=1e-5)
        assert x.argmax(-1).numpy().shape == (3,)

    def test_static_mode_tensor_ops(self):
        """Same functions appended to a Program and executed via XLA."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.framework.program import Program, program_guard

        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", [4])
            y = paddle.add(paddle.exp(x), paddle.scale(x, 2.0))
            z = paddle.sum(y)
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup)
        a = np.random.RandomState(3).randn(2, 4).astype("f4")
        (zv,) = exe.run(main, feed={"x": a}, fetch_list=[z])
        np.testing.assert_allclose(zv, (np.exp(a) + 2 * a).sum(), rtol=1e-5)

    def test_variable_operators_static(self):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.framework.program import Program, program_guard

        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", [3])
            y = (x * 2.0 + 1.0) / 2.0
            z = y.mean()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup)
        a = np.random.RandomState(5).randn(2, 3).astype("f4")
        (zv,) = exe.run(main, feed={"x": a}, fetch_list=[z])
        np.testing.assert_allclose(zv, ((a * 2 + 1) / 2).mean(), rtol=1e-5)


class TestNN:
    def test_linear_and_activations(self, rng):
        x = T(rng.randn(4, 8))
        for layer, ref in [
            (nn.ReLU(), lambda v: np.maximum(v, 0)),
            (nn.Sigmoid(), lambda v: 1 / (1 + np.exp(-v))),
            (nn.Tanh(), np.tanh),
        ]:
            np.testing.assert_allclose(layer(x).numpy(), ref(x.numpy()), rtol=1e-5)
        lin = nn.Linear(8, 2)
        np.testing.assert_allclose(
            lin(x).numpy(),
            x.numpy() @ lin.weight.numpy() + lin.bias.numpy(), rtol=1e-4)

    def test_conv_pool_shapes(self, rng):
        x = T(rng.randn(2, 3, 16, 16))
        conv = nn.Conv2D(3, 8, 3, padding=1)
        y = conv(x)
        assert y.shape == [2, 8, 16, 16]
        assert nn.MaxPool2D(2, 2)(y).shape == [2, 8, 8, 8]
        assert nn.AdaptiveAvgPool2D(1)(y).shape == [2, 8, 1, 1]
        assert nn.Conv2DTranspose(3, 4, 2, stride=2)(x).shape == [2, 4, 32, 32]

    def test_conv_transpose_matches_torch(self, rng):
        import torch
        import torch.nn.functional as tF

        x = rng.randn(2, 3, 8, 8).astype("f4")
        w = rng.randn(3, 4, 3, 3).astype("f4")
        for stride, pad in [(1, 0), (2, 1)]:
            ref = tF.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                      stride=stride, padding=pad).numpy()
            got = F.conv2d_transpose(T(x), T(w), stride=stride, padding=pad)
            np.testing.assert_allclose(got.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_batchnorm_updates_stats(self, rng):
        bn = nn.BatchNorm2D(3)
        x = T(rng.randn(8, 3, 4, 4) * 2 + 1)
        bn.train()
        y = bn(x)
        assert y.shape == [8, 3, 4, 4]
        assert not np.allclose(bn._mean.numpy(), np.zeros(3))
        bn.eval()
        y2 = bn(x)  # uses running stats, no update
        m = bn._mean.numpy().copy()
        bn(x)
        np.testing.assert_allclose(bn._mean.numpy(), m)

    def test_layernorm_matches_numpy(self, rng):
        ln = nn.LayerNorm(6)
        a = rng.randn(3, 6).astype("f4")
        y = ln(T(a)).numpy()
        ref = (a - a.mean(-1, keepdims=True)) / np.sqrt(a.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)

    def test_embedding_and_dropout(self, rng):
        emb = nn.Embedding(10, 4)
        ids = paddle.to_tensor(np.array([[1, 2], [3, 4]], dtype="int64"))
        assert emb(ids).shape == [2, 2, 4]
        d = nn.Dropout(0.5)
        d.eval()
        x = T(rng.randn(4, 4))
        np.testing.assert_allclose(d(x).numpy(), x.numpy())

    def test_losses(self, rng):
        logits = T(rng.randn(6, 5))
        labels = paddle.to_tensor(rng.randint(0, 5, (6,)).astype("int64"))
        loss = nn.CrossEntropyLoss()(logits, labels)
        lp = logits.numpy() - np.log(np.exp(logits.numpy()).sum(-1, keepdims=True))
        ref = -lp[np.arange(6), labels.numpy()].mean()
        np.testing.assert_allclose(loss.numpy(), ref, rtol=1e-4)

        a, b = T(rng.randn(4)), T(rng.randn(4))
        np.testing.assert_allclose(nn.MSELoss()(a, b).numpy(),
                                   ((a.numpy() - b.numpy()) ** 2).mean(), rtol=1e-5)

    def test_sequential_and_layerlist(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        assert len(m) == 3
        x = T(np.random.RandomState(0).randn(2, 4))
        assert m(x).shape == [2, 2]
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(list(ll.parameters())) == 6

    def test_transformer_forward_backward(self, rng):
        layer = nn.TransformerEncoderLayer(d_model=16, nhead=4, dim_feedforward=32,
                                           dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        x = T(rng.randn(2, 6, 16))
        y = enc(x)
        assert y.shape == [2, 6, 16]
        loss = paddle.mean(paddle.square(y))
        loss.backward()
        grads = [p.grad for p in enc.parameters()]
        assert all(g is not None for g in grads)
        assert all(np.isfinite(g.numpy()).all() for g in grads)

    def test_attention_mask(self, rng):
        mha = nn.MultiHeadAttention(8, 2, dropout=0.0)
        x = T(rng.randn(1, 4, 8))
        mask = paddle.to_tensor(np.tril(np.ones((1, 1, 4, 4))).astype("bool"))
        y = mha(x, x, x, attn_mask=mask)
        assert y.shape == [1, 4, 8]


class TestOptimizer2:
    def _loss(self, w):
        return paddle.mean(paddle.square(w))

    def test_sgd_matches_closed_form(self):
        w = nn.Parameter(np.ones(4, dtype="f4") * 2.0)
        opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=[w])
        self._loss(w).backward()
        opt.step()
        # dL/dw = 2w/4 = w/2 -> w' = w - 0.5*w/2 = 1.5
        np.testing.assert_allclose(w.numpy(), np.full(4, 1.5), rtol=1e-6)

    def test_adam_matches_reference_formula(self):
        a = np.array([1.0, -2.0, 3.0], dtype="f4")
        w = nn.Parameter(a.copy())
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
        paddle.sum(w * w).backward()
        opt.step()
        g = 2 * a
        m = 0.1 * g
        v = 0.001 * g * g
        lr_t = 0.1 * np.sqrt(1 - 0.999) / (1 - 0.9)
        ref = a - lr_t * m / (np.sqrt(v) + 1e-8)
        np.testing.assert_allclose(w.numpy(), ref, rtol=1e-4)

    def test_adamw_decay(self):
        a = np.ones(3, dtype="f4")
        w = nn.Parameter(a.copy())
        opt = paddle.optimizer.AdamW(learning_rate=0.1, weight_decay=0.1,
                                     parameters=[w])
        paddle.sum(w).backward()
        opt.step()
        assert (w.numpy() < 1.0).all()

    def test_momentum_and_clear(self):
        w = nn.Parameter(np.ones(2, dtype="f4"))
        opt = paddle.optimizer.Momentum(0.1, 0.9, parameters=[w])
        self._loss(w).backward()
        opt.step()
        opt.clear_grad()
        assert w.grad is None

    def test_grad_clip_global_norm(self):
        w = nn.Parameter(np.ones(4, dtype="f4"))
        clip = nn.ClipGradByGlobalNorm(0.1)
        opt = paddle.optimizer.SGD(1.0, parameters=[w], grad_clip=clip)
        paddle.sum(w * w * 100).backward()  # big grads
        opt.step()
        # ||update|| == lr * clip_norm
        delta = np.linalg.norm(1.0 - w.numpy())
        np.testing.assert_allclose(delta, 0.1, rtol=1e-4)

    def test_lr_scheduler(self):
        sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
        w = nn.Parameter(np.ones(2, dtype="f4"))
        opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[w])
        assert abs(opt.get_lr() - 0.1) < 1e-8
        sched.step()
        sched.step()
        assert abs(opt.get_lr() - 0.05) < 1e-8

    def test_eager_static_adam_parity(self):
        """Same init + same data: dygraph Adam trajectory == static Adam."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.framework.program import Program, program_guard

        w0 = np.random.RandomState(0).randn(4, 1).astype("f4")
        xd = np.random.RandomState(1).randn(16, 4).astype("f4")
        yd = (xd @ w0).astype("f4")

        # static
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", [4])
            y = layers.data("y", [1])
            from paddle_tpu.param_attr import ParamAttr
            from paddle_tpu.initializer import NumpyArrayInitializer

            pred = layers.fc(x, 1, param_attr=ParamAttr(
                name="w", initializer=NumpyArrayInitializer(np.ones((4, 1), "f4"))),
                bias_attr=False)
            loss = layers.mean(layers.square_error_cost(pred, y))
            pt.optimizer.AdamOptimizer(0.1).minimize(loss)
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup)
        static_losses = [float(exe.run(main, feed={"x": xd, "y": yd},
                                       fetch_list=[loss])[0]) for _ in range(5)]

        # dygraph
        w = nn.Parameter(np.ones((4, 1), dtype="f4"))
        opt = paddle.optimizer.Adam(0.1, parameters=[w])
        dy_losses = []
        for _ in range(5):
            pred = paddle.matmul(paddle.to_tensor(xd), w)
            l = paddle.mean(paddle.square(paddle.subtract(pred, paddle.to_tensor(yd))))
            l.backward()
            opt.step()
            opt.clear_grad()
            dy_losses.append(float(l.numpy()))
        np.testing.assert_allclose(static_losses, dy_losses, rtol=1e-4, atol=1e-6)


class TestUtilsVersion:
    """paddle.utils / paddle.version parity (reference python/paddle/
    utils/, version.py)."""

    def test_run_check(self, capsys):
        import paddle_tpu as pt

        pt.utils.run_check()
        assert "successfully" in capsys.readouterr().out

    def test_deprecated_warns_and_forwards(self):
        import warnings

        import paddle_tpu as pt

        @pt.utils.deprecated(update_to="new_fn", since="2.0")
        def old_fn(a):
            return a + 1

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert old_fn(1) == 2
            assert any("deprecated" in str(x.message) for x in w)

    def test_try_import_and_download_guard(self):
        import pytest

        import paddle_tpu as pt

        assert pt.utils.try_import("math").sqrt(4) == 2.0
        with pytest.raises(ImportError):
            pt.utils.try_import("definitely_not_a_module_xyz")
        with pytest.raises(RuntimeError, match="zero-egress"):
            pt.utils.download("http://example.com/x")

    def test_version(self):
        import paddle_tpu as pt

        assert pt.version.full_version == pt.__version__
        assert pt.version.mkl() == "OFF"
