"""IR foundation tests: desc round-trip, program builders, fingerprints."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.framework import ir_pb2
from paddle_tpu.framework.program import Program, program_guard


def test_program_roundtrip():
    prog = Program()
    b = prog.global_block
    b.create_var(name="x", shape=[-1, 3], dtype="float32", stop_gradient=True)
    b.create_parameter("w", [3, 4], dtype="float32")
    b.append_op(
        "mul",
        {"X": "x", "Y": "w"},
        {"Out": "y"},
        {"x_num_col_dims": 1, "y_num_col_dims": 1},
    )
    b.create_var(name="y", shape=[-1, 4])
    data = prog.serialize_to_string()
    prog2 = Program.parse_from_string(data)
    assert len(prog2.blocks) == 1
    b2 = prog2.global_block
    assert set(b2.vars) == {"x", "w", "y"}
    assert b2.vars["w"].persistable
    assert b2.vars["x"].shape == (-1, 3)
    assert len(b2.ops) == 1
    op = b2.ops[0]
    assert op.type == "mul"
    assert op.input("X") == ["x"]
    assert op.attr("x_num_col_dims") == 1
    # fingerprint stability
    assert prog.fingerprint() == prog2.fingerprint()


def test_attr_kinds_roundtrip():
    prog = Program()
    b = prog.global_block
    b.append_op(
        "fake_op",
        {},
        {},
        {
            "i": 7,
            "f": 0.5,
            "s": "hello",
            "b_true": True,
            "ints": [1, 2, 3],
            "floats": [1.5, 2.5],
            "strings": ["a", "b"],
            "bools": [True, False],
        },
    )
    p2 = Program.parse_from_string(prog.serialize_to_string())
    op = p2.global_block.ops[0]
    assert op.attr("i") == 7
    assert op.attr("f") == 0.5
    assert op.attr("s") == "hello"
    assert op.attr("b_true") is True
    assert op.attr("ints") == [1, 2, 3]
    assert op.attr("floats") == [1.5, 2.5]
    assert op.attr("strings") == ["a", "b"]
    assert op.attr("bools") == [True, False]


def test_program_guard_and_defaults():
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = pt.layers.data("x", [4], dtype="float32")
        y = pt.layers.fc(x, 8)
    assert pt.default_main_program() is not main  # restored after guard
    assert any(op.type == "mul" for op in main.global_block.ops)
    # parameters created in both programs
    params = [v.name for v in main.all_parameters()]
    assert len(params) == 2  # weight + bias
    startup_outs = [
        n for op in startup.global_block.ops for n in op.output_arg_names()
    ]
    for p in params:
        assert p in startup_outs


def test_fingerprint_invalidation():
    prog = Program()
    f1 = prog.fingerprint()
    prog.global_block.append_op("relu", {"X": "a"}, {"Out": "b"})
    assert prog.fingerprint() != f1


def test_clone_for_test_flips_is_test():
    main = Program()
    with program_guard(main, Program()):
        x = pt.layers.data("x", [4])
        h = pt.layers.dropout(x, 0.5)
    test_prog = main.clone(for_test=True)
    dop = [op for op in test_prog.global_block.ops if op.type == "dropout"][0]
    assert dop.attr("is_test") is True


def test_executor_basic_feed_fetch():
    prog = Program()
    with program_guard(prog, Program()):
        x = pt.layers.data("x", [3], append_batch_size=True)
        y = pt.layers.scale(x, scale=2.0, bias=1.0)
    exe = pt.Executor(pt.CPUPlace())
    xv = np.arange(6, dtype="float32").reshape(2, 3)
    (out,) = exe.run(prog, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(out, xv * 2 + 1)


def test_executor_compile_cache():
    prog = Program()
    with program_guard(prog, Program()):
        x = pt.layers.data("x", [3])
        y = pt.layers.relu(x)
    exe = pt.Executor(pt.CPUPlace())
    xv = np.ones((2, 3), "float32")
    exe.run(prog, feed={"x": xv}, fetch_list=[y])
    assert len(exe._cache) == 1
    exe.run(prog, feed={"x": xv + 1}, fetch_list=[y])
    assert len(exe._cache) == 1  # same shapes -> cache hit
    exe.run(prog, feed={"x": np.ones((4, 3), "float32")}, fetch_list=[y])
    assert len(exe._cache) == 2  # new batch size -> new executable


def test_rng_determinism_per_scope_seed():
    prog = Program()
    prog.random_seed = 42
    with program_guard(prog, Program()):
        u = pt.layers.uniform_random([4, 4], min=0.0, max=1.0)
    s1, s2 = pt.framework.Scope(), pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace())
    (a,) = exe.run(prog, fetch_list=[u], scope=s1)
    (b,) = exe.run(prog, fetch_list=[u], scope=s2)
    np.testing.assert_allclose(a, b)  # same seed, same stream
    (c,) = exe.run(prog, fetch_list=[u], scope=s1)
    assert not np.allclose(a, c)  # key advances within a scope
