"""paddle_tpu.ckpt — async/atomic checkpoint manager.

Crash-consistency oracle: a save torn at ANY point before the manifest
rename must be invisible to restore() (fall back to the newest intact
step), and a resumed run — params, optimizer slots, LR-scheduler step,
RNG, AMP dynamic loss-scale, data-iterator position — must continue
bitwise-identically to a never-interrupted run.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.ckpt import (CheckpointError, CheckpointManager, KVBarrier,
                             LocalShard, ResumableIterator)
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.framework.scope import Scope, _switch_scope, global_scope


def _state(seed=0, n=4):
    rs = np.random.RandomState(seed)
    return {f"w{i}": rs.randn(8, 4).astype("f4") for i in range(n)}


def _assert_state_equal(a, b):
    assert sorted(a) == sorted(b)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


# ---------------------------------------------------------------------------
# basics: roundtrip, atomic commit, integrity fallback
# ---------------------------------------------------------------------------


def test_state_roundtrip_and_layout(tmp_path):
    m = CheckpointManager(str(tmp_path), async_save=False)
    st = _state()
    saved = m.save(7, state=st, host_state={"note": "x"})
    assert saved == sorted(st)
    # committed layout: final dir + hashed manifest, no .tmp leftover
    d = tmp_path / "step_7"
    assert d.is_dir() and not (tmp_path / "step_7.tmp").exists()
    manifest = json.load(open(d / "MANIFEST.json"))
    assert "shard_r0.npz" in manifest["files"]
    assert "meta_r0.json" in manifest["files"]
    meta = m.restore()
    assert meta["step"] == 7 and meta["host_state"]["note"] == "x"
    _assert_state_equal(meta["state"], st)
    m.close()


def test_scope_roundtrip_includes_rng_dtype_preserved(tmp_path):
    import jax
    import jax.numpy as jnp

    sc = Scope()
    sc.set_var("p", jnp.arange(6, dtype=jnp.float32).reshape(2, 3))
    sc.set_var("halfp", jnp.ones((3,), jnp.bfloat16) * 1.5)
    sc.set_var("@RNG_KEY@", jax.random.PRNGKey(11))
    m = CheckpointManager(str(tmp_path), async_save=False)
    m.save(0, scope=sc)
    sc2 = Scope()
    meta = m.restore(scope=sc2)
    assert "@RNG_KEY@" in meta["vars"]
    np.testing.assert_array_equal(np.asarray(sc2.get_var("p")),
                                  np.asarray(sc.get_var("p")))
    got = np.asarray(sc2.get_var("halfp"))
    assert str(got.dtype) == "bfloat16"  # npz void bytes view-cast back
    np.testing.assert_array_equal(got, np.asarray(sc.get_var("halfp")))
    np.testing.assert_array_equal(np.asarray(sc2.get_var("@RNG_KEY@")),
                                  np.asarray(sc.get_var("@RNG_KEY@")))
    m.close()


def test_torn_save_is_invisible_and_falls_back(tmp_path):
    """Kill the writer mid-save at every fault point: restore() must
    always land on the previous intact step."""
    for phase in ("serialize", "write_shard", "pre_commit"):
        d = tmp_path / phase
        m = CheckpointManager(str(d), async_save=True)
        m.save(1, state=_state(1), wait=True)

        def hook(p, step, _kill=phase):
            if p == _kill and step == 2:
                raise RuntimeError(f"injected crash at {_kill}")

        m.set_fault_hook(hook)
        m.save(2, state=_state(2))
        with pytest.raises(CheckpointError, match="injected crash"):
            m.wait()
        assert m.all_steps() == [1], phase  # step 2 never committed
        meta = m.restore()
        assert meta["step"] == 1, phase
        _assert_state_equal(meta["state"], _state(1))
        m.set_fault_hook(None)
        m.close()


def test_corrupt_committed_shard_detected(tmp_path):
    m = CheckpointManager(str(tmp_path), async_save=False)
    m.save(1, state=_state(1))
    m.save(2, state=_state(2))
    # flip bytes inside step 2's shard: manifest hash must catch it
    p = tmp_path / "step_2" / "shard_r0.npz"
    raw = bytearray(p.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    p.write_bytes(bytes(raw))
    ok, why = m.validate(2)
    assert not ok and "hash mismatch" in why
    meta = m.restore()
    assert meta["step"] == 1
    # every candidate torn -> loud error, not a silent fresh start
    p1 = tmp_path / "step_1" / "MANIFEST.json"
    p1.unlink()
    with pytest.raises(CheckpointError, match="no intact checkpoint"):
        m.restore()
    m.close()


def test_restore_on_missing_or_empty_dir(tmp_path):
    m = CheckpointManager(str(tmp_path / "never_written"))
    assert m.restore() is None  # nothing ever committed -> fresh run
    assert m.latest_intact_step() is None
    m.close()


def test_load_sharded_clear_error_on_missing_dir(tmp_path):
    """Satellite: a wrong path must raise a readable CheckpointError,
    not a third-party traceback."""
    from paddle_tpu.distributed.checkpoint import load_sharded

    sc = Scope()
    with pytest.raises(CheckpointError, match="does not exist"):
        load_sharded(sc, str(tmp_path / "nope"))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(CheckpointError, match="no committed checkpoint"):
        load_sharded(sc, str(empty))


def test_save_sharded_manager_is_cached(tmp_path):
    """Satellite: one manager per directory, not one per call."""
    from paddle_tpu.distributed import checkpoint as dckpt

    sc = Scope()
    sc.set_var("w", np.ones((2,), "f4"))
    dckpt.save_sharded(sc, str(tmp_path))
    m1 = dckpt._MANAGERS[os.path.abspath(str(tmp_path))]
    dckpt.save_sharded(sc, str(tmp_path))
    assert dckpt._MANAGERS[os.path.abspath(str(tmp_path))] is m1
    assert m1.all_steps() == [0, 1]  # successive saves = new steps


# ---------------------------------------------------------------------------
# retention, coalescing, wait/drain
# ---------------------------------------------------------------------------


def test_retention_gc(tmp_path):
    m = CheckpointManager(str(tmp_path), keep_n=2, keep_every_n_steps=4,
                          async_save=False)
    for s in range(1, 11):
        m.save(s, state={"w": np.full((2,), s, "f4")})
    # keep_n=2 newest {9,10} plus every 4th {4,8}
    assert m.all_steps() == [4, 8, 9, 10]
    m.close()


def test_keep_all_when_zero(tmp_path):
    m = CheckpointManager(str(tmp_path), keep_n=0, async_save=False)
    for s in range(5):
        m.save(s, state={"w": np.zeros(1, "f4")})
    assert m.all_steps() == [0, 1, 2, 3, 4]
    m.close()


def test_stale_pending_save_coalesced(tmp_path):
    from paddle_tpu.monitor import stat_get, stat_reset

    stat_reset("ckpt_saves_coalesced")
    m = CheckpointManager(str(tmp_path), async_save=True)
    step1_started = threading.Event()

    def slow(phase, step):
        if phase == "serialize" and step == 1:
            step1_started.set()
            time.sleep(0.3)

    m.set_fault_hook(slow)
    m.save(1, state={"w": np.full(4, 1.0, "f4")})
    # only queue more once the writer holds job 1 (otherwise job 1
    # itself could be the one superseded and the assert is a coin flip)
    assert step1_started.wait(10)
    # while step 1 writes, queue 2 then 3: 2 must be superseded
    m.save(2, state={"w": np.full(4, 2.0, "f4")})
    m.save(3, state={"w": np.full(4, 3.0, "f4")})
    m.wait()
    assert stat_get("ckpt_saves_coalesced") >= 1
    assert 2 not in m.all_steps()
    assert m.restore()["step"] == 3
    m.close()


def test_wait_barrier_and_executor_close_drains(tmp_path):
    m = CheckpointManager(str(tmp_path), async_save=True)

    def slow(phase, step):
        if phase == "serialize":
            time.sleep(0.3)

    m.set_fault_hook(slow)
    m.save(1, state=_state())
    # Executor.close() must drain the pending background save
    exe = pt.Executor(pt.CPUPlace())
    exe.close()
    assert m.all_steps() == [1]
    m.close()


# ---------------------------------------------------------------------------
# resumable data iterator
# ---------------------------------------------------------------------------


def test_resumable_iterator_position_roundtrip():
    from paddle_tpu.io import DataLoader, TensorDataset

    xs = np.arange(24, dtype="f4").reshape(24, 1)
    loader = DataLoader(TensorDataset([xs]), batch_size=4, shuffle=False)
    it = ResumableIterator(loader)
    seen = [next(it)[0][0, 0] for _ in range(8)]  # crosses epoch edge
    state = it.state_dict()
    assert state == {"epoch": 1, "batch": 2}
    rest = [next(it)[0][0, 0] for _ in range(4)]

    loader2 = DataLoader(TensorDataset([xs]), batch_size=4, shuffle=False)
    it2 = ResumableIterator(loader2)
    it2.set_state_dict(state)
    resumed = [next(it2)[0][0, 0] for _ in range(4)]
    np.testing.assert_array_equal(resumed, rest)
    assert seen[:6] == [0, 4, 8, 12, 16, 20]


def test_resumable_iterator_as_component(tmp_path):
    batches = [np.full((2,), i, "f4") for i in range(6)]
    it = ResumableIterator(batches)
    m = CheckpointManager(str(tmp_path), async_save=False)
    m.register("data", it)
    next(it), next(it), next(it)
    m.save(3, state={"w": np.zeros(1, "f4")})
    it2 = ResumableIterator(batches)
    m2 = CheckpointManager(str(tmp_path), async_save=False)
    m2.register("data", it2)
    m2.restore()
    np.testing.assert_array_equal(next(it2), batches[3])
    m.close(), m2.close()


# ---------------------------------------------------------------------------
# multi-rank sharded commit over the fleet KV barrier
# ---------------------------------------------------------------------------


def test_two_rank_sharded_commit_over_kv_barrier(tmp_path):
    """Per-rank shard files; rank 0 commits the manifest only after the
    KV-server barrier confirmed both ranks' writes; restore re-assembles
    the sharded value and takes replicated vars from rank 0's file."""
    from paddle_tpu.distributed.fleet.utils import KVServer

    srv = KVServer(0)
    srv.start()
    try:
        ep = f"127.0.0.1:{srv.port}"
        w = np.arange(12, dtype="f4").reshape(3, 4)       # replicated
        s_full = np.arange(16, dtype="f4").reshape(8, 2)  # dp-sharded
        mgrs = [CheckpointManager(
            str(tmp_path), async_save=False, rank=r, world_size=2,
            barrier=KVBarrier(ep, rank=r, world_size=2, timeout=30))
            for r in range(2)]
        states = [
            {"w": w, "s": LocalShard(s_full[:4], s_full.shape)},
            {"w": w, "s": LocalShard(s_full[4:], s_full.shape)},
        ]
        errs = []

        def run(r):
            try:
                mgrs[r].save(5, state=states[r])
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert not errs, errs
        manifest = json.load(open(tmp_path / "step_5" / "MANIFEST.json"))
        assert manifest["world_size"] == 2
        assert {"shard_r0.npz", "shard_r1.npz", "meta_r0.json",
                "meta_r1.json"} <= set(manifest["files"])
        meta = mgrs[0].restore()
        np.testing.assert_array_equal(meta["state"]["w"], w)
        np.testing.assert_array_equal(meta["state"]["s"], s_full)
        for m in mgrs:
            m.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# full-state elastic resume: the async-parity acceptance oracle
# ---------------------------------------------------------------------------


def _build_full_model():
    """fc -> dropout (consumes RNG) -> fc, MSE, Momentum under an
    LR schedule and fp16 dynamic loss scaling: every state family the
    checkpoint must carry is live."""
    from paddle_tpu.amp.static_amp import decorate
    from paddle_tpu.optimizer import MomentumOptimizer
    from paddle_tpu.optimizer_lr import StepDecay

    main, startup = Program(), Program()
    main.random_seed = 3
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1])
        h = layers.fc(x, 16, act="relu", bias_attr=False)
        h = layers.dropout(h, 0.3)
        pred = layers.fc(h, 1, bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        sched = StepDecay(0.1, step_size=2, gamma=0.5)
        opt = MomentumOptimizer(sched, 0.9)
        amp = decorate(opt, use_bf16=False, init_loss_scaling=2.0 ** 4,
                       incr_every_n_steps=2, use_dynamic_loss_scaling=True)
        amp.minimize(loss)
    return main, startup, loss, sched


def _full_data():
    rs = np.random.RandomState(0)
    X = rs.randn(32, 8).astype("f4")
    Y = (X.sum(1, keepdims=True) * 0.3).astype("f4")
    return X, Y


def _make_iter():
    from paddle_tpu.io import DataLoader, TensorDataset

    X, Y = _full_data()
    return ResumableIterator(DataLoader(TensorDataset([X, Y]),
                                        batch_size=8, shuffle=False))


def _run_training(ckpt_dir, steps, manager=None, crash_at=None,
                  resume=False):
    """One 'process': fresh programs/scope/scheduler/iterator; optional
    restore; per-step async save; returns (losses, final_params,
    manager)."""
    main, startup, loss, sched = _build_full_model()
    exe = pt.Executor(pt.CPUPlace())
    old = _switch_scope(Scope())
    try:
        exe.run(startup)
        it = _make_iter()
        m = manager or CheckpointManager(ckpt_dir, keep_n=0,
                                         async_save=True)
        m.register("lr_sched", sched)
        m.register("data", it)
        start = 0
        if resume:
            meta = m.restore(scope=global_scope())
            assert meta is not None
            start = meta["step"]
        if crash_at is not None:
            def hook(phase, step):
                if phase == "pre_commit" and step == crash_at:
                    raise RuntimeError("injected mid-save crash")

            m.set_fault_hook(hook)
        losses = []
        for step in range(start + 1, steps + 1):
            bx, by = next(it)
            out = exe.run(main, feed={"x": bx, "y": by},
                          fetch_list=[loss])
            losses.append(float(np.asarray(out[0]).ravel()[0]))
            sched.step()
            m.save(step, scope=global_scope())
            if crash_at is not None and step < crash_at:
                # the crashing run commits each pre-crash step (a fast
                # loop would otherwise coalesce them away — correct for
                # throughput, but this test needs step crash_at-1 on
                # disk to prove the fallback lands exactly there)
                m.wait()
        if crash_at is None:
            m.wait()
        else:
            with pytest.raises(CheckpointError, match="injected"):
                m.wait()
        sc = global_scope()
        params = {n: np.asarray(sc.get_var(n))
                  for n in sc.local_var_names()
                  if hasattr(sc.get_var(n), "dtype")}
        return losses, params, m
    finally:
        _switch_scope(old)


def test_async_crash_resume_bitwise_parity(tmp_path):
    """THE acceptance oracle: crash during the async save of step 4 ->
    restore lands on intact step 3 -> resumed steps 4..7 are bitwise the
    uninterrupted run's (params + optimizer slots + LR step + RNG +
    iterator position + loss-scale all carried)."""
    oracle_dir = str(tmp_path / "oracle")
    crash_dir = str(tmp_path / "crashy")

    full_losses, full_params, mo = _run_training(oracle_dir, steps=7)
    mo.close()

    # run B: dies mid-commit of step 4's async save
    b_losses, _, mb = _run_training(crash_dir, steps=4, crash_at=4)
    mb.set_fault_hook(None)
    mb.close()
    # the torn step is on disk as .tmp only; newest intact is 3
    assert os.path.isdir(os.path.join(crash_dir, "step_4.tmp"))
    probe = CheckpointManager(crash_dir)
    assert probe.latest_intact_step() == 3
    probe.close()

    # run C: fresh process restores and continues 4 steps (>= 3)
    c_losses, c_params, mc = _run_training(crash_dir, steps=7,
                                           resume=True)
    mc.close()

    # pre-crash prefix matched the oracle too (sanity)
    np.testing.assert_array_equal(b_losses, full_losses[:4])
    # resumed steps 4..7: bitwise identical losses and final state
    np.testing.assert_array_equal(c_losses, full_losses[3:])
    assert sorted(c_params) == sorted(full_params)
    for n in full_params:
        np.testing.assert_array_equal(c_params[n], full_params[n],
                                      err_msg=n)


def test_async_vs_sync_bitwise_state_parity(tmp_path):
    """The background writer must commit exactly the snapshot the step
    boundary saw: async and sync checkpoints of the same run are
    bitwise identical."""
    a_losses, _, ma = _run_training(str(tmp_path / "a"), steps=3)
    ma.close()
    # sync manager, same deterministic run
    sync_mgr = CheckpointManager(str(tmp_path / "b"), keep_n=0,
                                 async_save=False)
    b_losses, _, mb = _run_training(str(tmp_path / "b"), steps=3,
                                    manager=sync_mgr)
    mb.close()
    np.testing.assert_array_equal(a_losses, b_losses)
    sa = CheckpointManager(str(tmp_path / "a")).restore(step=3)["state"]
    sb = CheckpointManager(str(tmp_path / "b")).restore(step=3)["state"]
    _assert_state_equal(sa, sb)


def test_loss_scale_and_lr_state_actually_round_trip(tmp_path):
    """White-box: the AMP dynamic loss-scale counters and the LR var are
    IN the checkpoint and move (incr_every_n_steps=2 doubles the scale;
    StepDecay halves the LR every 2 steps)."""
    _, params, m = _run_training(str(tmp_path), steps=4)
    m.close()
    state = CheckpointManager(str(tmp_path)).restore(step=4)["state"]
    names = sorted(state)
    ls = [n for n in names if "loss_scaling" in n]
    lr = [n for n in names if n.startswith("learning_rate")]
    good = [n for n in names if "good_steps" in n]
    assert ls and lr and good, names
    assert float(state[ls[0]][0]) == 2.0 ** 6  # 2 doublings in 4 steps
    np.testing.assert_allclose(float(state[lr[0]][0]), 0.1 * 0.5 ** 2)
    assert "@RNG_KEY@" in names


# ---------------------------------------------------------------------------
# hapi ModelCheckpoint: async + retention
# ---------------------------------------------------------------------------


def test_model_checkpoint_async_retention(tmp_path):
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi.callbacks import ModelCheckpoint
    from paddle_tpu.io import DataLoader, TensorDataset

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 1)

        def forward(self, x):
            return self.fc(x)

    rs = np.random.RandomState(0)
    X = rs.randn(16, 4).astype("f4")
    Y = (X.sum(1, keepdims=True) * 0.5).astype("f4")

    def build():
        model = pt.Model(Net())
        model.prepare(optimizer=pt.optimizer.Adam(
            0.01, parameters=model.parameters()),
            loss=nn.MSELoss())
        return model

    model = build()

    class DrainedCheckpoint(ModelCheckpoint):
        """Commit every epoch: a fast fit() loop otherwise coalesces
        intermediate epochs away (correct manager behavior, but this
        test pins the retention set deterministically)."""

        def on_epoch_end(self, epoch, logs=None):
            super().on_epoch_end(epoch, logs)
            if self._manager is not None:
                self._manager.wait()

    cb = DrainedCheckpoint(save_freq=1, save_dir=str(tmp_path), keep_n=2,
                           async_save=True)
    loader = DataLoader(TensorDataset([X, Y]), batch_size=8,
                        shuffle=False)
    model.fit(loader, epochs=4, verbose=0, callbacks=[cb])
    # retention: only the 2 newest epochs survive; commits are atomic
    kept = cb._manager.all_steps()
    assert kept == [2, 3]
    for s in kept:
        assert (tmp_path / f"step_{s}" / "MANIFEST.json").is_file()
    # legacy final export still written
    assert (tmp_path / "final.pdparams").is_file()

    trained = {k: np.asarray(v.numpy())
               for k, v in model.network.state_dict().items()}
    fresh = build()
    epoch = cb.restore_latest(fresh)
    assert epoch == 3
    for k, v in fresh.network.state_dict().items():
        np.testing.assert_array_equal(np.asarray(v.numpy()), trained[k])
    cb._manager.close()


# ---------------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------------


def test_two_rank_async_saves_queue_fifo(tmp_path):
    """async_save + world>1: pending saves must NOT be coalesced (the
    drop decision is per-rank timing, and the commit barriers need every
    rank's writer to run the identical step sequence).  Three rapid-fire
    async saves from both ranks must all commit, in order, with no
    barrier deadlock."""
    from paddle_tpu.distributed.fleet.utils import KVServer

    srv = KVServer(0)
    srv.start()
    try:
        ep = f"127.0.0.1:{srv.port}"
        mgrs = [CheckpointManager(
            str(tmp_path), keep_n=0, async_save=True, rank=r, world_size=2,
            barrier=KVBarrier(ep, rank=r, world_size=2, timeout=30))
            for r in range(2)]
        full = np.arange(8, dtype="f4")
        for step in (1, 2, 3):
            for r, m in enumerate(mgrs):
                # queued back-to-back: single-process managers would
                # coalesce 1 and 2 away here
                m.save(step, state={
                    "s": LocalShard(full[r * 4:(r + 1) * 4] + step,
                                    full.shape)})
        errs = []

        def drain(m):
            try:
                m.wait()
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=drain, args=(m,)) for m in mgrs]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in ts), "wait() deadlocked"
        assert not errs, errs
        assert mgrs[0].all_steps() == [1, 2, 3]
        meta = mgrs[0].restore()
        np.testing.assert_array_equal(meta["state"]["s"], full + 3)
        for m in mgrs:
            m.close()
    finally:
        srv.stop()


def test_kv_barrier_unreachable_server_times_out_as_checkpoint_error():
    """A down KV server (URLError: connection refused) must surface as a
    deadline CheckpointError, not a raw URLError mid-save."""
    b = KVBarrier("127.0.0.1:9", rank=0, world_size=1, timeout=0.5)
    with pytest.raises(CheckpointError, match="cannot announce"):
        b("commit:1")


def test_kv_barrier_past_tags_trimmed_on_all_ranks(tmp_path):
    from paddle_tpu.distributed.fleet.utils import KVServer

    srv = KVServer(0)
    srv.start()
    try:
        bs = [KVBarrier(f"127.0.0.1:{srv.port}", rank=r, world_size=2,
                        timeout=30) for r in range(2)]
        errs = []

        def run(b):
            try:
                for i in range(6):
                    b(f"t{i}")
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=run, args=(b,)) for b in bs]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert not errs, errs
        for b in bs:
            assert len(b._past_tags) <= 2  # non-zero rank trims too
            assert len(b._tag_gens) <= 3  # swept tags drop their gens
    finally:
        srv.stop()


def test_resumable_iterator_stale_restore_state_raises(tmp_path):
    """A restored batch position past the loader's current epoch length
    (dataset shrank between save and resume) must raise, not let
    StopIteration silently end the consumer's for-loop."""
    batches = [np.full((2,), i, "f4") for i in range(3)]
    it = ResumableIterator(batches)
    it.set_state_dict({"epoch": 0, "batch": 5})  # loader only has 3
    with pytest.raises(CheckpointError, match="fast-forward"):
        next(it)


def test_model_checkpoint_legacy_format(tmp_path):
    """legacy_format=True keeps the reference per-epoch layout
    (save_dir/{epoch} via Model.save) for consumers that load those
    paths."""
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi.callbacks import ModelCheckpoint
    from paddle_tpu.io import DataLoader, TensorDataset

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 1)

        def forward(self, x):
            return self.fc(x)

    rs = np.random.RandomState(0)
    X = rs.randn(8, 4).astype("f4")
    Y = X.sum(1, keepdims=True).astype("f4")
    model = pt.Model(Net())
    model.prepare(optimizer=pt.optimizer.Adam(
        0.01, parameters=model.parameters()), loss=nn.MSELoss())
    cb = ModelCheckpoint(save_freq=1, save_dir=str(tmp_path),
                         legacy_format=True)
    loader = DataLoader(TensorDataset([X, Y]), batch_size=8, shuffle=False)
    model.fit(loader, epochs=2, verbose=0, callbacks=[cb])
    assert (tmp_path / "0.pdparams").is_file()
    assert (tmp_path / "1.pdparams").is_file()
    assert (tmp_path / "final.pdparams").is_file()
    assert cb._manager is None  # the manager path never engaged


def test_save_sharded_explicit_step(tmp_path):
    """Multi-process callers pass the (globally agreed) training step so
    no rank derives it from a lag-prone local directory listing."""
    from paddle_tpu.distributed import checkpoint as dckpt

    sc = Scope()
    sc.set_var("w", np.ones((2,), "f4"))
    dckpt.save_sharded(sc, str(tmp_path), step=42)
    m = dckpt._MANAGERS[os.path.abspath(str(tmp_path))]
    assert m.all_steps() == [42]
    dckpt.save_sharded(sc, str(tmp_path))  # inference still one-past
    assert m.all_steps() == [42, 43]


def test_model_checkpoint_roundtrips_lr_scheduler_state(tmp_path):
    """Dict-valued optimizer state (the LR_Scheduler entry) rides the
    host-state JSON — resume must not restart the schedule."""
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi.callbacks import ModelCheckpoint
    from paddle_tpu.io import DataLoader, TensorDataset
    from paddle_tpu.optimizer_lr import StepDecay

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 1)

        def forward(self, x):
            return self.fc(x)

    rs = np.random.RandomState(0)
    X = rs.randn(8, 4).astype("f4")
    Y = X.sum(1, keepdims=True).astype("f4")

    def build():
        model = pt.Model(Net())
        sched = StepDecay(0.1, step_size=2)
        model.prepare(optimizer=pt.optimizer.Adam(
            sched, parameters=model.parameters()), loss=nn.MSELoss())
        return model, sched

    model, sched = build()
    sched.step(), sched.step(), sched.step()
    saved_state = sched.state_dict()
    assert saved_state["last_epoch"] == 3
    cb = ModelCheckpoint(save_freq=1, save_dir=str(tmp_path),
                         async_save=False)
    loader = DataLoader(TensorDataset([X, Y]), batch_size=8, shuffle=False)
    model.fit(loader, epochs=1, verbose=0, callbacks=[cb])

    fresh, fresh_sched = build()
    assert fresh_sched.state_dict() != saved_state
    cb.restore_latest(fresh)
    assert fresh_sched.state_dict() == saved_state
    cb._manager.close()


def test_world1_manager_rejects_partial_shard(tmp_path):
    """A partial shard saved through a world_size=1 manager (e.g. the
    rank-0-local auto-checkpoint over ZeRO-sharded state) can never
    restore — the save must fail loudly, not commit a dead snapshot."""
    m = CheckpointManager(str(tmp_path), async_save=False)
    block = np.arange(4, dtype="f4")
    with pytest.raises(CheckpointError, match="partial shard"):
        m.save(1, state={"s": LocalShard(block, (8,))})
    assert m.all_steps() == []
    # a FULL LocalShard (block == global) is fine single-process
    m.save(2, state={"s": LocalShard(block, (4,))})
    np.testing.assert_array_equal(m.restore()["state"]["s"], block)
    m.close()


def test_kv_barrier_dead_rank_fails_fast_with_named_rank(tmp_path):
    """ISSUE 14 satellite: a 2-rank barrier whose peer is dead-listed
    by the health plane mid-wait fails FAST with the missing rank
    named, instead of burning the full deadline.  Rank 0 arrives and
    polls; rank 1 never arrives and gets dead-listed ~0.4s in — the
    raise must come well under the 30s deadline."""
    from paddle_tpu.distributed.fleet.utils import KVServer

    srv = KVServer(0)
    srv.start()
    try:
        ep = f"127.0.0.1:{srv.port}"
        dead = set()
        b = KVBarrier(ep, rank=0, world_size=2, timeout=30,
                      dead_ranks_fn=lambda: dead)
        threading.Timer(0.4, lambda: dead.add(1)).start()
        t0 = time.monotonic()
        with pytest.raises(CheckpointError,
                           match=r"rank\(s\) \[1\] dead-listed"):
            b("written:9:j0")
        assert time.monotonic() - t0 < 10.0  # fast, not the deadline
    finally:
        srv.stop()


def test_kv_barrier_dead_rank_fn_errors_do_not_fail_the_barrier(
        tmp_path):
    """No evidence, no verdict: a dead_ranks_fn that raises (health
    aggregator down) must not fail a barrier whose peers DO arrive."""
    from paddle_tpu.distributed.fleet.utils import KVServer

    srv = KVServer(0)
    srv.start()
    try:
        ep = f"127.0.0.1:{srv.port}"

        def broken():
            raise OSError("aggregator down")

        bs = [KVBarrier(ep, rank=r, world_size=2, timeout=30,
                        dead_ranks_fn=broken) for r in range(2)]
        errs = []

        def run(r):
            try:
                bs[r]("t")
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert not errs, errs
    finally:
        srv.stop()


def test_kv_barrier_stalled_server_times_out_as_checkpoint_error():
    """A server that ACCEPTS the connection but never responds raises a
    raw TimeoutError from urlopen (not URLError) — it must still be
    retried until the deadline and surface as CheckpointError."""
    import socket

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    try:
        port = srv.getsockname()[1]
        b = KVBarrier(f"127.0.0.1:{port}", rank=0, world_size=1,
                      timeout=0.5)
        with pytest.raises(CheckpointError, match="cannot announce"):
            b("x")
    finally:
        srv.close()


def test_multi_rank_async_queue_is_bounded(tmp_path):
    """FIFO (world>1) mode has no coalescing, so save() must apply
    backpressure: each pending job holds a full host snapshot and an
    unbounded backlog would exhaust host RAM."""
    release = threading.Event()
    m = CheckpointManager(str(tmp_path), keep_n=0, async_save=True,
                          rank=0, world_size=2,
                          barrier=lambda tag: None)
    m.set_fault_hook(lambda phase, step: release.wait(30)
                     if phase == "serialize" else None)
    for s in (1, 2, 3):  # 1 active (stalled) + 2 queued = the cap
        m.save(s, state={"w": np.zeros(1, "f4")})
    unblocked = threading.Event()

    def extra():
        m.save(4, state={"w": np.zeros(1, "f4")})
        unblocked.set()

    t = threading.Thread(target=extra)
    t.start()
    assert not unblocked.wait(0.5), "4th save should block at the cap"
    release.set()
    assert unblocked.wait(20), "save must unblock once the writer drains"
    t.join(timeout=10)
    m.wait()
    assert m.all_steps() == [1, 2, 3, 4]  # FIFO: nothing coalesced
    m.close()


def test_model_checkpoint_legacy_restore_latest(tmp_path):
    """legacy_format restore_latest loads the newest save_dir/{epoch}
    Model.save files instead of silently reporting 'no checkpoint'."""
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi.callbacks import ModelCheckpoint
    from paddle_tpu.io import DataLoader, TensorDataset

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 1)

        def forward(self, x):
            return self.fc(x)

    rs = np.random.RandomState(0)
    X = rs.randn(8, 4).astype("f4")
    Y = X.sum(1, keepdims=True).astype("f4")

    def build():
        model = pt.Model(Net())
        model.prepare(optimizer=pt.optimizer.Adam(
            0.01, parameters=model.parameters()), loss=nn.MSELoss())
        return model

    model = build()
    cb = ModelCheckpoint(save_freq=1, save_dir=str(tmp_path),
                         legacy_format=True)
    loader = DataLoader(TensorDataset([X, Y]), batch_size=8, shuffle=False)
    model.fit(loader, epochs=2, verbose=0, callbacks=[cb])
    trained = {k: np.asarray(v.numpy())
               for k, v in model.network.state_dict().items()}

    fresh = build()
    assert cb.restore_latest(fresh) == 1
    for k, v in fresh.network.state_dict().items():
        np.testing.assert_array_equal(np.asarray(v.numpy()), trained[k])
    assert cb._manager is None  # legacy path never builds a manager


def test_kv_barrier_resyncs_after_asymmetric_timeout(tmp_path):
    """Rank 1 times out on a barrier rank 0 never reached (asymmetric
    failure): with per-tag generations the NEXT tag still rendezvous —
    a global call counter would desynchronize every later barrier."""
    from paddle_tpu.distributed.fleet.utils import KVServer

    srv = KVServer(0)
    srv.start()
    try:
        ep = f"127.0.0.1:{srv.port}"
        bs = [KVBarrier(ep, rank=r, world_size=2, timeout=30)
              for r in range(2)]
        bs[1].timeout = 0.5
        with pytest.raises(CheckpointError):
            bs[1]("orphan")  # rank 0 never calls this one
        bs[1].timeout = 30
        errs = []

        def run(b):
            try:
                b("next")
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=run, args=(b,)) for b in bs]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert not errs, errs
    finally:
        srv.stop()


def test_multi_rank_save_recovers_after_asymmetric_failure(tmp_path):
    """Rank 0's writer dies mid-save (rank 1 times out at the commit
    barrier): a RETRY of the same step must succeed — the job-sequence
    barrier tags plus per-tag generations keep the ranks aligned, so one
    failed save can't brick checkpointing for the life of the run."""
    from paddle_tpu.distributed.fleet.utils import KVServer

    srv = KVServer(0)
    srv.start()
    try:
        ep = f"127.0.0.1:{srv.port}"
        mgrs = [CheckpointManager(
            str(tmp_path), async_save=False, rank=r, world_size=2,
            barrier=KVBarrier(ep, rank=r, world_size=2, timeout=4))
            for r in range(2)]
        boom = {"on": True}

        def fault(phase, step):
            if boom["on"] and phase == "write_shard":
                raise RuntimeError("disk full")

        mgrs[0].set_fault_hook(fault)
        full = np.arange(4, dtype="f4")
        states = [{"s": LocalShard(full[r * 2:(r + 1) * 2], full.shape)}
                  for r in range(2)]

        def attempt():
            errs = [None, None]

            def run(r):
                try:
                    mgrs[r].save(1, state=states[r])
                except BaseException as e:  # noqa: BLE001
                    errs[r] = e

            ts = [threading.Thread(target=run, args=(r,))
                  for r in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
            return errs

        errs = attempt()
        assert errs[0] is not None  # the injected write failure
        assert errs[1] is not None  # barrier timeout, not a hang
        assert mgrs[0].all_steps() == []

        boom["on"] = False
        errs = attempt()
        assert errs == [None, None], errs
        assert mgrs[0].all_steps() == [1]
        np.testing.assert_array_equal(mgrs[0].restore()["state"]["s"],
                                      full)
        for m in mgrs:
            m.close()
    finally:
        srv.stop()


def test_resumable_iterator_coherent_after_stale_state_error(tmp_path):
    """A caught stale-restore CheckpointError leaves the iterator at a
    coherent position: continuing restarts the restored epoch from
    batch 0 instead of tracking a position that never matched the feed."""
    batches = [np.full((2,), i, "f4") for i in range(3)]
    it = ResumableIterator(batches)
    it.set_state_dict({"epoch": 2, "batch": 5})
    with pytest.raises(CheckpointError):
        next(it)
    assert (it.epoch, it.batch) == (2, 0)
    np.testing.assert_array_equal(next(it), batches[0])
    assert (it.epoch, it.batch) == (2, 1)


def test_two_rank_2d_localshard_commit_and_elastic_restore(tmp_path):
    """Tensor-parallel layouts: LocalShard blocks with non-axis-0 / 2D
    origins (a column-parallel weight's block starts at (0, k*N/mp))
    save per rank, restore bitwise, and re-assemble into the FULL value
    — so the checkpoint resumes elastically onto any other mp degree
    (the executor reshards full host values per the new plan)."""
    from paddle_tpu.distributed.fleet.utils import KVServer

    srv = KVServer(0)
    srv.start()
    try:
        ep = f"127.0.0.1:{srv.port}"
        col = np.arange(32, dtype="f4").reshape(4, 8)   # (None,'mp') cols
        row = np.arange(24, dtype="f4").reshape(8, 3)   # ('mp',None) rows
        grid = np.arange(64, dtype="f4").reshape(8, 8)  # ('dp','mp') 2D
        mgrs = [CheckpointManager(
            str(tmp_path), async_save=False, rank=r, world_size=2,
            barrier=KVBarrier(ep, rank=r, world_size=2, timeout=30))
            for r in range(2)]
        states = [
            {"col": LocalShard(col[:, :4], col.shape, origin=(0, 0)),
             "row": LocalShard(row[:4], row.shape, origin=(0, 0)),
             "grid": LocalShard(grid[:, :4], grid.shape, origin=(0, 0))},
            {"col": LocalShard(col[:, 4:], col.shape, origin=(0, 4)),
             "row": LocalShard(row[4:], row.shape, origin=(4, 0)),
             # rank 1 holds BOTH remaining 2D blocks of the grid
             # (simulating its two local devices' shards — the manager
             # takes one block per rank, so ranks pre-assemble via
             # ckpt.state._assemble_blocks; here the right half)
             "grid": LocalShard(grid[:, 4:], grid.shape, origin=(0, 4))},
        ]
        # rank 0 also owns the bottom-left block in this layout
        states[0]["grid2"] = LocalShard(grid[4:, :4], grid.shape,
                                        origin=(4, 0))
        states[1]["grid2"] = LocalShard(grid[:4, :4], grid.shape,
                                        origin=(0, 0))
        # grid2 intentionally leaves (4:, 4:) uncovered -> must FAIL
        errs = []

        def run(r):
            try:
                mgrs[r].save(7, state=states[r])
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert not errs, errs

        # holes in coverage fail LOUDLY (grid2 misses its bottom-right)
        with pytest.raises(CheckpointError, match="hole|missing"):
            mgrs[0].restore()

        # re-save without the torn var: full 2D re-assembly round-trips
        for st in states:
            st.pop("grid2")

        def run8(r):
            try:
                mgrs[r].save(8, state=states[r])
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=run8, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert not errs, errs

        meta = mgrs[0].restore(step=8)
        np.testing.assert_array_equal(meta["state"]["col"], col)
        np.testing.assert_array_equal(meta["state"]["row"], row)
        np.testing.assert_array_equal(meta["state"]["grid"], grid)
    finally:
        srv.stop()
        for m in mgrs:
            m.close()


def test_assemble_blocks_2d_grid():
    """ckpt.state._assemble_blocks stitches a process's device blocks
    (cartesian origin grid) into one contiguous hyperrectangle."""
    from paddle_tpu.ckpt.state import _assemble_blocks

    full = np.arange(48, dtype="f4").reshape(6, 8)
    blocks = {
        (0, 0): full[:3, :4], (0, 4): full[:3, 4:],
        (3, 0): full[3:, :4], (3, 4): full[3:, 4:],
    }
    arr, origin = _assemble_blocks(blocks, 2)
    assert origin == (0, 0)
    np.testing.assert_array_equal(arr, full)

    # partial (one process's half): assembles the covered rectangle
    arr, origin = _assemble_blocks(
        {(0, 4): full[:3, 4:], (3, 4): full[3:, 4:]}, 2)
    assert origin == (0, 4)
    np.testing.assert_array_equal(arr, full[:, 4:])

    # a non-grid block set must refuse, not mis-assemble
    with pytest.raises(ValueError, match="tile"):
        _assemble_blocks({(0, 0): full[:3, :4], (3, 4): full[3:, 4:]}, 2)


def test_tp_elastic_resume_other_mp_degree(tmp_path):
    """A tp-sharded training run checkpoints, then resumes onto a mesh
    with a DIFFERENT mp degree: the manager hands back full host
    values and the executor reshards them per the new plan — losses
    continue bitwise-identically to an uninterrupted run on the new
    topology."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.framework import unique_name
    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.optimizer import MomentumOptimizer
    from paddle_tpu.param_attr import ParamAttr

    rules = [(r"blk_ffn1\.w_\d+$", "None,mp"),
             (r"blk_ffn1\.b_\d+$", "mp"),
             (r"blk_ffn2\.w_\d+$", "mp,None")]

    def build():
        from paddle_tpu.distributed import fleet

        main, startup = Program(), Program()
        main.random_seed = 1
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [8])
            y = layers.data("y", [1])
            h = layers.fc(x, 16, act="relu", name="blk_ffn1",
                          param_attr=ParamAttr(
                              initializer=ConstantInitializer(0.1)))
            pred = layers.fc(h, 1, name="blk_ffn2",
                             param_attr=ParamAttr(
                                 initializer=ConstantInitializer(0.2)),
                             bias_attr=False)
            loss = layers.mean(layers.square_error_cost(pred, y))
            strat = fleet.DistributedStrategy()
            strat.tensor_parallel = True
            strat.tensor_parallel_configs = {"partition_rules": rules}
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
            fleet.minimize(loss)
        return main, startup, loss

    rs = np.random.RandomState(0)
    X = rs.randn(16, 8).astype("f4")
    Y = (X.sum(1, keepdims=True) * 0.3).astype("f4")
    devs = np.array(jax.devices())

    def mesh_of(dp, mp):
        return jax.sharding.Mesh(devs.reshape(dp, mp), ("dp", "mp"))

    def steps(exe, main, loss, scope, n):
        return [float(np.asarray(exe.run(
            main, feed={"x": X, "y": Y}, fetch_list=[loss],
            scope=scope)[0]).item()) for _ in range(n)]

    # train 3 steps on mp=4, checkpoint
    reset_mesh()
    m4 = mesh_of(2, 4)
    set_mesh(m4)
    main, startup, loss = build()
    sc = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace(), mesh=m4)
    exe.run(startup, scope=sc)
    steps(exe, main, loss, sc, 3)
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    mgr.save(3, scope=sc)
    mgr.close()
    # oracle: continue 2 more steps on an mp=2 mesh from the SAME state
    reset_mesh()
    m2 = mesh_of(4, 2)
    set_mesh(m2)
    main2, startup2, loss2 = build()
    sc2 = pt.framework.Scope()
    exe2 = pt.Executor(pt.CPUPlace(), mesh=m2)
    exe2.run(startup2, scope=sc2)  # init, then overwrite via restore
    mgr2 = CheckpointManager(str(tmp_path), async_save=False)
    meta = mgr2.restore(scope=sc2)
    mgr2.close()
    assert meta["step"] == 3
    cont = steps(exe2, main2, loss2, sc2, 2)
    assert np.isfinite(cont).all()

    # reference: 5 uninterrupted steps on the ORIGINAL topology — the
    # resumed trajectory must continue it bitwise
    reset_mesh()
    m4b = mesh_of(2, 4)
    set_mesh(m4b)
    main3, startup3, loss3 = build()
    sc3 = pt.framework.Scope()
    exe3 = pt.Executor(pt.CPUPlace(), mesh=m4b)
    exe3.run(startup3, scope=sc3)
    ref = steps(exe3, main3, loss3, sc3, 5)
    np.testing.assert_allclose(cont, ref[3:], rtol=1e-6, atol=1e-7)
    # and the restored state on mp=2 really lives 2-way sharded
    w = sc2.get_var("blk_ffn1.w_0")
    assert w.addressable_shards[0].data.shape == (8, 8)  # 16/2 cols
    reset_mesh()
