"""Input-pipeline-inclusive training path (the bench.py pipeline mode):
multiprocess DataLoader -> uint8 feed -> on-device normalize -> chunked
run_steps.  Small shapes on CPU; the full-size numbers come from
bench.py on the chip."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.amp.static_amp import decorate
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import program_guard
from paddle_tpu.io import DataLoader, Dataset


class _TinyImages(Dataset):
    def __init__(self, n=128, shape=(3, 32, 32)):
        self.n, self.shape = n, shape

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rs = np.random.RandomState(i)
        img = rs.randint(0, 256, self.shape, np.uint8)
        return img, np.array([i % 10], np.int64)


def test_uint8_chunked_training_pipeline():
    import jax

    from paddle_tpu.vision.static_models import resnet50_train_program

    # resnet50 is too heavy for CPU CI; reuse the builder's uint8 head
    # contract on a small custom net instead
    from paddle_tpu import layers
    from paddle_tpu.framework.program import Program
    from paddle_tpu.optimizer import MomentumOptimizer

    B, K = 8, 3
    main, startup = Program(), Program()
    main.random_seed = 1
    with unique_name.guard(), program_guard(main, startup):
        raw = layers.data("image", [3, 32, 32], dtype="uint8")
        img = layers.scale(layers.cast(raw, "float32"), 1.0 / 127.5,
                           bias=-1.0)
        img.shape = tuple(raw.shape)
        h = layers.conv2d(img, 8, 3, padding=1, act="relu")
        h = layers.pool2d(h, 2, pool_stride=2)
        logits = layers.fc(h, 10)  # fc flattens trailing dims itself
        label = layers.data("label", [1], dtype="int64")
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        MomentumOptimizer(0.05, 0.9).minimize(loss)

    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)

    loader = DataLoader(_TinyImages(), batch_size=B, num_workers=2,
                        shuffle=False)
    it = iter(loader)

    def next_chunk():
        imgs, lbls = [], []
        for _ in range(K):
            im, lb = next(it)
            imgs.append(np.asarray(im))
            lbls.append(np.asarray(lb).astype("int32"))
        return {"image": np.stack(imgs), "label": np.stack(lbls)}

    losses = []
    for _ in range(2):
        out = exe.run_steps(main, feed=next_chunk(), fetch_list=[loss],
                            scope=scope)
        vals = np.asarray(out[0]).reshape(-1)
        assert vals.shape[0] == K
        losses.extend(float(v) for v in vals)
    assert all(np.isfinite(losses)), losses
    # uint8 feed dtype is preserved end-to-end (normalize on device)
    assert np.asarray(next_chunk()["image"]).dtype == np.uint8
