"""Sampling ops: NCE, sample_logits, correlation — structural + oracle
checks (sampling is stochastic; correlations are exact).
"""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.framework.program import Program, program_guard


def _run(op_type, feed_specs, outputs, attrs):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        blk = main.global_block
        ins = {}
        feed = {}
        for slot, name, arr in feed_specs:
            blk.create_var(name=name, shape=arr.shape,
                           dtype=str(arr.dtype), stop_gradient=True)
            ins.setdefault(slot, []).append(name)
            feed[name] = arr
        outs = {}
        for slot, name in outputs:
            blk.create_var(name=name, dtype="float32")
            outs.setdefault(slot, []).append(name)
        blk.append_op(op_type, ins, outs, attrs)
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.framework.Scope()
    exe.run(startup, scope=sc)
    main.random_seed = 5
    got = exe.run(main, feed=feed,
                  fetch_list=[n for _, n in outputs], scope=sc)
    return [np.asarray(g) for g in got]


def test_correlation_zero_displacement_is_channel_mean_product():
    rs = np.random.RandomState(0)
    x1 = rs.randn(1, 4, 5, 5).astype("f4")
    x2 = rs.randn(1, 4, 5, 5).astype("f4")
    (out,) = _run(
        "correlation",
        [("Input1", "x1", x1), ("Input2", "x2", x2)],
        [("Output", "out")],
        {"pad_size": 1, "kernel_size": 1, "max_displacement": 1,
         "stride1": 1, "stride2": 1})
    assert out.shape == (1, 9, 5, 5)
    # center displacement (dy=0, dx=0) is index 4 of the 3x3 grid
    want = (x1 * x2).mean(axis=1)
    # padded border rows include zero-padding; compare interior
    np.testing.assert_allclose(out[0, 4, 1:-1, 1:-1], want[0, 1:-1, 1:-1],
                               rtol=1e-5, atol=1e-6)


def test_nce_cost_finite_and_shaped():
    rs = np.random.RandomState(1)
    B, D, C, T, K = 4, 6, 20, 1, 5
    x = rs.randn(B, D).astype("f4")
    lbl = rs.randint(0, C, (B, T)).astype("i8")
    w = rs.randn(C, D).astype("f4") * 0.1
    b = np.zeros(C, "f4")
    cost, slog = _run(
        "nce",
        [("Input", "x", x), ("Label", "lbl", lbl), ("Weight", "w", w),
         ("Bias", "b", b)],
        [("Cost", "cost"), ("SampleLogits", "slog")],
        {"num_total_classes": C, "num_neg_samples": K, "sampler": 0})
    assert cost.shape == (B, 1) and np.isfinite(cost).all()
    assert (cost > 0).all()  # NCE loss is positive
    assert slog.shape == (B, T + K)


def test_sample_logits_gathers_true_label_first():
    rs = np.random.RandomState(2)
    B, C, K = 3, 10, 4
    logits = rs.randn(B, C).astype("f4")
    lbl = rs.randint(0, C, (B, 1)).astype("i8")
    sampled, samples = _run(
        "sample_logits",
        [("Logits", "lg", logits), ("Labels", "lb", lbl)],
        [("SampledLogits", "sl"), ("Samples", "sm")],
        {"num_samples": K, "sampler": 0,
         "remove_accidental_hits": False})
    assert sampled.shape == (B, 1 + K)
    # first column = true-label logit + log C (uniform logQ correction)
    want = logits[np.arange(B), lbl[:, 0]] + np.log(C)
    np.testing.assert_allclose(sampled[:, 0], want, rtol=1e-5)
    np.testing.assert_array_equal(samples[:, 0], lbl[:, 0])


def test_correlation_kernel3_matches_numpy_oracle():
    """kernel_size=3: windowed channel-mean products; direct numpy
    reference (FlowNet-C correlation, correlation_op.cu)."""
    rs = np.random.RandomState(2)
    C, H, W = 3, 6, 7
    x1 = rs.randn(1, C, H, W).astype("f4")
    x2 = rs.randn(1, C, H, W).astype("f4")
    pad, ks, md = 2, 3, 2
    (out,) = _run(
        "correlation",
        [("Input1", "x1", x1), ("Input2", "x2", x2)],
        [("Output", "out")],
        {"pad_size": pad, "kernel_size": ks, "max_displacement": md,
         "stride1": 1, "stride2": 1})

    # reference geometry: border_radius = max_displacement + kernel
    # radius bounds output size and centers (correlation_op.cc)
    kr = (ks - 1) // 2
    border = md + kr
    hp, wp = H + 2 * pad, W + 2 * pad
    x1p = np.zeros((C, hp, wp), "f4")
    x2p = np.zeros_like(x1p)
    x1p[:, pad:pad + H, pad:pad + W] = x1[0]
    x2p[:, pad:pad + H, pad:pad + W] = x2[0]
    oh, ow = hp - 2 * border, wp - 2 * border
    assert out.shape == (1, (2 * md + 1) ** 2, oh, ow), out.shape
    ref = np.zeros(((2 * md + 1) ** 2, oh, ow), "f4")
    di = 0
    for dy in range(-md, md + 1):
        for dx in range(-md, md + 1):
            for i in range(oh):
                for j in range(ow):
                    cy, cx = border + i, border + j
                    a = x1p[:, cy - kr:cy + kr + 1, cx - kr:cx + kr + 1]
                    b = x2p[:, cy + dy - kr:cy + dy + kr + 1,
                            cx + dx - kr:cx + dx + kr + 1]
                    ref[di, i, j] = (a * b).mean()
            di += 1
    np.testing.assert_allclose(out[0], ref, rtol=1e-5, atol=1e-6)


def test_nce_custom_dist_sampler():
    """sampler=2 draws from CustomDistProbs (reference CustomSampler):
    classes with zero probability must never be sampled, and the
    reported sample probabilities must read the user distribution."""
    rs = np.random.RandomState(3)
    B, D, C, K = 4, 6, 10, 50
    x = rs.randn(B, D).astype("f4")
    lbl = rs.randint(0, 3, (B, 1)).astype("i8")
    w = rs.randn(C, D).astype("f4") * 0.1
    b = np.zeros(C, "f4")
    probs = np.zeros(C, "f4")
    probs[:3] = [0.5, 0.3, 0.2]  # classes 3..9 never drawn
    cost, slog, slab = _run(
        "nce",
        [("Input", "x", x), ("Label", "lbl", lbl), ("Weight", "w", w),
         ("Bias", "b", b), ("CustomDistProbs", "cd", probs)],
        [("Cost", "cost"), ("SampleLogits", "slog"),
         ("SampleLabels", "slab")],
        {"num_total_classes": C, "num_neg_samples": K, "sampler": 2})
    assert cost.shape == (B, 1) and np.isfinite(cost).all()
    sampled = slab[:, 1:]  # negatives
    assert sampled.max() <= 2, sampled.max()


def test_correlation_kernel3_stride2():
    """stride1=2 with k=3: banded strided reduce must hit the same
    centers as the naive oracle."""
    rs = np.random.RandomState(4)
    C, H, W = 2, 8, 9
    x1 = rs.randn(1, C, H, W).astype("f4")
    x2 = rs.randn(1, C, H, W).astype("f4")
    pad, ks, md, s1 = 2, 3, 2, 2
    (out,) = _run(
        "correlation",
        [("Input1", "x1", x1), ("Input2", "x2", x2)],
        [("Output", "out")],
        {"pad_size": pad, "kernel_size": ks, "max_displacement": md,
         "stride1": s1, "stride2": 1})
    kr = (ks - 1) // 2
    border = md + kr
    hp, wp = H + 2 * pad, W + 2 * pad
    x1p = np.zeros((C, hp, wp), "f4")
    x2p = np.zeros_like(x1p)
    x1p[:, pad:pad + H, pad:pad + W] = x1[0]
    x2p[:, pad:pad + H, pad:pad + W] = x2[0]
    oh = -(-(hp - 2 * border) // s1)
    ow = -(-(wp - 2 * border) // s1)
    assert out.shape == (1, (2 * md + 1) ** 2, oh, ow), out.shape
    di = 0
    for dy in range(-md, md + 1):
        for dx in range(-md, md + 1):
            for i in range(oh):
                for j in range(ow):
                    cy, cx = border + s1 * i, border + s1 * j
                    a = x1p[:, cy - kr:cy + kr + 1, cx - kr:cx + kr + 1]
                    b = x2p[:, cy + dy - kr:cy + dy + kr + 1,
                            cx + dx - kr:cx + dx + kr + 1]
                    np.testing.assert_allclose(
                        out[0, di, i, j], (a * b).mean(),
                        rtol=1e-5, atol=1e-6)
            di += 1
