"""Detection ops vs numpy oracles (reference operators/detection/)."""
import numpy as np

from op_test import OpTest


class TestIouSimilarity(OpTest):
    op_type = "iou_similarity"

    def setup(self):
        x = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], "f4")
        y = np.array([[0, 0, 2, 2], [2, 2, 4, 4], [0, 0, 4, 4]], "f4")

        def iou(a, b):
            ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
            iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
            inter = ix * iy
            ua = ((a[2] - a[0]) * (a[3] - a[1])
                  + (b[2] - b[0]) * (b[3] - b[1]) - inter)
            return inter / ua

        out = np.array([[iou(a, b) for b in y] for a in x], "f4")
        self.inputs = {"X": [("x", x)], "Y": [("y", y)]}
        self.attrs = {"box_normalized": True}
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output()


class TestBoxCoderDecode(OpTest):
    op_type = "box_coder"

    def setup(self):
        prior = np.array([[0.1, 0.1, 0.5, 0.5], [0.2, 0.2, 0.8, 0.8]], "f4")
        pvar = np.tile(np.array([[0.1, 0.1, 0.2, 0.2]], "f4"), (2, 1))
        deltas = np.random.RandomState(0).randn(3, 2, 4).astype("f4") * 0.1

        pw = prior[:, 2] - prior[:, 0]
        ph = prior[:, 3] - prior[:, 1]
        pcx = prior[:, 0] + pw / 2
        pcy = prior[:, 1] + ph / 2
        dcx = pvar[:, 0] * deltas[..., 0] * pw + pcx
        dcy = pvar[:, 1] * deltas[..., 1] * ph + pcy
        dw = np.exp(pvar[:, 2] * deltas[..., 2]) * pw
        dh = np.exp(pvar[:, 3] * deltas[..., 3]) * ph
        out = np.stack([dcx - dw / 2, dcy - dh / 2,
                        dcx + dw / 2, dcy + dh / 2], axis=-1).astype("f4")
        self.inputs = {"PriorBox": [("prior", prior)],
                       "PriorBoxVar": [("pvar", pvar)],
                       "TargetBox": [("t", deltas)]}
        self.attrs = {"code_type": "decode_center_size",
                      "box_normalized": True, "axis": 0}
        self.outputs = {"OutputBox": [("out", out)]}

    def test_output(self):
        self.check_output(atol=1e-5, rtol=1e-4)


class TestPriorBox(OpTest):
    op_type = "prior_box"

    def setup(self):
        feat = np.zeros((1, 8, 2, 2), "f4")
        image = np.zeros((1, 3, 32, 32), "f4")
        min_sizes, ar = [4.0], [1.0]
        # cells at step 16, offset .5 -> centers 8, 24; one box (ar=1)
        boxes = np.zeros((2, 2, 1, 4), "f4")
        for i in range(2):
            for j in range(2):
                cx, cy = (j + 0.5) * 16, (i + 0.5) * 16
                boxes[i, j, 0] = [(cx - 2) / 32, (cy - 2) / 32,
                                  (cx + 2) / 32, (cy + 2) / 32]
        var = np.tile(np.array([0.1, 0.1, 0.2, 0.2], "f4"), (2, 2, 1, 1))
        self.inputs = {"Input": [("feat", feat)], "Image": [("img", image)]}
        self.attrs = {"min_sizes": min_sizes, "aspect_ratios": ar,
                      "variances": [0.1, 0.1, 0.2, 0.2], "flip": True,
                      "clip": True, "offset": 0.5}
        self.outputs = {"Boxes": [("boxes", boxes)],
                        "Variances": [("var", var)]}

    def test_output(self):
        self.check_output(atol=1e-5, rtol=1e-4)


class TestYoloBox(OpTest):
    op_type = "yolo_box"

    def setup(self):
        n, a, c, h, w = 1, 2, 3, 2, 2
        rs = np.random.RandomState(0)
        x = rs.randn(n, a * (5 + c), h, w).astype("f4")
        img = np.array([[64, 64]], "i4")
        anchors = [10, 13, 16, 30]
        down = 32

        def sig(v):
            return 1.0 / (1.0 + np.exp(-v))

        xr = x.reshape(n, a, 5 + c, h, w)
        boxes = np.zeros((n, a, h, w, 4), "f4")
        scores = np.zeros((n, a, h, w, c), "f4")
        for ai in range(a):
            for i in range(h):
                for j in range(w):
                    bx = (j + sig(xr[0, ai, 0, i, j])) * 64 / w
                    by = (i + sig(xr[0, ai, 1, i, j])) * 64 / h
                    bw = np.exp(xr[0, ai, 2, i, j]) * anchors[2 * ai] * 64 / (down * w)
                    bh = np.exp(xr[0, ai, 3, i, j]) * anchors[2 * ai + 1] * 64 / (down * h)
                    conf = sig(xr[0, ai, 4, i, j])
                    bb = [max(bx - bw / 2, 0), max(by - bh / 2, 0),
                          min(bx + bw / 2, 63), min(by + bh / 2, 63)]
                    if conf >= 0.5:
                        boxes[0, ai, i, j] = bb
                        scores[0, ai, i, j] = conf * sig(xr[0, ai, 5:, i, j])
        self.inputs = {"X": [("x", x)], "ImgSize": [("img", img)]}
        self.attrs = {"anchors": anchors, "class_num": c,
                      "conf_thresh": 0.5, "downsample_ratio": down,
                      "clip_bbox": True, "scale_x_y": 1.0}
        self.outputs = {
            "Boxes": [("boxes", boxes.reshape(n, a * h * w, 4))],
            "Scores": [("scores", scores.reshape(n, a * h * w, c))],
        }

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)


class TestAnchorGenerator(OpTest):
    op_type = "anchor_generator"

    def setup(self):
        feat = np.zeros((1, 8, 2, 2), "f4")
        sizes, ars, stride, offset = [32.0], [1.0, 2.0], [16.0, 16.0], 0.5
        # reference math (anchor_generator_op.h:53-75)
        whs = []
        for ar in ars:
            for s in sizes:
                base_w = round(np.sqrt(16 * 16 / ar))
                base_h = round(base_w * ar)
                whs.append((s / 16 * base_w, s / 16 * base_h))
        anchors = np.zeros((2, 2, len(whs), 4), "f4")
        for i in range(2):
            for j in range(2):
                xc = j * 16 + 0.5 * 15
                yc = i * 16 + 0.5 * 15
                for k, (aw, ah) in enumerate(whs):
                    anchors[i, j, k] = [xc - 0.5 * (aw - 1),
                                        yc - 0.5 * (ah - 1),
                                        xc + 0.5 * (aw - 1),
                                        yc + 0.5 * (ah - 1)]
        var = np.tile(np.array([0.1, 0.1, 0.2, 0.2], "f4"),
                      (2, 2, len(whs), 1))
        self.inputs = {"Input": [("feat", feat)]}
        self.attrs = {"anchor_sizes": sizes, "aspect_ratios": ars,
                      "stride": stride, "offset": offset,
                      "variances": [0.1, 0.1, 0.2, 0.2]}
        self.outputs = {"Anchors": [("anchors", anchors)],
                        "Variances": [("var", var)]}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)


def test_nms_lowerings_registered():
    """The NMS family is real now (nms_ops.py, fixed-size masked);
    parity tests live in test_nms_ops.py."""
    from paddle_tpu.framework.lowering import LOWERINGS

    for name in ("multiclass_nms", "multiclass_nms2", "matrix_nms",
                 "generate_proposals", "bipartite_match"):
        assert name in LOWERINGS


class TestPriorBoxMinMaxOrderFirst(OpTest):
    """min_max_aspect_ratios_order=True: [min(ar=1), max, other ars]
    (reference prior_box_op.h — the SSD-caffe checkpoint layout)."""

    op_type = "prior_box"

    def setup(self):
        feat = np.zeros((1, 8, 1, 1), "f4")
        image = np.zeros((1, 3, 32, 32), "f4")
        ms, mx, ar = 4.0, 8.0, 2.0
        cx = cy = 16.0  # one cell, step 32, offset .5
        whs = [(ms, ms),
               (np.sqrt(ms * mx), np.sqrt(ms * mx)),
               (ms * np.sqrt(ar), ms / np.sqrt(ar)),
               (ms / np.sqrt(ar), ms * np.sqrt(ar))]  # flip of ar=2
        boxes = np.zeros((1, 1, 4, 4), "f4")
        for p, (bw, bh) in enumerate(whs):
            boxes[0, 0, p] = [(cx - bw / 2) / 32, (cy - bh / 2) / 32,
                              (cx + bw / 2) / 32, (cy + bh / 2) / 32]
        var = np.tile(np.array([0.1, 0.1, 0.2, 0.2], "f4"), (1, 1, 4, 1))
        self.inputs = {"Input": [("feat", feat)], "Image": [("img", image)]}
        self.attrs = {"min_sizes": [ms], "max_sizes": [mx],
                      "aspect_ratios": [1.0, ar],
                      "variances": [0.1, 0.1, 0.2, 0.2], "flip": True,
                      "clip": False, "offset": 0.5,
                      "min_max_aspect_ratios_order": True}
        self.outputs = {"Boxes": [("boxes", boxes)],
                        "Variances": [("var", var)]}

    def test_output(self):
        self.check_output(atol=1e-5, rtol=1e-4)
