"""OpTest parity for the round-3 op-breadth batch: rnn/losses/linalg/
interp/vision/sequence/misc families vs numpy oracles.

Reference parity model: unittests op_test.py pattern — declare inputs/
attrs/expected outputs, run through the real Executor, compare; grads
checked against numeric differences for a representative sample.
"""
import numpy as np

from op_test import OpTest


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


# --------------------------------------------------------------------------
# rnn family
# --------------------------------------------------------------------------


class TestRnnLSTM(OpTest):
    op_type = "rnn"

    def setup(self):
        T, B, I, H = 4, 2, 3, 5
        rs = np.random.RandomState(0)
        x = rs.randn(T, B, I).astype("f4")
        h0 = rs.randn(1, B, H).astype("f4")
        c0 = rs.randn(1, B, H).astype("f4")
        w_ih = rs.randn(4 * H, I).astype("f4") * 0.5
        w_hh = rs.randn(4 * H, H).astype("f4") * 0.5
        b_ih = rs.randn(4 * H).astype("f4") * 0.1
        b_hh = rs.randn(4 * H).astype("f4") * 0.1

        outs = []
        h, c = h0[0], c0[0]
        for t in range(T):
            g = x[t] @ w_ih.T + b_ih + h @ w_hh.T + b_hh
            i, f, gg, o = np.split(g, 4, axis=-1)
            i, f, o = _sigmoid(i), _sigmoid(f), _sigmoid(o)
            c = f * c + i * np.tanh(gg)
            h = o * np.tanh(c)
            outs.append(h)
        out = np.stack(outs)

        self.inputs = {
            "Input": [("x", x)],
            "PreState": [("h0", h0), ("c0", c0)],
            "WeightList": [("w_ih", w_ih), ("w_hh", w_hh),
                           ("b_ih", b_ih), ("b_hh", b_hh)],
        }
        self.attrs = {"mode": "LSTM", "hidden_size": 5, "num_layers": 1,
                      "is_bidirec": False}
        self.outputs = {
            "Out": [("out", out)],
            "State": [("hT", h[None]), ("cT", c[None])],
        }

    def test_output(self):
        self.check_output(no_check_set=["Reserve", "DropoutState"])

    def test_grad(self):
        self.check_grad(["x"], "Out", max_relative_error=0.02)


class TestRnnGRU(OpTest):
    op_type = "rnn"

    def setup(self):
        T, B, I, H = 3, 2, 4, 3
        rs = np.random.RandomState(1)
        x = rs.randn(T, B, I).astype("f4")
        h0 = rs.randn(1, B, H).astype("f4")
        w_ih = rs.randn(3 * H, I).astype("f4") * 0.5
        w_hh = rs.randn(3 * H, H).astype("f4") * 0.5

        h = h0[0]
        outs = []
        for t in range(T):
            xg = x[t] @ w_ih.T
            hg = h @ w_hh.T
            xr, xz, xn = np.split(xg, 3, -1)
            hr, hz, hn = np.split(hg, 3, -1)
            r = _sigmoid(xr + hr)
            z = _sigmoid(xz + hz)
            n = np.tanh(xn + r * hn)
            h = (1 - z) * n + z * h
            outs.append(h)
        self.inputs = {
            "Input": [("x", x)],
            "PreState": [("h0", h0)],
            "WeightList": [("w_ih", w_ih), ("w_hh", w_hh)],
        }
        self.attrs = {"mode": "GRU", "hidden_size": 3, "num_layers": 1}
        self.outputs = {"Out": [("out", np.stack(outs))],
                        "State": [("hT", h[None])]}

    def test_output(self):
        self.check_output(no_check_set=["Reserve", "DropoutState"])


class TestLstmUnit(OpTest):
    op_type = "lstm_unit"

    def setup(self):
        B, H = 3, 4
        rs = np.random.RandomState(2)
        x = rs.randn(B, 4 * H).astype("f4")
        c_prev = rs.randn(B, H).astype("f4")
        # reference lstm_unit_op.h chunk order: (i, f, o, g)
        i, f, o, g = np.split(x, 4, -1)
        c = _sigmoid(f) * c_prev + _sigmoid(i) * np.tanh(g)
        h = _sigmoid(o) * np.tanh(c)
        self.inputs = {"X": [("x", x)], "C_prev": [("c_prev", c_prev)]}
        self.outputs = {"C": [("c", c)], "H": [("h", h)]}

    def test_output(self):
        self.check_output()


# --------------------------------------------------------------------------
# losses
# --------------------------------------------------------------------------


class TestBceLoss(OpTest):
    op_type = "bce_loss"

    def setup(self):
        rs = np.random.RandomState(3)
        x = rs.uniform(0.05, 0.95, (4, 5)).astype("f4")
        lbl = rs.randint(0, 2, (4, 5)).astype("f4")
        out = -(lbl * np.log(x) + (1 - lbl) * np.log(1 - x))
        self.inputs = {"X": [("x", x)], "Label": [("lbl", lbl)]}
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x"], "Out", max_relative_error=0.02)


class TestKldivLoss(OpTest):
    op_type = "kldiv_loss"

    def setup(self):
        rs = np.random.RandomState(4)
        x = np.log(rs.uniform(0.1, 0.9, (3, 4)).astype("f4"))
        t = rs.uniform(0.1, 0.9, (3, 4)).astype("f4")
        loss = (t * (np.log(t) - x)).mean()
        self.inputs = {"X": [("x", x)], "Target": [("t", t)]}
        self.attrs = {"reduction": "mean"}
        self.outputs = {"Loss": [("loss", np.float32(loss))]}

    def test_output(self):
        self.check_output()


class TestSmoothL1(OpTest):
    op_type = "smooth_l1_loss"

    def setup(self):
        rs = np.random.RandomState(5)
        x = rs.randn(4, 3).astype("f4")
        y = rs.randn(4, 3).astype("f4")
        d = x - y
        ad = np.abs(d)
        loss = np.where(ad < 1.0, 0.5 * d * d, ad - 0.5).sum(1, keepdims=True)
        self.inputs = {"X": [("x", x)], "Y": [("y", y)]}
        self.attrs = {"sigma": 1.0}
        self.outputs = {"Out": [("out", loss)], "Diff": [("diff", d)]}

    def test_output(self):
        self.check_output()


class TestSmoothL1HighRank(OpTest):
    """4-D input still yields Out of shape [N, 1] (smooth_l1_loss_op.cc)."""
    op_type = "smooth_l1_loss"

    def setup(self):
        rs = np.random.RandomState(15)
        x = rs.randn(2, 3, 4, 5).astype("f4")
        y = rs.randn(2, 3, 4, 5).astype("f4")
        d = x - y
        ad = np.abs(d)
        loss = np.where(ad < 1.0, 0.5 * d * d, ad - 0.5)
        out = loss.reshape(2, -1).sum(1, keepdims=True)
        self.inputs = {"X": [("x", x)], "Y": [("y", y)]}
        self.attrs = {"sigma": 1.0}
        self.outputs = {"Out": [("out", out)], "Diff": [("diff", d)]}

    def test_output(self):
        self.check_output()


def _gru_unit_numpy(x, h_prev, w, bias, origin_mode):
    hid = h_prev.shape[-1]
    if bias is not None:
        x = x + bias.reshape(-1)
    gu = _sigmoid(x[:, :2 * hid] + h_prev @ w[:, :2 * hid])
    u, r = gu[:, :hid], gu[:, hid:]
    c = np.tanh(x[:, 2 * hid:] + (r * h_prev) @ w[:, 2 * hid:])
    if origin_mode:
        h = u * h_prev + (1.0 - u) * c
    else:
        h = u * c + (1.0 - u) * h_prev
    return gu, r * h_prev, c, h


class TestGruUnitDefault(OpTest):
    """origin_mode default False: h = u*c + (1-u)*h_prev
    (gru_kernel.h gru_finalOutput)."""
    op_type = "gru_unit"
    origin_mode = False

    def setup(self):
        B, H = 3, 4
        rs = np.random.RandomState(21)
        x = rs.randn(B, 3 * H).astype("f4")
        h_prev = rs.randn(B, H).astype("f4")
        w = rs.randn(H, 3 * H).astype("f4") * 0.5
        bias = rs.randn(1, 3 * H).astype("f4") * 0.1
        gu, rh, c, h = _gru_unit_numpy(x, h_prev, w, bias, self.origin_mode)
        self.inputs = {"Input": [("x", x)], "HiddenPrev": [("hp", h_prev)],
                       "Weight": [("w", w)], "Bias": [("b", bias)]}
        self.attrs = {"origin_mode": self.origin_mode}
        gate = np.concatenate([gu, c], axis=-1)
        self.outputs = {"Gate": [("gate", gate)],
                        "ResetHiddenPrev": [("rh", rh)],
                        "Hidden": [("h", h)]}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestGruUnitOriginMode(TestGruUnitDefault):
    """origin_mode=True: h = u*h_prev + (1-u)*c (gru_unit_op.h)."""
    origin_mode = True


class TestGruOpDefault(OpTest):
    """Fluid gru op, origin_mode default False."""
    op_type = "gru"
    origin_mode = False

    def setup(self):
        T, H = 5, 3
        rs = np.random.RandomState(22)
        x = rs.randn(T, 3 * H).astype("f4")
        w = rs.randn(H, 3 * H).astype("f4") * 0.5
        h = np.zeros(H, "f4")
        hidden = []
        for t in range(T):
            gu = _sigmoid(x[t, :2 * H] + h @ w[:, :2 * H])
            u, r = gu[:H], gu[H:]
            c = np.tanh(x[t, 2 * H:] + (r * h) @ w[:, 2 * H:])
            if self.origin_mode:
                h = u * h + (1.0 - u) * c
            else:
                h = u * c + (1.0 - u) * h
            hidden.append(h)
        self.inputs = {"Input": [("x", x)], "Weight": [("w", w)]}
        self.attrs = {"origin_mode": self.origin_mode}
        self.outputs = {"Hidden": [("hid", np.stack(hidden))]}

    def test_output(self):
        self.check_output(no_check_set=["BatchGate", "BatchResetHiddenPrev",
                                        "BatchHidden"], atol=1e-5)


class TestGruOpOriginMode(TestGruOpDefault):
    origin_mode = True


class TestNllLoss(OpTest):
    op_type = "nll_loss"

    def setup(self):
        rs = np.random.RandomState(6)
        x = np.log(rs.dirichlet(np.ones(5), 4)).astype("f4")
        lbl = rs.randint(0, 5, (4,)).astype("i8")
        picked = x[np.arange(4), lbl]
        self.inputs = {"X": [("x", x)], "Label": [("lbl", lbl)]}
        self.attrs = {"reduction": "mean", "ignore_index": -100}
        self.outputs = {
            "Out": [("out", np.float32(-picked.mean()))],
            "Total_weight": [("tw", np.float32(4.0))],
        }

    def test_output(self):
        self.check_output()


# --------------------------------------------------------------------------
# linalg
# --------------------------------------------------------------------------


class TestCholesky(OpTest):
    op_type = "cholesky"

    def setup(self):
        rs = np.random.RandomState(7)
        a = rs.randn(4, 4).astype("f4")
        spd = a @ a.T + 4 * np.eye(4, dtype="f4")
        self.inputs = {"X": [("x", spd)]}
        self.attrs = {"upper": False}
        self.outputs = {"Out": [("out", np.linalg.cholesky(spd))]}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)


class TestInverse(OpTest):
    op_type = "inverse"

    def setup(self):
        rs = np.random.RandomState(8)
        a = rs.randn(3, 3).astype("f4") + 3 * np.eye(3, dtype="f4")
        self.inputs = {"Input": [("x", a)]}
        self.outputs = {"Output": [("out", np.linalg.inv(a))]}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)


class TestAddmm(OpTest):
    op_type = "addmm"

    def setup(self):
        rs = np.random.RandomState(9)
        inp = rs.randn(2, 4).astype("f4")
        x = rs.randn(2, 3).astype("f4")
        y = rs.randn(3, 4).astype("f4")
        self.inputs = {"Input": [("inp", inp)], "X": [("x", x)],
                       "Y": [("y", y)]}
        self.attrs = {"Alpha": 2.0, "Beta": 0.5}
        self.outputs = {"Out": [("out", 0.5 * inp + 2.0 * (x @ y))]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["x", "y"], "Out", max_relative_error=0.02)


class TestKron(OpTest):
    op_type = "kron"

    def setup(self):
        rs = np.random.RandomState(10)
        x = rs.randn(2, 3).astype("f4")
        y = rs.randn(4, 2).astype("f4")
        self.inputs = {"X": [("x", x)], "Y": [("y", y)]}
        self.outputs = {"Out": [("out", np.kron(x, y))]}

    def test_output(self):
        self.check_output()


class TestLogsumexp(OpTest):
    op_type = "logsumexp"

    def setup(self):
        rs = np.random.RandomState(11)
        x = rs.randn(3, 4).astype("f4")
        out = np.log(np.exp(x).sum(1))
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"axis": [1], "keepdim": False, "reduce_all": False}
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output()


class TestTrace(OpTest):
    op_type = "trace"

    def setup(self):
        rs = np.random.RandomState(12)
        x = rs.randn(4, 5).astype("f4")
        self.inputs = {"Input": [("x", x)]}
        self.attrs = {"offset": 1, "axis1": 0, "axis2": 1}
        self.outputs = {"Out": [("out", np.trace(x, offset=1))]}

    def test_output(self):
        self.check_output()


class TestNormL2(OpTest):
    op_type = "norm"

    def setup(self):
        rs = np.random.RandomState(13)
        x = rs.randn(3, 4).astype("f4")
        n = np.sqrt((x * x).sum(1, keepdims=True) + 1e-10)
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"axis": 1, "epsilon": 1e-10}
        self.outputs = {"Out": [("out", x / n)], "Norm": [("n", n)]}

    def test_output(self):
        self.check_output()


# --------------------------------------------------------------------------
# interpolation
# --------------------------------------------------------------------------


class TestNearestInterp(OpTest):
    op_type = "nearest_interp_v2"

    def setup(self):
        rs = np.random.RandomState(14)
        x = rs.randn(2, 3, 4, 4).astype("f4")
        out = x.repeat(2, axis=2).repeat(2, axis=3)
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"out_h": 8, "out_w": 8, "align_corners": False}
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output()


class TestBilinearInterpAlignCorners(OpTest):
    op_type = "bilinear_interp_v2"

    def setup(self):
        rs = np.random.RandomState(15)
        x = rs.randn(1, 1, 3, 3).astype("f4")
        oh = ow = 5

        def oracle(img):
            out = np.zeros((oh, ow), "f4")
            for i in range(oh):
                for j in range(ow):
                    sy = i * (3 - 1) / (oh - 1)
                    sx = j * (3 - 1) / (ow - 1)
                    y0, x0 = int(np.floor(sy)), int(np.floor(sx))
                    y1, x1 = min(y0 + 1, 2), min(x0 + 1, 2)
                    wy, wx = sy - y0, sx - x0
                    out[i, j] = (img[y0, x0] * (1 - wy) * (1 - wx)
                                 + img[y0, x1] * (1 - wy) * wx
                                 + img[y1, x0] * wy * (1 - wx)
                                 + img[y1, x1] * wy * wx)
            return out

        out = oracle(x[0, 0])[None, None]
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"out_h": oh, "out_w": ow, "align_corners": True}
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output(atol=1e-5, rtol=1e-4)


# --------------------------------------------------------------------------
# vision / spatial
# --------------------------------------------------------------------------


class TestPixelShuffle(OpTest):
    op_type = "pixel_shuffle"

    def setup(self):
        rs = np.random.RandomState(16)
        x = rs.randn(2, 8, 3, 3).astype("f4")
        r = 2
        n, c, h, w = x.shape
        oc = c // (r * r)
        out = x.reshape(n, oc, r, r, h, w).transpose(0, 1, 4, 2, 5, 3)
        out = out.reshape(n, oc, h * r, w * r)
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"upscale_factor": 2}
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output()


class TestLabelSmooth(OpTest):
    op_type = "label_smooth"

    def setup(self):
        rs = np.random.RandomState(17)
        x = rs.dirichlet(np.ones(5), 4).astype("f4")
        eps = 0.1
        out = (1 - eps) * x + eps / 5
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"epsilon": eps}
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output()


class TestUnfold(OpTest):
    op_type = "unfold"

    def setup(self):
        rs = np.random.RandomState(18)
        x = rs.randn(1, 2, 4, 4).astype("f4")
        # oracle: manual im2col, k=2, s=2, p=0 -> 4 patches
        cols = []
        for i in range(0, 3, 2):
            for j in range(0, 3, 2):
                cols.append(x[:, :, i:i + 2, j:j + 2].reshape(1, -1))
        out = np.stack(cols, axis=-1)  # [1, C*k*k, L]
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"kernel_sizes": [2, 2], "strides": [2, 2],
                      "paddings": [0, 0, 0, 0], "dilations": [1, 1]}
        self.outputs = {"Y": [("y", out)]}

    def test_output(self):
        self.check_output()


class TestMaxPoolWithIndex(OpTest):
    op_type = "max_pool2d_with_index"

    def setup(self):
        rs = np.random.RandomState(19)
        x = rs.randn(1, 1, 4, 4).astype("f4")
        out = np.zeros((1, 1, 2, 2), "f4")
        mask = np.zeros((1, 1, 2, 2), "i8")
        for i in range(2):
            for j in range(2):
                win = x[0, 0, 2 * i:2 * i + 2, 2 * j:2 * j + 2]
                out[0, 0, i, j] = win.max()
                k = int(win.argmax())
                mask[0, 0, i, j] = (2 * i + k // 2) * 4 + (2 * j + k % 2)
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]}
        self.outputs = {"Out": [("out", out)], "Mask": [("mask", mask)]}

    def test_output(self):
        self.check_output()


class TestRoiAlignSingleBox(OpTest):
    op_type = "roi_align"

    def setup(self):
        # whole-image 2x2 roi_align over a linear ramp: averages quadrants
        x = np.arange(16, dtype="f4").reshape(1, 1, 4, 4)
        rois = np.array([[0.0, 0.0, 4.0, 4.0]], "f4")
        self.inputs = {"X": [("x", x)], "ROIs": [("rois", rois)]}
        self.attrs = {"pooled_height": 2, "pooled_width": 2,
                      "spatial_scale": 1.0, "sampling_ratio": 2}
        # bilinear on the ramp img[y,x]=4y+x at sample points {0.5,1.5}
        # per bin axis: bin(0,0) -> mean(4y+x) = 5; out-of-range samples
        # clamp to the border (reference roi_align clamp), so bins
        # touching the right/bottom edge average x=2.5 and x=3 -> 6.75
        out = np.array([[[[5.0, 6.75], [12.0, 13.75]]]], "f4")
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)


# --------------------------------------------------------------------------
# sequence (dense semantics)
# --------------------------------------------------------------------------


class TestSequencePoolSum(OpTest):
    op_type = "sequence_pool"

    def setup(self):
        rs = np.random.RandomState(20)
        x = rs.randn(3, 4, 5).astype("f4")
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"pooltype": "SUM"}
        self.outputs = {"Out": [("out", x.sum(1))]}

    def test_output(self):
        self.check_output(no_check_set=["MaxIndex"])


class TestSequencePad(OpTest):
    op_type = "sequence_pad"

    def setup(self):
        x = np.arange(12, dtype="f4").reshape(6, 2)  # 2 seqs of 3 rows
        length = np.array([3, 2], "i8")
        pv = np.array([0.0], "f4")
        out = x.reshape(2, 3, 2).copy()
        out[1, 2] = 0.0  # beyond length 2
        self.inputs = {"X": [("x", x)], "PadValue": [("pv", pv)],
                       "Length": [("len", length)]}
        self.attrs = {"padded_length": -1}
        self.outputs = {"Out": [("out", out)], "Length": [("lo", length)]}

    def test_output(self):
        self.check_output()


class TestSequenceConv(OpTest):
    op_type = "sequence_conv"

    def setup(self):
        rs = np.random.RandomState(21)
        x = rs.randn(5, 3).astype("f4")
        f = rs.randn(9, 2).astype("f4")
        t = x.shape[0]
        cols = []
        for k in range(3):
            shift = -1 + k
            g = np.zeros_like(x)
            for r in range(t):
                rr = r + shift
                if 0 <= rr < t:
                    g[r] = x[rr]
            cols.append(g)
        out = np.concatenate(cols, 1) @ f
        self.inputs = {"X": [("x", x)], "Filter": [("f", f)]}
        self.attrs = {"contextLength": 3, "contextStart": -1}
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output()


# --------------------------------------------------------------------------
# misc
# --------------------------------------------------------------------------


class TestConvShift(OpTest):
    op_type = "conv_shift"

    def setup(self):
        rs = np.random.RandomState(22)
        x = rs.randn(2, 6).astype("f4")
        y = rs.randn(2, 3).astype("f4")
        b, d = x.shape
        k = y.shape[1]
        out = np.zeros_like(x)
        for bi in range(b):
            for i in range(d):
                for j in range(k):
                    out[bi, i] += x[bi, (i + j - k // 2) % d] * y[bi, j]
        self.inputs = {"X": [("x", x)], "Y": [("y", y)]}
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output(atol=1e-5, rtol=1e-4)


class TestMultiplex(OpTest):
    op_type = "multiplex"

    def setup(self):
        rs = np.random.RandomState(23)
        xs = [rs.randn(3, 4).astype("f4") for _ in range(2)]
        ids = np.array([[1], [0], [1]], "i4")
        out = np.stack([xs[ids[i, 0]][i] for i in range(3)])
        self.inputs = {"Ids": [("ids", ids)],
                       "X": [("x0", xs[0]), ("x1", xs[1])]}
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output()


class TestDiagV2(OpTest):
    op_type = "diag_v2"

    def setup(self):
        x = np.array([1.0, 2.0, 3.0], "f4")
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"offset": 0, "padding_value": 0.0}
        self.outputs = {"Out": [("out", np.diag(x))]}

    def test_output(self):
        self.check_output()


class TestBroadcastTo(OpTest):
    op_type = "broadcast_to"

    def setup(self):
        x = np.arange(3, dtype="f4").reshape(1, 3)
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"shape": [4, 3]}
        self.outputs = {"Out": [("out", np.broadcast_to(x, (4, 3)))]}

    def test_output(self):
        self.check_output()


class TestGatherTree(OpTest):
    op_type = "gather_tree"

    def setup(self):
        ids = np.array(
            [[[2, 2]], [[3, 4]], [[5, 6]]], "i8")  # [T=3, B=1, W=2]
        parents = np.array(
            [[[0, 0]], [[1, 0]], [[1, 0]]], "i8")
        # walk back from last step: beam0 parent 1 -> step1 id 4's parent 0
        out = np.array([[[2, 2]], [[4, 3]], [[5, 6]]], "i8")
        self.inputs = {"Ids": [("ids", ids)], "Parents": [("par", parents)]}
        self.outputs = {"Out": [("out", out)]}

    def test_output(self):
        self.check_output()
