"""Quantization: fake_quant op parity vs numpy oracles, QAT transform
pass (STE training), PTQ calibration round-trip.

Parity model: reference operators/fake_quantize_op.cc (ClipAndFakeQuant,
FindAbsMax, FindChannelAbsMax, FindMovingAverage, FindRangeAbsMax),
contrib/slim/quantization/quantization_pass.py:216,
post_training_quantization.py:120.
"""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.place import CPUPlace
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.optimizer.static_opt import SGDOptimizer
from paddle_tpu.slim import (
    PostTrainingQuantization,
    QuantizationTransformPass,
)

from op_test import OpTest, skip_check_grad_ci


def _q(x, scale, qmax=127.0):
    return np.clip(np.round(x / scale * qmax), -qmax, qmax)


@skip_check_grad_ci(reason="round has zero true gradient; STE covered "
                          "by the QAT training test")
class TestFakeQuantizeAbsMax(OpTest):
    op_type = "fake_quantize_abs_max"

    def setup(self):
        rs = np.random.RandomState(0)
        x = rs.randn(4, 6).astype("f4")
        scale = np.abs(x).max()
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"bit_length": 8}
        self.outputs = {"Out": [("o", _q(x, scale).astype("f4"))],
                        "OutScale": [("s", np.array([scale], "f4"))]}

    def test_output(self):
        self.check_output()


@skip_check_grad_ci(reason="STE covered by QAT training test")
class TestFakeQuantizeDequantizeAbsMax(OpTest):
    op_type = "fake_quantize_dequantize_abs_max"

    def setup(self):
        rs = np.random.RandomState(1)
        x = rs.randn(3, 5).astype("f4")
        scale = np.abs(x).max()
        out = (_q(x, scale) * scale / 127.0).astype("f4")
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"bit_length": 8}
        self.outputs = {"Out": [("o", out)],
                        "OutScale": [("s", np.array([scale], "f4"))]}

    def test_output(self):
        self.check_output()


@skip_check_grad_ci(reason="STE covered by QAT training test")
class TestFakeChannelWiseQuantizeAbsMax(OpTest):
    op_type = "fake_channel_wise_quantize_abs_max"

    def setup(self):
        rs = np.random.RandomState(2)
        x = rs.randn(4, 3, 2, 2).astype("f4")  # OIHW, quant_axis 0
        scales = np.abs(x).reshape(4, -1).max(axis=1)
        out = _q(x, scales.reshape(4, 1, 1, 1)).astype("f4")
        self.inputs = {"X": [("x", x)]}
        self.attrs = {"bit_length": 8, "quant_axis": 0}
        self.outputs = {"Out": [("o", out)],
                        "OutScale": [("s", scales.astype("f4"))]}

    def test_output(self):
        self.check_output()


@skip_check_grad_ci(reason="state update, not a training op")
class TestFakeQuantizeMovingAverageAbsMax(OpTest):
    op_type = "fake_quantize_moving_average_abs_max"

    def setup(self):
        rs = np.random.RandomState(3)
        x = rs.randn(4, 4).astype("f4")
        rate = 0.9
        state = rate * 1.0 + 1.0
        accum = rate * 1.0 + np.abs(x).max()
        scale = accum / state
        self.inputs = {"X": [("x", x)],
                       "InScale": [("is", np.array([1.0], "f4"))],
                       "InState": [("ist", np.array([1.0], "f4"))],
                       "InAccum": [("ia", np.array([1.0], "f4"))]}
        self.attrs = {"bit_length": 8, "moving_rate": rate,
                      "is_test": False}
        self.outputs = {
            "Out": [("o", _q(x, scale).astype("f4"))],
            "OutScale": [("os", np.array([scale], "f4"))],
            "OutState": [("ost", np.array([state], "f4"))],
            "OutAccum": [("oa", np.array([accum], "f4"))]}

    def test_output(self):
        self.check_output()


@skip_check_grad_ci(reason="windowed state update")
class TestFakeQuantizeRangeAbsMax(OpTest):
    op_type = "fake_quantize_range_abs_max"

    def setup(self):
        rs = np.random.RandomState(4)
        x = rs.randn(4, 4).astype("f4")
        window = np.array([0.5, 3.0, 0.0, 0.0], "f4")  # it=1 slot updated
        cur = np.abs(x).max()
        new_window = window.copy()
        new_window[1] = cur
        scale = max(new_window.max(), 1e-8)
        self.inputs = {"X": [("x", x)],
                       "InScale": [("is", np.array([0.5], "f4"))],
                       "InScales": [("iw", window)],
                       "Iter": [("it", np.array([1], "i4"))]}
        self.attrs = {"bit_length": 8, "window_size": 4,
                      "is_test": False}
        self.outputs = {
            "Out": [("o", _q(x, scale).astype("f4"))],
            "OutScale": [("os", np.array([scale], "f4"))],
            "OutScales": [("ow", new_window)],
            "OutIter": [("oi", np.array([2], "i4"))]}

    def test_output(self):
        self.check_output()


@skip_check_grad_ci(reason="pure dequant scaling")
class TestFakeDequantizeMaxAbs(OpTest):
    op_type = "fake_dequantize_max_abs"

    def setup(self):
        rs = np.random.RandomState(5)
        x = _q(rs.randn(3, 4), 2.0).astype("f4")
        self.inputs = {"X": [("x", x)],
                       "Scale": [("s", np.array([2.0], "f4"))]}
        self.attrs = {"max_range": 127.0}
        self.outputs = {"Out": [("o", (x * 2.0 / 127.0).astype("f4"))]}

    def test_output(self):
        self.check_output()


# -- graph-level: QAT + PTQ -------------------------------------------


def _lenet_programs(qat_pass=None, with_loss=True):
    """Tiny conv net; optionally quantized BEFORE minimize (QAT).
    ``with_loss=False`` builds the inference form (the program shape
    PostTrainingQuantization expects, like the reference's
    load_inference_model output)."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("img", shape=[1, 8, 8], dtype="float32")
        h = layers.conv2d(img, num_filters=4, filter_size=3, act="relu")
        h = layers.pool2d(h, pool_size=2, pool_type="max")
        h = layers.fc(h, size=4)
        if not with_loss:
            return main, startup, h
        lbl = layers.data("lbl", shape=[1], dtype="int32")
        loss = layers.mean(layers.softmax_with_cross_entropy(h, lbl))
        if qat_pass is not None:
            qat_pass.apply(main, startup)
        SGDOptimizer(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _proto_batch(rs, protos, n=32):
    c = rs.randint(0, 4, n)
    x = protos[c] + 0.1 * rs.randn(n, 1, 8, 8).astype("f4")
    return x.astype("f4"), c.reshape(-1, 1).astype("i4")


def test_qat_lenet_trains():
    """QAT: the quantized graph trains through the STE — loss drops and
    the quantizable ops now consume quant-dequantized inputs."""
    tp = QuantizationTransformPass()
    main, startup, loss = _lenet_programs(qat_pass=tp)
    qdq_types = [op.type for op in main.global_block.ops
                 if op.type.startswith("fake_")]
    assert any("channel_wise" in t for t in qdq_types), qdq_types
    assert any("moving_average" in t for t in qdq_types), qdq_types

    exe = pt.Executor(CPUPlace())
    exe.run(startup)
    rs = np.random.RandomState(0)
    protos = rs.randn(4, 1, 8, 8).astype("f4")
    losses = []
    for step in range(40):
        x, y = _proto_batch(rs, protos)
        out = exe.run(main, feed={"img": x, "lbl": y}, fetch_list=[loss])
        losses.append(float(np.asarray(out[0])))
    assert losses[0] / losses[-1] > 2.0, (losses[0], losses[-1])


def test_qat_moving_average_scale_updates():
    """The persistable activation-scale accumulators must move during
    training (the op round-trips its state through the scope)."""
    tp = QuantizationTransformPass()
    main, startup, loss = _lenet_programs(qat_pass=tp)
    scale_vars = [op.output("OutScale")[0]
                  for op in main.global_block.ops
                  if op.type ==
                  "fake_quantize_dequantize_moving_average_abs_max"]
    assert scale_vars
    exe = pt.Executor(CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    rs = np.random.RandomState(1)
    protos = rs.randn(4, 1, 8, 8).astype("f4")
    x, y = _proto_batch(rs, protos)
    exe.run(main, feed={"img": x, "lbl": y}, fetch_list=[loss],
            scope=scope)
    v0 = np.asarray(scope.find_var(scale_vars[0]).get_tensor())
    exe.run(main, feed={"img": x, "lbl": y}, fetch_list=[loss],
            scope=scope)
    v1 = np.asarray(scope.find_var(scale_vars[0]).get_tensor())
    assert not np.allclose(v0, 1.0), v0  # moved off the init
    assert not np.allclose(v0, v1)  # still adapting


def test_qat_clone_for_test_freezes_scales():
    tp = QuantizationTransformPass()
    main, startup, _ = _lenet_programs(qat_pass=tp)
    test_prog = main.clone(for_test=True)
    for op in test_prog.global_block.ops:
        if op.type == "fake_quantize_dequantize_moving_average_abs_max":
            assert op.attr("is_test") is True
            return
    raise AssertionError("no moving-average qdq op found in clone")


def test_ptq_round_trip_close_to_fp32():
    """PTQ: calibrate on sample batches; the quantized inference program
    must track the fp32 program within int8 simulation tolerance."""
    main, startup, logits = _lenet_programs(with_loss=False)
    infer = main.clone(for_test=True)
    exe = pt.Executor(CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)

    rs = np.random.RandomState(2)
    protos = rs.randn(4, 1, 8, 8).astype("f4")
    fc_out = [op for op in infer.global_block.ops if op.type == "mul"]
    assert fc_out

    calib = [{"img": _proto_batch(rs, protos)[0]} for _ in range(4)]
    ptq = PostTrainingQuantization(
        exe, infer, feed_list=["img"], fetch_list=[],
        data_loader=calib, scope=scope, batch_nums=4)
    qprog = ptq.quantize()
    qdq = [op.type for op in qprog.global_block.ops
           if op.type.startswith("fake_")]
    assert qdq, "PTQ emitted no quant ops"

    x, _ = _proto_batch(rs, protos, n=16)
    # compare the final quantizable op's output downstream: fetch loss
    # inputs is awkward; instead fetch the fc output var by name
    out_name = fc_out[-1].output("Out")[0]
    ref = np.asarray(exe.run(infer, feed={"img": x},
                             fetch_list=[out_name], scope=scope)[0])
    got = np.asarray(exe.run(qprog, feed={"img": x},
                             fetch_list=[out_name], scope=scope)[0])
    # int8 simulation error bound: a few quantization steps
    denom = max(np.abs(ref).max(), 1e-6)
    assert np.abs(ref - got).max() / denom < 0.1, \
        np.abs(ref - got).max() / denom


def test_qat_freeze_export_predictor_roundtrip(tmp_path):
    """The full slim deployment loop (reference QAT flow): train with
    the transform pass -> clone(for_test=True) freezes the scales ->
    save_inference_model -> Predictor serves the quantized graph with
    outputs matching the frozen eval program."""
    from paddle_tpu.fluid.io import save_inference_model
    from paddle_tpu.inference import Predictor

    tp = QuantizationTransformPass()
    main, startup, loss = _lenet_programs(qat_pass=tp)
    exe = pt.Executor(CPUPlace())
    exe.run(startup)  # global scope: save_inference_model reads it
    rs = np.random.RandomState(7)
    protos = rs.randn(4, 1, 8, 8).astype("f4")
    for _ in range(10):
        x, y = _proto_batch(rs, protos)
        exe.run(main, feed={"img": x, "lbl": y}, fetch_list=[loss])

    test_prog = main.clone(for_test=True)
    logits = [op for op in test_prog.global_block.ops
              if op.type == "softmax_with_cross_entropy"][0].input("Logits")[0]
    x, _ = _proto_batch(rs, protos, n=8)
    ref = np.asarray(exe.run(test_prog, feed={"img": x},
                             fetch_list=[logits], use_prune=True)[0])

    path = str(tmp_path / "qat_model")
    save_inference_model(path, ["img"],
                         [test_prog.global_block.var(logits)],
                         exe, main_program=test_prog)
    pred = Predictor(path)
    got = np.asarray(pred.run({"img": x})[0])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
