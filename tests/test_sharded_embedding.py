"""Sharded embedding engine (paddle_tpu.distributed.embedding).

The recommender acceptance of ISSUE/ROADMAP: tables row-sharded over
the mesh's 'mp' axis, lookups routed with an all-to-all, gradients a
dense scatter-add on the owning shard — replacing the reference's
parameter-server sparse stack.  Fast sections exercise the engine
core, the lowering dispatch, the pass stamps and the checkpoint
round-trip; the slow composition matrix trains the wide&deep flagship
on dp×mp / mp×pp meshes against replicated oracles and retags mp
across an elastic resume.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.distributed import embedding as dist_emb
from paddle_tpu.framework import passes as passes_mod
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import (Program, device_guard,
                                          program_guard)
from paddle_tpu.monitor import stat_get, stat_reset
from paddle_tpu.ops import embedding_ops
from paddle_tpu.rec import wide_deep_program

# mesh fixtures (mesh8 / mesh_dp_mp / mesh_mp_only): tests/conftest.py


# ---------------------------------------------------------------------------
# shared builders
# ---------------------------------------------------------------------------

# wide&deep sized so tier-1 compiles stay cheap; the slow matrix
# overrides vocab/dims to the "table exceeds one chip" regime
WD = dict(batch_size=8, vocab_size=64, emb_dim=4, n_fields=4,
          n_dense=3, hidden=(8,), padding_idx=0)


def _np_oracle(w, ids, padding_idx=-1):
    """Dense numpy reference with the engine contract: OOV and padding
    ids yield zero rows."""
    w = np.asarray(w)
    ids = np.asarray(ids)
    keep = (ids >= 0) & (ids < w.shape[0])
    if padding_idx >= 0:
        keep = keep & (ids != padding_idx)
    out = w[np.where(keep, ids, 0)]
    return out * keep[..., None].astype(w.dtype)


def _np_grad_oracle(wshape, ids, ct, padding_idx=-1):
    """Scatter-add gradient oracle matching the custom_vjp backward."""
    g = np.zeros(wshape, ct.dtype)
    flat, ctf = np.asarray(ids).reshape(-1), ct.reshape(-1, wshape[-1])
    for i, t in zip(flat, ctf):
        if 0 <= i < wshape[0] and i != padding_idx:
            g[i] += t
    return g


def _build_wd(sparse, fleet_tp=False, lr=0.1, seed=7, **over):
    cfg = dict(WD, sparse=sparse, lr=lr)
    cfg.update(over)
    # own name scope: every build gets IDENTICAL param names, so
    # checkpoints restore across independently-built programs
    with unique_name.guard():
        main, startup, feeds, loss, opt = wide_deep_program(**cfg)
    main.random_seed = seed
    with unique_name.guard(), program_guard(main, startup):
        if fleet_tp:
            from paddle_tpu.distributed import fleet

            strat = fleet.DistributedStrategy()
            strat.tensor_parallel = True
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(opt)
            fleet.minimize(loss)
        else:
            opt.minimize(loss)
    return main, startup, loss


def _wd_feed(seed=0, **over):
    cfg = dict(WD)
    cfg.update(over)
    rs = np.random.RandomState(seed)
    ids = rs.randint(0, cfg["vocab_size"],
                     (cfg["batch_size"], cfg["n_fields"])).astype("int64")
    ids[0, 0] = cfg["padding_idx"]  # exercise the padding row
    return {
        "sparse_ids": ids,
        "dense_x": rs.randn(cfg["batch_size"],
                            cfg["n_dense"]).astype("float32"),
        "labels": rs.randint(0, 2,
                             (cfg["batch_size"], 1)).astype("int64"),
    }


def _train(main, startup, loss, feed, mesh, steps=3, scope=None):
    scope = scope or pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup, scope=scope)
    out = [float(np.asarray(exe.run(main, feed=feed, fetch_list=[loss],
                                    scope=scope)[0]).ravel()[0])
           for _ in range(steps)]
    exe.drain()
    return out, scope


# ---------------------------------------------------------------------------
# engine core: dense reference + all-to-all shard_map path
# ---------------------------------------------------------------------------


class TestEngineCore:
    def test_dense_ref_forward_contract(self, rng):
        w = rng.randn(16, 4).astype("float32")
        ids = np.array([[3, 15, 2], [-1, 99, 0]], dtype="int64")
        out = np.asarray(embedding_ops.embedding_lookup_ref(w, ids, 2))
        np.testing.assert_array_equal(out, _np_oracle(w, ids, 2))
        # padding + OOV rows are exactly zero, valid rows exact bytes
        assert not out[0, 2].any() and not out[1, 0].any() \
            and not out[1, 1].any()
        np.testing.assert_array_equal(out[0, 0], w[3])

    def test_dense_padding_and_oov_grad_zero(self, rng):
        """Satellite (b): padding_idx gradient exactly zero on the
        dense engine path; OOV ids contribute no gradient."""
        w = rng.randn(16, 4).astype("float32")
        ids = np.array([1, 2, 2, 5, -3, 99, 1], dtype="int64")

        def loss(w):
            return embedding_ops.embedding_lookup_ref(w, ids, 2).sum()

        g = np.asarray(jax.grad(loss)(w))
        ct = np.ones((ids.size, 4), "float32")
        np.testing.assert_array_equal(g, _np_grad_oracle(w.shape, ids,
                                                         ct, 2))
        assert not g[2].any()           # padding row pinned zero
        assert g[1, 0] == 2.0           # id 1 looked up twice
        assert g[0, 0] == 0.0           # id 0 never looked up

    def test_alltoall_bytes_accounting(self):
        # degree=4, 10 ids pad to cap=3 per rank: 4*3 slots of
        # (8-byte id out + 16*4-byte row back)
        assert embedding_ops.alltoall_bytes_per_lookup(10, 4, 16) == \
            4 * 3 * (8 + 64)

    def test_sharded_lookup_roundtrip_and_grad(self, rng):
        """The all-to-all engine under shard_map: forward parity with
        the dense oracle (incl. OOV and a non-divisible id count) and
        the custom_vjp backward yields the exact scatter-add grad with
        the padding row zero — satellite (b), sharded path."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        degree, vocab, dim, pad = 4, 32, 4, 1
        mesh = Mesh(np.array(jax.devices()[:degree]), ("mp",))
        w = rng.randn(vocab, dim).astype("float32")
        # n=7 ids (not divisible by degree) incl. padding + both OOV kinds
        ids = np.array([5, 1, 31, -2, 40, 5, 17], dtype="int64")
        coef = rng.randn(ids.size, dim).astype("float32")

        f = shard_map(
            lambda lw, i: dist_emb.sharded_lookup(
                lw, i, axis_name="mp", degree=degree, padding_idx=pad),
            mesh=mesh, in_specs=(P("mp", None), P()), out_specs=P(),
            check_rep=False)

        @jax.jit
        def fwd_and_grad(w):  # one compile covers both directions
            out, vjp = jax.vjp(lambda w: f(w, ids), w)
            return out, vjp(coef)[0]

        out, g = map(np.asarray, fwd_and_grad(w))
        np.testing.assert_allclose(out, _np_oracle(w, ids, pad),
                                   rtol=0, atol=0)
        np.testing.assert_allclose(
            g, _np_grad_oracle(w.shape, ids, coef, pad),
            rtol=1e-6, atol=1e-6)
        assert not g[pad].any()

    @pytest.mark.slow
    def test_sharded_matches_dense_ref_vjp(self, rng):
        """The two engine custom_vjps (per-shard all-to-all vs global
        dense ref) are the same mathematical operator."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        degree, vocab, dim = 4, 16, 3
        mesh = Mesh(np.array(jax.devices()[:degree]), ("mp",))
        w = rng.randn(vocab, dim).astype("float32")
        ids = np.array([[0, 7, 7], [15, 3, 0]], dtype="int64")
        f = shard_map(
            lambda lw, i: dist_emb.sharded_lookup(
                lw, i, axis_name="mp", degree=degree, padding_idx=0),
            mesh=mesh, in_specs=(P("mp", None), P()), out_specs=P(),
            check_rep=False)
        np.testing.assert_allclose(
            np.asarray(f(w, ids)),
            np.asarray(embedding_ops.embedding_lookup_ref(w, ids, 0)),
            rtol=0, atol=0)
        g_sh = jax.grad(lambda w: jnp.sin(f(w, ids)).sum())(w)
        g_ref = jax.grad(lambda w: jnp.sin(
            embedding_ops.embedding_lookup_ref(w, ids, 0)).sum())(w)
        np.testing.assert_allclose(np.asarray(g_sh), np.asarray(g_ref),
                                   rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# lowering dispatch + the sparse-fallback bugfix
# ---------------------------------------------------------------------------


class TestLoweringDispatch:
    def test_sparse_fallback_warns_and_counts(self):
        """Satellite (a): is_sparse with no sharding plan degrades to a
        dense replicated table LOUDLY — warn once + counter — instead
        of silently ignoring the flag."""
        embedding_ops._warned_sparse_fallback = False
        stat_reset("emb_sparse_fallback_dense")
        main, startup, loss = _build_wd(sparse=True)
        with pytest.warns(UserWarning,
                          match="no active sharding plan"):
            losses, _ = _train(main, startup, loss, _wd_feed(), None,
                               steps=2)
        assert np.isfinite(losses).all()
        assert stat_get("emb_sparse_fallback_dense") >= 2  # both tables
        # warn-once: a second program does not warn again
        import warnings as _w

        main2, startup2, loss2 = _build_wd(sparse=True, seed=8)
        with _w.catch_warnings():
            _w.simplefilter("error", UserWarning)
            _train(main2, startup2, loss2, _wd_feed(), None, steps=1)

    def test_plain_dense_path_untouched(self):
        """sparse=False stays on the historical jnp.take path: no
        warning, no counter."""
        embedding_ops._warned_sparse_fallback = False
        stat_reset("emb_sparse_fallback_dense")
        main, startup, loss = _build_wd(sparse=False)
        import warnings as _w

        with _w.catch_warnings():
            _w.simplefilter("error", UserWarning)
            losses, _ = _train(main, startup, loss, _wd_feed(), None,
                               steps=2)
        assert np.isfinite(losses).all()
        assert stat_get("emb_sparse_fallback_dense") == 0

    def test_is_sparse_attr_reaches_op(self):
        """Satellite (a): the flag survives layers.embedding /
        nn.functional.embedding / nn.Embedding into the op attrs."""
        main, startup = Program(), Program()
        with program_guard(main, startup):
            ids = layers.data("i", [4, 2], dtype="int64",
                              append_batch_size=False)
            layers.embedding(ids, (8, 3), is_sparse=True)
            layers.embedding(ids, (8, 3))
        ops = [op for op in main.global_block.ops
               if op.type.startswith("lookup_table")]
        assert [bool(op.attr("is_sparse", False)) for op in ops] == \
            [True, False]
        emb = pt.nn.Embedding(8, 3, sparse=True)
        assert emb.sparse is True
        assert pt.nn.Embedding(8, 3, is_sparse=True).sparse is True  # 1.x
        assert pt.nn.Embedding(8, 3).sparse is False


# ---------------------------------------------------------------------------
# sharding pass: seeding, stamps, shard_info
# ---------------------------------------------------------------------------


class TestShardingPass:
    def _planned(self, mesh):
        main, _, loss = _build_wd(sparse=True, fleet_tp=True)
        out = passes_mod.apply_passes(
            main, fetch_names=(loss.name,),
            feed_names=("sparse_ids", "dense_x", "labels"), mesh=mesh)
        return out

    def test_pass_seeds_row_sharding_and_stamps(self, mesh_dp_mp):
        """is_sparse tables get P('mp', None) with NO partition rule,
        and every lookup op (forward AND grad) carries the engine
        stamp."""
        out = self._planned(mesh_dp_mp)
        plan = out._tp_plan
        assert plan is not None and plan.mp_degree == 4
        assert plan.spec_tuple("wd_table") == ("mp", None)
        assert plan.spec_tuple("wd_wide_table") == ("mp", None)
        fwd = [op for op in out.global_block.ops
               if op.type in ("lookup_table", "lookup_table_v2")]
        bwd = [op for op in out.global_block.ops
               if op.type in ("lookup_table_grad",
                              "lookup_table_v2_grad")]
        assert fwd and bwd
        for op in fwd + bwd:
            assert int(op.attr(passes_mod.EMB_SHARD_ATTR, 0)) == 4, \
                (op.type, dict(op.attrs))
        # forward ops also pin their output layout (mp -> replicated)
        for op in fwd:
            anchors = op.attr(passes_mod.TP_CONSTRAINT_ATTR, ())
            assert any(a.split("\t")[0] == op.output("Out")[0]
                       for a in anchors), anchors

    def test_table_grad_reduced_in_shard_bytes(self, mesh_dp_mp):
        """The dp grad-allreduce accounting sees the SHARD, not the
        full table — the whole point of not replicating it."""
        plan = self._planned(mesh_dp_mp)._tp_plan
        rec = plan.grad_reduce.get("wd_table@GRAD")
        assert rec is not None and rec["axes"] == ("dp",)
        full = WD["vocab_size"] * WD["emb_dim"] * 4
        assert rec["bytes"] == full // 4

    def test_shard_info(self, mesh_dp_mp):
        out = self._planned(mesh_dp_mp)
        info = dist_emb.shard_info(out, "wd_table", mesh=mesh_dp_mp)
        assert info["row_sharded"] is True
        assert info["spec"] == ("mp", None)
        assert info["shard_divisor"] == 4
        assert info["rows_per_shard"] == WD["vocab_size"] // 4
        assert info["bytes_per_chip"] * 4 == info["global_bytes"] \
            == WD["vocab_size"] * WD["emb_dim"] * 4

    def test_partition_rules_helper(self):
        rules = dist_emb.partition_rules("tbl", "other.w_0")
        assert rules == [(r"^tbl$", "mp,None"),
                         (r"^other\.w_0$", "mp,None")]

    def test_fleet_facade(self):
        from paddle_tpu.distributed import fleet

        assert fleet.distributed_embedding is \
            dist_emb.distributed_embedding


# ---------------------------------------------------------------------------
# eager helper telemetry
# ---------------------------------------------------------------------------


class TestEagerLookup:
    def test_lookup_telemetry(self, rng):
        stat_reset("emb_oov_ids")
        w = rng.randn(8, 3).astype("float32")
        ids = np.array([1, 7, -1, 9], dtype="int64")
        out = np.asarray(dist_emb.lookup(w, ids))
        np.testing.assert_array_equal(out, _np_oracle(w, ids, -1))
        assert stat_get("emb_oov_ids") == 2
        from paddle_tpu.monitor import export_stats

        stats = dict(export_stats())
        assert any(k.startswith("emb_lookup_seconds") for k in stats), \
            sorted(k for k in stats if k.startswith("emb_"))


# ---------------------------------------------------------------------------
# checkpoint: row-sharded table round-trip
# ---------------------------------------------------------------------------


class TestCheckpoint:
    def test_localshard_assembles_row_shards(self, rng):
        """LocalShard covers the table layout: axis-0 row blocks at
        explicit origins reassemble bitwise."""
        from paddle_tpu.ckpt.state import LocalShard, _assemble_blocks

        w = rng.randn(32, 4).astype("float32")
        shards = [LocalShard(w[o:o + 8], w.shape, origin=(o, 0))
                  for o in range(0, 32, 8)]
        arr, origin = _assemble_blocks(
            {s.origin: s.array for s in shards}, 2)
        assert origin == (0, 0)
        np.testing.assert_array_equal(arr, w)

    def test_row_sharded_table_ckpt_roundtrip(self, tmp_path,
                                              mesh_dp_mp):
        """save_sharded/load_sharded round-trips a live mp-row-sharded
        table and the run resumes the uninterrupted trajectory."""
        from paddle_tpu.distributed.checkpoint import (load_sharded,
                                                       save_sharded)

        feed = _wd_feed()

        def fresh():
            main, startup, loss = _build_wd(sparse=True, fleet_tp=True)
            scope = pt.framework.Scope()
            exe = pt.Executor(pt.CPUPlace(), mesh=mesh_dp_mp)
            exe.run(startup, scope=scope)
            return main, loss, exe, scope

        def step(main, loss, exe, scope):
            return float(np.asarray(exe.run(
                main, feed=feed, fetch_list=[loss],
                scope=scope)[0]).ravel()[0])

        main, loss, exe, scope = fresh()
        full = [step(main, loss, exe, scope) for _ in range(4)]
        exe.drain()

        main, loss, exe, scope = fresh()
        for _ in range(2):
            step(main, loss, exe, scope)
        exe.drain()
        # the live table is genuinely row-sharded before the save
        tbl = scope.get_var("wd_table")
        assert tuple(tbl.sharding.spec) == ("mp", None), tbl.sharding
        saved = save_sharded(scope, str(tmp_path))
        assert "wd_table" in saved

        main2, loss2, exe2, scope2 = fresh()
        step(main2, loss2, exe2, scope2)  # materialize layouts
        load_sharded(scope2, str(tmp_path))
        resumed = [step(main2, loss2, exe2, scope2) for _ in range(2)]
        exe2.drain()
        np.testing.assert_allclose(resumed, full[2:4], rtol=1e-5,
                                   atol=1e-7)


# ---------------------------------------------------------------------------
# slow composition matrix: dp×mp parity+budget, mp×pp, elastic mp retag
# ---------------------------------------------------------------------------

# the "one simulated chip" of the acceptance: both replicated tables
# (~278 KB) blow it, one mp=4 shard (~70 KB) fits
EMB_CHIP_BUDGET_BYTES = 150_000
BIG = dict(vocab_size=4096, emb_dim=16, n_fields=8, batch_size=16,
           n_dense=4, hidden=(32,), padding_idx=0)


@pytest.mark.slow
class TestComposition:
    def test_dp_mp_parity_and_chip_budget(self, mesh_dp_mp,
                                          restore_flags_budget):
        """Acceptance: a wide&deep model whose tables exceed one
        simulated chip's HBM trains on dp×mp with loss parity <=1e-4
        vs the replicated oracle, the table physically row-sharded,
        and the PR 8 pre-dispatch budget gate passing on the sharded
        footprint (and rejecting the replicated one)."""
        from paddle_tpu.distributed.parallel_env import (reset_mesh,
                                                         set_mesh)
        from paddle_tpu.observe import xla_stats
        from paddle_tpu.observe.xla_stats import MemoryBudgetError

        feed = _wd_feed(seed=3, **BIG)
        reset_mesh()
        base, _ = _train(*_build_wd(sparse=False, **BIG), feed, None,
                         steps=5)

        set_mesh(mesh_dp_mp)
        got, scope = _train(*_build_wd(sparse=True, fleet_tp=True,
                                       **BIG), feed, mesh_dp_mp,
                            steps=5)
        assert np.isfinite(got).all(), got
        np.testing.assert_allclose(got, base, rtol=1e-4, atol=1e-6)

        tbl = scope.get_var("wd_table")
        assert tuple(tbl.sharding.spec) == ("mp", None), tbl.sharding
        assert tbl.addressable_shards[0].data.shape == \
            (BIG["vocab_size"] // 4, BIG["emb_dim"])
        full = sum(int(np.prod(scope.get_var(n).shape)) * 4
                   for n in ("wd_table", "wd_wide_table"))
        per_chip = sum(
            int(np.prod(
                scope.get_var(n).addressable_shards[0].data.shape)) * 4
            for n in ("wd_table", "wd_wide_table"))
        assert full > EMB_CHIP_BUDGET_BYTES >= per_chip, \
            (full, per_chip)

        # PR 8 budget gate on the simulated chip: shard fits, full
        # table is rejected BEFORE dispatch
        pt.set_flags({"FLAGS_hbm_budget_fraction": 1.0,
                      "FLAGS_hbm_bytes_per_device":
                          EMB_CHIP_BUDGET_BYTES})
        assert xla_stats.check_hbm_budget(per_chip)["verdict"] == "pass"
        with pytest.raises(MemoryBudgetError):
            xla_stats.check_hbm_budget(full)

        # the engine accounted its collective traffic
        from paddle_tpu.monitor import export_stats

        stats = dict(export_stats())
        assert stats.get("emb_rows_per_shard") == \
            BIG["vocab_size"] // 4

    def test_pipeline_mp_composed_parity(self):
        """mp×pp: the embedding rides the EXPLICIT all-to-all engine
        inside the per-stage shard_map; parity vs the pp-only
        PipelineOptimizer oracle."""
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.parallel_env import (reset_mesh,
                                                         set_mesh)
        from paddle_tpu.initializer import NormalInitializer
        from paddle_tpu.monitor import stat_get as _sg, \
            stat_reset as _sr
        from paddle_tpu.optimizer import (MomentumOptimizer,
                                          PipelineOptimizer)
        from paddle_tpu.param_attr import ParamAttr

        V, D, B, F = 32, 8, 8, 4

        def build(use_tp, n_micro=2):
            main, startup = Program(), Program()
            main.random_seed = 3
            with program_guard(main, startup):
                ids = layers.data("ids", [B, F], dtype="int64",
                                  append_batch_size=False)
                y = layers.data("y", [B, 1], dtype="float32",
                                append_batch_size=False)
                with device_guard("stage:0"):
                    emb = layers.embedding(
                        ids, (V, D), is_sparse=True, padding_idx=0,
                        param_attr=ParamAttr(
                            name="tbl",
                            initializer=NormalInitializer(0.0, 0.1)))
                    h = layers.reshape(emb, [0, F * D])
                    h = layers.fc(h, 16, act="relu", name="s0_fc",
                                  param_attr=ParamAttr(
                                      initializer=NormalInitializer(
                                          0.0, 0.05)))
                with device_guard("stage:1"):
                    pred = layers.fc(h, 1, name="head",
                                     param_attr=ParamAttr(
                                         initializer=NormalInitializer(
                                             0.0, 0.05)),
                                     bias_attr=False)
                    loss = layers.mean(layers.square_error_cost(pred, y))
                opt = MomentumOptimizer(0.05, 0.9)
                if use_tp:
                    strat = fleet.DistributedStrategy()
                    strat.tensor_parallel = True
                    strat.pipeline = True
                    strat.pipeline_configs = {"micro_batch": n_micro}
                    fleet.init(is_collective=True, strategy=strat)
                    fleet.distributed_optimizer(opt)
                    fleet.minimize(loss)
                else:
                    PipelineOptimizer(
                        opt, num_microbatches=n_micro).minimize(loss)
            return main, startup, loss

        rs = np.random.RandomState(0)
        ids = rs.randint(0, V, (B, F)).astype("int64")
        ids[1, 2] = 0
        feed = {"ids": ids, "y": rs.randn(B, 1).astype("float32")}
        devs = np.array(jax.devices())

        reset_mesh()
        mesh_pp = jax.sharding.Mesh(devs[:2], ("pp",))
        with unique_name.guard():
            base, _ = _train(*build(False), feed, mesh_pp, steps=4)

        _sr("emb_alltoall_bytes")
        mesh = jax.sharding.Mesh(devs[:4].reshape(2, 2), ("mp", "pp"))
        set_mesh(mesh)
        try:
            with unique_name.guard():
                got, _ = _train(*build(True), feed, mesh, steps=4)
        finally:
            reset_mesh()
        np.testing.assert_allclose(got, base, rtol=1e-4, atol=1e-6)
        assert _sg("emb_alltoall_bytes") > 0  # explicit engine engaged

    def test_elastic_resume_mp4_to_mp2(self):
        """Elastic retag mp 4 -> 2: the checkpointed table restores
        BITWISE onto the new topology (placed as vocab/2 row shards)
        and training continues with loss parity vs the replicated
        oracle."""
        from paddle_tpu.ckpt import restore_scope, snapshot_scope
        from paddle_tpu.distributed.parallel_env import (
            init_parallel_env, reset_mesh)

        feed = _wd_feed(seed=5, **BIG)
        reset_mesh()
        base, _ = _train(*_build_wd(sparse=False, **BIG), feed, None,
                         steps=4)

        reset_mesh()
        mesh4 = init_parallel_env(mesh_shape=[2, 4],
                                  axis_names=("dp", "mp"))
        with unique_name.guard():
            _, scope = _train(*_build_wd(sparse=True, fleet_tp=True,
                                         **BIG), feed, mesh4, steps=2)
        snap = snapshot_scope(scope)
        saved_tbl = np.asarray(snap["wd_table"])
        reset_mesh()

        # new topology, lr=0: one no-op step just places the restored
        # state -> the table must be bitwise the saved bytes, now
        # sharded vocab/2 per chip
        mesh2 = init_parallel_env(mesh_shape=[4, 2],
                                  axis_names=("dp", "mp"))
        with unique_name.guard():
            main, startup, loss = _build_wd(sparse=True, fleet_tp=True,
                                            lr=0.0, **BIG)
        scope2 = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh2)
        exe.run(startup, scope=scope2)
        # keep THIS program's lr=0.0 (the snapshot carries the real lr)
        restore_scope(scope2, snap,
                      var_names=[n for n in snap
                                 if not n.startswith("learning_rate")])
        exe.run(main, feed=feed, fetch_list=[loss], scope=scope2)
        exe.drain()
        tbl = scope2.get_var("wd_table")
        assert tuple(tbl.sharding.spec) == ("mp", None), tbl.sharding
        assert tbl.addressable_shards[0].data.shape == \
            (BIG["vocab_size"] // 2, BIG["emb_dim"])
        np.testing.assert_array_equal(np.asarray(tbl), saved_tbl)
        reset_mesh()

        # and a real-lr continuation tracks the oracle tail
        mesh2b = init_parallel_env(mesh_shape=[4, 2],
                                   axis_names=("dp", "mp"))
        with unique_name.guard():
            main, startup, loss = _build_wd(sparse=True, fleet_tp=True,
                                            **BIG)
        scope3 = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh2b)
        exe.run(startup, scope=scope3)
        restore_scope(scope3, snap)
        resumed = [float(np.asarray(exe.run(
            main, feed=feed, fetch_list=[loss],
            scope=scope3)[0]).ravel()[0]) for _ in range(2)]
        exe.drain()
        reset_mesh()
        np.testing.assert_allclose(resumed, base[2:4], rtol=1e-4,
                                   atol=1e-6)


@pytest.fixture
def restore_flags_budget():
    yield
    pt.set_flags({"FLAGS_hbm_budget_fraction": 0.0,
                  "FLAGS_hbm_bytes_per_device": 0})
