"""Prefix-cache page sharing, chunked prefill, and speculative decoding
(serving/kv_cache.py PrefixIndex + serving/decode.py tentpole paths).

The load-bearing property carried over from PR 10: decode-with-cache
logits are BITWISE equal to the full-recompute oracle on EVERY path —
full prefix hit (prefill skipped entirely), partial-tail borrow with
copy-on-write at the first divergent token, suffix prefill after a
page-aligned divergence, chunked prefill, and speculative verify.  Any
sharing bug (stale page, wrong CoW timing, draft desync) shows up as a
bit difference or a refcount imbalance (``PagedKVCache.debug_check``).
"""
import time

import numpy as np
import pytest

from paddle_tpu import serving
from paddle_tpu.monitor import stat_get
from paddle_tpu.serving.decode import DecodeConfig, DecodeEngine, \
    TransformerLM
from paddle_tpu.serving.kv_cache import PageAllocator, PrefixIndex

VOCAB = 61


@pytest.fixture(scope="module")
def model_and_weights():
    import jax

    model = TransformerLM(vocab_size=VOCAB, d_model=32, num_layers=2,
                          num_heads=2, max_seq_len=256)
    weights = model.init_weights(jax.random.PRNGKey(7))
    return model, weights


@pytest.fixture(scope="module")
def draft_and_weights():
    import jax

    # a real small draft: same vocab, smaller body, DIFFERENT weights
    # (low acceptance — exercises the rejection paths)
    draft = TransformerLM(vocab_size=VOCAB, d_model=16, num_layers=1,
                          num_heads=2, max_seq_len=256)
    return draft, draft.init_weights(jax.random.PRNGKey(99))


def make_engine(model_and_weights, draft=None, **cfg_kw):
    model, weights = model_and_weights
    kw = dict(slots=2, max_seq_len=64, page_size=8, max_new_tokens=8)
    kw.update(cfg_kw)
    dm, dw = draft if draft is not None else (None, None)
    return DecodeEngine(model, weights, DecodeConfig(**kw),
                        draft_model=dm, draft_weights=dw)


def assert_oracle_bitwise(eng, prompt, req, out):
    for t in range(len(out)):
        oracle = eng.recompute_logits(list(prompt) + list(out[:t]))
        assert np.array_equal(oracle, req.logits_trace[t]), (
            f"cached logits diverged from the full recompute at step "
            f"{t} (max diff "
            f"{np.abs(oracle - req.logits_trace[t]).max()})")


# -- prefix index plumbing ------------------------------------------------


def test_prefix_index_lookup_register_evict():
    idx = PrefixIndex(page_size=4)
    # register two pages of [1..8] then a partial tail [9, 9]
    n = idx.register([5, 6, 7], [1, 2, 3, 4, 5, 6, 7, 8, 9, 9],
                     on_new=lambda pid: None)
    assert n == 3 and len(idx) == 3
    full, partial = idx.lookup([1, 2, 3, 4, 5, 6, 7, 8, 9, 9])
    assert full == [5, 6] and partial == 7
    # a SHORTER tail that prefixes the registered partial also hits
    full, partial = idx.lookup([1, 2, 3, 4, 5, 6, 7, 8, 9])
    assert full == [5, 6] and partial == 7
    # divergence inside page 2 -> only page 1 matches, no partial
    full, partial = idx.lookup([1, 2, 3, 4, 5, 6, 99, 8, 1])
    assert full == [5] and partial is None
    # duplicate registration adopts the existing chain, registers none
    assert idx.register([11, 12], [1, 2, 3, 4, 5, 6, 7, 8],
                        on_new=lambda pid: None) == 0
    # eviction is bottom-up: the mid-chain page is never a victim
    # while its child lives
    evicted = []
    idx.evict(1, can_evict=lambda pid: True, on_evict=evicted.append)
    assert evicted == [7]  # the leaf (LRU-ranked among childless)
    idx.evict(10, can_evict=lambda pid: True, on_evict=evicted.append)
    assert evicted == [7, 6, 5] and len(idx) == 0


def test_page_allocator_double_free_raises():
    a = PageAllocator(6)
    pages = a.alloc(2)
    a.free(pages)
    with pytest.raises(RuntimeError, match="double free"):
        a.free([pages[0]])


def test_page_allocator_zero_alloc_takes_nothing():
    """Review pin: a fully-shared claim needs ZERO fresh pages; the
    n==0 slice (`_free[-0:]` == whole list) must not drain the pool."""
    a = PageAllocator(6)
    assert a.alloc(0) == []
    assert a.num_free == 5


def test_claim_eviction_never_recycles_matched_pages():
    """Review-hardening pin: under pool pressure the eviction-backed
    allocation must never free a page the SAME claim just matched and
    hand it back as a fresh page (one physical page in two table
    roles).  Matched pages are pinned before allocation; a partial
    borrow that then cannot fit is dropped (becoming evictable again)
    rather than deadlocking the queue head behind its own match."""
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.serving.kv_cache import CacheConfig, PagedKVCache

    cfg = CacheConfig(1, 1, 4, num_slots=2, max_seq_len=16,
                      page_size=4, num_pages=5)  # 4 usable pages
    cache = PagedKVCache(cfg, Scope(), prefix_cache=True)
    assert cache.claim(0, 8, prompt=[1, 2, 3, 4, 5, 6]) is not None
    cache.release(0, register_tokens=[1, 2, 3, 4, 5, 6])
    assert cache.shared_pages == 2 and cache.allocator.num_free == 2
    # total 4 pages, full hit 1, partial hit 1 -> 3 fresh vs 2 free:
    # the matched partial must not be evicted into the fresh set
    info = cache.claim(1, 16, prompt=[1, 2, 3, 4, 5, 6])
    assert info is not None  # liveness: the borrow is dropped, not stuck
    assert info.full_hits == 1 and not info.partial
    held = cache.slot_pages(1) + cache._cow_spare[1]
    assert len(held) == len(set(held)), \
        f"one physical page holds two table roles: {held}"
    cache.debug_check()
    cache.release(1)
    cache.debug_check()


# -- full prefix hit: prefill skipped, CoW at the first new token ---------


def test_full_hit_skips_prefill_cow_bitwise(model_and_weights):
    eng = make_engine(model_and_weights).start()
    prompt = [1, 2, 3, 4, 5]  # 5 tokens: partial tail page -> CoW
    try:
        out1 = eng.generate(prompt, max_new_tokens=6)
        skip0 = stat_get("decode_prefill_skipped")
        cow0 = stat_get("decode_cow_copies")
        r2 = eng.submit(prompt, max_new_tokens=6, record_logits=True)
        out2 = r2.result(timeout=120)
    finally:
        eng.stop()
    assert out2 == out1  # greedy: the shared-prefix replay is identical
    assert stat_get("decode_prefill_skipped") == skip0 + 1
    # the borrowed partial tail page was copy-on-written exactly once,
    # at the first token the new request wrote into it
    assert stat_get("decode_cow_copies") == cow0 + 1
    assert_oracle_bitwise(eng, prompt, r2, out2)
    assert eng.stats()["cache_hit_rate"] > 0
    eng._cache.debug_check()


def test_page_aligned_divergence_suffix_prefill_bitwise(
        model_and_weights):
    """Prompts sharing whole pages then diverging: the shared pages
    are borrowed, ONLY the unmatched suffix is prefilled, and logits
    stay bitwise-equal to the no-sharing oracle."""
    eng = make_engine(model_and_weights).start()
    base = list(range(1, 17))  # 2 full pages (page_size=8)
    try:
        eng.generate(base + [20, 21], max_new_tokens=4)
        hit0 = stat_get("decode_prefix_pages_hit")
        r = eng.submit(base + [40, 41, 42], max_new_tokens=5,
                       record_logits=True)
        out = r.result(timeout=120)
    finally:
        eng.stop()
    assert stat_get("decode_prefix_pages_hit") - hit0 == 2
    assert len(out) == 5
    assert_oracle_bitwise(eng, base + [40, 41, 42], r, out)
    eng._cache.debug_check()


def test_mid_page_divergence_is_a_miss_and_stays_bitwise(
        model_and_weights):
    eng = make_engine(model_and_weights).start()
    try:
        eng.generate([1, 2, 3, 4, 5, 6, 7, 8, 9, 10], max_new_tokens=4)
        # diverges at position 9 (inside page 2): page 1 hits, the
        # divergent page is computed fresh
        r = eng.submit([1, 2, 3, 4, 5, 6, 7, 8, 9, 77],
                       max_new_tokens=4, record_logits=True)
        out = r.result(timeout=120)
    finally:
        eng.stop()
    assert_oracle_bitwise(eng, [1, 2, 3, 4, 5, 6, 7, 8, 9, 77], r, out)
    eng._cache.debug_check()


# -- admission capacity: >= 2x at fixed pool size -------------------------


@pytest.mark.slow  # wall-clock paced (sleep-held slots); the 2x ratio
# is also enforced by bench.py's decode_shared_admission_capacity_ratio
def test_shared_admission_capacity_at_least_doubles(model_and_weights):
    """The acceptance bar: at a FIXED pool size, prefix sharing must
    admit >= 2x the concurrent requests of the unshared engine.  Each
    request needs 3 pages unshared; the pool holds 7, so unshared
    concurrency is 2.  With the 2-page prefix shared, each extra
    request only allocates 1 fresh page."""
    prefix = list(range(1, 17))  # 2 full pages
    model, weights = model_and_weights

    def max_live(prefix_cache):
        eng = make_engine(model_and_weights, slots=6, max_seq_len=64,
                          page_size=8, num_pages=8,
                          prefix_cache=prefix_cache).start()
        try:
            if prefix_cache:  # register the prefix
                eng.generate(prefix + [50], max_new_tokens=5)
            reqs = [eng.submit(prefix + [51 + i], max_new_tokens=6,
                               on_token=lambda t: time.sleep(0.05))
                    for i in range(6)]
            peak = 0
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline \
                    and not all(r.done() for r in reqs):
                peak = max(peak, eng.live_slots)
                time.sleep(0.005)
            for r in reqs:
                r.result(timeout=120)
        finally:
            eng.stop()
        return peak

    unshared = max_live(False)
    shared = max_live(True)
    assert unshared <= 2  # 7 pages // 3 per request
    assert shared >= 2 * unshared, (
        f"sharing admitted {shared} concurrent vs {unshared} unshared")


def test_prefix_eviction_under_pool_pressure(model_and_weights):
    """Cache-retained pages are reclaimed (LRU, childless-first) when
    admission needs them — retention never blocks new work."""
    eng = make_engine(model_and_weights, slots=2, max_seq_len=64,
                      page_size=8, num_pages=9).start()
    try:
        # three disjoint finished requests pin 2 registered pages each
        for base in (0, 20, 40):
            eng.generate([base + i for i in range(1, 9)],
                         max_new_tokens=8)
        ev0 = stat_get("decode_prefix_evictions")
        assert eng._cache.shared_pages == 6  # 8 usable, 2 free
        out = eng.generate(list(range(50, 50 + 16)), max_new_tokens=8)
    finally:
        eng.stop()
    assert len(out) == 8
    assert stat_get("decode_prefix_evictions") > ev0
    eng._cache.debug_check()


# -- chunked prefill ------------------------------------------------------


def test_chunked_prefill_bitwise(model_and_weights):
    eng = make_engine(model_and_weights, slots=2, max_seq_len=64,
                      page_size=8, prefill_chunk_pages=1,
                      prefix_cache=False).start()
    prompt = list(range(1, 28))  # 27 tokens -> 4 one-page chunks
    try:
        c0 = stat_get("prefill_chunks")
        r = eng.submit(prompt, max_new_tokens=5, record_logits=True)
        out = r.result(timeout=120)
    finally:
        eng.stop()
    assert stat_get("prefill_chunks") - c0 == 4
    assert_oracle_bitwise(eng, prompt, r, out)


def test_chunked_prefill_protects_ttft_under_long_prompt_adversary(
        model_and_weights):
    """A long prompt fills its pages across several step boundaries;
    short requests keep streaming between chunks, so the adversary
    cannot stall their time-to-first-token behind its whole prefill.
    Deterministic scheduling property: the short request's first token
    must arrive BEFORE the long request's (the long prefill needs ~6
    boundaries, the short one 1)."""
    eng = make_engine(model_and_weights, slots=3, max_seq_len=128,
                      page_size=8, prefill_chunk_pages=1,
                      max_new_tokens=64, prefix_cache=False).start()
    try:
        eng.generate([9, 9], max_new_tokens=2)  # pay the step compiles
        adversary = eng.submit(list(range(1, 49)), max_new_tokens=4)
        short = eng.submit([3, 1], max_new_tokens=4)
        out_s = short.result(timeout=120)
        out_a = adversary.result(timeout=120)
    finally:
        eng.stop()
    assert len(out_s) == 4 and len(out_a) == 4
    assert short.t_first_token < adversary.t_first_token, (
        "the short request's first token waited for the adversary's "
        "whole prefill — chunking did not yield the step loop")


# -- speculative decoding -------------------------------------------------


@pytest.mark.parametrize(
    "k", [1, pytest.param(4, marks=pytest.mark.slow)])
# tier-1 keeps k=1 here and k=4 in the self-draft test below: both k
# values and both acceptance regimes stay covered within the budget
def test_spec_greedy_bitwise_low_acceptance_draft(
        model_and_weights, draft_and_weights, k):
    """With a REAL (weak) draft, rejections dominate — output must
    still be bitwise-identical to non-speculative greedy decode, and
    every emitted token's logits must match the full-recompute
    oracle."""
    prompt = [1, 2, 3, 4, 5]
    eng = make_engine(model_and_weights).start()
    try:
        ref = eng.generate(prompt, max_new_tokens=10)
    finally:
        eng.stop()
    eng = make_engine(model_and_weights, draft=draft_and_weights,
                      spec_k=k).start()
    try:
        r = eng.submit(prompt, max_new_tokens=10, record_logits=True)
        out = r.result(timeout=120)
    finally:
        eng.stop()
    assert out == ref
    assert_oracle_bitwise(eng, prompt, r, out)
    eng._cache.debug_check()


@pytest.mark.parametrize(
    "k", [pytest.param(1, marks=pytest.mark.slow), 4])
def test_spec_self_draft_full_acceptance_fewer_rounds(
        model_and_weights, k):
    """Draft == target: every proposal is accepted, so N tokens take
    ~N/(k+1) verify rounds instead of N steps — the speedup mechanism,
    pinned via dispatch counts (wall-clock-free)."""
    model, weights = model_and_weights
    prompt = [1, 2, 3]
    n_new = 12
    eng = make_engine(model_and_weights).start()
    try:
        ref = eng.generate(prompt, max_new_tokens=n_new)
    finally:
        eng.stop()
    eng = make_engine(model_and_weights, draft=(model, weights),
                      spec_k=k).start()
    try:
        r0 = stat_get("decode_spec_rounds")
        p0 = stat_get("decode_spec_proposed")
        a0 = stat_get("decode_spec_accepted")
        r = eng.submit(prompt, max_new_tokens=n_new, record_logits=True)
        out = r.result(timeout=120)
    finally:
        eng.stop()
    assert out == ref
    assert_oracle_bitwise(eng, prompt, r, out)
    rounds = stat_get("decode_spec_rounds") - r0
    proposed = stat_get("decode_spec_proposed") - p0
    accepted = stat_get("decode_spec_accepted") - a0
    assert accepted == proposed > 0  # self-draft: full acceptance
    # prefill emits 1, each round emits k+1, a possible final single
    # step emits the remainder
    import math
    assert rounds <= math.ceil((n_new - 1) / (k + 1))


def test_spec_composes_with_prefix_sharing(model_and_weights):
    """A full prefix hit on a spec engine: prefill skipped AND the
    draft reads the shared pages (its pools share page ids), with
    output still bitwise-equal to the oracle."""
    model, weights = model_and_weights
    prompt = [7, 6, 5, 4, 3, 2, 1]
    eng = make_engine(model_and_weights, draft=(model, weights),
                      spec_k=2).start()
    try:
        out1 = eng.generate(prompt, max_new_tokens=8)
        skip0 = stat_get("decode_prefill_skipped")
        r = eng.submit(prompt, max_new_tokens=8, record_logits=True)
        out2 = r.result(timeout=120)
    finally:
        eng.stop()
    assert out2 == out1
    assert stat_get("decode_prefill_skipped") == skip0 + 1
    assert_oracle_bitwise(eng, prompt, r, out2)
    eng._cache.debug_check()


def test_spec_vocab_mismatch_and_submit_rejections(model_and_weights,
                                                   draft_and_weights):
    model, weights = model_and_weights
    bad_draft = TransformerLM(vocab_size=VOCAB + 1, d_model=16,
                              num_layers=1, num_heads=2,
                              max_seq_len=256)
    import jax

    with pytest.raises(ValueError, match="vocab mismatch"):
        make_engine(model_and_weights,
                    draft=(bad_draft,
                           bad_draft.init_weights(jax.random.PRNGKey(0))))
    # a request that DEMANDS speculation fails loudly at submit when
    # the engine cannot honor it
    eng = make_engine(model_and_weights)  # no draft
    with pytest.raises(ValueError, match="no draft"):
        eng.submit([1, 2], speculative=True)
    eng2 = make_engine(model_and_weights, draft=draft_and_weights,
                       spec_k=0)
    with pytest.raises(ValueError, match="spec_k"):
        eng2.submit([1, 2], speculative=True)
    eng3 = make_engine(model_and_weights, draft=draft_and_weights,
                       spec_k=2)
    with pytest.raises(ValueError, match="greedy-only"):
        eng3.submit([1, 2], speculative=True, temperature=0.7)


# -- pallas multi-row kernel ----------------------------------------------


def test_paged_chunk_attention_pallas_interpret_matches_reference():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas_decode_attention import \
        paged_chunk_attention

    rs = np.random.RandomState(0)
    s, r, h, d, pool, page, pps = 3, 5, 2, 16, 9, 8, 4
    q = jnp.asarray(rs.randn(s, r, h, d).astype("f4"))
    kp = jnp.asarray(rs.randn(pool, page, h, d).astype("f4"))
    vp = jnp.asarray(rs.randn(pool, page, h, d).astype("f4"))
    table = jnp.asarray(rs.randint(1, pool, (s, pps)).astype("i4"))
    # starts at a mid-page offset, zero, and near the table's end
    starts = np.array([7, 0, 27], "i4")
    row_lengths = jnp.asarray(
        starts[:, None] + np.arange(1, r + 1, dtype="i4")[None, :])
    ref = paged_chunk_attention(q, kp, vp, table, row_lengths,
                                use_pallas="never")
    pal = paged_chunk_attention(q, kp, vp, table, row_lengths,
                                use_pallas="always", interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(pal),
                               rtol=1e-5, atol=1e-5)
    # review pin: the kernel's page-skip bound must hold for ARBITRARY
    # per-row lengths, not just the ascending ones the engine passes
    # (the widest row used to be assumed last)
    weird = jnp.asarray(np.array([[20, 5, 1, 17, 9],
                                  [3, 30, 2, 2, 2],
                                  [1, 1, 1, 1, 32]], "i4"))
    ref = paged_chunk_attention(q, kp, vp, table, weird,
                                use_pallas="never")
    pal = paged_chunk_attention(q, kp, vp, table, weird,
                                use_pallas="always", interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(pal),
                               rtol=1e-5, atol=1e-5)


# -- free-list audit: chaos across admit / CoW / reap ---------------------


@pytest.mark.parametrize("kv_quant", [False, True],
                         ids=["fp", "kv_quant"])
def test_chaos_admit_cow_reap_never_leaks_or_double_frees(
        model_and_weights, kv_quant):
    """The bugfix-sweep pin: randomized waves of shared-prefix
    requests — full hits, partial borrows, CoW, deadline reaps,
    abandons, chunked prefills, speculative rounds — must leave the
    refcount/free-list/index books EXACTLY balanced
    (``debug_check``).  With ``kv_quant`` the audit extends to the
    scale pools (target + draft): finite scales everywhere, freed
    pages' scale planes reset."""
    model, weights = model_and_weights
    rs = np.random.RandomState(11)
    prefixes = [list(range(1, 9)), list(range(30, 42)), [5, 5, 5]]
    eng = make_engine(model_and_weights, slots=3, max_seq_len=64,
                      page_size=8, num_pages=17, max_queue=64,
                      prefill_chunk_pages=1, kv_quant=kv_quant,
                      draft=(model, weights), spec_k=2).start()
    try:
        waves = []
        for _ in range(6):
            reqs = []
            for _ in range(6):
                prompt = list(prefixes[rs.randint(len(prefixes))])
                prompt += [int(t) for t in
                           rs.randint(1, VOCAB, rs.randint(0, 5))]
                kw = dict(max_new_tokens=int(rs.randint(2, 8)))
                roll = rs.rand()
                if roll < 0.2:
                    kw["deadline_ms"] = 1  # reaped while queued/early
                elif roll < 0.4:
                    kw["temperature"] = 1.0  # non-spec slot in the mix
                reqs.append(eng.submit(prompt, **kw))
            waves.append(reqs)
            time.sleep(0.02)
        for reqs in waves:
            for r in reqs:
                try:
                    r.result(timeout=120)
                except serving.DeadlineExceededError:
                    pass
        # quiesce, then audit the books
        deadline = time.monotonic() + 30
        while eng.live_slots and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.live_slots == 0
        eng._cache.debug_check()
        usable = eng._cache.config.num_pages - 1
        assert (eng._cache.allocator.num_free
                + eng._cache.shared_pages) == usable
        # the chaos actually exercised the tentpole paths
        st = eng.stats()
        assert st["prefix_hit_pages"] > 0
        assert st["prefill_chunks"] > 0
        assert st["spec_proposed"] > 0
    finally:
        eng.stop()


# -- observability --------------------------------------------------------


def test_tentpole_metrics_on_prometheus(model_and_weights):
    model, weights = model_and_weights
    eng = make_engine(model_and_weights, draft=(model, weights),
                      spec_k=2, prefill_chunk_pages=1).start()
    try:
        prompt = list(range(1, 12))
        eng.generate(prompt, max_new_tokens=4)
        eng.generate(prompt, max_new_tokens=4)  # hit + CoW
    finally:
        eng.stop()
    from paddle_tpu.observe.histogram import prometheus_text

    text = prometheus_text()
    for series in ("decode_cache_hit_rate", "decode_shared_pages",
                   "decode_cow_copies", "spec_accept_rate",
                   "prefill_chunks", "decode_prefix_pages_hit",
                   "decode_prefill_skipped"):
        assert series in text, series


@pytest.mark.slow  # two spec replicas = the compile-heaviest setup;
# the aggregation fields are plain sums over the per-replica stats
# that test_tentpole_metrics_on_prometheus already exercises
def test_decode_server_aggregates_tentpole_stats(model_and_weights):
    model, weights = model_and_weights
    cfg = DecodeConfig(slots=2, max_seq_len=64, page_size=8,
                       max_new_tokens=6, spec_k=2)
    srv = serving.DecodeServer(model, weights, cfg, replicas=2,
                               draft_model=model,
                               draft_weights=weights).start()
    try:
        prompt = [2, 4, 6, 8]
        for eng in srv.replicas:  # register + hit on BOTH replicas
            eng.generate(prompt, max_new_tokens=4)
            eng.generate(prompt, max_new_tokens=4)
        st = srv.stats()
    finally:
        srv.stop()
    assert st["cache_hit_rate"] > 0
    assert st["shared_pages"] > 0
    assert st["cow_copies"] >= 2
    assert {p["name"] for p in st["replicas"]} == \
        {"replica-0", "replica-1"}
    assert all("cache_hit_rate" in p for p in st["replicas"])


# -- ragged prefill packing (ISSUE 17) ------------------------------------


def _run_prompts(eng, prompts, new=4):
    """Submit concurrently, return ([outputs], [logits traces])."""
    try:
        reqs = [eng.submit(p, max_new_tokens=new, record_logits=True)
                for p in prompts]
        outs = [r.result(timeout=120) for r in reqs]
    finally:
        eng.stop()
    return outs, [r.logits_trace for r in reqs]


def test_ragged_prefill_bitwise_and_waste_drop(model_and_weights):
    """FLAGS_decode_ragged_prefill packs several prompts' chunk tails
    into one multi-lane dispatch (per-lane (page, offset) coords).
    Contract: decoded tokens AND per-step logits stay bitwise equal to
    the padded chunk path, while the measured prefill pad waste
    (record_pad_waste counters) strictly drops — padding rounded 27/13/5
    up to 8-row chunks (56 rows), packing shares 3x16 lanes (48 rows)."""
    prompts = [list(range(1, 28)), [7, 3, 9, 2, 11, 5, 4, 8, 6, 1, 2, 3,
                                    4], [5, 1, 2, 4, 3]]

    def waste_fraction(run):
        p0 = stat_get("prefill_padded_tokens_total")
        l0 = stat_get("prefill_live_tokens_total")
        result = run()
        pad = stat_get("prefill_padded_tokens_total") - p0
        live = stat_get("prefill_live_tokens_total") - l0
        assert pad + live > 0, "no prefill dispatches accounted"
        return result, pad / (pad + live)

    (pad_outs, pad_logits), frac_padded = waste_fraction(
        lambda: _run_prompts(make_engine(
            model_and_weights, slots=4, prefill_chunk_pages=1,
            prefix_cache=False).start(), prompts))
    r0 = stat_get("decode_ragged_dispatches")
    (rag_outs, rag_logits), frac_ragged = waste_fraction(
        lambda: _run_prompts(make_engine(
            model_and_weights, slots=4, prefill_chunk_pages=1,
            prefix_cache=False, ragged_prefill_rows=16).start(),
            prompts))

    assert stat_get("decode_ragged_dispatches") - r0 >= 1
    assert rag_outs == pad_outs, "ragged packing changed decoded tokens"
    for pt_, rt in zip(pad_logits, rag_logits):
        assert len(pt_) == len(rt)
        for a, b in zip(pt_, rt):
            assert np.array_equal(a, b), \
                "ragged packing changed a recorded logits row"
    assert frac_ragged < frac_padded, (
        f"ragged packing did not reduce prefill pad waste "
        f"({frac_ragged:.4f} vs {frac_padded:.4f})")


def test_ragged_prefill_single_prompt_bitwise(model_and_weights):
    """Degenerate packing (one request, dead lanes to the trash page)
    must still be bitwise vs the full-recompute oracle."""
    eng = make_engine(model_and_weights, slots=2, prefill_chunk_pages=1,
                      prefix_cache=False, ragged_prefill_rows=16).start()
    prompt = list(range(1, 28))
    try:
        r = eng.submit(prompt, max_new_tokens=5, record_logits=True)
        out = r.result(timeout=120)
    finally:
        eng.stop()
    assert_oracle_bitwise(eng, prompt, r, out)


def test_pad_waste_gauge_accounts_padded_path(model_and_weights):
    """Satellite bugfix: the pad-waste gauge must move on the PADDED
    paths too (full prefill and chunked rows), not only under ragged
    packing — otherwise the A/B has no baseline."""
    from paddle_tpu.serving.buckets import record_pad_waste

    w0 = stat_get("prefill_padded_tokens_total")
    eng = make_engine(model_and_weights, slots=2,
                      prefix_cache=False).start()
    try:
        eng.generate([1, 2, 3, 4, 5], max_new_tokens=2)  # 5 -> bucket 8
    finally:
        eng.stop()
    assert stat_get("prefill_padded_tokens_total") - w0 >= 3
    # the gauge re-derives ppm from the cumulative counters
    record_pad_waste(1, 2)
    g = stat_get("prefill_pad_waste")
    assert 0 < g < 1_000_000
    assert eng.stats()["prefill_pad_waste"] == pytest.approx(g / 1e6)
