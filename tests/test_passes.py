"""Graph-pass pipeline tests (framework/passes.py).

Reference parity: fuse_all_reduce_op_pass + coalesce_tensor_op (tensor
fusion for data-parallel gradient allreduce), delete_cast_op_pass, and
graph DCE.  The oracle mirrors test_dist_base.py: fused and unfused
runs must produce identical losses AND identical parameter updates on
the multi-device CPU mesh.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import dtypes, passes
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.monitor import stat_get, stat_reset
from paddle_tpu.distributed.parallel_env import init_parallel_env, reset_mesh


# mesh8 fixture: shared in tests/conftest.py


def _mark(mb=32.0):
    return {passes.FUSED_ALLREDUCE_ATTR: True, passes.FUSE_SIZE_ATTR: mb}


def _allreduce_program(specs, mb=32.0, fp16=False):
    """Hand-built program shaped like the transpiler output: per tensor
    a producer, then [cast bf16] -> marked c_allreduce_sum -> [cast
    back], all in-place, exactly what FuseAllReducePass consumes."""
    main = Program()
    block = main.global_block
    for name, shape, dtype in specs:
        block.create_var(name=name, shape=shape, dtype=dtype)
        block.append_op("fill_constant", {}, {"Out": [name]},
                        {"shape": list(shape), "dtype": dtype, "value": 1.0})
        if fp16:
            block.append_op("cast", {"X": [name]}, {"Out": [name]},
                            {"out_dtype": dtypes.to_enum("bfloat16"),
                             **_mark(mb)})
        block.append_op("c_allreduce_sum", {"X": [name]}, {"Out": [name]},
                        {"ring_id": 0, "use_calc_stream": True, **_mark(mb)})
        if fp16:
            block.append_op("cast", {"X": [name]}, {"Out": [name]},
                            {"out_dtype": dtypes.to_enum(dtype), **_mark(mb)})
    return main


def _coalesce_ops(program):
    return [op for op in program.global_block.ops
            if op.type == "coalesce_tensor"]


def _count(program, op_type):
    return sum(1 for op in program.global_block.ops if op.type == op_type)


def _build_fleet_net(fuse=True, mb=32, fp16=False, layers_n=4, width=64,
                     lr=0.05):
    from paddle_tpu.distributed import fleet
    from paddle_tpu.framework import unique_name
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.optimizer import MomentumOptimizer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = Program(), Program()
    main.random_seed = 1
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1])
        h = x
        for i in range(layers_n):
            h = layers.fc(h, width, act="relu", param_attr=ParamAttr(
                initializer=ConstantInitializer(0.02 * (i + 1))),
                bias_attr=False)
        pred = layers.fc(h, 1, param_attr=ParamAttr(
            initializer=ConstantInitializer(0.1)), bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        strat = fleet.DistributedStrategy()
        strat.fuse_all_reduce_ops = fuse
        strat.fuse_grad_size_in_MB = mb
        if fp16:
            strat.fp16_allreduce = True
        fleet.init(is_collective=True, strategy=strat)
        fleet.distributed_optimizer(MomentumOptimizer(lr, 0.9))
        fleet.minimize(loss)
    return main, startup, loss


def _train(main, startup, loss, X, Y, steps=4, mesh=None):
    scope = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup, scope=scope)
    losses = [float(np.asarray(
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                scope=scope)[0]).item()) for _ in range(steps)]
    params = {n: np.asarray(scope.get_var(n)).copy()
              for n in scope.local_var_names()
              if ".w" in n or ".b" in n}
    return losses, params, exe


class TestFuseAllReducePass:
    def test_bucket_size_cap_respected(self):
        # 16 x 64KB fp32 tensors; cap 0.25MB -> exactly 4 buckets of 4
        specs = [(f"g{i}", [128, 128], "float32") for i in range(16)]
        prog = _allreduce_program(specs, mb=0.25)
        changed = passes.FuseAllReducePass().apply(prog, passes.PassContext())
        assert changed
        co = _coalesce_ops(prog)
        assert len(co) == 4
        cap = 0.25 * 1024 * 1024
        for op in co:
            nbytes = sum(128 * 128 * 4 for _ in op.inputs["Input"])
            assert nbytes <= cap
        # exactly ceil(total_bytes / cap) collectives survive
        assert _count(prog, "c_allreduce_sum") == 4
        assert _count(prog, "uncoalesce_tensor") == 4
        assert stat_get("pass_fused_allreduce_buckets") == 4
        assert stat_get("pass_allreduce_ops_before") == 16
        assert stat_get("pass_allreduce_ops_after") == 4

    def test_oversize_tensor_gets_own_bucket(self):
        # 'big' sits BETWEEN the small grads: it must not evict the open
        # bucket, so s1+s2 still fuse across it
        specs = [("s1", [64, 64], "float32"),
                 ("big", [600, 128], "float32"),   # ~0.29MB > cap
                 ("s2", [64, 64], "float32")]
        prog = _allreduce_program(specs, mb=0.25)
        passes.FuseAllReducePass().apply(prog, passes.PassContext())
        groups = [op.inputs["Input"] for op in _coalesce_ops(prog)]
        assert ["s1", "s2"] in groups
        # the oversize tensor stays in a singleton -> left unfused
        assert all("big" not in g for g in groups)
        assert _count(prog, "c_allreduce_sum") == 2

    def test_mixed_dtype_never_share_bucket(self):
        specs = [("a32", [32, 32], "float32"), ("a16", [32, 32], "bfloat16"),
                 ("b32", [32, 32], "float32"), ("b16", [32, 32], "bfloat16")]
        prog = _allreduce_program(specs, mb=32.0)
        passes.FuseAllReducePass().apply(prog, passes.PassContext())
        for op in _coalesce_ops(prog):
            dts = {passes.dtypes.to_str(
                prog.global_block.var(n).dtype) for n in op.inputs["Input"]}
            assert len(dts) == 1, dts
        assert _count(prog, "c_allreduce_sum") == 2

    def test_fp16_one_cast_pair_per_bucket(self):
        specs = [(f"g{i}", [32, 32], "float32") for i in range(6)]
        prog = _allreduce_program(specs, mb=32.0, fp16=True)
        assert _count(prog, "cast") == 12
        passes.FuseAllReducePass().apply(prog, passes.PassContext())
        # 6 per-grad pairs collapse to ONE pair around the one bucket
        assert _count(prog, "cast") == 2
        assert _count(prog, "c_allreduce_sum") == 1

    def test_unmarked_allreduce_untouched(self):
        main = Program()
        block = main.global_block
        block.create_var(name="g", shape=[4, 4], dtype="float32")
        block.append_op("fill_constant", {}, {"Out": ["g"]},
                        {"shape": [4, 4], "dtype": "float32", "value": 1.0})
        block.append_op("c_allreduce_sum", {"X": ["g"]}, {"Out": ["g"]},
                        {"ring_id": 0})
        p = passes.FuseAllReducePass()
        assert not p.should_apply(main, passes.PassContext())
        assert not p.apply(main, passes.PassContext())
        assert _count(main, "coalesce_tensor") == 0


class TestFusedNumerics:
    def test_coalesce_uncoalesce_roundtrip(self, mesh8):
        """Fused collective == per-tensor collective, elementwise, on the
        real 8-device mesh."""
        specs = [("a", [8, 3], "float32"), ("b", [8, 5], "float32")]
        prog = _allreduce_program(specs, mb=32.0)
        passes.FuseAllReducePass().apply(prog, passes.PassContext())
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh8)
        a, b = exe.run(prog, feed={}, fetch_list=["a", "b"], scope=scope)
        # fill_constant(1.0) psum'd over 8 shards -> all 8s
        np.testing.assert_array_equal(np.asarray(a), np.full((8, 3), 8.0))
        np.testing.assert_array_equal(np.asarray(b), np.full((8, 5), 8.0))

    def test_fused_vs_unfused_parity_fp32(self, mesh8):
        """The acceptance oracle: fused and unfused DP training produce
        bitwise-identical losses and parameter updates in fp32."""
        rs = np.random.RandomState(0)
        X = rs.randn(32, 8).astype("f4")
        Y = rs.randn(32, 1).astype("f4")

        m1, s1, l1 = _build_fleet_net(fuse=False)
        base_losses, base_params, _ = _train(m1, s1, l1, X, Y, mesh=mesh8)

        stat_reset("pass_fused_allreduce_buckets")
        m2, s2, l2 = _build_fleet_net(fuse=True)
        fused_losses, fused_params, _ = _train(m2, s2, l2, X, Y, mesh=mesh8)

        # fusion actually engaged (observable via monitor stats)
        assert stat_get("pass_fused_allreduce_buckets") >= 1
        assert stat_get("pass_allreduce_ops_after") \
            < stat_get("pass_allreduce_ops_before")
        np.testing.assert_array_equal(base_losses, fused_losses)
        assert base_params.keys() == fused_params.keys()
        for n in base_params:
            np.testing.assert_array_equal(base_params[n], fused_params[n])

    def test_fused_vs_unfused_parity_fp16_allreduce(self, mesh8):
        """bf16-allreduce strategy: per-bucket cast pair must give the
        same result as per-grad casts (elementwise identical ops)."""
        rs = np.random.RandomState(1)
        X = rs.randn(32, 8).astype("f4")
        Y = rs.randn(32, 1).astype("f4")

        m1, s1, l1 = _build_fleet_net(fuse=False, fp16=True)
        base_losses, base_params, _ = _train(m1, s1, l1, X, Y, mesh=mesh8)

        m2, s2, l2 = _build_fleet_net(fuse=True, fp16=True)
        fused_losses, fused_params, _ = _train(m2, s2, l2, X, Y, mesh=mesh8)

        np.testing.assert_allclose(base_losses, fused_losses,
                                   rtol=1e-2, atol=1e-4)
        for n in base_params:
            np.testing.assert_allclose(base_params[n], fused_params[n],
                                       rtol=1e-2, atol=1e-4)

    def test_user_program_never_mutated(self, mesh8):
        """The executor rewrites a CLONE: the user's transpiled program
        keeps its per-grad allreduces (fuse off restores it exactly)."""
        rs = np.random.RandomState(2)
        X = rs.randn(32, 8).astype("f4")
        Y = rs.randn(32, 1).astype("f4")
        m, s, l = _build_fleet_net(fuse=True)
        fp_before = m.fingerprint()
        n_ar = _count(m, "c_allreduce_sum")
        _train(m, s, l, X, Y, steps=1, mesh=mesh8)
        assert m.fingerprint() == fp_before
        assert _count(m, "c_allreduce_sum") == n_ar
        assert _count(m, "coalesce_tensor") == 0

    def test_fuse_off_restores_prepass_program(self, mesh8):
        m, s, l = _build_fleet_net(fuse=False)
        assert not any(op.attr(passes.FUSED_ALLREDUCE_ATTR)
                       for op in m.global_block.ops)
        # nothing for the pipeline to do -> executor compiles the
        # original object itself
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh8)
        out = exe._apply_graph_passes(m, (l.name,), {},
                                      pt.framework.Scope())
        assert out is m


class TestRedundantCastElimination:
    def test_duplicate_cast_removed(self):
        main = Program()
        block = main.global_block
        block.create_var(name="x", shape=[4], dtype="float32")
        block.create_var(name="y", shape=[4], dtype="bfloat16")
        block.create_var(name="z", shape=[4], dtype="bfloat16")
        block.append_op("cast", {"X": ["x"]}, {"Out": ["y"]},
                        {"out_dtype": dtypes.to_enum("bfloat16")})
        # y provably bf16 already -> this cast is a no-op
        block.append_op("cast", {"X": ["y"]}, {"Out": ["z"]},
                        {"out_dtype": dtypes.to_enum("bfloat16")})
        ctx = passes.PassContext(feed_names=("x",))
        assert passes.RedundantCastEliminationPass().apply(main, ctx)
        types = [op.type for op in block.ops]
        assert types.count("cast") == 1
        assert "assign" in types  # y->z value flow preserved

    def test_feed_dtype_not_trusted(self):
        """jax device-array feeds bypass _feed_spec's dtype coercion, so
        a cast of a feed to its DECLARED dtype is not provably a no-op
        and must survive."""
        main = Program()
        block = main.global_block
        block.create_var(name="x", shape=[4], dtype="float32")
        block.create_var(name="y", shape=[4], dtype="float32")
        block.append_op("cast", {"X": ["x"]}, {"Out": ["y"]},
                        {"out_dtype": dtypes.to_enum("float32")})
        ctx = passes.PassContext(feed_names=("x",))
        assert not passes.RedundantCastEliminationPass().apply(main, ctx)
        assert _count(main, "cast") == 1

    def test_inplace_bf16_roundtrip_kept(self):
        """Declared-fp32 var holding bf16 bits (fp16-allreduce pattern):
        the cast back to fp32 is NOT redundant and must survive."""
        main = Program()
        block = main.global_block
        block.create_var(name="g", shape=[4], dtype="float32")
        block.append_op("fill_constant", {}, {"Out": ["g"]},
                        {"shape": [4], "dtype": "float32", "value": 1.0})
        block.append_op("cast", {"X": ["g"]}, {"Out": ["g"]},
                        {"out_dtype": dtypes.to_enum("bfloat16")})
        block.append_op("c_allreduce_sum", {"X": ["g"]}, {"Out": ["g"]},
                        {"ring_id": 0})
        block.append_op("cast", {"X": ["g"]}, {"Out": ["g"]},
                        {"out_dtype": dtypes.to_enum("float32")})
        changed = passes.RedundantCastEliminationPass().apply(
            main, passes.PassContext())
        assert not changed
        assert _count(main, "cast") == 2


class TestDeadOpElimination:
    def _program(self):
        main = Program()
        block = main.global_block
        for n in ("a", "dead", "out"):
            block.create_var(name=n, shape=[2], dtype="float32")
        block.create_var(name="state", shape=[2], dtype="float32",
                         persistable=True)
        block.append_op("fill_constant", {}, {"Out": ["a"]},
                        {"shape": [2], "dtype": "float32", "value": 1.0})
        block.append_op("scale", {"X": ["a"]}, {"Out": ["out"]},
                        {"scale": 2.0, "bias": 0.0})
        block.append_op("scale", {"X": ["a"]}, {"Out": ["dead"]},
                        {"scale": 3.0, "bias": 0.0})  # feeds nothing
        block.append_op("scale", {"X": ["a"]}, {"Out": ["state"]},
                        {"scale": 4.0, "bias": 0.0})  # persistable write
        return main

    def test_dead_op_removed_roots_kept(self):
        main = self._program()
        ctx = passes.PassContext(fetch_names=("out",))
        assert passes.DeadOpEliminationPass().apply(main, ctx)
        written = [n for op in main.global_block.ops
                   for n in op.output_arg_names()]
        assert "dead" not in written
        assert "out" in written and "state" in written

    def test_side_effect_ops_survive(self):
        """send AND recv must both survive: the lowering pairs them
        POSITIONALLY per ring, so pruning a dead-output recv while its
        send stays pinned would mis-pair every later transfer."""
        main = self._program()
        block = main.global_block
        block.create_var(name="rcv", shape=[2], dtype="float32")
        block.append_op("send_v2", {"X": ["a"]}, {},
                        {"ring_id": 7, "peer": 1})
        block.append_op("recv_v2", {}, {"Out": ["rcv"]},
                        {"ring_id": 7, "peer": 0})  # rcv feeds nothing
        ctx = passes.PassContext(fetch_names=("out",))
        passes.DeadOpEliminationPass().apply(main, ctx)
        assert _count(main, "send_v2") == 1
        assert _count(main, "recv_v2") == 1

    def test_end_to_end_dead_removed(self):
        main = self._program()
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        out = exe.run(main, feed={}, fetch_list=["out"], scope=scope)
        np.testing.assert_array_equal(np.asarray(out[0]), [2.0, 2.0])
        np.testing.assert_array_equal(
            np.asarray(scope.get_var("state")), [4.0, 4.0])


class TestPassCacheAndFlags:
    def test_pass_cache_hit_and_fingerprint_invalidation(self):
        main = self._two_op_program()
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(main, feed={}, fetch_list=["out"], scope=scope)
        n_entries = len(exe._pass_cache)
        h0 = stat_get("executor_pass_cache_hit")
        exe.run(main, feed={}, fetch_list=["out"], scope=scope)
        assert stat_get("executor_pass_cache_hit") == h0 + 1
        assert len(exe._pass_cache) == n_entries
        # mutation bumps the fingerprint -> pass pipeline re-applies
        main.global_block.append_op(
            "scale", {"X": ["out"]}, {"Out": ["out"]},
            {"scale": 1.0, "bias": 0.0})
        exe.run(main, feed={}, fetch_list=["out"], scope=scope)
        assert len(exe._pass_cache) == n_entries + 1

    def test_flag_gates_pipeline_and_rekeys_compile_cache(self):
        from paddle_tpu.framework import flags as fl

        assert ("fuse_passes", True) in fl.lowering_key()
        main = self._two_op_program()
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(main, feed={}, fetch_list=["out"], scope=scope)
        n_compiled = len(exe._cache)
        pt.set_flags({"FLAGS_fuse_passes": False})
        try:
            out = exe.run(main, feed={}, fetch_list=["out"], scope=scope)
            # flag flip = new compile entry, not a stale cache hit
            assert len(exe._cache) == n_compiled + 1
            np.testing.assert_array_equal(np.asarray(out[0]), [2.0, 2.0])
        finally:
            pt.set_flags({"FLAGS_fuse_passes": True})

    def test_close_clears_all_caches(self):
        main = self._two_op_program()
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(main, feed={}, fetch_list=["out"], scope=scope,
                use_prune=True)
        assert exe._cache and exe._analysis_cache and exe._prune_cache \
            and exe._pass_cache
        exe.close()
        assert not exe._cache and not exe._analysis_cache \
            and not exe._prune_cache and not exe._pass_cache

    def test_analysis_and_prune_cache_hit_stats(self):
        main = self._two_op_program()
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(main, feed={}, fetch_list=["out"], scope=scope,
                use_prune=True)
        a0 = stat_get("executor_analysis_cache_hit")
        p0 = stat_get("executor_prune_cache_hit")
        exe.run(main, feed={}, fetch_list=["out"], scope=scope,
                use_prune=True)
        assert stat_get("executor_analysis_cache_hit") == a0 + 1
        assert stat_get("executor_prune_cache_hit") == p0 + 1

    def test_strategy_bucket_cap_rejects_silent_truncation(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy

        s = DistributedStrategy()
        s.fuse_grad_size_in_MB = 64
        assert s.fuse_grad_size_in_MB == 64
        for bad in (0.5, 0, -4):
            with pytest.raises(ValueError):
                s.fuse_grad_size_in_MB = bad

    @staticmethod
    def _two_op_program():
        main = Program()
        block = main.global_block
        for n in ("a", "out"):
            block.create_var(name=n, shape=[2], dtype="float32")
        block.append_op("fill_constant", {}, {"Out": ["a"]},
                        {"shape": [2], "dtype": "float32", "value": 1.0})
        block.append_op("scale", {"X": ["a"]}, {"Out": ["out"]},
                        {"scale": 2.0, "bias": 0.0})
        return main
