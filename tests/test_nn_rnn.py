"""paddle.nn.LSTM/GRU/SimpleRNN layer classes vs numpy oracles.

Reference parity: python/paddle/nn/layer/rnn.py (RNNBase cudnn path
emitting the `rnn` op with the flat WeightList layout).
"""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.dygraph.tensor import Tensor


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def test_lstm_layer_matches_numpy():
    B, T, I, H = 2, 5, 3, 4
    rs = np.random.RandomState(0)
    lstm = nn.LSTM(I, H)
    x = rs.randn(B, T, I).astype("f4")

    out, (h_n, c_n) = lstm(Tensor(x))
    assert out.shape == [B, T, H]
    assert h_n.shape == [1, B, H] and c_n.shape == [1, B, H]

    w_ih = np.asarray(lstm._weight_list[0].numpy())
    w_hh = np.asarray(lstm._weight_list[1].numpy())
    b_ih = np.asarray(lstm._weight_list[2].numpy())
    b_hh = np.asarray(lstm._weight_list[3].numpy())
    h = np.zeros((B, H), "f4")
    c = np.zeros((B, H), "f4")
    outs = []
    for t in range(T):
        g = x[:, t] @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        i, f, gg, o = np.split(g, 4, -1)
        c = _sigmoid(f) * c + _sigmoid(i) * np.tanh(gg)
        h = _sigmoid(o) * np.tanh(c)
        outs.append(h)
    want = np.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(out.numpy()), want, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_n.numpy())[0], h, rtol=1e-4,
                               atol=1e-5)


def test_gru_bidirectional_shapes_and_grad():
    B, T, I, H = 2, 4, 3, 5
    rs = np.random.RandomState(1)
    gru = nn.GRU(I, H, num_layers=2, direction="bidirectional")
    x = Tensor(rs.randn(B, T, I).astype("f4"), stop_gradient=False)
    out, h_n = gru(x)
    assert out.shape == [B, T, 2 * H]
    assert h_n.shape == [4, B, H]  # num_layers * 2 directions
    loss = pt.tensor.math.sum(out * out)
    loss.backward()
    g = gru._weight_list[0].grad
    assert g is not None and np.isfinite(np.asarray(g.numpy())).all()


def test_simple_rnn_trains():
    B, T, I, H = 4, 6, 3, 8
    rs = np.random.RandomState(2)
    net = nn.SimpleRNN(I, H)
    head = nn.Linear(H, 1)
    x = Tensor(rs.randn(B, T, I).astype("f4"))
    y = Tensor(rs.randn(B, 1).astype("f4"))
    losses = []
    for _ in range(10):
        out, _ = net(x)
        last = out[:, -1]
        pred = head(last)
        diff = pred - y
        loss = pt.tensor.math.mean(diff * diff)
        losses.append(float(np.asarray(loss.numpy()).ravel()[0]))
        loss.backward()
        for p in list(net.parameters()) + list(head.parameters()):
            if p.grad is not None:
                p._set_raw(p._value - 0.05 * p.grad._value)
                p.grad = None
    assert losses[-1] < losses[0], losses
