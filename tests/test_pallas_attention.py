"""Custom Pallas flash-attention kernel: numpy/jnp-oracle parity in
interpret mode, bias streaming forms, causal masking, gradients, and
flag-controlled engagement through the fused_multihead_attention op.

Parity model: reference operators/fused/multihead_matmul_op.cu (the
scores->mask->softmax->context fusion); oracle is the plain composition
(ops/fused.py _plain_attention).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops import fused as fused_mod
from paddle_tpu.ops.fused import _plain_attention
from paddle_tpu.ops.pallas_attention import flash_attention_bias


def _qkv(rs, B=2, H=2, S=256, D=64):
    return (jnp.asarray(rs.randn(B, H, S, D).astype("f4")),
            jnp.asarray(rs.randn(B, H, S, D).astype("f4")),
            jnp.asarray(rs.randn(B, H, S, D).astype("f4")))


def _key_mask(rs, B=2, S=256):
    keep = rs.rand(B, 1, 1, S) > 0.2
    return jnp.asarray(np.where(keep, 0.0, -1e9).astype("f4"))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("bias_kind", ["none", "key", "full"])
def test_forward_parity(causal, bias_kind):
    rs = np.random.RandomState(0)
    q, k, v = _qkv(rs)
    if bias_kind == "none":
        bias = None
    elif bias_kind == "key":
        bias = _key_mask(rs)
    else:
        bias = jnp.asarray(rs.randn(2, 2, 256, 256).astype("f4"))
    ref = _plain_attention(q, k, v, bias, 0.125, causal=causal)
    got = flash_attention_bias(q, k, v, bias, sm_scale=0.125,
                               causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_gradients_match_plain_path():
    rs = np.random.RandomState(1)
    q, k, v = _qkv(rs)
    mask = _key_mask(rs)

    def loss_ref(q, k, v):
        return jnp.sum(_plain_attention(q, k, v, mask, 0.125) ** 2)

    def loss_got(q, k, v):
        return jnp.sum(flash_attention_bias(
            q, k, v, mask, sm_scale=0.125, interpret=True) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(loss_got, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gr, gg, "qkv"):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=5e-4, rtol=1e-3, err_msg=n)


@pytest.mark.parametrize("bias_shape", [(2, 1, 1, 256), (1, 1, 256, 256),
                                        (2, 2, 256, 256)])
def test_bias_gradient_matches_plain_path(bias_shape):
    """A LEARNABLE additive bias must receive its true gradient from the
    kernel path (a silent zero cotangent would freeze e.g. a relative-
    position bias whenever flash engages)."""
    rs = np.random.RandomState(4)
    q, k, v = _qkv(rs)
    bias = jnp.asarray(rs.randn(*bias_shape).astype("f4"))

    def loss_ref(b):
        return jnp.sum(_plain_attention(q, k, v, b, 0.125) ** 2)

    def loss_got(b):
        return jnp.sum(flash_attention_bias(
            q, k, v, b, sm_scale=0.125, interpret=True) ** 2)

    gr = jax.grad(loss_ref)(bias)
    gg = jax.grad(loss_got)(bias)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(gr),
                               atol=5e-4, rtol=1e-3)


def test_unaligned_shapes_are_loud():
    rs = np.random.RandomState(2)
    q, k, v = _qkv(rs, S=200)
    with pytest.raises(ValueError, match="multiples"):
        flash_attention_bias(q, k, v, interpret=True)


def test_fused_op_engages_kernel_under_always_flag():
    """FLAGS_flash_attention=always routes the fused op through the
    pallas kernel (interpret off-TPU) and matches the plain lowering."""
    from paddle_tpu import layers
    import paddle_tpu as pt
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.framework.program import Program, program_guard

    rs = np.random.RandomState(3)
    B, S, H, D = 2, 128, 2, 64
    qkv = {n: rs.randn(B, S, H * D).astype("f4") for n in "qkv"}
    mask = np.where(rs.rand(B, 1, 1, S) > 0.2, 0.0, -1e9).astype("f4")

    def run():
        main, startup = Program(), Program()
        with program_guard(main, startup):
            qv = layers.data("q", [S, H * D])
            kv = layers.data("k", [S, H * D])
            vv = layers.data("v", [S, H * D])
            bv = layers.data("bias", [1, 1, S])
            out = main.global_block.create_var(
                name="mha_out", shape=[-1, S, H * D], dtype="float32")
            main.global_block.append_op(
                "fused_multihead_attention",
                {"Q": [qv.name], "K": [kv.name], "V": [vv.name],
                 "BiasQK": [bv.name]},
                {"Out": [out.name]}, {"head_number": H})
        exe = pt.Executor(pt.CPUPlace())
        return np.asarray(exe.run(
            main, feed={"q": qkv["q"], "k": qkv["k"], "v": qkv["v"],
                        "bias": mask},
            fetch_list=[out])[0])

    plain = run()
    fused_mod._FORCE_INTERPRET = True
    set_flags({"FLAGS_flash_attention": "always"})
    try:
        flash = run()
    finally:
        fused_mod._FORCE_INTERPRET = False
        set_flags({"FLAGS_flash_attention": "auto"})
    np.testing.assert_allclose(flash, plain, atol=2e-5, rtol=1e-4)


def test_flag_flip_takes_effect_on_same_executor():
    """FLAGS_flash_attention keys the executor compile cache: flipping
    it between runs of ONE program on ONE executor must re-lower (a
    stale cached lowering would silently ignore the flag)."""
    from paddle_tpu import layers
    import paddle_tpu as pt
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.monitor import stat_get, stat_reset

    rs = np.random.RandomState(5)
    B, S, H, D = 2, 128, 2, 64
    main, startup = Program(), Program()
    with program_guard(main, startup):
        qv = layers.data("q", [S, H * D])
        out = main.global_block.create_var(
            name="mha_out2", shape=[-1, S, H * D], dtype="float32")
        main.global_block.append_op(
            "fused_multihead_attention",
            {"Q": [qv.name], "K": [qv.name], "V": [qv.name]},
            {"Out": [out.name]}, {"head_number": H})
    exe = pt.Executor(pt.CPUPlace())
    feed = {"q": rs.randn(B, S, H * D).astype("f4")}

    stat_reset("flash_attention_engaged")
    plain = np.asarray(exe.run(main, feed=feed, fetch_list=[out])[0])
    assert stat_get("flash_attention_engaged") == 0
    fused_mod._FORCE_INTERPRET = True
    set_flags({"FLAGS_flash_attention": "always"})
    try:
        flash = np.asarray(exe.run(main, feed=feed,
                                   fetch_list=[out])[0])
        assert stat_get("flash_attention_engaged") >= 1, \
            "flag flip ignored: stale compile-cache entry reused"
    finally:
        fused_mod._FORCE_INTERPRET = False
        set_flags({"FLAGS_flash_attention": "auto"})
    np.testing.assert_allclose(flash, plain, atol=2e-5, rtol=1e-4)


def test_never_flag_forces_plain_path(monkeypatch):
    """FLAGS_flash_attention=never keeps flash out even at huge scores
    (no kernel import happens)."""
    from paddle_tpu.framework.flags import set_flags

    set_flags({"FLAGS_flash_attention": "never"})
    try:
        assert not fused_mod._flash_engaged(64, 16, 4096, 4096, 128)
    finally:
        set_flags({"FLAGS_flash_attention": "auto"})
    # auto at the same (huge) shape engages on TPU
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert fused_mod._flash_engaged(64, 16, 4096, 4096, 128)
