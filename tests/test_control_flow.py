"""Control flow: while / While / cond lowering to lax.while_loop / lax.cond.

Parity model: reference operators/controlflow/ (while_op.cc,
conditional_block_op.cc) + layers/control_flow.py (While:1020,
while_loop:1035, cond:2333); unittests test_while_op.py / test_cond.py.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.optimizer import MomentumOptimizer


def _run(main, startup, feed, fetch):
    sc = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=sc)
    return exe.run(main, feed=feed, fetch_list=fetch, scope=sc)


class TestWhileLoop:
    def test_sum_to_n(self):
        main, startup = Program(), Program()
        with program_guard(main, startup):
            i = layers.fill_constant([1], "int64", 0)
            acc = layers.fill_constant([1], "int64", 0)
            limit = layers.fill_constant([1], "int64", 10)

            def cond(i, acc):
                return layers.less_than(i, limit)

            def body(i, acc):
                acc = layers.elementwise_add(acc, i)
                i = layers.increment(i)
                return i, acc

            i, acc = layers.while_loop(cond, body, [i, acc])
        out = _run(main, startup, {}, [acc, i])
        assert int(np.asarray(out[0]).item()) == sum(range(10))
        assert int(np.asarray(out[1]).item()) == 10

    def test_tensor_carry(self):
        """Matrix power by repeated multiply — tensor-valued carry."""
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", [2, 2], append_batch_size=False)
            i = layers.fill_constant([1], "int64", 0)
            n = layers.fill_constant([1], "int64", 3)
            y = layers.fill_constant([2, 2], "float32", 0.0)
            y = layers.elementwise_add(y, x)  # y = x

            def cond(i, y):
                return layers.less_than(i, n)

            def body(i, y):
                y = layers.matmul(y, x)
                i = layers.increment(i)
                return i, y

            i, y = layers.while_loop(cond, body, [i, y])
        A = np.array([[1.0, 1.0], [0.0, 1.0]], "f4")
        out = _run(main, startup, {"x": A}, [y])
        np.testing.assert_allclose(np.asarray(out[0]),
                                   np.linalg.matrix_power(A, 4), rtol=1e-5)

    def test_shape_change_rejected(self):
        main, startup = Program(), Program()
        with program_guard(main, startup):
            i = layers.fill_constant([1], "int64", 0)
            n = layers.fill_constant([1], "int64", 3)
            y = layers.fill_constant([2], "float32", 1.0)

            def cond(i, y):
                return layers.less_than(i, n)

            def body(i, y):
                y = layers.concat([y, y], axis=0)  # shape grows: illegal
                return layers.increment(i), y

            layers.while_loop(cond, body, [i, y])
        with pytest.raises(Exception, match="loop-invariant|shape"):
            _run(main, startup, {}, [])


class TestWhileContextManager:
    def test_v18_style_loop(self):
        main, startup = Program(), Program()
        with program_guard(main, startup):
            i = layers.fill_constant([1], "int64", 0)
            ten = layers.fill_constant([1], "int64", 10)
            acc = layers.fill_constant([1], "float32", 0.0)
            c = layers.less_than(i, ten)
            w = layers.While(c)
            with w.block():
                layers.assign(
                    layers.elementwise_add(acc, layers.fill_constant(
                        [1], "float32", 2.0)), acc)
                layers.assign(layers.increment(i), i)
                layers.assign(layers.less_than(i, ten), c)
        out = _run(main, startup, {}, [acc])
        assert float(np.asarray(out[0]).item()) == 20.0


class TestCond:
    def test_both_branches(self):
        for flag, expect in ((1.0, 5.0), (0.0, -5.0)):
            main, startup = Program(), Program()
            with program_guard(main, startup):
                x = layers.data("x", [1])
                pred = layers.greater_than(
                    x, layers.fill_constant([1], "float32", 0.5))
                out = layers.cond(
                    pred,
                    lambda: layers.fill_constant([1], "float32", 5.0),
                    lambda: layers.fill_constant([1], "float32", -5.0))
            got = _run(main, startup,
                       {"x": np.array([[flag]], "f4")}, [out])
            assert float(np.asarray(got[0]).item()) == expect

    def test_branch_structure_mismatch_rejected(self):
        main, startup = Program(), Program()
        with program_guard(main, startup):
            pred_v = layers.fill_constant([1], "bool", 1)
            with pytest.raises(ValueError, match="different numbers"):
                layers.cond(
                    pred_v,
                    lambda: (layers.zeros([1]), layers.zeros([1])),
                    lambda: layers.zeros([1]))

    def test_cond_in_training_grads_flow(self):
        """cond train e2e: params captured inside a branch must receive
        gradients (generic vjp over the re-emitted lax.cond)."""
        from paddle_tpu.framework import unique_name
        from paddle_tpu.initializer import ConstantInitializer
        from paddle_tpu.param_attr import ParamAttr

        rng = np.random.RandomState(0)
        X = rng.randn(8, 4).astype("f4")
        Y = (X.sum(1, keepdims=True) * 0.5).astype("f4")

        main, startup = Program(), Program()
        main.random_seed = 1
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [4])
            y = layers.data("y", [1])
            flag = layers.data("flag", [1])
            h = layers.fc(x, 8, act="relu", param_attr=ParamAttr(
                initializer=ConstantInitializer(0.2)), bias_attr=False)
            pred_b = layers.greater_than(
                layers.reduce_sum(flag),
                layers.fill_constant([1], "float32", 0.0))
            out = layers.cond(
                pred_b,
                lambda: layers.fc(h, 1, param_attr=ParamAttr(
                    initializer=ConstantInitializer(0.1)), bias_attr=False),
                lambda: layers.reduce_sum(h, dim=1, keep_dim=True))
            loss = layers.mean(layers.square_error_cost(out, y))
            MomentumOptimizer(0.1, 0.9).minimize(loss)

        sc = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=sc)
        flag_on = np.ones((1, 1), "f4")
        losses = [
            float(np.asarray(exe.run(
                main, feed={"x": X, "y": Y, "flag": flag_on},
                fetch_list=[loss], scope=sc)[0]).item())
            for _ in range(10)
        ]
        assert losses[-1] < losses[0] * 0.5, losses
        # the branch-captured fc param must have moved
        w = np.asarray(sc.get_var("fc_1.w_0"))
        assert not np.allclose(w, 0.1), "no gradient reached branch param"
