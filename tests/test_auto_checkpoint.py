"""Auto-checkpoint + fleet utils (fs, http KV).

Reference parity: fluid/incubate/checkpoint/auto_checkpoint.py (hooked
into Executor.run at executor.py:1200), fleet/utils/fs.py, and the KV
http_server behind the gloo rendezvous.
"""
import json
import os
import urllib.request

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.incubate.checkpoint import auto_checkpoint as acp


def _build():
    from paddle_tpu.framework import unique_name
    from paddle_tpu.optimizer import MomentumOptimizer

    main, startup = Program(), Program()
    main.random_seed = 1
    # fresh name generator: separate processes get identical var names;
    # this test simulates the second process inside one interpreter
    with unique_name.guard():
        with program_guard(main, startup):
            x = layers.data("x", [4])
            y = layers.data("y", [1])
            pred = layers.fc(x, 1, bias_attr=False)
            loss = layers.mean(layers.square_error_cost(pred, y))
            MomentumOptimizer(0.05, 0.9).minimize(loss)
    return main, startup, loss


def test_auto_checkpoint_saves_and_resumes(tmp_path):
    rs = np.random.RandomState(0)
    feed = {"x": rs.randn(8, 4).astype("f4"), "y": rs.randn(8, 1).astype("f4")}

    # run A: 5 steps with every-2-step checkpointing
    acp.configure(str(tmp_path), every_n_steps=2)
    try:
        main, startup, loss = _build()
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.framework.Scope()
        exe.run(startup, scope=scope)
        losses_a = [float(np.asarray(
            exe.run(main, feed=feed, fetch_list=[loss], scope=scope)[0]
        ).ravel()[0]) for _ in range(5)]
        acp.wait()  # saves are async (paddle_tpu.ckpt manager)
        # manager layout: committed step dirs with a hashed manifest
        assert os.path.isdir(tmp_path / "auto_ckpt" / "step_4")
        manifest = json.load(
            open(tmp_path / "auto_ckpt" / "step_4" / "MANIFEST.json"))
        assert manifest["step"] == 4  # last even step
    finally:
        acp.disable()

    # run B (fresh "process"): resume from the checkpoint and continue;
    # steps 5.. must match a never-interrupted run
    acp.configure(str(tmp_path), every_n_steps=2)
    try:
        main2, startup2, loss2 = _build()
        exe2 = pt.Executor(pt.CPUPlace())
        scope2 = pt.framework.Scope()
        exe2.run(startup2, scope=scope2)
        meta = acp.load_checkpoint(exe2, main2, scope2)
        assert meta is not None and meta["step"] == 4
        resumed = [float(np.asarray(
            exe2.run(main2, feed=feed, fetch_list=[loss2], scope=scope2)[0]
        ).ravel()[0]) for _ in range(2)]
    finally:
        acp.disable()

    # oracle: uninterrupted 7-step run; its steps 4..5 are what the
    # resumed run (from the step-4 snapshot) must reproduce
    main3, startup3, loss3 = _build()
    exe3 = pt.Executor(pt.CPUPlace())
    scope3 = pt.framework.Scope()
    exe3.run(startup3, scope=scope3)
    full = [float(np.asarray(
        exe3.run(main3, feed=feed, fetch_list=[loss3], scope=scope3)[0]
    ).ravel()[0]) for _ in range(7)]
    np.testing.assert_allclose(resumed, full[4:6], rtol=1e-5)


def test_train_epoch_range_skips_finished_epochs(tmp_path):
    acp.configure(str(tmp_path), every_n_steps=1000)
    try:
        seen = []
        for e in acp.train_epoch_range("job", 4):
            seen.append(e)
            if e == 1:
                break  # "crash" after finishing epochs 0..1? (epoch 1 not marked)
        assert seen == [0, 1]
        # epoch 0 completed, epoch 1 interrupted before completion
        resumed = list(acp.train_epoch_range("job", 4))
        assert resumed == [1, 2, 3]
    finally:
        acp.disable()


def test_local_fs_roundtrip(tmp_path):
    from paddle_tpu.distributed.fleet.utils import LocalFS

    fs = LocalFS()
    d = str(tmp_path / "a" / "b")
    fs.mkdirs(d)
    assert fs.is_dir(d) and fs.is_exist(d)
    f = os.path.join(d, "x.txt")
    fs.touch(f)
    assert fs.is_file(f)
    dirs, files = fs.ls_dir(str(tmp_path / "a"))
    assert dirs == ["b"] and files == []
    fs.mv(f, os.path.join(d, "y.txt"))
    assert not fs.is_exist(f)
    fs.delete(d)
    assert not fs.is_exist(d)


def test_kv_http_server_roundtrip():
    from paddle_tpu.distributed.fleet.utils import KVServer

    srv = KVServer(0)  # ephemeral port
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        req = urllib.request.Request(base + "/rank/0", data=b"host:1234",
                                     method="PUT")
        assert urllib.request.urlopen(req).status == 200
        got = urllib.request.urlopen(base + "/rank/0").read()
        assert got == b"host:1234"
        try:
            urllib.request.urlopen(base + "/rank/1")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        req = urllib.request.Request(base + "/rank/0", method="DELETE")
        assert urllib.request.urlopen(req).status == 200
    finally:
        srv.stop()


def test_auto_checkpoint_manager_is_rank_local(tmp_path):
    """Only rank 0 saves (the on_executor_run gate), so the manager must
    be pinned to rank=0/world_size=1 — an inferred world_size from
    jax.process_count() on a multi-process run would park the writer on
    sync_global_devices barriers no other rank calls and demand
    shard_r1.. files nobody writes."""
    cfg = acp.configure(str(tmp_path))
    try:
        m = acp._manager(cfg)
        assert m.rank == 0 and m.world_size == 1
        # pinned explicitly, not inferred from the jax backend
        assert m._rank == 0 and m._world == 1
    finally:
        acp.disable()


def test_disable_detaches_even_when_drain_fails(tmp_path):
    """disable() must deactivate auto-checkpointing BEFORE draining: if
    close() re-raises a failed background save, a config left active
    with a closed manager would crash every later Executor.run."""
    import pytest

    from paddle_tpu.ckpt import CheckpointError

    cfg = acp.configure(str(tmp_path))

    class _FailingManager:
        def close(self):
            raise CheckpointError("background save failed")

    cfg.manager = _FailingManager()
    with pytest.raises(CheckpointError):
        acp.disable()
    assert acp._cfg is None  # detached despite the raise


def test_is_rank0_falls_back_to_jax_process_index(monkeypatch):
    """Pure jax multi-process runs never set PADDLE_TRAINER_ID; every
    process passing the rank-0 gate would race all of them on the same
    checkpoint directory."""
    import jax

    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    assert not acp._is_rank0()
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    assert acp._is_rank0()  # explicit env wins over the jax fallback
    monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
    assert not acp._is_rank0()
