"""Tensor-parallel auto-sharding (ShardingPropagationPass + GSPMD
executor path + TensorParallelMetaOptimizer).

Oracles, per the reference's dist-test discipline (test_dist_base.py):
the tensor-parallel run's per-step losses must MATCH a small replicated
oracle within 1e-4 rel on the 8-virtual-device CPU mesh, and the
sharding must be REAL — params and their optimizer slots physically
hold 1/mp of their bytes per chip, grad allreduces move shard-sized
payloads over the dp axis only, and FuseAllReducePass never mixes
sharding specs inside one bucket.
"""
import re

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import passes as passes_mod
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.initializer import ConstantInitializer, NormalInitializer
from paddle_tpu.optimizer import MomentumOptimizer
from paddle_tpu.param_attr import ParamAttr

MLP_RULES = [
    (r"blk_ffn1\.w_\d+$", "None,mp"),
    (r"blk_ffn1\.b_\d+$", "mp"),
    (r"blk_ffn2\.w_\d+$", "mp,None"),
]

# "one simulated chip's budget": the replicated MLP's weights exceed
# it, the per-chip shard stays under it — the assertion that makes
# "model too large for one chip" concrete on the CPU mesh
CHIP_BUDGET_BYTES = 600_000


def _build_mlp(use_tp, rules=MLP_RULES, hidden=256, extra_strategy=None,
               dropout=0.0, recompute_ckpt=False):
    from paddle_tpu.distributed import fleet

    main, startup = Program(), Program()
    main.random_seed = 1
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1])
        h = layers.fc(x, hidden, act="relu", name="blk_ffn1",
                      param_attr=ParamAttr(
                          initializer=NormalInitializer(0.0, 0.05)))
        if dropout:
            h = layers.dropout(h, dropout, name="blk_drop")
        h2 = layers.fc(h, hidden, act="relu", name="mid",
                       param_attr=ParamAttr(
                           initializer=ConstantInitializer(0.02)),
                       bias_attr=False)
        pred = layers.fc(h2, 1, name="blk_ffn2", param_attr=ParamAttr(
            initializer=ConstantInitializer(0.1)), bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = MomentumOptimizer(0.05, 0.9)
        if use_tp:
            strat = fleet.DistributedStrategy()
            strat.tensor_parallel = True
            if rules is not None:
                strat.tensor_parallel_configs = {"partition_rules": rules}
            for k, v in (extra_strategy or {}).items():
                setattr(strat, k, v)
            if recompute_ckpt:
                strat.recompute = True
                strat.recompute_configs = {"checkpoints": [h2.name]}
            if extra_strategy and extra_strategy.get("amp"):
                strat.amp_configs = {"use_bf16": True}
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(opt)
            fleet.minimize(loss)
        else:
            opt.minimize(loss)
    return main, startup, loss


def _data(n=16):
    rs = np.random.RandomState(0)
    X = rs.randn(n, 8).astype("float32")
    Y = (X.sum(axis=1, keepdims=True) * 0.3).astype("float32")
    return X, Y


def _train(main, startup, loss, X, Y, mesh, steps=5):
    scope = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup, scope=scope)
    losses = [float(np.asarray(exe.run(
        main, feed={"x": X, "y": Y}, fetch_list=[loss],
        scope=scope)[0]).item()) for _ in range(steps)]
    return losses, scope, exe


class TestShardingPropagationPass:
    def test_rule_match_specs_and_slot_inheritance(self, mesh_dp_mp):
        main, _, loss = _build_mlp(True)
        out = passes_mod.apply_passes(
            main, fetch_names=(loss.name,), feed_names=("x", "y"),
            mesh=mesh_dp_mp)
        plan = out._tp_plan
        assert plan is not None and plan.mp_degree == 4
        assert plan.spec_tuple("blk_ffn1.w_0") == (None, "mp")
        assert plan.spec_tuple("blk_ffn1.b_0") == ("mp",)
        assert plan.spec_tuple("blk_ffn2.w_0") == ("mp", None)
        # optimizer slots inherit their param's spec automatically
        assert plan.spec_tuple("blk_ffn1.w_0_velocity_0") == (None, "mp")
        assert plan.spec_tuple("blk_ffn1.b_0_velocity_0") == ("mp",)
        assert plan.spec_tuple("blk_ffn2.w_0_velocity_0") == ("mp", None)
        # unmatched params stay replicated
        assert plan.spec_tuple("mid.w_0") == ()

    def test_non_divisible_param_falls_back_replicated(self, mesh_dp_mp):
        # hidden=254 is not divisible by mp=4: the rule matches but the
        # pass must fall back to replicated, never shard unevenly
        main, _, loss = _build_mlp(True, hidden=252 + 2)
        out = passes_mod.apply_passes(
            main, fetch_names=(loss.name,), feed_names=("x", "y"),
            mesh=mesh_dp_mp)
        plan = out._tp_plan
        assert plan.spec_tuple("blk_ffn1.w_0") == ()
        assert plan.n_fallback >= 1

    def test_constraint_anchors_stamped_on_matmuls(self, mesh_dp_mp):
        main, _, loss = _build_mlp(True)
        out = passes_mod.apply_passes(
            main, fetch_names=(loss.name,), feed_names=("x", "y"),
            mesh=mesh_dp_mp)
        anchored = [op for op in out.global_block.ops
                    if op.attr(passes_mod.TP_CONSTRAINT_ATTR)]
        assert anchored, "no sharding anchors stamped"
        # the column-parallel fc's output must be anchored mp-sharded
        col = [ent for op in anchored
               for ent in op.attr(passes_mod.TP_CONSTRAINT_ATTR)
               if "mp" in ent.split("\t")[1]]
        assert col, "no mp-sharded activation anchor found"

    def test_grad_collectives_stamped_with_spec(self, mesh_dp_mp):
        main, _, loss = _build_mlp(True)
        out = passes_mod.apply_passes(
            main, fetch_names=(loss.name,), feed_names=("x", "y"),
            mesh=mesh_dp_mp)
        plan = out._tp_plan
        # dp=2 -> the GraphExecution transpile inserted per-grad
        # allreduces; tp-sharded grads carry the shard-bytes accounting
        g = "blk_ffn1.w_0@GRAD"
        assert g in plan.grad_reduce
        rec = plan.grad_reduce[g]
        assert rec["axes"] == ("dp",)
        assert rec["bytes"] == 8 * 256 * 4 // 4  # full bytes / mp

    def test_no_tp_marks_means_no_plan(self, mesh_dp_mp):
        main, _, loss = _build_mlp(False)
        out = passes_mod.apply_passes(
            main, fetch_names=(loss.name,), feed_names=("x", "y"),
            mesh=mesh_dp_mp)
        assert getattr(out, "_tp_plan", None) is None


class TestTensorParallelTraining:
    def test_loss_parity_and_state_sharded(self, mesh_dp_mp):
        """Acceptance: an MLP whose replicated weights exceed one
        simulated chip's budget trains on the dp×mp mesh with loss
        parity (<=1e-4 rel) vs the replicated oracle, and optimizer
        slots verifiably carry their param's sharding spec."""
        from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh

        rules = MLP_RULES + [(r"mid\.w_\d+$", "mp,None")]
        X, Y = _data(n=32)
        reset_mesh()  # oracle runs without any mesh
        base, _, _ = _train(*_build_mlp(False, hidden=512), X, Y, None)

        set_mesh(mesh_dp_mp)
        tp, scope, _ = _train(
            *_build_mlp(True, rules=rules, hidden=512), X, Y, mesh_dp_mp)
        assert np.isfinite(tp).all(), tp
        np.testing.assert_allclose(tp, base, rtol=1e-4, atol=1e-6)

        w = scope.get_var("blk_ffn1.w_0")
        v = scope.get_var("blk_ffn1.w_0_velocity_0")
        assert tuple(w.sharding.spec) == (None, "mp"), w.sharding
        # slots carry their param's spec on the LIVE arrays, not just
        # the plan
        assert tuple(v.sharding.spec) == (None, "mp"), v.sharding
        assert tuple(scope.get_var("mid.w_0").sharding.spec) == \
            ("mp", None)

        # "exceeds one chip's budget": the replicated model's param +
        # slot bytes blow the budget; the per-chip sharded footprint
        # fits under it — the model is only trainable BECAUSE of tp
        names = ["blk_ffn1.w_0", "blk_ffn1.b_0", "mid.w_0",
                 "blk_ffn2.w_0"]
        names += [n + "_velocity_0" for n in names]
        full = sum(int(np.prod(scope.get_var(n).shape)) * 4
                   for n in names)
        per_chip = sum(
            int(np.prod(scope.get_var(n).addressable_shards[0].data.shape))
            * 4 for n in names)
        assert full > CHIP_BUDGET_BYTES, full
        assert per_chip <= CHIP_BUDGET_BYTES, per_chip

    def test_parity_with_dropout(self, mesh_dp_mp):
        """Dropout masks must be IDENTICAL between the replicated and
        tp runs (partitionable threefry: bits are sharding-invariant)."""
        from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh

        X, Y = _data(n=32)
        reset_mesh()
        base, _, _ = _train(*_build_mlp(False, dropout=0.3), X, Y, None)
        set_mesh(mesh_dp_mp)
        tp, _, _ = _train(*_build_mlp(True, dropout=0.3), X, Y, mesh_dp_mp)
        np.testing.assert_allclose(tp, base, rtol=1e-4, atol=1e-6)

    def test_mp_only_mesh(self, mesh_mp_only):
        """Pure tensor parallelism (dp=1): no grad allreduces at all,
        parity still holds."""
        from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh

        X, Y = _data()
        reset_mesh()
        base, _, _ = _train(*_build_mlp(False), X, Y, None)
        set_mesh(mesh_mp_only)
        main, startup, loss = _build_mlp(True)
        assert not any(op.type == "c_allreduce_sum"
                       for op in main.global_block.ops)
        tp, scope, _ = _train(main, startup, loss, X, Y, mesh_mp_only)
        np.testing.assert_allclose(tp, base, rtol=1e-4, atol=1e-6)
        w = scope.get_var("blk_ffn1.w_0")
        assert w.addressable_shards[0].data.shape == (8, 256 // 8)

    def test_pure_mp_1d_mesh(self):
        """A 1D ('mp',)-only mesh (no 'dp' axis anywhere): specs and
        anchors must degrade 'dp' tokens to replicated instead of
        naming a mesh axis jax has never heard of (review regression)."""
        import jax

        from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh

        X, Y = _data()
        reset_mesh()
        base, _, _ = _train(*_build_mlp(False), X, Y, None)
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("mp",))
        set_mesh(mesh)
        try:
            tp, scope, _ = _train(*_build_mlp(True), X, Y, mesh)
            np.testing.assert_allclose(tp, base, rtol=1e-4, atol=1e-6)
            w = scope.get_var("blk_ffn1.w_0")
            assert tuple(w.sharding.spec) == (None, "mp")
        finally:
            reset_mesh()

    def test_run_steps_scan_path(self, mesh_dp_mp):
        """Multi-step on-device scan (run_steps) under the GSPMD path."""
        from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh

        X, Y = _data(n=16)
        reset_mesh()
        m0, s0, l0 = _build_mlp(False)
        sc0 = pt.framework.Scope()
        e0 = pt.Executor(pt.CPUPlace())
        e0.run(s0, scope=sc0)
        out0 = e0.run_steps(m0, feed={"x": X, "y": Y}, fetch_list=[l0],
                            scope=sc0, steps=4)
        base = np.asarray(out0[0]).ravel()

        set_mesh(mesh_dp_mp)
        m1, s1, l1 = _build_mlp(True)
        sc1 = pt.framework.Scope()
        e1 = pt.Executor(pt.CPUPlace(), mesh=mesh_dp_mp)
        e1.run(s1, scope=sc1)
        out1 = e1.run_steps(m1, feed={"x": X, "y": Y}, fetch_list=[l1],
                            scope=sc1, steps=4)
        np.testing.assert_allclose(np.asarray(out1[0]).ravel(), base,
                                   rtol=1e-4, atol=1e-6)

    def test_tp_program_without_mp_mesh_raises(self, mesh_dp_mp):
        """Two guard layers: minimize refuses a mesh without an 'mp'
        axis outright, and a tp-stamped program handed to an executor
        whose mesh lost the axis refuses at dispatch (the dp loss-grad
        scale was removed, so the shard_map path would be numerically
        wrong)."""
        import jax

        from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh

        # built under a valid dp×mp mesh...
        main, startup, loss = _build_mlp(True)
        X, Y = _data()
        # ...then dispatched on a dp-only mesh: executor-level guard
        reset_mesh()
        dp_mesh = jax.sharding.Mesh(np.array(jax.devices()), ("dp",))
        set_mesh(dp_mesh)
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=dp_mesh)
        exe.run(startup, scope=scope)
        with pytest.raises(ValueError, match="'mp' axis"):
            exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                    scope=scope)

        # minimize-level guard: a dp-only global mesh is refused early
        reset_mesh()
        from paddle_tpu.distributed.parallel_env import init_parallel_env

        init_parallel_env()  # 1D dp mesh
        with pytest.raises(ValueError, match="'mp'"):
            _build_mlp(True)
        reset_mesh()

    def test_ckpt_roundtrip_same_topology_bitwise(self, mesh_dp_mp,
                                                  tmp_path):
        """tp-sharded state saves through the ckpt manager and restores
        bitwise on the same topology (single-process: fully-addressable
        arrays snapshot as full host values — elastic by construction)."""
        from paddle_tpu.ckpt import CheckpointManager

        X, Y = _data()
        _, scope, exe = _train(*_build_mlp(True), X, Y, mesh_dp_mp,
                               steps=3)
        m = CheckpointManager(str(tmp_path), async_save=False)
        m.save(3, scope=scope)
        m.close()

        w_before = np.asarray(scope.get_var("blk_ffn1.w_0"))
        m2 = CheckpointManager(str(tmp_path), async_save=False)
        scope2 = pt.framework.Scope()
        meta = m2.restore(scope=scope2)
        m2.close()
        assert meta["step"] == 3
        np.testing.assert_array_equal(
            np.asarray(scope2.get_var("blk_ffn1.w_0")), w_before)


class TestCollectiveTelemetry:
    def test_grad_allreduce_dp_only_shard_bytes(self, mesh_dp_mp):
        """Acceptance: per-param grad allreduces for tp-sharded params
        run over the dp mesh axis only, asserted via the collective
        span/byte telemetry (tracer spans carry axes='dp' + SHARD
        bytes) and the StepTimer's static allreduce accounting."""
        from paddle_tpu import observe
        from paddle_tpu.distributed.parallel_env import set_mesh

        set_mesh(mesh_dp_mp)
        X, Y = _data()
        main, startup, loss = _build_mlp(True)
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh_dp_mp)
        exe.run(startup, scope=scope)
        observe.clear()
        observe.enable()
        try:
            exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                    scope=scope)
            exe.drain()
            spans = [s for s in observe.snapshot()
                     if s.name == "collective/c_allreduce_sum"]
        finally:
            observe.disable()
            observe.clear()
        assert spans, "no grad-allreduce spans traced"
        by_var = {(s.args or {}).get("var"): (s.args or {}) for s in spans}
        a = by_var.get("blk_ffn1.w_0@GRAD")
        assert a is not None
        assert a.get("axes") == "dp"
        assert a["bytes"] == 8 * 256 * 4 // 4  # mp-shard payload
        # replicated param's grad: full bytes, still dp-only by
        # construction of the 2D mesh collective lowering
        b = by_var.get("mid.w_0@GRAD")
        assert b is not None and b["bytes"] == 256 * 256 * 4

        # compiled-entry static accounting agrees (sum of per-grad
        # dp payloads, shard-sized for mp-sharded grads)
        entry = [e for e in exe._cache.values() if e.allreduce_bytes][-1]
        expected = (8 * 256 * 4 // 4          # blk_ffn1.w col-sharded
                    + 256 * 4 // 4            # blk_ffn1.b
                    + 256 * 256 * 4           # mid.w replicated
                    + 256 * 1 * 4 // 4)       # blk_ffn2.w row-sharded
        assert entry.allreduce_bytes == expected

    def test_fuse_bucket_never_mixes_specs(self):
        """Acceptance: FuseAllReducePass buckets never mix sharding
        specs — same dtype/ring grads with different __tp_spec__ stamps
        land in separate fused buffers."""
        from paddle_tpu.framework.program import Operator

        main = Program()
        block = main.global_block
        mark = {passes_mod.FUSED_ALLREDUCE_ATTR: True,
                passes_mod.FUSE_SIZE_ATTR: 32.0}
        specs = ["None,mp", "None,mp", "", "", "mp,None"]
        for i, spec in enumerate(specs):
            g = f"g{i}"
            block.create_var(name=g, shape=[4, 4], dtype="float32")
            attrs = dict(mark)
            if spec:
                attrs[passes_mod.TP_SPEC_ATTR] = spec
            block.append_op("c_allreduce_sum", {"X": [g]}, {"Out": [g]},
                            attrs)
        work = main.clone()
        passes_mod.FuseAllReducePass().apply(work, passes_mod.PassContext())
        fused = [op for op in work.global_block.ops
                 if op.type == "coalesce_tensor"]
        # g0+g1 fuse (same spec), g2+g3 fuse (unsharded), g4 stays alone
        assert len(fused) == 2
        members = sorted(tuple(op.inputs["Input"]) for op in fused)
        assert members == [("g0", "g1"), ("g2", "g3")]
        # the fused collective keeps its members' spec stamp
        fused_ar = [op for op in work.global_block.ops
                    if op.type == "c_allreduce_sum"
                    and op.inputs["X"][0].startswith("@FUSED_GRAD@")]
        stamped = {op.attr(passes_mod.TP_SPEC_ATTR) for op in fused_ar}
        assert "None,mp" in stamped

    def test_mfu_per_chip_flops_divided_by_mp(self, mesh_dp_mp):
        """Satellite: per-chip FLOPs under tp are program_flops /
        mp_degree, so MFU is not overstated by mp× on sharded runs."""
        from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh

        X, Y = _data()
        reset_mesh()
        m0, s0, l0 = _build_mlp(False)
        sc0 = pt.framework.Scope()
        e0 = pt.Executor(pt.CPUPlace())
        e0.run(s0, scope=sc0)
        e0.run(m0, feed={"x": X, "y": Y}, fetch_list=[l0], scope=sc0)
        e0.drain()
        plain = [e for e in e0._cache.values() if e.flops_per_step > 0]
        assert plain

        set_mesh(mesh_dp_mp)
        m1, s1, l1 = _build_mlp(True)
        sc1 = pt.framework.Scope()
        e1 = pt.Executor(pt.CPUPlace(), mesh=mesh_dp_mp)
        e1.run(s1, scope=sc1)
        e1.run(m1, feed={"x": X, "y": Y}, fetch_list=[l1], scope=sc1)
        e1.drain()
        tp = [e for e in e1._cache.values() if e.flops_per_step > 0]
        assert tp
        assert tp[-1].flops_per_step == pytest.approx(
            plain[-1].flops_per_step / 4, rel=1e-6)


class TestMetaOptimizerComposition:
    def test_full_chain_compiles_and_tracks_tp_only(self, mesh_dp_mp):
        """Satellite acceptance: tensor_parallel × fuse_all_reduce ×
        AMP(bf16) × recompute × ZeRO-1 all enabled on one program
        compiles and holds loss parity vs tp-only on the 8-device mesh
        (loose tolerance: bf16 AMP is in the chain)."""
        from paddle_tpu.distributed.parallel_env import set_mesh

        X, Y = _data(n=32)
        set_mesh(mesh_dp_mp)
        tp_only, _, _ = _train(*_build_mlp(True), X, Y, mesh_dp_mp,
                               steps=4)

        set_mesh(mesh_dp_mp)
        main, startup, loss = _build_mlp(
            True,
            extra_strategy={"amp": True, "fuse_all_reduce_ops": True,
                            "sharding": True},
            recompute_ckpt=True)
        # the chain really applied: ZeRO rewired optimizer ops and the
        # tp stamps are on them
        assert any(op.attr("__sharded_accumulators__") is not None
                   for op in main.global_block.ops)
        assert any(op.attr(passes_mod.TP_RULES_ATTR)
                   for op in main.global_block.ops)
        assert any(op.type == "cast" for op in main.global_block.ops)
        full, scope, _ = _train(main, startup, loss, X, Y, mesh_dp_mp,
                                steps=4)
        assert np.isfinite(full).all(), full
        np.testing.assert_allclose(full, tp_only, rtol=3e-2, atol=1e-3)
        # tp sharding survived the whole chain on the live state
        w = scope.get_var("blk_ffn1.w_0")
        assert tuple(w.sharding.spec) == (None, "mp")

    def test_tp_pipeline_composes_localsgd_still_rejected(self,
                                                          mesh_dp_mp):
        """tensor_parallel × pipeline now COMPOSES (the dp×mp×pp mesh;
        full numerics covered in tests/test_parallel_3d.py) — but a
        dp×mp mesh without a 'pp' axis is rejected loudly, and the
        localsgd combo keeps the pinned rejection."""
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.parallel_env import set_mesh

        set_mesh(mesh_dp_mp)  # has 'mp' but no 'pp'
        main, startup = Program(), Program()
        main.random_seed = 1
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [8])
            y = layers.data("y", [1])
            pred = layers.fc(x, 1, bias_attr=False)
            loss = layers.mean(layers.square_error_cost(pred, y))
            strat = fleet.DistributedStrategy()
            strat.tensor_parallel = True
            strat.pipeline = True
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
            with pytest.raises(ValueError, match="'pp'"):
                fleet.minimize(loss)

    def test_tp_rejects_localsgd_combo(self, mesh_dp_mp):
        from paddle_tpu.distributed import fleet

        main, startup = Program(), Program()
        main.random_seed = 1
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [8])
            y = layers.data("y", [1])
            pred = layers.fc(x, 1, bias_attr=False)
            loss = layers.mean(layers.square_error_cost(pred, y))
            strat = fleet.DistributedStrategy()
            strat.tensor_parallel = True
            strat.localsgd = True
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
            with pytest.raises(NotImplementedError,
                               match="does not compose with "
                                     "strategy.localsgd"):
                fleet.minimize(loss)

    def test_degree_mismatch_raises(self, mesh_dp_mp):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.parallel_env import set_mesh

        set_mesh(mesh_dp_mp)  # mp = 4
        main, startup = Program(), Program()
        main.random_seed = 1
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [8])
            y = layers.data("y", [1])
            pred = layers.fc(x, 8, name="blk_ffn2", bias_attr=False)
            loss = layers.mean(layers.square_error_cost(
                layers.fc(pred, 1, bias_attr=False), y))
            strat = fleet.DistributedStrategy()
            strat.tensor_parallel = True
            strat.tensor_parallel_configs = {"tensor_parallel_degree": 8}
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
            fleet.minimize(loss)
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh_dp_mp)
        exe.run(startup, scope=scope)
        X, Y = _data()
        with pytest.raises(ValueError, match="tensor_parallel_degree"):
            exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                    scope=scope)


class TestBertStyleTP:
    def test_bert_default_rules_parity_and_sharding(self, mesh_dp_mp):
        """BERT-style model under the DEFAULT Megatron rules: loss
        parity vs the replicated oracle, QKV/FFN weights and their Adam
        moments mp-sharded, vocab-parallel embedding."""
        import bench

        from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh

        reset_mesh()
        m0, s0, l0, feed = bench._small_bert(pt)
        sc0 = pt.framework.Scope()
        e0 = pt.Executor(pt.CPUPlace())
        e0.run(s0, scope=sc0)
        base = [float(np.asarray(e0.run(
            m0, feed=feed, fetch_list=[l0], scope=sc0)[0]).ravel()[0])
            for _ in range(3)]

        set_mesh(mesh_dp_mp)
        m1, s1, l1, feed1 = bench._small_bert(pt, use_fleet_tp=True)
        sc1 = pt.framework.Scope()
        e1 = pt.Executor(pt.CPUPlace(), mesh=mesh_dp_mp)
        e1.run(s1, scope=sc1)
        tp = [float(np.asarray(e1.run(
            m1, feed=feed1, fetch_list=[l1], scope=sc1)[0]).ravel()[0])
            for _ in range(3)]
        assert np.isfinite(tp).all(), tp
        np.testing.assert_allclose(tp, base, rtol=1e-4, atol=1e-6)

        for name, spec in (("enc_0_attn_q.w_0", (None, "mp")),
                           ("enc_0_ffn1.w_0", (None, "mp")),
                           ("enc_0_ffn2.w_0", ("mp", None)),
                           ("word_embedding", ("mp", None)),
                           ("enc_0_attn_q.w_0_moment1_0", (None, "mp"))):
            v = sc1.get_var(name)
            assert tuple(v.sharding.spec) == spec, (name, v.sharding)
