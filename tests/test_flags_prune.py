"""Tier-1 flags, the check_nan_inf per-op scan, and fetch-list pruning.

Reference parity: platform/flags.cc + paddle.set_flags,
FLAGS_check_nan_inf (operator.cc:1129, nan_inf_utils_detail.cc), and
Executor.run(use_prune) / framework/prune.h.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.program import Program, program_guard


def test_set_get_flags_roundtrip():
    assert pt.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] is False
    pt.set_flags({"FLAGS_check_nan_inf": True})
    try:
        assert pt.get_flags(["check_nan_inf"])["check_nan_inf"] is True
    finally:
        pt.set_flags({"FLAGS_check_nan_inf": False})
    with pytest.raises(KeyError):
        pt.set_flags({"FLAGS_no_such_flag": 1})


def test_check_nan_inf_names_the_bad_op():
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("x", [3])
        y = layers.log(x)  # log of a negative input -> NaN
        z = layers.scale(y, 2.0)
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    pt.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(RuntimeError, match="log"):
            exe.run(main, feed={"x": np.array([[-1.0, 2.0, 3.0]], "f4")},
                    fetch_list=[z], scope=scope)
        # clean inputs pass the scan
        out = exe.run(main, feed={"x": np.ones((1, 3), "f4")},
                      fetch_list=[z], scope=scope)
        assert np.isfinite(np.asarray(out[0])).all()
    finally:
        pt.set_flags({"FLAGS_check_nan_inf": False})


def test_check_nan_inf_all_pseudo_program():
    """nan-scan on a program whose compiled op list is empty (feed/fetch
    only) must not leak the sentinel fetch to callers."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("x", [3])
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    pt.set_flags({"FLAGS_check_nan_inf": True})
    try:
        out = exe.run(main, feed={"x": np.ones((2, 3), "f4")},
                      fetch_list=[x], scope=scope)
        assert len(out) == 1
        np.testing.assert_array_equal(np.asarray(out[0]), np.ones((2, 3)))
    finally:
        pt.set_flags({"FLAGS_check_nan_inf": False})


def test_use_prune_skips_optimizer_ops():
    """Eval fetch on a training program must not advance params/optimizer
    state when use_prune=True (reference Executor.run(use_prune))."""
    from paddle_tpu.optimizer import MomentumOptimizer

    main, startup = Program(), Program()
    main.random_seed = 1
    with program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        pred = layers.fc(x, 1, bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        MomentumOptimizer(0.1, 0.9).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)

    pname = next(n for n in scope.local_var_names() if ".w" in n)
    w_before = np.asarray(scope.get_var(pname)).copy()
    rs = np.random.RandomState(0)
    feed = {"x": rs.randn(8, 4).astype("f4"), "y": rs.randn(8, 1).astype("f4")}

    # pruned eval: loss computed, params untouched
    l1 = exe.run(main, feed=feed, fetch_list=[loss], scope=scope,
                 use_prune=True)[0]
    np.testing.assert_array_equal(np.asarray(scope.get_var(pname)), w_before)

    # unpruned training run: params move
    l2 = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)[0]
    assert not np.array_equal(np.asarray(scope.get_var(pname)), w_before)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), rtol=1e-6)


def test_scope_serial_distinct():
    s1 = pt.framework.Scope()
    s2 = pt.framework.Scope()
    assert s1.serial != s2.serial
