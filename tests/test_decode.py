"""Greedy/beam decoding vs numpy oracles.

Parity model: reference BeamSearchDecoder + dynamic_decode
(layers/rnn.py:866, :1398) and math/beam_search.cc — a seq2seq-style
step model decoded both ways, checked against an independent numpy
implementation of merged-queue beam search.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.text import decode

V, H = 11, 7
EOS = 0


def _mk_model(seed=0):
    rs = np.random.RandomState(seed)
    emb = rs.randn(V, H).astype("f4") * 0.7
    w = rs.randn(H, H).astype("f4") * 0.5
    out = rs.randn(H, V).astype("f4") * 0.9
    return emb, w, out


def _np_step(tok, h, model):
    emb, w, out = model
    h2 = np.tanh(emb[tok] + h @ w)
    return h2 @ out, h2


def _jax_step_fn(model):
    import jax.numpy as jnp

    emb, w, out = (jnp.asarray(m) for m in model)

    def step(tok, h):
        h2 = jnp.tanh(emb[tok] + h @ w)
        return h2 @ out, h2

    return step


def _np_log_softmax(x):
    x = x - x.max(axis=-1, keepdims=True)
    return x - np.log(np.exp(x).sum(axis=-1, keepdims=True))


def test_greedy_matches_numpy():
    model = _mk_model(0)
    step = _jax_step_fn(model)
    import jax.numpy as jnp

    ids, scores = decode.greedy_search(
        step, jnp.zeros((3, H)), np.array([1, 2, 3]), max_len=8, end_id=EOS)
    # numpy oracle from the same bos tokens
    h = np.zeros((3, H), "f4")
    tok = np.array([1, 2, 3])
    done = np.zeros(3, bool)
    out, score = [], np.zeros(3, "f4")
    for _ in range(8):
        logits, h = _np_step(tok, h, model)
        lp = _np_log_softmax(logits)
        tok = logits.argmax(-1)
        tok = np.where(done, EOS, tok)
        score = score + np.where(done, 0.0, lp[np.arange(3), tok])
        done |= tok == EOS
        out.append(tok.copy())
    np.testing.assert_array_equal(np.asarray(ids), np.stack(out, 1))
    np.testing.assert_allclose(np.asarray(scores), score, rtol=1e-5)


@pytest.mark.parametrize("K", [2, 4])
def test_beam_matches_numpy(K):
    import jax
    import jax.numpy as jnp

    model = _mk_model(1)
    step = _jax_step_fn(model)
    bos = np.array([1, 2])
    ids, scores = jax.jit(
        lambda s0, b: decode.beam_search(step, s0, b, beam_size=K,
                                         max_len=6, end_id=EOS))(
        jnp.zeros((2, H)), bos)

    # oracle from the same bos
    NEG = -1e9
    batch = 2
    h = np.zeros((batch * K, H), "f4")
    tok = np.repeat(bos, K)
    logp = np.tile([0.0] + [NEG] * (K - 1), batch).reshape(batch, K)
    fin = np.zeros((batch, K), bool)
    buf = np.full((batch, K, 6), EOS, np.int64)
    for t in range(6):
        logits, h = _np_step(tok, h, model)
        lp = _np_log_softmax(logits).reshape(batch, K, V)
        eos_row = np.full((V,), NEG)
        eos_row[EOS] = 0.0
        lp = np.where(fin[:, :, None], eos_row[None, None, :], lp)
        total = (logp[:, :, None] + lp).reshape(batch, K * V)
        top = np.argsort(-total, axis=1)[:, :K]
        logp = np.take_along_axis(total, top, axis=1)
        parent, token = top // V, top % V
        buf = np.take_along_axis(buf, parent[:, :, None], axis=1)
        buf[:, :, t] = token
        fin = np.take_along_axis(fin, parent, axis=1) | (token == EOS)
        gidx = (np.arange(batch)[:, None] * K + parent).ravel()
        h = h[gidx]
        tok = token.ravel()
    order = np.argsort(-logp, axis=1, kind="stable")
    buf = np.take_along_axis(buf, order[:, :, None], axis=1)
    logp = np.take_along_axis(logp, order, axis=1)
    np.testing.assert_allclose(np.asarray(scores), logp, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_array_equal(np.asarray(ids), buf)


def test_beam_scores_sorted_and_eos_padded():
    import jax.numpy as jnp

    model = _mk_model(2)
    ids, scores = decode.beam_search(
        _jax_step_fn(model), jnp.zeros((4, H)), np.array([1, 2, 3, 4]),
        beam_size=3, max_len=10, end_id=EOS, length_penalty=0.6)
    s = np.asarray(scores)
    assert (np.diff(s, axis=1) <= 1e-6).all(), "beams not sorted"
    ids = np.asarray(ids)
    # after the first EOS, everything is EOS padding
    for b in range(4):
        for k in range(3):
            row = ids[b, k]
            if (row == EOS).any():
                first = int((row == EOS).argmax())
                assert (row[first:] == EOS).all()


def test_dynamic_decode_dispatch():
    import jax.numpy as jnp

    model = _mk_model(3)
    g_ids, _ = decode.dynamic_decode(_jax_step_fn(model), jnp.zeros((2, H)),
                                     np.array([1, 2]), 5, EOS)
    assert np.asarray(g_ids).shape == (2, 5)
    b_ids, _ = decode.dynamic_decode(_jax_step_fn(model), jnp.zeros((2, H)),
                                     np.array([1, 2]), 5, EOS, beam_size=2)
    assert np.asarray(b_ids).shape == (2, 2, 5)


# --------------------------------------------------------------------------
# op-level: beam_search / beam_search_decode dense lowerings
# --------------------------------------------------------------------------

from op_test import OpTest  # noqa: E402


class TestBeamSearchOp(OpTest):
    op_type = "beam_search"

    def setup(self):
        K, C = 2, 3
        # batch 2, beam 2; row 2 is finished (pre_id == end 0)
        pre_ids = np.array([[3], [5], [0], [7]], np.int64)
        pre_scores = np.array([[-1.0], [-2.0], [-0.5], [-3.0]], "f4")
        ids = np.array([[4, 2, 8], [1, 9, 6], [4, 2, 8], [3, 5, 2]],
                       np.int64)
        scores = np.array([[-1.2, -1.4, -1.9], [-2.2, -2.5, -2.6],
                           [-9.0, -9.1, -9.2], [-3.1, -3.3, -3.9]], "f4")
        # group 0 candidates: (-1.2,4) (-1.4,2) (-1.9,8) (-2.2,1) ...
        #   top2: -1.2 (id 4, parent 0), -1.4 (id 2, parent 0)
        # group 1: finished row 2 contributes (end,-0.5) frozen;
        #   row 3 alive: -3.1 -3.3 -3.9 -> top2: -0.5 (end, parent 2),
        #   -3.1 (id 3, parent 3)
        sel_ids = np.array([[4], [2], [0], [3]], np.int64)
        sel_scores = np.array([[-1.2], [-1.4], [-0.5], [-3.1]], "f4")
        parent = np.array([0, 0, 2, 3], np.int32)
        self.inputs = {"pre_ids": [("pi", pre_ids)],
                       "pre_scores": [("ps", pre_scores)],
                       "ids": [("ids", ids)],
                       "scores": [("sc", scores)]}
        self.attrs = {"beam_size": 2, "end_id": 0, "is_accumulated": True,
                      "level": 0}
        self.outputs = {"selected_ids": [("si", sel_ids)],
                        "selected_scores": [("ss", sel_scores)],
                        "parent_idx": [("pa", parent)]}

    def test_output(self):
        self.check_output()


class TestBeamSearchDecodeOp(OpTest):
    op_type = "beam_search_decode"

    def setup(self):
        # T=3, batch*beam=2; chain: final lane 0 <- parent 1 <- parent 0
        ids = np.array([[4, 7], [5, 8], [6, 9]], np.int64)
        parents = np.array([[0, 0], [0, 0], [1, 0]], np.int64)
        scores = np.array([[-1.0, -1.1], [-2.0, -2.1], [-3.0, -3.1]], "f4")
        # lane 0: t2 tok 6, parent 1 -> t1 tok 8, parent 0 -> t0 tok 4
        # lane 1: t2 tok 9, parent 0 -> t1 tok 5, parent 0 -> t0 tok 4
        sent = np.array([[4, 8, 6], [4, 5, 9]], np.int64)
        self.inputs = {"Ids": [("ids", ids)],
                       "ParentIdx": [("par", parents)],
                       "Scores": [("sc", scores)]}
        self.attrs = {"beam_size": 2, "end_id": 0}
        self.outputs = {"SentenceIds": [("si", sent)],
                        "SentenceScores": [("ss",
                                            np.array([-3.0, -3.1], "f4"))]}

    def test_output(self):
        self.check_output()
