"""Fleet strategy implementations: AMP, recompute, gradient merge, and
loud rejection of unimplemented flags.

Parity model: reference fleet/meta_optimizers/{amp_optimizer,
recompute_optimizer}.py, fluid GradientMergeOptimizer (optimizer.py:5025),
checkpointed backward (fluid/backward.py:689).  Oracles: rewrite artifacts
must appear in the program AND training must stay numerically faithful.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.optimizer import MomentumOptimizer


def _net(x_dim=8, hidden=16, seed=1):
    from paddle_tpu.framework import unique_name
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = Program(), Program()
    main.random_seed = seed
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [x_dim])
        y = layers.data("y", [1])
        h = layers.fc(x, hidden, act="relu", param_attr=ParamAttr(
            initializer=ConstantInitializer(0.1)), bias_attr=False)
        h2 = layers.fc(h, hidden, act="relu", param_attr=ParamAttr(
            initializer=ConstantInitializer(0.05)), bias_attr=False)
        pred = layers.fc(h2, 1, param_attr=ParamAttr(
            initializer=ConstantInitializer(0.2)), bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
    return main, startup, loss, h

def _data(rng, n=16, x_dim=8):
    X = rng.randn(n, x_dim).astype("float32")
    Y = (X.sum(axis=1, keepdims=True) * 0.3).astype("float32")
    return X, Y


def _train(main, startup, loss, X, Y, steps):
    scope = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    out = []
    for _ in range(steps):
        out.append(float(np.asarray(exe.run(
            main, feed={"x": X, "y": Y}, fetch_list=[loss],
            scope=scope)[0]).item()))
    return out, scope


class TestAMPStrategy:
    def test_amp_inserts_casts_and_trains(self):
        from paddle_tpu.distributed import fleet

        rng = np.random.RandomState(0)
        X, Y = _data(rng)
        main, startup, loss, _ = _net()
        with program_guard(main, startup):
            strat = fleet.DistributedStrategy()
            strat.amp = True
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
            fleet.minimize(loss)
        casts = [op for op in main.global_block.ops if op.type == "cast"]
        assert casts, "strategy.amp must insert cast ops"
        losses, _ = _train(main, startup, loss, X, Y, 15)
        # bf16 compute: coarse convergence check
        assert min(losses[1:]) < losses[0], losses


class TestRecomputeStrategy:
    def test_recompute_reemits_segments_behind_barrier(self):
        from paddle_tpu.distributed import fleet

        rng = np.random.RandomState(0)
        X, Y = _data(rng)

        # oracle: plain training
        main0, startup0, loss0, _ = _net()
        with program_guard(main0, startup0):
            MomentumOptimizer(0.05, 0.9).minimize(loss0)
        base, _ = _train(main0, startup0, loss0, X, Y, 6)

        main, startup, loss, ckpt_var = _net()
        with program_guard(main, startup):
            strat = fleet.DistributedStrategy()
            strat.recompute = True
            strat.recompute_configs = {"checkpoints": [ckpt_var.name]}
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
            fleet.minimize(loss)
        ops = [op.type for op in main.global_block.ops]
        assert "recompute_barrier" in ops, "CSE fence missing"
        assert any(n.endswith("@RECOMPUTE")
                   for op in main.global_block.ops
                   for n in op.output_arg_names()), "no re-emitted segment"
        got, _ = _train(main, startup, loss, X, Y, 6)
        np.testing.assert_allclose(base, got, rtol=1e-5, atol=1e-6)

    def test_recompute_without_checkpoints_rejected(self):
        from paddle_tpu.distributed import fleet

        main, startup, loss, _ = _net()
        with program_guard(main, startup):
            strat = fleet.DistributedStrategy()
            strat.recompute = True
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
            with pytest.raises(ValueError, match="checkpoints"):
                fleet.minimize(loss)


class TestGradientMergeStrategy:
    def test_k2_matches_double_batch(self):
        """GM(k=2, avg) on micro-batches b1,b2 == one momentum step on
        concat(b1,b2) (mean losses => mean of micro-grads)."""
        from paddle_tpu.distributed import fleet

        rng = np.random.RandomState(0)
        X, Y = _data(rng, n=32)
        b1, b2 = (X[:16], Y[:16]), (X[16:], Y[16:])

        # oracle: one step on the full batch
        main0, startup0, loss0, _ = _net()
        with program_guard(main0, startup0):
            MomentumOptimizer(0.05, 0.9).minimize(loss0)
        scope0 = pt.framework.Scope()
        exe0 = pt.Executor(pt.CPUPlace())
        exe0.run(startup0, scope=scope0)
        exe0.run(main0, feed={"x": X, "y": Y}, fetch_list=[loss0],
                 scope=scope0)

        # gradient merge: two micro-steps
        main, startup, loss, _ = _net()
        with program_guard(main, startup):
            strat = fleet.DistributedStrategy()
            strat.gradient_merge = True
            strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
            fleet.minimize(loss)
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        exe.run(main, feed={"x": b1[0], "y": b1[1]}, fetch_list=[loss],
                scope=scope)
        # after micro-step 1 params must be UNCHANGED
        p = "fc_0.w_0"
        np.testing.assert_allclose(np.asarray(scope.get_var(p)),
                                   np.full((8, 16), 0.1, "f4"), rtol=1e-6)
        exe.run(main, feed={"x": b2[0], "y": b2[1]}, fetch_list=[loss],
                scope=scope)
        # after micro-step 2 params must equal the full-batch oracle step
        np.testing.assert_allclose(
            np.asarray(scope.get_var(p)), np.asarray(scope0.get_var(p)),
            rtol=1e-5, atol=1e-6)

    def test_momentum_state_frozen_between_updates(self):
        from paddle_tpu.distributed import fleet

        rng = np.random.RandomState(0)
        X, Y = _data(rng)
        main, startup, loss, _ = _net()
        with program_guard(main, startup):
            strat = fleet.DistributedStrategy()
            strat.gradient_merge = True
            strat.gradient_merge_configs = {"k_steps": 3, "avg": True}
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
            fleet.minimize(loss)
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        vel_names = [n for n in scope.local_var_names()
                     if "velocity" in n.lower()]
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss], scope=scope)
        vel_names = [n for n in scope.local_var_names()
                     if "velocity" in n.lower()]
        assert vel_names, "no velocity accumulator found"
        for n in vel_names:
            np.testing.assert_allclose(np.asarray(scope.get_var(n)), 0.0,
                                       atol=1e-7)


class TestUnsupportedStrategiesRejected:
    @pytest.mark.parametrize("flag", ["a_sync", "sequence_parallel"])
    def test_flag_raises(self, flag):
        from paddle_tpu.distributed import fleet

        main, startup, loss, _ = _net()
        with program_guard(main, startup):
            strat = fleet.DistributedStrategy()
            setattr(strat, flag, True)
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
            with pytest.raises(NotImplementedError):
                fleet.minimize(loss)


class TestShardingZeRO1:
    def test_sharding_loss_parity_and_state_sharded(self):
        """ZeRO-1 (reference sharding_optimizer.py:33): loss parity with
        plain DP, and optimizer accumulators physically sharded over the
        8-device mesh (per-device memory ~1/8)."""
        import jax

        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.parallel_env import (init_parallel_env,
                                                         reset_mesh)

        rng = np.random.RandomState(0)
        X, Y = _data(rng, n=32)

        def run(strategy_flags, steps=4):
            reset_mesh()
            mesh = init_parallel_env()
            main, startup, loss, _ = _net()
            with program_guard(main, startup):
                strat = fleet.DistributedStrategy()
                for k, v in strategy_flags.items():
                    setattr(strat, k, v)
                fleet.init(is_collective=True, strategy=strat)
                fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
                fleet.minimize(loss)
            scope = pt.framework.Scope()
            exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
            exe.run(startup, scope=scope)
            losses = [float(np.asarray(exe.run(
                main, feed={"x": X, "y": Y}, fetch_list=[loss],
                scope=scope)[0]).item()) for _ in range(steps)]
            return main, losses, scope

        main_dp, base, _ = run({})
        main_sh, got, scope = run({"sharding": True})
        assert any(op.type == "c_shard_slice"
                   for op in main_sh.global_block.ops)
        np.testing.assert_allclose(base, got, rtol=1e-4, atol=1e-6)

        # accumulators live sharded: each device holds 1/8 of dim 0
        sharded = set()
        for op in main_sh.global_block.ops:
            sharded.update(op.attr("__sharded_accumulators__", None) or [])
        assert sharded, "no accumulator was sharded"
        for name in sharded:
            arr = scope.get_var(name)
            full_dim0 = arr.shape[0]
            shard_shapes = {s.data.shape[0] for s in arr.addressable_shards}
            assert shard_shapes == {full_dim0 // 8}, (
                name, arr.sharding, shard_shapes)
        reset_mesh()


class TestStrategyComposition:
    """Round-5: composition the reference StrategyCompiler chains freely
    (fleet/base/strategy_compiler.py:89)."""

    def _run(self, strategy_flags, steps=6, opt=None, use_mesh=True):
        import paddle_tpu as _pt
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.parallel_env import (init_parallel_env,
                                                         reset_mesh)

        rng = np.random.RandomState(0)
        X, Y = _data(rng, n=32)
        reset_mesh()
        mesh = init_parallel_env() if use_mesh else None
        main, startup, loss, _ = _net()
        with program_guard(main, startup):
            strat = fleet.DistributedStrategy()
            for k, v in strategy_flags.items():
                setattr(strat, k, v)
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(opt or MomentumOptimizer(0.05, 0.9))
            fleet.minimize(loss)
        scope = _pt.framework.Scope()
        exe = _pt.Executor(_pt.CPUPlace(), mesh=mesh)
        exe.run(startup, scope=scope)
        losses = [float(np.asarray(exe.run(
            main, feed={"x": X, "y": Y}, fetch_list=[loss],
            scope=scope)[0]).item()) for _ in range(steps)]
        reset_mesh()
        return main, losses, scope

    def test_sharding_with_gradient_merge_parity(self):
        """sharding x gradient_merge: loss trajectory matches plain
        gradient_merge, and the merge accumulators join the sharded
        state (1/8 per device)."""
        gm_cfg = {"k_steps": 2, "avg": True}
        _, base, _ = self._run({"gradient_merge": True,
                                "gradient_merge_configs": gm_cfg})
        main, got, scope = self._run({"sharding": True,
                                      "gradient_merge": True,
                                      "gradient_merge_configs": gm_cfg})
        np.testing.assert_allclose(base, got, rtol=1e-4, atol=1e-6)

        sharded = set()
        for op in main.global_block.ops:
            sharded.update(op.attr("__sharded_accumulators__", None) or [])
        gm_accs = {n for n in sharded if "_gm_acc" in n}
        assert gm_accs, f"merge accumulators not sharded: {sorted(sharded)}"
        for name in gm_accs:
            arr = scope.get_var(name)
            shard_shapes = {s.data.shape[0] for s in arr.addressable_shards}
            assert shard_shapes == {arr.shape[0] // 8}, (name, shard_shapes)

    def test_fp16_amp_with_gradient_merge(self):
        """fp16 AMP x gradient_merge: trains, and the loss-scaling
        counters advance only on update steps (the scaler rides the
        merge mask)."""
        import paddle_tpu as _pt
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.parallel_env import reset_mesh

        rng = np.random.RandomState(0)
        X, Y = _data(rng, n=32)
        reset_mesh()
        main, startup, loss, _ = _net()
        with program_guard(main, startup):
            strat = fleet.DistributedStrategy()
            strat.amp = True
            strat.amp_configs = {"use_bf16": False,
                                 "init_loss_scaling": 1024.0}
            strat.gradient_merge = True
            strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
            fleet.minimize(loss)
        ops = [op.type for op in main.global_block.ops]
        assert "check_finite_and_unscale" in ops
        assert "update_loss_scaling" in ops
        good_name = next(
            op.output("OutGoodSteps")[0] for op in main.global_block.ops
            if op.type == "update_loss_scaling")
        scope = _pt.framework.Scope()
        exe = _pt.Executor(_pt.CPUPlace())
        exe.run(startup, scope=scope)
        losses, goods = [], []
        for _ in range(6):
            out = exe.run(main, feed={"x": X, "y": Y},
                          fetch_list=[loss, good_name], scope=scope)
            losses.append(float(np.asarray(out[0]).item()))
            goods.append(int(np.asarray(out[1]).ravel()[0]))
        # counters move on update steps only: steps 2,4,6 -> 1,2,3
        assert goods == [0, 1, 1, 2, 2, 3], goods
        assert min(losses[1:]) < losses[0], losses

    def test_fp16_amp_with_degenerate_gradient_merge(self):
        """k_steps=1 merge must still unscale (the early-return path
        once dropped the grad transform — gradients stayed multiplied
        by the 2^15 loss scale and training diverged)."""
        _, merged, _ = self._run(
            {"amp": True,
             "amp_configs": {"use_bf16": False,
                            "init_loss_scaling": 1024.0},
             "gradient_merge": True,
             "gradient_merge_configs": {"k_steps": 1}},
            use_mesh=False)
        _, plain, _ = self._run({}, use_mesh=False)
        np.testing.assert_allclose(merged, plain, rtol=5e-2, atol=1e-3)

    def test_fp16_amp_gm_matches_bf16_free_updates(self):
        """Same chain under fp16 must track the no-merge equivalent:
        k=2 merged-average updates == one update per two identical
        batches (coarse parity; fp16 rounding allows loose tolerance)."""
        gm_cfg = {"k_steps": 2, "avg": True}
        _, merged, _ = self._run(
            {"amp": True,
             "amp_configs": {"use_bf16": False,
                            "init_loss_scaling": 1024.0},
             "gradient_merge": True, "gradient_merge_configs": gm_cfg},
            use_mesh=False)
        _, plain, _ = self._run(
            {"gradient_merge": True, "gradient_merge_configs": gm_cfg},
            use_mesh=False)
        # fp16 forward/backward vs the fp32 oracle: rounding compounds
        # over steps; ~5% after 6 steps is numerics, not a logic bug
        np.testing.assert_allclose(merged, plain, rtol=5e-2, atol=1e-3)
