"""Pipeline parallelism: GPipe schedule over a 'pp' mesh axis.

Parity model: reference fluid PipelineOptimizer (optimizer.py:3695) +
PipelineTrainer (pipeline_trainer.cc) with the test_dist oracle — the
pipelined run's losses must match the same program run non-pipelined.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import (Program, device_guard,
                                          program_guard)
from paddle_tpu.optimizer import MomentumOptimizer, PipelineOptimizer


def _build(n_micro, hidden=16):
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = Program(), Program()
    main.random_seed = 1
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1])
        with device_guard("stage:0"):
            h = layers.fc(x, hidden, act="relu", param_attr=ParamAttr(
                initializer=ConstantInitializer(0.1)), bias_attr=False)
        with device_guard("stage:1"):
            h2 = layers.fc(h, hidden, act="relu", param_attr=ParamAttr(
                initializer=ConstantInitializer(0.07)), bias_attr=False)
            pred = layers.fc(h2, 1, param_attr=ParamAttr(
                initializer=ConstantInitializer(0.2)), bias_attr=False)
            loss = layers.mean(layers.square_error_cost(pred, y))
        PipelineOptimizer(MomentumOptimizer(0.05, 0.9),
                          num_microbatches=n_micro).minimize(loss)
    return main, startup, loss


def _data(n=32):
    rng = np.random.RandomState(0)
    X = rng.randn(n, 8).astype("f4")
    Y = (X.sum(1, keepdims=True) * 0.3).astype("f4")
    return X, Y


def _train(main, startup, loss, X, Y, steps, mesh=None):
    sc = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup, scope=sc)
    return [float(np.asarray(exe.run(main, feed={"x": X, "y": Y},
                                     fetch_list=[loss], scope=sc)[0]).item())
            for _ in range(steps)]


class TestPipelineParity:
    @pytest.mark.parametrize("n_micro,stages", [(4, 2), (2, 4)])
    def test_matches_non_pipelined(self, n_micro, stages):
        import jax

        X, Y = _data(32)
        main, startup, loss = _build(n_micro)
        base = _train(main, startup, loss, X, Y, steps=4)

        # same program, GPipe over 'pp'
        main2, startup2, loss2 = _build(n_micro)
        if stages == 4:
            # retag the middle ops across 4 stages? keep 2-stage program on
            # a 2-wide axis slice instead
            pytest.skip("4-stage retag covered by the 2-stage parametrize")
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:stages]), ("pp",))
        got = _train(main2, startup2, loss2, X, Y, steps=4, mesh=mesh)
        np.testing.assert_allclose(base, got, rtol=1e-4, atol=1e-6)

    def test_multi_tensor_boundary_parity(self):
        """v2: boundaries may pass several tensors (packed carrier)."""
        import jax

        from paddle_tpu.initializer import ConstantInitializer
        from paddle_tpu.param_attr import ParamAttr

        def build():
            main, startup = Program(), Program()
            main.random_seed = 1
            with unique_name.guard(), program_guard(main, startup):
                x = layers.data("x", [8])
                y = layers.data("y", [1])
                with device_guard("stage:0"):
                    h1 = layers.fc(x, 8, param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.1)),
                        bias_attr=False)
                    h2 = layers.fc(x, 12, param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.05)),
                        bias_attr=False)  # two boundary vars, ragged widths
                with device_guard("stage:1"):
                    h2s = layers.fc(h2, 8, param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.03)),
                        bias_attr=False)
                    both = layers.elementwise_add(h1, h2s)
                    pred = layers.fc(both, 1, param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.2)),
                        bias_attr=False)
                    loss = layers.mean(layers.square_error_cost(pred, y))
                PipelineOptimizer(MomentumOptimizer(0.05, 0.9),
                                  num_microbatches=2).minimize(loss)
            return main, startup, loss

        X, Y = _data(8)
        base = _train(*build(), X, Y, steps=3)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("pp",))
        got = _train(*build(), X, Y, steps=3, mesh=mesh)
        np.testing.assert_allclose(base, got, rtol=1e-4, atol=1e-6)

    def test_skip_connection_across_three_stages(self):
        """v2: a stage-0 output consumed at stage 2 rides through the
        intermediate boundary (pass-through packing)."""
        import jax

        from paddle_tpu.initializer import ConstantInitializer
        from paddle_tpu.param_attr import ParamAttr

        def build():
            main, startup = Program(), Program()
            main.random_seed = 1
            with unique_name.guard(), program_guard(main, startup):
                x = layers.data("x", [8])
                y = layers.data("y", [1])
                with device_guard("stage:0"):
                    h0 = layers.fc(x, 8, param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.1)),
                        bias_attr=False)
                with device_guard("stage:1"):
                    h1 = layers.fc(h0, 8, act="relu", param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.07)),
                        bias_attr=False)
                with device_guard("stage:2"):
                    res = layers.elementwise_add(h0, h1)  # skip from stage 0
                    pred = layers.fc(res, 1, param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.2)),
                        bias_attr=False)
                    loss = layers.mean(layers.square_error_cost(pred, y))
                PipelineOptimizer(MomentumOptimizer(0.05, 0.9),
                                  num_microbatches=2).minimize(loss)
            return main, startup, loss

        X, Y = _data(8)
        base = _train(*build(), X, Y, steps=3)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:3]), ("pp",))
        got = _train(*build(), X, Y, steps=3, mesh=mesh)
        np.testing.assert_allclose(base, got, rtol=1e-4, atol=1e-6)

    def test_dropout_pipeline_deterministic_and_trains(self):
        """v2: dropout inside stages — deterministic across identical
        runs (fwd/bwd masks match by construction) and the loss drops."""
        import jax

        from paddle_tpu.initializer import ConstantInitializer
        from paddle_tpu.param_attr import ParamAttr

        def build():
            main, startup = Program(), Program()
            main.random_seed = 7
            with unique_name.guard(), program_guard(main, startup):
                x = layers.data("x", [8])
                y = layers.data("y", [1])
                with device_guard("stage:0"):
                    h = layers.fc(x, 16, act="relu", param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.1)),
                        bias_attr=False)
                    h = layers.dropout(h, 0.25)
                with device_guard("stage:1"):
                    # head starts at 0: with init 0.2 the model already
                    # sits at the optimum of the y = 0.3*sum(x) target
                    # and the loss is pure dropout noise around the
                    # floor — "trains" was then a coin flip (flaky since
                    # PR 2); from 0 the drop is ~3x and monotone
                    pred = layers.fc(h, 1, param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.0)),
                        bias_attr=False)
                    loss = layers.mean(layers.square_error_cost(pred, y))
                PipelineOptimizer(MomentumOptimizer(0.05, 0.9),
                                  num_microbatches=2).minimize(loss)
            return main, startup, loss

        X, Y = _data(16)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("pp",))
        a = _train(*build(), X, Y, steps=6, mesh=mesh)
        b = _train(*build(), X, Y, steps=6, mesh=mesh)
        np.testing.assert_allclose(a, b, rtol=1e-6)
        assert a[-1] < a[0], a

    def test_batch_norm_running_stats_carried(self):
        """v2: state written inside staged forwards (BN running stats) is
        carried per microbatch on the owning rank and persists to the
        scope, matching the non-pipelined run."""
        import jax

        from paddle_tpu.initializer import ConstantInitializer
        from paddle_tpu.param_attr import ParamAttr

        def build():
            main, startup = Program(), Program()
            main.random_seed = 1
            with unique_name.guard(), program_guard(main, startup):
                x = layers.data("x", [8])
                y = layers.data("y", [1])
                with device_guard("stage:0"):
                    h = layers.fc(x, 8, param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.1)),
                        bias_attr=False)
                    h = layers.batch_norm(h)
                with device_guard("stage:1"):
                    pred = layers.fc(h, 1, param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.2)),
                        bias_attr=False)
                    loss = layers.mean(layers.square_error_cost(pred, y))
                PipelineOptimizer(MomentumOptimizer(0.05, 0.9),
                                  num_microbatches=2).minimize(loss)
            return main, startup, loss

        def run(mesh):
            main, startup, loss = build()
            sc = pt.framework.Scope()
            exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
            exe.run(startup, scope=sc)
            X, Y = _data(8)
            losses = [float(np.asarray(
                exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                        scope=sc)[0]).item()) for _ in range(3)]
            # running mean/var are the BN layer's global vars (.gv_0/.gv_1)
            mean_name = next(n for n in sorted(sc.local_var_names())
                             if "batch_norm" in n and ".gv_" in n)
            return losses, np.asarray(sc.get_var(mean_name))

        # GPipe BN normalizes each MICROBATCH (reference semantics too),
        # so exact loss parity with the full-batch run does not hold;
        # the v2 contract is: stats update, persist, and are
        # deterministic, and training proceeds.
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("pp",))
        pp_losses, pp_mean = run(mesh)
        pp_losses2, pp_mean2 = run(mesh)
        assert np.isfinite(pp_losses).all() and pp_losses[-1] < pp_losses[0]
        assert np.any(pp_mean != 0.0), "running mean never updated"
        np.testing.assert_allclose(pp_mean, pp_mean2, rtol=1e-6)
        np.testing.assert_allclose(pp_losses, pp_losses2, rtol=1e-6)

    def test_dp_x_pp_composition_parity(self):
        """v2: 2x2 dp x pp mesh matches the single-device run."""
        import jax

        X, Y = _data(32)
        main, startup, loss = _build(2)
        base = _train(main, startup, loss, X, Y, steps=3)
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "pp"))
        main2, startup2, loss2 = _build(2)
        got = _train(main2, startup2, loss2, X, Y, steps=3, mesh=mesh)
        np.testing.assert_allclose(base, got, rtol=1e-4, atol=1e-6)


class TestPipelineStateSharding:
    """v3: params + optimizer state live ONLY on their owning stage's
    rank (the memory point of pipeline parallelism), fetches are no
    longer loss-only, and save/restore still sees true values."""

    def _build4(self, hidden=32):
        from paddle_tpu.initializer import ConstantInitializer
        from paddle_tpu.param_attr import ParamAttr

        main, startup = Program(), Program()
        main.random_seed = 1
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [hidden])
            y = layers.data("y", [1])
            h = x
            for s in range(3):
                with device_guard(f"stage:{s}"):
                    h = layers.fc(h, hidden, act="relu",
                                  param_attr=ParamAttr(
                                      initializer=ConstantInitializer(
                                          0.05 + 0.01 * s)),
                                  bias_attr=False)
            with device_guard("stage:3"):
                pred = layers.fc(h, 1, param_attr=ParamAttr(
                    initializer=ConstantInitializer(0.1)), bias_attr=False)
                loss = layers.mean(layers.square_error_cost(pred, y))
            PipelineOptimizer(MomentumOptimizer(0.05, 0.9),
                              num_microbatches=2).minimize(loss)
        return main, startup, loss, pred

    def test_per_rank_state_is_one_stage_share(self):
        """Per-rank packed param+velocity bytes ~= total/S (balanced
        stages), not total — the defining benefit of PP."""
        import jax

        from paddle_tpu.distributed.pipeline import PACKED_STATE_VAR

        hidden = 32
        main, startup, loss, _ = self._build4(hidden)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("pp",))
        sc = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
        exe.run(startup, scope=sc)
        rng = np.random.RandomState(0)
        X = rng.randn(8, hidden).astype("f4")
        Y = (X.sum(1, keepdims=True) * 0.1).astype("f4")
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss], scope=sc)

        buf = sc.get_var(PACKED_STATE_VAR)
        # total trainable state: 3x (h,h) + 1x (h,1) params, doubled for
        # momentum velocity slots
        total = (3 * hidden * hidden + hidden) * 2 * 4  # bytes
        per_rank = {}
        for shard in buf.addressable_shards:
            per_rank[shard.device] = per_rank.get(shard.device, 0) \
                + shard.data.nbytes
        assert len(per_rank) == 4
        for dev, nbytes in per_rank.items():
            # width pads every rank to the widest stage; the 3 hidden x
            # hidden stages dominate -> each rank holds ~total/3.3, far
            # below the replicated total
            assert nbytes <= total / 4 * 1.45, (
                f"rank {dev} holds {nbytes} bytes, expected ~{total / 4}")

    def test_sharded_parity_and_activation_fetch(self):
        """4-stage sharded run matches non-pipelined losses, and batched
        activation fetches (pred) come back assembled."""
        import jax

        hidden = 32
        rng = np.random.RandomState(0)
        X = rng.randn(8, hidden).astype("f4")
        Y = (X.sum(1, keepdims=True) * 0.1).astype("f4")

        main, startup, loss, pred = self._build4(hidden)
        base_sc = pt.framework.Scope()
        exe0 = pt.Executor(pt.CPUPlace())
        exe0.run(startup, scope=base_sc)
        base = [exe0.run(main, feed={"x": X, "y": Y},
                         fetch_list=[loss, pred], scope=base_sc)
                for _ in range(3)]

        main2, startup2, loss2, pred2 = self._build4(hidden)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("pp",))
        sc = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
        exe.run(startup2, scope=sc)
        got = [exe.run(main2, feed={"x": X, "y": Y},
                       fetch_list=[loss2, pred2], scope=sc)
               for _ in range(3)]
        for (bl, bp), (gl, gp) in zip(base, got):
            np.testing.assert_allclose(np.asarray(bl), np.asarray(gl),
                                       rtol=1e-4, atol=1e-6)
            np.testing.assert_allclose(np.asarray(bp), np.asarray(gp),
                                       rtol=1e-4, atol=1e-5)

    def test_second_fetch_list_reuses_packed_scope(self):
        """A new fetch list compiles a sibling PackPlan; it must adopt
        the already-packed scope (regression: entries stayed None)."""
        import jax

        hidden = 32
        rng = np.random.RandomState(0)
        X = rng.randn(8, hidden).astype("f4")
        Y = (X.sum(1, keepdims=True) * 0.1).astype("f4")
        main, startup, loss, pred = self._build4(hidden)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("pp",))
        sc = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
        exe.run(startup, scope=sc)
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss, pred],
                scope=sc)
        out = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                      scope=sc)
        assert np.isfinite(np.asarray(out[0]))

    def test_packed_refs_roundtrip_save_restore(self):
        """Owned scope vars become PackedParamRef views that materialize
        true values; writing a concrete array over one re-packs."""
        import jax

        from paddle_tpu.framework.scope import PackedParamRef

        hidden = 32
        rng = np.random.RandomState(0)
        X = rng.randn(8, hidden).astype("f4")
        Y = (X.sum(1, keepdims=True) * 0.1).astype("f4")

        main, startup, loss, _ = self._build4(hidden)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("pp",))
        sc = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
        exe.run(startup, scope=sc)
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss], scope=sc)

        pnames = [n for n in sorted(sc.local_var_names()) if ".w_" in n]
        assert pnames and all(
            isinstance(sc.get_var(n), PackedParamRef) for n in pnames)
        # materialized view has the declared shape and a trained value
        vals = {n: np.asarray(sc.get_var(n)) for n in pnames}
        assert vals[pnames[0]].shape == (hidden, hidden)

        # restore path: write concrete arrays (as paddle.load does) and
        # check the next run re-packs them — training continues from the
        # restored values, reproducing the original trajectory
        state_names = [n for n in sorted(sc.local_var_names())
                       if isinstance(sc.get_var(n), PackedParamRef)]
        snapshot = {n: np.asarray(sc.get_var(n)) for n in state_names}
        l1 = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                     scope=sc)[0]
        sc2 = pt.framework.Scope()
        exe.run(startup, scope=sc2)
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss], scope=sc2)
        # overwrite sc2's packed state with sc's post-step-1 snapshot
        for n, v in snapshot.items():
            sc2.set_var(n, v)
        l2 = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                     scope=sc2)[0]
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=1e-5)


class TestPipelineFleet:
    def test_strategy_pipeline_via_fleet(self):
        import jax

        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh
        from paddle_tpu.initializer import ConstantInitializer
        from paddle_tpu.param_attr import ParamAttr

        X, Y = _data(16)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("pp",))
        set_mesh(mesh)
        try:
            main, startup = Program(), Program()
            main.random_seed = 1
            with unique_name.guard(), program_guard(main, startup):
                x = layers.data("x", [8])
                y = layers.data("y", [1])
                with device_guard("stage:0"):
                    h = layers.fc(x, 16, act="relu", param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.1)),
                        bias_attr=False)
                with device_guard("stage:1"):
                    pred = layers.fc(h, 1, param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.2)),
                        bias_attr=False)
                    loss = layers.mean(layers.square_error_cost(pred, y))
                strat = fleet.DistributedStrategy()
                strat.pipeline = True
                strat.pipeline_configs = {"micro_batch": 4}
                fleet.init(is_collective=True, strategy=strat)
                fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
                fleet.minimize(loss)
            assert getattr(main, "_pipeline", None) is not None
            losses = _train(main, startup, loss, X, Y, steps=5, mesh=mesh)
            assert losses[-1] < losses[0], losses
        finally:
            reset_mesh()
