"""Pipeline parallelism: GPipe schedule over a 'pp' mesh axis.

Parity model: reference fluid PipelineOptimizer (optimizer.py:3695) +
PipelineTrainer (pipeline_trainer.cc) with the test_dist oracle — the
pipelined run's losses must match the same program run non-pipelined.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import (Program, device_guard,
                                          program_guard)
from paddle_tpu.optimizer import MomentumOptimizer, PipelineOptimizer


def _build(n_micro, hidden=16):
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = Program(), Program()
    main.random_seed = 1
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1])
        with device_guard("stage:0"):
            h = layers.fc(x, hidden, act="relu", param_attr=ParamAttr(
                initializer=ConstantInitializer(0.1)), bias_attr=False)
        with device_guard("stage:1"):
            h2 = layers.fc(h, hidden, act="relu", param_attr=ParamAttr(
                initializer=ConstantInitializer(0.07)), bias_attr=False)
            pred = layers.fc(h2, 1, param_attr=ParamAttr(
                initializer=ConstantInitializer(0.2)), bias_attr=False)
            loss = layers.mean(layers.square_error_cost(pred, y))
        PipelineOptimizer(MomentumOptimizer(0.05, 0.9),
                          num_microbatches=n_micro).minimize(loss)
    return main, startup, loss


def _data(n=32):
    rng = np.random.RandomState(0)
    X = rng.randn(n, 8).astype("f4")
    Y = (X.sum(1, keepdims=True) * 0.3).astype("f4")
    return X, Y


def _train(main, startup, loss, X, Y, steps, mesh=None):
    sc = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup, scope=sc)
    return [float(np.asarray(exe.run(main, feed={"x": X, "y": Y},
                                     fetch_list=[loss], scope=sc)[0]).item())
            for _ in range(steps)]


class TestPipelineParity:
    @pytest.mark.parametrize("n_micro,stages", [(4, 2), (2, 4)])
    def test_matches_non_pipelined(self, n_micro, stages):
        import jax

        X, Y = _data(32)
        main, startup, loss = _build(n_micro)
        base = _train(main, startup, loss, X, Y, steps=4)

        # same program, GPipe over 'pp'
        main2, startup2, loss2 = _build(n_micro)
        if stages == 4:
            # retag the middle ops across 4 stages? keep 2-stage program on
            # a 2-wide axis slice instead
            pytest.skip("4-stage retag covered by the 2-stage parametrize")
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:stages]), ("pp",))
        got = _train(main2, startup2, loss2, X, Y, steps=4, mesh=mesh)
        np.testing.assert_allclose(base, got, rtol=1e-4, atol=1e-6)

    def test_boundary_must_be_single_tensor(self):
        import jax

        from paddle_tpu.initializer import ConstantInitializer
        from paddle_tpu.param_attr import ParamAttr

        main, startup = Program(), Program()
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [8])
            y = layers.data("y", [1])
            with device_guard("stage:0"):
                h1 = layers.fc(x, 8, param_attr=ParamAttr(
                    initializer=ConstantInitializer(0.1)), bias_attr=False)
                h2 = layers.fc(x, 8, param_attr=ParamAttr(
                    initializer=ConstantInitializer(0.1)), bias_attr=False)
            with device_guard("stage:1"):
                both = layers.elementwise_add(h1, h2)  # two boundary vars
                pred = layers.fc(both, 1, bias_attr=False)
                loss = layers.mean(layers.square_error_cost(pred, y))
            PipelineOptimizer(MomentumOptimizer(0.05, 0.9),
                              num_microbatches=2).minimize(loss)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("pp",))
        X, Y = _data(8)
        with pytest.raises(ValueError, match="exactly.*one activation|one tensor"):
            _train(main, startup, loss, X, Y, steps=1, mesh=mesh)


class TestPipelineFleet:
    def test_strategy_pipeline_via_fleet(self):
        import jax

        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.parallel_env import reset_mesh, set_mesh
        from paddle_tpu.initializer import ConstantInitializer
        from paddle_tpu.param_attr import ParamAttr

        X, Y = _data(16)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("pp",))
        set_mesh(mesh)
        try:
            main, startup = Program(), Program()
            main.random_seed = 1
            with unique_name.guard(), program_guard(main, startup):
                x = layers.data("x", [8])
                y = layers.data("y", [1])
                with device_guard("stage:0"):
                    h = layers.fc(x, 16, act="relu", param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.1)),
                        bias_attr=False)
                with device_guard("stage:1"):
                    pred = layers.fc(h, 1, param_attr=ParamAttr(
                        initializer=ConstantInitializer(0.2)),
                        bias_attr=False)
                    loss = layers.mean(layers.square_error_cost(pred, y))
                strat = fleet.DistributedStrategy()
                strat.pipeline = True
                strat.pipeline_configs = {"micro_batch": 4}
                fleet.init(is_collective=True, strategy=strat)
                fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
                fleet.minimize(loss)
            assert getattr(main, "_pipeline", None) is not None
            losses = _train(main, startup, loss, X, Y, steps=5, mesh=mesh)
            assert losses[-1] < losses[0], losses
        finally:
            reset_mesh()
