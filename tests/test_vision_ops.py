"""paddle.vision.ops functional namespace (reference
python/paddle/vision/ops.py) — dygraph + gradient flow.
"""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.dygraph.tensor import Tensor
from paddle_tpu.vision import ops as vops


def test_deform_conv2d_zero_offset_matches_functional_conv():
    rs = np.random.RandomState(0)
    x = Tensor(rs.randn(2, 4, 6, 6).astype("f4"), stop_gradient=False)
    w = Tensor(rs.randn(3, 4, 3, 3).astype("f4"), stop_gradient=False)
    off = Tensor(np.zeros((2, 18, 6, 6), "f4"))
    got = vops.deform_conv2d(x, off, w, padding=1)

    import paddle_tpu.nn.functional as F

    want = F.conv2d(x, w, padding=1)
    np.testing.assert_allclose(np.asarray(got.numpy()),
                               np.asarray(want.numpy()),
                               rtol=1e-4, atol=1e-5)
    loss = pt.tensor.math.sum(got * got)
    loss.backward()
    assert w.grad is not None
    assert np.isfinite(np.asarray(w.grad.numpy())).all()


def test_roi_align_and_pool_shapes():
    rs = np.random.RandomState(1)
    x = Tensor(rs.randn(1, 3, 8, 8).astype("f4"))
    rois = Tensor(np.array([[0., 0., 8., 8.], [2., 2., 6., 6.]], "f4"))
    ra = vops.roi_align(x, rois, output_size=2, aligned=False)
    assert ra.shape == [2, 3, 2, 2]
    rp = vops.roi_pool(x, rois, output_size=2)
    assert rp.shape == [2, 3, 2, 2]


def test_yolo_box_decodes():
    rs = np.random.RandomState(2)
    x = Tensor(rs.randn(1, 2 * 8, 4, 4).astype("f4"))
    img = Tensor(np.array([[32, 32]], "i4"))
    boxes, scores = vops.yolo_box(x, img, anchors=[10, 13, 16, 30],
                                  class_num=3, conf_thresh=0.0,
                                  downsample_ratio=8)
    b = np.asarray(boxes.numpy())
    assert b.shape == (1, 32, 4)
    assert (b[..., 2] >= b[..., 0]).all() and (b[..., 3] >= b[..., 1]).all()
    s = np.asarray(scores.numpy())
    assert ((s >= 0) & (s <= 1)).all()
