"""Optimizer numerical parity vs hand-computed reference updates."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu import optimizer as opt


def _one_param_program(optimizer, w0):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("x", [2], append_batch_size=False)
        w = main.global_block.create_parameter("w", [2], dtype="float32")
        sb = startup.global_block
        sv = sb.create_var(name="w", shape=[2], dtype="float32", persistable=True)
        from paddle_tpu.initializer import NumpyArrayInitializer

        NumpyArrayInitializer(np.asarray(w0, "float32"))(sv, sb)
        y = layers.elementwise_mul(x, w)
        loss = layers.mean(y)
        optimizer.minimize(loss)
    return main, startup, loss


def _run_steps(optimizer, w0, xs):
    main, startup, loss = _one_param_program(optimizer, w0)
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    for x in xs:
        exe.run(main, feed={"x": np.asarray(x, "float32")}, scope=scope, fetch_list=[loss])
    return np.asarray(scope.get_var("w"))


def test_sgd_exact():
    # loss = mean(x*w) -> dw = x/2
    w = _run_steps(opt.SGDOptimizer(0.1), [1.0, 2.0], [[2.0, 4.0]])
    np.testing.assert_allclose(w, [1.0 - 0.1 * 1.0, 2.0 - 0.1 * 2.0], rtol=1e-6)


def test_adam_matches_reference_update():
    """Reference Adam (adam_op.h): correction uses beta_pow^t at step t."""
    lr, b1, b2, eps = 0.1, 0.9, 0.999, 1e-8
    w = np.array([1.0, 1.0], "float32")
    m = np.zeros(2)
    v = np.zeros(2)
    b1p, b2p = b1, b2
    xs = [[1.0, 1.0], [2.0, 2.0], [0.5, 1.5]]
    for x in xs:
        g = np.asarray(x) / 2.0
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2p) / (1 - b1p)
        w = w - lr_t * m / (np.sqrt(v) + eps)
        b1p *= b1
        b2p *= b2
    got = _run_steps(opt.AdamOptimizer(lr, beta1=b1, beta2=b2, epsilon=eps), [1.0, 1.0], xs)
    np.testing.assert_allclose(got, w, rtol=1e-5)


def test_momentum_matches_reference_update():
    lr, mu = 0.1, 0.9
    w = np.array([1.0, 2.0], "float32")
    vel = np.zeros(2)
    xs = [[2.0, 4.0], [1.0, 1.0]]
    for x in xs:
        g = np.asarray(x) / 2.0
        vel = mu * vel + g
        w = w - lr * vel
    got = _run_steps(opt.MomentumOptimizer(lr, mu), [1.0, 2.0], xs)
    np.testing.assert_allclose(got, w, rtol=1e-6)


def test_adamw_decoupled_decay():
    """AdamW multiplies param by (1 - lr*coeff) before the adam update."""
    lr, b1, b2, eps, wd = 0.1, 0.9, 0.999, 1e-8, 0.01
    w = np.array([1.0, 1.0], "float64")
    m = np.zeros(2)
    v = np.zeros(2)
    b1p, b2p = b1, b2
    xs = [[1.0, 1.0], [3.0, 1.0]]
    for x in xs:
        g = np.asarray(x) / 2.0
        w = w * (1 - lr * wd)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2p) / (1 - b1p)
        w = w - lr_t * m / (np.sqrt(v) + eps)
        b1p *= b1
        b2p *= b2
    got = _run_steps(opt.AdamWOptimizer(lr, weight_decay=wd, beta1=b1, beta2=b2, epsilon=eps), [1.0, 1.0], xs)
    np.testing.assert_allclose(got, w, rtol=1e-5)


def test_lr_scheduler_updates_scope():
    from paddle_tpu.optimizer_lr import StepDecay

    sched = StepDecay(0.1, step_size=2, gamma=0.5)
    o = opt.SGDOptimizer(sched)
    main, startup, loss = _one_param_program(o, [1.0, 1.0])
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    exe.run(main, feed={"x": np.ones(2, "float32")}, scope=scope)
    assert abs(o.get_lr() - 0.1) > -1  # bound lr var exists
    sched.step()
    sched.step()
    # scheduler wrote the decayed value into the scope var
    import numpy as _np

    # note: set_lr writes to global scope by default; write into test scope
    o.set_lr(sched.last_lr, scope=scope)
    lrv = float(np.asarray(scope.get_var(o._lr_var.name))[0])
    assert abs(lrv - 0.05) < 1e-7


def test_l2_regularization_adds_decay():
    from paddle_tpu.regularizer import L2Decay

    lr, coeff = 0.1, 0.5
    w0 = np.array([1.0, 2.0], "float32")
    x = np.array([2.0, 4.0], "float32")
    g = x / 2 + coeff * w0
    expect = w0 - lr * g
    got = _run_steps(
        opt.SGDOptimizer(lr, regularization=L2Decay(coeff)), w0.tolist(), [x.tolist()]
    )
    np.testing.assert_allclose(got, expect, rtol=1e-6)
