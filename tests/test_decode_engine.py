"""Decode engine (paddle_tpu.serving.decode): paged KV cache,
continuous batching, streaming, deadlines, sampling determinism, and
multi-replica scale-out.  (tests/test_decode.py was already taken by
the beam-search text decoder.)

The load-bearing test is the prefix-cache ORACLE: decode-with-cache
logits must be BITWISE equal to a full recompute of the whole prefix
at every generated step — prefill and decode share one masked-softmax
formulation at one width, so any cache bug (wrong page, wrong offset,
stale entry) shows up as a bit difference.
"""
import json
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import serving
from paddle_tpu.monitor import stat_get
from paddle_tpu.observe.histogram import histogram
from paddle_tpu.serving.buckets import prefill_bucket_grid
from paddle_tpu.serving.decode import DecodeConfig, DecodeEngine, \
    TransformerLM
from paddle_tpu.serving.kv_cache import CacheConfig, PageAllocator

VOCAB = 61  # prime-ish: catches transposed vocab/d_model bugs


@pytest.fixture(scope="module")
def model_and_weights():
    import jax

    model = TransformerLM(vocab_size=VOCAB, d_model=32, num_layers=2,
                          num_heads=2, max_seq_len=256)
    weights = model.init_weights(jax.random.PRNGKey(7))
    return model, weights


def make_engine(model_and_weights, **cfg_kw):
    model, weights = model_and_weights
    kw = dict(slots=2, max_seq_len=64, page_size=8, max_new_tokens=8)
    kw.update(cfg_kw)
    return DecodeEngine(model, weights, DecodeConfig(**kw))


# -- kv cache plumbing ----------------------------------------------------


def test_page_allocator_alloc_free_exhaust():
    a = PageAllocator(8)  # pages 1..7 allocatable
    assert a.num_free == 7
    p1 = a.alloc(3)
    assert len(p1) == 3 and 0 not in p1
    assert a.alloc(5) is None  # atomic: nothing taken on failure
    assert a.num_free == 4
    p2 = a.alloc(4)
    assert a.num_free == 0 and set(p1) | set(p2) == set(range(1, 8))
    a.free(p1)
    assert a.num_free == 3
    a.free([0])  # the trash page is never pooled
    assert a.num_free == 3


def test_cache_config_validation():
    with pytest.raises(ValueError):
        CacheConfig(2, 2, 16, 4, max_seq_len=65, page_size=8)
    c = CacheConfig(2, 2, 16, num_slots=4, max_seq_len=64, page_size=8)
    assert c.pages_per_slot == 8
    assert c.num_pages == 4 * 8 + 1  # default pool + trash page
    assert c.pages_for(1) == 1 and c.pages_for(9) == 2
    assert c.cache_bytes() == 2 * 2 * 33 * 8 * 2 * 16 * 4


def test_prefill_bucket_grid():
    assert prefill_bucket_grid(64, 8) == (8, 16, 32, 64)
    assert prefill_bucket_grid(48, 16) == (16, 32, 48)


# -- pallas kernel --------------------------------------------------------


def test_paged_attention_pallas_interpret_matches_reference():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas_decode_attention import \
        paged_decode_attention

    rs = np.random.RandomState(0)
    s, h, d, pool, page, pps = 4, 2, 16, 9, 8, 4
    q = jnp.asarray(rs.randn(s, h, d).astype("f4"))
    kp = jnp.asarray(rs.randn(pool, page, h, d).astype("f4"))
    vp = jnp.asarray(rs.randn(pool, page, h, d).astype("f4"))
    table = jnp.asarray(rs.randint(1, pool, (s, pps)).astype("i4"))
    # edge lengths: page-boundary, partial page, full table, one token
    lengths = jnp.asarray(np.array([8, 17, 32, 1], "i4"))
    ref = paged_decode_attention(q, kp, vp, table, lengths,
                                 use_pallas="never")
    pal = paged_decode_attention(q, kp, vp, table, lengths,
                                 use_pallas="always", interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(pal),
                               rtol=1e-5, atol=1e-5)


# -- THE oracle: cached decode == full recompute, bitwise -----------------


def test_decode_bitwise_equals_full_recompute_every_step(
        model_and_weights):
    eng = make_engine(model_and_weights, slots=3).start()
    try:
        prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [11] * 17]
        reqs = [eng.submit(p, max_new_tokens=6, record_logits=True,
                           seed=i) for i, p in enumerate(prompts)]
        outs = [r.result(timeout=120) for r in reqs]
    finally:
        eng.stop()
    for p, r, out in zip(prompts, reqs, outs):
        assert len(out) == 6 and len(r.logits_trace) == 6
        for t in range(len(out)):
            oracle = eng.recompute_logits(p + out[:t])
            assert np.array_equal(oracle, r.logits_trace[t]), (
                f"decode-with-cache logits diverged from the full "
                f"recompute at step {t} (max diff "
                f"{np.abs(oracle - r.logits_trace[t]).max()})")


def test_batch_composition_invariance(model_and_weights):
    """A request's (greedy) tokens must not depend on what else is in
    the slot batch — the continuous-batching correctness property."""
    eng = make_engine(model_and_weights, slots=1).start()
    try:
        solo = eng.generate([5, 4, 3], max_new_tokens=5)
    finally:
        eng.stop()
    eng = make_engine(model_and_weights, slots=3).start()
    try:
        # same request staggered among unrelated neighbors
        others = [eng.submit([7, 7, 7, 7], max_new_tokens=8, seed=50),
                  eng.submit([1] * 9, max_new_tokens=8, seed=51)]
        joined = eng.generate([5, 4, 3], max_new_tokens=5)
        for o in others:
            o.result(timeout=120)
    finally:
        eng.stop()
    assert joined == solo


# -- continuous batching join/leave ---------------------------------------


def test_join_and_leave_at_step_boundaries(model_and_weights):
    """Short requests submitted while a long one is mid-flight must
    complete BEFORE it (slots join a running batch; finished slots
    free immediately — no group barrier)."""
    eng = make_engine(model_and_weights, slots=2, max_seq_len=128,
                      max_new_tokens=64).start()
    done_order = []
    try:
        long_req = eng.submit([3, 1], max_new_tokens=60)
        # wait until the long request is actually decoding
        for _ in long_req.tokens(timeout=60):
            break
        short1 = eng.submit([2, 2], max_new_tokens=3)
        short1.result(timeout=60)
        done_order.append("short1")
        if long_req.done():
            pytest.skip("machine too fast: long request finished first")
        # leave: short1's slot freed mid-flight; a second short joins
        short2 = eng.submit([4, 4], max_new_tokens=3)
        short2.result(timeout=60)
        done_order.append("short2")
        long_req.result(timeout=120)
        done_order.append("long")
    finally:
        eng.stop()
    assert done_order == ["short1", "short2", "long"]


def test_admission_blocks_on_pages_not_slots(model_and_weights):
    """A shared page pool smaller than slots*max_seq exercises real
    paging pressure: the second request waits for pages, then runs.
    prefix_cache=False pins the PURE paging semantics (with the prefix
    cache on, released pages are deliberately RETAINED by the index —
    tests/test_decode_prefix_spec.py covers that accounting)."""
    # pool: trash + 6 pages of 8 = 48 positions; each request needs
    # ceil((2+30)/8) = 4 pages, so two can't fit at once
    eng = make_engine(model_and_weights, slots=2, max_seq_len=64,
                      page_size=8, num_pages=7,
                      prefix_cache=False).start()
    blocked0 = stat_get("decode_admission_blocked_pages")
    try:
        r1 = eng.submit([1, 2], max_new_tokens=30)
        r2 = eng.submit([3, 4], max_new_tokens=30)
        out1 = r1.result(timeout=120)
        out2 = r2.result(timeout=120)
    finally:
        eng.stop()
    assert len(out1) == 30 and len(out2) == 30
    assert stat_get("decode_admission_blocked_pages") > blocked0
    assert eng._cache.allocator.num_free == 6  # everything returned


# -- streaming ------------------------------------------------------------


def test_streaming_generator_and_callback_order(model_and_weights):
    eng = make_engine(model_and_weights).start()
    try:
        cb_tokens = []
        req = eng.submit([1, 2, 3], max_new_tokens=6,
                         on_token=cb_tokens.append)
        streamed = list(req.tokens(timeout=60))
        final = req.result(timeout=10)
    finally:
        eng.stop()
    assert streamed == final == cb_tokens
    assert len(final) == 6


def test_streaming_starts_before_completion(model_and_weights):
    """First token arrives while the request is still generating —
    streaming is per-step, not a batch reply at the end."""
    eng = make_engine(model_and_weights, max_seq_len=128,
                      max_new_tokens=64).start()
    try:
        req = eng.submit([1, 2], max_new_tokens=40)
        it = req.tokens(timeout=60)
        first = next(it)
        assert isinstance(first, int)
        assert not req.done()  # 39 tokens still to come
        rest = list(it)
    finally:
        eng.stop()
    assert [first] + rest == req.result(timeout=10)


# -- deadlines ------------------------------------------------------------


def test_deadline_reaped_mid_decode_frees_slot(model_and_weights):
    """The satellite contract: a lapsed deadline is honored at the next
    step boundary — the slot frees immediately instead of staying
    pinned for the full max_new_tokens."""
    eng = make_engine(model_and_weights, slots=1, max_seq_len=256,
                      max_new_tokens=200).start()
    reaped0 = stat_get("decode_deadline_exceeded")
    try:
        eng.generate([9, 9], max_new_tokens=2)  # pay the compiles first
        # the on_token sleep paces the engine thread deterministically:
        # ~25 ms/token against a 120 ms deadline -> reaped after a few
        slow = eng.submit([1, 2], max_new_tokens=200, deadline_ms=120,
                          on_token=lambda t: time.sleep(0.025))
        with pytest.raises(serving.DeadlineExceededError):
            slow.result(timeout=60)
        # partial output survives the reap
        assert 0 < len(slow.generated) < 200
        # the slot must be free NOW: a follow-up request completes
        out = eng.generate([5, 5], max_new_tokens=3)
        assert len(out) == 3
        assert eng.free_slots == 1
    finally:
        eng.stop()
    assert stat_get("decode_deadline_exceeded") > reaped0


def test_deadline_reaped_while_queued(model_and_weights):
    eng = make_engine(model_and_weights, slots=1).start()
    try:
        blocker = eng.submit([1], max_new_tokens=8,
                             on_token=lambda t: time.sleep(0.05))
        doomed = eng.submit([2], max_new_tokens=4, deadline_ms=60)
        with pytest.raises(serving.DeadlineExceededError):
            doomed.result(timeout=30)
        assert doomed.generated == []
        blocker.result(timeout=60)
    finally:
        eng.stop()


def test_streaming_deadline_raises_after_partial_yield(
        model_and_weights):
    eng = make_engine(model_and_weights, slots=1, max_seq_len=256,
                      max_new_tokens=200).start()
    try:
        eng.generate([9, 9], max_new_tokens=2)  # pay the compiles first
        req = eng.submit([1, 2], max_new_tokens=200, deadline_ms=120,
                         on_token=lambda t: time.sleep(0.025))
        got = []
        with pytest.raises(serving.DeadlineExceededError):
            for tok in req.tokens(timeout=60):
                got.append(tok)
        assert got == req.generated and len(got) > 0
    finally:
        eng.stop()


# -- admission control ----------------------------------------------------


def test_submit_validation_and_backpressure(model_and_weights):
    eng = make_engine(model_and_weights, slots=1, max_queue=2)
    # not started: queue accepts, nothing drains
    with pytest.raises(serving.RequestTooLargeError):
        eng.submit(list(range(60)), max_new_tokens=10)  # 70 > 64
    with pytest.raises(ValueError):
        eng.submit([])
    eng.submit([1], max_new_tokens=2)
    eng.submit([2], max_new_tokens=2)
    with pytest.raises(serving.QueueFullError):
        eng.submit([3], max_new_tokens=2)
    eng.start()
    try:
        pass
    finally:
        eng.stop(drain=True)  # drains the two queued requests
    with pytest.raises(serving.ServerClosedError):
        eng.submit([4])


def test_unsatisfiable_page_reservation_rejected_at_submit(
        model_and_weights):
    """A reservation the pool can NEVER cover must be rejected at
    submit: queued, it would head-of-line-block the engine forever (no
    finish can free enough pages) and hang stop(drain=True)."""
    # usable pool: 4 pages of 8 = 32 positions; slot capacity is 64
    eng = make_engine(model_and_weights, slots=2, max_seq_len=64,
                      page_size=8, num_pages=5)
    with pytest.raises(serving.RequestTooLargeError, match="pages"):
        eng.submit([1, 2], max_new_tokens=40)  # needs 6 > 4 pages
    # the boundary case still fits and completes
    eng.start()
    try:
        out = eng.generate([1, 2], max_new_tokens=30)
        assert len(out) == 30
    finally:
        eng.stop()


def test_recompute_oracle_safe_while_engine_serving(model_and_weights):
    """The oracle runs on throwaway page pools, so calling it from a
    client thread must not race the engine thread's donating step."""
    eng = make_engine(model_and_weights, slots=1, max_seq_len=256,
                      max_new_tokens=200).start()
    try:
        eng.generate([9, 9], max_new_tokens=2)  # pay the compiles
        req = eng.submit([1, 2], max_new_tokens=60,
                         on_token=lambda t: time.sleep(0.005))
        for _ in range(10):  # concurrent with live decode steps
            eng.recompute_logits([3, 1, 4])
        out = req.result(timeout=120)
    finally:
        eng.stop()
    assert len(out) == 60  # no step died on a deleted/donated buffer


def test_stop_without_drain_cancels(model_and_weights):
    eng = make_engine(model_and_weights, slots=1)
    r1 = eng.submit([1], max_new_tokens=4)
    eng.stop(drain=False)
    with pytest.raises(serving.ServerClosedError):
        r1.result(timeout=5)


# -- sampling determinism (satellite) -------------------------------------


def test_sampling_filters_unit():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.sampling_ops import (filter_top_k_top_p,
                                             sample_tokens)

    rs = np.random.RandomState(3)
    logits = jnp.asarray(rs.randn(5, 17).astype("f4"))
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(5))
    ids = np.argsort(np.asarray(logits), axis=-1)

    filt = np.asarray(filter_top_k_top_p(
        logits, jnp.full((5,), 3, jnp.int32), jnp.ones((5,))))
    assert ((filt > -np.inf).sum(-1) == 3).all()
    assert (np.take_along_axis(filt, ids[:, -3:], -1) > -np.inf).all()

    # top_k=1 and near-zero top_p both collapse to greedy
    g = np.asarray(logits).argmax(-1)
    t1 = sample_tokens(keys, logits, jnp.ones((5,)),
                       jnp.full((5,), 1, jnp.int32), jnp.ones((5,)))
    t2 = sample_tokens(keys, logits, jnp.ones((5,)),
                       jnp.zeros((5,), jnp.int32), jnp.full((5,), 1e-6))
    t3 = sample_tokens(keys, logits, jnp.zeros((5,)),
                       jnp.zeros((5,), jnp.int32), jnp.ones((5,)))
    assert (np.asarray(t1) == g).all()
    assert (np.asarray(t2) == g).all()
    assert (np.asarray(t3) == g).all()
    # explicit key thread: same key -> same draw, jit-stable
    jit = jax.jit(sample_tokens)
    a = jit(keys, logits, jnp.ones((5,)), jnp.full((5,), 8, jnp.int32),
            jnp.full((5,), 0.9))
    b = jit(keys, logits, jnp.ones((5,)), jnp.full((5,), 8, jnp.int32),
            jnp.full((5,), 0.9))
    assert (np.asarray(a) == np.asarray(b)).all()


def test_two_replicas_same_seed_emit_identical_tokens(
        model_and_weights):
    """The PR 7 sharding-invariant-RNG guarantee carried to serving:
    stochastic sampling is keyed by request seed + token index only,
    so replica choice, slot index, and batch neighbors cannot change
    a request's tokens."""
    kw = dict(max_new_tokens=8, temperature=1.0, top_k=7, top_p=0.95,
              seed=123)
    eng_a = make_engine(model_and_weights, slots=2).start()
    try:
        out_a = eng_a.generate([4, 5, 6], **kw)
    finally:
        eng_a.stop()
    eng_b = make_engine(model_and_weights, slots=3).start()
    try:
        # occupy slot 0 first so the same request lands on a DIFFERENT
        # slot with different neighbors on replica B
        other = eng_b.submit([9] * 5, max_new_tokens=8, seed=999)
        out_b = eng_b.generate([4, 5, 6], **kw)
        other.result(timeout=120)
    finally:
        eng_b.stop()
    assert out_a == out_b
    assert len(out_a) == 8


# -- executor persistent entry --------------------------------------------


def test_executor_run_persistent_state_stays_on_device():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.framework.scope import Scope, is_device_array

    scope = Scope()
    scope.set_var("acc", jnp.zeros((4,), jnp.float32))
    exe = pt.Executor(pt.CPUPlace())

    @jax.jit
    def step(state, delta):
        (acc,) = state
        acc = acc + delta
        return (jnp.sum(acc),), (acc,)

    d0 = stat_get("executor_steps_dispatched")
    exe.run_persistent(step, ("acc",), args=(jnp.ones((4,)),),
                       scope=scope)
    (total,) = exe.run_persistent(step, ("acc",),
                                  args=(jnp.ones((4,)),), scope=scope)
    assert float(total) == 8.0
    acc = scope.get_var("acc")
    assert is_device_array(acc)  # never round-tripped to host
    np.testing.assert_array_equal(np.asarray(acc), np.full((4,), 2.0))
    assert stat_get("executor_steps_dispatched") == d0 + 2
    with pytest.raises(KeyError):
        exe.run_persistent(step, ("missing",), scope=scope)


# -- throughput: cache, not recompute -------------------------------------


def test_per_token_cost_flat_as_sequence_grows(model_and_weights):
    """8x more generated tokens must cost ~8x the wall time (cached
    decode: O(1) per token).  A prefix-recompute engine would be ~8x
    per-token slower at the long length; the 2.5x bound leaves room
    for CPU timing noise while still refuting recompute."""
    eng = make_engine(model_and_weights, slots=1, max_seq_len=256,
                      max_new_tokens=200).start()
    try:
        eng.generate([1, 2], max_new_tokens=140)  # warm every compile

        t0 = time.monotonic()
        eng.generate([1, 2], max_new_tokens=16)
        per_tok_short = (time.monotonic() - t0) / 16

        t0 = time.monotonic()
        eng.generate([1, 2], max_new_tokens=128)
        per_tok_long = (time.monotonic() - t0) / 128
    finally:
        eng.stop()
    assert per_tok_long < 2.5 * per_tok_short, (
        f"per-token cost grew {per_tok_long / per_tok_short:.2f}x over "
        f"an 8x longer generation — cache is not being reused")


# -- open-loop load smoke (capped for tier-1) -----------------------------


def test_poisson_open_loop_smoke(model_and_weights):
    rs = np.random.RandomState(0)
    eng = make_engine(model_and_weights, slots=4).start()
    tok0 = stat_get("decode_tokens_total")
    ttft0 = histogram("ttft_seconds").count
    try:
        reqs = []
        for i in range(12):
            plen = int(rs.randint(1, 12))
            reqs.append(eng.submit(
                list(rs.randint(0, VOCAB, plen)),
                max_new_tokens=int(rs.randint(2, 8)), seed=i))
            time.sleep(float(rs.exponential(0.01)))  # open loop
        outs = [r.result(timeout=120) for r in reqs]
    finally:
        eng.stop()
    produced = sum(len(o) for o in outs)
    assert all(outs)
    assert stat_get("decode_tokens_total") - tok0 == produced
    assert histogram("ttft_seconds").count - ttft0 == len(reqs)
    # the decode series must be on the Prometheus exposition
    from paddle_tpu.observe.histogram import prometheus_text

    text = prometheus_text()
    for series in ("decode_tokens_total", "decode_slot_occupancy",
                   "ttft_seconds", "tpot_seconds"):
        assert series in text, series


# -- multi-replica server -------------------------------------------------


def test_decode_server_least_loaded_dispatch_and_stats(
        model_and_weights):
    model, weights = model_and_weights
    cfg = DecodeConfig(slots=1, max_seq_len=64, page_size=8,
                       max_new_tokens=6)
    srv = serving.DecodeServer(model, weights, cfg, replicas=2,
                               http_port=0).start()
    try:
        # 2 one-slot replicas + slow-paced tokens: concurrent requests
        # must spread across BOTH replicas
        reqs = [srv.submit([i + 1], max_new_tokens=4,
                           on_token=lambda t: time.sleep(0.01))
                for i in range(4)]
        outs = [r.result(timeout=120) for r in reqs]
        assert all(len(o) == 4 for o in outs)
        st = srv.stats()
        assert st["n_replicas"] == 2
        assert len(st["replicas"]) == 2
        per_replica = [p["tokens_total"] for p in st["replicas"]]
        assert all(t > 0 for t in per_replica), per_replica
        assert st["tokens_total"] == sum(per_replica) == 16

        # per-replica stats over real HTTP
        url = f"http://127.0.0.1:{srv.http_port}"
        via_http = json.loads(
            urllib.request.urlopen(f"{url}/stats", timeout=10).read())
        assert via_http["n_replicas"] == 2
        assert {p["name"] for p in via_http["replicas"]} == \
            {"replica-0", "replica-1"}
        health = json.loads(
            urllib.request.urlopen(f"{url}/health", timeout=10).read())
        assert health["status"] == "ok" and health["replicas"] == 2
        metrics = urllib.request.urlopen(
            f"{url}/metrics", timeout=10).read().decode()
        assert "decode_tokens_total" in metrics
    finally:
        srv.stop()


def test_one_shot_mode_vs_continuous_admission(model_and_weights):
    """continuous=False degrades to group admission (the bench A/B
    baseline): a follow-up request cannot start until the WHOLE group
    finishes, while the continuous engine admits it mid-flight."""
    model, weights = model_and_weights
    cfg = dict(slots=2, max_seq_len=128, max_new_tokens=64)
    eng = DecodeEngine(model, weights, DecodeConfig(**cfg),
                       continuous=False).start()
    try:
        long_r = eng.submit([1, 2], max_new_tokens=50)
        short_r = eng.submit([3, 4], max_new_tokens=2)
        short_r.result(timeout=120)
        third = eng.submit([5, 6], max_new_tokens=2)
        third.result(timeout=120)
        # group mode: the third request could only start after the
        # long request's group fully drained
        assert long_r.done()
    finally:
        eng.stop()
    eng = DecodeEngine(model, weights, DecodeConfig(**cfg),
                       continuous=True).start()
    try:
        long_r = eng.submit([1, 2], max_new_tokens=50)
        for _ in long_r.tokens(timeout=60):
            break
        third = eng.submit([5, 6], max_new_tokens=2)
        third.result(timeout=120)
        assert not long_r.done()  # joined mid-flight, left early
        long_r.result(timeout=120)
    finally:
        eng.stop()
