"""Round-5 carried items: StatRegistry monitor (reference
platform/monitor.h:77), DGC gradient compression (reference
operators/dgc_op.cc + fleet/meta_optimizers/dgc_optimizer.py), and
generic p2p send/recv pairing (collective/send_v2_op.cc,
recv_v2_op.cc)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.monitor import (
    StatRegistry,
    export_stats,
    stat_add,
    stat_get,
    stat_reset,
)
from paddle_tpu.optimizer.static_opt import SGDOptimizer


class TestMonitor:
    def test_stat_add_get_reset(self):
        stat_reset("t_counter")
        stat_add("t_counter", 3)
        stat_add("t_counter")
        assert stat_get("t_counter") == 4
        stat_reset("t_counter")
        assert stat_get("t_counter") == 0

    def test_registry_is_singleton_and_exports_sorted(self):
        assert StatRegistry.instance() is StatRegistry.instance()
        stat_reset()
        stat_add("zz_b", 2)
        stat_add("aa_a", 1)
        snap = dict(export_stats())
        assert snap["zz_b"] == 2 and snap["aa_a"] == 1
        names = [n for n, _ in export_stats()]
        assert names == sorted(names)

    def test_executor_feeds_compile_and_hit_counters(self):
        stat_reset()
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", [4])
            y = layers.fc(x, 2)
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.framework.Scope()
        exe.run(startup, scope=scope)
        feed = {"x": np.zeros((2, 4), "f4")}
        exe.run(main, feed=feed, fetch_list=[y], scope=scope)
        compiles = stat_get("executor_compile")
        assert compiles >= 1
        exe.run(main, feed=feed, fetch_list=[y], scope=scope)
        assert stat_get("executor_cache_hit") >= 1
        assert stat_get("executor_compile") == compiles  # no recompile
        assert stat_get("executor_run") >= 2


def _dgc_oracle(g_seq, m, ratio, shape):
    """Numpy reference of the dgc op over a step sequence."""
    u = np.zeros(shape, "f4")
    v = np.zeros(shape, "f4")
    outs = []
    for g in g_seq:
        u = m * u + g
        v = v + u
        flat = np.abs(v).ravel()
        k = max(1, int(round(ratio * flat.size)))
        thr = np.sort(flat)[::-1][k - 1]
        mask = (np.abs(v) >= thr).astype("f4")
        outs.append(v * mask)
        v = v * (1 - mask)
        u = u * (1 - mask)
    return outs


class TestDGC:
    def test_dgc_strategy_matches_numpy_oracle(self):
        """Three steps of constant-ish grads: the sparsified grad the
        optimizer consumes must match the numpy u/v/top-k recurrence."""
        from paddle_tpu.distributed import fleet

        main, startup = Program(), Program()
        main.random_seed = 3
        from paddle_tpu.framework import unique_name
        from paddle_tpu.initializer import ConstantInitializer
        from paddle_tpu.param_attr import ParamAttr

        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [6])
            y = layers.data("y", [1])
            pred = layers.fc(x, 1, param_attr=ParamAttr(
                initializer=ConstantInitializer(0.0)), bias_attr=False)
            loss = layers.mean(layers.square_error_cost(pred, y))
            strat = fleet.DistributedStrategy()
            strat.dgc = True
            strat.dgc_configs = {"sparsity": [0.5],
                                 "rampup_begin_step": 0}
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(SGDOptimizer(learning_rate=1.0))
            fleet.minimize(loss)
        assert any(op.type == "dgc" for op in main.global_block.ops)

        rng = np.random.RandomState(0)
        X = rng.randn(8, 6).astype("f4")
        Y = np.zeros((8, 1), "f4")
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)

        w_name = [p.name for p in main.all_parameters()][0]
        w_hist = [np.asarray(scope.find_var(w_name).get_tensor()).copy()]
        g_seq = []
        for _ in range(3):
            # grad of mean((x@w - 0)^2) wrt w at current w
            w = w_hist[-1]
            pred_np = X @ w
            g_seq.append((2.0 / X.shape[0]) * X.T @ (pred_np - Y))
            exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                    scope=scope)
            w_hist.append(
                np.asarray(scope.find_var(w_name).get_tensor()).copy())

        enc_oracle = _dgc_oracle(g_seq, m=0.9, ratio=0.5,
                                 shape=g_seq[0].shape)
        # SGD(lr=1): w_{t+1} = w_t - encoded_t
        for t in range(3):
            np.testing.assert_allclose(
                w_hist[t] - w_hist[t + 1], enc_oracle[t],
                rtol=1e-4, atol=1e-5)

    def test_dgc_pre_rampup_is_pure_passthrough(self):
        """Before rampup_begin_step the op is an early return (reference
        dgc_op.h): dense grad through, U/V untouched — so the first
        ENGAGED step must match an oracle whose accumulators start from
        zero.  The old behavior (warmup momentum accumulated into U
        during passthrough) double-applies those gradients at
        engagement."""
        from paddle_tpu.distributed import fleet

        main, startup = Program(), Program()
        main.random_seed = 11
        from paddle_tpu.framework import unique_name
        from paddle_tpu.initializer import ConstantInitializer
        from paddle_tpu.param_attr import ParamAttr

        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [6])
            y = layers.data("y", [1])
            pred = layers.fc(x, 1, param_attr=ParamAttr(
                initializer=ConstantInitializer(0.0)), bias_attr=False)
            loss = layers.mean(layers.square_error_cost(pred, y))
            strat = fleet.DistributedStrategy()
            strat.dgc = True
            # the step counter increments BEFORE the dgc op, so run t
            # sees step=t+1: rampup_begin_step=3 -> runs 0,1 pass
            # through, run 2 onward engages
            strat.dgc_configs = {"sparsity": [0.5],
                                 "rampup_begin_step": 3}
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(SGDOptimizer(learning_rate=1.0))
            fleet.minimize(loss)

        rng = np.random.RandomState(4)
        X = rng.randn(8, 6).astype("f4")
        Y = np.zeros((8, 1), "f4")
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)

        w_name = [p.name for p in main.all_parameters()][0]
        w_hist = [np.asarray(scope.find_var(w_name).get_tensor()).copy()]
        g_seq = []
        for _ in range(4):
            w = w_hist[-1]
            g_seq.append((2.0 / X.shape[0]) * X.T @ (X @ w - Y))
            exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                    scope=scope)
            w_hist.append(
                np.asarray(scope.find_var(w_name).get_tensor()).copy())

        # passthrough runs 0..1: the DENSE grad reached the optimizer
        for t in (0, 1):
            np.testing.assert_allclose(
                w_hist[t] - w_hist[t + 1], g_seq[t],
                rtol=1e-4, atol=1e-5, err_msg=f"passthrough step {t}")
        # engaged runs 2..3: oracle accumulators start from ZERO (no
        # warmup momentum leaked out of the passthrough phase)
        enc = _dgc_oracle(g_seq[2:], m=0.9, ratio=0.5,
                          shape=g_seq[0].shape)
        for i, t in enumerate((2, 3)):
            np.testing.assert_allclose(
                w_hist[t] - w_hist[t + 1], enc[i],
                rtol=1e-4, atol=1e-5, err_msg=f"engaged step {t}")

    def test_dgc_trains(self):
        from paddle_tpu.distributed import fleet

        main, startup = Program(), Program()
        main.random_seed = 5
        from paddle_tpu.framework import unique_name

        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [8])
            y = layers.data("y", [1])
            h = layers.fc(x, 16, act="relu")
            pred = layers.fc(h, 1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            strat = fleet.DistributedStrategy()
            strat.dgc = True
            strat.dgc_configs = {"sparsity": [0.9],
                                 "rampup_begin_step": 0}
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(SGDOptimizer(learning_rate=0.05))
            fleet.minimize(loss)
        rng = np.random.RandomState(1)
        X = rng.randn(32, 8).astype("f4")
        Y = (X.sum(axis=1, keepdims=True) * 0.3).astype("f4")
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        losses = [float(np.asarray(exe.run(
            main, feed={"x": X, "y": Y}, fetch_list=[loss],
            scope=scope)[0]).item()) for _ in range(30)]
        assert losses[-1] < losses[0] / 2, (losses[0], losses[-1])


class TestSendRecvPair:
    def test_unpaired_recv_is_loud(self):
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", [4])
            out = main.global_block.create_var(
                name="recv_out", shape=[-1, 4], dtype="float32")
            main.global_block.append_op(
                "recv_v2", {}, {"Out": [out.name]},
                {"ring_id": 7, "peer": 0})
        exe = pt.Executor(pt.CPUPlace())
        with pytest.raises((NotImplementedError, RuntimeError),
                           match="send_v2|matching"):
            exe.run(main, feed={"x": np.zeros((2, 4), "f4")},
                    fetch_list=[out])

    def test_paired_send_recv_single_device_identity(self):
        """With no mesh axis in scope the pair degenerates to identity
        (reference nranks==1 behavior) — proves the pairing plumbing."""
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", [4])
            out = main.global_block.create_var(
                name="recv_out2", shape=[-1, 4], dtype="float32")
            main.global_block.append_op(
                "send_v2", {"X": [x.name]}, {},
                {"ring_id": 3, "peer": 1})
            main.global_block.append_op(
                "recv_v2", {}, {"Out": [out.name]},
                {"ring_id": 3, "peer": 0})
        exe = pt.Executor(pt.CPUPlace())
        a = np.arange(8, dtype="f4").reshape(2, 4)
        got = exe.run(main, feed={"x": a}, fetch_list=[out])[0]
        np.testing.assert_allclose(np.asarray(got), a)

    def test_partial_send_recv_chunk(self):
        """partial_send transmits the id-th of num flat chunks
        (reference partial_send_op.cc); single-device identity path."""
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", [8])
            out = main.global_block.create_var(
                name="precv_out", shape=[-1], dtype="float32")
            main.global_block.append_op(
                "partial_send", {"X": [x.name]}, {},
                {"ring_id": 5, "peer": 1, "num": 2, "id": 1})
            main.global_block.append_op(
                "partial_recv", {}, {"Out": [out.name]},
                {"ring_id": 5, "peer": 0, "num": 2, "id": 1})
        exe = pt.Executor(pt.CPUPlace())
        a = np.arange(16, dtype="f4").reshape(2, 8)
        got = np.asarray(exe.run(main, feed={"x": a},
                                 fetch_list=[out])[0])
        # reference contract: chunk id lands at its offset in the
        # FULL-size buffer, other slots zero
        want = np.zeros(16, "f4")
        want[8:] = a.ravel()[8:]
        np.testing.assert_allclose(got, want)
