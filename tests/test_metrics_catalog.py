"""Metrics catalog drift gate (observe/metrics_catalog.py + METRICS.md).

Two invariants: (1) the checked-in METRICS.md is exactly what the
catalog rules generate — editing one without the other fails tier-1;
(2) every series a real process exports on ``/metrics`` matches a
catalog rule.  The coverage scrape runs in a SUBPROCESS with a
representative slice of the framework exercised — the in-process test
registry is polluted by every synthetic stat name other tests mint
(``aa_a``, ``t_counter``...), which would make the assertion about the
test suite, not the product.
"""
import json
import os
import subprocess
import sys

import paddle_tpu as pt  # noqa: F401 - conftest backend setup
from paddle_tpu.observe import metrics_catalog as mc

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_checked_in_catalog_matches_rules():
    path = os.path.join(ROOT, "METRICS.md")
    assert os.path.isfile(path), "METRICS.md missing — run " \
        "python -m paddle_tpu.observe.metrics_catalog --write"
    assert mc.check_file(path), \
        "METRICS.md drifted from observe/metrics_catalog.py RULES — " \
        "regenerate with python -m paddle_tpu.observe.metrics_catalog " \
        "--write"


def test_rules_cover_statically_registered_names():
    """Every literal stat name in the source tree has a catalog row
    (cheap static half of the coverage gate; the subprocess scrape
    below covers the dynamic names)."""
    import re

    pat = re.compile(r'stat_(?:add|set|max|time)\("([a-z0-9_]+)"')
    missing = set()
    for dirpath, _dirs, files in os.walk(
            os.path.join(ROOT, "paddle_tpu")):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                for name in pat.findall(f.read()):
                    if mc.lookup(name) is None:
                        missing.add(name)
    assert not missing, f"stats without a catalog rule: {sorted(missing)}"


def test_lookup_first_match_and_units():
    assert mc.lookup("step_time_seconds").type == "histogram"
    assert mc.lookup("executor_steps_drained").subsystem == "executor"
    assert mc.lookup("zz_not_a_metric") is None
    assert mc.unit_of("phase_compute_seconds_micro") == \
        "microseconds (int)"
    assert mc.unit_of("comm_exposed_share_ppm") == "parts-per-million"
    assert mc.unit_of("executor_steps_drained") == "count"


_SCRAPE_SCRIPT = r"""
import json
import numpy as np
import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.optimizer import MomentumOptimizer
from paddle_tpu.observe import (histogram, phases, prometheus_text,
                                profiler_capture, slo, stat_time)

# exercise a representative slice: train steps (executor/pass/phase
# stats), SLO gauges, request-path histograms
main, startup = Program(), Program()
main.random_seed = 1
with program_guard(main, startup):
    x = layers.data("x", [16])
    label = layers.data("label", [1], dtype="int64")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(x, 10), label))
    MomentumOptimizer(0.05, 0.9).minimize(loss)
sc = pt.framework.Scope()
exe = pt.Executor(pt.CPUPlace())
exe.run(startup, scope=sc)
rs = np.random.RandomState(0)
for _ in range(3):
    exe.run(main, feed={"x": rs.randn(4, 16).astype("f4"),
                        "label": rs.randint(0, 10, (4, 1)).astype("int64")},
            fetch_list=[loss], scope=sc)
exe.close()
stat_time("ttft_seconds", 0.01)
slo.observe_request({"ttft_s": 0.01, "tpot_s": 0.001, "ok": True})
slo.refresh_gauges()
series = set()
for line in prometheus_text().splitlines():
    if line.startswith("# TYPE "):
        series.add(line.split()[2])
print(json.dumps(sorted(series)))
"""


def test_every_exported_series_has_a_catalog_row():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=ROOT)
    out = subprocess.run(
        [sys.executable, "-c", _SCRAPE_SCRIPT], env=env, cwd=ROOT,
        capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr[-2000:]
    series = json.loads(out.stdout.strip().splitlines()[-1])
    assert len(series) > 20, "scrape produced implausibly few series"
    missing = []
    for m in series:
        assert m.startswith("paddle_tpu_"), m
        if mc.lookup(m[len("paddle_tpu_"):]) is None:
            missing.append(m)
    assert not missing, \
        f"/metrics series without a METRICS.md row: {missing}"
