"""paddle.nn.utils (reference nn/utils/weight_norm_hook.py,
spectral_norm_hook.py, transform_parameters.py) and
paddle.nn.initializer 2.0 spellings."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import dygraph, nn


def test_weight_norm_reparameterizes_and_trains():
    with dygraph.guard():
        lyr = nn.Linear(4, 3)
        w0 = np.asarray(lyr.weight._value).copy()
        nn.utils.weight_norm(lyr, name="weight", dim=0)
        names = set(lyr._parameters)
        assert "weight" not in names and {"weight_g",
                                          "weight_v"} <= names
        x = pt.to_tensor(np.ones((2, 4), "f4"))
        y0 = np.asarray(lyr(x)._value)
        # w = g * v/||v|| reproduces the original weight at init
        ref = x._value @ w0
        np.testing.assert_allclose(
            y0, np.asarray(ref + lyr.bias._value), rtol=1e-5, atol=1e-6)
        # gradients reach the factors
        lyr(x).sum().backward()
        assert lyr._parameters["weight_g"].grad is not None
        assert lyr._parameters["weight_v"].grad is not None


def test_remove_weight_norm_bakes_value():
    with dygraph.guard():
        lyr = nn.Linear(4, 3)
        nn.utils.weight_norm(lyr)
        x = pt.to_tensor(np.ones((2, 4), "f4"))
        y_normed = np.asarray(lyr(x)._value)
        nn.utils.remove_weight_norm(lyr)
        assert "weight" in lyr._parameters
        assert "weight_g" not in lyr._parameters
        np.testing.assert_allclose(np.asarray(lyr(x)._value), y_normed,
                                   rtol=1e-5)


def test_weight_norm_dim_none_is_whole_tensor_norm():
    """dim in (None, -1): one scalar g over the whole tensor (reference
    norm_except_dim with dim=-1); forward still reproduces the original
    weight at init."""
    for dim in (None, -1):
        with dygraph.guard():
            lyr = nn.Linear(4, 3)
            w0 = np.asarray(lyr.weight._value).copy()
            nn.utils.weight_norm(lyr, name="weight", dim=dim)
            g = lyr._parameters["weight_g"]
            assert int(np.prod(g.shape)) == 1, g.shape
            np.testing.assert_allclose(
                float(np.asarray(g._value).reshape(())),
                np.sqrt((w0 * w0).sum() + 1e-12), rtol=1e-6)
            x = pt.to_tensor(np.ones((2, 4), "f4"))
            y = np.asarray(lyr(x)._value)
            ref = np.ones((2, 4), "f4") @ w0 + np.asarray(lyr.bias._value)
            np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)


def test_weight_norm_negative_dim_counts_from_back():
    """dim=-2 on a rank-2 weight == dim 0 (dim % ndim), NOT whole-tensor."""
    with dygraph.guard():
        lyr = nn.Linear(4, 3)
        w0 = np.asarray(lyr.weight._value).copy()
        nn.utils.weight_norm(lyr, name="weight", dim=-2)
        g = np.asarray(lyr._parameters["weight_g"]._value)
        assert g.shape == (4, 1), g.shape  # per-dim-0 magnitudes
        np.testing.assert_allclose(
            g, np.sqrt((w0 * w0).sum(axis=1, keepdims=True) + 1e-12),
            rtol=1e-6)
        x = pt.to_tensor(np.ones((2, 4), "f4"))
        ref = np.ones((2, 4), "f4") @ w0 + np.asarray(lyr.bias._value)
        np.testing.assert_allclose(np.asarray(lyr(x)._value), ref,
                                   rtol=1e-5, atol=1e-6)


def test_spectral_norm_unit_top_singular_value():
    with dygraph.guard():
        lyr = nn.Linear(6, 5)
        nn.utils.spectral_norm(lyr, n_power_iterations=20)
        x = pt.to_tensor(np.eye(6, dtype="f4"))
        lyr(x)  # trigger hook; layer.weight now normalized
        w = np.asarray(lyr.weight._value)
        s = np.linalg.svd(w, compute_uv=False)
        assert abs(s.max() - 1.0) < 1e-3, s.max()


def test_spectral_norm_grad_treats_uv_as_constants():
    """The power-iteration vectors are detached: for L = sum(W/sigma),
    dL/dW must equal 1/sigma - (sum(W)/sigma^2) * u v^T with u, v the
    post-iteration constants (reference spectral_norm_hook semantics)."""
    with dygraph.guard():
        lyr = nn.Linear(6, 5, bias_attr=False)
        W = np.asarray(lyr.weight._value).copy()
        nn.utils.spectral_norm(lyr, n_power_iterations=1)
        x = pt.to_tensor(np.eye(6, dtype="f4"))
        lyr(x).sum().backward()
        got = np.asarray(lyr._parameters["weight_orig"].grad._value)

        # numpy oracle with the SAME u0 (seeded buffer init) and one
        # power iteration, u/v held constant in the differentiation
        eps = 1e-12
        u = np.random.RandomState(0).randn(6).astype("f4")
        v = W.T @ u
        v = v / (np.linalg.norm(v) + eps)
        u = W @ v
        u = u / (np.linalg.norm(u) + eps)
        sigma = u @ W @ v
        want = np.full_like(W, 1.0 / sigma) \
            - (W.sum() / sigma**2) * np.outer(u, v)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_spectral_norm_u_is_persistent_buffer():
    """u rides state_dict (reference registers it as a buffer), so the
    power-iteration state survives save/load instead of restarting."""
    with dygraph.guard():
        lyr = nn.Linear(6, 5)
        nn.utils.spectral_norm(lyr)
        x = pt.to_tensor(np.eye(6, dtype="f4"))
        for _ in range(5):
            lyr(x)  # advance the power iteration
        sd = lyr.state_dict()
        assert "weight_u" in sd
        u_trained = np.asarray(sd["weight_u"]._value).copy()

        lyr2 = nn.Linear(6, 5)
        nn.utils.spectral_norm(lyr2)
        missing, unexpected = lyr2.set_state_dict(sd)
        assert not missing and not unexpected, (missing, unexpected)
        np.testing.assert_allclose(
            np.asarray(lyr2._buffers["weight_u"]._value), u_trained)
        np.testing.assert_allclose(np.asarray(lyr2(x)._value),
                                   np.asarray(lyr(x)._value),
                                   rtol=1e-6)


def test_parameters_vector_roundtrip():
    with dygraph.guard():
        lyr = nn.Linear(3, 2)
        vec = nn.utils.parameters_to_vector(lyr.parameters())
        assert vec.shape == [3 * 2 + 2]
        new = pt.to_tensor(np.arange(8, dtype="f4"))
        nn.utils.vector_to_parameters(new, lyr.parameters())
        np.testing.assert_allclose(
            np.asarray(lyr.weight._value).ravel(), np.arange(6, dtype="f4"))
        np.testing.assert_allclose(np.asarray(lyr.bias._value),
                                   [6.0, 7.0])


def test_nn_initializer_namespace():
    from paddle_tpu.nn import initializer as I

    for cls in (I.Constant, I.Normal, I.Uniform, I.TruncatedNormal,
                I.XavierNormal, I.XavierUniform, I.KaimingNormal,
                I.KaimingUniform, I.Assign):
        assert cls is not None
    v = I.XavierUniform().eager_value((4, 4), "float32",
                                      __import__("jax").random.PRNGKey(0))
    lim = np.sqrt(6.0 / 8)
    assert float(np.abs(np.asarray(v)).max()) <= lim + 1e-6


@pytest.mark.parametrize("shape", [(7, 9, 3, 4), (10, 10, 3, 3),
                                   (5, 7, 5, 2)])
@pytest.mark.parametrize("mode", ["max", "avg"])
def test_adaptive_pool_non_divisible(shape, mode):
    """Arbitrary adaptive pooling sizes (reference AdaptivePool: cell i
    pools [floor(i*I/O), ceil((i+1)*I/O))); torch is the oracle."""
    import torch

    ih, iw, oh, ow = shape
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, ih, iw).astype("f4")
    t = torch.tensor(x)
    ref = (torch.nn.functional.adaptive_max_pool2d(t, (oh, ow))
           if mode == "max" else
           torch.nn.functional.adaptive_avg_pool2d(t, (oh, ow))).numpy()
    with dygraph.guard():
        lyr = (nn.AdaptiveMaxPool2D((oh, ow)) if mode == "max"
               else nn.AdaptiveAvgPool2D((oh, ow)))
        got = np.asarray(lyr(pt.to_tensor(x))._value)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("mode", ["bilinear", "nearest"])
@pytest.mark.parametrize("align_corners", [True, False])
def test_grid_sample_reflection_padding(mode, align_corners):
    """Reflection padding (reference grid_sampler_op.cc); torch is the
    oracle, incl. far-out-of-range coordinates."""
    import torch

    from paddle_tpu.dygraph import run_op
    from paddle_tpu.dygraph.tensor import Tensor

    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 6, 7).astype("f4")
    grid = (rs.rand(2, 5, 4, 2).astype("f4") * 3.0 - 1.5)
    ref = torch.nn.functional.grid_sample(
        torch.tensor(x), torch.tensor(grid), mode=mode,
        padding_mode="reflection", align_corners=align_corners).numpy()
    with dygraph.guard():
        out = run_op("grid_sampler",
                     {"X": Tensor(x), "Grid": Tensor(grid)},
                     {"mode": mode, "padding_mode": "reflection",
                      "align_corners": align_corners},
                     out_slots=("Output",))["Output"]
    np.testing.assert_allclose(np.asarray(out._value), ref,
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("shape", [(7, 9, 3, 4), (5, 7, 5, 2)])
def test_adaptive_max_pool_with_index_non_divisible(shape):
    """max_pool2d_with_index adaptive non-divisible: values AND flat
    h*w argmax indices match torch's return_indices contract."""
    import torch

    from paddle_tpu.dygraph import run_op
    from paddle_tpu.dygraph.tensor import Tensor

    ih, iw, oh, ow = shape
    rs = np.random.RandomState(1)
    x = rs.randn(2, 3, ih, iw).astype("f4")
    ref, ridx = torch.nn.functional.adaptive_max_pool2d(
        torch.tensor(x), (oh, ow), return_indices=True)
    with dygraph.guard():
        res = run_op("max_pool2d_with_index", {"X": Tensor(x)},
                     {"ksize": [oh, ow], "adaptive": True},
                     out_slots=("Out", "Mask"))
    np.testing.assert_allclose(np.asarray(res["Out"]._value),
                               ref.numpy(), rtol=1e-6, atol=1e-7)
    np.testing.assert_array_equal(np.asarray(res["Mask"]._value),
                                  ridx.numpy())


def test_adaptive_max_pool3d_with_index_non_divisible():
    import torch

    from paddle_tpu.dygraph import run_op
    from paddle_tpu.dygraph.tensor import Tensor

    rs = np.random.RandomState(2)
    x = rs.randn(2, 2, 5, 7, 9).astype("f4")
    ref, ridx = torch.nn.functional.adaptive_max_pool3d(
        torch.tensor(x), (2, 3, 4), return_indices=True)
    with dygraph.guard():
        res = run_op("max_pool3d_with_index", {"X": Tensor(x)},
                     {"ksize": [2, 3, 4], "adaptive": True},
                     out_slots=("Out", "Mask"))
    np.testing.assert_allclose(np.asarray(res["Out"]._value),
                               ref.numpy(), rtol=1e-6, atol=1e-7)
    np.testing.assert_array_equal(np.asarray(res["Mask"]._value),
                                  ridx.numpy())
