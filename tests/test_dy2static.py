"""AST-based to_static: data-dependent control flow exports for real.

Parity model: reference dygraph_to_static (program_translator.py,
ifelse_transformer.py, loop_transformer.py,
break_continue_transformer.py) — a dygraph function with python
``if``/``while``/``for`` over tensor values must export a static
program whose cond/while OPS reproduce eager outputs on BOTH branches
and at data-dependent trip counts, through TracedLayer and the
inference Predictor (the VERDICT round-3 'done' criterion).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import dygraph
from paddle_tpu.dygraph import jit as djit
from paddle_tpu.dygraph.tensor import Tensor


def _branch_fn(x):
    if x.mean() > 0:
        y = x * 2.0 + 1.0
    else:
        y = -x
    return y


def test_if_both_branches_export():
    with dygraph.guard():
        xpos = dygraph.to_variable(np.ones((2, 3), "f4"))
        xneg = dygraph.to_variable(-np.ones((2, 3), "f4"))
        eager_pos = np.asarray(_branch_fn(xpos)._value)
        eager_neg = np.asarray(_branch_fn(xneg)._value)

        # trace on the POSITIVE input only
        _, tl = djit.TracedLayer.trace(_branch_fn, [xpos])
        ops = [op.type for op in tl.program.global_block.ops]
        assert "cond_pair" in ops, ops
        np.testing.assert_allclose(np.asarray(tl(xpos)[0]._value), eager_pos)
        np.testing.assert_allclose(np.asarray(tl(xneg)[0]._value), eager_neg)


def test_if_return_form():
    def f(x):
        if x.sum() > 0:
            return x + 10.0
        else:
            return x - 10.0

    with dygraph.guard():
        a = dygraph.to_variable(np.full((2,), 1.0, "f4"))
        b = dygraph.to_variable(np.full((2,), -1.0, "f4"))
        _, tl = djit.TracedLayer.trace(f, [a])
        np.testing.assert_allclose(np.asarray(tl(a)[0]._value), [11., 11.])
        np.testing.assert_allclose(np.asarray(tl(b)[0]._value),
                                   [-11., -11.])


def test_while_data_dependent_trip_count():
    def f(x):
        # double until the sum crosses 100: trip count depends on data
        while x.sum() < 100.0:
            x = x * 2.0
        return x

    with dygraph.guard():
        a = dygraph.to_variable(np.full((4,), 1.0, "f4"))   # 5 doublings
        b = dygraph.to_variable(np.full((4,), 30.0, "f4"))  # 1 doubling
        c = dygraph.to_variable(np.full((4,), 99.0, "f4"))  # 0 doublings?
        eager = [np.asarray(f(dygraph.to_variable(
            np.asarray(t._value).copy()))._value) for t in (a, b, c)]
        _, tl = djit.TracedLayer.trace(f, [a])
        ops = [op.type for op in tl.program.global_block.ops]
        assert "while" in ops, ops
        for t, e in zip((a, b, c), eager):
            np.testing.assert_allclose(np.asarray(tl(t)[0]._value), e)


def test_for_range_with_break():
    def f(x):
        acc = x * 0.0
        for i in range(10):
            acc = acc + x
            if acc.sum() > 50.0:
                break
        return acc

    with dygraph.guard():
        small = dygraph.to_variable(np.full((2,), 1.0, "f4"))  # never breaks
        big = dygraph.to_variable(np.full((2,), 30.0, "f4"))   # breaks at 1
        eager_small = np.asarray(f(small)._value)
        eager_big = np.asarray(f(big)._value)
        _, tl = djit.TracedLayer.trace(f, [small])
        np.testing.assert_allclose(np.asarray(tl(small)[0]._value),
                                   eager_small)
        np.testing.assert_allclose(np.asarray(tl(big)[0]._value), eager_big)


def test_bool_ops_and_not():
    def f(x):
        if (x.mean() > 0) and (x.sum() < 10.0):
            y = x + 1.0
        else:
            y = x - 1.0
        if not (x.mean() > 0):
            y = y * 3.0
        return y

    with dygraph.guard():
        ins = [np.full((2,), v, "f4") for v in (1.0, 20.0, -1.0)]
        eager = [np.asarray(f(dygraph.to_variable(v))._value) for v in ins]
        _, tl = djit.TracedLayer.trace(
            f, [dygraph.to_variable(ins[0])])
        for v, e in zip(ins, eager):
            got = tl(dygraph.to_variable(v))[0]
            np.testing.assert_allclose(np.asarray(got._value), e)


def test_jit_save_load_predictor_roundtrip(tmp_path):
    """The VERDICT criterion: data-dependent branch + loop export via
    jit.save; the loaded Predictor reproduces eager on both branches."""
    from paddle_tpu.hapi.model import InputSpec

    @djit.to_static
    def model(x):
        if x.mean() > 0:
            h = x * 2.0
        else:
            h = x * -3.0
        s = h
        while s.sum() < 64.0:
            s = s * 2.0
        return s

    path = str(tmp_path / "dy2static_model")
    djit.save(model, path,
              input_spec=[Tensor(np.full((2, 2), 0.5, "f4"))])
    loaded = djit.load(path)

    with dygraph.guard():
        for fill in (0.5, -0.25, 5.0):
            x = np.full((2, 2), fill, "f4")
            eager = np.asarray(model._fn(dygraph.to_variable(x))._value)
            got = loaded(dygraph.to_variable(x))
            got = got[0] if isinstance(got, list) else got
            np.testing.assert_allclose(np.asarray(got._value), eager,
                                       rtol=1e-6)


def test_python_control_flow_stays_python():
    """Non-tensor conditions take the plain python path and unroll, as
    the reference's convert shims do."""
    def f(x, n):
        for _ in range(n):
            x = x + 1.0
        if n > 2:
            x = x * 2.0
        return x

    with dygraph.guard():
        x = dygraph.to_variable(np.zeros((2,), "f4"))
        out = f(x, 3)
        np.testing.assert_allclose(np.asarray(out._value), [6.0, 6.0])
        _, tl = djit.TracedLayer.trace(lambda t: f(t, 3), [x])
        np.testing.assert_allclose(np.asarray(tl(x)[0]._value), [6.0, 6.0])


def test_nested_if_converts():
    """Nested ifs must not trip the early-return detector (the inner
    conversion introduces _pt_* defs containing `return`)."""
    def f(x):
        if x.mean() > 0:
            if x.sum() > 10.0:
                y = x * 2.0
            else:
                y = x * 3.0
        else:
            y = -x
        return y

    with dygraph.guard():
        ins = [np.full((2,), v, "f4") for v in (10.0, 1.0, -1.0)]
        eager = [np.asarray(f(dygraph.to_variable(v))._value) for v in ins]
        _, tl = djit.TracedLayer.trace(f, [dygraph.to_variable(ins[0])])
        for v, e in zip(ins, eager):
            np.testing.assert_allclose(
                np.asarray(tl(dygraph.to_variable(v))[0]._value), e)


def test_break_leaves_loop_var_at_breaking_index():
    """Python leaves `i` at the breaking index; the converted loop must
    not run the induction step on the breaking iteration."""
    def g(x):
        k = x * 0.0
        for i in range(10):
            k = k + x
            if k.sum() > 50.0:
                break
        return k + i

    with dygraph.guard():
        big = np.full((2,), 30.0, "f4")
        small = np.full((2,), 1.0, "f4")
        eager_big = np.asarray(g(dygraph.to_variable(big))._value)
        eager_small = np.asarray(g(dygraph.to_variable(small))._value)
        _, tl = djit.TracedLayer.trace(g, [dygraph.to_variable(small)])
        np.testing.assert_allclose(
            np.asarray(tl(dygraph.to_variable(big))[0]._value), eager_big)
        np.testing.assert_allclose(
            np.asarray(tl(dygraph.to_variable(small))[0]._value),
            eager_small)


def test_two_break_sites_nested_guards():
    """A second break firing mid-iteration must skip the statements
    after it (per-region nested guards)."""
    def f(x):
        acc = x * 0.0
        for _ in range(6):
            acc = acc + x
            if acc.sum() > 100.0:
                break
            acc = acc + x
            if acc.sum() > 50.0:
                break
            acc = acc + 1.0
        return acc

    with dygraph.guard():
        ins = [np.full((2,), v, "f4") for v in (1.0, 20.0, 60.0)]
        eager = [np.asarray(f(dygraph.to_variable(v))._value) for v in ins]
        _, tl = djit.TracedLayer.trace(f, [dygraph.to_variable(ins[0])])
        for v, e in zip(ins, eager):
            np.testing.assert_allclose(
                np.asarray(tl(dygraph.to_variable(v))[0]._value), e)


def test_use_prune_keeps_cond_passthrough_producers():
    """Executor.run(use_prune=True) must keep ops producing a cond
    branch's pass-through outputs (regression: _prune_ops dropped them)."""
    def f(x):
        y1 = x * 2.0
        y2 = x * 3.0
        if x.mean() > 0:
            z = y1
        else:
            z = y2
        return z

    with dygraph.guard():
        xv = np.full((2,), 1.0, "f4")
        _, tl = djit.TracedLayer.trace(f, [dygraph.to_variable(xv)])
        exe, scope = tl._ensure_exe()
        out = exe.run(tl.program, feed={tl._feed_names[0]: xv},
                      fetch_list=tl._fetch_names, scope=scope,
                      use_prune=True)
        np.testing.assert_allclose(np.asarray(out[0]), [2.0, 2.0])


def test_early_return_tensor_cond_converts():
    """Round-4 gap (reference return_transformer.py:135): a guard-style
    early return over a TENSOR condition now converts via the return
    flag/value rewrite instead of raising."""
    def f(x):
        if x.mean() > 0:
            return x
        x = x * 2.0
        return x

    with dygraph.guard():
        pos = dygraph.to_variable(np.ones((2,), "f4"))
        neg = dygraph.to_variable(np.full((2,), -1.0, "f4"))
        eager = [np.asarray(f(dygraph.to_variable(
            np.asarray(t._value).copy()))._value) for t in (pos, neg)]
        _, tl = djit.TracedLayer.trace(f, [pos])
        for t, e in zip((pos, neg), eager):
            np.testing.assert_allclose(np.asarray(tl(t)[0]._value), e)


def test_return_inside_while_loop():
    """Return inside a data-dependent while: the return flag folds into
    the loop condition and the value merges through the carry."""
    def f(x):
        while x.sum() < 100.0:
            x = x * 2.0
            if x.mean() > 20.0:
                return x - 1.0
        return x + 0.5

    with dygraph.guard():
        ins = [np.full((4,), v, "f4") for v in (1.0, 30.0, 99.0)]
        eager = [np.asarray(f(dygraph.to_variable(v))._value) for v in ins]
        _, tl = djit.TracedLayer.trace(
            f, [dygraph.to_variable(ins[0])])
        for v, e in zip(ins, eager):
            np.testing.assert_allclose(
                np.asarray(tl(dygraph.to_variable(v))[0]._value), e)


def test_return_inside_for_range_loop():
    def f(x):
        acc = x * 0.0
        for i in range(10):
            acc = acc + x
            if acc.sum() > 50.0:
                return acc * 10.0
        return acc

    with dygraph.guard():
        ins = [np.full((2,), v, "f4") for v in (1.0, 30.0)]
        eager = [np.asarray(f(dygraph.to_variable(v))._value) for v in ins]
        _, tl = djit.TracedLayer.trace(
            f, [dygraph.to_variable(ins[0])])
        for v, e in zip(ins, eager):
            np.testing.assert_allclose(
                np.asarray(tl(dygraph.to_variable(v))[0]._value), e)


def test_statements_after_returning_loop_are_guarded():
    """Code after a loop that may have returned must be skipped when the
    return fired (the not-flag guard cascade)."""
    def f(x):
        for i in range(4):
            x = x + 1.0
            if x.mean() > 3.0:
                return x * 100.0
        x = x - 0.25
        return x

    with dygraph.guard():
        ins = [np.full((2,), v, "f4") for v in (0.0, 5.0)]
        eager = [np.asarray(f(dygraph.to_variable(v))._value) for v in ins]
        _, tl = djit.TracedLayer.trace(
            f, [dygraph.to_variable(ins[0])])
        for v, e in zip(ins, eager):
            np.testing.assert_allclose(
                np.asarray(tl(dygraph.to_variable(v))[0]._value), e)


def test_for_over_tensor_rows_with_list_append():
    """Iterating a tensor yields its rows (ForToWhileTransformer /
    list_transformer roles); appended rows concat back together."""
    from paddle_tpu import tensor as pt_tensor

    def f(x):
        rows = []
        for r in x:
            if r.sum() > 0:
                rows.append(r * 2.0)
            else:
                rows.append(r - 1.0)
        return pt_tensor.stack(rows)

    with dygraph.guard():
        a = np.array([[1.0, 2.0], [-3.0, 1.0], [0.5, -2.0]], "f4")
        eager = np.asarray(f(dygraph.to_variable(a))._value)
        _, tl = djit.TracedLayer.trace(f, [dygraph.to_variable(a)])
        np.testing.assert_allclose(
            np.asarray(tl(dygraph.to_variable(a))[0]._value), eager)


def test_python_guard_early_return_still_traces():
    """`if b is None: return ...` over a PYTHON value is the classic
    forward-signature guard; it must keep tracing (plain python path)."""
    def f(x, b=None):
        if b is None:
            return x * 2.0
        return x + b

    with dygraph.guard():
        x = dygraph.to_variable(np.ones((2,), "f4"))
        _, tl = djit.TracedLayer.trace(lambda t: f(t), [x])
        np.testing.assert_allclose(np.asarray(tl(x)[0]._value), [2.0, 2.0])


def test_layer_forward_hooks_survive_conversion():
    """Trace goes through Layer.__call__, so forward hooks record."""
    from paddle_tpu import nn

    class M(nn.Layer):
        def forward(self, x):
            if x.mean() > 0:
                return x * 2.0
            else:
                return -x

    with dygraph.guard():
        m = M()
        m.register_forward_post_hook(lambda l, i, o: o + 100.0)
        x = dygraph.to_variable(np.ones((2,), "f4"))
        eager = np.asarray(m(x)._value)
        np.testing.assert_allclose(eager, [102.0, 102.0])
        _, tl = djit.TracedLayer.trace(m, [x])
        np.testing.assert_allclose(np.asarray(tl(x)[0]._value), eager)


def test_zero_trip_range_keeps_existing_var():
    def g(x, n):
        k = x * 5.0
        for _ in range(n):
            k = k + 1.0
        return k

    with dygraph.guard():
        x = dygraph.to_variable(np.ones((2,), "f4"))
        # zero-trip range leaves the pre-existing binding untouched
        out = g(x, 0)
        np.testing.assert_allclose(np.asarray(out._value), [5.0, 5.0])
        _, tl = djit.TracedLayer.trace(lambda t: g(t, 0), [x])
        np.testing.assert_allclose(np.asarray(tl(x)[0]._value), [5.0, 5.0])


def test_return_inside_loop_converts():
    """Formerly a loud error; the return rewriter now converts it
    (reference return_transformer.py:135)."""
    def f(x):
        acc = x * 0.0
        for i in range(3):
            acc = acc + x
            if acc.sum() > 1.0:
                return acc
        return acc

    with dygraph.guard():
        ins = [np.full((2,), v, "f4") for v in (1.0, 0.1)]
        eager = [np.asarray(f(dygraph.to_variable(v))._value) for v in ins]
        _, tl = djit.TracedLayer.trace(
            f, [dygraph.to_variable(ins[0])])
        for v, e in zip(ins, eager):
            np.testing.assert_allclose(
                np.asarray(tl(dygraph.to_variable(v))[0]._value), e)


def test_container_for_with_break_stays_python():
    """break under an if inside a python-container loop must not be
    moved into a generated branch function (SyntaxError regression)."""
    def f(x):
        acc = x * 0.0
        for w in [1.0, 2.0, 3.0]:
            acc = acc + x * w
            if float(np.asarray(acc._value).sum()) > 4.0:
                break
        return acc

    with dygraph.guard():
        x = dygraph.to_variable(np.ones((2,), "f4"))
        eager = np.asarray(f(x)._value)
        _, tl = djit.TracedLayer.trace(f, [x])
        np.testing.assert_allclose(np.asarray(tl(x)[0]._value), eager)


def test_container_for_break_still_converts_tensor_ifs():
    """A container loop with a break must STILL convert its tensor-
    conditioned ifs (flag rewrite + real guarded break), so the export
    carries cond ops instead of a baked branch."""
    def f(x):
        acc = x * 0.0
        for w in [1.0, 2.0, 3.0]:
            if acc.mean() > 0.5:
                acc = acc + x * w
            else:
                acc = acc + x * (2.0 * w)
            if float(np.asarray(acc._value).sum()) > 100.0:
                break
        return acc

    with dygraph.guard():
        xs = [np.full((2,), v, "f4") for v in (1.0, -1.0)]
        eager = [np.asarray(f(dygraph.to_variable(v))._value) for v in xs]
        _, tl = djit.TracedLayer.trace(f, [dygraph.to_variable(xs[0])])
        ops = [op.type for op in tl.program.global_block.ops]
        assert "cond_pair" in ops, ops
        for v, e in zip(xs, eager):
            np.testing.assert_allclose(
                np.asarray(tl(dygraph.to_variable(v))[0]._value), e)


def test_static_mode_variable_dispatch():
    """convert shims route framework Variables to layers.cond."""
    from paddle_tpu import layers
    from paddle_tpu.dygraph.dy2static import convert_ifelse
    from paddle_tpu.framework.program import Program, program_guard

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("x", [3])
        pred = layers.reduce_sum(x) > 0.0
        out = convert_ifelse(
            pred, lambda: x * 2.0, lambda: x - 1.0, (), {})
    exe = pt.Executor(pt.CPUPlace())
    o1 = exe.run(main, feed={"x": np.ones((1, 3), "f4")}, fetch_list=[out])
    o2 = exe.run(main, feed={"x": -np.ones((1, 3), "f4")}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(o1[0]), np.full((1, 3), 2.0))
    np.testing.assert_allclose(np.asarray(o2[0]), np.full((1, 3), -2.0))


def test_return_inside_nested_loop():
    """Return from a while nested in a for: the inner break folds into
    the inner loop condition, the fired-flag guard breaks the outer."""
    def f(x):
        for i in range(3):
            while x.sum() < 50.0:
                x = x * 2.0
                if x.mean() > 8.0:
                    return x + 100.0
            x = x + 1.0
        return x

    with dygraph.guard():
        ins = [np.full((4,), v, "f4") for v in (1.0, 30.0, 60.0)]
        eager = [np.asarray(f(dygraph.to_variable(v))._value) for v in ins]
        _, tl = djit.TracedLayer.trace(f, [dygraph.to_variable(ins[0])])
        for v, e in zip(ins, eager):
            np.testing.assert_allclose(
                np.asarray(tl(dygraph.to_variable(v))[0]._value), e,
                rtol=1e-5)


def test_return_in_both_arms_inside_loop():
    def f(x):
        for i in range(4):
            x = x + 1.0
            if x.mean() > 3.0:
                if x.sum() > 20.0:
                    return x * 10.0
                else:
                    return x * -1.0
        return x

    with dygraph.guard():
        ins = [np.full((4,), v, "f4") for v in (0.0, 3.0, 9.0)]
        eager = [np.asarray(f(dygraph.to_variable(v))._value) for v in ins]
        _, tl = djit.TracedLayer.trace(f, [dygraph.to_variable(ins[0])])
        for v, e in zip(ins, eager):
            np.testing.assert_allclose(
                np.asarray(tl(dygraph.to_variable(v))[0]._value), e,
                rtol=1e-5)
