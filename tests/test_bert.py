"""BERT static builder: program builds, trains, and MLM masking is honest.

Reference parity: the transformer dist workload
(python/paddle/fluid/tests/unittests/dist_transformer.py) and
softmax_with_cross_entropy ignore_index semantics
(operators/softmax_with_cross_entropy_op.h).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.text import bert_base_pretrain_program

B, S, V, P = 4, 16, 64, 3


def _feed(rng):
    ids = rng.randint(0, V, (B, S)).astype("int64")
    flat_pos = np.zeros((B * P,), "int64")
    labels = np.zeros((B * P, 1), "int64")
    weights = np.ones((B * P, 1), "float32")
    for b in range(B):
        pos = rng.choice(S, P, replace=False)
        flat_pos[b * P:(b + 1) * P] = b * S + pos
        labels[b * P:(b + 1) * P, 0] = ids[b, pos]
    weights[-1, 0] = 0.0  # one padding prediction slot
    return {
        "input_ids": ids,
        "token_type_ids": np.zeros((B, S), "int64"),
        "pos_ids": np.tile(np.arange(S, dtype="int64"), (B, 1)),
        "input_mask": np.zeros((B, 1, 1, S), "float32"),
        "masked_flat_pos": flat_pos,
        "masked_labels": labels,
        "masked_weights": weights,
        "nsp_labels": rng.randint(0, 2, (B, 1)).astype("int64"),
    }


@pytest.fixture(scope="module")
def tiny_bert():
    main, startup, feeds, loss, opt = bert_base_pretrain_program(
        batch_size=B, seq_len=S, vocab_size=V, hidden=32, n_layers=2,
        n_heads=4, ffn_size=64, dropout_prob=0.0, lr=1e-3,
        max_preds_per_seq=P)
    from paddle_tpu.framework.program import program_guard

    with program_guard(main, startup):
        opt.minimize(loss)
    return main, startup, loss


def test_bert_trains_and_loss_drops(tiny_bert):
    main, startup, loss = tiny_bert
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    feed = _feed(rng)  # same batch every step: loss must drop fast
    losses = [
        float(np.asarray(
            exe.run(main, feed=feed, fetch_list=[loss], scope=scope)[0]
        ).ravel()[0])
        for _ in range(25)
    ]
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] * 0.7, losses
    assert losses[-1] < losses[-2] < losses[0], losses


def test_mlm_ignore_index_masks_loss_and_grad():
    """Positions labelled -1 must contribute zero loss and zero gradient."""
    from paddle_tpu import layers
    from paddle_tpu.framework.backward import append_backward
    from paddle_tpu.framework.program import Program, program_guard

    main, startup = Program(), Program()
    with program_guard(main, startup):
        logits = layers.data("logits", [2, 3, 5], append_batch_size=False)
        logits.stop_gradient = False  # feeds default to stop_gradient
        label = layers.data("label", [2, 3, 1], dtype="int64",
                            append_batch_size=False)
        tok = layers.softmax_with_cross_entropy(logits, label,
                                                ignore_index=-1)
        total = layers.mean(tok)
        append_backward(total)
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(1)
    lg = rng.randn(2, 3, 5).astype("float32")
    lb = np.array([[[1], [-1], [2]], [[-1], [0], [-1]]], dtype="int64")
    tok_v, dlg = exe.run(
        main, feed={"logits": lg, "label": lb},
        fetch_list=[tok, "logits@GRAD"], scope=scope)
    tok_v = np.asarray(tok_v)
    assert tok_v[0, 1, 0] == 0.0 and tok_v[1, 0, 0] == 0.0 and tok_v[1, 2, 0] == 0.0
    # numpy oracle for a live position
    sm = np.exp(lg[0, 0]) / np.exp(lg[0, 0]).sum()
    np.testing.assert_allclose(tok_v[0, 0, 0], -np.log(sm[1]), rtol=1e-5)
    dlg = np.asarray(dlg)
    assert np.all(dlg[0, 1] == 0.0) and np.all(dlg[1, 0] == 0.0) and np.all(dlg[1, 2] == 0.0)
    assert np.any(dlg[0, 0] != 0.0)
