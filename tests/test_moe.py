"""Mixture-of-experts over the 'ep' mesh axis (ShardingPropagationPass
ep seeding + ExpertParallelMetaOptimizer + ops/moe_ops.py).

Tier-1-lean units: router determinism and the GShard slot-priority
rule (the router is RNG-free, so determinism holds under any threefry
partitioning config), capacity-factor drop accounting, plan-time
rejection of ep-sharded consumers outside the routed-FFN family, the
aux-loss gradient path, and the FLAGS_ep_degree mesh-carve validation.

Slow-marked composition matrix, per the dist-test oracle discipline:
ep×dp per-step loss parity <= 1e-4 vs the replicated single-device
oracle (dense execution of the same routed FFN — matched activated
FLOPs by construction), ep×mp×pp compile + collective-ledger keys, and
elastic checkpoint resume across an ep 2->4 retag (bitwise on the
surviving state).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import passes as passes_mod
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import (Program, device_guard,
                                          program_guard)
from paddle_tpu.optimizer import MomentumOptimizer

E, K, DM, FFN = 4, 2, 16, 32


def _softmax_np(logits):
    z = logits - logits.max(axis=-1, keepdims=True)
    ez = np.exp(z)
    return ez / ez.sum(axis=-1, keepdims=True)


def _router_inputs(s=12, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(s, DM).astype(np.float32)
    gw = rs.randn(DM, E).astype(np.float32)
    return x, gw


# ---------------------------------------------------------------------------
# tier-1-lean units (no executor compile)
# ---------------------------------------------------------------------------


class TestRouter:
    def test_topk_selection_deterministic_and_correct(self):
        from paddle_tpu.ops.moe_ops import moe_router_ref

        x, gw = _router_inputs()
        kw = dict(num_experts=E, top_k=K, capacity_factor=2.0)
        c1, a1, l1 = moe_router_ref(x, gw, **kw)
        c2, a2, l2 = moe_router_ref(x, gw, **kw)
        # bitwise-deterministic: same inputs, same combine/aux/load
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
        assert float(a1) == float(a2)
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))

        # each token's nonzero combine experts are exactly its top-k
        # by router logit (softmax is monotone, so logits decide)
        logits = x @ gw
        combine = np.asarray(c1)           # [S, E, C]
        for s in range(x.shape[0]):
            got = set(np.nonzero(combine[s].sum(axis=-1) > 0)[0])
            want = set(np.argsort(-logits[s])[:K])
            assert got == want, (s, got, want)
        # kept gate weights renormalize over the top-k per token
        np.testing.assert_allclose(
            combine.sum(axis=(1, 2)), np.ones(x.shape[0]), atol=1e-5)

    def test_capacity_values(self):
        from paddle_tpu.ops.moe_ops import moe_capacity

        assert moe_capacity(64, 4, 2, 1.25) == 40
        assert moe_capacity(8, 4, 1, 1.0) == 2
        # floor: never zero slots, even at tiny token counts
        assert moe_capacity(1, 64, 1, 0.5) == 1

    def test_capacity_drops_follow_gshard_priority(self):
        """All tokens routed to expert 0 with cap=2: the two lowest
        token indices claim the slots (choice-then-token order), every
        later token is dropped with ZERO combine weight, and the
        balance gauges price the drop fraction in ppm."""
        from paddle_tpu.ops.moe_ops import (moe_balance_gauges,
                                            moe_router_ref)

        s = 8
        x = np.abs(np.random.RandomState(1).randn(s, DM)).astype("f4")
        gw = np.zeros((DM, E), np.float32)
        gw[:, 0] = 1.0                       # every token -> expert 0
        combine, _aux, load = moe_router_ref(
            x, gw, num_experts=E, top_k=1, capacity_factor=1.0)
        combine = np.asarray(combine)        # [S, E, cap=2]
        np.testing.assert_array_equal(np.asarray(load), [2, 0, 0, 0])
        assert (combine[:2].sum(axis=(1, 2)) > 0).all()
        np.testing.assert_array_equal(
            combine[2:], np.zeros_like(combine[2:]))

        g = moe_balance_gauges(load, num_tokens=s, top_k=1,
                               publish=False)
        assert g["moe_dropped_fraction_ppm"] == 750000   # 6/8 dropped
        # one hot expert out of four: mean/max load = 0.25
        assert g["moe_expert_balance_ppm"] == 250000

    def test_aux_loss_gradient_reaches_gate(self):
        """The Switch aux loss must train the ROUTER: its gradient wrt
        the gate weight is finite and nonzero (f is stop-gradient, P is
        not — d(aux)/d(gate) flows through the mean router prob)."""
        import jax

        from paddle_tpu.ops.moe_ops import moe_router_ref

        x, gw = _router_inputs(seed=3)

        def aux_of(g):
            return moe_router_ref(x, g, num_experts=E, top_k=K,
                                  capacity_factor=1.25)[1]

        grad = np.asarray(jax.grad(aux_of)(gw))
        assert np.isfinite(grad).all()
        assert np.abs(grad).max() > 0.0


def _build_moe(use_ep, cf=1.25, seed=1, aux_coeff=0.01):
    from paddle_tpu.distributed import fleet

    main, startup = Program(), Program()
    main.random_seed = seed
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [DM])
        y = layers.data("y", [1])
        h, aux, load = layers.moe_ffn(
            x, num_experts=E, ffn_dim=FFN, top_k=K,
            capacity_factor=cf, name="moe0")
        pred = layers.fc(h, 1, name="head")
        loss = layers.elementwise_add(
            layers.mean(layers.square_error_cost(pred, y)),
            layers.scale(aux, aux_coeff))
        opt = MomentumOptimizer(0.05, 0.9)
        if use_ep:
            strat = fleet.DistributedStrategy()
            strat.expert_parallel = True
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(opt)
            fleet.minimize(loss)
        else:
            opt.minimize(loss)
    return main, startup, loss


def _data(n=32, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, DM).astype("float32")
    Y = (X.sum(axis=1, keepdims=True) * 0.3).astype("float32")
    return X, Y


def _train(main, startup, loss, X, Y, mesh, steps=4, scope=None):
    sc = scope if scope is not None else pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup, scope=sc)
    out = [float(np.asarray(exe.run(
        main, feed={"x": X, "y": Y}, fetch_list=[loss],
        scope=sc)[0]).item()) for _ in range(steps)]
    exe.drain()
    return out, sc, exe


@pytest.fixture
def mesh_dp_ep():
    from paddle_tpu.distributed.parallel_env import (init_parallel_env,
                                                     reset_mesh)

    reset_mesh()
    mesh = init_parallel_env(mesh_shape=[4, 2], axis_names=("dp", "ep"))
    yield mesh
    reset_mesh()


class TestPlanTime:
    def test_plan_stamps_ep_specs(self, mesh_dp_ep):
        main, _, loss = _build_moe(True)
        out = passes_mod.apply_passes(
            main, fetch_names=(loss.name,), feed_names=("x", "y"),
            mesh=mesh_dp_ep)
        plan = out._tp_plan
        assert plan is not None and plan.ep_degree == 2
        # stacked expert carriers shard on the leading (expert) axis;
        # the router gate stays replicated
        assert plan.spec_tuple("moe0.w_1") == ("ep", None, None)
        assert plan.spec_tuple("moe0.w_2") == ("ep", None, None)
        assert plan.spec_tuple("moe0.b_0") == ("ep", None)
        assert plan.spec_tuple("moe0.w_0") == ()
        # optimizer slots inherit the expert sharding
        assert plan.spec_tuple("moe0.w_1_velocity_0") == \
            ("ep", None, None)
        assert passes_mod.has_ep_marks(out)
        moe_ops = [op for op in out.global_block.ops
                   if op.type == "moe_ffn"]
        assert moe_ops and all(
            op.attr(passes_mod.MOE_EP_ATTR) == 2 for op in moe_ops)

    def test_plan_rejects_ep_consumer_outside_ffn_family(
            self, mesh_dp_ep):
        """An op outside the routed-FFN family reading an ep-sharded
        var would silently compute on a 1/ep slice; the strict flow
        walk refuses it at plan time, naming op and var."""
        main, _, loss = _build_moe(True)
        with program_guard(main):
            bad = layers.mean(main.global_block.var("moe0.w_1"))
        with pytest.raises(ValueError,
                           match=r"expert-parallel-sharded var"):
            passes_mod.apply_passes(
                main, fetch_names=(loss.name, bad.name),
                feed_names=("x", "y"), mesh=mesh_dp_ep)

    def test_ep_degree_flag_carve_validation(self):
        """init_parallel_env() must reject bad FLAGS_ep_degree
        factorizations LOUDLY with the axis named — not deep in GSPMD
        with an opaque sharding error."""
        from paddle_tpu.distributed.parallel_env import (
            init_parallel_env, reset_mesh)

        reset_mesh()
        try:
            pt.set_flags({"FLAGS_ep_degree": 3})
            with pytest.raises(ValueError,
                               match=r"FLAGS_ep_degree=3 does not "
                                     r"divide"):
                init_parallel_env()
            # ep x pp over-subscription: 4 x 4 = 16 > 8 devices
            pt.set_flags({"FLAGS_ep_degree": 4, "FLAGS_pp_degree": 4})
            with pytest.raises(ValueError, match=r"exceeds"):
                init_parallel_env()
            # a valid degree carves (dp, ep) out of the 8 devices
            pt.set_flags({"FLAGS_ep_degree": 4, "FLAGS_pp_degree": 0})
            mesh = init_parallel_env()
            assert tuple(mesh.axis_names) == ("dp", "ep")
            assert int(mesh.shape["ep"]) == 4
            assert int(mesh.shape["dp"]) == 2
        finally:
            pt.set_flags({"FLAGS_ep_degree": 0, "FLAGS_pp_degree": 0})
            reset_mesh()


# ---------------------------------------------------------------------------
# slow composition matrix
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestComposition:
    def test_ep_dp_parity_vs_replicated_oracle(self, mesh_dp_ep):
        """Per-step losses of the dp×ep run match the replicated
        single-device oracle within 1e-4 rel, and the expert stack is
        PHYSICALLY sharded (each chip holds E/ep experts)."""
        from paddle_tpu.distributed.parallel_env import (reset_mesh,
                                                         set_mesh)

        X, Y = _data()
        reset_mesh()
        base, _, _ = _train(*_build_moe(False), X, Y, None)
        set_mesh(mesh_dp_ep)
        ep_losses, scope, _ = _train(*_build_moe(True), X, Y,
                                     mesh_dp_ep)
        rel = max(abs(a - b) / max(abs(a), 1e-8)
                  for a, b in zip(base, ep_losses))
        assert rel <= 1e-4, (rel, base, ep_losses)
        w1 = scope.get_var("moe0.w_1")
        shard_shapes = {tuple(s.data.shape)
                        for s in w1.addressable_shards}
        assert shard_shapes == {(E // 2, DM, FFN)}

    def test_ep_mp_pp_compile_and_ledger_keys(self):
        """The full ep×mp×pp composition compiles and trains (moe
        stage 0, Megatron ffn pair stage 1), and the collective ledger
        prices the dispatch/combine all-to-alls — chunked inventories
        mark overlap=True legs the sequential schedule lacks."""
        import jax

        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.parallel_env import (reset_mesh,
                                                         set_mesh)
        from paddle_tpu.observe.phases import collective_inventory

        reset_mesh()
        devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
        mesh = jax.sharding.Mesh(devs, ("ep", "mp", "pp"))
        set_mesh(mesh)
        try:
            main, startup = Program(), Program()
            main.random_seed = 2
            with unique_name.guard(), program_guard(main, startup):
                x = layers.data("x", [DM])
                y = layers.data("y", [1])
                with device_guard("stage:0"):
                    h, aux, _load = layers.moe_ffn(
                        x, num_experts=E, ffn_dim=FFN, top_k=K,
                        capacity_factor=1.25, name="moe0")
                with device_guard("stage:1"):
                    h2 = layers.fc(h, 2 * DM, act="relu",
                                   name="s1_ffn1")
                    h2 = layers.fc(h2, DM, name="s1_ffn2")
                    pred = layers.fc(h2, 1, name="head")
                    loss = layers.elementwise_add(
                        layers.mean(layers.square_error_cost(pred, y)),
                        layers.scale(aux, 0.01))
                strat = fleet.DistributedStrategy()
                strat.expert_parallel = True
                strat.tensor_parallel = True
                strat.pipeline = True
                strat.pipeline_configs = {"micro_batch": 2}
                fleet.init(is_collective=True, strategy=strat)
                fleet.distributed_optimizer(MomentumOptimizer(0.05, 0.9))
                fleet.minimize(loss)

            from paddle_tpu.monitor import stat_get

            before = stat_get("moe_ep_manual_replicated")
            X, Y = _data(n=8)
            losses, _, _ = _train(main, startup, loss, X, Y, mesh,
                                  steps=2)
            assert all(np.isfinite(v) for v in losses)
            # inside the GPipe shard_map the experts run replicated
            # (GSPMD constraints are illegal under manual axes) and
            # the fallback is COUNTED, not silent
            assert stat_get("moe_ep_manual_replicated") > before

            out = passes_mod.apply_passes(
                main, fetch_names=(loss.name,), feed_names=("x", "y"),
                mesh=mesh)
            assert out._tp_plan.ep_degree == 2

            def a2a(chunks):
                blk = out.global_block
                return [e for e in collective_inventory(
                    blk, list(blk.ops), mesh=mesh,
                    tp_plan=out._tp_plan, moe_chunks=chunks)
                    if e["op"] == "ep_alltoall"]

            seq, chunked = a2a(0), a2a(2)
            assert seq and chunked
            for entry in chunked:
                assert set(entry) >= {"id", "op", "dtype", "bytes",
                                      "overlap"}
            assert not any(e["overlap"] for e in seq)
            assert any(e["overlap"] for e in chunked)
        finally:
            reset_mesh()

    def test_elastic_ckpt_resumes_across_ep_retag(self, tmp_path):
        """ep=2 state saves through the ckpt manager and restores into
        an ep=4 mesh bitwise (single-process: fully-addressable arrays
        snapshot as full host values — elastic by construction); the
        resumed run retags P('ep', ...) at the new degree and keeps
        training."""
        from paddle_tpu.ckpt import CheckpointManager
        from paddle_tpu.distributed.parallel_env import (
            init_parallel_env, reset_mesh, set_mesh)

        X, Y = _data()
        reset_mesh()
        mesh2 = init_parallel_env(mesh_shape=[4, 2],
                                  axis_names=("dp", "ep"))
        try:
            _, scope, _ = _train(*_build_moe(True), X, Y, mesh2,
                                 steps=3)
            m = CheckpointManager(str(tmp_path), async_save=False)
            m.save(3, scope=scope)
            m.close()
            w_before = np.asarray(scope.get_var("moe0.w_1"))
            g_before = np.asarray(scope.get_var("moe0.w_0"))
        finally:
            reset_mesh()

        mesh4 = init_parallel_env(mesh_shape=[2, 4],
                                  axis_names=("dp", "ep"))
        try:
            main, startup, loss = _build_moe(True)
            scope2 = pt.framework.Scope()
            exe = pt.Executor(pt.CPUPlace(), mesh=mesh4)
            exe.run(startup, scope=scope2)
            m2 = CheckpointManager(str(tmp_path), async_save=False)
            meta = m2.restore(scope=scope2)
            m2.close()
            assert meta["step"] == 3
            np.testing.assert_array_equal(
                np.asarray(scope2.get_var("moe0.w_1")), w_before)
            np.testing.assert_array_equal(
                np.asarray(scope2.get_var("moe0.w_0")), g_before)

            out = exe.run(main, feed={"x": X, "y": Y},
                          fetch_list=[loss], scope=scope2)
            exe.drain()
            assert np.isfinite(np.asarray(out[0])).all()
            # the retagged plan physically reshards: 1 expert per chip
            w1 = scope2.get_var("moe0.w_1")
            shard_shapes = {tuple(s.data.shape)
                            for s in w1.addressable_shards}
            assert shard_shapes == {(E // 4, DM, FFN)}
        finally:
            reset_mesh()
