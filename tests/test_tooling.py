"""L11 tooling gates: API signature spec + op-registry compat check.

Reference parity: tools/print_signatures.py + check_api_approvals.sh
(signature diffs need deliberate approval) and tools/check_op_desc.py /
op_version_registry (removing an op breaks saved programs).
"""
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args):
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", script), *args],
        capture_output=True, text=True, env=env)


def test_api_spec_is_current():
    """Any public-signature change must ship an updated API.spec in the
    same commit (run tools/print_signatures.py --update)."""
    p = _run("print_signatures.py", "--check")
    assert p.returncode == 0, p.stderr


def test_op_registry_never_shrinks():
    """Ops may be added freely; removing one breaks saved programs and
    must fail the gate."""
    p = _run("check_op_desc.py", "--check")
    assert p.returncode == 0, p.stderr


def test_op_spec_counts_grads():
    spec = open(os.path.join(ROOT, "OPS.spec")).read().splitlines()
    assert len(spec) >= 350
    kinds = {ln.split()[1] for ln in spec}
    assert kinds <= {"explicit_grad", "grad_maker", "generic_vjp"}


# ---------------------------------------------------------------------------
# tools/bench_diff.py against the checked-in bench rounds
# ---------------------------------------------------------------------------


def _bench_diff(*args):
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "tools.bench_diff", *args],
        capture_output=True, text=True, env=env, cwd=ROOT)


def test_bench_diff_clean_rounds_improvement():
    """r02 -> r03 is the PR-3 throughput jump: both rounds clean, no
    regression, exit 0, and the improvement is flagged."""
    p = _bench_diff("BENCH_r02.json", "BENCH_r03.json")
    assert p.returncode == 0, p.stderr
    assert "no regressions past threshold" in p.stdout
    assert "improved" in p.stdout
    assert "caveat" not in p.stdout


def test_bench_diff_broken_round_is_advisory_not_a_failure():
    """r05 is the dead-device round (preflight timeout, every metric
    zeroed): the -100% 'regression' must be downgraded to advisory —
    exit 0 — with the caveat printed."""
    p = _bench_diff("BENCH_r03.json", "BENCH_r05.json")
    assert p.returncode == 0, p.stderr
    assert "caveat [B]" in p.stdout
    assert "ADVISORY" in p.stdout


def test_bench_diff_real_regression_fails(tmp_path):
    import json

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps({"resnet50_images_per_sec": 1000.0,
                             "vs_baseline": 1.0, "status": "ok"}))
    b.write_text(json.dumps({"resnet50_images_per_sec": 800.0,
                             "vs_baseline": 0.8, "status": "ok"}))
    p = _bench_diff(str(a), str(b))
    assert p.returncode == 1, p.stdout
    assert "REGRESSION" in p.stdout
    # json mode carries the same verdict for machines
    pj = _bench_diff(str(a), str(b), "--json")
    doc = json.loads(pj.stdout)
    assert doc["advisory"] is False
    assert "resnet50_images_per_sec" in doc["regressions"]


def test_bench_diff_threshold_is_respected(tmp_path):
    import json

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps({"bert_base_tokens_per_sec": 100.0}))
    b.write_text(json.dumps({"bert_base_tokens_per_sec": 93.0}))
    assert _bench_diff(str(a), str(b), "--threshold",
                       "0.10").returncode == 0
    assert _bench_diff(str(a), str(b), "--threshold",
                       "0.05").returncode == 1
