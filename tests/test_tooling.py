"""L11 tooling gates: API signature spec + op-registry compat check.

Reference parity: tools/print_signatures.py + check_api_approvals.sh
(signature diffs need deliberate approval) and tools/check_op_desc.py /
op_version_registry (removing an op breaks saved programs).
"""
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args):
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", script), *args],
        capture_output=True, text=True, env=env)


def test_api_spec_is_current():
    """Any public-signature change must ship an updated API.spec in the
    same commit (run tools/print_signatures.py --update)."""
    p = _run("print_signatures.py", "--check")
    assert p.returncode == 0, p.stderr


def test_op_registry_never_shrinks():
    """Ops may be added freely; removing one breaks saved programs and
    must fail the gate."""
    p = _run("check_op_desc.py", "--check")
    assert p.returncode == 0, p.stderr


def test_op_spec_counts_grads():
    spec = open(os.path.join(ROOT, "OPS.spec")).read().splitlines()
    assert len(spec) >= 350
    kinds = {ln.split()[1] for ln in spec}
    assert kinds <= {"explicit_grad", "grad_maker", "generic_vjp"}
