"""Distributed tests on the 8-virtual-device CPU mesh.

Parity model: reference unittests/test_dist_base.py `TestDistBase`
(:578/:1007) — the dist run's per-step losses must match the
single-process run within tolerance — and test_collective_base.py
(:34/:212) — each c_* op verified numerically.  Multi-node is modeled by
the 8-device mesh exactly as the reference models it with localhost
subprocesses (SURVEY §4 lesson).
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu import layers
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.distributed.parallel_env import init_parallel_env, reset_mesh


# mesh8 fixture: shared in tests/conftest.py


def _build_mlp(lr=0.05, use_fleet=False, strategy=None):
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.optimizer import MomentumOptimizer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = Program(), Program()
    main.random_seed = 1
    with program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1])
        h = layers.fc(x, 16, act="relu", param_attr=ParamAttr(
            initializer=ConstantInitializer(0.1)), bias_attr=False)
        pred = layers.fc(h, 1, param_attr=ParamAttr(
            initializer=ConstantInitializer(0.2)), bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = MomentumOptimizer(lr, 0.9)
        if use_fleet:
            from paddle_tpu.distributed import fleet

            fleet.init(is_collective=True, strategy=strategy)
            fleet.distributed_optimizer(opt)
            fleet.minimize(loss)
        else:
            opt.minimize(loss)
    return main, startup, loss


def _train(main, startup, loss, X, Y, steps=5, mesh=None):
    scope = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup, scope=scope)
    return [float(np.asarray(exe.run(main, feed={"x": X, "y": Y},
                                     fetch_list=[loss], scope=scope)[0]).item())
            for _ in range(steps)]


class TestDistLossParity:
    def test_dp_matches_single_process(self, mesh8):
        """The reference's core oracle (test_dist_base.py:1007): dist loss
        trajectory == local loss trajectory."""
        rs = np.random.RandomState(0)
        X = rs.randn(32, 8).astype("f4")
        Y = rs.randn(32, 1).astype("f4")

        reset_mesh()
        m, s, l = _build_mlp()
        base = _train(m, s, l, X, Y)

        mesh = init_parallel_env()
        m2, s2, l2 = _build_mlp(use_fleet=True)
        dist_losses = _train(m2, s2, l2, X, Y, mesh=mesh)
        np.testing.assert_allclose(base, dist_losses, rtol=1e-4, atol=1e-6)

    def test_fleet_world_size(self, mesh8):
        from paddle_tpu.distributed import fleet

        fleet.init(is_collective=True)
        assert fleet.worker_num() == 8
        assert fleet.is_first_worker()


class TestCollectiveOps:
    """Each c_* op verified numerically on the mesh
    (reference test_collective_base.py pattern)."""

    def _run_collective(self, op_type, x_np, attrs=None, mesh=None):
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", list(x_np.shape[1:]))
            out = main.current_block().create_var(name="out")
            main.current_block().append_op(op_type, {"X": x.name},
                                           {"Out": "out"}, attrs or {})
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
        return exe.run(main, feed={"x": x_np}, fetch_list=["out"],
                       scope=scope)[0]

    def test_c_allreduce_sum(self, mesh8):
        # each shard holds 1 row; psum -> every shard has the column sums;
        # the result is replica-invariant so the fetch is the local copy
        # (the reference fetch likewise returns the rank-local tensor)
        x = np.arange(16, dtype="f4").reshape(8, 2) + 1
        out = self._run_collective("c_allreduce_sum", x, mesh=mesh8)
        np.testing.assert_allclose(out, x.sum(0, keepdims=True), rtol=1e-6)

    def test_c_allreduce_max(self, mesh8):
        x = np.arange(16, dtype="f4").reshape(8, 2)
        out = self._run_collective("c_allreduce_max", x, mesh=mesh8)
        np.testing.assert_allclose(out, x.max(0, keepdims=True))

    def test_c_broadcast(self, mesh8):
        x = np.arange(16, dtype="f4").reshape(8, 2)
        out = self._run_collective("c_broadcast", x, {"root": 3}, mesh=mesh8)
        np.testing.assert_allclose(out, x[3:4])

    def test_c_allgather(self, mesh8):
        x = np.arange(16, dtype="f4").reshape(8, 2)
        out = self._run_collective("c_allgather", x, mesh=mesh8)
        # every shard gathers all rows -> the full batch, replica-invariant
        assert out.shape == (8, 2)
        np.testing.assert_allclose(out, x)

    def test_c_reducescatter(self, mesh8):
        # shard r holds X[r*16:(r+1)*16]; psum_scatter gives shard r slice
        # r of the elementwise sum; the fetch re-gathers -> column sums
        x = np.arange(128, dtype="f4")
        out = self._run_collective("c_reducescatter", x, mesh=mesh8)
        np.testing.assert_allclose(out, x.reshape(8, 16).sum(0), rtol=1e-6)

    def test_identity_without_mesh(self):
        reset_mesh()
        x = np.arange(4, dtype="f4").reshape(4, 1)
        out = self._run_collective("c_allreduce_sum", x, mesh=None)
        np.testing.assert_allclose(out, x)


class TestCollectiveAPI:
    def test_eager_single_process_semantics(self):
        t = pt.to_tensor(np.ones(4, dtype="f4"))
        dist.all_reduce(t)
        np.testing.assert_allclose(t.numpy(), np.ones(4))
        assert dist.get_world_size() >= 1
        assert dist.get_rank() == 0
        dist.barrier()


class TestDistributedStrategy:
    def test_proto_roundtrip(self, tmp_path):
        from paddle_tpu.distributed.fleet import DistributedStrategy

        s = DistributedStrategy()
        s.amp = True
        s.localsgd = True
        s.localsgd_configs = {"k_steps": 4}
        s.amp_configs = {"init_loss_scaling": 1024.0}
        data = s.serialize_to_string()
        s2 = DistributedStrategy()
        s2.parse_from_string(data)
        assert s2.amp and s2.localsgd
        assert s2.localsgd_configs["k_steps"] == 4
        assert s2.amp_configs["init_loss_scaling"] == 1024.0

        p = tmp_path / "strategy.prototxt"
        s.save_to_prototxt(str(p))
        s3 = DistributedStrategy()
        s3.load_from_prototxt(str(p))
        assert s3.localsgd_configs["k_steps"] == 4

    def test_unknown_config_key_rejected(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy

        s = DistributedStrategy()
        with pytest.raises(ValueError):
            s.localsgd_configs = {"bogus": 1}


class TestMetaOptimizers:
    def test_lamb_swap(self, mesh8):
        """strategy.lamb=True swaps Adam for LAMB (reference
        lamb_optimizer.py)."""
        from paddle_tpu.distributed import fleet
        from paddle_tpu.optimizer import AdamOptimizer

        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", [4])
            loss = layers.mean(layers.fc(x, 1))
            strat = fleet.DistributedStrategy()
            strat.lamb = True
            fleet.init(is_collective=True, strategy=strat)
            fleet.distributed_optimizer(AdamOptimizer(0.01))
            fleet.minimize(loss)
        assert any(op.type == "lamb" for op in main.global_block.ops)

    def test_gradallreduce_inserted(self, mesh8):
        m, s, l = _build_mlp(use_fleet=True)
        types = [op.type for op in m.global_block.ops]
        assert "c_allreduce_sum" in types
        # loss grad scaled by 1/nranks right after its fill_constant seed
        i_fill = next(i for i, op in enumerate(m.global_block.ops)
                      if op.type == "fill_constant"
                      and l.name + "@GRAD" in op.output_arg_names())
        assert m.global_block.ops[i_fill + 1].type == "scale"
