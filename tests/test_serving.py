"""paddle_tpu.serving: dynamic-batching inference server.

Pins the ISSUE-1 acceptance contract: ≥32 concurrent variable-length
clients get results numerically equal to direct Predictor.run; the
executor compiles at most one executable per configured shape bucket
(no compile storm); at least one batch coalesces multiple requests;
deadline-expired requests error instead of blocking the queue; a full
queue rejects with explicit backpressure; shutdown drains gracefully.
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, serving
from paddle_tpu.fluid import io as fluid_io
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.framework.scope import _switch_scope
from paddle_tpu.monitor import stat_get, stat_reset

N_CLIENTS = 32
BATCH_SIZES = (1, 2, 4, 8)
SEQ_LENS = (8, 16)
N_BUCKETS = len(BATCH_SIZES) * len(SEQ_LENS)


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    """A padding-invariant variable-length model: relu(x@W) summed over
    the seq dim — padded rows/positions contribute exactly zero, so
    bucket padding must be invisible in the results."""
    d = str(tmp_path_factory.mktemp("serving") / "model")
    main, startup = Program(), Program()
    main.random_seed = 7
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [-1, 4])  # declared [-1, -1, 4]
        h = layers.fc(x, 8, num_flatten_dims=2, act="relu",
                      bias_attr=False)
        out = layers.reduce_sum(h, dim=1)
    sc = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=sc)
    old = _switch_scope(sc)
    try:
        fluid_io.save_inference_model(d, ["x"], [out], exe, main)
    finally:
        _switch_scope(old)
    return d


def _requests(n=N_CLIENTS, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randn(1 + rs.randint(4), 1 + rs.randint(SEQ_LENS[-1]),
                     4).astype("f4") for _ in range(n)]


def _server(model_dir, **overrides):
    kw = dict(batch_sizes=BATCH_SIZES, seq_lens=SEQ_LENS,
              batch_window_ms=30.0, max_queue=64)
    kw.update(overrides)
    return serving.Server(model_dir, serving.ServingConfig(**kw))


class TestBuckets:
    def test_bucket_selection_and_bounds(self):
        spec = serving.BucketSpec((1, 2, 4, 8), (8, 16))
        assert spec.batch_bucket(3) == 4
        assert spec.batch_bucket(8) == 8
        assert spec.seq_bucket(1) == 8
        assert spec.seq_bucket(9) == 16
        assert spec.n_buckets() == 8
        with pytest.raises(serving.RequestTooLargeError):
            spec.batch_bucket(9)
        with pytest.raises(serving.RequestTooLargeError):
            spec.seq_bucket(17)

    def test_exact_shape_mode_passthrough(self):
        spec = serving.BucketSpec((1, 4), None)
        assert spec.seq_bucket(13) == 13  # no inner padding configured


class TestServing:
    def test_concurrent_parity_bounded_compiles_and_coalescing(
            self, model_dir):
        """The acceptance-criteria test: 32 concurrent mixed-length
        clients, parity with direct Predictor.run, compile count ≤
        bucket count, and real multi-request batches."""
        from paddle_tpu.inference import Config, create_predictor

        reqs = _requests()
        # sequential oracle FIRST (its per-shape compiles must not be
        # attributed to the serving path)
        ref_pred = create_predictor(Config(model_dir))
        refs = [np.asarray(ref_pred.run({"x": r})[0]) for r in reqs]

        srv = _server(model_dir)
        stat_reset()
        srv.start()  # AOT warmup compiles every bucket up front
        warm = stat_get("executor_compile")
        assert 0 < warm <= N_BUCKETS, warm
        assert stat_get("serving_warmup_compiles") == warm

        results = [None] * len(reqs)
        errors = [None] * len(reqs)

        def client(i):
            try:
                results[i] = srv.infer({"x": reqs[i]})
            except Exception as e:  # noqa: BLE001 — assert below
                errors[i] = e

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(reqs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        srv.stop(drain=True)

        assert not any(errors), [e for e in errors if e]
        for got, ref in zip(results, refs):
            np.testing.assert_allclose(np.asarray(got[0]), ref,
                                       rtol=1e-5, atol=1e-6)
        # no compile storm: warmup covered every shape traffic produced
        assert stat_get("executor_compile") <= N_BUCKETS
        assert len(srv._predictor._exe._cache) <= N_BUCKETS
        # the batcher actually coalesced concurrent requests
        assert stat_get("serving_max_batch_occupancy") > 1
        assert stat_get("serving_batches") < N_CLIENTS
        assert stat_get("serving_completed") == N_CLIENTS

    def test_deadline_expiry_does_not_block_queue(self, model_dir):
        srv = _server(model_dir).start()
        try:
            with pytest.raises(serving.DeadlineExceededError):
                srv.infer({"x": _requests(1)[0]}, deadline_ms=0.0)
            assert stat_get("serving_deadline_exceeded") >= 1
            # the queue is alive: a normal request still completes
            out = srv.infer({"x": np.ones((2, 3, 4), "f4")})
            assert np.asarray(out[0]).shape == (2, 8)
        finally:
            srv.stop()

    def test_deadline_lapsing_during_window_is_reaped_at_dequeue(
            self, model_dir):
        """A request whose deadline expires WHILE the batcher waits out
        the coalescing window must error, not execute: an async client
        that only calls result() later would otherwise get data for a
        request it contractually abandoned (and the chip does the
        work)."""
        srv = _server(model_dir, batch_window_ms=300.0).start()
        try:
            req = srv.submit({"x": np.ones((1, 3, 4), "f4")},
                             deadline_ms=30.0)
            time.sleep(0.5)  # well past the window: dequeue happened
            with pytest.raises(serving.DeadlineExceededError):
                req.result()
            assert stat_get("serving_deadline_exceeded") >= 1
        finally:
            srv.stop()

    def test_queue_full_backpressure(self, model_dir):
        srv = _server(model_dir, max_queue=3).start()
        try:
            srv._batcher.pause()  # hold the consumer: queue must fill
            pending = [srv.submit({"x": np.ones((1, 3, 4), "f4")})
                       for _ in range(3)]
            with pytest.raises(serving.QueueFullError):
                srv.submit({"x": np.ones((1, 3, 4), "f4")})
            assert stat_get("serving_rejected_queue_full") >= 1
            srv._batcher.resume()
            for req in pending:  # backlog drains once resumed
                assert np.asarray(req.result()[0]).shape == (1, 8)
        finally:
            srv.stop()

    def test_graceful_drain_and_closed_rejection(self, model_dir):
        srv = _server(model_dir).start()
        pending = [srv.submit({"x": np.ones((1, 5, 4), "f4")})
                   for _ in range(4)]
        srv.stop(drain=True)  # finishes queued work before returning
        for req in pending:
            assert np.asarray(req.result()[0]).shape == (1, 8)
        with pytest.raises(serving.ServerClosedError):
            srv.submit({"x": np.ones((1, 5, 4), "f4")})

    def test_server_restarts_after_stop(self, model_dir):
        """stop() is not terminal: a restarted server serves again
        (the batcher clears its closing flag on start)."""
        srv = _server(model_dir).start()
        srv.infer({"x": np.ones((1, 3, 4), "f4")})
        srv.stop(drain=True)
        srv.start()
        try:
            out = srv.infer({"x": np.ones((2, 3, 4), "f4")})
            assert np.asarray(out[0]).shape == (2, 8)
        finally:
            srv.stop()

    def test_request_too_large_and_contract_violations(self, model_dir):
        srv = _server(model_dir).start()
        try:
            with pytest.raises(serving.RequestTooLargeError):
                srv.infer({"x": np.ones((9, 3, 4), "f4")})  # batch > 8
            with pytest.raises(serving.RequestTooLargeError):
                srv.infer({"x": np.ones((1, 17, 4), "f4")})  # seq > 16
            with pytest.raises(ValueError):
                srv.infer({"x": np.ones((1, 3, 5), "f4")})  # fixed dim
            with pytest.raises(KeyError):
                srv.infer({"not_x": np.ones((1, 3, 4), "f4")})
        finally:
            srv.stop()

    def test_stats_and_health_http_endpoints(self, model_dir):
        srv = _server(model_dir, http_port=0).start()
        try:
            srv.infer({"x": np.ones((2, 3, 4), "f4")})
            port = srv.http_port
            stats = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=10).read())
            health = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=10).read())
            assert stats["serving_completed"] >= 1
            assert "serving_latency_ms_avg" in stats
            assert health["status"] == "ok"
            assert health["queue_depth"] == 0
            assert health["buckets"] == N_BUCKETS
        finally:
            srv.stop()


class TestWarmup:
    def test_executor_warmup_is_state_neutral_and_counts(self, model_dir):
        """Executor.warmup compiles each spec once, later runs are pure
        cache hits, and the scope (incl. RNG) is byte-identical after."""
        from paddle_tpu.inference import Config, create_predictor

        pred = create_predictor(Config(model_dir))
        exe, scope, prog = pred._exe, pred._scope, pred._program
        before = {n: np.asarray(scope.get_var(n)).copy()
                  for n in scope.local_var_names()
                  if scope.get_var(n) is not None
                  and not callable(scope.get_var(n))}
        specs = [{"x": ((b, s, 4), "float32")}
                 for b in (1, 2) for s in (8, 16)]
        n = exe.warmup(prog, specs, fetch_list=pred._fetch_targets,
                       scope=pred._scope)
        assert n == 4
        # idempotent: same specs are all cache hits
        assert exe.warmup(prog, specs, fetch_list=pred._fetch_targets,
                          scope=pred._scope) == 0
        after = {n_: np.asarray(scope.get_var(n_))
                 for n_ in before}
        for k in before:
            np.testing.assert_array_equal(before[k], after[k])
        # a live run with a warmed shape does not compile
        stat_reset()
        pred.run({"x": np.zeros((2, 16, 4), "f4")})
        assert stat_get("executor_compile") == 0
        assert stat_get("executor_cache_hit") == 1

    def test_warmup_requires_fetch_contract(self):
        exe = pt.Executor(pt.CPUPlace())
        with pytest.raises(ValueError, match="fetch"):
            exe.warmup(Program(), [{"x": ((1, 4), "float32")}])

    def test_warmup_survives_donated_training_state(self):
        """A training program's jitted step DONATES its state buffers;
        warmup must deep-copy the snapshot or the restore resurrects
        deleted arrays and the scope is corrupted."""
        from paddle_tpu.optimizer import SGDOptimizer

        main, startup = Program(), Program()
        main.random_seed = 5
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [4])
            y = layers.data("y", [1])
            loss = layers.mean(layers.square_error_cost(
                layers.fc(x, 1, bias_attr=False), y))
            SGDOptimizer(learning_rate=0.1).minimize(loss)
        sc = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=sc)
        feed = {"x": np.ones((2, 4), "f4"), "y": np.zeros((2, 1), "f4")}
        exe.run(main, feed=feed, fetch_list=[loss], scope=sc)

        w = main.all_parameters()[0].name
        before = np.asarray(sc.find_var(w).get_tensor()).copy()
        assert exe.warmup(
            main, [{"x": ((8, 4), "float32"), "y": ((8, 1), "float32")}],
            fetch_list=[loss], scope=sc) == 1
        np.testing.assert_array_equal(
            np.asarray(sc.find_var(w).get_tensor()), before)
        # the scope is alive: training continues after warmup
        out = exe.run(main, feed=feed, fetch_list=[loss], scope=sc)
        assert np.isfinite(np.asarray(out[0])).all()


class TestMonitorGauges:
    def test_stat_set_and_stat_max(self):
        from paddle_tpu.monitor import stat_max, stat_set

        stat_reset("g_depth")
        stat_set("g_depth", 7)
        assert stat_get("g_depth") == 7
        stat_set("g_depth", 3)
        assert stat_get("g_depth") == 3
        stat_reset("g_hwm")
        stat_max("g_hwm", 5)
        stat_max("g_hwm", 2)
        assert stat_get("g_hwm") == 5
