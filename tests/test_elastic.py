"""fleet.elastic — the preemption-proof training supervisor (ISSUE 14).

Acceptance oracle: a chaos-injected rank kill mid-step re-shards onto
the smaller topology via the supervisor and the FULL trajectory
(losses + final params) is bitwise the uninterrupted run's — the
extension of ``test_ckpt.test_async_crash_resume_bitwise_parity`` to
topology loss.  Every other classified failure path (preflight
init-timeout/compile-error, watchdog stall, torn checkpoint, dead-rank
detection, poison step, budget exhaustion) is pinned here too, all
driven through ``elastic.chaos`` — the paths run every suite, not only
when real hardware dies.
"""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.ckpt import CheckpointManager
from paddle_tpu.distributed.fleet import elastic
from paddle_tpu.distributed.fleet.elastic import chaos
from paddle_tpu.framework.scope import Scope
from paddle_tpu.monitor import stat_get


@pytest.fixture(autouse=True)
def _chaos_and_postmortem(tmp_path):
    """Every test starts with a disarmed armory and its own postmortem
    dir (supervisor bundles must not litter the repo).  ckpt fsync is
    off per its own flag doc (throwaway dirs; torn-save coverage here
    uses fault injection, not real crashes) — the suite runs near the
    tier-1 budget and these tests save every step."""
    chaos.clear()
    old = pt.get_flags(["FLAGS_postmortem_dir", "FLAGS_ckpt_fsync"])
    pt.set_flags({"FLAGS_postmortem_dir": str(tmp_path / "postmortem"),
                  "FLAGS_ckpt_fsync": False})
    yield
    chaos.clear()
    pt.set_flags(old)


# ---------------------------------------------------------------------------
# preflight: subprocess isolation + structured verdicts
# ---------------------------------------------------------------------------


class TestPreflight:
    def test_ok_probe_reports_platform(self):
        v = elastic.preflight_device(
            attempts=1, timeout_s=30,
            probe_code="print('PREFLIGHT_OK cpu')")
        assert v.ok and v.verdict == "ok"
        assert v.platform == "cpu" and v.attempts == 1
        assert v.to_dict()["verdict"] == "ok"

    def test_init_timeout_bounded_with_exponential_backoff(self):
        """A child that never finishes init cannot hang the caller:
        the deadline converts it to a structured init_timeout, and
        retries back off exponentially."""
        sleeps = []
        v = elastic.preflight_device(
            attempts=3, timeout_s=0.3, backoff_s=0.5,
            probe_code="import time; time.sleep(60)",
            sleep_fn=sleeps.append)
        assert not v.ok and v.verdict == "init_timeout"
        assert v.attempts == 3
        assert sleeps == [0.5, 1.0]  # backoff * 2^k, no sleep after last
        assert "did not complete" in v.diag

    def test_compile_error_carries_stderr_diag(self):
        v = elastic.preflight_device(
            attempts=1, timeout_s=30,
            probe_code="import sys; sys.stderr.write('XLA kaboom'); "
                       "sys.exit(3)")
        assert v.verdict == "compile_error" and not v.ok
        assert "kaboom" in v.diag and "3" in v.diag

    def test_chaos_injected_timeout_then_recovers(self):
        """The r04/r05 failure on demand: one injected init-timeout,
        then the retry succeeds — no subprocess spawned for the
        injected attempt."""
        chaos.inject("preflight_init_timeout", count=1)
        sleeps = []
        before = stat_get("elastic_preflight_init_timeout")
        v = elastic.preflight_device(
            attempts=2, timeout_s=5, backoff_s=0.1,
            probe_code="print('PREFLIGHT_OK cpu')",
            sleep_fn=sleeps.append)
        assert v.ok and v.attempts == 2 and sleeps == [0.1]
        assert stat_get("elastic_preflight_init_timeout") == before + 1
        assert chaos.armed() == []  # consumed


# ---------------------------------------------------------------------------
# supervisor over a pure-host toy program (fast classification paths)
# ---------------------------------------------------------------------------


class _Toy:
    """Deterministic 'training': the state is one float accumulating
    the batches; checkpointable via the state()/load_state() half of
    the program protocol."""

    def __init__(self):
        self.w = 0.0

    def step(self, batch):
        self.w += float(batch)
        return self.w

    def state(self):
        return {"w": np.asarray([self.w], dtype="f8")}

    def load_state(self, state):
        self.w = float(np.asarray(state["w"]).ravel()[0])


_BATCHES = [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]
_CUMSUM = [1.0, 3.0, 6.0, 10.0, 15.0, 21.0]


def _sup(**kw):
    kw.setdefault("preflight", False)
    kw.setdefault("backoff_s", 0.0)
    return elastic.ElasticSupervisor(**kw)


class TestSupervisor:
    def test_plain_run_ok(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "c"), keep_n=0,
                                async_save=False)
        r = _sup(world_size=1).run(lambda topo: _Toy(), manager=mgr,
                                   loader=_BATCHES, total_steps=6)
        mgr.close()
        assert r.status == "ok" and r.restarts == 0 and r.reshards == 0
        assert r.losses == _CUMSUM and r.final_step == 6

    def test_kill_rank_reshards_and_resumes(self, tmp_path):
        """kill_rank_mid_step -> topology_change -> world 2 -> 1,
        restore from the latest intact step, fast-forward the
        iterator, continue: the trajectory matches the uninterrupted
        one and the failure left a postmortem bundle + history."""
        mgr = CheckpointManager(str(tmp_path / "c"), keep_n=0,
                                async_save=False)
        chaos.inject("kill_rank_mid_step", rank=1, at_step=4)
        r = _sup(world_size=2).run(lambda topo: _Toy(), manager=mgr,
                                   loader=_BATCHES, total_steps=6)
        mgr.close()
        assert r.status == "recovered"
        assert r.restarts == 1 and r.reshards == 1
        assert r.final_world_size == 1
        assert r.losses == _CUMSUM
        h = r.history[0]
        assert h["kind"] == "topology_change" and h["step"] == 4
        assert h["dead_ranks"] == [1]
        bundles = os.listdir(tmp_path / "postmortem")
        assert any("elastic_topology_change" in b for b in bundles)

    def test_train_fn_sees_shrunken_topology(self, tmp_path):
        worlds = []

        def train_fn(topo):
            worlds.append((topo.world_size, tuple(topo.ranks)))
            return _Toy()

        mgr = CheckpointManager(str(tmp_path / "c"), keep_n=0,
                                async_save=False)
        chaos.inject("kill_rank_mid_step", rank=1, at_step=2)
        _sup(world_size=3).run(train_fn, manager=mgr, loader=_BATCHES,
                               total_steps=4)
        mgr.close()
        assert worlds == [(3, (0, 1, 2)), (2, (0, 2))]

    def test_poison_step_terminates_loudly(self, tmp_path):
        """The same step failing identically twice is poison: replay
        cannot help, so the supervisor terminates with the history —
        it must NOT burn the whole restart budget first."""

        class Bad(_Toy):
            def step(self, batch):
                if float(batch) == 3.0:
                    raise ValueError("deterministic step bug")
                return super().step(batch)

        mgr = CheckpointManager(str(tmp_path / "c"), keep_n=0,
                                async_save=False)
        with pytest.raises(elastic.ElasticTerminated,
                           match="poison") as ei:
            _sup(world_size=1, max_restarts=10).run(
                lambda topo: Bad(), manager=mgr, loader=_BATCHES,
                total_steps=6)
        mgr.close()
        assert len(ei.value.history) == 2  # first + identical repeat
        assert all(h["step"] == 3 for h in ei.value.history)

    def test_restart_budget_exhaustion_is_terminal_not_a_hang(self):
        """Distinct transient failures every attempt: the budget bounds
        them and the terminal error names it — never a silent hang."""
        n = [0]

        def train_fn(topo):
            n[0] += 1

            def step(i, batch):
                raise RuntimeError(f"flaky device episode {n[0]}")

            return step

        with pytest.raises(elastic.ElasticTerminated,
                           match="budget") as ei:
            _sup(world_size=1, max_restarts=2).run(
                train_fn, total_steps=3)
        assert len(ei.value.history) == 3  # initial + 2 restarts

    def test_dead_rank_detection_from_cluster_plane(self, tmp_path):
        """The health plane dead-lists rank 1 while the loop runs: the
        supervisor notices via its cluster poll, classifies
        topology_change, and re-shards without any exception from the
        train step itself."""
        seen = {"steps": 0}

        class Counting(_Toy):
            def step(self, batch):
                seen["steps"] += 1
                return super().step(batch)

        def cluster_fn():
            return {"dead_ranks": [1] if seen["steps"] >= 2 else []}

        mgr = CheckpointManager(str(tmp_path / "c"), keep_n=0,
                                async_save=False)
        r = _sup(world_size=2, cluster_fn=cluster_fn,
                 cluster_poll_s=0.0).run(
            lambda topo: Counting(), manager=mgr, loader=_BATCHES,
            total_steps=5)
        mgr.close()
        assert r.reshards == 1 and r.final_world_size == 1
        assert r.history[0]["kind"] == "topology_change"
        assert r.history[0]["dead_ranks"] == [1]
        assert r.losses == _CUMSUM[:5]

    def test_watchdog_stall_dumps_bundle_and_restarts_in_place(
            self, tmp_path):
        """hang_device_call holds the step window past the watchdog
        timeout: the PR 6 watchdog trips (bundle dumped), the attempt
        is classified transient, and the restart completes the run."""
        mgr = CheckpointManager(str(tmp_path / "c"), keep_n=0,
                                async_save=False)
        chaos.inject("hang_device_call", at_step=3, seconds=0.7)
        r = _sup(world_size=1, watchdog_timeout_s=0.15).run(
            lambda topo: _Toy(), manager=mgr, loader=_BATCHES,
            total_steps=5)
        mgr.close()
        assert r.status == "recovered" and r.restarts == 1
        assert r.reshards == 0  # restart IN PLACE: same world
        assert r.history[0]["kind"] == "transient"
        assert "StallDetected" in r.history[0]["error"]
        assert r.losses == _CUMSUM[:5]
        bundles = os.listdir(tmp_path / "postmortem")
        # one bundle from the watchdog trip itself + one from the
        # supervisor's failure record
        assert any(b.startswith("bundle_") and "stall" in b
                   for b in bundles)

    def test_torn_checkpoint_falls_back_and_recovers(self, tmp_path):
        """torn_checkpoint kills the writer pre-commit at step 4: the
        save fails (transient), restore falls back to intact step 3,
        and the replay commits a clean step 4..6."""
        mgr = CheckpointManager(str(tmp_path / "c"), keep_n=0,
                                async_save=False)
        chaos.inject("torn_checkpoint", at_step=4)
        r = _sup(world_size=1).run(lambda topo: _Toy(), manager=mgr,
                                   loader=_BATCHES, total_steps=6)
        assert r.status == "recovered" and r.restarts == 1
        assert "TornCheckpoint" in r.history[0]["error"]
        assert r.losses == _CUMSUM
        assert mgr.latest_intact_step() == 6
        mgr.close()

    def test_no_manager_runs_unsupervised_checkpointing(self):
        r = _sup(world_size=1).run(lambda topo: _Toy(),
                                   loader=_BATCHES, total_steps=4)
        assert r.losses == _CUMSUM[:4] and r.status == "ok"

    def test_stateless_program_with_manager_skips_saves(self, tmp_path):
        """A bare callable has nothing to checkpoint: the supervisor
        must run it (saves skipped) rather than crash the first save
        and read the crash as a poison step."""
        mgr = CheckpointManager(str(tmp_path / "c"), keep_n=0,
                                async_save=False)
        r = _sup(world_size=1).run(
            lambda topo: (lambda i, batch: float(batch)),
            manager=mgr, loader=_BATCHES, total_steps=3)
        assert r.losses == _BATCHES[:3] and r.status == "ok"
        assert mgr.all_steps() == []  # nothing was saved
        mgr.close()

    def test_caller_fault_hook_chained_and_restored(self, tmp_path):
        """The supervisor chains the chaos ckpt hook in FRONT of a
        caller-installed one (both fire) and restores the caller's
        when the run ends."""
        mgr = CheckpointManager(str(tmp_path / "c"), keep_n=0,
                                async_save=False)
        phases = []

        def user_hook(phase, step):
            phases.append((phase, step))

        mgr.set_fault_hook(user_hook)
        r = _sup(world_size=1).run(lambda topo: _Toy(), manager=mgr,
                                   loader=_BATCHES, total_steps=2)
        assert r.status == "ok"
        assert ("pre_commit", 1) in phases  # caller's hook still fired
        assert mgr._fault_hook is user_hook  # and was restored
        mgr.close()

    def test_classify_failure_table(self):
        assert elastic.classify_failure(
            chaos.RankKilled(2)) == "topology_change"
        assert elastic.classify_failure(
            RuntimeError("x"), dead_ranks=[1]) == "topology_change"
        assert elastic.classify_failure(
            RuntimeError("x")) == "transient"
        assert elastic.classify_failure(
            RuntimeError("x"), repeat=True) == "poison_step"
        from paddle_tpu.observe.xla_stats import MemoryBudgetError

        assert elastic.classify_failure(
            MemoryBudgetError("too big")) == "poison_step"
        # a budget refusal is poison even on its FIRST occurrence
        assert elastic.is_device_failure(RuntimeError(
            "RESOURCE_EXHAUSTED: out of memory".lower()))
        assert not elastic.is_device_failure(KeyError("shape"))


# ---------------------------------------------------------------------------
# THE acceptance test: rank kill mid-step -> re-shard -> bitwise parity
# (extends test_ckpt.test_async_crash_resume_bitwise_parity to topology
# loss: same full-state model — params, Momentum slots, LR schedule,
# RNG/dropout, AMP loss-scale counters, iterator position)
# ---------------------------------------------------------------------------


def _full_train_fn():
    """Supervisor-protocol wrapper around test_ckpt's full-state model
    (fc -> dropout -> fc, Momentum + StepDecay + dynamic loss scaling):
    a fresh build per (re)start, exactly like a restarted process."""
    from test_ckpt import _build_full_model

    def train_fn(topo):
        main, startup, loss, sched = _build_full_model()
        sc = Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=sc)

        class Prog:
            scope = sc
            components = {"lr_sched": sched}

            def step(self, batch):
                bx, by = batch
                out = exe.run(main, feed={"x": bx, "y": by},
                              fetch_list=[loss], scope=sc)
                sched.step()
                return float(np.asarray(out[0]).ravel()[0])

            def params(self):
                return {n: np.asarray(sc.get_var(n))
                        for n in sc.local_var_names()
                        if hasattr(sc.get_var(n), "dtype")}

        return Prog()

    return train_fn


def _full_loader():
    from paddle_tpu.io import DataLoader, TensorDataset

    rs = np.random.RandomState(0)
    X = rs.randn(32, 8).astype("f4")
    Y = (X.sum(1, keepdims=True) * 0.3).astype("f4")
    return DataLoader(TensorDataset([X, Y]), batch_size=8,
                      shuffle=False)


def test_chaos_rank_kill_reshards_bitwise(tmp_path):
    """ISSUE 14 acceptance: chaos kills rank 1 mid-step 5 of a 2-rank
    run; the supervisor classifies topology_change, re-shards to the
    surviving world (1), restores the latest intact async checkpoint,
    fast-forwards the ResumableIterator, and continues — the full loss
    trajectory AND final state (params + optimizer slots + LR step +
    RNG + loss-scale) are bitwise the uninterrupted run's."""
    # oracle: uninterrupted supervised run at the surviving world size
    mo = CheckpointManager(str(tmp_path / "oracle"), keep_n=0,
                           async_save=True)
    ro = _sup(world_size=1).run(_full_train_fn(), manager=mo,
                                loader=_full_loader(), total_steps=7)
    mo.close()
    assert ro.status == "ok" and len(ro.losses) == 7
    oracle_params = ro.train.params()

    # chaos run: rank 1 dies mid-step 5
    chaos.inject("kill_rank_mid_step", rank=1, at_step=5)
    mc = CheckpointManager(str(tmp_path / "chaos"), keep_n=0,
                           async_save=True)
    before = stat_get("elastic_reshards")
    rc = _sup(world_size=2).run(_full_train_fn(), manager=mc,
                                loader=_full_loader(), total_steps=7)
    mc.close()
    assert rc.status == "recovered"
    assert rc.restarts == 1 and rc.reshards == 1
    assert rc.final_world_size == 1
    assert stat_get("elastic_reshards") == before + 1
    # the restart resumed from a committed step, not from scratch
    assert rc.history[0]["kind"] == "topology_change"

    # losses bitwise (replayed steps overwrote their first emission)
    np.testing.assert_array_equal(rc.losses, ro.losses)
    # final state bitwise across every state family
    chaos_params = rc.train.params()
    assert sorted(chaos_params) == sorted(oracle_params)
    for n in oracle_params:
        np.testing.assert_array_equal(chaos_params[n], oracle_params[n],
                                      err_msg=n)
