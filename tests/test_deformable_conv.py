"""Deformable conv v1/v2 vs oracles.

Zero offsets must reduce EXACTLY to plain conv2d (the defining
identity); integer offsets equal a shifted conv; the modulation mask
scales sampled values.  Reference operators/deformable_conv_op.cu.
"""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.framework.program import Program, program_guard


def _run(op_type, x, offset, f, mask=None, attrs=None):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        blk = main.global_block
        ins = {"Input": ["x"], "Offset": ["off"], "Filter": ["f"]}
        feed = {"x": x, "off": offset, "f": f}
        for n, a in list(feed.items()):
            blk.create_var(name=n, shape=a.shape, dtype="float32",
                           stop_gradient=True)
        if mask is not None:
            blk.create_var(name="m", shape=mask.shape, dtype="float32",
                           stop_gradient=True)
            ins["Mask"] = ["m"]
            feed["m"] = mask
        blk.create_var(name="out", dtype="float32")
        blk.append_op(op_type, ins, {"Output": ["out"]}, dict(attrs or {}))
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.framework.Scope()
    exe.run(startup, scope=sc)
    return np.asarray(exe.run(main, feed=feed, fetch_list=["out"],
                              scope=sc)[0])


def _conv_oracle(x, f, stride=1, pad=1):
    n, c, h, w = x.shape
    o, _, kh, kw = f.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (w + 2 * pad - kw) // stride + 1
    out = np.zeros((n, o, oh, ow), "f4")
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh,
                       j * stride:j * stride + kw]
            out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, f)
    return out


def test_zero_offset_equals_plain_conv():
    rs = np.random.RandomState(0)
    x = rs.randn(2, 4, 6, 6).astype("f4")
    f = rs.randn(3, 4, 3, 3).astype("f4")
    off = np.zeros((2, 2 * 9, 6, 6), "f4")
    got = _run("deformable_conv_v1", x, off, f,
               attrs={"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 1,
                      "deformable_groups": 1})
    want = _conv_oracle(x, f)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_mask_scales_v2():
    rs = np.random.RandomState(1)
    x = rs.randn(1, 2, 5, 5).astype("f4")
    f = rs.randn(2, 2, 3, 3).astype("f4")
    off = np.zeros((1, 18, 5, 5), "f4")
    mask_half = np.full((1, 9, 5, 5), 0.5, "f4")
    got = _run("deformable_conv", x, off, f, mask=mask_half,
               attrs={"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 1,
                      "deformable_groups": 1})
    want = 0.5 * _conv_oracle(x, f)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_integer_offset_shifts_sampling():
    """Offset (dy=0, dx=1) samples one pixel right: equals plain conv on
    the right-shifted image (interior columns)."""
    rs = np.random.RandomState(2)
    x = rs.randn(1, 1, 6, 6).astype("f4")
    f = rs.randn(1, 1, 3, 3).astype("f4")
    off = np.zeros((1, 18, 6, 6), "f4")
    off[:, 1::2] = 1.0  # dx entries
    got = _run("deformable_conv_v1", x, off, f,
               attrs={"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 1,
                      "deformable_groups": 1})
    x_shift = np.zeros_like(x)
    x_shift[..., :-1] = x[..., 1:]  # shift left = sample right
    want = _conv_oracle(x_shift, f)
    # both edges touch zero-padding differently (the shifted-image
    # oracle pads where the deformable op samples real pixels): compare
    # the interior columns where the identity is exact
    np.testing.assert_allclose(got[..., 1:-2], want[..., 1:-2],
                               rtol=1e-4, atol=1e-5)
