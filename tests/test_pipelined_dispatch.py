"""Pipelined step dispatch (PR 5): async StepHandle fetches, the bounded
in-flight window (FLAGS_max_inflight_steps), window-drain telemetry, and
the DataLoader device-side input prefetch stage.

Acceptance oracles:
- pipelined mode (the default) is bitwise-parity with sync mode
  (FLAGS_max_inflight_steps=0) over a multi-step train run with live
  dropout RNG and Momentum slots;
- dispatch backpressures at the window cap and drains on fetch;
- a checkpoint snapshot taken mid-pipeline drains the window first and
  captures the exact state a sync run would have (crash-resume parity);
- the CPU micro-bench: with a simulated slow input source, per-step
  host-blocking time drops >= 2x vs sync mode;
- input_wait_seconds / fetch_sync_seconds / executor_inflight_steps /
  h2d_bytes_per_step ride /metrics (prometheus exposition).
"""
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, observe
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.framework.executor import StepHandle
from paddle_tpu.io import DataLoader, DevicePrefetcher, TensorDataset
from paddle_tpu.monitor import stat_get
from paddle_tpu.optimizer import MomentumOptimizer


@pytest.fixture
def window(request):
    """Set FLAGS_max_inflight_steps for a test; restore the default."""

    def set_to(n):
        pt.set_flags({"FLAGS_max_inflight_steps": n})

    yield set_to
    pt.set_flags({"FLAGS_max_inflight_steps": 2})


def _train_model(seed=3):
    """fc -> dropout (consumes RNG) -> fc, MSE, Momentum: parameters,
    velocity slots, and the RNG key are all live state."""
    main, startup = Program(), Program()
    main.random_seed = seed
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1])
        h = layers.fc(x, 16, act="relu")
        h = layers.dropout(h, 0.3)
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        MomentumOptimizer(0.05, 0.9).minimize(loss)
    return main, startup, loss


def _batches(n_steps, batch=16):
    rs = np.random.RandomState(0)
    X = rs.randn(n_steps, batch, 8).astype("f4")
    Y = X.sum(2, keepdims=True).astype("f4") * 0.3
    return [(X[i], Y[i]) for i in range(n_steps)]


def _heavy_model(width=800, depth=16):
    """Forward-only fc chain sized so one step takes a measurable wall
    time on CPU (the device work the pipeline must hide)."""
    main, startup = Program(), Program()
    main.random_seed = 1
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [width])
        h = x
        for _ in range(depth):
            h = layers.fc(h, width, act="tanh", bias_attr=False)
        out = layers.mean(h)
    return main, startup, out


def _run_training(n_steps, max_inflight, seed=3, read_each=True):
    """Fresh program/scope/executor train loop; returns (losses, host
    state snapshot, executor, scope)."""
    pt.set_flags({"FLAGS_max_inflight_steps": max_inflight})
    main, startup, loss = _train_model(seed=seed)
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.framework.Scope()
    exe.run(startup, scope=sc)
    handles = []
    for bx, by in _batches(n_steps):
        handles.append(exe.run(main, feed={"x": bx, "y": by},
                               fetch_list=[loss], scope=sc))
    if read_each:
        losses = [float(np.asarray(h[0]).ravel()[0]) for h in handles]
    else:
        losses = None
    exe.drain()
    state = {n: np.asarray(sc.get_var(n)) for n in sorted(sc.local_var_names())
             if hasattr(sc.get_var(n), "dtype")}
    return losses, state, exe, sc


# ---------------------------------------------------------------------------
# async-vs-sync bitwise parity
# ---------------------------------------------------------------------------


def test_async_sync_bitwise_loss_and_state_parity(window):
    """THE parity oracle: 8 train steps with dropout RNG and Momentum
    velocity slots — pipelined (default window 2) must be bitwise the
    sync run (window 0), losses AND final state (params, slots, RNG)."""
    try:
        sync_l, sync_s, _, _ = _run_training(8, max_inflight=0)
        pipe_l, pipe_s, _, _ = _run_training(8, max_inflight=2)
    finally:
        window(2)
    assert sync_l == pipe_l
    assert set(sync_s) == set(pipe_s)
    for n in sync_s:
        np.testing.assert_array_equal(sync_s[n], pipe_s[n], err_msg=n)


def test_handle_semantics(window):
    """StepHandle is a lazy list: items materialize (and cache) on
    access, numpy() syncs everything, device_arrays() never syncs, and
    a return_numpy=False handle yields device arrays."""
    window(2)
    main, startup, loss = _train_model()
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.framework.Scope()
    exe.run(startup, scope=sc)
    bx, by = _batches(1)[0]
    h = exe.run(main, feed={"x": bx, "y": by}, fetch_list=[loss], scope=sc)
    assert isinstance(h, StepHandle) and isinstance(h, list)
    assert len(h) == 1
    raw = h.device_arrays()[0]
    assert hasattr(raw, "sharding")  # still a device array: no sync yet
    v = h[0]
    assert isinstance(v, np.ndarray)
    assert h[0] is v  # cached in place
    assert h.numpy()[0] is v
    # unpacking / iteration work like a list
    (only,) = h
    assert only is v
    # return_numpy=False: device arrays on access
    h2 = exe.run(main, feed={"x": bx, "y": by}, fetch_list=[loss], scope=sc,
                 return_numpy=False)
    assert hasattr(h2[0], "sharding")
    assert np.isfinite(np.asarray(h2.numpy()[0])).all()
    exe.drain()


def test_nan_scan_raises_inside_the_run(window):
    """FLAGS_check_nan_inf forces an immediate window drain, so the
    raise still happens inside the offending run() call even in
    pipelined mode."""
    window(2)
    main, startup = Program(), Program()
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [3])
        y = layers.log(x)
        z = layers.scale(y, 2.0)
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.framework.Scope()
    exe.run(startup, scope=sc)
    pt.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(RuntimeError, match="log"):
            exe.run(main, feed={"x": np.array([[-1.0, 2.0, 3.0]], "f4")},
                    fetch_list=[z], scope=sc)
        assert len(exe._window) == 0  # the failed step is not in flight
    finally:
        pt.set_flags({"FLAGS_check_nan_inf": False})


# ---------------------------------------------------------------------------
# backpressure + the host-blocking micro-bench
# ---------------------------------------------------------------------------


def test_inflight_window_backpressure_and_drain_on_fetch(window):
    """Dispatch is free until the cap, blocks AT the cap (draining the
    oldest step), and reading a handle drains through its step."""
    window(2)
    main, startup, out = _heavy_model()
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.framework.Scope()
    exe.run(startup, scope=sc)
    feed = {"x": np.random.RandomState(0).randn(768, 800).astype("f4")}
    # warm (compile) + measure one sync step
    exe.run(main, feed=feed, fetch_list=[out], scope=sc).numpy()
    t0 = time.perf_counter()
    exe.run(main, feed=feed, fetch_list=[out], scope=sc).numpy()
    t_step = time.perf_counter() - t0
    assert len(exe._window) == 0

    def dispatch():
        t0 = time.perf_counter()
        h = exe.run(main, feed=feed, fetch_list=[out], scope=sc)
        return h, time.perf_counter() - t0

    h1, d1 = dispatch()
    h2, d2 = dispatch()
    assert len(exe._window) == 2
    assert stat_get("executor_inflight_steps") == 2
    h3, d3 = dispatch()  # cap hit: must wait for step 1 to complete
    assert len(exe._window) == 2  # 1 drained, 3 pushed
    # under the cap dispatch is async (a small fraction of a step);
    # at the cap it blocks for about the remaining step time
    assert d1 < t_step / 2, (d1, t_step)
    assert d2 < t_step / 2, (d2, t_step)
    assert d3 > t_step / 4, (d3, t_step)
    assert h1._entry.drained  # the oldest step was drained by backpressure
    # reading the NEWEST handle drains everything up to and incl. it
    h3.numpy()
    assert len(exe._window) == 0
    assert h2._entry.drained
    exe.drain()


def test_host_blocking_drops_2x_with_slow_input_source(window):
    """Acceptance micro-bench: a training loop fed by a slow input
    source (sleep ~ one step time per batch).  Sync mode blocks ~a full
    step per iteration; pipelined mode overlaps input wait with device
    compute, so per-step host-blocking time must drop >= 2x."""
    main, startup, out = _heavy_model()
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.framework.Scope()
    exe.run(startup, scope=sc)
    feed = {"x": np.random.RandomState(0).randn(768, 800).astype("f4")}
    exe.run(main, feed=feed, fetch_list=[out], scope=sc).numpy()  # compile
    t0 = time.perf_counter()
    exe.run(main, feed=feed, fetch_list=[out], scope=sc).numpy()
    t_step = time.perf_counter() - t0

    n_steps = 6
    t_input = t_step * 1.2  # the "slow" input source

    def run_mode(max_inflight):
        window(max_inflight)
        blocking = 0.0
        handles = []
        for _ in range(n_steps):
            time.sleep(t_input)  # simulated input pipeline
            t0 = time.perf_counter()
            h = exe.run(main, feed=feed, fetch_list=[out], scope=sc)
            if max_inflight == 0:
                np.asarray(h[0])  # sync mode reads every step
            else:
                handles.append(h)
            blocking += time.perf_counter() - t0
        for h in handles:
            h.numpy()  # final sync is outside the per-step measurement
        exe.drain()
        return blocking / n_steps

    try:
        sync_block = run_mode(0)
        pipe_block = run_mode(2)
    finally:
        window(2)
    assert pipe_block * 2 <= sync_block, (
        f"pipelined host-blocking {pipe_block * 1e3:.2f}ms/step did not "
        f"drop 2x vs sync {sync_block * 1e3:.2f}ms/step "
        f"(step {t_step * 1e3:.1f}ms)")


# ---------------------------------------------------------------------------
# checkpoint quiescence
# ---------------------------------------------------------------------------


def test_ckpt_snapshot_mid_pipeline_drains_and_matches_sync(window, tmp_path):
    """A snapshot taken while steps are still in flight must drain the
    window first and capture bitwise the state a sync run has at the
    same step; resuming from it continues bitwise-identically."""
    from paddle_tpu.ckpt import CheckpointManager, restore_scope

    # sync reference: 5 steps, snapshot state at step 3
    try:
        window(0)
        main, startup, loss = _train_model()
        exe = pt.Executor(pt.CPUPlace())
        sc = pt.framework.Scope()
        exe.run(startup, scope=sc)
        sync_losses = []
        sync_state3 = None
        for i, (bx, by) in enumerate(_batches(5), 1):
            o = exe.run(main, feed={"x": bx, "y": by}, fetch_list=[loss],
                        scope=sc)
            sync_losses.append(float(np.asarray(o[0]).ravel()[0]))
            if i == 3:
                from paddle_tpu.ckpt import snapshot_scope

                sync_state3 = snapshot_scope(sc)

        # pipelined run: dispatch 3 steps, save mid-pipeline WITHOUT
        # reading anything — the manager must drain before snapshotting
        window(2)
        main2, startup2, loss2 = _train_model()
        exe2 = pt.Executor(pt.CPUPlace())
        sc2 = pt.framework.Scope()
        exe2.run(startup2, scope=sc2)
        handles = []
        for bx, by in _batches(3):
            handles.append(exe2.run(main2, feed={"x": bx, "y": by},
                                    fetch_list=[loss2], scope=sc2))
        assert len(exe2._window) > 0  # steps genuinely in flight
        m = CheckpointManager(str(tmp_path), keep_n=0, async_save=False)
        m.save(3, scope=sc2, wait=True)
        assert len(exe2._window) == 0  # snapshot drained the pipeline
        m.close()
        pipe_losses = [float(np.asarray(h[0]).ravel()[0]) for h in handles]
        assert pipe_losses == sync_losses[:3]

        # the committed snapshot is bitwise the sync run's step-3 state
        m2 = CheckpointManager(str(tmp_path), keep_n=0, async_save=False)
        meta = m2.restore()
        assert meta is not None and meta["step"] == 3
        assert set(meta["state"]) == set(sync_state3)
        for n in sync_state3:
            np.testing.assert_array_equal(
                np.asarray(meta["state"][n]), np.asarray(sync_state3[n]),
                err_msg=n)

        # crash-resume parity: restore into a fresh process-alike and
        # run steps 4..5 pipelined -> bitwise the uninterrupted run
        main3, startup3, loss3 = _train_model()
        exe3 = pt.Executor(pt.CPUPlace())
        sc3 = pt.framework.Scope()
        exe3.run(startup3, scope=sc3)
        restore_scope(sc3, meta["state"])
        m2.close()
        resumed = []
        for bx, by in _batches(5)[3:]:
            o = exe3.run(main3, feed={"x": bx, "y": by}, fetch_list=[loss3],
                         scope=sc3)
            resumed.append(float(np.asarray(o[0]).ravel()[0]))
        assert resumed == sync_losses[3:]
    finally:
        window(2)


# ---------------------------------------------------------------------------
# DataLoader device prefetch
# ---------------------------------------------------------------------------


class _FailingDataset:
    def __len__(self):
        return 10

    def __getitem__(self, i):
        if i >= 6:
            raise ValueError(f"boom at {i}")
        return np.float32(i)


def test_device_prefetch_ordering_and_types():
    X = np.arange(128, dtype="f4").reshape(64, 2)
    Y = (np.arange(64, dtype="f4") * 2).reshape(64, 1)
    dl = DataLoader(TensorDataset([X, Y]), batch_size=8, shuffle=False,
                    device_prefetch=True)
    got = list(dl)
    assert len(got) == 8
    for i, (bx, by) in enumerate(got):
        # leaves arrive ON DEVICE, in order, value-identical
        assert hasattr(bx, "sharding") and hasattr(by, "sharding")
        np.testing.assert_array_equal(np.asarray(bx), X[i * 8:(i + 1) * 8])
        np.testing.assert_array_equal(np.asarray(by), Y[i * 8:(i + 1) * 8])


def test_device_prefetch_exception_propagates():
    dl = DataLoader(_FailingDataset(), batch_size=2, device_prefetch=True)
    with pytest.raises(ValueError, match="boom"):
        list(dl)


def test_device_prefetch_passes_device_arrays_through():
    import jax

    src = [(jax.device_put(np.full(3, i, "f4")),) for i in range(4)]
    outs = list(DevicePrefetcher(iter(src)))
    assert len(outs) == 4
    for (o,), (s,) in zip(outs, src):
        assert o is s  # no copy, no re-transfer


def test_device_prefetch_feeds_pipelined_executor(window):
    """End to end: device-prefetched batches feed pipelined Executor.run
    and produce the same losses as a host-fed sync loop."""
    X = np.random.RandomState(7).randn(32, 8).astype("f4")
    Y = X.sum(1, keepdims=True).astype("f4") * 0.3
    try:
        results = {}
        for mode, (win, dev) in {"sync": (0, False),
                                 "pipe": (2, True)}.items():
            window(win)
            main, startup, loss = _train_model()
            exe = pt.Executor(pt.CPUPlace())
            sc = pt.framework.Scope()
            exe.run(startup, scope=sc)
            dl = DataLoader(TensorDataset([X, Y]), batch_size=8,
                            shuffle=False, device_prefetch=dev)
            handles = [exe.run(main, feed={"x": bx, "y": by},
                               fetch_list=[loss], scope=sc)
                       for bx, by in dl]
            results[mode] = [float(np.asarray(h[0]).ravel()[0])
                             for h in handles]
            exe.drain()
        assert results["sync"] == results["pipe"]
    finally:
        window(2)


# ---------------------------------------------------------------------------
# metrics exposure
# ---------------------------------------------------------------------------


def test_pipeline_metrics_ride_the_metrics_route(window):
    """input_wait_seconds / fetch_sync_seconds histograms, the
    executor_inflight_steps gauge, and the h2d byte counters all render
    in the prometheus text served by the fleet KV server's /metrics
    route (test_observe pins that the route serves this exposition)."""
    window(2)
    X = np.random.RandomState(0).randn(16, 8).astype("f4")
    Y = X.sum(1, keepdims=True).astype("f4")
    main, startup, loss = _train_model()
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.framework.Scope()
    exe.run(startup, scope=sc)
    dl = DataLoader(TensorDataset([X, Y]), batch_size=8, shuffle=False,
                    device_prefetch=True)
    for bx, by in dl:
        exe.run(main, feed={"x": bx, "y": by}, fetch_list=[loss], scope=sc)
    exe.drain()
    assert observe.histogram("input_wait_seconds").count > 0
    assert observe.histogram("fetch_sync_seconds").count > 0
    text = observe.prometheus_text()
    assert "paddle_tpu_executor_inflight_steps" in text
    assert "paddle_tpu_input_wait_seconds_bucket{" in text
    assert "paddle_tpu_fetch_sync_seconds_bucket{" in text
    assert "paddle_tpu_h2d_bytes_per_step" in text
    assert "paddle_tpu_h2d_bytes_total" in text


def test_inflight_gauge_sums_across_executors(window):
    """executor_inflight_steps totals every live Executor's window — a
    per-window write would flap between unrelated executors."""
    window(2)
    bx, by = _batches(1)[0]
    exes = []
    for seed in (3, 4):
        main, startup, loss = _train_model(seed=seed)
        exe = pt.Executor(pt.CPUPlace())
        sc = pt.framework.Scope()
        exe.run(startup, scope=sc)
        exe.drain()
        exe.run(main, feed={"x": bx, "y": by}, fetch_list=[loss], scope=sc)
        exes.append(exe)
    assert stat_get("executor_inflight_steps") == 2  # 1 + 1, not "last"
    for exe in exes:
        exe.drain()
    assert stat_get("executor_inflight_steps") == 0


def test_exhausted_prefetch_iterators_keep_raising_stopiteration():
    """Re-entering an exhausted iterator must raise StopIteration, not
    block forever on the empty queue (the single _END marker is gone)."""
    X = np.zeros((4, 1), "f4")
    it = iter(DataLoader(TensorDataset([X]), batch_size=2,
                         device_prefetch=True))
    assert len(list(it)) == 2
    for _ in range(2):
        with pytest.raises(StopIteration):
            next(it)
    it2 = iter(DataLoader(TensorDataset([X]), batch_size=2))
    list(it2)
    for _ in range(2):
        with pytest.raises(StopIteration):
            next(it2)


def test_device_prefetcher_wrapping_a_loader_records_wait_once():
    """Wrapping a DataLoader directly must still suppress the INNER
    stage's input_wait recording (its queue waits are background idle
    time): exactly one observation per consumer get."""
    X = np.zeros((8, 1), "f4")
    dl = DataLoader(TensorDataset([X]), batch_size=2)  # buffered reader
    observe.histogram("input_wait_seconds").reset()
    got = list(DevicePrefetcher(dl))
    assert len(got) == 4
    # 4 batches + the END get — the inner _PrefetchIterator adds none
    assert observe.histogram("input_wait_seconds").count == 5


def test_telemetry_drain_parks_failures_for_the_next_raising_point(window):
    """A drain failure hit on the telemetry path (StepTimer.summary,
    raise_errors=False) must not be swallowed: it is parked on the
    window and re-raised at the next raising drain point, exactly
    once."""
    from paddle_tpu.framework.executor import _InflightStep

    window(2)
    exe = pt.Executor(pt.CPUPlace())
    bad = _InflightStep(
        sync_refs=(), nan_flags=np.zeros((1,), bool),
        nan_ops=(("log", "<test>"),), t_dispatch=0.0, steps=1,
        examples=0, compiled=False, flops_per_step=0.0, allreduce_bytes=0)
    exe._window.push(bad)
    observe.step_timer().summary()  # telemetry read: must not raise
    assert len(exe._window) == 0  # the entry was drained (and parked)
    with pytest.raises(RuntimeError, match="log"):
        exe.drain()  # the parked failure is delivered here
    exe.drain()  # ... and only once


def test_step_timer_summary_drains_the_window(window):
    """StepTimer.summary() is a telemetry read point: it must reflect
    completed steps even when nothing was ever fetched."""
    window(2)
    observe.reset_step_stats()
    main, startup, loss = _train_model()
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.framework.Scope()
    exe.run(startup, scope=sc)
    for bx, by in _batches(4):
        exe.run(main, feed={"x": bx, "y": by}, fetch_list=[loss], scope=sc)
    s = observe.step_timer().summary()
    assert len(exe._window) == 0
    # startup + first main run are compiles; the other 3 are steps
    assert s["steps"] == 3
    assert s["step_time_s"]["count"] == 3
