"""Static-graph checkpointing + inference export + predictor.

Parity model: reference io.py save/load_persistables (:620/:994) via
save/load ops (save_op.cc:85), save_inference_model:1198 /
load_inference_model:1424, AnalysisPredictor (analysis_predictor.h:82),
paddle.save/load (framework/io.py).  Oracle: train -> save -> fresh scope
(and a real fresh process) -> load -> resume produces identical losses.
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.fluid import io as fluid_io
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.optimizer import MomentumOptimizer


def _build():
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.param_attr import ParamAttr

    main, startup = Program(), Program()
    main.random_seed = 3
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        h = layers.fc(x, 8, act="relu", param_attr=ParamAttr(
            initializer=ConstantInitializer(0.3)))
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        MomentumOptimizer(0.05, 0.9).minimize(loss)
    return main, startup, loss, pred


def _data():
    rng = np.random.RandomState(7)
    X = rng.randn(16, 4).astype("f4")
    Y = (X @ rng.randn(4, 1) * 0.5).astype("f4")
    return X, Y


def _step(exe, main, loss, X, Y, scope):
    return float(np.asarray(exe.run(
        main, feed={"x": X, "y": Y}, fetch_list=[loss],
        scope=scope)[0]).item())


@pytest.mark.parametrize("filename", [None, "all_params"])
def test_save_load_persistables_resume_parity(tmp_path, filename):
    X, Y = _data()
    ckpt = str(tmp_path / "ckpt")

    main, startup, loss, _ = _build()
    sc = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=sc)
    for _ in range(3):
        _step(exe, main, loss, X, Y, sc)
    from paddle_tpu.framework.scope import _switch_scope

    old = _switch_scope(sc)
    try:
        fluid_io.save_persistables(exe, ckpt, main, filename=filename)
    finally:
        _switch_scope(old)
    expected = [_step(exe, main, loss, X, Y, sc) for _ in range(3)]

    # fresh scope + fresh executor: load and resume
    sc2 = pt.framework.Scope()
    exe2 = pt.Executor(pt.CPUPlace())
    exe2.run(startup, scope=sc2)
    old = _switch_scope(sc2)
    try:
        fluid_io.load_persistables(exe2, ckpt, main, filename=filename)
    finally:
        _switch_scope(old)
    got = [_step(exe2, main, loss, X, Y, sc2) for _ in range(3)]
    np.testing.assert_allclose(expected, got, rtol=1e-6, atol=1e-7)


def test_resume_in_fresh_process(tmp_path):
    """The reference oracle is a literally-new process (auto-checkpoint
    resume, executor.py:1200)."""
    script = textwrap.dedent("""
        import sys
        import numpy as np
        sys.path.insert(0, {repo!r})
        sys.path.insert(0, {tests!r})
        import conftest  # forces cpu backend + 8 virtual devices
        import paddle_tpu as pt
        from paddle_tpu.fluid import io as fluid_io
        from paddle_tpu.framework.scope import _switch_scope
        from test_checkpoint_io import _build, _data, _step

        phase = sys.argv[1]
        ckpt = sys.argv[2]
        X, Y = _data()
        main, startup, loss, _ = _build()
        sc = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=sc)
        old = _switch_scope(sc)
        if phase == "train":
            _switch_scope(old)
            for _ in range(3):
                _step(exe, main, loss, X, Y, sc)
            old = _switch_scope(sc)
            fluid_io.save_persistables(exe, ckpt, main)
            _switch_scope(old)
        else:
            fluid_io.load_persistables(exe, ckpt, main)
            _switch_scope(old)
        out = [_step(exe, main, loss, X, Y, sc) for _ in range(3)]
        print("LOSSES:" + ",".join(f"{v:.9f}" for v in out))
    """)
    script = script.replace(
        "{repo!r}",
        repr(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    script = script.replace(
        "{tests!r}", repr(os.path.dirname(os.path.abspath(__file__))))
    ckpt = str(tmp_path / "ckpt")

    def run(phase):
        r = subprocess.run([sys.executable, "-c", script, phase, ckpt],
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr
        line = [l for l in r.stdout.splitlines()
                if l.startswith("LOSSES:")][0]
        return [float(v) for v in line[len("LOSSES:"):].split(",")]

    first = run("train")
    second = run("resume")
    np.testing.assert_allclose(first, second, rtol=1e-6, atol=1e-7)


def test_save_inference_model_and_predictor(tmp_path):
    X, Y = _data()
    model_dir = str(tmp_path / "infer_model")

    main, startup, loss, pred = _build()
    sc = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=sc)
    for _ in range(3):
        _step(exe, main, loss, X, Y, sc)

    from paddle_tpu.framework.scope import _switch_scope

    old = _switch_scope(sc)
    try:
        fluid_io.save_inference_model(model_dir, ["x"], [pred], exe, main)
    finally:
        _switch_scope(old)
    assert os.path.exists(os.path.join(model_dir, "__model__"))

    # independent numpy oracle from the params as saved
    w1 = np.asarray(sc.get_var("fc_0.w_0"))
    b1 = np.asarray(sc.get_var("fc_0.b_0"))
    w2 = np.asarray(sc.get_var("fc_1.w_0"))
    b2 = np.asarray(sc.get_var("fc_1.b_0"))
    direct = np.maximum(X @ w1 + b1, 0) @ w2 + b2

    # low-level load path
    exe2 = pt.Executor(pt.CPUPlace())
    prog2, feeds, targets = fluid_io.load_inference_model(model_dir, exe2)
    assert feeds == ["x"]
    out = np.asarray(exe2.run(prog2, feed={"x": X},
                              fetch_list=targets)[0])
    np.testing.assert_allclose(direct, out, rtol=1e-5, atol=1e-6)
    # pruning removed the label branch and the optimizer
    assert all(op.type not in ("momentum", "sgd")
               for op in prog2.global_block.ops)

    # export carries only the serving surface: no optimizer state
    exported = set(os.listdir(model_dir))
    assert not any("velocity" in n or "learning_rate" in n
                   for n in exported), exported

    # predictor (compile-once serve path); must not clobber global scope
    from paddle_tpu.framework.scope import global_scope
    from paddle_tpu.inference import Config, create_predictor

    global_scope().set_var("fc_0.w_0", np.float32(123.0))
    predictor = create_predictor(Config(model_dir))
    assert float(np.asarray(global_scope().get_var("fc_0.w_0"))) == 123.0
    assert predictor.get_input_names() == ["x"]
    out2 = np.asarray(predictor.run({"x": X})[0])
    np.testing.assert_allclose(direct, out2, rtol=1e-5, atol=1e-6)
    with pytest.raises(KeyError):
        predictor.run({"not_x": X})


import collections

Rec = collections.namedtuple("Rec", ["a", "b"])


def test_paddle_save_namedtuple(tmp_path):
    path = str(tmp_path / "rec.bin")
    pt.save(Rec(a=np.ones(3, "f4"), b=2.0), path)
    loaded = pt.load(path)
    np.testing.assert_allclose(loaded.a, np.ones(3))
    assert loaded.b == 2.0


def test_paddle_save_load_state_dict(tmp_path):
    path = str(tmp_path / "model.pdparams")
    from paddle_tpu import nn

    with pt.dygraph.guard():
        layer = nn.Linear(4, 2)
        sd = layer.state_dict()
        pt.save(sd, path)
        loaded = pt.load(path)
        assert set(loaded) == set(sd)
        for k in sd:
            np.testing.assert_allclose(np.asarray(sd[k].numpy()),
                                       loaded[k], rtol=1e-7)
        layer2 = nn.Linear(4, 2)
        layer2.set_state_dict(loaded)
        x = pt.to_tensor(np.ones((3, 4), "f4"))
        np.testing.assert_allclose(layer(x).numpy(), layer2(x).numpy(),
                                   rtol=1e-6)


def test_paddle_save_load_program(tmp_path):
    path = str(tmp_path / "prog.pdmodel")
    main, _, loss, _ = _build()
    pt.save(main, path)
    loaded = pt.load(path)
    assert [op.type for op in loaded.global_block.ops] == \
        [op.type for op in main.global_block.ops]


def test_load_errors(tmp_path):
    bad = tmp_path / "bad.bin"
    bad.write_bytes(b"NOTMAGIC" + b"x" * 16)
    with pytest.raises(ValueError, match="magic"):
        pt.load(str(bad))
    with pytest.raises(FileNotFoundError):
        pt.load(str(tmp_path / "missing.bin"))
