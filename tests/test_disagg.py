"""Disaggregated prefill/decode serving (paddle_tpu.serving.disagg):
deterministic routing, KV-page migration bitwise parity, the
migrated-page cache audit, chaos-driven prefill-replica death with
zero drops, and the SLO autoscaler's hysteresis/cooldown policy.

The load-bearing oracle: a request served disaggregated (prefill on
one engine, pages migrated, decode on another) must produce BITWISE
the same tokens and logits as the same request served locally with the
same seed — plain and kv_quant pools both.
"""
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.elastic import chaos
from paddle_tpu.framework.scope import Scope
from paddle_tpu.monitor import stat_get
from paddle_tpu.serving.decode import DecodeConfig, DecodeEngine, \
    TransformerLM
from paddle_tpu.serving.disagg import Autoscaler, DisaggConfig, \
    DisaggServer
from paddle_tpu.serving.kv_cache import CacheConfig, PagedKVCache
from paddle_tpu.serving.server import least_loaded_order

VOCAB = 61


@pytest.fixture(scope="module")
def model_and_weights():
    import jax

    model = TransformerLM(vocab_size=VOCAB, d_model=32, num_layers=2,
                          num_heads=2, max_seq_len=256)
    weights = model.init_weights(jax.random.PRNGKey(7))
    return model, weights


def _decode_cfg(**kw):
    cfg = dict(slots=2, max_seq_len=32, page_size=8, max_new_tokens=6)
    cfg.update(kw)
    return DecodeConfig(**cfg)


# -- satellite: deterministic least-loaded tie-break ----------------------


class _FakeEngine:
    def __init__(self, free_slots, queue_depth):
        self.free_slots = free_slots
        self.queue_depth = queue_depth


def test_least_loaded_tie_break_is_lowest_index():
    # four identical replicas: the order must be the INDEX order, not
    # an iteration-order accident
    engines = [_FakeEngine(2, 0) for _ in range(4)]
    assert least_loaded_order(engines) == engines
    # ties broken only after (free_slots desc, queue_depth asc)
    a, b, c, d = (_FakeEngine(1, 2), _FakeEngine(2, 1),
                  _FakeEngine(2, 1), _FakeEngine(2, 0))
    assert least_loaded_order([a, b, c, d]) == [d, b, c, a]


# -- migration bitwise oracle ---------------------------------------------


def _run_disagg_vs_local(model_and_weights, kv_quant):
    model, weights = model_and_weights
    prompts = [[5, 4, 3, 2, 1, 6, 7, 8],   # exactly one page
               list(range(1, 14)),          # two pages, partial tail
               [7]]                         # single-token prompt
    seeds = [11, 22, 33]
    srv = DisaggServer(
        model, weights, config=_decode_cfg(kv_quant=kv_quant),
        disagg=DisaggConfig(prefill_replicas=1, decode_replicas=1))
    with srv:
        dreqs = [srv.submit(p, max_new_tokens=5, temperature=1.0,
                            seed=s, record_logits=True)
                 for p, s in zip(prompts, seeds)]
        douts = [r.result(timeout=120) for r in dreqs]
    # engines stopped (threads joined): the audit can read the books
    # without racing the engine loop
    for rep in srv.replicas:
        rep.engine._cache.debug_check()
    local = DecodeEngine(model, weights,
                         _decode_cfg(kv_quant=kv_quant)).start()
    try:
        lreqs = [local.submit(p, max_new_tokens=5, temperature=1.0,
                              seed=s, record_logits=True)
                 for p, s in zip(prompts, seeds)]
        louts = [r.result(timeout=120) for r in lreqs]
    finally:
        local.stop()
    for p, dout, lout, dreq, lreq in zip(prompts, douts, louts, dreqs,
                                         lreqs):
        assert dout == lout, (
            f"migrated decode diverged from local for prompt {p}: "
            f"{dout} vs {lout}")
        dtrace = dreq.decode_request.logits_trace
        assert len(dtrace) == len(lreq.logits_trace) == 5
        for i, (dl, ll) in enumerate(zip(dtrace, lreq.logits_trace)):
            assert np.array_equal(np.asarray(dl), np.asarray(ll)), (
                f"logits diverged at step {i} for prompt {p}")


def test_migrated_decode_bitwise_equals_local(model_and_weights):
    before = stat_get("migrate_pages_total")
    _run_disagg_vs_local(model_and_weights, kv_quant=False)
    assert stat_get("migrate_pages_total") > before
    assert stat_get("decode_migrated_admissions") > 0
    assert stat_get("decode_kv_exports") > 0


def test_migrated_decode_bitwise_equals_local_kv_quant(
        model_and_weights):
    before = stat_get("migrate_bytes_total")
    _run_disagg_vs_local(model_and_weights, kv_quant=True)
    assert stat_get("migrate_bytes_total") > before


# -- migrated-page audit (cache level) ------------------------------------


def test_debug_check_migrated_page_audit():
    cfg = CacheConfig(2, 2, 8, num_slots=2, max_seq_len=32,
                      page_size=8, quantized=True)
    src = PagedKVCache(cfg, Scope())
    dst = PagedKVCache(cfg, Scope())
    prompt = list(range(1, 14))  # 13 tokens -> 2 pages
    assert src.claim(0, len(prompt) + 4, prompt=prompt) is not None
    export_pages = src.slot_pages(0)[:cfg.pages_for(len(prompt))]
    arrays = src.export_pages(export_pages)
    assert set(arrays) == set(src.state_var_names())
    assert dst.claim(0, len(prompt) + 4, prompt=None) is not None
    from paddle_tpu.serving.kv_cache import KVPageExport

    exp = KVPageExport(n_tokens=len(prompt), n_pages=2,
                       src_pages=export_pages, arrays=arrays,
                       quantized=True, page_size=8)
    dst.install_pages(0, exp)
    assert len(dst._migrated_in) == 2
    dst.debug_check()  # refcount 1, unregistered, live scales: OK
    # tamper: register a migrated page in the prefix index while it is
    # still slot-owned — the audit must catch the leaked sharing
    pid = dst.slot_pages(0)[0]
    dst.prefix.register([pid], prompt[:8], on_new=dst._incref)
    with pytest.raises(AssertionError, match="migrated-in page"):
        dst.debug_check()
    dst.prefix.evict(1, can_evict=lambda p: True,
                     on_evict=dst._decref)
    dst.debug_check()
    # release ends the invariant: pages become ordinary, audit stays
    # green and the tracking empties
    dst.release(0)
    assert not dst._migrated_in
    dst.debug_check()
    src.release(0)
    src.debug_check()


# -- chaos: prefill replica killed mid-stream -----------------------------


def test_chaos_prefill_kill_zero_drops(model_and_weights):
    model, weights = model_and_weights
    srv = DisaggServer(
        model, weights, config=_decode_cfg(),
        disagg=DisaggConfig(prefill_replicas=2, decode_replicas=1))
    deaths0 = stat_get("disagg_replica_deaths")
    redisp0 = stat_get("disagg_redispatches_total")
    chaos.clear()
    # the router's deterministic tie-break picks replica 0 first, so
    # arming replica=0 kills the FIRST request's prefill mid-stream
    chaos.inject("kill_prefill_replica", count=1, replica=0)
    try:
        with srv:
            reqs = [srv.submit([3 + i, 5, 7, 9, 2], max_new_tokens=4,
                               seed=100 + i) for i in range(4)]
            outs = [r.result(timeout=120) for r in reqs]
            # zero drops: every request produced its full budget
            assert all(len(o) == 4 for o in outs)
            assert stat_get("disagg_replica_deaths") == deaths0 + 1
            assert stat_get("disagg_redispatches_total") > redisp0
            assert [r.dead for r in srv.replicas] == [True, False,
                                                      False]
        # server stopped: the migrated-page audit holds on the
        # surviving fleet's books
        for rep in srv.replicas:
            if not rep.dead:
                rep.engine._cache.debug_check()
    finally:
        chaos.clear()


# -- autoscaler: re-role, hysteresis, cooldown, preflight -----------------


class _Signals:
    def __init__(self):
        self.burn = 0.0
        self.queue = 0.0
        self.now = 1000.0
        self.preflight_ok = True

    def clock(self):
        return self.now

    def sleep(self, s):
        self.now += s


def _roles(srv):
    return [r.role for r in srv.replicas]


def test_autoscaler_rerole_cooldown_and_preflight(model_and_weights):
    model, weights = model_and_weights
    srv = DisaggServer(
        model, weights, config=_decode_cfg(),
        disagg=DisaggConfig(prefill_replicas=1, decode_replicas=3,
                            autoscale_cooldown_s=30.0,
                            autoscale_burn_high=1.0,
                            autoscale_burn_low=0.25,
                            autoscale_queue_high=4))
    sig = _Signals()
    auto = Autoscaler(srv, burn_fn=lambda: sig.burn,
                      queue_fn=lambda: sig.queue,
                      preflight=lambda: sig.preflight_ok,
                      clock=sig.clock, sleep=sig.sleep)
    assert _roles(srv) == ["prefill", "decode", "decode", "decode"]
    # healthy signals: no action
    assert auto.tick() is None
    # induced ttft burn: one decode replica re-roles to prefill
    # (lowest index wins the tie — deterministic)
    sig.burn = 2.0
    reroles0 = stat_get("autoscale_reroles_total")
    skips0 = stat_get("autoscale_cooldown_skips_total")
    assert auto.tick() == "decode->prefill"
    assert _roles(srv) == ["prefill", "prefill", "decode", "decode"]
    assert stat_get("autoscale_reroles_total") == reroles0 + 1
    # still burning, but inside the cooldown window: counted + DROPPED
    # — the no-flap pin
    assert auto.tick() is None
    assert _roles(srv) == ["prefill", "prefill", "decode", "decode"]
    assert stat_get("autoscale_cooldown_skips_total") == skips0 + 1
    # cooldown elapsed, burn healthy, decode queue piling up: the
    # replica comes back (hysteresis: burn must sit UNDER burn_low)
    sig.now += 31.0
    sig.burn = 0.1
    sig.queue = 5.0
    assert auto.tick() == "prefill->decode"
    # the pick is least-loaded/lowest-index among PREFILL replicas, so
    # replica 0 (the original prefill) converts — deterministic
    assert _roles(srv) == ["decode", "prefill", "decode", "decode"]
    # queue pressure with burn INSIDE the hysteresis band: no action
    sig.now += 31.0
    sig.burn = 0.5
    assert auto.tick() is None
    # preflight failure aborts the re-role: roles unchanged, replica
    # undrained, failure counted
    sig.burn = 2.0
    sig.preflight_ok = False
    pf0 = stat_get("autoscale_preflight_failures")
    assert auto.tick() is None
    assert _roles(srv) == ["decode", "prefill", "decode", "decode"]
    assert stat_get("autoscale_preflight_failures") == pf0 + 1
    assert all(not r.draining for r in srv.replicas)


def test_autoscaler_thread_lifecycle(model_and_weights):
    model, weights = model_and_weights
    srv = DisaggServer(
        model, weights, config=_decode_cfg(),
        disagg=DisaggConfig(prefill_replicas=1, decode_replicas=1,
                            autoscale_interval_s=0.01))
    ticks = []
    auto = Autoscaler(srv, burn_fn=lambda: ticks.append(1) or 0.0,
                      queue_fn=lambda: 0.0,
                      preflight=lambda: True)
    auto.start()
    try:
        deadline = time.monotonic() + 5.0
        while len(ticks) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(ticks) >= 3, "autoscaler loop never ticked"
        assert stat_get("disagg_prefill_replicas") == 1
        assert stat_get("disagg_decode_replicas") == 1
    finally:
        auto.stop()
    assert auto._thread is None
