"""OpTest: numpy-oracle per-op parity harness.

Role parity: reference python/paddle/fluid/tests/unittests/op_test.py
(OpTest:226, check_output_with_place:1021, check_grad_with_place:1341) —
declare op_type / inputs / attrs / expected outputs in numpy; the harness
builds a one-op program, runs it through the real Executor, and compares.
check_grad compares append_backward analytic grads against numeric central
differences.
"""
from __future__ import annotations

import unittest

import numpy as np

import paddle_tpu as pt
from paddle_tpu.framework import dtypes
from paddle_tpu.framework.backward import append_backward
from paddle_tpu.framework.program import Program, program_guard


def _flatten_spec(spec):
    """inputs/outputs may be {slot: array} or {slot: [(name, array), ...]}."""
    flat = {}
    for slot, val in (spec or {}).items():
        if isinstance(val, list) and val and isinstance(val[0], tuple):
            flat[slot] = [(n, np.asarray(a)) for n, a in val]
        elif val is None:
            flat[slot] = []
        else:
            flat[slot] = [(f"{slot}_0" if slot != slot.upper() else slot, np.asarray(val))]
    return flat


class OpTest(unittest.TestCase):
    op_type: str = ""

    def setUp(self):
        self.inputs = {}
        self.outputs = {}
        self.attrs = {}
        if hasattr(self, "setup"):
            self.setup()

    # ------------------------------------------------------------------
    def _build(self, need_grad_of=None):
        prog = Program()
        startup = Program()
        feed = {}
        fetch = []
        with program_guard(prog, startup):
            block = prog.global_block
            in_spec = _flatten_spec(self.inputs)
            out_spec = _flatten_spec(self.outputs)
            op_inputs = {}
            for slot, pairs in in_spec.items():
                names = []
                for name, arr in pairs:
                    var = block.create_var(
                        name=name,
                        shape=arr.shape,
                        dtype=str(arr.dtype)
                        if arr.dtype.name != "bfloat16"
                        else "bfloat16",
                        stop_gradient=False
                        if np.issubdtype(arr.dtype, np.floating)
                        else True,
                    )
                    feed[name] = arr
                    names.append(name)
                op_inputs[slot] = names
            op_outputs = {}
            for slot, pairs in out_spec.items():
                names = []
                for name, arr in pairs:
                    block.create_var(name=name, shape=arr.shape, dtype=str(arr.dtype))
                    names.append(name)
                    fetch.append((slot, name, arr))
                op_outputs[slot] = names
            block.append_op(self.op_type, op_inputs, op_outputs, dict(self.attrs))
        return prog, feed, fetch

    # ------------------------------------------------------------------
    def check_output(self, atol=1e-5, rtol=1e-5, no_check_set=None, place=None):
        prog, feed, fetch = self._build()
        exe = pt.Executor(place or pt.CPUPlace())
        no_check = set(no_check_set or ())
        names = [n for _, n, _ in fetch]
        outs = exe.run(prog, feed=feed, fetch_list=names)
        for (slot, name, expect), got in zip(fetch, outs):
            if slot in no_check or name in no_check:
                continue
            got = np.asarray(got, dtype=np.asarray(expect).dtype)
            np.testing.assert_allclose(
                got,
                expect,
                atol=atol,
                rtol=rtol,
                err_msg=f"op {self.op_type}: output {slot}/{name} mismatch",
            )

    # ------------------------------------------------------------------
    def check_grad(
        self,
        inputs_to_check,
        output_name,
        max_relative_error=0.005,
        no_grad_set=None,
        numeric_delta=1e-3,
        user_defined_grads=None,
    ):
        """Analytic (append_backward) vs numeric central-difference grads of
        sum(output) w.r.t. each input in inputs_to_check."""
        prog, feed, fetch = self._build()
        with program_guard(prog):
            block = prog.global_block
            out_var = block.var(
                output_name
                if block.has_var(output_name)
                else _flatten_spec(self.outputs)[output_name][0][0]
            )
            # scalarize: loss = mean-like reduce via reduce_sum -> shape [1]
            loss_name = "__loss__"
            block.create_var(name=loss_name, shape=(), dtype="float32")
            ssum = "__loss_sum__"
            block.create_var(name=ssum, shape=(), dtype=out_var.dtype)
            block.append_op(
                "reduce_sum", {"X": out_var}, {"Out": ssum}, {"reduce_all": True}
            )
            block.append_op(
                "cast",
                {"X": ssum},
                {"Out": loss_name},
                {"in_dtype": out_var.dtype, "out_dtype": dtypes.to_enum("float32")},
            )
            loss = block.var(loss_name)
            pg = append_backward(
                loss,
                parameter_list=list(inputs_to_check),
                no_grad_set=no_grad_set,
            )
        grad_names = {p.name: g.name for p, g in pg}
        exe = pt.Executor(pt.CPUPlace())
        missing = [n for n in inputs_to_check if n not in grad_names]
        assert not missing, f"no grad produced for {missing}"
        analytic = exe.run(
            prog, feed=feed, fetch_list=[grad_names[n] for n in inputs_to_check]
        )

        if user_defined_grads is not None:
            for name, got, expect in zip(inputs_to_check, analytic, user_defined_grads):
                self._assert_grad_close(got, np.asarray(expect), name, max_relative_error)
            return

        # numeric grads on a fresh forward-only program
        fprog, ffeed, ffetch = self._build()
        with program_guard(fprog):
            block = fprog.global_block
            out_var2 = block.var(out_var.name)
            block.create_var(name=ssum, shape=(), dtype=out_var2.dtype)
            block.append_op(
                "reduce_sum", {"X": out_var2}, {"Out": ssum}, {"reduce_all": True}
            )

        def f(feed_override):
            vals = exe.run(fprog, feed=feed_override, fetch_list=[ssum])
            return float(np.asarray(vals[0]))

        for name, got in zip(inputs_to_check, analytic):
            base = feed[name].astype(np.float64)
            num = np.zeros_like(base, dtype=np.float64)
            flat = base.ravel()
            nflat = num.ravel()
            for i in range(flat.size):
                for sgn, acc in ((1, 1.0), (-1, -1.0)):
                    pert = flat.copy()
                    pert[i] += sgn * numeric_delta
                    f2 = dict(feed)
                    f2[name] = pert.reshape(base.shape).astype(feed[name].dtype)
                    nflat[i] += acc * f(f2)
                nflat[i] /= 2 * numeric_delta
            self._assert_grad_close(np.asarray(got), num, name, max_relative_error)

    def _assert_grad_close(self, got, expect, name, max_rel):
        got = got.astype(np.float64)
        expect = expect.astype(np.float64)
        denom = np.maximum(np.abs(expect), 1.0)
        rel = np.abs(got - expect) / denom
        self.assertLessEqual(
            float(rel.max(initial=0.0)),
            max_rel,
            msg=f"op {self.op_type}: grad mismatch for {name}: "
            f"max rel err {rel.max(initial=0.0):.3e}\nanalytic={got}\nnumeric={expect}",
        )


def skip_check_grad_ci(reason=""):
    def deco(cls):
        return cls

    return deco
