"""Step-phase attribution + anomaly-triggered profiler capture.

observe/phases.py: the four-bucket wall-time decomposition (compute /
comm_exposed / host / input_wait, summing exactly to the inter-drain
wall), the deterministic compile-time cost model (hide-under-compute
overlap walk), and the per-collective exposed-vs-hidden ledger keyed by
FuseAllReducePass bucket identity.  observe/profiler_capture.py: the
rolling-baseline spike trigger, the one-bundle-per-episode latch +
cooldown, and the continuous low-duty-cycle mode.  All on the CPU
backend: the measured split comes from real drain timestamps, the
predicted split from static inputs only, so every assertion here is
deterministic.
"""
import io
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.monitor import stat_get
from paddle_tpu.observe import phases, profiler_capture
from paddle_tpu.optimizer import MomentumOptimizer

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _quench_slo_burn():
    """Zero any slo_burn_rate_*_ppm gauges an earlier test file left in
    the process-wide registry — the capture engine's SLO-burn trigger
    reads them, so stale induced violations would fire captures here."""
    from paddle_tpu.monitor import StatRegistry, stat_set

    for name, _v in StatRegistry.instance().export():
        if name.startswith("slo_burn_rate_") and name.endswith("_ppm"):
            stat_set(name, 0)


@pytest.fixture(autouse=True)
def _clean_phase_plane():
    """Fresh engines + default flags around every test."""
    _quench_slo_burn()
    phases.reset_phases()
    profiler_capture.reset_capture()
    yield
    profiler_capture.reset_capture()
    phases.reset_phases()
    pt.set_flags({"FLAGS_phase_attribution": True,
                  "FLAGS_phase_interconnect_gbps": 100.0,
                  "FLAGS_prof_trigger_ratio": 0.0,
                  "FLAGS_prof_capture_s": 2.0,
                  "FLAGS_prof_cooldown_s": 60.0,
                  "FLAGS_prof_continuous_s": 0.0,
                  "FLAGS_device_peak_tflops": 275.0,
                  "FLAGS_overlap_grad_allreduce": True,
                  "FLAGS_layer_scan": False})


def _mlp_program(depth=2, width=32, fleet_dp=False):
    from paddle_tpu.distributed import fleet

    main, startup = Program(), Program()
    main.random_seed = 1
    with program_guard(main, startup):
        x = layers.data("x", [width])
        label = layers.data("label", [1], dtype="int64")
        h = x
        for _ in range(depth):
            h = layers.fc(h, width, act="relu")
        logits = layers.fc(h, 10)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        opt = MomentumOptimizer(0.05, 0.9)
        if fleet_dp:
            fleet.init(is_collective=True)
            fleet.distributed_optimizer(opt)
            fleet.minimize(loss)
        else:
            opt.minimize(loss)
    return main, startup, loss


def _train(main, startup, loss, steps=4, width=32, batch=8):
    rs = np.random.RandomState(0)
    scope = pt.framework.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    for _ in range(steps):
        exe.run(main, feed={
            "x": rs.randn(batch, width).astype("f4"),
            "label": rs.randint(0, 10, (batch, 1)).astype("int64")},
            fetch_list=[loss], scope=scope)
    exe.close()


# ---------------------------------------------------------------------------
# PhasePlan: the deterministic cost model
# ---------------------------------------------------------------------------


class TestPhasePlan:
    def test_hide_under_compute_walk(self):
        # peak 1 TFLOP/s, 1 GB/s: 1e9 FLOPs = 1s compute budget
        pt.set_flags({"FLAGS_device_peak_tflops": 1e-3,
                      "FLAGS_phase_interconnect_gbps": 1.0})
        plan = phases.PhasePlan(1e9, [
            {"id": "a", "op": "ar", "dtype": "f4",
             "bytes": 400_000_000, "overlap": True},   # 0.4s, hides
            {"id": "b", "op": "ar", "dtype": "f4",
             "bytes": 800_000_000, "overlap": True},   # 0.8s, 0.6 budget
            {"id": "c", "op": "ar", "dtype": "f4",
             "bytes": 100_000_000, "overlap": False},  # never hides
        ])
        assert plan.compute_s == pytest.approx(1.0)
        assert plan.comm_hidden_s == pytest.approx(0.4 + 0.6)
        assert plan.comm_exposed_s == pytest.approx(0.2 + 0.1)
        by_id = {r["id"]: r for r in plan.ledger}
        assert by_id["a"]["hidden_s"] == pytest.approx(0.4)
        assert by_id["b"]["exposed_s"] == pytest.approx(0.2)
        assert by_id["c"]["exposed_s"] == pytest.approx(0.1)
        fr = plan.predicted_fractions()
        assert fr["compute"] + fr["comm_exposed"] == pytest.approx(1.0)

    def test_update_flops_recosts_hidden_budget(self):
        pt.set_flags({"FLAGS_device_peak_tflops": 1e-3,
                      "FLAGS_phase_interconnect_gbps": 1.0})
        coll = [{"id": "a", "op": "ar", "dtype": "f4",
                 "bytes": 500_000_000, "overlap": True}]  # 0.5s
        plan = phases.PhasePlan(1e8, coll)  # 0.1s budget: mostly exposed
        assert plan.comm_hidden_s == pytest.approx(0.1)
        plan.update_flops(1e9)  # 1s budget: fully hidden
        assert plan.comm_hidden_s == pytest.approx(0.5)
        assert plan.comm_exposed_s == pytest.approx(0.0)

    def test_deterministic_across_builds(self):
        coll = [{"id": "a", "op": "ar", "dtype": "f4",
                 "bytes": 12345, "overlap": True}]
        a = phases.PhasePlan(3e6, coll).to_dict()
        b = phases.PhasePlan(3e6, coll).to_dict()
        assert a == b


# ---------------------------------------------------------------------------
# PhaseEngine: the drain-side decomposition
# ---------------------------------------------------------------------------


class TestPhaseEngine:
    def test_buckets_sum_exactly_to_wall(self):
        eng = phases.PhaseEngine()
        split = eng.on_step_drained(wall_s=0.10, sync_s=0.03,
                                    host_s=0.02)
        assert split is not None
        assert sum(split.values()) == pytest.approx(0.10, abs=0)
        assert split["host"] == pytest.approx(0.02)
        assert split["compute"] == pytest.approx(0.03)  # no plan: all
        assert split["input_wait"] == pytest.approx(0.05)
        rep = eng.report()
        assert sum(rep["measured_fractions"].values()) == \
            pytest.approx(1.0, abs=5e-6)  # 4 fractions rounded to 6dp

    def test_sync_splits_by_plan_comm_fraction(self):
        pt.set_flags({"FLAGS_device_peak_tflops": 1e-3,
                      "FLAGS_phase_interconnect_gbps": 1.0})
        # compute 1s, exposed comm 1s -> predicted comm fraction 0.5
        plan = phases.PhasePlan(1e9, [
            {"id": "a", "op": "ar", "dtype": "f4",
             "bytes": 1_000_000_000, "overlap": False}])
        eng = phases.PhaseEngine()
        split = eng.on_step_drained(wall_s=0.08, sync_s=0.04,
                                    host_s=0.0, plan=plan)
        assert split["comm_exposed"] == pytest.approx(0.02)
        assert split["compute"] == pytest.approx(0.02)

    def test_host_and_sync_clamped_to_wall(self):
        eng = phases.PhaseEngine()
        split = eng.on_step_drained(wall_s=0.01, sync_s=0.5, host_s=0.5)
        assert sum(split.values()) == pytest.approx(0.01)
        assert all(v >= 0 for v in split.values())

    def test_compiled_steps_and_flag_off_are_skipped(self):
        eng = phases.PhaseEngine()
        assert eng.on_step_drained(0.1, 0.1, 0.0, compiled=True) is None
        pt.set_flags({"FLAGS_phase_attribution": False})
        assert eng.on_step_drained(0.1, 0.1, 0.0) is None
        pt.set_flags({"FLAGS_phase_attribution": True})
        assert eng.steps == 0

    def test_reset_zeroes_report_and_gauges(self):
        eng = phases.phase_engine()
        eng.on_step_drained(0.1, 0.05, 0.01)
        assert stat_get("phase_steps_attributed") >= 1
        phases.reset_phases()
        rep = phases.phases_report()
        assert rep["steps"] == 0 and rep["wall_s"] == 0.0
        assert stat_get("phase_compute_seconds_micro") == 0


# ---------------------------------------------------------------------------
# composition matrix: the split must hold on real compiled programs
# ---------------------------------------------------------------------------


class TestProgramComposition:
    def _report_for(self, fleet_dp=False, **flag_over):
        if flag_over:
            pt.set_flags({f"FLAGS_{k}": v for k, v in flag_over.items()})
        main, startup, loss = _mlp_program(
            depth=6 if flag_over.get("layer_scan") else 2,
            fleet_dp=fleet_dp)
        _train(main, startup, loss)
        return phases.phases_report()

    def _assert_sane(self, rep):
        assert rep["steps"] >= 3  # first (compile) drain skipped
        # each of the 4 fractions is rounded to 6dp in the report, so
        # the sum can be off by up to 2e-6
        assert sum(rep["measured_fractions"].values()) == \
            pytest.approx(1.0, abs=5e-6)
        assert rep["wall_s"] > 0
        assert all(v >= 0 for v in rep["measured_s"].values())

    def test_plain_program(self):
        rep = self._report_for()
        self._assert_sane(rep)
        # single device, no collectives: predicted split is all compute
        assert rep["predicted"]["predicted_fractions"]["compute"] == 1.0
        assert rep["ledger"] == []

    def test_dp_fused_program_has_bucket_ledger(self, mesh8):
        # slow modeled fabric so the tiny test grads price above the
        # report's µs rounding
        rep = self._report_for(fleet_dp=True,
                               phase_interconnect_gbps=1e-3)
        self._assert_sane(rep)
        assert rep["ledger"], "dp grad allreduces must be priced"
        assert any(r["id"].startswith("bucket:") for r in rep["ledger"])
        assert rep["comm_exposed_s"] + rep["comm_hidden_s"] > 0
        assert stat_get("comm_exposed_seconds_micro") >= 0

    def test_scanned_program_overlap_hides_carrier(self, mesh8):
        # big compute budget (tiny peak) so the stretched carrier
        # bucket hides fully under the edge-layer backward
        rep = self._report_for(fleet_dp=True, layer_scan=True,
                               overlap_grad_allreduce=True,
                               device_peak_tflops=1e-6,
                               phase_interconnect_gbps=1e-3)
        self._assert_sane(rep)
        assert stat_get("pass_overlap_stretched_buckets") >= 1
        hidden = [r for r in rep["ledger"] if r["hidden_s"] > 0]
        assert hidden, "stretched bucket must be modeled hidden"
        assert rep["comm_hidden_s"] > 0

    def test_flash_attention_program(self):
        pt.set_flags({"FLAGS_flash_attention": "always"})
        try:
            import math

            from paddle_tpu.initializer import NormalInitializer
            from paddle_tpu.param_attr import ParamAttr

            S, HEADS, D = 8, 2, 8
            HID = HEADS * D
            main, startup = Program(), Program()
            main.random_seed = 3
            with program_guard(main, startup):
                x = layers.data("x", [S, HID])
                y = layers.data("y", [S, HID])

                def proj(name):
                    t = layers.fc(x, HID, num_flatten_dims=2, name=name,
                                  param_attr=ParamAttr(
                                      initializer=NormalInitializer(
                                          0.0, 0.05)))
                    t = layers.reshape(t, [0, S, HEADS, D])
                    return layers.transpose(t, [0, 2, 1, 3])

                q, k, v = proj("aq"), proj("ak"), proj("av")
                scores = layers.matmul(q, k, transpose_y=True,
                                       alpha=1.0 / math.sqrt(D))
                probs = layers.softmax(scores)
                ctx = layers.matmul(probs, v)
                ctx = layers.reshape(
                    layers.transpose(ctx, [0, 2, 1, 3]), [0, S, HID])
                out = layers.fc(ctx, HID, num_flatten_dims=2)
                loss = layers.mean(layers.square_error_cost(out, y))
                MomentumOptimizer(0.05, 0.9).minimize(loss)
            rs = np.random.RandomState(0)
            scope = pt.framework.Scope()
            exe = pt.Executor(pt.CPUPlace())
            exe.run(startup, scope=scope)
            for _ in range(4):
                exe.run(main, feed={
                    "x": rs.randn(2, S, HID).astype("f4"),
                    "y": rs.randn(2, S, HID).astype("f4")},
                    fetch_list=[loss], scope=scope)
            exe.close()
        finally:
            pt.set_flags({"FLAGS_flash_attention": "auto"})
        assert stat_get("pass_flash_attention_fused") >= 1
        self._assert_sane(phases.phases_report())


class TestOverlapAB:
    def test_exposed_share_strictly_drops_with_stretching(self, mesh8):
        """The acceptance A/B: on the scanned dp program, the ledger's
        exposed share with FLAGS_overlap_grad_allreduce=1 is strictly
        below the =0 baseline (deterministic: both sides are the
        static cost model)."""
        shares = {}
        for overlap in (0, 1):
            phases.reset_phases()
            pt.set_flags({"FLAGS_overlap_grad_allreduce": bool(overlap),
                          "FLAGS_layer_scan": True,
                          "FLAGS_device_peak_tflops": 1e-6,
                          "FLAGS_phase_interconnect_gbps": 1e-3})
            main, startup, loss = _mlp_program(depth=6, fleet_dp=True)
            _train(main, startup, loss, steps=3)
            rep = phases.phases_report()
            assert rep["comm_exposed_s"] + rep["comm_hidden_s"] > 0
            shares[overlap] = rep["comm_exposed_share"]
        assert shares[1] < shares[0], shares
        assert shares[0] == pytest.approx(1.0)  # baseline hides nothing


# ---------------------------------------------------------------------------
# anomaly-triggered capture
# ---------------------------------------------------------------------------


class TestAnomalyCapture:
    def _engine(self, tmp_path, ratio=2.0, cooldown=60.0):
        pt.set_flags({"FLAGS_prof_trigger_ratio": ratio,
                      "FLAGS_prof_capture_s": 0.02,
                      "FLAGS_prof_cooldown_s": cooldown,
                      "FLAGS_postmortem_dir": str(tmp_path / "pm")})
        return profiler_capture.CaptureEngine(window=16, warmup=4)

    def test_spike_fires_exactly_one_bounded_capture(self, tmp_path):
        eng = self._engine(tmp_path)
        for _ in range(8):
            eng.on_step(0.010)
        for _ in range(5):        # sustained episode: latch holds
            eng.on_step(0.100)
        assert eng.wait(30)
        assert eng.captures == 1
        assert len(eng.bundles) == 1
        bundle = eng.bundles[0]
        assert os.path.basename(bundle).endswith("step_time_anomaly")
        ph = json.load(open(os.path.join(bundle, "phases.json")))
        assert set(ph) >= {"steps", "measured_fractions", "ledger"}
        meta = json.load(open(os.path.join(bundle, "meta.json")))
        assert "baseline" in meta["extra"]["trigger"]
        assert meta["extra"]["prof_capture_s"] == pytest.approx(0.02)
        assert stat_get("prof_captures_triggered") >= 1

    def test_latch_rearms_but_cooldown_blocks_refire(self, tmp_path):
        eng = self._engine(tmp_path, cooldown=3600.0)
        for _ in range(8):
            eng.on_step(0.010)
        eng.on_step(0.100)        # fire #1
        assert eng.wait(30)
        for _ in range(4):
            eng.on_step(0.010)    # episode over: re-arms
        eng.on_step(0.100)        # would fire, but inside cooldown
        assert eng.wait(30)
        assert eng.captures == 1

    def test_compiled_steps_never_feed_or_fire(self, tmp_path):
        eng = self._engine(tmp_path)
        for _ in range(8):
            eng.on_step(0.010)
        eng.on_step(10.0, compiled=True)  # a recompile is not a spike
        assert eng.wait(5)
        assert eng.captures == 0

    def test_zero_ratio_disables(self, tmp_path):
        eng = self._engine(tmp_path, ratio=0.0)
        for _ in range(20):
            eng.on_step(0.010)
        eng.on_step(9.9)
        assert eng.captures == 0

    def test_executor_spike_to_rendered_bundle(self, tmp_path):
        """End to end: an induced inter-drain stall on a real training
        loop produces exactly one bundle whose phases.json renders
        through the pure-stdlib CLI reader."""
        pt.set_flags({"FLAGS_prof_trigger_ratio": 4.0,
                      "FLAGS_prof_capture_s": 0.05,
                      "FLAGS_postmortem_dir": str(tmp_path / "pm")})
        main, startup, loss = _mlp_program()
        rs = np.random.RandomState(0)
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)

        def step():
            exe.run(main, feed={
                "x": rs.randn(8, 32).astype("f4"),
                "label": rs.randint(0, 10, (8, 1)).astype("int64")},
                fetch_list=[loss], scope=scope)

        for _ in range(12):
            step()
        time.sleep(0.25)  # the anomaly: one slow inter-drain gap
        step()
        for _ in range(3):
            step()
        exe.close()
        eng = profiler_capture.capture_engine()
        assert eng.wait(30)
        assert eng.captures == 1, "latch+cooldown: one bundle only"
        from tools import postmortem as pm

        out = io.StringIO()
        pm.render(eng.bundles[0], out=out)
        text = out.getvalue()
        assert "phase attribution" in text
        assert "step_time_anomaly" in text

    def test_continuous_mode_smoke_and_rotation(self, tmp_path):
        pt.set_flags({"FLAGS_prof_continuous_s": 0.05,
                      "FLAGS_prof_capture_s": 0.01,
                      "FLAGS_postmortem_dir": str(tmp_path / "pm")})
        eng = profiler_capture.capture_engine()
        assert eng.start_continuous()
        assert eng.start_continuous()  # idempotent
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                stat_get("prof_continuous_captures") + \
                stat_get("prof_trace_unavailable") < 2:
            time.sleep(0.05)
        eng.stop_continuous()
        n = stat_get("prof_continuous_captures")
        if n == 0:
            pytest.skip("backend cannot trace (prof_trace_unavailable)")
        root = str(tmp_path / "pm" / "prof_continuous")
        slots = os.listdir(root)
        assert set(slots) <= {"window_0", "window_1"}  # 2-deep bound

    def test_continuous_off_by_default(self):
        assert not profiler_capture.maybe_start_continuous()


class TestPureObserver:
    def test_attribution_off_is_bitwise_identical(self):
        """FLAGS_phase_attribution must not touch numerics: the same
        seeded program yields bitwise-equal losses with the plane on
        and off."""
        losses = {}
        for on in (True, False):
            pt.set_flags({"FLAGS_phase_attribution": on})
            phases.reset_phases()
            main, startup, loss = _mlp_program()
            rs = np.random.RandomState(7)
            scope = pt.framework.Scope()
            exe = pt.Executor(pt.CPUPlace())
            exe.run(startup, scope=scope)
            vals = []
            for _ in range(3):
                out = exe.run(main, feed={
                    "x": rs.randn(8, 32).astype("f4"),
                    "label": rs.randint(0, 10, (8, 1)).astype("int64")},
                    fetch_list=[loss], scope=scope)
                vals.append(np.asarray(out[0]).copy())
            exe.close()
            losses[on] = np.stack(vals)
        assert np.array_equal(losses[True], losses[False])
        rep = phases.phases_report()
        assert rep["steps"] == 0  # the off run attributed nothing
