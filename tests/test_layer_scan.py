"""Scan-over-layers (framework/passes.py LayerScanPass + ops/layer_scan.py).

Oracles: the scanned program must be BITWISE equal to the unrolled one
— per-step losses, parameters, AND optimizer slots, including the
dropout RNG stream — while trace+compile time and executable HLO op
count collapse from linear-in-depth to ~constant.  The acceptance
number (48 deep, >=5x compile drop) is asserted here via the
``compile_seconds`` histogram the Executor feeds, and checkpoints stay
per-layer so resume is elastic across the scan flag.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import passes as passes_mod
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.program import Program, program_guard
from paddle_tpu.initializer import ConstantInitializer, NormalInitializer
from paddle_tpu.optimizer import MomentumOptimizer
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.monitor import stat_get, stat_reset, stat_set

# mesh8 / mesh_dp_mp fixtures: shared in tests/conftest.py

SKIP_REASONS = (
    "no_repeats", "stack_align", "rename_conflict", "input_classify",
    "output_classify", "shared_written", "outside_write",
    "family_mismatch", "tp_spec_mismatch", "ys_conflict", "var_missing",
)


def _reset_scan_stats():
    for k in ("pass_layer_scan_segments", "pass_layer_scan_layers",
              "pass_layer_scan_skipped"):
        stat_reset(k)
    for r in SKIP_REASONS:
        stat_reset("pass_layer_scan_skipped_" + r)


@pytest.fixture(autouse=True)
def _scan_flag_reset():
    yield
    pt.set_flags({"FLAGS_layer_scan": False,
                  "FLAGS_layer_scan_min_layers": 4,
                  "FLAGS_layer_scan_policy": "",
                  "FLAGS_layer_scan_unroll": 1})


def _build_mlp(n_layers=6, width=16, in_dim=8, dropout=0.1,
               fleet_strategy=None, ffn=0, optimizer=None):
    """Repeated-layer MLP; with ``ffn`` a 2-sublayer (expand/contract)
    transformer-ffn-shaped block."""
    from paddle_tpu.distributed import fleet

    main, startup = Program(), Program()
    main.random_seed = 7
    with unique_name.guard(), program_guard(main, startup):
        x = layers.data("x", [in_dim])
        y = layers.data("y", [1])
        h = x
        for i in range(n_layers):
            if ffn:
                h1 = layers.fc(h, ffn, act="relu", name=f"blk{i}_ffn1",
                               param_attr=ParamAttr(
                                   initializer=NormalInitializer(0.0, 0.05)))
                h = layers.fc(h1, width, name=f"blk{i}_ffn2",
                              param_attr=ParamAttr(
                                  initializer=ConstantInitializer(0.02)),
                              bias_attr=False)
            else:
                h = layers.fc(h, width, act="relu", param_attr=ParamAttr(
                    name=f"blk{i}.w",
                    initializer=ConstantInitializer(0.02 * (i + 1))),
                    bias_attr=ParamAttr(name=f"blk{i}.b",
                                        initializer=ConstantInitializer(0.0)))
            if dropout:
                h = layers.dropout(h, dropout_prob=dropout)
        pred = layers.fc(h, 1, param_attr=ParamAttr(
            name="head.w", initializer=ConstantInitializer(0.1)),
            bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = optimizer or MomentumOptimizer(0.05, 0.9)
        if fleet_strategy is not None:
            fleet.init(is_collective=True, strategy=fleet_strategy)
            fleet.distributed_optimizer(opt)
            fleet.minimize(loss)
        else:
            opt.minimize(loss)
    return main, startup, loss


def _train(main, startup, loss, X, Y, steps=4, mesh=None, scope=None,
           exe=None, run_startup=True):
    if scope is None:
        scope = pt.framework.Scope()
    if exe is None:
        exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    if run_startup:
        exe.run(startup, scope=scope)
    losses = [float(np.asarray(
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss],
                scope=scope)[0]).item()) for _ in range(steps)]
    return losses, scope, exe


def _state(scope):
    """Per-layer params + optimizer slots as host arrays (reads through
    StackedParamRef views on a scanned scope)."""
    return {n: np.asarray(scope.get_var(n)).copy()
            for n in scope.local_var_names()
            if ("blk" in n or "head" in n)
            and not n.startswith(passes_mod.LAYER_STACK_PREFIX)}


def _data(in_dim=8, n=16, seed=0):
    rs = np.random.RandomState(seed)
    return (rs.randn(n, in_dim).astype("f4"),
            rs.randn(n, 1).astype("f4"))


class TestAcceptance:
    def test_depth48_compile_drops_5x_bitwise(self):
        """The acceptance oracle: a 48-deep transformer-ffn-block stack
        compiles >=5x faster scanned than unrolled (compile_seconds
        histogram), the optimized executable's HLO op count shrinks
        superlinearly, and 4 train steps stay bitwise — losses, params,
        Momentum slots, dropout RNG."""
        from paddle_tpu import observe

        X, Y = _data(32)

        def once(scan):
            pt.set_flags({"FLAGS_layer_scan": scan})
            _reset_scan_stats()
            m, s, l = _build_mlp(n_layers=48, width=32, in_dim=32,
                                 dropout=0.1, ffn=128)
            scope = pt.framework.Scope()
            exe = pt.Executor(pt.CPUPlace())
            exe.run(s, scope=scope)
            observe.histogram("compile_seconds").reset()
            losses, _, _ = _train(m, s, l, X, Y, scope=scope, exe=exe,
                                  run_startup=False)
            comp = observe.histogram("compile_seconds").summary()["sum"]
            hlo = int(stat_get("executable_hlo_ops") or 0)
            segs = int(stat_get("pass_layer_scan_segments") or 0)
            state = _state(scope)
            exe.close()
            return losses, comp, hlo, segs, state

        u_losses, u_comp, u_hlo, _, u_state = once(False)
        s_losses, s_comp, s_hlo, segs, s_state = once(True)

        # forward, backward, and optimizer regions all scan
        assert segs == 3, segs
        assert stat_get("pass_layer_scan_layers") >= 3 * 46
        # compile-time acceptance: >=5x (typ. 6-7x on this shape; the
        # 48-layer transformer A-B in bench.py measures ~30x)
        assert u_comp / s_comp >= 5.0, (u_comp, s_comp)
        # executable size ~constant in depth instead of linear: the
        # unrolled HLO is ~8x the scanned one at depth 48
        assert s_hlo * 6 < u_hlo, (s_hlo, u_hlo)
        # bitwise step parity
        np.testing.assert_array_equal(u_losses, s_losses)
        assert u_state.keys() == s_state.keys()
        for n in u_state:
            np.testing.assert_array_equal(u_state[n], s_state[n],
                                          err_msg=n)


class TestParity:
    def test_bitwise_parity_dropout_momentum(self):
        X, Y = _data()
        pt.set_flags({"FLAGS_layer_scan": False})
        base_losses, base_scope, _ = _train(*_build_mlp(), X, Y)

        pt.set_flags({"FLAGS_layer_scan": True})
        _reset_scan_stats()
        scan_losses, scan_scope, _ = _train(*_build_mlp(), X, Y)
        assert stat_get("pass_layer_scan_segments") >= 1
        np.testing.assert_array_equal(base_losses, scan_losses)
        b, s = _state(base_scope), _state(scan_scope)
        assert b.keys() == s.keys()
        assert any("velocity" in n for n in b), "slots missing from oracle"
        for n in b:
            np.testing.assert_array_equal(b[n], s[n], err_msg=n)

    def test_dp_mesh_parity(self, mesh8):
        from paddle_tpu.distributed import fleet

        X, Y = _data()

        def strat():
            st = fleet.DistributedStrategy()
            st.fuse_all_reduce_ops = False
            return st

        pt.set_flags({"FLAGS_layer_scan": False})
        with unique_name.guard():
            m, s, l = _build_mlp(fleet_strategy=strat())
        base_losses, base_scope, _ = _train(m, s, l, X, Y, mesh=mesh8)

        pt.set_flags({"FLAGS_layer_scan": True})
        _reset_scan_stats()
        with unique_name.guard():
            m, s, l = _build_mlp(fleet_strategy=strat())
        scan_losses, scan_scope, _ = _train(m, s, l, X, Y, mesh=mesh8)
        assert stat_get("pass_layer_scan_segments") >= 1
        np.testing.assert_array_equal(base_losses, scan_losses)
        b, s_ = _state(base_scope), _state(scan_scope)
        for n in b:
            np.testing.assert_array_equal(b[n], s_[n], err_msg=n)

    def test_fuse_scan_composition_parity(self, mesh8):
        """Fuse x scan regression: the scanned program's layer_index
        materializations read the stacked grad carrier right after its
        pulled-out allreduce, so FuseAllReducePass must close the
        bucket at that read barrier — without it the coalesced
        reduction lands after the read and the optimizer consumes
        pre-reduce grads (caught as a ~1e-2 loss drift by this test)."""
        from paddle_tpu.distributed import fleet

        X, Y = _data()

        def run(fuse, scan):
            pt.set_flags({"FLAGS_layer_scan": scan})
            st = fleet.DistributedStrategy()
            st.fuse_all_reduce_ops = fuse
            with unique_name.guard():
                m, s, l = _build_mlp(fleet_strategy=st)
            losses, scope, _ = _train(m, s, l, X, Y, mesh=mesh8)
            return losses, _state(scope)

        base_losses, base_state = run(fuse=False, scan=False)
        _reset_scan_stats()
        losses, state = run(fuse=True, scan=True)
        assert stat_get("pass_layer_scan_segments") >= 1
        np.testing.assert_array_equal(base_losses, losses)
        for n in base_state:
            np.testing.assert_array_equal(base_state[n], state[n],
                                          err_msg=n)

    def test_tp_scan_composition(self, mesh_dp_mp):
        """TP x scan on the 2x4 mesh: bitwise parity vs the unrolled tp
        run, and the stacked carrier's sharding applies the per-layer
        spec with the stack axis replicated."""
        from paddle_tpu.distributed import fleet

        rules = [(r"blk\d+_ffn1\.w_\d+$", "None,mp"),
                 (r"blk\d+_ffn1\.b_\d+$", "mp"),
                 (r"blk\d+_ffn2\.w_\d+$", "mp,None")]
        X, Y = _data(32)

        def build():
            st = fleet.DistributedStrategy()
            st.tensor_parallel = True
            st.tensor_parallel_configs = {"partition_rules": rules}
            with unique_name.guard():
                return _build_mlp(n_layers=6, width=32, in_dim=32,
                                  dropout=0.0, ffn=64, fleet_strategy=st)

        pt.set_flags({"FLAGS_layer_scan": False})
        base_losses, base_scope, _ = _train(*build(), X, Y, mesh=mesh_dp_mp)

        pt.set_flags({"FLAGS_layer_scan": True})
        _reset_scan_stats()
        scan_losses, scan_scope, _ = _train(*build(), X, Y, mesh=mesh_dp_mp)
        assert stat_get("pass_layer_scan_segments") >= 1
        np.testing.assert_array_equal(base_losses, scan_losses)
        for n in _state(base_scope):
            np.testing.assert_array_equal(
                np.asarray(base_scope.get_var(n)),
                np.asarray(scan_scope.get_var(n)), err_msg=n)
        # the carrier is mp-sharded on the per-layer dim, replicated on
        # the leading stack axis
        carriers = [n for n in scan_scope.local_var_names()
                    if n.startswith(passes_mod.LAYER_STACK_PREFIX)
                    and "ffn1.w" in n]
        assert carriers
        v = scan_scope.get_var(carriers[0])
        spec = tuple(v.sharding.spec)
        assert v.ndim == 3 and spec[0] is None and "mp" in spec, (
            carriers[0], v.shape, spec)

    def test_remat_policy_parity_and_unroll_knob(self):
        """jax.checkpoint wrapping and lax.scan unroll>1 change neither
        the primal losses nor the trained state."""
        X, Y = _data()
        pt.set_flags({"FLAGS_layer_scan": True})
        base_losses, base_scope, _ = _train(*_build_mlp(), X, Y)

        for flags in ({"FLAGS_layer_scan_policy": "dots_saveable"},
                      {"FLAGS_layer_scan_policy": "nothing_saveable"},
                      {"FLAGS_layer_scan_unroll": 2}):
            pt.set_flags({"FLAGS_layer_scan_policy": "",
                          "FLAGS_layer_scan_unroll": 1, **flags})
            _reset_scan_stats()
            losses, scope, _ = _train(*_build_mlp(), X, Y)
            assert stat_get("pass_layer_scan_segments") >= 1, flags
            np.testing.assert_array_equal(base_losses, losses,
                                          err_msg=str(flags))
            b, s = _state(base_scope), _state(scope)
            for n in b:
                np.testing.assert_array_equal(b[n], s[n], err_msg=n)


class TestElasticity:
    def test_ckpt_roundtrip_into_unrolled_run(self, tmp_path):
        """Checkpoints of a scanned run hold PER-LAYER entries (no
        carrier arrays), restore into an unrolled run, and the resumed
        steps are bitwise the scanned continuation."""
        from paddle_tpu import ckpt as ckpt_mod
        from paddle_tpu.ckpt.state import snapshot_scope

        X, Y = _data()
        pt.set_flags({"FLAGS_layer_scan": True})
        m, s, l = _build_mlp()
        _, scope, exe = _train(m, s, l, X, Y, steps=2)

        snap = snapshot_scope(scope)
        assert not any(k.startswith(passes_mod.LAYER_STACK_PREFIX)
                       for k in snap), "carrier leaked into checkpoint"
        assert any("velocity" in k for k in snap)

        mgr = ckpt_mod.CheckpointManager(str(tmp_path), async_save=False)
        mgr.save(2, scope=scope)
        mgr.wait()

        pt.set_flags({"FLAGS_layer_scan": False})
        m2, s2, l2 = _build_mlp()
        scope2 = pt.framework.Scope()
        exe2 = pt.Executor(pt.CPUPlace())
        exe2.run(s2, scope=scope2)
        meta = mgr.restore(scope=scope2)
        assert meta and meta.get("step") == 2

        resumed, _, _ = _train(m2, s2, l2, X, Y, steps=2, scope=scope2,
                               exe=exe2, run_startup=False)
        pt.set_flags({"FLAGS_layer_scan": True})
        cont, _, _ = _train(m, s, l, X, Y, steps=2, scope=scope,
                            exe=exe, run_startup=False)
        np.testing.assert_array_equal(cont, resumed)

    def test_flag_flip_mid_run_continues_bitwise(self):
        """A live scope survives the flag flipping between runs: the
        executor reads per-layer state through the StackedParamRef
        views, so scanned steps -> unrolled steps == all-unrolled."""
        X, Y = _data()
        pt.set_flags({"FLAGS_layer_scan": False})
        m, s, l = _build_mlp()
        oracle, _, _ = _train(m, s, l, X, Y, steps=4)

        pt.set_flags({"FLAGS_layer_scan": True})
        m2, s2, l2 = _build_mlp()
        first, scope, exe = _train(m2, s2, l2, X, Y, steps=2)
        pt.set_flags({"FLAGS_layer_scan": False})
        rest, _, _ = _train(m2, s2, l2, X, Y, steps=2, scope=scope,
                            exe=exe, run_startup=False)
        np.testing.assert_array_equal(oracle, first + rest)


class TestDetection:
    def test_shallow_program_untouched(self):
        pt.set_flags({"FLAGS_layer_scan": True})
        _reset_scan_stats()
        m, s, l = _build_mlp(n_layers=2)
        X, Y = _data()
        losses, _, _ = _train(m, s, l, X, Y, steps=1)
        assert np.isfinite(losses).all()
        assert not stat_get("pass_layer_scan_segments")
        assert stat_get("pass_layer_scan_skipped") >= 1
        assert stat_get("pass_layer_scan_skipped_no_repeats") >= 1

    def test_non_isomorphic_layers_skipped(self):
        """Alternating widths break the structural fingerprint: nothing
        rewritten, numerics untouched."""
        pt.set_flags({"FLAGS_layer_scan": True})
        _reset_scan_stats()
        main, startup = Program(), Program()
        main.random_seed = 7
        with unique_name.guard(), program_guard(main, startup):
            x = layers.data("x", [8])
            y = layers.data("y", [1])
            h = x
            for i in range(8):
                h = layers.fc(h, 16 if i % 2 else 24, act="relu",
                              bias_attr=False)
            pred = layers.fc(h, 1, bias_attr=False)
            loss = layers.mean(layers.square_error_cost(pred, y))
            MomentumOptimizer(0.05, 0.9).minimize(loss)
        X, Y = _data()
        _train(main, startup, loss, X, Y, steps=1)
        assert not stat_get("pass_layer_scan_segments")

    def test_flag_off_is_default_and_untouched(self):
        _reset_scan_stats()
        m, s, l = _build_mlp()
        out = passes_mod.apply_passes(m, fetch_names=("loss",),
                                      feed_names=("x", "y"))
        assert not any(op.type == "layer_scan"
                       for op in out.global_block.ops)
        assert not stat_get("pass_layer_scan_segments")

    def test_rewrite_emits_one_scan_per_region(self):
        pt.set_flags({"FLAGS_layer_scan": True})
        m, s, l = _build_mlp(dropout=0.0)
        out = passes_mod.apply_passes(
            m, fetch_names=(l.name,), feed_names=("x", "y"))
        scans = [op for op in out.global_block.ops
                 if op.type == "layer_scan"]
        assert len(scans) >= 2  # forward + backward at least
        # each scan op points at a template block holding ONE layer
        for op in scans:
            tblock = out.blocks[int(op.attr("layer_block"))]
            assert 0 < len(tblock.ops) < 12
        # the user program is never mutated
        assert not any(op.type == "layer_scan"
                       for op in m.global_block.ops)


class TestCaching:
    def test_pass_cache_rekeys_on_flag_and_policy_flip(self):
        """FLAGS_layer_scan / FLAGS_layer_scan_policy key the executor
        pass cache: a flip re-runs the pipeline instead of serving the
        stale rewrite (same contract as the compile cache)."""
        X, Y = _data()
        pt.set_flags({"FLAGS_layer_scan": True})
        m, s, l = _build_mlp()
        scope = pt.framework.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(s, scope=scope)

        stat_reset("executor_pass_cache_hit")
        exe.run(m, feed={"x": X, "y": Y}, fetch_list=[l], scope=scope)
        assert not stat_get("executor_pass_cache_hit")
        exe.run(m, feed={"x": X, "y": Y}, fetch_list=[l], scope=scope)
        assert stat_get("executor_pass_cache_hit") == 1

        # policy flip -> new pass-cache key (no hit), scan still fires
        pt.set_flags({"FLAGS_layer_scan_policy": "dots_saveable"})
        _reset_scan_stats()
        exe.run(m, feed={"x": X, "y": Y}, fetch_list=[l], scope=scope)
        assert stat_get("executor_pass_cache_hit") == 1
        assert stat_get("pass_layer_scan_segments") >= 1

        # flag flip -> new key AND the fresh rewrite does not scan
        pt.set_flags({"FLAGS_layer_scan": False,
                      "FLAGS_layer_scan_policy": ""})
        _reset_scan_stats()
        exe.run(m, feed={"x": X, "y": Y}, fetch_list=[l], scope=scope)
        assert stat_get("executor_pass_cache_hit") == 1
        assert not stat_get("pass_layer_scan_segments")
        exe.close()


class TestStrategyPlumbing:
    def test_recompute_configs_scan_layers_enables_per_program(self):
        """recompute_configs={'scan_layers': N, 'policy': ...} turns the
        pass on for THIS program with FLAGS_layer_scan off, via attrs
        stamped on the optimizer ops (clone/fingerprint-safe)."""
        from paddle_tpu.distributed import fleet

        X, Y = _data()
        pt.set_flags({"FLAGS_layer_scan": False})
        st0 = fleet.DistributedStrategy()
        st0.fuse_all_reduce_ops = False
        with unique_name.guard():
            base_losses, _, _ = _train(*_build_mlp(fleet_strategy=st0),
                                       X, Y)

        _reset_scan_stats()
        st = fleet.DistributedStrategy()
        st.fuse_all_reduce_ops = False
        st.recompute = True
        st.recompute_configs = {"scan_layers": 4,
                                "policy": "dots_saveable"}
        assert st.recompute_configs["scan_layers"] == 4
        with unique_name.guard():
            m, s, l = _build_mlp(fleet_strategy=st)
        stamped = [op for op in m.global_block.ops
                   if op.has_attr(passes_mod.LAYER_SCAN_ATTR)]
        assert stamped and all(
            op.attr(passes_mod.LAYER_SCAN_POLICY_ATTR) == "dots_saveable"
            for op in stamped)
        losses, _, _ = _train(m, s, l, X, Y)
        assert stat_get("pass_layer_scan_segments") >= 1
        np.testing.assert_array_equal(base_losses, losses)

    def test_policy_only_recompute_configs_applies(self):
        """recompute_configs={'policy': ...} ALONE (no scan_layers) is
        a legal stamp: it picks the remat policy for a
        FLAGS_layer_scan-enabled run and must not be skipped just
        because no scan_layers attr rides the op."""
        from paddle_tpu.distributed import fleet

        X, Y = _data()
        pt.set_flags({"FLAGS_layer_scan": True})
        st = fleet.DistributedStrategy()
        st.fuse_all_reduce_ops = False
        st.recompute = True
        st.recompute_configs = {"policy": "nothing_saveable"}
        with unique_name.guard():
            m, s, l = _build_mlp(fleet_strategy=st)
        enabled, _, policy = passes_mod.LayerScanPass._config(m)
        assert enabled and policy == "nothing_saveable"
        _reset_scan_stats()
        losses, _, _ = _train(m, s, l, X, Y)
        assert stat_get("pass_layer_scan_segments") >= 1
        # the wrapped body computes the same numbers
        pt.set_flags({"FLAGS_layer_scan": False})
        st0 = fleet.DistributedStrategy()
        st0.fuse_all_reduce_ops = False
        with unique_name.guard():
            base_losses, _, _ = _train(*_build_mlp(fleet_strategy=st0),
                                       X, Y)
        np.testing.assert_array_equal(base_losses, losses)

    def test_layer_scan_fires_with_fuse_passes_off(self):
        """FLAGS_fuse_passes=0 turns off the OPTIMIZATION pipeline, not
        scan-over-layers — the scan flag owns its own gate, so a user
        debugging fusion keeps the compile-time win they asked for."""
        X, Y = _data()
        pt.set_flags({"FLAGS_fuse_passes": False})
        try:
            with unique_name.guard():
                base_losses, _, _ = _train(*_build_mlp(), X, Y)
            pt.set_flags({"FLAGS_layer_scan": True})
            _reset_scan_stats()
            with unique_name.guard():
                losses, _, _ = _train(*_build_mlp(), X, Y)
            assert stat_get("pass_layer_scan_segments") >= 1
            np.testing.assert_array_equal(base_losses, losses)
        finally:
            pt.set_flags({"FLAGS_fuse_passes": True})

    def test_invalid_policy_rejected(self):
        from paddle_tpu.distributed import fleet

        st = fleet.DistributedStrategy()
        st.recompute = True
        st.recompute_configs = {"scan_layers": 4, "policy": "bogus"}
        with unique_name.guard():
            with pytest.raises(ValueError, match="policy"):
                _build_mlp(fleet_strategy=st)


class TestFuseBucketAccounting:
    def test_stacked_grad_sized_num_layers_x(self):
        """The satellite bugfix: a LAYER_STACK_ATTR-stamped allreduce
        moves num_layers x the var's declared per-layer bytes — bucket
        sizing must use the TRUE stacked payload.  Three 8-layer stacks
        of 64KB-per-layer grads = 512KB each under a 1.3MB cap: the
        first two fit one bucket, the third overflows into its own —
        per-layer sizing (3 x 64KB) would silently fuse all three."""
        from paddle_tpu.framework.passes import (FUSE_SIZE_ATTR,
                                                 FUSED_ALLREDUCE_ATTR,
                                                 LAYER_STACK_ATTR,
                                                 FuseAllReducePass,
                                                 PassContext)

        def build(stack):
            main = Program()
            block = main.global_block
            for name in ("g0", "g1", "g2"):
                block.create_var(name=name, shape=[128, 128],
                                 dtype="float32")
                block.append_op("fill_constant", {}, {"Out": [name]},
                                {"shape": [128, 128], "dtype": "float32",
                                 "value": 1.0})
                attrs = {"ring_id": 0, FUSED_ALLREDUCE_ATTR: True,
                         FUSE_SIZE_ATTR: 1.3}
                if stack:
                    attrs[LAYER_STACK_ATTR] = stack
                block.append_op("c_allreduce_sum", {"X": [name]},
                                {"Out": [name]}, attrs)
            return main

        def n_allreduce(prog):
            return sum(1 for op in prog.global_block.ops
                       if op.type == "c_allreduce_sum")

        def coalesce_groups(prog):
            return [op.inputs["Input"] for op in prog.global_block.ops
                    if op.type == "coalesce_tensor"]

        # unstacked: 3 x 64KB fuse into ONE bucket under the cap
        stat_reset("pass_fused_allreduce_buckets")
        p = build(0)
        FuseAllReducePass().apply(p, PassContext())
        assert n_allreduce(p) == 1
        assert stat_get("pass_fused_allreduce_buckets") == 1
        # stacked x8: 512KB each -> [g0,g1] fuse, g2 overflows the cap
        # and stays a singleton
        stat_reset("pass_fused_allreduce_buckets")
        p = build(8)
        FuseAllReducePass().apply(p, PassContext())
        assert n_allreduce(p) == 2
        assert stat_get("pass_fused_allreduce_buckets") == 1
        assert coalesce_groups(p) == [["g0", "g1"]]


class TestCompat:
    def test_remat_policy_unavailable_degrades(self, monkeypatch):
        """A jax without checkpoint_policies degrades to plain
        jax.checkpoint and counts remat_policy_unavailable."""
        import jax as jax_mod

        from paddle_tpu.framework import jax_compat

        monkeypatch.delattr(jax_mod, "checkpoint_policies", raising=False)
        stat_reset("remat_policy_unavailable")

        def f(c, x):
            return c, x

        wrapped = jax_compat.wrap_checkpoint(f, "dots_saveable")
        assert wrapped is not f
        assert stat_get("remat_policy_unavailable") == 1

    def test_policy_name_resolution(self):
        from paddle_tpu.framework import jax_compat

        assert jax_compat.checkpoint_policy("") is None
        for name in jax_compat.REMAT_POLICIES:
            # on this jax every mapped policy resolves; the accessor
            # never raises either way
            jax_compat.checkpoint_policy(name)

    def test_scan_unroll_kwarg_guard(self):
        import jax.numpy as jnp

        from paddle_tpu.framework import jax_compat

        def body(c, x):
            return c + x, c

        final, ys = jax_compat.scan(body, jnp.float32(0.0),
                                    jnp.arange(4, dtype="float32"),
                                    length=4, unroll=2)
        assert float(final) == 6.0


class TestStackedCkptHostValue:
    """ckpt/state.py _host_value over StackedParamRef views: the
    fully-addressable fast path slices the layer; a carrier this
    process cannot assemble fails LOUDLY instead of silently dropping
    the parameter from the checkpoint."""

    def test_addressable_carrier_slices(self):
        from paddle_tpu.ckpt.state import _host_value
        from paddle_tpu.framework.scope import StackedParamRef

        scope = pt.framework.Scope()
        carrier = np.arange(12, dtype="f4").reshape(4, 3)
        name = passes_mod.LAYER_STACK_PREFIX + "w"
        scope.set_var(name, carrier)
        ref = StackedParamRef(scope, name, 2, (3,), "float32")
        np.testing.assert_array_equal(_host_value(ref), carrier[2])

    def test_non_addressable_carrier_fails_loudly(self):
        from paddle_tpu.ckpt.manager import CheckpointError
        from paddle_tpu.ckpt.state import _host_value
        from paddle_tpu.framework.scope import StackedParamRef

        class _Shard:
            index = (slice(0, 2), slice(0, 3))
            data = np.zeros((2, 3), "f4")

        class _FakeGlobal:
            # duck-typed multi-process jax global array: local shards
            # cover only part of the (4, 3) stack
            sharding = object()
            dtype = np.dtype("float32")
            shape = (4, 3)
            is_fully_addressable = False
            addressable_shards = [_Shard()]

        scope = pt.framework.Scope()
        name = passes_mod.LAYER_STACK_PREFIX + "w"
        scope.set_var(name, _FakeGlobal())
        ref = StackedParamRef(scope, name, 1, (3,), "float32")
        with pytest.raises(CheckpointError, match="layer stack"):
            _host_value(ref)

    def test_non_addressable_gather_once_per_carrier(self):
        """snapshot_scope gathers a non-addressable carrier ONCE and
        slices every member from it — not once per layer."""
        from paddle_tpu.ckpt.state import snapshot_scope
        from paddle_tpu.framework.scope import StackedParamRef

        gathers = {"n": 0}
        full = np.arange(12, dtype="f4").reshape(4, 3)

        class _Shard:
            index = (slice(0, 4), slice(0, 3))
            data = full

        class _FakeGlobal:
            sharding = object()
            dtype = np.dtype("float32")
            shape = (4, 3)
            is_fully_addressable = False

            @property
            def addressable_shards(self):
                gathers["n"] += 1
                return [_Shard()]

        scope = pt.framework.Scope()
        name = passes_mod.LAYER_STACK_PREFIX + "w"
        scope.set_var(name, _FakeGlobal())
        for i in range(4):
            scope.set_var(f"m{i}", StackedParamRef(scope, name, i, (3,),
                                                   "float32"))
        snap = snapshot_scope(scope)
        assert gathers["n"] == 1, gathers
        assert name not in snap  # carrier itself never checkpointed
        for i in range(4):
            np.testing.assert_array_equal(snap[f"m{i}"], full[i])


class TestEnsureStacked:
    def test_incremental_refresh_on_host_packed_carrier(self):
        """A carrier the program only READS stays the host numpy array
        the full pack built; a later partial concrete write (e.g. a
        partial restore) must take the incremental branch without
        assuming the carrier is a jax array."""
        from paddle_tpu.framework.passes import LayerScanPlan
        from paddle_tpu.framework.scope import StackedParamRef

        scope = pt.framework.Scope()
        name = passes_mod.LAYER_STACK_PREFIX + "w"
        members = tuple(f"m{i}" for i in range(4))
        plan = LayerScanPlan([{"carrier": name, "members": members,
                               "shape": (3,), "dtype": "float32"}])
        for i, m in enumerate(members):
            scope.set_var(m, np.full((3,), float(i), "f4"))
        plan.ensure_stacked(scope)  # full host-side pack
        assert isinstance(scope.get_var("m1"), StackedParamRef)
        # one member restored concrete over the still-host carrier
        scope.set_var("m2", np.full((3,), 9.0, "f4"))
        plan.ensure_stacked(scope)  # incremental branch
        np.testing.assert_array_equal(np.asarray(scope.get_var("m2")),
                                      np.full((3,), 9.0, "f4"))
        np.testing.assert_array_equal(np.asarray(scope.get_var("m3")),
                                      np.full((3,), 3.0, "f4"))
