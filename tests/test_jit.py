"""Trace-based jit: TracedLayer / to_static / jit.save+load / Model export.

Reference parity: python/paddle/fluid/dygraph/jit.py (save:466,
TracedLayer:995) and dygraph_to_static program_translator (to_static).
Oracle: traced/loaded outputs must match the eager forward bitwise-ish.
"""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import jit, nn
from paddle_tpu.dygraph.tensor import Tensor


def _lenet():
    import paddle_tpu.nn as nn

    class LeNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.c1 = nn.Conv2D(1, 6, 5, padding=2)
            self.p1 = nn.MaxPool2D(2, 2)
            self.c2 = nn.Conv2D(6, 16, 5)
            self.p2 = nn.MaxPool2D(2, 2)
            self.fc1 = nn.Linear(16 * 5 * 5, 64)
            self.fc2 = nn.Linear(64, 10)

        def forward(self, x):
            y = self.p1(nn.functional.relu(self.c1(x)))
            y = self.p2(nn.functional.relu(self.c2(y)))
            # 0 = copy input dim: keeps the trace batch-size-agnostic
            # (shape[0] would bake the example batch into the program)
            y = pt.reshape(y, [0, -1])
            y = nn.functional.relu(self.fc1(y))
            return self.fc2(y)

    return LeNet()


def test_traced_layer_matches_eager_and_roundtrips(tmp_path):
    net = _lenet()
    net.eval()
    rng = np.random.RandomState(0)
    x = Tensor(rng.randn(4, 1, 28, 28).astype("float32"))

    eager_out = np.asarray(net(x).numpy())
    outs, traced = jit.TracedLayer.trace(net, [x])
    np.testing.assert_allclose(np.asarray(outs.numpy()), eager_out,
                               rtol=1e-5)

    # run the traced static program on fresh inputs
    x2 = Tensor(rng.randn(4, 1, 28, 28).astype("float32"))
    want = np.asarray(net(x2).numpy())
    got = np.asarray(traced(x2)[0].numpy())
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    # save -> load -> predict parity (fresh Predictor process path)
    model_dir = str(tmp_path / "lenet_infer")
    traced.save_inference_model(model_dir)
    loaded = jit.load(model_dir)
    got2 = np.asarray(loaded(x2).numpy())
    np.testing.assert_allclose(got2, want, rtol=1e-4, atol=1e-5)


def test_jit_save_with_input_spec_and_load(tmp_path):
    from paddle_tpu.hapi.model import InputSpec

    net = _lenet()
    net.eval()
    model_dir = str(tmp_path / "lenet_spec")
    jit.save(net, model_dir, input_spec=[InputSpec([-1, 1, 28, 28])])
    loaded = jit.load(model_dir)
    rng = np.random.RandomState(1)
    x = Tensor(rng.randn(2, 1, 28, 28).astype("float32"))
    # spec traced with batch 1; predictor recompiles per shape bucket
    want = np.asarray(net(x).numpy())
    got = np.asarray(loaded(x).numpy())
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_to_static_compiles_and_matches():
    calls = []

    @jit.to_static
    def f(a, b):
        calls.append(1)
        return pt.matmul(a, b) + a

    rng = np.random.RandomState(0)
    a = Tensor(rng.randn(3, 3).astype("float32"))
    b = Tensor(rng.randn(3, 3).astype("float32"))
    want = np.asarray(a.numpy()) @ np.asarray(b.numpy()) + np.asarray(a.numpy())
    got1 = np.asarray(f(a, b).numpy())
    got2 = np.asarray(f(a, b).numpy())  # second call: cached program
    np.testing.assert_allclose(got1, want, rtol=1e-5)
    np.testing.assert_allclose(got2, want, rtol=1e-5)
    assert len(calls) == 1, "python body must run only for the trace"


def test_model_save_inference_export(tmp_path):
    from paddle_tpu.hapi.model import InputSpec

    net = _lenet()
    model = pt.Model(net, inputs=[InputSpec([-1, 1, 28, 28])])
    path = str(tmp_path / "hapi_export")
    model.save(path, training=False)
    loaded = jit.load(path)
    rng = np.random.RandomState(2)
    x = Tensor(rng.randn(2, 1, 28, 28).astype("float32"))
    net.eval()
    want = np.asarray(net(x).numpy())
    got = np.asarray(loaded(x).numpy())
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
